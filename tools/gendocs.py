"""Render HTML API docs for ``distributedfft_tpu`` into ``documentation/``.

The reference ships Doxygen output (``/root/reference/Doxyfile`` →
``documentation/html``); this is the TPU repo's equivalent, built on the
STDLIB ``pydoc`` renderer because the environment bakes in neither pdoc
nor sphinx (and installs are disallowed). The docstrings are the
documentation source — they carry the design rationale, measured numbers
and reference file:line provenance — so a plain renderer loses nothing
that matters.

Usage (from the repo root):
    python tools/gendocs.py          # writes documentation/*.html
    make docs                        # same, via the root Makefile
"""

from __future__ import annotations

import importlib
import os
import pkgutil
import pydoc
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
OUT = os.path.join(REPO, "documentation")
PACKAGE = "distributedfft_tpu"


def iter_module_names() -> list:
    """All importable module names under the package, package first."""
    sys.path.insert(0, REPO)
    pkg = importlib.import_module(PACKAGE)
    names = [PACKAGE]
    for info in pkgutil.walk_packages(pkg.__path__, prefix=PACKAGE + "."):
        names.append(info.name)
    return names


def main() -> int:
    # Stay off the TPU tunnel: importing the package imports jax, and the
    # axon sitecustomize would otherwise dial the device.
    import jax
    jax.config.update("jax_platforms", "cpu")

    os.makedirs(OUT, exist_ok=True)
    os.chdir(OUT)  # pydoc.writedoc writes into the current directory
    written, failed = [], []
    for name in iter_module_names():
        try:
            importlib.import_module(name)
            pydoc.writedoc(name)
            written.append(name)
        except Exception as e:  # noqa: BLE001 — skip, report, keep going
            failed.append((name, f"{type(e).__name__}: {e}"))

    index = ["<html><head><title>distributedfft_tpu API</title></head>",
             "<body><h1>distributedfft_tpu API reference</h1>",
             "<p>Rendered from the package docstrings by tools/gendocs.py "
             "(stdlib pydoc). Docstrings carry design rationale, measured "
             "numbers and reference-code provenance (file:line into the "
             "upstream CUDA/MPI implementation).</p><ul>"]
    for name in written:
        index.append(f'<li><a href="{name}.html">{name}</a></li>')
    index.append("</ul></body></html>")
    with open("index.html", "w") as f:
        f.write("\n".join(index))

    print(f"wrote {len(written)} module pages + index.html to {OUT}")
    for name, err in failed:
        print(f"SKIPPED {name}: {err}", file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
