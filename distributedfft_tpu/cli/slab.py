"""``slab`` executable — reference CLI surface (``tests/src/slab/main.cpp``)
on the TPU framework.

Example (reference: ``mpirun -n 4 slab -nx 256 -ny 256 -nz 256 -s Z_Then_YX
-snd Streams -o 1 -i 10``):

    python -m distributedfft_tpu.cli.slab -nx 256 -ny 256 -nz 256 \
        -s Z_Then_YX -o 1 -i 10 -p 4 --emulate-devices 4

``-p`` replaces ``mpirun -n``: the decomposition width is a mesh-axis size,
not a process count.
"""

from __future__ import annotations

import argparse
import sys

from .common import (add_common_args, maybe_autotune_comm,
                     overlap_config_kwargs, resilience_config_kwargs,
                     run_testcase, setup_backend, wire_config_kwargs,
                     wisdom_config_kwargs)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="slab", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    add_common_args(ap, pencil=False, comm_tunable=True)
    ap.add_argument("--sequence", "-s", default="ZY_Then_X",
                    help='"ZY_Then_X" (default), "Z_Then_YX" or "Y_Then_ZX"')
    ap.add_argument("--partitions", "-p", type=int, default=0,
                    help="number of slabs (default: all devices)")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    setup_backend(args)

    import jax
    from .. import params as pm
    from ..testing import testcases as tc

    p = args.partitions or len(jax.devices())
    g = pm.GlobalSize(args.input_dim_x, args.input_dim_y, args.input_dim_z)
    cfg = pm.Config(
        comm_method=pm.parse_comm_method(args.comm_method),
        send_method=pm.SendMethod.parse(args.send_method),
        opt=args.opt, cuda_aware=args.cuda_aware,
        warmup_rounds=args.warmup_rounds, iterations=args.iterations,
        double_prec=args.double_prec, benchmark_dir=args.benchmark_dir,
        fft_backend=args.fft_backend, streams_chunks=args.streams_chunks,
        **overlap_config_kwargs(args), **wire_config_kwargs(args),
        **wisdom_config_kwargs(args), **resilience_config_kwargs(args))
    part = pm.SlabPartition(p)
    cfg = maybe_autotune_comm(args, "slab", g, part, cfg,
                              sequence=args.sequence)
    plan = tc.make_plan("slab", g, part, cfg, sequence=args.sequence)
    return run_testcase(plan, args)


if __name__ == "__main__":
    sys.exit(main())
