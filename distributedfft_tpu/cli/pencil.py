"""``pencil`` executable — reference CLI surface
(``tests/src/pencil/main.cpp``) on the TPU framework.

Example (reference: ``mpirun -n 4 pencil -nx 256 -ny 256 -nz 256 -p1 2 -p2 2
-snd Streams -o 1 -i 10``):

    python -m distributedfft_tpu.cli.pencil -nx 256 -ny 256 -nz 256 \
        -p1 2 -p2 2 -o 1 -i 10 --emulate-devices 4
"""

from __future__ import annotations

import argparse
import sys

from .common import (add_common_args, maybe_autotune_comm,
                     overlap_config_kwargs, resilience_config_kwargs,
                     run_testcase, setup_backend, wire_config_kwargs,
                     wisdom_config_kwargs)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="pencil", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    add_common_args(ap, pencil=True, comm_tunable=True)
    ap.add_argument("--partition1", "-p1", type=int, required=True,
                    help="partitions in x-direction")
    ap.add_argument("--partition2", "-p2", type=int, required=True,
                    help="partitions in y-direction")
    ap.add_argument("--fft-dim", "-f", type=int, default=3, choices=(1, 2, 3),
                    help="number of transform dimensions (partial-dim exec)")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    setup_backend(args)

    from .. import params as pm
    from ..testing import testcases as tc

    g = pm.GlobalSize(args.input_dim_x, args.input_dim_y, args.input_dim_z)
    cfg = pm.Config(
        comm_method=pm.parse_comm_method(args.comm_method1),
        send_method=pm.SendMethod.parse(args.send_method1),
        comm_method2=(pm.parse_comm_method(args.comm_method2)
                      if args.comm_method2 else None),
        send_method2=(pm.SendMethod.parse(args.send_method2)
                      if args.send_method2 else None),
        opt=args.opt, cuda_aware=args.cuda_aware,
        warmup_rounds=args.warmup_rounds, iterations=args.iterations,
        double_prec=args.double_prec, benchmark_dir=args.benchmark_dir,
        fft_backend=args.fft_backend, streams_chunks=args.streams_chunks,
        **overlap_config_kwargs(args), **wire_config_kwargs(args),
        **wisdom_config_kwargs(args), **resilience_config_kwargs(args))
    part = pm.PencilPartition(args.partition1, args.partition2)
    cfg = maybe_autotune_comm(args, "pencil", g, part, cfg,
                              dims=args.fft_dim)
    plan = tc.make_plan("pencil", g, part, cfg, dims=args.fft_dim)
    return run_testcase(plan, args, dims=args.fft_dim)


if __name__ == "__main__":
    sys.exit(main())
