"""``batched`` executable — BASELINE config #4's workload ("Batched 2D FFT
4096^2 x 64, 1D mesh") through the same testcase/Timer/eval harness as the
3D engines. The reference has no batched-2D executable (it reaches batching
only through cufftMakePlanMany batch counts); this CLI is the framework
extension that makes config #4 a first-class benchmark target.

Flag mapping: ``-nx``/``-ny`` are the IMAGE dimensions and ``-nz`` is the
BATCH count, so config #4 reads naturally:

    python -m distributedfft_tpu.cli.batched -nx 4096 -ny 4096 -nz 64 \
        --shard batch -t 0 -p 8 --emulate-devices 8

The Timer CSV filename slots are ``<batch>_<nx>_<ny>`` (the plan's
``global_size`` — batch rides the first slot; the halved spectral axis ny
rides the last, mirroring the 3D schema's halved z).

``--shard batch`` (default) shards the batch axis — embarrassingly
parallel, zero collectives; ``--shard x`` runs the slab-style decomposition
(1D FFT y -> all_to_all transpose -> 1D FFT x) for batches too small to
fill the mesh. ``--batch-chunk`` caps peak memory / compiled-program size via
sequential ``lax.map`` slices of that size (1 = per-plane, the most
chunked; the on-chip sweep measured 4096^2 x 64 fastest at 1).

Testcases 0-3 are supported (4 is the 3D Laplacian validation — not
meaningful for a 2D stack).
"""

from __future__ import annotations

import argparse
import sys

from .common import (add_common_args, maybe_autotune_comm,
                     overlap_config_kwargs, resilience_config_kwargs,
                     run_testcase, setup_backend, wire_config_kwargs,
                     wisdom_config_kwargs)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="batched", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    add_common_args(ap, pencil=False, comm_tunable=True)
    ap.add_argument("--shard", default="batch", choices=("batch", "x"),
                    help="decomposed axis: 'batch' (no collectives) or 'x' "
                         "(slab-style transpose pipeline)")
    ap.add_argument("--batch-chunk", type=int, default=None,
                    help="transform the per-device batch in sequential "
                         "chunks of this size (lax.map) — caps compiled "
                         "program size; must divide the local padded batch "
                         "(0 = whole stack fused, same as omitting the flag)")
    ap.add_argument("--partitions", "-p", type=int, default=0,
                    help="mesh width (default: all devices)")
    ap.add_argument("--c2c", action="store_true",
                    help="complex-to-complex transform instead of R2C/C2R")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    setup_backend(args)

    import jax
    from .. import params as pm
    from ..models.batched2d import Batched2DFFTPlan

    if args.testcase == 4:
        print("testcase 4 (3D Laplacian) is not defined for the batched-2D "
              "plan; use testcases 0-3", file=sys.stderr)
        return 2
    p = args.partitions or len(jax.devices())
    cfg = pm.Config(
        comm_method=pm.parse_comm_method(args.comm_method),
        send_method=pm.SendMethod.parse(args.send_method),
        opt=args.opt, cuda_aware=args.cuda_aware,
        warmup_rounds=args.warmup_rounds, iterations=args.iterations,
        double_prec=args.double_prec, benchmark_dir=args.benchmark_dir,
        fft_backend=args.fft_backend, streams_chunks=args.streams_chunks,
        **overlap_config_kwargs(args), **wire_config_kwargs(args),
        **wisdom_config_kwargs(args), **resilience_config_kwargs(args))
    if getattr(args, "autotune_comm", False):
        if args.shard != "x":
            print("autotune-comm: shard='batch' issues no collectives; "
                  "nothing to tune")
        else:
            g = pm.GlobalSize(args.input_dim_z, args.input_dim_x,
                              args.input_dim_y)  # (batch, nx, ny) slots
            cfg = maybe_autotune_comm(args, "batched2d", g,
                                      pm.SlabPartition(p), cfg, dims=2,
                                      variant="x",
                                      transform="c2c" if args.c2c
                                      else "r2c")
    plan = Batched2DFFTPlan(
        batch=args.input_dim_z, nx=args.input_dim_x, ny=args.input_dim_y,
        partition=pm.SlabPartition(p), config=cfg, shard=args.shard,
        transform="c2c" if args.c2c else "r2c",
        batch_chunk=args.batch_chunk)
    # dims=2: the roundtrip scale of an unnormalized 2D transform is nx*ny
    # (testcases._roundtrip_scale maps dims=2 onto the last two size slots).
    return run_testcase(plan, args, dims=2)


if __name__ == "__main__":
    sys.exit(main())
