"""``reference`` executable — single-device baseline + communication
microbenchmarks (reference ``tests/src/reference/main.cpp``,
``tests/include/tests_reference.hpp:42-96``).

Testcases:
  0: full 3D FFT on one device (the reference's gather -> cufftMakePlan3d
     baseline; in the single-controller model the gather is a device_put).
  1: redistribution bandwidth, explicit All2All vs GSPMD (Peer2Peer) via
     ``--opt 0|1``.
  2: slab-geometry (1D mesh) transpose bandwidth.
  3: pencil-geometry (2D mesh axis) transpose bandwidth.
"""

from __future__ import annotations

import argparse
import sys

from .common import add_common_args, setup_backend


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="reference", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    add_common_args(ap, pencil=False)
    ap.add_argument("--partition1", "-p1", type=int, default=0)
    ap.add_argument("--partition2", "-p2", type=int, default=0)
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    setup_backend(args)

    import jax
    import numpy as np

    shape = (args.input_dim_x, args.input_dim_y, args.input_dim_z)
    dtype = np.float64 if args.double_prec else np.float32
    it, wu = args.iterations, args.warmup_rounds

    if args.profile_dir:
        with jax.profiler.trace(args.profile_dir):
            return _dispatch(args, shape, dtype, it, wu)
    return _dispatch(args, shape, dtype, it, wu)


def _dispatch(args, shape, dtype, it, wu) -> int:
    import jax

    from ..testing import microbench as mb

    if args.testcase == 0:
        ms = mb.single_device_fft_ms(shape, it, wu, dtype,
                                     backend=args.fft_backend)
        print(f"Run complete: {ms:.4f} ms (single-device 3D R2C, "
              f"{shape[0]}x{shape[1]}x{shape[2]})")
        return 0

    p = len(jax.devices())
    if args.testcase in (1, 2, 3):
        explicit = args.opt != 0  # opt 0: Peer2Peer/GSPMD, opt 1: All2All
        pencil_axis = args.testcase == 3
        r = mb.transpose_bandwidth(shape, p, explicit=explicit,
                                   iterations=it or 1, warmup=wu,
                                   dtype=dtype, pencil_axis=pencil_axis)
        kind = "All2All" if explicit else "Peer2Peer(GSPMD)"
        geom = "pencil-axis" if pencil_axis else "slab"
        print(f"Bandwidth: {r['gb_per_s'] * 1e3:.2f} MB/s "
              f"[{kind}, {geom}, {p} devices, "
              f"{r['bytes'] / 1e6:.1f} MB moved in {r['seconds'] * 1e3:.3f} ms]")
        return 0
    print(f"unknown testcase {args.testcase}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
