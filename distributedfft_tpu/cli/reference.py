"""``reference`` executable — single-device baseline + communication
microbenchmarks (reference ``tests/src/reference/main.cpp``,
``tests/include/tests_reference.hpp:42-96``).

Testcases (the reference's 1D/2D/3D-memcpy bandwidth probes, strategy via
``--opt``: 0 = Peer2Peer/GSPMD resharding, 1 = explicit All2All):
  0: full 3D FFT on one device (the reference's gather -> cufftMakePlan3d
     baseline; in the single-controller model the gather is a device_put).
  1: 1D geometry — slab transpose over a 1D mesh.
  2: 2D geometry — pencil transpose over one axis of a 2D mesh.
  3: 3D geometry — both non-exchanged axes sharded (strided in two axes).
  4: north-star fraction gate — the slab pipeline transpose's achieved
     fraction of the raw collective ceiling, via the interleaved
     K-chained-pair methodology (``microbench.transpose_fraction_chain``:
     the ceiling's work is a per-iteration subset of the pipeline's, so
     the fraction is <=1 in expectation, reported with a spread).
Each bandwidth line reports the collectives found in the compiled HLO, so
a GSPMD 'reshard' that XLA elided would be visible as an empty list.
"""

from __future__ import annotations

import argparse
import sys

from .common import (add_common_args, maybe_profile, print_obs_snapshot,
                     print_stage_profile, setup_backend)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="reference", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    add_common_args(ap, pencil=False)
    ap.add_argument("--partition1", "-p1", type=int, default=0)
    ap.add_argument("--partition2", "-p2", type=int, default=0)
    ap.add_argument("--autotune", action="store_true",
                    help="race the local-FFT backends (xla / matmul@high / "
                         "matmul@highest / pallas) for this shape on the "
                         "current device and report the fastest that meets "
                         "the accuracy budget")
    ap.add_argument("--autotune-budget", type=float, default=1e-4,
                    help="max roundtrip rel. error a backend may incur")
    ap.add_argument("--autotune-k", type=int, default=257,
                    help="chained roundtrips per timing sample; must be "
                         "large enough that the work dominates the TPU "
                         "tunnel's tens-of-ms constant noise (257 matches "
                         "bench.py at 256^3; smaller is fine on CPU)")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    setup_backend(args)

    import jax
    import numpy as np

    shape = (args.input_dim_x, args.input_dim_y, args.input_dim_z)
    dtype = np.float64 if args.double_prec else np.float32
    it, wu = args.iterations, args.warmup_rounds

    if getattr(args, "selftest", False):
        # The reference executable has no distributed plan; its selftest
        # is the single-device roundtrip at this shape (the coordinator-
        # rank baseline every distributed run is validated against).
        from .. import params as pm
        from ..models.slab import SlabFFTPlan
        from ..resilience.selftest import run_selftest
        be = args.fft_backend if args.fft_backend != "auto" else "xla"
        plan = SlabFFTPlan(
            pm.GlobalSize(*shape), pm.SlabPartition(1),
            pm.Config(double_prec=args.double_prec, fft_backend=be,
                      guards=getattr(args, "guards", None)))
        if not run_selftest(plan)["ok"]:
            print("selftest FAILED; aborting", file=sys.stderr)
            return 1

    if args.autotune:
        from ..testing import autotune as at
        prec = "f64" if args.double_prec else "f32"
        print(f"autotuning local FFT backends for {shape} {prec} on "
              f"{jax.devices()[0].platform}:")
        with maybe_profile(args):
            ranked = at.autotune_local_fft(shape, args.autotune_budget,
                                           k=args.autotune_k,
                                           double_prec=args.double_prec,
                                           verbose=True)
        best = ranked[0]
        if not best.ok:
            print(f"no usable backend: {at.describe_failures(ranked)}",
                  file=sys.stderr)
            return 1
        print(f"best: {best.label} ({best.per_iter_ms:.3f} ms/roundtrip, "
              f"rel_err {best.rel_err:.2e})")
        # Persist the measured winner so later runs (bench.py warm-start,
        # --fft-backend auto plans of this shape) reuse it instead of
        # re-racing — the explicit "tune once" entry point.
        from ..utils import wisdom
        store = wisdom.open_store(args.wisdom, not args.no_wisdom)
        if store is not None:
            key = wisdom.local_key(shape, args.double_prec)
            if store.record(key, "local_fft", wisdom.local_fft_record(best)):
                print(f"wisdom: winner recorded -> {store.path}")
        print_obs_snapshot(args)
        return 0

    with maybe_profile(args):
        rc = _dispatch(args, shape, dtype, it, wu)
    print_obs_snapshot(args)
    return rc


def _dispatch(args, shape, dtype, it, wu) -> int:
    import jax

    from ..testing import microbench as mb

    if args.testcase == 0:
        backend = args.fft_backend
        settings = None
        if backend == "auto":
            # Bare single-device transform: resolve via the wisdom store
            # (hit -> reuse, miss -> bounded race-and-record), mirroring
            # what the plan constructors do for Config(fft_backend="auto").
            from .. import params as pm
            from ..utils import wisdom
            backend, rec = wisdom.resolve_local_backend(
                shape, args.double_prec, path=args.wisdom,
                enabled=not args.no_wisdom)
            src = "wisdom" if rec is not None else "fallback"
            print(f"fft-backend auto -> {backend} ({src})")
            if rec is not None:
                # The gate/timing in the record were measured at the raced
                # precision/direct_max — run the SAME program, not the
                # backend at default MXU settings.
                settings = pm.Config(
                    fft_backend=backend,
                    mxu_precision=rec.get("mxu_precision"),
                    mxu_direct_max=rec.get("mxu_direct_max"),
                ).mxu_settings()
        ms = mb.single_device_fft_ms(shape, it, wu, dtype,
                                     backend=backend, settings=settings)
        print(f"Run complete: {ms:.4f} ms (single-device 3D R2C, "
              f"{shape[0]}x{shape[1]}x{shape[2]})")
        if getattr(args, "profile_stages", False):
            print("stage profile: needs a declared plan graph — the "
                  "single-device baseline has none (use testcase 4 or a "
                  "decomposition executable)")
        return 0

    p = len(jax.devices())
    if args.testcase in (1, 2, 3):
        explicit = args.opt != 0  # opt 0: Peer2Peer/GSPMD, opt 1: All2All
        geometry = {1: "1d", 2: "2d", 3: "3d"}[args.testcase]
        r = mb.transpose_bandwidth(shape, p, explicit=explicit,
                                   iterations=it or 1, warmup=wu,
                                   dtype=dtype, geometry=geometry)
        kind = "All2All" if explicit else "Peer2Peer(GSPMD)"
        print(f"Bandwidth: {r['gb_per_s'] * 1e3:.2f} MB/s "
              f"[{kind}, {geometry}, {p} devices, "
              f"{r['bytes'] / 1e6:.1f} MB moved in {r['seconds'] * 1e3:.3f} ms, "
              f"collectives={r['collective_ops']}]")
        if getattr(args, "profile_stages", False):
            print("stage profile: needs a declared plan graph — the "
                  "geometry probes have none (use testcase 4 or a "
                  "decomposition executable)")
        return 0
    if args.testcase == 4:
        import numpy as np

        from .. import params as pm
        from ..models.slab import SlabFFTPlan

        g = pm.GlobalSize(*shape)
        from .common import overlap_config_kwargs
        plan = SlabFFTPlan(g, pm.SlabPartition(p),
                           pm.Config(comm_method=pm.CommMethod.ALL2ALL,
                                     double_prec=args.double_prec,
                                     guards=getattr(args, "guards", None),
                                     **overlap_config_kwargs(args)))
        x = plan.pad_input(np.random.default_rng(0).random(g.shape)
                           .astype(dtype))
        spec = plan.forward_stages()[0][1](x)
        # --streams-chunks N (N > 1: chunks=1 is byte-identical to the
        # monolithic opt1 chain) adds the chunked-exchange rendering
        # (opt1sN) to the selection race, so the gate can report whether
        # splitting the collective beats the monolithic realigned
        # exchange.
        sc = getattr(args, "streams_chunks", None)
        sv = (sc,) if sc and sc > 1 else ()
        try:
            r = mb.transpose_fraction_chain(plan, spec,
                                            repeats=max(it or 1, 3),
                                            warmup=max(wu, 1),
                                            streams_variants=sv)
        except ValueError as e:  # shape/divisibility precondition
            print(f"fraction gate unavailable for this shape: {e}",
                  file=sys.stderr)
            return 2
        if r.get("degenerate"):
            print(f"fraction chain degenerate ({r['dropped']} repeats "
                  "noise-swamped; raise -i or use a bigger size)",
                  file=sys.stderr)
            return 1
        lo, hi = r["fraction_spread"]
        rlo, rhi = r.get("fraction_range", (lo, hi))
        print(f"All2All fraction: {r['fraction']:.3f} "
              f"[{r.get('variant', 'opt0')}, IQR {lo:.3f}-{hi:.3f}, "
              f"range {rlo:.3f}-{rhi:.3f}, "
              f"pipeline {r['pipe_gb_per_s']:.3f} GB/s vs ceiling "
              f"{r['raw_gb_per_s']:.3f} GB/s, k={r['k']}, "
              f"{p} devices]")
        print_stage_profile(plan, args)
        return 0
    print(f"unknown testcase {args.testcase}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
