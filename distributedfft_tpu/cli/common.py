"""Shared CLI plumbing for the three executables.

The reference ships hand-rolled parsers (``getValueOfParam``/``checkFlag``,
``tests/src/slab/main.cpp:76-118``) with both long and short option names;
here argparse carries the same flag surface (argparse accepts multi-char
short options like ``-nx`` verbatim).

Device selection: by default the real backend is used (TPU under axon). Set
``--emulate-devices N`` (or env ``DFFT_EMULATE_DEVICES``) to force N virtual
CPU devices — the testing story the reference lacks (it can only test
multi-rank on real clusters, SURVEY §4).
"""

from __future__ import annotations

import argparse
import os

from ..ops.fft import BACKENDS


def add_common_args(ap: argparse.ArgumentParser, pencil: bool = False,
                    comm_tunable: bool = False) -> None:
    ap.add_argument("--input-dim-x", "-nx", type=int, required=True,
                    help="size of the input data in x-direction")
    ap.add_argument("--input-dim-y", "-ny", type=int, required=True,
                    help="size of the input data in y-direction")
    ap.add_argument("--input-dim-z", "-nz", type=int, required=True,
                    help="size of the input data in z-direction")
    ap.add_argument("--testcase", "-t", type=int, default=0,
                    help="which testcase to execute (0-4)")
    ap.add_argument("--opt", "-o", type=int, default=0, choices=(0, 1),
                    help="0: default layout; 1: realigned (coordinate "
                         "transform) layout")
    ap.add_argument("--iterations", "-i", type=int, default=1)
    ap.add_argument("--warmup-rounds", "-w", type=int, default=0)
    ap.add_argument("--cuda_aware", "-c", action="store_true", default=True,
                    help="accepted for reference CLI compatibility; "
                         "device-resident collectives are always on for TPU "
                         "(default true, matching Config.cuda_aware, so CLI "
                         "and library runs share one CSV namespace)")
    ap.add_argument("--host-staged", dest="cuda_aware", action="store_false",
                    help="label this run as host-staged (cuda=0 in CSV names)")
    ap.add_argument("--double_prec", "-d", action="store_true",
                    help="use float64/complex128 (CPU backend only; TPU has "
                         "no native f64)")
    ap.add_argument("--benchmark_dir", "-b", default="benchmarks",
                    help="prefix for the benchmark directory")
    ap.add_argument("--fft-backend", default="xla",
                    choices=BACKENDS + ("auto",),
                    help="local transform implementation: XLA's FFT "
                         "expansion (default), MXU four-step DFT matmuls "
                         "(ops/mxu_fft.py), Pallas fused DFT+twiddle "
                         "kernels (ops/pallas_fft.py), or 'auto' — pick by "
                         "measurement via the wisdom store (race once, "
                         "reuse on every later run; see --wisdom)")
    ap.add_argument("--wisdom", default=None, metavar="PATH",
                    help="persistent plan-wisdom store (JSON; default "
                         "$DFFT_WISDOM, unset = no store): 'auto' choices "
                         "and --autotune[-comm] winners are recorded there "
                         "and reused silently on later runs — the FFTW-"
                         "wisdom analog of the reference's plan-time tuning")
    ap.add_argument("--no-wisdom", action="store_true",
                    help="never consult or write the wisdom store ('auto' "
                         "then re-races each run; with concrete backends "
                         "this is byte-identical to not having wisdom)")
    ap.add_argument("--emulate-devices", type=int,
                    default=int(os.environ.get("DFFT_EMULATE_DEVICES", "0")),
                    help="force N virtual CPU devices (0 = use real backend)")
    ap.add_argument("--profile-dir", default=None,
                    help="write a jax.profiler trace of the testcase run to "
                         "this directory (view with TensorBoard / Perfetto) — "
                         "the deep-dive complement to the per-phase Timer "
                         "CSVs, SURVEY §5 tracing; obs span names appear on "
                         "the trace as dfft:* annotations")
    ap.add_argument("--profile-stages", action="store_true",
                    help="after the run, measure a short stage-attributed "
                         "device profile: a few forward iterations under "
                         "jax.profiler.trace with device time joined back "
                         "onto the declared plan-graph nodes "
                         "(obs/profile.py) — per-stage ms, exchange-vs-"
                         "compute split, per-stage roofline gap")
    ap.add_argument("--obs", action="store_true",
                    help="observability console: print wisdom-provenance "
                         "one-liners (hit|miss|migrated) as they happen and "
                         "the obs metrics snapshot after the run (the "
                         "structured event log is separate: $DFFT_OBS_DIR / "
                         "--obs-dir)")
    ap.add_argument("--obs-dir", default=None, metavar="DIR",
                    help="write the structured JSONL event log (spans + "
                         "events; see README 'Observability') under this "
                         "directory — same effect as $DFFT_OBS_DIR, default "
                         "off")
    ap.add_argument("--multihost", action="store_true",
                    help="join the multi-controller runtime (one process per "
                         "host; rendezvous via DFFT_COORDINATOR / "
                         "DFFT_NUM_PROCESSES / DFFT_PROCESS_ID or TPU-pod "
                         "autodetection; see parallel/multihost.py and "
                         "jobs/tpu/scripts/). Perf testcases only (0, 2)")
    if comm_tunable:
        # Only the decomposition executables run plans; the reference
        # executable's probes have no comm matrix to tune.
        ap.add_argument("--autotune-comm", action="store_true",
                        help="race the comm-strategy matrix (All2All vs "
                             "Peer2Peer per transpose, x opt 0/1) for this "
                             "size on the active mesh before running, and "
                             "use the measured winner — the TPU rendering "
                             "of the reference's primary comparative "
                             "dimension (transpose is >=97%% of runtime at "
                             "scale)")
    if pencil:
        ap.add_argument("--comm-method1", "-comm1", default="Peer2Peer",
                        help='"Peer2Peer" (XLA-scheduled redistribution), '
                             '"All2All" (explicit collective) or "auto" '
                             '(measured via the wisdom store; owns the whole '
                             'comm x send x opt x chunks choice), transpose 1')
        ap.add_argument("--send-method1", "-snd1", default="Sync",
                        help="Sync (monolithic exchange) | Streams (chunked/"
                             "pipelined transpose, see --streams-chunks) | "
                             "Ring (ppermute-ring exchange with per-block "
                             "FFTs pipelined between steps; owns the "
                             "rendering regardless of comm method) | "
                             "RingOverlap (the ring on the double-"
                             "buffered schedule — bit-identical output, "
                             "one transfer in flight under every "
                             "block's compute) | "
                             "MPI_Type (alias of Sync)")
        ap.add_argument("--comm-method2", "-comm2", default=None,
                        help="same as --comm-method1 for transpose 2")
        ap.add_argument("--send-method2", "-snd2", default=None)
    else:
        ap.add_argument("--comm-method", "-comm", default="Peer2Peer",
                        help='"Peer2Peer", "All2All" or "auto" (measured '
                             "via the wisdom store; owns the whole comm x "
                             "send x opt x chunks choice)")
        ap.add_argument("--send-method", "-snd", default="Sync",
                        help="Sync (monolithic exchange) | Streams (chunked/"
                             "pipelined transpose, see --streams-chunks) | "
                             "Ring (ppermute-ring exchange with per-block "
                             "FFTs pipelined between steps; owns the "
                             "rendering regardless of comm method) | "
                             "RingOverlap (the ring on the double-"
                             "buffered schedule — bit-identical output, "
                             "one transfer in flight under every "
                             "block's compute) | "
                             "MPI_Type (alias of Sync)")
    ap.add_argument("--streams-chunks", type=int, default=None,
                    help="piece count for the Streams pipelined transpose "
                         "(default 4; ignored unless a send method is "
                         "Streams)")
    ap.add_argument("--overlap-depth", default="auto",
                    help="revolving receive-buffer depth of the overlapped "
                         "exchange schedules (RingOverlap and the pipelined "
                         "all-to-all): up to depth-1 transfers are issued "
                         "ahead of the compute consuming them (capped at "
                         "ranks-1 ring steps). 2 | 4 | 8 | 'auto' (default: "
                         "the comm race / wisdom picks when the comm choice "
                         "is 'auto', else the shipped double-buffered "
                         "depth 2)")
    ap.add_argument("--overlap-subblocks", type=int, default=None,
                    help="split every exchanged peer block into this many "
                         "sub-blocks so the first sub-block's compute "
                         "starts before the whole block arrives (default "
                         "1 = whole blocks). With a Sync/MPI_Type send on "
                         "All2All, >1 selects the software-pipelined "
                         "all-to-all rendering (a2a_pipe) instead of the "
                         "monolithic collective")
    ap.add_argument("--wire-dtype", "-wire",
                    default=os.environ.get("DFFT_WIRE", "native"),
                    choices=("native", "bf16", "auto"),
                    help="wire encoding of the global exchanges (default "
                         "$DFFT_WIRE or 'native'): 'native' = bit-identical "
                         "payload; 'bf16' = OPT-IN LOSSY planar (real, imag) "
                         "bf16 pair encoded immediately before each "
                         "collective and decoded after — half the wire "
                         "bytes of a complex64 exchange (~2e-3 max rel "
                         "error per crossing, README 'wire dtype'); 'auto' "
                         "= race compressed vs native on this shape under "
                         "--wire-error-budget and reuse the recorded "
                         "winner via the wisdom store")
    ap.add_argument("--wire-error-budget", type=float, default=None,
                    help="max rel error (vs the native path, measured on "
                         "the actual shape) the 'auto' wire race accepts "
                         "from a compressed wire (default 2e-2); tighter "
                         "budgets fall back to native")
    ap.add_argument("--guards", default=None,
                    choices=("off", "check", "enforce"),
                    help="in-graph numerical guards (resilience layer; "
                         "default $DFFT_GUARDS or 'off'): 'check' adds a "
                         "Parseval/energy-conservation residual and (on a "
                         "compressed wire) a drift probe to every jitted "
                         "pipeline — violations are counted/noticed and a "
                         "drifting wire demotes itself to native; "
                         "'enforce' raises a structured GuardViolation "
                         "instead (README 'Resilience')")
    ap.add_argument("--selftest", action="store_true",
                    help="run one guarded forward+inverse roundtrip of "
                         "this exact plan (Parseval + roundtrip identity "
                         "+ host np.fft reference at small sizes) and "
                         "print a PASS/FAIL line before the timed loop; "
                         "FAIL aborts with exit code 1")
    ap.add_argument("--tc1-truth", choices=("host", "analytic"),
                    default="host",
                    help="testcase-1 ground truth: 'host' = dense random "
                         "input vs full np.fft on the host (reference "
                         "parity, host-memory-bound); 'analytic' = sine "
                         "field vs its closed-form spectrum, both built "
                         "on device — validates at sizes the host truth "
                         "cannot reach")


def wisdom_config_kwargs(args) -> dict:
    """Config kwargs carrying the CLI wisdom surface (--wisdom/--no-wisdom,
    shared by all four executables). Defaults reproduce pre-wisdom behavior
    exactly: no flag + no $DFFT_WISDOM = no store is ever touched."""
    return {"wisdom_path": getattr(args, "wisdom", None),
            "use_wisdom": not getattr(args, "no_wisdom", False)}


def wire_config_kwargs(args) -> dict:
    """Config kwargs carrying the CLI wire surface (-wire /
    --wire-error-budget; shared by the decomposition executables).
    Defaults reproduce pre-wire behavior exactly: no flag + no $DFFT_WIRE
    = the bit-identical native wire."""
    from .. import params as pm
    return {"wire_dtype": pm.parse_wire_dtype(
                getattr(args, "wire_dtype", "native")),
            "wire_error_budget": getattr(args, "wire_error_budget", None)}


def overlap_config_kwargs(args) -> dict:
    """Config kwargs carrying the CLI overlap surface (--overlap-depth /
    --overlap-subblocks; shared by all four executables). Defaults
    reproduce the shipped schedules exactly: depth 'auto' resolves to the
    double-buffered depth 2 outside a race, and no sub-block split keeps
    whole-block exchanges."""
    from .. import params as pm
    return {"overlap_depth": pm.parse_overlap_depth(
                getattr(args, "overlap_depth", "auto")),
            "overlap_subblocks": getattr(args, "overlap_subblocks", None)}


def resilience_config_kwargs(args) -> dict:
    """Config kwargs carrying the CLI resilience surface (--guards).
    Default None defers to $DFFT_GUARDS -> "off", reproducing pre-guard
    behavior (byte-identical programs) exactly."""
    return {"guards": getattr(args, "guards", None)}


def maybe_selftest(plan, args, dims=None) -> bool:
    """--selftest: one guarded roundtrip of the exact plan before the
    timed loop (resilience/selftest.py); returns False — abort with exit
    code 1 — on FAIL."""
    if not getattr(args, "selftest", False):
        return True
    from ..resilience.selftest import run_selftest
    return bool(run_selftest(plan, dims=dims)["ok"])


def maybe_autotune_comm(args, kind, global_size, partition, cfg,
                        sequence=None, dims=3, variant=None,
                        transform="r2c"):
    """--autotune-comm: race the comm matrix for this shape on the active
    mesh, print the measured table, and return the winning Config (the
    original one when the flag is off). ``dims`` is the pencil partial
    depth and ``transform`` the r2c/c2c choice, so the race times the
    program the run will actually execute. The winner is also recorded
    into the wisdom store when one is configured, so later runs can reuse
    it via ``comm-method auto``."""
    if not getattr(args, "autotune_comm", False):
        return cfg
    if dims < 2:
        print("autotune-comm: dims=1 performs no transpose; nothing to tune")
        return cfg
    from ..testing import autotune as at

    print(f"autotuning comm strategies for {global_size.shape} "
          f"({kind}, {partition.num_ranks} ranks, dims={dims}):")
    base = cfg  # the config the send=None candidates were actually timed on
    from .. import params as pm
    ranked = at.autotune_comm(kind, global_size, partition, base,
                              sequence=sequence, dims=dims,
                              transform=transform,
                              iterations=max(args.iterations, 3),
                              warmup=max(args.warmup_rounds, 1),
                              race_send=True,
                              # -wire auto hands the wire axis to this race
                              # (bf16 twins, error-budget-gated); an
                              # explicit -wire is respected, not re-raced.
                              race_wire=cfg.wire_dtype == pm.AUTO,
                              verbose=True)
    best = ranked[0]
    cfg = at.apply_best_comm(ranked, base)
    runner = ranked[1] if len(ranked) > 1 and ranked[1].ok else None
    delta = (f", {runner.total_ms - best.total_ms:+.3f} ms vs next "
             f"({runner.label})" if runner else "")
    print(f"best: {best.label} ({best.total_ms:.3f} ms roundtrip{delta})")
    from ..utils import wisdom
    store = wisdom.store_for_config(cfg)
    if store is not None and best.ok:
        key = wisdom.plan_key(kind, global_size.shape, cfg.double_prec,
                              partition, cfg.norm, sequence=sequence,
                              variant=variant, transform=transform,
                              dims=dims)
        if store.record(key, "comm", wisdom.comm_record(best, base)):
            print(f"wisdom: comm winner recorded -> {store.path}")
    return cfg


def maybe_profile(args):
    """Context manager: a ``jax.profiler.trace`` over the block when
    ``--profile-dir`` was given, a no-op otherwise (shared by all CLIs)."""
    import contextlib

    profile_dir = getattr(args, "profile_dir", None)
    if not profile_dir:
        return contextlib.nullcontext()
    import jax
    return jax.profiler.trace(profile_dir)


def run_testcase(plan, args, dims=None) -> int:
    """Dispatch -t N to the testcase implementations and print the perf
    summary; shared by the slab and pencil executables. ``dims`` is the
    pencil-only --fft-dim depth (None for slab)."""
    import sys

    from ..testing import testcases as tc

    fn = {0: tc.testcase0, 1: tc.testcase1, 2: tc.testcase2,
          3: tc.testcase3, 4: tc.testcase4}.get(args.testcase)
    if fn is None:
        print(f"unknown testcase {args.testcase}", file=sys.stderr)
        return 2
    import jax
    tc1_analytic = (args.testcase == 1
                    and getattr(args, "tc1_truth", "host") == "analytic")
    if (jax.process_count() > 1 and args.testcase not in (0, 2)
            and not tc1_analytic):
        # Validation testcases compare against a host-side reference array,
        # which no single controller holds in a multi-host run. Like the
        # reference, validate at single-host scale (jobs/**/validation.json
        # run small sizes) and benchmark at pod scale. Exception: tc1 with
        # --tc1-truth analytic is fully device-resident (sine field vs
        # closed-form spectrum), so it validates at pod scale too —
        # something the reference's coordinator-rank scheme cannot do.
        print("testcases 1/3/4 validate against a host-side reference and "
              "need a single-controller run (use --emulate-devices or one "
              "host); multi-host supports perf testcases 0 and 2, plus "
              "testcase 1 with --tc1-truth analytic",
              file=sys.stderr)
        return 2
    if not maybe_selftest(plan, args, dims=dims):
        print("selftest FAILED; aborting before the timed loop",
              file=sys.stderr)
        return 1
    kwargs = {}
    if args.testcase in (0, 2, 3, 4):
        kwargs.update(iterations=args.iterations, warmup=args.warmup_rounds)
    if args.testcase == 1:
        kwargs["truth"] = getattr(args, "tc1_truth", "host")
    if dims is not None and args.testcase != 4:
        kwargs["dims"] = dims
    with maybe_profile(args):
        result = fn(plan, **kwargs)
    if "mean_ms" in result:
        print(f"Run complete: {result['mean_ms']:.4f} ms "
              f"(mean over {args.iterations} iterations)")
    print_obs_snapshot(args)
    print_stage_profile(plan, args, dims=dims)
    return 0


def setup_obs(args) -> None:
    """Apply the CLI observability surface (--obs / --obs-dir) before any
    plan is constructed, so provenance notices and build spans from the
    very first resolution are captured."""
    from .. import obs
    if getattr(args, "obs_dir", None):
        obs.enable(args.obs_dir)
    if getattr(args, "obs", False):
        obs.enable_console()


def print_stage_profile(plan, args, dims=None) -> None:
    """The ``--profile-stages`` epilogue (shared by all four CLIs): a
    short measured window of the forward plan under ``jax.profiler``,
    printed as device time per declared plan-graph node
    (``obs/profile.py``). Best-effort — a profile failure must never
    fail a run that already printed its result."""
    if not getattr(args, "profile_stages", False):
        return
    from ..obs import profile as prof_mod
    print("stage profile (measured device time per declared plan-graph "
          "node):")
    try:
        prof = prof_mod.stage_profile(plan, "forward",
                                      3 if dims is None else dims)
        print("\n".join(prof_mod.format_stage_profile(prof)))
    except Exception as e:  # noqa: BLE001 — epilogue is best-effort
        print(f"  unavailable: {type(e).__name__}: {e}")


def print_obs_snapshot(args) -> None:
    """The --obs epilogue: one compact JSON line of the metrics registry."""
    if not getattr(args, "obs", False):
        return
    import json as _json

    from .. import obs
    print("obs metrics: "
          + _json.dumps(obs.metrics.snapshot(), sort_keys=True))


def setup_backend(args) -> None:
    """Apply device emulation / multi-host rendezvous before any jax backend
    use. Must be called before the first jax device query."""
    setup_obs(args)
    import jax
    if args.emulate_devices:
        if getattr(args, "multihost", False):
            raise SystemExit("--multihost and --emulate-devices are mutually "
                             "exclusive (emulation is single-process)")
        from ..parallel.mesh import force_cpu_devices
        force_cpu_devices(args.emulate_devices)
    if getattr(args, "double_prec", False):
        jax.config.update("jax_enable_x64", True)
    if getattr(args, "multihost", False):
        from ..parallel.multihost import maybe_initialize
        maybe_initialize(require=True)
