"""Benchmark evaluation — the analog of the reference's eval layer (L7).

Reduces raw Timer CSVs (reference schema, see ``utils/timer.py``) into the
reference's reduced formats (``eval/global_redist/evaluation_slab.py``,
``evaluation_pencil.py``, ``eval/complete/plot_complete.py``):

* ``<out>/<variant>/runs/runs_<opt>_<P>_<cuda>.csv`` — header ``,,size...``,
  one ``comm,snd,means...`` row per strategy (mean "Run complete" ms);
* ``<out>/<variant>/sd/sd_<opt>_<P>_<cuda>.csv`` — same layout, standard
  deviations;
* ``<out>/proportions_<P>_<cuda>.csv`` — per variant: best strategy per
  size and each phase's share of "Run complete" for that strategy;
* ``<out>/results_<P>.csv`` — per (variant, opt) a row triple
  (CI low / mean / CI high) of "Run complete" across sizes, the format the
  reference's ``plot_complete.py`` emits (``results_{P}.csv``);
* optional matplotlib comparison plot when available.

Confidence intervals use the Student-t 95% interval like the reference
(``evaluation_slab.py`` via ``scipy.stats.t``).
"""

from __future__ import annotations

import argparse
import functools
import math
import os
import re
import sys
from collections import defaultdict
from typing import Dict, List, Tuple

import numpy as np

from ..utils.timer import read_timer_csv

# Slab: test_<opt>_<comm>_<snd>_<Nx>_<Ny>_<Nz>_<cuda>_<P>
#       [_d<depth>][_s<sub>][_w<wire>].csv
# Pencil: test_<opt>_<comm1>_<snd1>_<comm2>_<snd2>_<Nx>_<Ny>_<Nz>_<cuda>
#         _<P1>_<P2>[_d<depth>][_s<sub>][_w<wire>].csv
# The optional _w<code> token is the wire-dtype extension (utils/timer
# _WIRE_CODE; native omits it, keeping legacy names byte-for-byte) —
# non-native wires reduce as their own variant rows, like the batched2d
# _ck chunk variants, so compressed and native runs never merge. The
# _d<depth>/_s<sub> tokens are the overlap-schedule extension on the same
# pattern (utils/timer._overlap_suffix; the shipped depth-2/whole-block
# schedules omit them): each depth/sub-block combination reduces as its
# own variant row too.
_SLAB_FILE_RE = re.compile(
    r"test_(?P<opt>\d+)_(?P<comm>\d+)_(?P<snd>\d+)_(?P<nx>\d+)_(?P<ny>\d+)"
    r"_(?P<nz>\d+)_(?P<cuda>\d+)_(?P<p>\d+)(?:_d(?P<depth>\d+))?"
    r"(?:_s(?P<sub>\d+))?(?:_w(?P<wire>\d+))?\.csv$")
_PENCIL_FILE_RE = re.compile(
    r"test_(?P<opt>\d+)_(?P<comm>\d+)_(?P<snd>\d+)_(?P<comm2>\d+)"
    r"_(?P<snd2>\d+)_(?P<nx>\d+)_(?P<ny>\d+)_(?P<nz>\d+)_(?P<cuda>\d+)"
    r"_(?P<p1>\d+)_(?P<p2>\d+)(?:_d(?P<depth>\d+))?(?:_s(?P<sub>\d+))?"
    r"(?:_w(?P<wire>\d+))?\.csv$")

_COMM_NAMES = {0: "Peer2Peer", 1: "All2All"}
# 3/4 = the RING / RING_OVERLAP extensions, 0-2 the reference's own codes
# (params.hpp:87-89).
_SND_NAMES = {0: "Sync", 1: "Streams", 2: "MPI_Type", 3: "Ring",
              4: "RingOverlap"}
_WIRE_NAMES = {1: "bf16"}

_VARIANT_LABELS = {
    "slab_default": ("Slab", "2D-1D"),
    "slab_z_then_yx": ("Slab", "1D-2D"),
    "slab_y_then_zx": ("Slab", "1D-2D-Y"),
    "pencil": ("Pencil", ""),
    "batched2d_batch": ("Batched2D", "batch-sharded"),
    "batched2d_x": ("Batched2D", "x-sharded"),
}


def _variant_label(variant: str):
    """Pretty (family, flavor) label; chunked batched2d variants
    (``batched2d_<shard>_ck<N>``) derive from their base variant with the
    chunk appended so the whole open-ended family stays labeled."""
    if variant in _VARIANT_LABELS:
        return _VARIANT_LABELS[variant]
    base, sep, w = variant.rpartition("_w")
    if sep and w.isdigit():
        fam, flavor = _variant_label(base)
        wire = _WIRE_NAMES.get(int(w), f"wire{w}")
        return fam, f"{flavor} wire={wire}".strip()
    base, sep, sub = variant.rpartition("_s")
    if sep and sub.isdigit():
        fam, flavor = _variant_label(base)
        return fam, f"{flavor} subblocks={sub}".strip()
    base, sep, depth = variant.rpartition("_d")
    if sep and depth.isdigit():
        fam, flavor = _variant_label(base)
        return fam, f"{flavor} depth={depth}".strip()
    base, sep, ck = variant.rpartition("_ck")
    if sep and ck.isdigit() and base in _VARIANT_LABELS:
        fam, flavor = _VARIANT_LABELS[base]
        return fam, f"{flavor} chunk={ck}"
    return variant, ""


def _t_ci(values: np.ndarray, conf: float = 0.95) -> Tuple[float, float, float]:
    """(low, mean, high) Student-t confidence interval, reference-style."""
    m = float(np.mean(values))
    if len(values) < 2:
        return (m, m, m)
    sd = float(np.std(values, ddof=1))
    try:
        from scipy import stats
        h = sd / np.sqrt(len(values)) * stats.t.ppf((1 + conf) / 2, len(values) - 1)
    except ImportError:
        h = 1.96 * sd / np.sqrt(len(values))
    return (float(m - h), m, float(m + h))


def scan(prefix: str) -> Dict:
    """Collect raw Timer CSVs:
    {variant: {(opt, comm, snd, cuda, P): {size_label: blocks}}}."""
    data: Dict = defaultdict(lambda: defaultdict(dict))
    for variant in sorted(os.listdir(prefix)):
        vdir = os.path.join(prefix, variant)
        if not os.path.isdir(vdir):
            continue
        for fname in sorted(os.listdir(vdir)):
            m = _PENCIL_FILE_RE.match(fname) or _SLAB_FILE_RE.match(fname)
            if not m:
                continue
            g = {k: int(v) for k, v in m.groupdict().items()
                 if v is not None}
            size = f"{g['nx']}_{g['ny']}_{g['nz']}"
            p = g.get("p", g.get("p1", 1) * g.get("p2", 1))
            # pencil strategy identity includes the second transpose
            comm = (g["comm"], g["comm2"]) if "comm2" in g else g["comm"]
            snd = (g["snd"], g["snd2"]) if "snd2" in g else g["snd"]
            key = (g["opt"], comm, snd, g["cuda"], p)
            # Non-native wires reduce as their own variant (the CSV schema
            # keeps them in separate files; merging them into the native
            # rows would average lossy and lossless runs). Overlap
            # depth/sub-block variants follow the same rule — each timed
            # schedule stays its own row.
            vkey = variant
            if g.get("depth"):
                vkey += f"_d{g['depth']}"
            if g.get("sub"):
                vkey += f"_s{g['sub']}"
            if g.get("wire"):
                vkey += f"_w{g['wire']}"
            data[vkey][key][size] = read_timer_csv(os.path.join(vdir, fname))
    return data


FUSED_DESC = "Run complete (fused)"


def _run_complete(blocks) -> np.ndarray:
    return np.array([b["Run complete"][0] for b in blocks
                     if "Run complete" in b])


def _fused_ms(blocks) -> np.ndarray:
    """Fused-production-program time per iteration: the FUSED_DESC mark
    minus the "Run complete" mark (the fused call runs right after the
    staged pipeline inside the same timer window)."""
    return np.array([b[FUSED_DESC][0] - b["Run complete"][0] for b in blocks
                     if FUSED_DESC in b and "Run complete" in b
                     and b[FUSED_DESC][0] > 0.0])


def _phase_durations(blocks) -> Dict[str, float]:
    """Mean per-phase durations from the cumulative timeline markers: each
    stored section's duration is its mark minus the largest earlier mark
    (sections never stored contribute 0). The "Run complete" total and the
    fused-run marker are not phases."""
    sums: Dict[str, List[float]] = defaultdict(list)
    for b in blocks:
        marks = [(d, v[0]) for d, v in b.items() if v and v[0] > 0.0]
        marks.sort(key=lambda kv: kv[1])
        prev = 0.0
        for desc, mark in marks:
            if desc in ("Run complete", FUSED_DESC):
                continue
            sums[desc].append(mark - prev)
            prev = mark
    return {d: float(np.mean(v)) for d, v in sums.items()}


def _size_sort_key(label: str):
    return tuple(int(t) for t in label.split("_"))


def _strategy_names(comm, snd):
    """Human strategy labels; pencil strategies are (t1, t2) tuples joined
    with '+' when the two transposes differ."""
    def one(table, v):
        if isinstance(v, tuple):
            a, b = table[v[0]], table[v[1]]
            return a if a == b else f"{a}+{b}"
        return table[v]
    return one(_COMM_NAMES, comm), one(_SND_NAMES, snd)


def reduce_prefix(prefix: str, out: str,
                  make_plots: bool = False) -> "Dict | None":
    """Reduce the raw tree; returns the scanned data so follow-up
    reducers (``scalability_stages``) can reuse it without re-walking."""
    data = scan(prefix)
    if not data:
        print(f"no Timer CSVs found under {prefix}", file=sys.stderr)
        return None
    os.makedirs(out, exist_ok=True)

    # union of sizes per (P, cuda) across variants, for results files
    # (label, cuda, (lo/mean/hi value lists), size labels) per variant row
    results_rows: Dict[int, List[Tuple[str, int, List, List[str]]]] = \
        defaultdict(list)
    proportions: Dict[Tuple[int, int], List[str]] = defaultdict(list)
    # (label, sizes, per-size {phase: share}) per variant
    prop_plot_data: Dict[Tuple[int, int], List[Tuple]] = defaultdict(list)

    for variant, combos in data.items():
        vlabel = _variant_label(variant)
        by_opc: Dict[Tuple[int, int, int], Dict] = defaultdict(dict)
        for (opt, comm, snd, cuda, p), sizes in combos.items():
            by_opc[(opt, cuda, p)][(comm, snd)] = sizes

        for (opt, cuda, p), strategies in sorted(by_opc.items()):
            all_sizes = sorted({s for szs in strategies.values() for s in szs},
                               key=_size_sort_key)
            runs_dir = os.path.join(out, variant, "runs")
            sd_dir = os.path.join(out, variant, "sd")
            os.makedirs(runs_dir, exist_ok=True)
            os.makedirs(sd_dir, exist_ok=True)
            header = ",," + ",".join(all_sizes)
            runs_lines, sd_lines, fused_lines = [header], [header], [header]
            have_fused = False
            best_per_size: Dict[str, Tuple[float, Tuple[int, int]]] = {}
            ci_per_size: Dict[str, Tuple[float, float, float]] = {}
            for (comm, snd), sizes in sorted(strategies.items()):
                means, sds, fmeans = [], [], []
                for s in all_sizes:
                    if s not in sizes:
                        means.append("")
                        sds.append("")
                        fmeans.append("")
                        continue
                    rc = _run_complete(sizes[s])
                    lo, m, hi = _t_ci(rc)
                    means.append(repr(m))
                    sds.append(repr(float(np.std(rc, ddof=1))
                                    if len(rc) > 1 else 0.0))
                    fu = _fused_ms(sizes[s])
                    fmeans.append(repr(float(np.mean(fu))) if len(fu) else "")
                    have_fused = have_fused or len(fu) > 0
                    # A strategy whose blocks carry no "Run complete" mark
                    # yields NaN; it must never win (NaN < comparisons are
                    # all False, so once stored it could never be evicted).
                    if np.isfinite(m) and (s not in best_per_size
                                           or m < best_per_size[s][0]):
                        best_per_size[s] = (m, (comm, snd))
                        ci_per_size[s] = (lo, m, hi)
                cname, sname = _strategy_names(comm, snd)
                runs_lines.append(f"{cname},{sname}," + ",".join(means))
                sd_lines.append(f"{cname},{sname}," + ",".join(sds))
                fused_lines.append(f"{cname},{sname}," + ",".join(fmeans))
            with open(os.path.join(runs_dir, f"runs_{opt}_{p}_{cuda}.csv"),
                      "w") as f:
                f.write("\n".join(runs_lines) + "\n")
            with open(os.path.join(sd_dir, f"sd_{opt}_{p}_{cuda}.csv"),
                      "w") as f:
                f.write("\n".join(sd_lines) + "\n")
            if have_fused:
                # The production-path runtimes (one jitted program per
                # direction); the staged runs_* numbers above attribute
                # phases but overstate the total (per-stage dispatch +
                # fences, no cross-stage overlap).
                with open(os.path.join(runs_dir,
                                       f"fused_{opt}_{p}_{cuda}.csv"),
                          "w") as f:
                    f.write("\n".join(fused_lines) + "\n")

            # results triples: best strategy's CI per size
            label = ",".join(filter(None, [*vlabel,
                                           "Realigned" if opt else "Default"]))
            triple = [[], [], []]
            for s in all_sizes:
                lo, m, hi = ci_per_size.get(s, (np.nan,) * 3)
                for i, v in enumerate((lo, m, hi)):
                    triple[i].append(repr(v))
            results_rows[p].append((label, cuda, triple, all_sizes))

            # proportions for the best strategy per size
            prop_lines = [label, "," + ",".join(all_sizes)]
            best_names = []
            per_size_props: List[Dict[str, float]] = []
            phases_seen: List[str] = []
            for s in all_sizes:
                if s not in best_per_size:  # no strategy timed this size
                    best_names.append("")
                    per_size_props.append({})
                    continue
                _, (comm, snd) = best_per_size[s]
                cname, sname = _strategy_names(comm, snd)
                best_names.append(f"{cname}_{sname}")
                blocks = strategies[(comm, snd)][s]
                durs = _phase_durations(blocks)
                total = float(np.mean(_run_complete(blocks))) or 1.0
                per_size_props.append({d: v / total for d, v in durs.items()})
                for d in durs:
                    if d not in phases_seen:
                        phases_seen.append(d)
            prop_lines.append("," + ",".join(best_names))
            for d in phases_seen:
                vals = [repr(props.get(d, 0.0)) for props in per_size_props]
                prop_lines.append(d.replace(" ", "_").replace(",", "") + ","
                                  + ",".join(vals))
            proportions[(p, cuda)] += prop_lines + [""]
            prop_plot_data[(p, cuda)].append(
                (label, all_sizes, per_size_props))

    for (p, cuda), lines in proportions.items():
        with open(os.path.join(out, f"proportions_{p}_{cuda}.csv"), "w") as f:
            f.write("\n".join(lines) + "\n")
    for p, rows in results_rows.items():
        multiple_cuda = len({cuda for _, cuda, _, _ in rows}) > 1
        # Align every row to the per-P size union (blank cells for sizes a
        # variant did not run) so column k means the same size in every
        # row; the header names the columns.
        union = sorted({s for _, _, _, sizes in rows for s in sizes},
                       key=_size_sort_key)
        with open(os.path.join(out, f"results_{p}.csv"), "w") as f:
            f.write(f"TPU P={p}," + ",".join(union) + "\n")
            for label, cuda, triple, sizes in rows:
                if multiple_cuda:
                    label = f"{label},cuda{cuda}"
                col = {s: i for i, s in enumerate(sizes)}
                for vals in triple:
                    cells = [vals[col[s]] if s in col else "" for s in union]
                    f.write(label + "," + ",".join(cells) + "\n")
    if make_plots:
        _plot(results_rows, out)
        _plot_proportions(prop_plot_data, out)


@functools.lru_cache(maxsize=1)
def _pyplot():
    """Headless pyplot, or None (with a one-time notice) when matplotlib is
    absent — the shared guard for every plot writer here."""
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        return plt
    except ImportError:
        print("matplotlib unavailable; skipping plots", file=sys.stderr)
        return None


def _plot(results_rows, out: str) -> None:
    plt = _pyplot()
    if plt is None:
        return
    for p, rows in results_rows.items():
        # Shared categorical size axis: variants with different size sets
        # must align on actual sizes, not per-row indices.
        union = sorted({s for _, _, _, sizes in rows for s in sizes},
                       key=_size_sort_key)
        pos = {s: i for i, s in enumerate(union)}
        fig, ax = plt.subplots(figsize=(8, 5))
        for label, cuda, triple, sizes in rows:
            means = [float(v) if v != "nan" else np.nan for v in triple[1]]
            ax.plot([pos[s] for s in sizes], means, marker="o", label=label)
        ax.set_yscale("log")
        ax.set_xticks(range(len(union)))
        ax.set_xticklabels([s.replace("_", "×") for s in union],
                           rotation=30, ha="right", fontsize=7)
        ax.set_xlabel("global size")
        ax.set_ylabel("Run complete [ms]")
        ax.set_title(f"P={p}")
        ax.legend(fontsize=7)
        fig.tight_layout()
        fig.savefig(os.path.join(out, f"comparison_{p}.png"), dpi=120)
        plt.close(fig)


# Fixed categorical assignment for phase stacks (Okabe-Ito CVD-safe set);
# phases beyond the palette fold into a neutral "other" — identity is
# carried by the legend, never by generated hues.
_PHASE_COLORS = ("#0072B2", "#E69F00", "#009E73", "#CC79A7",
                 "#56B4E9", "#D55E00", "#F0E442")
_OTHER_COLOR = "#999999"


def _plot_proportions(prop_plot_data, out: str) -> None:
    """Stacked per-size phase-share bars for the best strategy per size —
    the visual analog of the reference's proportions plots
    (``eval/complete/plot_complete.py``). One figure per (P, cuda), one
    subplot per variant; the phase -> color map is fixed across subplots,
    with the tail beyond the palette folded into "other"."""
    plt = _pyplot()
    if plt is None:
        return
    for (p, cuda), variants in prop_plot_data.items():
        if not variants:
            continue
        # Global phase order by mean share, so the palette goes to the
        # phases that matter and "other" absorbs the long tail.
        totals: Dict[str, float] = defaultdict(float)
        for _, _, props in variants:
            for pr in props:
                for d, v in pr.items():
                    totals[d] += v
        ranked = sorted(totals, key=totals.get, reverse=True)
        major = ranked[:len(_PHASE_COLORS)]
        colors = dict(zip(major, _PHASE_COLORS))
        fig_h = 1.6 + 2.2 * len(variants)
        fig, axes = plt.subplots(len(variants), 1, squeeze=False,
                                 figsize=(8, fig_h))
        drew_other = False
        for ax, (label, sizes, props) in zip(axes[:, 0], variants):
            xs = np.arange(len(sizes))
            bottom = np.zeros(len(sizes))
            for d in major:
                vals = np.array([pr.get(d, 0.0) for pr in props])
                if not vals.any():
                    continue
                ax.bar(xs, vals, bottom=bottom, color=colors[d],
                       edgecolor="white", linewidth=1.0)
                bottom += vals
            other = np.array([sum(v for k, v in pr.items()
                                  if k not in colors) for pr in props])
            if other.any():
                drew_other = True
                ax.bar(xs, other, bottom=bottom, color=_OTHER_COLOR,
                       edgecolor="white", linewidth=1.0)
            ax.set_xticks(xs)
            ax.set_xticklabels([s.replace("_", "×") for s in sizes],
                               fontsize=7)
            ax.set_ylabel("share of Run complete", fontsize=7)
            ax.set_title(label, fontsize=8)
        # One figure-level legend covering EVERY phase used in any subplot
        # (a per-axes legend would list only that subplot's phases, leaving
        # the rest identified by color alone).
        from matplotlib.patches import Patch
        handles = [Patch(facecolor=colors[d], label=d) for d in major]
        if drew_other:
            handles.append(Patch(facecolor=_OTHER_COLOR, label="other"))
        fig.legend(handles=handles, fontsize=6, ncol=3, loc="upper center",
                   bbox_to_anchor=(0.5, 1.0))
        # tight_layout ignores figure-level legends: reserve ~0.55in of
        # absolute headroom for the 3-row legend whatever the figure height.
        fig.tight_layout(rect=(0, 0, 1, max(0.0, 1.0 - 0.55 / fig_h)))
        fig.savefig(os.path.join(out, f"proportions_{p}_{cuda}.png"),
                    dpi=120)
        plt.close(fig)


_RUNS_FILE_RE = re.compile(r"runs_(?P<opt>\d+)_(?P<p>\d+)_(?P<cuda>\d+)\.csv$")


def scalability(eval_dir: str, size: str, out_path: "str | None" = None,
                make_plot: bool = False) -> List[Tuple[str, int, int, float]]:
    """Strong-scaling table from reduced runs CSVs — the analog of the
    reference's ``eval/complete/scalability.py`` (best method per variant
    across process counts, log2/log2 time-vs-P plot).

    Scans ``<eval_dir>/<variant>/runs/runs_<opt>_<P>_<cuda>.csv`` for every
    P, takes the best (minimum mean "Run complete") strategy at ``size``,
    and emits rows ``variant,opt,P,best_ms,speedup,efficiency`` where
    speedup/efficiency are relative to the smallest P of that series
    (efficiency = t_Pmin * Pmin / (t_P * P)).
    Returns the [(variant_opt_label, cuda, P, best_ms)] rows.
    """
    if not os.path.isdir(eval_dir):
        print(f"no reduced eval outputs under {eval_dir}; run the reduction "
              "first (scalability reads <eval>/<variant>/runs/)",
              file=sys.stderr)
        return []
    series: Dict[Tuple[str, int, int], Dict[int, float]] = defaultdict(dict)
    for variant in sorted(os.listdir(eval_dir)):
        runs_dir = os.path.join(eval_dir, variant, "runs")
        if not os.path.isdir(runs_dir):
            continue
        for fname in sorted(os.listdir(runs_dir)):
            m = _RUNS_FILE_RE.match(fname)
            if not m:
                continue
            opt, p, cuda = (int(m["opt"]), int(m["p"]), int(m["cuda"]))
            with open(os.path.join(runs_dir, fname)) as f:
                lines = [l.rstrip("\n") for l in f if l.strip()]
            if not lines:  # truncated/empty reduce output: skip, don't abort
                continue
            cols = lines[0].split(",")
            try:
                idx = cols.index(size)
            except ValueError:
                continue
            best = None
            for row in lines[1:]:
                cells = row.split(",")
                if idx < len(cells) and cells[idx]:
                    v = float(cells[idx])
                    # 'nan' cells (reduce of a CSV without "Run complete"
                    # markers) poison min() and, at the smallest P, the
                    # whole series' speedup column — drop them.
                    if math.isnan(v):
                        continue
                    best = v if best is None else min(best, v)
            if best is not None:
                series[(variant, opt, cuda)][p] = best

    rows = []
    out_lines = ["variant,opt,cuda,P,best_ms,speedup,efficiency"]
    for (variant, opt, cuda), by_p in sorted(series.items()):
        ps = sorted(by_p)
        p0, t0 = ps[0], by_p[ps[0]]
        for p in ps:
            t = by_p[p]
            speedup = t0 / t
            eff = (t0 * p0) / (t * p)
            label = f"{variant}_{'realigned' if opt else 'default'}"
            rows.append((label, cuda, p, t))
            out_lines.append(
                f"{label},{opt},{cuda},{p},{t!r},{speedup!r},{eff!r}")

    if out_path is None:
        out_path = os.path.join(eval_dir, f"scalability_{size}.csv")
    with open(out_path, "w") as f:
        f.write(f"size,{size}\n" + "\n".join(out_lines) + "\n")

    if make_plot and series:
        plt = _pyplot()
        if plt is None:
            return rows
        fig, ax = plt.subplots(figsize=(8, 5))
        multi_cuda = len({c for _, _, c in series}) > 1
        for (variant, opt, cuda), by_p in sorted(series.items()):
            ps = sorted(by_p)
            label = f"{variant}_{'realigned' if opt else 'default'}"
            if multi_cuda:
                label += f"_cuda{cuda}"
            ax.plot(ps, [by_p[p] for p in ps], marker="o", label=label)
        ax.set_xscale("log", base=2)
        ax.set_yscale("log", base=2)
        ax.set_xlabel("devices P")
        ax.set_ylabel('best "Run complete" [ms]')
        ax.set_title(f"Strong scaling, {size}")
        ax.grid(True, color="grey", alpha=0.4)
        ax.legend(fontsize=8)
        fig.savefig(os.path.splitext(out_path)[0] + ".png", dpi=120)
        plt.close(fig)
    return rows


def scalability_stages(prefix: str, size: str,
                       out_path: "str | None" = None,
                       data: "Dict | None" = None) -> List[tuple]:
    """Compute-vs-exchange decomposition of the strong-scaling series
    (VERDICT r3 weak#2: a scalability table whose headline trend is
    "more devices = slower" must say WHERE the time goes).

    For each (variant, opt, cuda) series, takes the best strategy at
    ``size`` per P (same min-mean-total criterion as ``scalability``),
    splits its phase durations into FFT stages vs transpose/exchange
    stages, and emits
    ``variant,opt,cuda,P,total_ms,fft_ms,xpose_ms,fft_vs_P0,xpose_vs_P0``
    where the ``_vs_P0`` columns are the stage time relative to the
    series' smallest P WITH stage marks (a fused single-program P=1 row
    records only the total; a zero baseline would nan out the whole
    series). Interpretation on a virtual mesh (all "devices" share one
    host's cores): the two ratio columns separate failure modes rather
    than promise a shape. Measured quiet-host behavior (round 4,
    committed ``scalability_stages_256_256_256.csv``) has BOTH classes
    shrinking with P — more executors soak otherwise-idle cores — while
    a loaded host inflates both together (the round-3 tree's apparent
    anti-scaling). A pipeline regression, by contrast, shows up in ONE
    column (the exchange) against a flat-or-shrinking compute column;
    that asymmetry is what this table exists to detect.

    ``data``: pre-scanned raw tree (``scan(prefix)``) so callers that
    already scanned (``main`` via ``reduce_prefix``) don't re-walk and
    re-parse every Timer CSV."""
    if data is None:
        data = scan(prefix)
    series: Dict[tuple, Dict[int, tuple]] = defaultdict(dict)
    for variant, by_key in sorted(data.items()):
        for (opt, comm, snd, cuda, p), by_size in sorted(by_key.items()):
            if size not in by_size:
                continue
            blocks = by_size[size]
            totals = _run_complete(blocks)
            if not len(totals):
                continue
            total = float(np.mean(totals))
            cur = series[(variant, opt, cuda)].get(p)
            if cur is not None and cur[0] <= total:
                continue
            phases = _phase_durations(blocks)
            fft = sum(v for d, v in phases.items() if "FFT" in d)
            xpose = sum(v for d, v in phases.items() if "Transpose" in d)
            series[(variant, opt, cuda)][p] = (total, fft, xpose)

    rows = []
    lines = ["variant,opt,cuda,P,total_ms,fft_ms,xpose_ms,"
             "fft_vs_P0,xpose_vs_P0"]
    for (variant, opt, cuda), by_p in sorted(series.items()):
        ps = sorted(by_p)
        # Ratio baseline: the smallest P that actually has stage marks.
        base_ps = [p for p in ps if by_p[p][1] > 0 or by_p[p][2] > 0]
        _, fft0, xpose0 = by_p[base_ps[0]] if base_ps else by_p[ps[0]]
        for p in ps:
            total, fft, xpose = by_p[p]
            fft_r = fft / fft0 if fft0 > 0 else float("nan")
            xp_r = xpose / xpose0 if xpose0 > 0 else float("nan")
            label = f"{variant}_{'realigned' if opt else 'default'}"
            rows.append((label, cuda, p, total, fft, xpose))
            lines.append(f"{label},{opt},{cuda},{p},{total:.3f},{fft:.3f},"
                         f"{xpose:.3f},{fft_r:.3f},{xp_r:.3f}")
    if out_path is None:
        out_path = os.path.join(prefix, "eval",
                                f"scalability_stages_{size}.csv")
    with open(out_path, "w") as f:
        f.write(f"size,{size}\n" + "\n".join(lines) + "\n")
    return rows


def numerical_results(log_dir: str, out_path: str) -> int:
    """Parse ``Result`` lines from launcher stdout logs (.out/.txt) into an
    accuracy table — the analog of ``eval/complete/numerical_results.py``
    keying on lines containing "Result" after a launcher command echo."""
    rows = []
    for fname in sorted(os.listdir(log_dir)):
        if not (fname.endswith(".out") or fname.endswith(".txt")):
            continue
        last_cmd = ""
        with open(os.path.join(log_dir, fname)) as f:
            for line in f:
                line = line.strip()
                if "distributedfft_tpu.cli" in line:
                    last_cmd = line
                elif line.startswith("Result") and last_cmd:
                    rows.append((fname, last_cmd, line))
    with open(out_path, "w") as f:
        f.write("log,command,result\n")
        for r in rows:
            f.write(",".join('"%s"' % c.replace('"', "'") for c in r) + "\n")
    return len(rows)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--prefix", required=True,
                    help="benchmark dir holding <variant>/test_*.csv files")
    ap.add_argument("--out", default=None,
                    help="output dir (default: <prefix>/eval)")
    ap.add_argument("--plots", action="store_true")
    ap.add_argument("--logs", default=None,
                    help="also parse Result lines from this log dir")
    ap.add_argument("--scalability", default=None, metavar="SIZE",
                    help='also emit a strong-scaling table/plot for this '
                         'size label (e.g. "1024_1024_1024") across all '
                         'reduced process counts')
    args = ap.parse_args(argv)
    out = args.out or os.path.join(args.prefix, "eval")
    scanned = reduce_prefix(args.prefix, out, make_plots=args.plots)
    if args.logs:
        n = numerical_results(args.logs, os.path.join(out, "numerical_results.csv"))
        print(f"parsed {n} Result lines")
    if args.scalability:
        rows = scalability(out, args.scalability, make_plot=args.plots)
        print(f"scalability: {len(rows)} rows for size {args.scalability}")
        srows = scalability_stages(
            args.prefix, args.scalability,
            os.path.join(out, f"scalability_stages_{args.scalability}.csv"),
            data=scanned)
        print(f"scalability stages: {len(srows)} rows")
    print(f"eval written to {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
