"""MXU-utilization roofline for the matmul FFT backend (VERDICT r3 item 5).

The artifact CSVs quote FFT-NOMINAL GFLOPS (2.5·N·log2 N — the rate a
textbook FFT would need, BASELINE.md §Derived), which is the right number
for cross-framework comparison but the wrong denominator for "is the chip
busy": the matmul backend executes O(n) MXU MACs per element per axis, not
O(log n). This module counts the MACs the backend ACTUALLY issues — by
mirroring the dispatch logic of ``ops/mxu_fft.py`` (direct vs four-step vs
radix-2, R2C/C2R real-matmul fast paths, XLA's 4-real-matmul complex dot
decomposition) — and converts each measured row into achieved MXU TFLOPS
and fraction of the v5e's effective peak.

Peak model: one v5e chip peaks at 197 bf16 TFLOPS (public spec). The
backend's default precision is ``HIGH`` = 3-pass bf16 emulation of f32
(``MXUSettings.precision`` docstring), so its effective peak is 197/3;
``HIGHEST`` is 6-pass (197/6).

Reference anchor: the reference derives GPU efficiency from cuFFT's nominal
flops only (``/root/reference/eval/complete/scalability.py``); a
hardware-true denominator is an extension.

DEFAULT-SETTINGS ASSUMPTION: the MAC model mirrors ``ops/mxu_fft.py`` at
its default ``MXUSettings`` only. Two non-default toggles change the MACs
actually issued — ``karatsuba=True`` lowers each complex dot to 3 real
matmuls plus extra adds, and ``fourstep_einsum=True`` makes ``_rfft_last``
skip the real-matmul fast path — and neither is recorded in the measured
CSV, so ``_BACKENDS`` maps only default-settings backend labels and any
row measured under those toggles must not be fed to ``roofline_rows``
(it would be silently miscounted, not skipped).
"""

from __future__ import annotations

import math
import os
import re
from typing import Optional, Tuple

from ..ops.bluestein import chirp_length, is_smooth
from ..ops.mxu_fft import DIRECT_MAX, _R2_BASE, _split_for

V5E_PEAK_BF16_TFLOPS = 197.0

# MXU passes per f32-emulating matmul at each lax.Precision.
_PREC_PASSES = {"default": 1, "high": 3, "highest": 6}


def effective_peak_tflops(precision: str = "high") -> float:
    """v5e effective matmul peak for f32 data at the given precision."""
    return V5E_PEAK_BF16_TFLOPS / _PREC_PASSES[precision]


# ---------------------------------------------------------------------------
# Per-element MAC counts, mirroring ops/mxu_fft.py dispatch
# ---------------------------------------------------------------------------


def macs_c2c_axis(n: int, direct_max: int = DIRECT_MAX, *,
                  radix2: bool = False, complex_mults: int = 4) -> float:
    """MXU MACs per element for one C2C pass along an axis of length ``n``
    (``_fft_last``): direct = one complex matmul lowered to
    ``complex_mults`` real depth-n matmuls; four-step recurses on both
    factors; radix-2 DIF halves the depth per level down to ``_R2_BASE``
    = 128 (butterflies/twiddles are VPU work, not MXU).

    ``complex_mults``: the textbook complex-dot lowering is 4 real
    matmuls (ArFr - AiFi, ArFi + AiFr); a 3-multiplication Karatsuba-form
    lowering also exists. Measured rows that exceed 100% of peak under
    the 4-matmul model (128^3) prove the compiler's actual lowering is
    cheaper than 4 — so the two models BRACKET the hardware count, and
    the roofline reports both."""
    if radix2 and n > _R2_BASE and n % 2 == 0:
        return macs_c2c_axis(n // 2, direct_max, radix2=radix2,
                             complex_mults=complex_mults)
    if n <= direct_max:
        return float(complex_mults) * n
    # The four-step factor choice mirrors _fft_last's _split_for
    # dispatch: the MXU-deep split (dominant factor = largest divisor
    # <= direct_max) when both factors stay direct, balanced otherwise.
    n1, n2 = _split_for(n, direct_max)
    if n1 == 1:
        return float(complex_mults) * n
    return (macs_c2c_axis(n2, direct_max, radix2=radix2,
                          complex_mults=complex_mults)
            + macs_c2c_axis(n1, direct_max, radix2=radix2,
                            complex_mults=complex_mults))


def macs_r2c_axis(n: int, direct_max: int = DIRECT_MAX, *,
                  complex_mults: int = 4) -> float:
    """MACs per INPUT element for the R2C first pass (``_rfft_last``):
    direct = 2 real n->n_out matmuls (2·n_out MACs/element); four-step =
    real depth-n2 pair + complex depth-n1 on the FULL volume (the crop to
    n_out happens after the transform)."""
    n_out = n // 2 + 1
    if n <= direct_max:
        return 2.0 * n_out
    n1, n2 = _split_for(n, direct_max)
    if n1 == 1:
        return 2.0 * n_out
    return 2.0 * n2 + macs_c2c_axis(n1, direct_max,
                                    complex_mults=complex_mults)


def macs_c2r_axis(n: int, direct_max: int = DIRECT_MAX, *,
                  radix2: bool = False, complex_mults: int = 4) -> float:
    """MACs per OUTPUT element for the C2R last pass (``irfft``): direct =
    2 real depth-n_out matmuls with conjugate symmetry folded in
    (``_c2r_np``); beyond direct_max the code Hermitian-extends and runs a
    full complex inverse (``_fft_last`` cost on the full length — which
    honors the radix-2 setting, so the model must too)."""
    n_out = n // 2 + 1
    if n <= direct_max:
        return 2.0 * n_out
    return macs_c2c_axis(n, direct_max, radix2=radix2,
                         complex_mults=complex_mults)


# ---------------------------------------------------------------------------
# Bluestein (chirp-z) honesty: non-smooth axes
# ---------------------------------------------------------------------------
#
# The nominal 2.5·N·log2 N FLOP model (BASELINE.md §Derived, quoted by the
# CSVs and `flops_roundtrip_3d`) silently assumes every axis is 5-smooth.
# A Bluestein-padded axis actually executes TWO smooth transforms at the
# padded chirp length m = chirp_length(n) (>= 2n-1, next power of two)
# plus O(m) chirp multiplies per pass, and the matmul backend off the
# chirp path executes a dense O(n^2) contraction — so for non-smooth axes
# the honest model must say so instead of quoting the smooth-size number.


def nominal_flops_axis(n: int) -> float:
    """Textbook per-element flops of ONE smooth-length-n axis pass
    (2.5·log2 n per element, the CSVs' nominal convention)."""
    return 2.5 * math.log2(float(n))


def bluestein_flops_axis(n: int) -> float:
    """Per-element flops one chirp-z pass of a non-smooth length-n axis
    actually needs: two length-m smooth FFTs amortized over n elements
    (the kernel spectrum is precomputed) plus the three O(1)-per-element
    chirp/pointwise multiplies (6 real flops each as complex mults)."""
    m = chirp_length(n)
    return 2.0 * 2.5 * m * math.log2(float(m)) / float(n) + 3.0 * 6.0


def bluestein_axis_report(n: int) -> Tuple[int, float]:
    """(padded chirp length m, flop overhead factor vs a natively smooth
    axis of the same length) — the pair dfft-explain quotes so a
    prime-size plan's roofline is honest rather than silently wrong.
    Smooth lengths report (n, 1.0): the backend delegates them."""
    if is_smooth(n):
        return n, 1.0
    return chirp_length(n), bluestein_flops_axis(n) / nominal_flops_axis(n)


def nonsmooth_axes(shape) -> list:
    """The distinct non-5-smooth axis lengths of a shape (sorted)."""
    return sorted({int(n) for n in shape if not is_smooth(int(n))})


# ---------------------------------------------------------------------------
# Whole-workload MXU flops (2 flops per MAC)
# ---------------------------------------------------------------------------


def mxu_flops_roundtrip_3d(n: int, direct_max: int = DIRECT_MAX,
                           radix2: bool = False,
                           complex_mults: int = 4) -> float:
    """MXU flops the matmul backend executes for one R2C+C2R roundtrip of
    an ``n^3`` f32 cube (``rfftn_3d`` then ``irfftn_3d``): z R2C pass on
    the full cube, two C2C passes each way on the halved volume, z C2R
    pass back to the full cube. Radix-2 applies to the C2C stages only
    (``_rfft_last`` never takes the radix-2 branch)."""
    n_out = n // 2 + 1
    v_half = n * n * n_out
    macs = (n ** 3 * macs_r2c_axis(n, direct_max,
                                   complex_mults=complex_mults)
            + 4 * v_half * macs_c2c_axis(n, direct_max, radix2=radix2,
                                         complex_mults=complex_mults)
            + n ** 3 * macs_c2r_axis(n, direct_max, radix2=radix2,
                                     complex_mults=complex_mults))
    return 2.0 * macs


def mxu_flops_batched2d(batch: int, m: int, direct_max: int = DIRECT_MAX,
                        complex_mults: int = 4,
                        radix2: bool = False) -> float:
    """MXU flops for one batched-2D R2C+C2R roundtrip of ``batch`` m x m
    planes (``Batched2DFFTPlan``): per plane, an R2C pass over m rows, one
    C2C pass each way on the halved volume, and a C2R pass back."""
    m_out = m // 2 + 1
    v_half = m * m_out
    macs_plane = (m * m * macs_r2c_axis(m, direct_max,
                                        complex_mults=complex_mults)
                  + 2 * v_half * macs_c2c_axis(m, direct_max, radix2=radix2,
                                               complex_mults=complex_mults)
                  + m * m * macs_c2r_axis(m, direct_max, radix2=radix2,
                                          complex_mults=complex_mults))
    return 2.0 * batch * macs_plane


# ---------------------------------------------------------------------------
# roofline_fraction: the tracked per-row gate (ISSUE 10 / ROADMAP item 3)
# ---------------------------------------------------------------------------
#
# ``roofline_fraction = ideal_ms / measured_ms``: the fraction of the
# model's 100%-of-effective-peak time a measured row achieved. The model
# is the SAME per-plan expectation dfft-explain prints — the exact MXU MAC
# count for the matmul-family backends, the nominal 2.5·N·log2 N flops for
# everything else — against the v5e effective peak, divided by the mesh
# size for distributed rows (per-chip share of the transform work; the
# exchange is deliberately NOT in the denominator, so communication time
# shows up as lost fraction — that is the seam this gate exists to track).
# On a non-TPU backend (the CPU test mesh) the v5e peak makes the fraction
# a tiny TRACKING number, not a utilization claim: it is comparable across
# runs of the same host, which is all the CI regression gate needs.


def _parse_size(shape):
    """Normalize a workload size to ``("cube", n)`` / ``("b2d", (b, m))``
    or None: accepts an int (cube edge), a ``"256^3"`` / ``"4096^2x64"``
    string (the bench row-key forms; a trailing ``:inverse``-style mode
    tag is ignored), or a shape tuple — (n, n, n) cubes and (b, m, m)
    batched planes."""
    if isinstance(shape, str):
        s = shape.split(":")[0]
        m = re.fullmatch(r"(\d+)(\^3)?", s)
        if m:
            return "cube", int(m.group(1))
        m = re.fullmatch(r"(\d+)\^2x(\d+)", s)
        if m:
            return "b2d", (int(m.group(2)), int(m.group(1)))
        return None
    if isinstance(shape, int):
        return "cube", int(shape)
    t = tuple(int(v) for v in shape)
    if len(t) == 3 and t[0] == t[1] == t[2]:
        return "cube", t[0]
    if len(t) == 3 and t[1] == t[2]:
        return "b2d", (t[0], t[1])
    return None


def _backend_model(backend: str):
    """(counts_on_mxu, precision, radix2) for a bench/Config backend
    label — bare names ("matmul") and CSV forms ("matmul@high") both
    resolve; non-matmul backends fall to the nominal model."""
    base = str(backend).split()[0]
    name, _, prec = base.partition("@")
    if name in ("matmul", "matmul-planes"):
        return True, (prec or "high"), False
    if name == "matmul-r2":
        return True, (prec or "high"), True
    return False, "high", False


def ideal_time_ms(shape, backend: str, *, devices: int = 1,
                  mode: str = "roundtrip",
                  direct_max: "Optional[int]" = None) -> Optional[float]:
    """The per-plan expectation: the time ``mode`` of this workload would
    take at 100% of the v5e effective MXU peak — exact MACs (4mm bound)
    for the matmul family, nominal FFT flops for other backends. None
    when the shape is outside the model (non-cube/non-square-batched).
    ``devices`` divides the work (per-chip share); ``direct_max``
    overrides the plan threshold (the ``direct(N)`` bench plan note)."""
    parsed = _parse_size(shape)
    if parsed is None or devices < 1:
        return None
    kind, dims = parsed
    mxu, precision, r2 = _backend_model(backend)
    dmax = DIRECT_MAX if direct_max is None else int(direct_max)
    if kind == "cube":
        n = dims
        if mxu:
            flops = mxu_flops_roundtrip_3d(n, dmax, radix2=r2)
        else:
            from ..testing.workloads import flops_roundtrip_3d
            flops = flops_roundtrip_3d(n)
    else:
        b, m = dims
        if mxu:
            flops = mxu_flops_batched2d(b, m, dmax, radix2=r2)
        else:
            from ..testing.workloads import flops_batched2d
            flops = flops_batched2d(b, m, m)
    if mode != "roundtrip":  # forward / inverse / forward-chunked
        flops /= 2.0
    peak = effective_peak_tflops(precision)
    return flops / (peak * 1e12) / float(devices) * 1e3


def _mesh_devices(mesh) -> int:
    """Device count of a mesh-ish argument: None (single chip), an int,
    or a ``jax.sharding.Mesh``."""
    if mesh is None:
        return 1
    if isinstance(mesh, int):
        return max(1, mesh)
    devs = getattr(mesh, "devices", None)
    return int(devs.size) if devs is not None else 1


def roofline_row(measured_ms: float, shape, backend: str, mesh=None, *,
                 mode: str = "roundtrip",
                 direct_max: "Optional[int]" = None) -> Optional[dict]:
    """The tracked roofline record for one measured row (what bench.py
    writes under BENCH_DETAILS.json's ``"roofline"`` block): the model's
    ideal time, the achieved ``roofline_fraction``, and which model
    produced it. None when unmodelable (bad shape / degenerate time)."""
    if not measured_ms or measured_ms <= 0:
        return None
    devices = _mesh_devices(mesh)
    ideal = ideal_time_ms(shape, backend, devices=devices, mode=mode,
                          direct_max=direct_max)
    if ideal is None:
        return None
    mxu, precision, _ = _backend_model(backend)
    # Significant digits, not fixed decimals: CPU tracking rows sit many
    # orders below the v5e model and must never round to a 0.0 the gate
    # would reject.
    return {
        "ideal_ms": float(f"{ideal:.4g}"),
        "roofline_fraction": float(f"{ideal / measured_ms:.4g}"),
        "model": (f"mxu-4mm@{precision}" if mxu else "nominal@high"),
        "mode": mode,
        "devices": devices,
    }


def roofline_fraction(measured_ms: float, shape, backend: str,
                      mesh=None, *, mode: str = "roundtrip",
                      direct_max: "Optional[int]" = None
                      ) -> Optional[float]:
    """``ideal_time_ms / measured_ms`` — the honest, tracked fraction of
    the per-plan roofline a measurement achieved (ROADMAP item 3's gate:
    every perf PR must move this number, and the CI roofline job fails a
    >10% regression against the committed BENCH_DETAILS.json)."""
    row = roofline_row(measured_ms, shape, backend, mesh, mode=mode,
                       direct_max=direct_max)
    return None if row is None else row["roofline_fraction"]


_BENCH_DETAILS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "..", "..", "BENCH_DETAILS.json")


def tracked_fractions(path: Optional[str] = None) -> dict:
    """The committed ``"roofline"`` rows of BENCH_DETAILS.json (row key ->
    record), or {} when the artifact/block is absent — what dfft-explain
    quotes as the tracked fraction and the CI job regresses against."""
    import json
    try:
        with open(path or _BENCH_DETAILS, encoding="utf-8") as f:
            data = json.load(f)
        rows = data.get("roofline", {}).get("rows", {})
        return rows if isinstance(rows, dict) else {}
    except (OSError, ValueError):
        return {}


# ---------------------------------------------------------------------------
# Roofline table from the committed measurement CSV
# ---------------------------------------------------------------------------

_CSV = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..",
                    "eval", "benchmarks", "tpu_v5e",
                    "single_chip_chain_timed.csv")

# backend label -> (counts_on_mxu, precision, radix2). The all-real-planes
# formulation issues the identical matmuls on split (re, im) planes, so it
# shares the matmul count; XLA's native FFT is not a matmul pipeline and
# pallas kernels schedule their own MXU passes — no honest count for
# either, so they are skipped rather than guessed.
_BACKENDS = {
    "matmul@high": ("high", False),
    "matmul@highest": ("highest", False),
    "matmul-r2@high": ("high", True),
    "matmul-planes": ("high", False),
}

# Plan suffix on the backend column (e.g. "matmul@high direct(1024)",
# "matmul@high four-step(16x32)", "matmul@high ck=1"): the execution-plan
# variant the row was measured under. The MAC model takes the plan as a
# ``direct_max`` threshold, so every suffix maps to one:
#   direct(N)        -> direct_max=N (the whole axis is one contraction);
#   four-step(AxB)   -> direct_max=max(A,B) (forces the four-step branch;
#                       the factors themselves are <= max(A,B) so they run
#                       direct, exactly as measured);
#   ck=N / chunked   -> batch/stage chunking re-orders work without
#                       changing the MACs issued -> no override.
_SUFFIX_DIRECT = re.compile(r"direct\((\d+)\)")
_SUFFIX_FOURSTEP = re.compile(r"four-step\((\d+)x(\d+)\)")


def _parse_backend(label: str):
    """Split a CSV backend label into (base, direct_max override or None).
    Returns ``None`` for labels whose MACs the model cannot count."""
    parts = label.split()
    if not parts or parts[0] not in _BACKENDS:
        return None
    base = parts[0]
    dmax = None
    for tok in parts[1:]:
        m = _SUFFIX_DIRECT.fullmatch(tok)
        if m:
            dmax = int(m.group(1))
            continue
        m = _SUFFIX_FOURSTEP.fullmatch(tok)
        if m:
            dmax = max(int(m.group(1)), int(m.group(2)))
            continue
        if tok.startswith("ck=") or tok == "chunked":
            continue
        return None  # unknown suffix: skip the row rather than miscount
    return base, dmax


def roofline_rows(csv_path: str = _CSV) -> list:
    """Parse the measured CSV and return roofline dicts for every row
    whose backend has an exact MXU MAC count."""
    out = []
    with open(csv_path) as f:
        header = f.readline().strip().split(",")
        idx = {k: i for i, k in enumerate(header)}
        for line in f:
            parts = line.rstrip("\n").split(",")
            if len(parts) < 5:
                continue
            size, transform = parts[idx["size"]], parts[idx["transform"]]
            backend = parts[idx["backend"]]
            per_ms = float(parts[idx["per_iter_ms"]])
            nominal = float(parts[idx["gflops"]])
            parsed = _parse_backend(backend)
            if parsed is None or "roundtrip" not in transform:
                continue
            base, dmax_override = parsed
            precision, r2 = _BACKENDS[base]
            dmax = DIRECT_MAX if dmax_override is None else dmax_override
            m_cube = re.fullmatch(r"(\d+)\^3", size)
            m_b2d = re.fullmatch(r"(\d+)\^2x(\d+)", size)
            if m_cube:
                n = int(m_cube.group(1))
                f4 = mxu_flops_roundtrip_3d(n, dmax, radix2=r2)
                f3 = mxu_flops_roundtrip_3d(n, dmax, radix2=r2,
                                            complex_mults=3)
            elif m_b2d:
                m, b = int(m_b2d.group(1)), int(m_b2d.group(2))
                f4 = mxu_flops_batched2d(b, m, dmax, radix2=r2)
                f3 = mxu_flops_batched2d(b, m, dmax, complex_mults=3,
                                         radix2=r2)
            else:
                continue
            peak = effective_peak_tflops(precision)
            t4 = f4 / (per_ms * 1e-3) / 1e12
            t3 = f3 / (per_ms * 1e-3) / 1e12
            out.append({
                "size": size, "backend": backend,
                "per_iter_ms": per_ms, "nominal_gflops": nominal,
                "mxu_tflops_4mm": round(t4, 1),
                "mxu_tflops_3mm": round(t3, 1),
                "peak_tflops": round(peak, 1),
                "util_4mm": round(t4 / peak, 3),
                "util_3mm": round(t3 / peak, 3),
            })
    return out


def _cube512_clause(rows) -> str:
    """Utilization bounds for the headline 512^3 matmul@high row, quoted
    FROM the rendered rows so the narrative can never contradict its own
    table; empty when that row is absent."""
    for r in rows:
        if r["size"] == "512^3" and r["backend"] == "matmul@high":
            return (f" (512^3 runs at {100 * r['util_3mm']:.0f}-"
                    f"{100 * r['util_4mm']:.0f}% of effective peak)")
    return ""


def _nominal_drop_clause(rows) -> str:
    """The 256^3 -> 512^3 nominal-GFLOPS drop, quoted FROM the rendered
    rows for the same can't-contradict-the-table reason; falls back to
    the sizeless statement when either row is absent."""
    vals = {}
    for r in rows:
        if r["backend"] == "matmul@high" and r["size"] in ("256^3", "512^3"):
            vals.setdefault(r["size"], r["nominal_gflops"])
    if len(vals) == 2:
        return (f"the 256^3 -> 512^3 nominal drop ({vals['256^3']:.1f} -> "
                f"{vals['512^3']:.1f}) is")
    return "the nominal fall with size is"


def render_markdown(rows, path: Optional[str] = None) -> str:
    lines = [
        "# MXU-utilization roofline (v5e single chip)",
        "",
        "Measured roundtrip rows from `single_chip_chain_timed.csv`, with",
        "the MXU flops the matmul backend ACTUALLY executes (counted by",
        "`evalkit/roofline.py`, mirroring `ops/mxu_fft.py` dispatch)",
        "against the v5e's effective peak (197 bf16 TFLOPS; `HIGH` = 3-pass",
        "bf16 f32 emulation -> 65.7 TFLOPS effective, `HIGHEST` = 6-pass",
        "-> 32.8).",
        "",
        "| size | backend | ms/iter | nominal GFLOPS | MXU TFLOPS "
        "(3mm-4mm) | eff. peak | utilization (3mm-4mm) |",
        "|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            f"| {r['size']} | {r['backend']} | {r['per_iter_ms']:.4f} | "
            f"{r['nominal_gflops']:.1f} | "
            f"{r['mxu_tflops_3mm']:.1f}-{r['mxu_tflops_4mm']:.1f} | "
            f"{r['peak_tflops']:.1f} | "
            f"{100 * r['util_3mm']:.1f}-{100 * r['util_4mm']:.1f}% |")
    lines += [
        "",
        "The two bounds bracket XLA's complex-dot lowering: `4mm` = the",
        "textbook 4-real-matmul decomposition, `3mm` = the 3-multiplication",
        "Karatsuba form. The 128^3 row EXCEEDING peak under 4mm proves the",
        "actual lowering is cheaper than 4 matmuls, so the hardware truth",
        "lies between the columns (R2C/C2R passes are exact in both — they",
        "are explicit real-matmul pairs in `ops/mxu_fft.py`). For",
        "`matmul-planes` the 4mm column is EXACT everywhere: `_rp_stage`",
        "writes the 4 real einsums out explicitly, nothing is left to the",
        "compiler's complex lowering.",
        "",
        "Reading: NOMINAL GFLOPS (2.5·N·log2 N — what a textbook FFT would",
        "need) falls with size because the matmul backend spends O(n)",
        "MACs/element per axis, while MXU utilization stays high — "
        + _nominal_drop_clause(rows) + " the O(n)/O(log n)",
        "flop-count ratio growing, not the chip idling"
        + _cube512_clause(rows) + ". The outliers are the point of the",
        "table: matmul-r2's low utilization shows its interleave relayout",
        "starving the MXU (matching its measured net loss), and the",
        "batched-2D rows' low single digits show the four-step swapaxes",
        "relayouts are HBM-bound — the 2026-07-31 on-chip chunk sweep",
        "(session_r5.jsonl) found per-plane lax.map slices (chunk size 1)",
        "fastest, with larger fused slices monotonically slower (the",
        "whole-stack fused program failed remote compile 2026-07-30 and",
        "remains unmeasured).",
    ]
    text = "\n".join(lines) + "\n"
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        "dfft-roofline", description="Render the MXU roofline table from "
        "the committed single-chip measurement CSV.")
    ap.add_argument("--csv", default=_CSV)
    ap.add_argument("--out", default=None,
                    help="write markdown here (default: print)")
    a = ap.parse_args(argv)
    if not os.path.exists(a.csv):
        ap.error(f"measurement CSV not found: {a.csv} — the default path "
                 "resolves inside a source checkout (eval/ is not "
                 "packaged); pass --csv explicitly")
    rows = roofline_rows(a.csv)
    text = render_markdown(rows, a.out)
    if not a.out:
        print(text, end="")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
