"""HLO scanning: lower/compile plan programs (never execute) and extract
the structural facts the contracts check.

Two distinct module views, used deliberately:

* the COMPILED module (``compiled_text``) — what the backend will run,
  post-GSPMD, post-fusion. The collective census runs here: "the ring's
  P-1 permutes were not re-fused" is a statement about the optimized
  program (the STREAMS chunked reshards WERE re-fused — OVERLAP.md).
* the STAGED module (``staged_text``) — the pre-optimization lowering,
  the program as the wire layer wrote it. Exchange payload bytes are
  reconciled here: the CPU backend is free to hoist a bf16 decode past a
  collective it knows is local (observed), which changes the optimized
  payload without changing what the wire layer staged — and on TPU, what
  is staged is what crosses the ICI. A wire-layer regression (encode not
  applied, payload doubled) shows up in the staged module on every
  backend.

Fingerprints (``op_graph_fingerprint``) hash the compiled text with
``metadata={...}`` attributes stripped: op metadata carries source file
and line numbers, which shift under pure refactors — the op graph is the
invariant. Byte-identity pins (obs on/off, fault spec set/unset,
guards="off") compare these.
"""

from __future__ import annotations

import hashlib
import math
import re
from typing import Any, Dict, List, Optional, Tuple

# Exchange collectives and their async start forms, as (census key, HLO op
# mnemonic) pairs. Counted as op INSTANCES — "<op>(" with the opening
# paren — so "all-to-all(" does not match the async "all-to-all-start("
# form and vice versa.
CENSUS_FORMS: Tuple[Tuple[str, str], ...] = (
    ("all_to_all", "all-to-all"),
    ("all_to_all_start", "all-to-all-start"),
    ("collective_permute", "collective-permute"),
    ("collective_permute_start", "collective-permute-start"),
    ("all_reduce", "all-reduce"),
    ("all_reduce_start", "all-reduce-start"),
    ("all_gather", "all-gather"),
    ("all_gather_start", "all-gather-start"),
    ("reduce_scatter", "reduce-scatter"),
    ("reduce_scatter_start", "reduce-scatter-start"),
)

# The ops that move an exchange payload (census keys); all_reduce and
# friends are counted but never payload-checked (guards legitimately fold
# a scalar all-reduce into their reduction under GSPMD).
EXCHANGE_OPS: Tuple[str, ...] = (
    "all_to_all", "all_to_all_start",
    "collective_permute", "collective_permute_start",
)

_HLO_SHAPE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_MLIR_SHAPE = re.compile(r"tensor<([0-9x]*)x?((?:complex<)?[a-z][a-z0-9]*>?)>")
_METADATA = re.compile(r",?\s*metadata=\{[^{}]*\}")
_MODULE_NAME = re.compile(r"^HloModule\s+\S+", re.MULTILINE)

_DTYPE_BYTES: Dict[str, int] = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
    "s64": 8, "u64": 8, "f8e4m3fn": 1, "f8e5m2": 1, "bf16": 2, "f16": 2,
    "f32": 4, "f64": 8, "c64": 8, "c128": 16,
    # MLIR spellings (StableHLO fallback when the HLO dialect is gone)
    "i1": 1, "i8": 1, "i16": 2, "i32": 4, "i64": 8,
    "complex<f32": 8, "complex<f64": 16, "complex<f32>": 8,
    "complex<f64>": 16,
}


# ---------------------------------------------------------------------------
# lowering (never executing)
# ---------------------------------------------------------------------------

def _input_aval(plan: Any, direction: str, dims: int = 3) -> Any:
    """The ShapeDtypeStruct the direction's builder is lowered against —
    exactly what the exec_* path feeds it (padded global shape)."""
    import jax
    import numpy as np

    dp = bool(plan.config.double_prec)
    cdt = np.complex128 if dp else np.complex64
    if direction == "forward":
        shape = tuple(plan.input_padded_shape)
        c2c = getattr(plan, "transform", "r2c") == "c2c"
        dt = cdt if c2c else (np.float64 if dp else np.float32)
    elif direction == "inverse":
        # Pencil plans shape their spectral input per partial-transform
        # depth; the other families have one padded spectral shape.
        getter = getattr(plan, "output_padded_shape_for", None)
        shape = tuple(getter(dims)) if getter is not None \
            else tuple(plan.output_padded_shape)
        dt = cdt
    else:
        raise ValueError(f"direction must be 'forward'|'inverse', "
                         f"got {direction!r}")
    return jax.ShapeDtypeStruct(shape, dt)


def _builder(plan: Any, direction: str, dims: int = 3) -> Any:
    """The direction's jitted builder across the three families
    (duck-typed on the family-specific builder names)."""
    fwd = direction == "forward"
    if hasattr(plan, "_build_r2c_d"):                   # pencil
        return plan._build_r2c_d(dims) if fwd else plan._build_c2r_d(dims)
    if hasattr(plan, "_build"):                         # batched2d
        return plan._build(forward=fwd)
    return plan._build_r2c() if fwd else plan._build_c2r()


def lower_plan(plan: Any, direction: str = "forward",
               dims: int = 3) -> Any:
    """Lower one direction of a plan (slab / pencil / batched2d) against
    its padded input aval — the compile-only entry every scan shares."""
    return _builder(plan, direction, dims).lower(
        _input_aval(plan, direction, dims))


def compiled_text(plan: Any, direction: str = "forward",
                  dims: int = 3) -> str:
    """Optimized (post-SPMD, post-fusion) module text of one direction."""
    return lower_plan(plan, direction, dims).compile().as_text()


def staged_text(plan: Any, direction: str = "forward",
                dims: int = 3) -> Tuple[str, str]:
    """Pre-optimization module text: ``(dialect, text)`` where dialect is
    ``"hlo"`` or (when this jax no longer exposes the HLO translation)
    ``"stablehlo"`` — the payload parser understands both."""
    lowered = lower_plan(plan, direction, dims)
    try:
        ir = lowered.compiler_ir("hlo")
        if ir is not None:
            return "hlo", ir.as_hlo_text()
    except (KeyError, ValueError, NotImplementedError, AttributeError):
        pass
    return "stablehlo", lowered.as_text()


# ---------------------------------------------------------------------------
# census
# ---------------------------------------------------------------------------

def collective_census(hlo: Any) -> Dict[str, int]:
    """Instance counts of the exchange collectives (and their async start
    forms) plus ``convert`` ops in a compiled module — the overlap/
    compression detector (``eval/benchmarks/cpumesh8/OVERLAP.md``).
    Accepts a compiled executable or raw HLO text. The counts are
    mirrored into the obs registry as ``hlo.*`` gauges (last census
    wins), so any caller's census lands in the metrics snapshot."""
    from .. import obs

    txt = hlo if isinstance(hlo, str) else hlo.as_text()
    out = {name: txt.count(f" {op}(") for name, op in CENSUS_FORMS}
    out["async_total"] = (out["all_to_all_start"]
                          + out["collective_permute_start"])
    out["convert"] = txt.count(" convert(")
    for name, v in out.items():
        obs.metrics.gauge(f"hlo.{name}", v)
    return out


def contains_bf16(txt: str) -> bool:
    """Whether a module text mentions bf16 anywhere — the structural pin
    behind the native wire's bit-identity (a native-wire program is
    bf16-FREE, not merely numerically indistinguishable)."""
    return "bf16" in txt


# ---------------------------------------------------------------------------
# exchange payloads
# ---------------------------------------------------------------------------

def _hlo_line_bytes(line: str, mnemonic: str) -> int:
    """Byte size of the result of one HLO op line (sum over tuple
    elements — the CPU backend lowers a tiled all-to-all in tuple form,
    one operand per participant, which together make up the shard)."""
    lhs = line.split(f" {mnemonic}(")[0]
    if " = " in lhs:
        lhs = lhs.split(" = ", 1)[1]
    total = 0
    for dt, dims in _HLO_SHAPE.findall(lhs):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


def _mlir_result_bytes(line: str) -> int:
    """Byte size of the RESULT type(s) on a StableHLO op line — summed
    over tuple elements, mirroring the HLO branch: a tiled all-to-all can
    stage in tuple form (one operand/result per participant), and its
    payload is the sum, not the last element."""
    # The result type(s) follow the last "->" of the op's type
    # annotation; without one (older syntax) fall back to the last
    # tensor<> on the line.
    if "->" in line:
        shapes = _MLIR_SHAPE.findall(line.rsplit("->", 1)[1])
    else:
        shapes = _MLIR_SHAPE.findall(line)[-1:]
    total = 0
    for dims, dt in shapes:
        n = 1
        for d in dims.split("x"):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dt, 0)
    return total


def exchange_payload_bytes(dialect: str, txt: str) -> Dict[str, List[int]]:
    """Per-op payload bytes (PER PARTICIPATING DEVICE) of every exchange
    collective in a staged module: ``{"all_to_all": [...],
    "collective_permute": [...]}``, one entry per op instance in module
    order. Multiply by the mesh size for global wire bytes (the
    convention ``wire_nbytes``/``wire_bytes_per_transpose`` report)."""
    out: Dict[str, List[int]] = {"all_to_all": [], "collective_permute": []}
    if dialect == "hlo":
        for line in txt.splitlines():
            for key, mnemonic in (("all_to_all", "all-to-all"),
                                  ("collective_permute",
                                   "collective-permute")):
                if f" {mnemonic}(" in line:
                    out[key].append(_hlo_line_bytes(line, mnemonic))
    else:
        for line in txt.splitlines():
            if "stablehlo.all_to_all" in line:
                out["all_to_all"].append(_mlir_result_bytes(line))
            elif "stablehlo.collective_permute" in line:
                out["collective_permute"].append(_mlir_result_bytes(line))
    return out


def predicted_payload_bytes(shape: Any, dtype: Any, wire: str,
                            ring_size: int = 0) -> int:
    """GLOBAL wire bytes one exchange of ``shape``/``dtype`` moves under
    the wire encoding — ``wire_nbytes`` with the ring discount applied:
    a ring of ``ring_size`` ranks never sends the local block, so its
    P-1 permute steps together carry ``(P-1)/P`` of the payload. The
    monolithic collectives (``ring_size=0``) carry it whole (the tiled
    all-to-all's local->local block stays in the accounting, matching
    ``wire_bytes_per_transpose``)."""
    from ..parallel.transpose import wire_nbytes

    nb = wire_nbytes(shape, dtype, wire)
    if ring_size > 1:
        # The discount divides exactly: every ring payload is padded to
        # ring_size blocks before the steps are staged.
        return nb * (ring_size - 1) // ring_size
    return nb


def staged_exchange_total(plan: Any, direction: str = "forward",
                          dims: int = 3) -> Optional[int]:
    """GLOBAL staged exchange bytes of one direction: per-device payload
    sum x mesh size. None when the staged module carries no explicit
    exchange (GSPMD renderings stage sharding constraints, not
    collectives — the partitioner picks those later)."""
    dialect, txt = staged_text(plan, direction, dims)
    per_dev = exchange_payload_bytes(dialect, txt)
    ops = per_dev["all_to_all"] + per_dev["collective_permute"]
    if not ops:
        return None
    mesh = getattr(plan, "mesh", None)
    size = math.prod(mesh.devices.shape) if mesh is not None else 1
    return sum(ops) * size


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------

def strip_metadata(txt: str) -> str:
    """Compiled module text with op ``metadata={...}`` (source file/line)
    and the module name dropped — the op graph, stable across pure
    refactors that only move code."""
    txt = _METADATA.sub("", txt)
    return _MODULE_NAME.sub("HloModule _", txt)


def op_graph_fingerprint(txt: str) -> str:
    """sha256 of the metadata-stripped module text — the byte-identity
    currency of the zero-overhead-off pins (obs on/off, fault spec
    set/unset, guards="off" vs never-guarded)."""
    return hashlib.sha256(strip_metadata(txt).encode()).hexdigest()


def plan_fingerprint(plan: Any, direction: str = "forward",
                     dims: int = 3) -> str:
    """``op_graph_fingerprint`` of one direction's compiled module."""
    return op_graph_fingerprint(compiled_text(plan, direction, dims))
