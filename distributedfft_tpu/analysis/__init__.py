"""Static analysis: the plan/HLO contract verifier and repo-invariant
lints (``dfft-verify``).

The reference validates its comm x send matrix only dynamically — one
test executable per configuration (SURVEY L4/L5). This package is the
static complement: every rendering x direction x wire x guard combo is
LOWERED AND COMPILED (never executed) and checked against declarative
contracts, so the invariants that keep the three plan families honest
live in one registry instead of N drifting test asserts:

* ``hloscan``    — compile/lower plan programs, collective census,
  metadata-stripped op-graph fingerprints, exchange payload extraction;
* ``contracts``  — the declarative contract model + registry: expected
  collective census per rendering, forbidden-op rules, predicted-vs-
  actual exchange payload bytes reconciled with ``wire_nbytes``;
* ``jaxprlint``  — jaxpr dataflow lints (unpaired wire encode/decode,
  dtype drift across an exchange, guard ops present at ``guards="off"``);
* ``srclint``    — AST-level repo-invariant lints (no host I/O in traced
  fns, host-only modules stay jax.numpy-free, wisdom-store writes only
  under the flock helper);
* ``verify``     — the ``dfft-verify`` runner: the full combo matrix as
  a pass/fail table, mutation self-tests, JSON artifact for CI.

These are the "HLO byte-identity pins as the migration safety net" the
Plan-IR refactor (ROADMAP item 1) gates on: a rendering PR is done when
``dfft-verify`` passes clean.
"""

from . import contracts, hloscan, jaxprlint, srclint  # noqa: F401
from .contracts import (  # noqa: F401
    Contract,
    ContractViolation,
    check_contract,
    contract_for,
    verify_plan,
)
from .hloscan import (  # noqa: F401
    collective_census,
    compiled_text,
    contains_bf16,
    lower_plan,
    op_graph_fingerprint,
    plan_fingerprint,
)
