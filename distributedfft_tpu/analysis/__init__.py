"""Static analysis: the plan/HLO contract verifier and repo-invariant
lints (``dfft-verify``).

The reference validates its comm x send matrix only dynamically — one
test executable per configuration (SURVEY L4/L5). This package is the
static complement: every rendering x direction x wire x guard combo is
LOWERED AND COMPILED (never executed) and checked against declarative
contracts, so the invariants that keep the three plan families honest
live in one registry instead of N drifting test asserts:

* ``hloscan``    — compile/lower plan programs, collective census,
  metadata-stripped op-graph fingerprints, exchange payload extraction;
* ``contracts``  — the declarative contract model + registry: expected
  collective census per rendering, forbidden-op rules, predicted-vs-
  actual exchange payload bytes reconciled with ``wire_nbytes``;
* ``plangraph``  — the declared stage-graph IR: every family emits a
  typed graph (local-FFT / exchange / wire encode/decode / guard /
  fused-kernel nodes; edges carry shape/dtype/sharding/wire bytes) with
  well-formedness, graph<->contract and graph<->trace conformance
  checks — the machine-checked pipeline the Plan-IR refactor lowers
  from;
* ``schedverify`` — the static hazard checker over the revolving-buffer
  ring schedules (read-before-arrive / write-after-send / overflow /
  lost-block), proving the RING_OVERLAP pipeline safe at any buffer
  depth before it traces;
* ``jaxprlint``  — jaxpr dataflow lints (unpaired wire encode/decode,
  dtype drift across an exchange, guard ops present at ``guards="off"``);
* ``srclint``    — AST-level repo-invariant lints (no host I/O in traced
  fns, host-only modules stay jax.numpy-free, atomic store writes only
  under the flock helper — ``serve/`` and ``solvers/`` included);
* ``verify``     — the ``dfft-verify`` runner: the full combo matrix as
  a pass/fail table (the plan-graph pass on every combo), mutation
  self-tests, the schedule sweep, JSON artifact for CI.

These are the "HLO byte-identity pins as the migration safety net" the
Plan-IR refactor (ROADMAP item 1) gates on: a rendering PR is done when
``dfft-verify`` passes clean.
"""

from . import (  # noqa: F401
    contracts,
    hloscan,
    jaxprlint,
    plangraph,
    schedverify,
    srclint,
)
from .contracts import (  # noqa: F401
    Contract,
    ContractViolation,
    check_contract,
    contract_for,
    verify_plan,
)
from .plangraph import (  # noqa: F401
    PlanGraph,
    StageEdge,
    StageNode,
    check_graph,
    graph_for,
    verify_graph,
)
from .schedverify import (  # noqa: F401
    check_schedule,
    revolving_schedule,
)
from .hloscan import (  # noqa: F401
    collective_census,
    compiled_text,
    contains_bf16,
    lower_plan,
    op_graph_fingerprint,
    plan_fingerprint,
)
