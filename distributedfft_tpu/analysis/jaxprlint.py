"""Jaxpr dataflow lints: invariants of the traced program that neither
numerics nor the compiled-HLO census can see.

The wire layer's contract is *structural*: every ``wire_encode`` (a
convert to bf16) is matched by a ``wire_decode`` (a convert from bf16)
on the far side of the exchange, restoring the payload's pre-encode
float width. The HLO census counts collectives but the CPU backend is
free to hoist/sink converts, so pairing is checked on the JAXPR — the
program as traced, before any backend rewrites:

* **unpaired encode/decode** — a bf16 wire crossing whose decode was
  dropped leaves the payload bf16 downstream (silent precision loss the
  first time a non-convert op consumes it);
* **bf16 leak** — a traced output carrying bf16 is the terminal form of
  the same bug;
* **dtype drift across an exchange** — encodes and decodes must restore
  the SAME float widths (a c128 plan whose decode lands on f32 silently
  halves precision past the wire);
* **guard ops at guards="off"** — an off-mode build returns exactly the
  transform result; the guarded wrapper's ``(y, stats)`` pair showing up
  means guard ops leaked into the default path (the dynamic half of the
  zero-overhead-off pin).

All checks accept a plan (``lint_plan``) or a bare jaxpr (the harness
the mutation tests feed).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterator, List, Optional


@dataclasses.dataclass(frozen=True)
class LintFinding:
    """One jaxpr-lint diagnostic; ``lint`` names the violated invariant
    (the mutation tests assert on it)."""

    lint: str
    message: str

    def __str__(self) -> str:
        return f"[jaxprlint/{self.lint}] {self.message}"


def _subjaxprs(params: dict) -> Iterator[Any]:
    """Nested jaxprs inside an eqn's params, across jax versions (pjit
    carries ``jaxpr``, control flow ``branches``/``body_jaxpr``/... —
    scan every param value duck-typed on ``.eqns``)."""
    for v in params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for x in vs:
            inner = getattr(x, "jaxpr", x)
            if hasattr(inner, "eqns"):
                yield inner


def iter_eqns(jaxpr: Any) -> Iterator[Any]:
    """Every eqn of a (closed) jaxpr, recursing through pjit / shard_map /
    control-flow sub-jaxprs."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _subjaxprs(eqn.params):
            yield from iter_eqns(sub)


def _is_bf16(dtype: Any) -> bool:
    return "bfloat16" in str(dtype)


def _convert_ends(eqn: Any) -> Optional[tuple]:
    """``(src_dtype, dst_dtype)`` of a convert eqn, else None."""
    if eqn.primitive.name != "convert_element_type":
        return None
    return (eqn.invars[0].aval.dtype, eqn.outvars[0].aval.dtype)


# The named-axis exchange primitives a plan stages (psum et al. are
# reductions, not payload moves).
EXCHANGE_PRIMITIVES = ("all_to_all", "ppermute")


def lint_wire_pairing(jaxpr: Any, expect_crossings: int = 0
                      ) -> List[LintFinding]:
    """Pairing/drift/leak checks over every convert in the jaxpr.
    ``expect_crossings`` is the number of wire crossings the plan's
    exchange declaration predicts for a compressed wire (0 = the wire is
    native and NO bf16 conversion may appear at all)."""
    encodes: List[Any] = []  # src dtypes of converts INTO bf16
    decodes: List[Any] = []  # dst dtypes of converts OUT OF bf16
    for eqn in iter_eqns(jaxpr):
        ends = _convert_ends(eqn)
        if ends is None:
            continue
        src, dst = ends
        if _is_bf16(dst) and not _is_bf16(src):
            encodes.append(src)
        elif _is_bf16(src) and not _is_bf16(dst):
            decodes.append(dst)
    out: List[LintFinding] = []
    if expect_crossings == 0:
        if encodes or decodes:
            out.append(LintFinding(
                "wire-pairing",
                f"0 wire crossings expected but {len(encodes)} bf16 "
                f"encode(s) / {len(decodes)} decode(s) traced; the wire "
                "layer must be structurally inert here"))
        return out
    if len(encodes) != len(decodes):
        out.append(LintFinding(
            "wire-pairing",
            f"unpaired wire_encode/wire_decode: {len(encodes)} convert(s) "
            f"to bf16 but {len(decodes)} back — a dropped decode leaves "
            "the payload bf16 past the exchange"))
    if len(encodes) < expect_crossings:
        out.append(LintFinding(
            "wire-pairing",
            f"compressed wire declares {expect_crossings} crossing(s) but "
            f"only {len(encodes)} encode(s) traced — the exchange payload "
            "is travelling unencoded"))
    # Drift only means something for PAIRED conversions: unequal counts
    # already reported above, and would trivially re-trip this rule.
    if len(encodes) == len(decodes) and \
            sorted(map(str, encodes)) != sorted(map(str, decodes)):
        out.append(LintFinding(
            "wire-drift",
            f"dtype drift across the exchange: encoded from "
            f"{sorted(map(str, encodes))} but decoded to "
            f"{sorted(map(str, decodes))} — the wire must restore the "
            "pre-encode float width"))
    closed = jaxpr if hasattr(jaxpr, "out_avals") else None
    if closed is not None:
        leaks = [a for a in closed.out_avals if _is_bf16(a.dtype)]
        if leaks:
            out.append(LintFinding(
                "wire-pairing",
                f"{len(leaks)} traced output(s) still bf16 — a wire "
                "payload leaked out undecoded"))
    return out


def lint_exchange_dtypes(jaxpr: Any) -> List[LintFinding]:
    """Every exchange primitive must move its payload dtype unchanged
    (a collective that retypes is a tracing bug, and under a compressed
    wire both ends must be the ENCODED dtype)."""
    out: List[LintFinding] = []
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name not in EXCHANGE_PRIMITIVES:
            continue
        din = {str(v.aval.dtype) for v in eqn.invars
               if hasattr(v, "aval") and hasattr(v.aval, "dtype")}
        dout = {str(v.aval.dtype) for v in eqn.outvars
                if hasattr(v.aval, "dtype")}
        if din != dout:
            out.append(LintFinding(
                "exchange-dtype",
                f"{eqn.primitive.name} retypes its payload: {sorted(din)} "
                f"-> {sorted(dout)}"))
    return out


def lint_guard_arity(jaxpr: Any, guard_mode: str) -> List[LintFinding]:
    """The guarded wrapper returns ``(y, stats)``; an off-mode build
    returning more than the transform result means guard ops leaked into
    the default path."""
    closed = jaxpr if hasattr(jaxpr, "out_avals") else None
    if closed is None:
        return []
    n = len(closed.out_avals)
    if guard_mode == "off" and n != 1:
        return [LintFinding(
            "guard-off",
            f"guards=\"off\" build returns {n} outputs (expected the "
            "transform result alone) — guard ops present in the default "
            "path")]
    if guard_mode != "off" and n != 2:
        return [LintFinding(
            "guard-arity",
            f"guards=\"{guard_mode}\" build returns {n} outputs (expected "
            "the (result, stats) pair)")]
    return []


def plan_jaxpr(plan: Any, direction: str = "forward", dims: int = 3) -> Any:
    """The traced (closed) jaxpr of one direction's builder — guards and
    wire layer included, exactly what the exec path jits."""
    import jax

    from . import hloscan

    fn = hloscan._builder(plan, direction, dims)
    return jax.make_jaxpr(fn)(hloscan._input_aval(plan, direction, dims))


def lint_plan(plan: Any, direction: str = "forward",
              dims: int = 3,
              jaxpr: Optional[Any] = None) -> List[LintFinding]:
    """All jaxpr lints over one direction of a live plan. ``jaxpr``
    lets a caller that already traced the combo (``dfft-verify`` shares
    one trace with the plan-graph pass) skip re-tracing."""
    from . import contracts

    if jaxpr is None:
        jaxpr = plan_jaxpr(plan, direction, dims)
    wire = plan.config.wire_dtype
    crossings = 0
    if wire != "native":
        decls = contracts._FAMILIES[contracts.family_of(plan)](
            plan, direction, dims)
        crossings = len(decls)
        if getattr(plan, "_guard_mode", "off") != "off":
            crossings += 1  # the guard drift probe's extra encode/decode
    out = lint_wire_pairing(jaxpr, expect_crossings=crossings)
    out += lint_exchange_dtypes(jaxpr)
    out += lint_guard_arity(jaxpr, getattr(plan, "_guard_mode", "off"))
    return out
