"""Declarative plan/HLO contracts: what each rendering's compiled program
MUST look like, checked without executing anything.

A **contract** is resolved per combo (family x rendering x direction x
wire x guards) from two declarative sources:

* the family's exchange declaration (``models/{slab,pencil,batched2d}.py``
  register an ``exchanges(plan, direction, dims)`` function next to the
  family) — one ``ExchangeDecl`` per global exchange the direction
  stages: its payload shape, participating axis size, and rendering;
* the rendering algebra in this module — how each exchange rendering
  contributes to the expected collective census:

  ============  =========================================================
  rendering     census contribution
  ============  =========================================================
  ``a2a``       exactly 1 ``all-to-all`` (sync or async-start form)
  ``streams``   exactly K ``all-to-all``\\ s (the chunked piece chains)
  ``a2a_pipe``  exactly K ``all-to-all``\\ s (the software-pipelined
                monolithic exchange; same K-instance pin as streams —
                a GSPMD re-fuse back into one collective fails it)
  ``ring``      >= (P-1) x S ``collective-permute``\\ s (S = sub-block
                split), 0 ``all-to-all``\\ s — the un-fusable
                split-exchange signature (OVERLAP.md)
  ``p2p``       GSPMD owns the schedule: >= 1 collective, exact counts
                unpinnable across backends (every exact rule degrades to
                a lower bound when a GSPMD exchange is present)
  ============  =========================================================

Cross-cutting rules resolved from plan state:

* **forbidden ops** — a native-wire program is bf16-FREE (the structural
  form of bit-identity); a plan with no exchanges (single-device
  reference path, batch sharding) carries ZERO exchange collectives, and
  zero all-reduces when guards are off;
* **payload reconciliation** — the staged module's summed exchange bytes
  equal the prediction from ``wire_nbytes`` over the declared payload
  shapes (ring exchanges carry the exact ``(P-1)/P`` discount: the local
  block never travels). Skipped when GSPMD stages no explicit collective.

``verify_plan`` is the one-call API: build the contract for a live plan,
compile both module views, return the violations (empty = verified).
Each violation names its contract and rule, so a failing gate says WHICH
invariant broke, not just that a count changed.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import hloscan

# Rendering keys of a single exchange (``ExchangeDecl.rendering``).
# "ring_overlap" is the revolving-buffer ring schedule (SendMethod.
# RING_OVERLAP at any overlap depth, with or without the fused wire
# kernels): same census algebra and (P-1)/P payload discount as "ring" —
# the permutes must stay distinct and un-fusable whichever schedule
# issued them, which is exactly the pin that stops GSPMD from
# serializing the overlap back. "a2a_pipe" is the software-pipelined
# monolithic exchange (ALL2ALL + SYNC/MPI_TYPE with overlap_subblocks >
# 1, ``transpose.pipelined_all_to_all``): K chunked all-to-alls like
# "streams", pinned to exactly K so a GSPMD re-fuse back into one
# collective fails the census.
RENDERINGS = ("a2a", "streams", "a2a_pipe", "ring", "ring_overlap", "p2p")

# The renderings that stage a ppermute ring (shared by the census and
# payload resolution below).
_RING_RENDERINGS = ("ring", "ring_overlap")


@dataclasses.dataclass(frozen=True)
class ExchangeDecl:
    """One global exchange a plan direction stages: the declarative unit
    the family modules register (``label`` names it in diagnostics;
    ``payload_shape`` is the GLOBAL padded payload; ``axis_size`` the
    participating mesh-axis extent; ``chunks`` the resolved STREAMS /
    a2a_pipe piece count, 1 otherwise; ``subblocks`` the resolved ring
    sub-block split — each peer step becomes ``subblocks`` distinct
    permutes, so the census scales with it)."""

    label: str
    payload_shape: Tuple[int, ...]
    axis_size: int
    rendering: str
    chunks: int = 1
    subblocks: int = 1

    def __post_init__(self) -> None:
        if self.rendering not in RENDERINGS:
            raise ValueError(
                f"rendering must be one of {RENDERINGS}, "
                f"got {self.rendering!r}")
        if self.subblocks < 1:
            raise ValueError(
                f"subblocks must be >= 1, got {self.subblocks}")


@dataclasses.dataclass(frozen=True)
class Rule:
    """One resolved check. ``kind``:

    * ``census``  — ``combined count of ``op`` <cmp> value`` on the
      compiled module (sync + async-start forms summed, the TPU-portable
      count the tier-1 gates always used);
    * ``forbid``  — substring ``op`` absent from the compiled text;
    * ``payload`` — staged exchange bytes == value (global convention).
    """

    kind: str
    op: str
    cmp: str = "=="
    value: int = 0
    why: str = ""

    def describe(self) -> str:
        if self.kind == "forbid":
            return f"forbid {self.op!r} in compiled HLO"
        if self.kind == "payload":
            return f"staged exchange payload == {self.value} B"
        return f"census {self.op} {self.cmp} {self.value}"


@dataclasses.dataclass(frozen=True)
class Contract:
    """A fully-resolved combo contract: ``name`` is
    ``<family>/<rendering-summary>`` and lands verbatim in diagnostics."""

    name: str
    family: str
    direction: str
    wire: str
    guards: str
    exchanges: Tuple[ExchangeDecl, ...]
    rules: Tuple[Rule, ...]


@dataclasses.dataclass(frozen=True)
class ContractViolation:
    """One broken rule, carrying enough to act on: the contract name (the
    diagnostic the mutation tests assert on), the rule, and what the
    module actually contained."""

    contract: str
    rule: Rule
    got: Any

    def __str__(self) -> str:
        return (f"[{self.contract}] violated: {self.rule.describe()} "
                f"(got {self.got})"
                + (f" — {self.rule.why}" if self.rule.why else ""))


# ---------------------------------------------------------------------------
# family registry (populated by the model modules at import)
# ---------------------------------------------------------------------------

_FAMILIES: Dict[str, Callable[..., Tuple[ExchangeDecl, ...]]] = {}
_FAMILY_OF_CLASS: Dict[str, str] = {}


def register_family(family: str, plan_class_name: str,
                    exchanges: Callable[..., Tuple[ExchangeDecl, ...]]
                    ) -> None:
    """Called by each model module, next to the family it declares:
    ``exchanges(plan, direction, dims)`` returns the direction's
    ``ExchangeDecl`` tuple."""
    _FAMILIES[family] = exchanges
    _FAMILY_OF_CLASS[plan_class_name] = family


def family_of(plan: Any) -> str:
    name = type(plan).__name__
    fam = _FAMILY_OF_CLASS.get(name)
    if fam is None:
        raise KeyError(
            f"no contract family registered for plan class {name!r} "
            f"(known: {sorted(_FAMILY_OF_CLASS)})")
    return fam


def scope_family(plan: Any) -> str:
    """The family key a plan's stage scopes are named under
    (``dfft/<family>/<node-id>``; ``obs/profile.py``): the registered
    contract family, falling back to the class name for plan types the
    registry does not know. The ONE resolution both the models' scope
    emission and the guard layer use, so scope names can never disagree
    between emitters."""
    try:
        return family_of(plan)
    except KeyError:
        return type(plan).__name__.lower()


def rendering_name(config: Any, second: bool = False) -> str:
    """The rendering key one transpose resolves to from a (concrete)
    Config — the same classification ``dfft-explain`` prints."""
    from .. import params as pm

    comm = config.resolved_comm2() if second else config.comm_method
    send = config.resolved_snd2() if second else config.send_method
    if send is pm.SendMethod.RING_OVERLAP:
        return "ring_overlap"
    if send is pm.SendMethod.RING:
        return "ring"
    if send is pm.SendMethod.STREAMS:
        # GSPMD re-fuses the piece reshards into ONE collective
        # (OVERLAP.md): structurally the p2p contract applies.
        return "p2p" if comm is pm.CommMethod.PEER2PEER else "streams"
    if comm is pm.CommMethod.PEER2PEER:
        return "p2p"
    if config.resolved_overlap_subblocks() > 1:
        # ALL2ALL + SYNC/MPI_TYPE with a sub-block split: the
        # software-pipelined monolithic exchange.
        return "a2a_pipe"
    return "a2a"


# ---------------------------------------------------------------------------
# resolution
# ---------------------------------------------------------------------------

def _complex_dtype(plan: Any) -> Any:
    import numpy as np

    return np.complex128 if plan.config.double_prec else np.complex64


def contract_for(plan: Any, direction: str = "forward",
                 dims: int = 3) -> Contract:
    """Resolve the declarative contract for one direction of a live plan."""
    family = family_of(plan)
    decls = tuple(_FAMILIES[family](plan, direction, dims))
    cfg = plan.config
    return contract_from_decls(family, direction, cfg.wire_dtype,
                               getattr(plan, "_guard_mode", "off"),
                               _complex_dtype(plan), decls)


def contract_from_decls(family: str, direction: str, wire: str,
                        guards: str, complex_dtype: Any,
                        decls: Tuple[ExchangeDecl, ...]) -> Contract:
    """The rendering algebra over an explicit declaration set — the
    resolution core of ``contract_for``, factored out so a contract can
    be synthesized from ANY declaration source (``plangraph`` derives
    one from a declared stage graph, proving the graph's exchanges
    against the same compiled census the family contract pins)."""
    cdt = complex_dtype

    n_a2a = 0          # deterministic all-to-all instances
    ring_steps = 0     # minimum collective-permute instances
    n_gspmd = 0        # exchanges whose schedule GSPMD owns
    payload = 0        # staged bytes of the deterministic exchanges
    for d in decls:
        if d.rendering == "a2a":
            n_a2a += 1
        elif d.rendering in ("streams", "a2a_pipe"):
            n_a2a += max(1, d.chunks)
        elif d.rendering in _RING_RENDERINGS:
            # Each peer step travels as ``subblocks`` distinct permutes
            # (the block-granularity micro-steps).
            ring_steps += max(0, d.axis_size - 1) * max(1, d.subblocks)
        else:
            n_gspmd += 1
        if d.rendering != "p2p":
            payload += hloscan.predicted_payload_bytes(
                d.payload_shape, cdt, wire,
                ring_size=(d.axis_size
                           if d.rendering in _RING_RENDERINGS else 0))

    rules: List[Rule] = []
    summary = "+".join(sorted({d.rendering for d in decls})) or "none"
    name = f"{family}/{summary}"
    if not decls:
        # The no-exchange contract: the single-device reference path and
        # batch sharding issue ZERO collectives (and zero all-reduces
        # until guards add their scalar reduction).
        for op in ("all_to_all", "collective_permute", "all_gather",
                   "reduce_scatter"):
            rules.append(Rule("census", op, "==", 0,
                              why="no-exchange path must stay "
                                  "collective-free"))
        if guards == "off":
            rules.append(Rule("census", "all_reduce", "==", 0,
                              why="guards off: nothing may reduce"))
    elif n_gspmd == 0:
        rules.append(Rule("census", "all_to_all", "==", n_a2a,
                          why="monolithic exchanges: one collective each; "
                              "STREAMS/a2a_pipe: one per chunk"))
        if ring_steps:
            rules.append(Rule("census", "collective_permute", ">=",
                              ring_steps,
                              why="ring steps must stay distinct "
                                  "(un-fusable) permutes"))
        else:
            rules.append(Rule("census", "collective_permute", "==", 0,
                              why="no ring declared: a permute would be "
                                  "a rendering regression"))
        rules.append(Rule("payload", "exchange", "==", payload,
                          why="staged wire bytes must reconcile with "
                              "wire_nbytes over the declared payloads"))
    else:
        # GSPMD owns part of the schedule: exact pins degrade to lower
        # bounds, plus "every boundary emits at least one collective".
        if n_a2a:
            rules.append(Rule("census", "all_to_all", ">=", n_a2a,
                              why="explicit exchanges survive GSPMD"))
        if ring_steps:
            rules.append(Rule("census", "collective_permute", ">=",
                              ring_steps,
                              why="ring steps must stay distinct "
                                  "(un-fusable) permutes"))
        rules.append(Rule("census", "exchange_total", ">=",
                          n_a2a + ring_steps + n_gspmd,
                          why="each GSPMD boundary reshards through at "
                              "least one collective"))
    if wire == "native":
        rules.append(Rule("forbid", "bf16",
                          why="native wire is structurally bf16-free, "
                              "not merely numerically close"))
    return Contract(name=name, family=family, direction=direction,
                    wire=wire, guards=guards, exchanges=decls,
                    rules=tuple(rules))


# ---------------------------------------------------------------------------
# checking
# ---------------------------------------------------------------------------

def _combined(census: Dict[str, int], op: str) -> int:
    """Sync + async-start instance count of one census op (or the
    combined exchange total)."""
    if op == "exchange_total":
        return sum(_combined(census, o)
                   for o in ("all_to_all", "collective_permute",
                             "all_gather", "reduce_scatter"))
    return census.get(op, 0) + census.get(f"{op}_start", 0)


def _cmp(cmp: str, got: int, want: int) -> bool:
    if cmp == "==":
        return got == want
    if cmp == ">=":
        return got >= want
    if cmp == "<=":
        return got <= want
    raise ValueError(f"unknown comparison {cmp!r}")


def check_contract(contract: Contract, census: Dict[str, int],
                   compiled_txt: str,
                   staged_total: Optional[int]) -> List[ContractViolation]:
    """Check one resolved contract against the module facts; returns the
    violations (empty = the combo verifies)."""
    out: List[ContractViolation] = []
    for rule in contract.rules:
        if rule.kind == "census":
            got = _combined(census, rule.op)
            if not _cmp(rule.cmp, got, rule.value):
                out.append(ContractViolation(contract.name, rule, got))
        elif rule.kind == "forbid":
            if rule.op in compiled_txt:
                out.append(ContractViolation(contract.name, rule,
                                             f"{rule.op!r} present"))
        elif rule.kind == "payload":
            if staged_total is None:
                # GSPMD staged no explicit collective; nothing to
                # reconcile (the census rules still apply).
                continue
            if staged_total != rule.value:
                out.append(ContractViolation(contract.name, rule,
                                             f"{staged_total} B"))
        else:  # pragma: no cover - Rule kinds are closed above
            raise ValueError(f"unknown rule kind {rule.kind!r}")
    return out


def verify_plan(plan: Any, direction: str = "forward", dims: int = 3,
                contract: Optional[Contract] = None
                ) -> List[ContractViolation]:
    """Lower + compile one direction of a live plan and check it against
    its (or an explicitly supplied) contract. The one-call API the test
    gates and ``dfft-verify`` share — and the census lands in the
    ``hlo.*`` obs gauges as a side effect, like every census."""
    contract = contract or contract_for(plan, direction, dims)
    txt = hloscan.compiled_text(plan, direction, dims)
    census = hloscan.collective_census(txt)
    staged = None
    if any(r.kind == "payload" for r in contract.rules):
        staged = hloscan.staged_exchange_total(plan, direction, dims)
    return check_contract(contract, census, txt, staged)
