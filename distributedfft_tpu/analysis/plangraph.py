"""Declared stage-graph IR: every plan family emits a typed graph of the
pipeline it builds, and this module proves the graph sound — and proves
the BUILD actually implements it.

Until now each family declared only its *exchanges*
(``_contract_exchanges``); the full pipeline — which local-FFT stages
run where, where the wire encode/decode sits, where the guard wraps —
existed only as Python closures the verifier could not inspect. The
Plan-IR refactor (ROADMAP item 1) needs exactly that structure as data,
so each family now also registers ``_declare_graph(plan, direction,
dims) -> PlanGraph``: a DAG of **stage nodes**

=================  =====================================================
kind               meaning
=================  =====================================================
``input``          the pipeline source (one per graph)
``local_fft``      one local FFT stage; ``axes`` = global axes it
                   transforms, in application order
``exchange``       one global exchange; carries the rendering key,
                   participating mesh-axis size, GLOBAL padded payload
                   shape, resolved STREAMS/a2a_pipe chunk count, the
                   ring sub-block split and the schedule depth (0 = no
                   pipelined schedule, 1 = serial ring, >= 2 =
                   revolving-buffer overlap / pipelined-a2a window)
``encode``         the wire encode (complex -> planar bf16 pair)
``decode``         the wire decode (planar pair -> complex)
``fused_kernel``   a fused Pallas wire kernel; ``fuses`` names what it
                   replaces (("encode","pack") / ("decode",) /
                   ("decode","fft"))
``guard``          the in-graph numerical guard wrapper (modes
                   check/enforce)
``output``         the pipeline sink (one per graph)
=================  =====================================================

and **edges** carrying the payload that flows between stages: global
padded shape, dtype, sharding spec, and — on the edges touching an
exchange — the wire bytes that cross the mesh (with the exact
``(P-1)/P`` ring discount).

Three checker layers, all consumed per-combo by ``dfft-verify``:

* ``check_graph``          — well-formedness: dataflow soundness (single
  source/sink DAG, every node on an input->output path), encode/decode
  pairing around every compressed exchange, dtype flow across exchanges
  (the payload crosses unchanged; the decode restores the pre-encode
  dtype), payload conservation (edge wire bytes == ``wire_nbytes`` over
  the declared payload, ring-discounted), guard arity, and a hazard pass
  over every ring exchange's revolving schedule
  (``analysis/schedverify.py``);
* ``check_graph_contract`` — the graph's exchange nodes must reconcile
  with the family's ``_contract_exchanges`` declaration 1:1, so the two
  declarative sources cannot drift;
* ``check_graph_trace``    — the declared graph against the program the
  build function actually traces/compiles: the traced jaxpr must contain
  at least the declared explicit collectives (a declared-but-unbuilt
  "phantom" exchange fails here), and a contract SYNTHESIZED from the
  graph's exchange nodes (``contracts.contract_from_decls``) must pass
  against the compiled census/payloads — a family cannot declare a graph
  its build function does not implement.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from . import contracts, schedverify

NODE_KINDS = ("input", "local_fft", "exchange", "encode", "decode",
              "fused_kernel", "guard", "output")


@dataclasses.dataclass(frozen=True)
class StageNode:
    """One pipeline stage. Only the fields meaningful for the ``kind``
    are populated (an ``exchange`` carries rendering/axis_size/payload;
    a ``local_fft`` carries axes; a ``fused_kernel`` names what it
    fuses)."""

    id: str
    kind: str
    label: str = ""
    axes: Tuple[int, ...] = ()
    rendering: str = ""
    axis_size: int = 0
    chunks: int = 1
    subblocks: int = 1
    payload_shape: Tuple[int, ...] = ()
    schedule_depth: int = 0
    fuses: Tuple[str, ...] = ()

    def __post_init__(self) -> None:
        if self.kind not in NODE_KINDS:
            raise ValueError(
                f"node kind must be one of {NODE_KINDS}, got {self.kind!r}")

    def encodes(self) -> bool:
        return self.kind == "encode" or (self.kind == "fused_kernel"
                                         and "encode" in self.fuses)

    def decodes(self) -> bool:
        return self.kind == "decode" or (self.kind == "fused_kernel"
                                         and "decode" in self.fuses)


@dataclasses.dataclass(frozen=True)
class StageEdge:
    """The payload flowing from stage ``src`` to stage ``dst``:
    ``shape``/``dtype`` of the GLOBAL (padded) array, its sharding spec
    (best-effort string), and ``wire_bytes`` — the bytes this payload
    puts on the mesh wire, non-zero only on the edges into/out of an
    exchange (ring-discounted there)."""

    src: str
    dst: str
    shape: Tuple[int, ...]
    dtype: str
    spec: str = ""
    wire_bytes: int = 0


@dataclasses.dataclass(frozen=True)
class PlanGraph:
    """One direction of one plan, as declared data. ``wire``/``guards``
    are the resolved plan state the checks interpret the graph under;
    ``complex_dtype`` the spectral payload dtype every exchange moves."""

    family: str
    direction: str
    wire: str
    guards: str
    complex_dtype: str
    nodes: Tuple[StageNode, ...]
    edges: Tuple[StageEdge, ...]

    @property
    def name(self) -> str:
        return f"{self.family}/{self.direction}"

    def node(self, node_id: str) -> StageNode:
        for n in self.nodes:
            if n.id == node_id:
                return n
        raise KeyError(node_id)

    def exchanges(self) -> Tuple[StageNode, ...]:
        return tuple(n for n in self.nodes if n.kind == "exchange")

    def in_edges(self, node_id: str) -> Tuple[StageEdge, ...]:
        return tuple(e for e in self.edges if e.dst == node_id)

    def out_edges(self, node_id: str) -> Tuple[StageEdge, ...]:
        return tuple(e for e in self.edges if e.src == node_id)


@dataclasses.dataclass(frozen=True)
class GraphViolation:
    """One broken graph invariant; ``check`` names the checker layer and
    rule (what the mutation tests assert on)."""

    graph: str
    check: str
    message: str

    def __str__(self) -> str:
        return f"[plangraph/{self.graph}] {self.check}: {self.message}"


class GraphBuilder:
    """Linear pipeline builder — the families' declaration helper. The
    payload set by ``payload(...)`` rides the NEXT edge (i.e. it
    describes what the most recent node emits); ``node(...)`` appends a
    stage and connects it from the previous one."""

    def __init__(self, family: str, direction: str, wire: str,
                 guards: str, complex_dtype: str) -> None:
        self._family = family
        self._direction = direction
        self._wire = wire
        self._guards = guards
        self._cdt = complex_dtype
        self._nodes: List[StageNode] = []
        self._edges: List[StageEdge] = []
        self._counts: Dict[str, int] = {}
        self._shape: Tuple[int, ...] = ()
        self._dtype: str = ""
        self._spec: str = ""
        self._wire_bytes: int = 0

    def payload(self, shape: Iterable[int], dtype: str, spec: Any = "",
                wire_bytes: int = 0) -> None:
        self._shape = tuple(int(s) for s in shape)
        self._dtype = str(dtype)
        self._spec = str(spec)
        self._wire_bytes = int(wire_bytes)

    def node(self, kind: str, **fields: Any) -> str:
        n = self._counts.get(kind, 0) + 1
        self._counts[kind] = n
        node_id = kind if kind in ("input", "output", "guard") \
            else f"{kind}:{n}"
        self._nodes.append(StageNode(id=node_id, kind=kind, **fields))
        if len(self._nodes) > 1:
            prev = self._nodes[-2]
            self._edges.append(StageEdge(
                prev.id, node_id, self._shape, self._dtype, self._spec,
                self._wire_bytes))
        return node_id

    def exchange(self, label: str, payload_shape: Iterable[int],
                 axis_size: int, rendering: str, *, chunks: int = 1,
                 subblocks: int = 1, schedule_depth: int = 0,
                 wire_spec: Any = "", decoded_spec: Any = "",
                 fused_encode: bool = False,
                 decode_fuses: Optional[Tuple[str, ...]] = None) -> str:
        """Append one declared exchange as its full stage group —
        ``(encode ->) exchange (-> decode)`` under a compressed wire,
        the bare exchange under native — with the wire-byte bookkeeping
        (ring discount included) applied to every edge touching it.

        Under a compressed wire the decode node is appended here and the
        payload is reset to the decoded complex form (``decoded_spec``).
        Under a native wire the exchange's OUT edge is the one the NEXT
        family-added node creates, so the caller must set its own
        payload only after appending that node."""
        from . import hloscan

        shape = tuple(int(s) for s in payload_shape)
        ring = rendering in contracts._RING_RENDERINGS
        pred = hloscan.predicted_payload_bytes(
            shape, self._cdt, self._wire,
            ring_size=axis_size if ring else 0)
        compressed = self._wire != "native"
        if compressed:
            # The edge into the encode carries the complex payload the
            # wire is about to compress (what the decode must restore).
            self.payload(shape, self._cdt, wire_spec, 0)
            if fused_encode:
                self.node("fused_kernel", fuses=("encode", "pack"),
                          label=f"{label} encode")
            else:
                self.node("encode", label=f"{label} encode")
            self.payload((2,) + shape, "bfloat16", wire_spec, pred)
        else:
            self.payload(shape, self._cdt, wire_spec, pred)
        xid = self.node("exchange", label=label, rendering=rendering,
                        axis_size=axis_size, chunks=chunks,
                        subblocks=subblocks, payload_shape=shape,
                        schedule_depth=schedule_depth)
        if compressed:
            if decode_fuses:
                self.node("fused_kernel", fuses=decode_fuses,
                          label=f"{label} decode")
            else:
                self.node("decode", label=f"{label} decode")
            self.payload(shape, self._cdt, decoded_spec, 0)
        return xid

    def graph(self) -> PlanGraph:
        return PlanGraph(self._family, self._direction, self._wire,
                         self._guards, self._cdt,
                         tuple(self._nodes), tuple(self._edges))


def shipped_schedule_depth(rendering: str, config: Any = None) -> int:
    """The pipelined-schedule depth a rendering ships with under
    ``config``: the resolved ``Config.overlap_depth`` for the
    revolving-buffer RING_OVERLAP pipeline and the pipelined a2a's
    issue-ahead window ("auto" -> 2, the shipped double-buffered
    schedule), 1 for the serial RING, 0 for every other rendering.
    ``config=None`` keeps the pre-autotune defaults. The single source
    the three family ``_declare_graph`` hooks share — ROADMAP item 3's
    autotuned depth landed here, not in three copies."""
    if rendering == "ring":
        return 1
    if rendering not in ("ring_overlap", "a2a_pipe"):
        return 0
    if config is None:
        return 2
    return int(config.resolved_overlap_depth())


def payload_dtypes(config: Any, transform: str) -> Tuple[str, str]:
    """``(complex_dtype, real_side_dtype)`` of a plan's payloads under
    its config: the spectral dtype every exchange moves, and the dtype
    of the real-side boundary (equal to the complex dtype for c2c
    plans). Shared by the family ``_declare_graph`` hooks."""
    cdt = "complex128" if config.double_prec else "complex64"
    if transform == "c2c":
        return cdt, cdt
    return cdt, "float64" if config.double_prec else "float32"


# ---------------------------------------------------------------------------
# family registry (populated by the model modules at import, next to the
# contracts registration — one import, two declarative surfaces)
# ---------------------------------------------------------------------------

_GRAPH_FAMILIES: Dict[str, Callable[..., PlanGraph]] = {}


def register_graph_family(family: str,
                          declare: Callable[..., PlanGraph]) -> None:
    """Called by each model module: ``declare(plan, direction, dims)``
    returns the direction's ``PlanGraph``. Families are keyed like the
    contract registry (``contracts.register_family``)."""
    _GRAPH_FAMILIES[family] = declare


class MissingGraph(KeyError):
    """No stage graph declared for a plan family — a verify-matrix
    failure, never a silent skip."""


def graph_for(plan: Any, direction: str = "forward",
              dims: int = 3) -> PlanGraph:
    """Resolve the declared stage graph for one direction of a live
    plan. Raises ``MissingGraph`` when the family never registered a
    declaration (``dfft-verify`` turns that into a combo FAILURE)."""
    family = contracts.family_of(plan)
    declare = _GRAPH_FAMILIES.get(family)
    if declare is None:
        raise MissingGraph(
            f"family {family!r} registered no _declare_graph "
            f"(known: {sorted(_GRAPH_FAMILIES)})")
    return declare(plan, direction, dims)


# ---------------------------------------------------------------------------
# (a) well-formedness
# ---------------------------------------------------------------------------

def _viol(graph: PlanGraph, check: str, message: str) -> GraphViolation:
    return GraphViolation(graph.name, check, message)


def _check_dataflow(graph: PlanGraph) -> List[GraphViolation]:
    """Single-source/single-sink DAG with every node on an
    input->output path — no orphan stages, no dead ends, no cycles."""
    out: List[GraphViolation] = []
    ids = [n.id for n in graph.nodes]
    if len(set(ids)) != len(ids):
        out.append(_viol(graph, "dataflow", "duplicate node ids"))
        return out
    idset = set(ids)
    for e in graph.edges:
        for end in (e.src, e.dst):
            if end not in idset:
                out.append(_viol(graph, "dataflow",
                                 f"edge references unknown node {end!r}"))
                return out
    sources = [n.id for n in graph.nodes if n.kind == "input"]
    sinks = [n.id for n in graph.nodes if n.kind == "output"]
    if len(sources) != 1 or len(sinks) != 1:
        out.append(_viol(
            graph, "dataflow",
            f"expected exactly one input and one output node, got "
            f"{len(sources)} input(s) / {len(sinks)} output(s)"))
        return out
    succ: Dict[str, List[str]] = {i: [] for i in ids}
    pred: Dict[str, List[str]] = {i: [] for i in ids}
    for e in graph.edges:
        succ[e.src].append(e.dst)
        pred[e.dst].append(e.src)
    # Reachability both ways: forward from input, backward from output.
    def closure(start: str, adj: Dict[str, List[str]]) -> set:
        seen = {start}
        stack = [start]
        while stack:
            for nxt in adj[stack.pop()]:
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return seen

    fwd = closure(sources[0], succ)
    bwd = closure(sinks[0], pred)
    for n in graph.nodes:
        if n.id not in fwd or n.id not in bwd:
            out.append(_viol(
                graph, "dataflow",
                f"node {n.id!r} is not on an input->output path "
                "(orphan or dead-end stage)"))
    # Cycle check: Kahn's topological sort must consume every node.
    indeg = {i: len(pred[i]) for i in ids}
    queue = [i for i in ids if indeg[i] == 0]
    seen = 0
    while queue:
        cur = queue.pop()
        seen += 1
        for nxt in succ[cur]:
            indeg[nxt] -= 1
            if indeg[nxt] == 0:
                queue.append(nxt)
    if seen != len(ids):
        out.append(_viol(graph, "dataflow", "graph contains a cycle"))
    return out


def _check_wire_pairing(graph: PlanGraph) -> List[GraphViolation]:
    out: List[GraphViolation] = []
    encoders = [n for n in graph.nodes if n.encodes()]
    decoders = [n for n in graph.nodes if n.decodes()]
    if graph.wire == "native":
        for n in encoders + decoders:
            out.append(_viol(
                graph, "wire-pairing",
                f"native wire but graph declares {n.kind} node "
                f"{n.id!r} — the wire layer must be structurally inert"))
        return out
    if len(encoders) != len(decoders):
        out.append(_viol(
            graph, "wire-pairing",
            f"unpaired encode/decode nodes: {len(encoders)} encode(s) "
            f"but {len(decoders)} decode(s) — a dropped decode leaves "
            "the payload bf16 past the exchange"))
    for x in graph.exchanges():
        preds = [graph.node(e.src) for e in graph.in_edges(x.id)]
        succs = [graph.node(e.dst) for e in graph.out_edges(x.id)]
        if not any(p.encodes() for p in preds):
            out.append(_viol(
                graph, "wire-pairing",
                f"compressed exchange {x.id!r} has no encode stage "
                "immediately upstream"))
        if not any(s.decodes() for s in succs):
            out.append(_viol(
                graph, "wire-pairing",
                f"compressed exchange {x.id!r} has no decode stage "
                "immediately downstream"))
    for n in encoders:
        succs = [graph.node(e.dst) for e in graph.out_edges(n.id)]
        if not any(s.kind == "exchange" for s in succs):
            out.append(_viol(
                graph, "wire-pairing",
                f"encode node {n.id!r} does not feed an exchange"))
    for n in decoders:
        preds = [graph.node(e.src) for e in graph.in_edges(n.id)]
        if not any(p.kind == "exchange" for p in preds):
            out.append(_viol(
                graph, "wire-pairing",
                f"decode node {n.id!r} is not fed by an exchange"))
    return out


def _check_dtype_flow(graph: PlanGraph) -> List[GraphViolation]:
    """An exchange moves its payload dtype unchanged, and the stage pair
    around a compressed exchange restores the pre-encode dtype."""
    out: List[GraphViolation] = []
    for x in graph.exchanges():
        ins = graph.in_edges(x.id)
        outs = graph.out_edges(x.id)
        din = {e.dtype for e in ins}
        dout = {e.dtype for e in outs}
        if din != dout:
            out.append(_viol(
                graph, "dtype-flow",
                f"exchange {x.id!r} retypes its payload: "
                f"{sorted(din)} -> {sorted(dout)}"))
        for e in ins:
            src = graph.node(e.src)
            if src.encodes():
                enc_in = {i.dtype for i in graph.in_edges(src.id)}
                for o in outs:
                    dst = graph.node(o.dst)
                    if dst.decodes():
                        dec_out = {d.dtype
                                   for d in graph.out_edges(dst.id)}
                        if enc_in != dec_out:
                            out.append(_viol(
                                graph, "dtype-flow",
                                f"decode after {x.id!r} restores "
                                f"{sorted(dec_out)} but the encode "
                                f"consumed {sorted(enc_in)} — the wire "
                                "must restore the pre-encode width"))
    return out


def _check_payload(graph: PlanGraph) -> List[GraphViolation]:
    """Payload conservation: the wire bytes on every edge touching an
    exchange equal ``wire_nbytes`` over the node's declared GLOBAL
    payload under the graph's wire encoding, with the exact ``(P-1)/P``
    discount for ring renderings — and in == out (the exchange moves
    bytes, it does not create or lose them)."""
    from . import hloscan

    out: List[GraphViolation] = []
    for x in graph.exchanges():
        ring = x.rendering in contracts._RING_RENDERINGS
        want = hloscan.predicted_payload_bytes(
            x.payload_shape, graph.complex_dtype, graph.wire,
            ring_size=x.axis_size if ring else 0)
        got_in = {e.wire_bytes for e in graph.in_edges(x.id)}
        got_out = {e.wire_bytes for e in graph.out_edges(x.id)}
        if got_in != got_out:
            out.append(_viol(
                graph, "payload",
                f"exchange {x.id!r} does not conserve wire bytes: "
                f"{sorted(got_in)} in vs {sorted(got_out)} out"))
        for got in sorted(got_in | got_out):
            if got != want:
                out.append(_viol(
                    graph, "payload",
                    f"exchange {x.id!r} edge carries {got} wire B but "
                    f"the declared payload {x.payload_shape} predicts "
                    f"{want} B"
                    + (" (with the (P-1)/P ring discount)" if ring
                       else "")))
    return out


def _check_guard_arity(graph: PlanGraph) -> List[GraphViolation]:
    guards = [n for n in graph.nodes if n.kind == "guard"]
    if graph.guards == "off":
        if guards:
            return [_viol(graph, "guard-arity",
                          f"guards=\"off\" but {len(guards)} guard "
                          "node(s) declared — guard stages may not "
                          "exist in the default path")]
        return []
    if len(guards) != 1:
        return [_viol(graph, "guard-arity",
                      f"guards=\"{graph.guards}\" expects exactly one "
                      f"guard node, got {len(guards)}")]
    succs = [graph.node(e.dst) for e in graph.out_edges(guards[0].id)]
    if not any(s.kind == "output" for s in succs):
        return [_viol(graph, "guard-arity",
                      "the guard node must wrap the pipeline result "
                      "(feed the output node)")]
    return []


def _check_schedules(graph: PlanGraph) -> List[GraphViolation]:
    """Every pipelined exchange schedule must prove hazard-free at its
    declared depth/sub-block split (``analysis/schedverify.py``): the
    ring renderings' revolving-buffer micro-step schedule, and the
    pipelined all_to_all's issue-ahead window (verified as the
    equivalent K-step revolving discipline — K chunk collectives, the
    same issue/wait/compute semantics)."""
    out: List[GraphViolation] = []
    for x in graph.exchanges():
        if x.rendering == "a2a_pipe":
            depth = x.schedule_depth
            if depth < 1:
                out.append(_viol(
                    graph, "schedule",
                    f"pipelined exchange {x.id!r} declares no schedule "
                    f"depth"))
                continue
            k = max(1, x.chunks)
            timeline = schedverify.revolving_schedule(k + 1, depth)
            for h in schedverify.check_schedule(timeline, k + 1, depth):
                out.append(_viol(graph, "schedule",
                                 f"exchange {x.id!r}: {h}"))
            continue
        if x.rendering not in contracts._RING_RENDERINGS:
            if x.schedule_depth:
                out.append(_viol(
                    graph, "schedule",
                    f"non-pipelined exchange {x.id!r} declares schedule "
                    f"depth {x.schedule_depth}"))
            continue
        depth = x.schedule_depth
        if depth < 1:
            out.append(_viol(
                graph, "schedule",
                f"ring exchange {x.id!r} declares no schedule depth"))
            continue
        if x.rendering == "ring_overlap" and depth < 2:
            out.append(_viol(
                graph, "schedule",
                f"ring_overlap exchange {x.id!r} declares depth "
                f"{depth} — the revolving pipeline needs >= 2 buffers"))
        timeline = schedverify.revolving_schedule(x.axis_size, depth,
                                                  x.subblocks)
        for h in schedverify.check_schedule(timeline, x.axis_size, depth,
                                            x.subblocks):
            out.append(_viol(graph, "schedule",
                             f"exchange {x.id!r}: {h}"))
    return out


def check_graph(graph: PlanGraph) -> List[GraphViolation]:
    """All well-formedness checks over one declared graph (empty = the
    graph is internally sound; conformance against the contract and the
    traced/compiled program are separate layers)."""
    out = _check_dataflow(graph)
    if out:
        # Structural breakage makes the local checks meaningless (and
        # possibly crashy — missing endpoints); report it alone.
        return out
    out += _check_wire_pairing(graph)
    out += _check_dtype_flow(graph)
    out += _check_payload(graph)
    out += _check_guard_arity(graph)
    out += _check_schedules(graph)
    return out


# ---------------------------------------------------------------------------
# (a2) stage-scope conformance (obs/profile.py attribution; ISSUE 12)
# ---------------------------------------------------------------------------

def _scoped_nodes(graph: PlanGraph) -> List[Tuple[StageNode, str]]:
    """``(node, expected scope string)`` for every declared node whose
    ops the build emits under a stage scope. Exempt: input/output
    (structural), GSPMD (``p2p``) exchanges (the partitioner inserts the
    collective at the stage boundary — there is no explicit op region to
    wrap), and guard nodes under ``guards="off"`` (none declared)."""
    from ..obs import profile

    out: List[Tuple[StageNode, str]] = []
    for n in graph.nodes:
        if n.kind in ("input", "output"):
            continue
        if n.kind == "exchange":
            if n.rendering == "p2p":
                continue
            out.append((n, profile.scope_name(graph.family, n.id)))
        elif n.kind in ("local_fft", "guard"):
            out.append((n, profile.scope_name(graph.family, n.id)))
        elif n.encodes():
            out.append((n, profile.scope_name("wire", "encode")))
        elif n.decodes():
            out.append((n, profile.scope_name("wire", "decode")))
    return out


def check_graph_scopes(graph: PlanGraph,
                       compiled_txt: str) -> List[GraphViolation]:
    """Every declared node with an op region must leave its
    ``dfft/<family>/<node-id>`` stage scope in the compiled module's op
    metadata (``jax.named_scope`` — metadata ONLY: the metadata-stripped
    fingerprint pins prove a scope never adds ops; this check proves the
    converse, that no declared stage is missing its scope, so
    ``obs/profile.py`` attribution can never silently drop a stage).
    Skipped when scopes are disabled (``profile.disable_scopes()`` /
    ``$DFFT_NO_STAGE_SCOPES`` — the pins' comparison side)."""
    from ..obs import profile

    if not profile.scopes_enabled():
        return []
    out: List[GraphViolation] = []
    for node, scope in _scoped_nodes(graph):
        if scope not in compiled_txt:
            out.append(_viol(
                graph, "scope-conformance",
                f"declared node {node.id!r} left no stage scope "
                f"{scope!r} in the compiled module metadata — its "
                "device time would be unattributable"))
    return out


# ---------------------------------------------------------------------------
# (b) graph <-> contract and graph <-> trace conformance
# ---------------------------------------------------------------------------

def graph_decls(graph: PlanGraph) -> Tuple[contracts.ExchangeDecl, ...]:
    """The graph's exchange nodes as ``ExchangeDecl``s — the common
    currency of the contract registry."""
    return tuple(contracts.ExchangeDecl(
        label=x.label or x.id, payload_shape=x.payload_shape,
        axis_size=x.axis_size, rendering=x.rendering, chunks=x.chunks,
        subblocks=x.subblocks)
        for x in graph.exchanges())


def check_graph_contract(graph: PlanGraph,
                         contract: contracts.Contract
                         ) -> List[GraphViolation]:
    """The graph's exchanges must reconcile 1:1 with the family's
    ``_contract_exchanges`` declaration — two declarative surfaces, one
    truth."""
    def key(d: contracts.ExchangeDecl) -> Tuple[Any, ...]:
        return (d.rendering, tuple(d.payload_shape), d.axis_size,
                max(1, d.chunks), max(1, d.subblocks))

    out: List[GraphViolation] = []
    got = sorted(key(d) for d in graph_decls(graph))
    want = sorted(key(d) for d in contract.exchanges)
    if got != want:
        out.append(_viol(
            graph, "contract-conformance",
            f"graph exchanges {got} do not reconcile with the family's "
            f"contract declaration {want}"))
    return out


def _jaxpr_exchange_census(jaxpr: Any) -> Dict[str, int]:
    from . import jaxprlint

    counts = {"all_to_all": 0, "ppermute": 0}
    for eqn in jaxprlint.iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name in counts:
            counts[name] += 1
    return counts


def check_graph_trace(plan: Any, graph: PlanGraph,
                      direction: str = "forward", dims: int = 3,
                      census: Optional[Dict[str, int]] = None,
                      compiled_txt: Optional[str] = None,
                      staged: Optional[int] = None,
                      _staged_resolved: bool = False,
                      jaxpr: Optional[Any] = None
                      ) -> List[GraphViolation]:
    """Graph <-> trace conformance: the program the build function
    traces and compiles must implement the declared graph.

    * jaxpr side — the traced program must contain AT LEAST the declared
      explicit collectives (one ``all_to_all`` eqn per declared a2a
      piece, ``P-1`` ``ppermute`` eqns per declared ring; a ring
      declared where none is traced, or a phantom exchange the build
      never stages, fails here). GSPMD (``p2p``) exchanges stage no
      explicit primitive and impose no jaxpr minimum.
    * HLO side — a contract synthesized from the GRAPH's exchange nodes
      (``contracts.contract_from_decls``) must pass against the compiled
      census / forbidden ops / staged payload, exactly like the family
      contract.

    ``census``/``compiled_txt``/``staged``/``jaxpr`` let a caller that
    already compiled or traced the combo (``dfft-verify``) share the
    module instead of compiling/tracing twice (pass
    ``_staged_resolved=True`` when the staged total was already
    computed, even if it resolved to None).
    """
    from . import hloscan, jaxprlint

    out: List[GraphViolation] = []
    decls = graph_decls(graph)
    if jaxpr is None:
        jaxpr = jaxprlint.plan_jaxpr(plan, direction, dims)
    traced = _jaxpr_exchange_census(jaxpr)
    want_a2a = sum(max(1, d.chunks) for d in decls
                   if d.rendering in ("a2a", "streams", "a2a_pipe"))
    want_pp = sum(max(0, d.axis_size - 1) * max(1, d.subblocks)
                  for d in decls
                  if d.rendering in contracts._RING_RENDERINGS)
    if traced["all_to_all"] < want_a2a:
        out.append(_viol(
            graph, "trace-conformance",
            f"graph declares {want_a2a} explicit all-to-all piece(s) "
            f"but the build traced {traced['all_to_all']} — a declared "
            "exchange the build does not implement (phantom exchange)"))
    if traced["ppermute"] < want_pp:
        out.append(_viol(
            graph, "trace-conformance",
            f"graph declares ring exchange(s) needing >= {want_pp} "
            f"ppermute step(s) but the build traced "
            f"{traced['ppermute']}"))
    if want_pp == 0 and traced["ppermute"] > 0:
        out.append(_viol(
            graph, "trace-conformance",
            f"build traced {traced['ppermute']} ppermute step(s) but "
            "the graph declares no ring exchange"))
    synth = contracts.contract_from_decls(
        graph.family, direction, graph.wire, graph.guards,
        graph.complex_dtype, decls)
    if compiled_txt is None:
        compiled_txt = hloscan.compiled_text(plan, direction, dims)
    if census is None:
        census = hloscan.collective_census(compiled_txt)
    if staged is None and not _staged_resolved \
            and any(r.kind == "payload" for r in synth.rules):
        staged = hloscan.staged_exchange_total(plan, direction, dims)
    for v in contracts.check_contract(synth, census, compiled_txt, staged):
        out.append(_viol(graph, "trace-conformance", str(v)))
    return out


def verify_graph(plan: Any, direction: str = "forward",
                 dims: int = 3) -> List[GraphViolation]:
    """The one-call graph pass over a live plan: resolve the declared
    graph, run well-formedness, contract conformance, trace conformance
    and stage-scope conformance. The per-combo entry ``dfft-verify``
    inlines (sharing its compile)."""
    from . import hloscan

    graph = graph_for(plan, direction, dims)
    out = check_graph(graph)
    out += check_graph_contract(
        graph, contracts.contract_for(plan, direction, dims))
    txt = hloscan.compiled_text(plan, direction, dims)
    out += check_graph_trace(plan, graph, direction, dims,
                             compiled_txt=txt)
    out += check_graph_scopes(graph, txt)
    return out


# ---------------------------------------------------------------------------
# presentation (shared by dfft-verify and dfft-explain)
# ---------------------------------------------------------------------------

def _fmt_bytes(n: int) -> str:
    if n >= 1 << 30:
        return f"{n / (1 << 30):.2f} GiB"
    if n >= 1 << 20:
        return f"{n / (1 << 20):.2f} MiB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.2f} KiB"
    return f"{n} B"


def _node_brief(n: StageNode) -> str:
    if n.kind == "local_fft":
        axes = ",".join("xyz"[a] if 0 <= a <= 2 else str(a)
                        for a in n.axes)
        return f"local_fft[{axes}]"
    if n.kind == "exchange":
        extra = f" depth={n.schedule_depth}" if n.schedule_depth else ""
        k = f" k={n.chunks}" if n.chunks > 1 else ""
        s = f" sub={n.subblocks}" if n.subblocks > 1 else ""
        return f"exchange[{n.rendering} P={n.axis_size}{k}{s}{extra}]"
    if n.kind == "fused_kernel":
        return f"fused[{'+'.join(n.fuses)}]"
    return n.kind


def format_graph(graph: PlanGraph) -> List[str]:
    """Human-readable graph lines — the ``graph:`` section of
    ``dfft-explain``, printed from the SAME registry the verifier
    checks so explain cannot disagree with it."""
    order = {n.id: i for i, n in enumerate(graph.nodes)}
    chain = " -> ".join(_node_brief(n) for n in
                        sorted(graph.nodes, key=lambda n: order[n.id]))
    lines = [f"  {graph.name} ({len(graph.nodes)} nodes / "
             f"{len(graph.edges)} edges, wire {graph.wire}, guards "
             f"{graph.guards}): {chain}"]
    for x in graph.exchanges():
        ins = graph.in_edges(x.id)
        wb = ins[0].wire_bytes if ins else 0
        sched = ""
        if x.schedule_depth:
            sched = f" (schedule depth {x.schedule_depth}"
            if x.subblocks > 1:
                sched += f", {x.subblocks} sub-blocks"
            sched += ")"
        lines.append(
            f"  {x.label or x.id}: payload {x.payload_shape} "
            f"{graph.complex_dtype} -> {_fmt_bytes(wb)} on the wire"
            + sched)
    return lines
