"""AST-level repo-invariant lints: properties of the SOURCE that no
runtime test can pin without racing the exact failure.

Three rules, each a real invariant this codebase already relies on:

* ``traced-host-io`` — functions that get traced (passed to ``jax.jit``
  / ``shard_map`` / ``lax.fori_loop`` / ``lax.scan`` / ``lax.map`` /
  ``grad`` / ``vmap``, or called by one in the same module) must not
  read ``os.environ`` or do host I/O (``open``, ``input``,
  ``subprocess``): a traced env read executes once at trace time and
  silently freezes into the compiled program — the exact bug class
  ``Config.resolved_guards`` documents ("resolved once at plan
  construction, so a mid-run env change cannot split a plan's
  directions").
* ``host-only-jnp`` — host-only modules (``utils/wisdom.py``,
  ``obs/tracing.py``) must not import ``jax.numpy``: wisdom is loaded
  standalone by the flock-contract subprocess tests and tracing must
  stay importable before any backend exists; a ``jnp`` import would
  initialize a backend as a side effect of reading a JSON file.
* ``wisdom-flock`` — every ``os.replace`` (the atomic-write idiom) in
  a lock-disciplined module must be reachable only under the
  ``_advisory_lock`` flock helper: a write outside the lock re-opens
  the read-merge-replace race the helper exists to close. This is a
  static race detector; it covers the wisdom store
  (``utils/wisdom.py``, the rule's namesake) AND the post-PR-6
  packages that persist state from long-lived processes — ``serve/``
  (plan-cache / health snapshots) and ``solvers/`` (checkpoint state,
  ROADMAP item 5c) — which shipped after the lint and were previously
  outside its scope.

The ``traced-host-io`` rule scans EVERY module ``lint_repo`` walks
(``scanned_files`` is the canonical list — ``serve/`` and ``solvers/``
included; the completeness test pins them in the walk).

An inline ``# srclint: allow(<rule>)`` comment on the offending line
suppresses a finding — visible, greppable, reviewed.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

# Call names whose function-valued arguments become traced code.
TRACING_ENTRY_POINTS = frozenset({
    "jit", "shard_map", "fori_loop", "scan", "map", "while_loop", "cond",
    "grad", "value_and_grad", "vmap", "pmap", "checkpoint", "remat",
    "custom_vjp", "custom_jvp", "make_jaxpr",
})

# Host-only modules (repo-relative): importing jax.numpy here couples a
# pure-host code path to backend initialization.
HOST_ONLY_MODULES = (
    os.path.join("utils", "wisdom.py"),
    os.path.join("obs", "tracing.py"),
)

_ALLOW_MARK = "# srclint: allow("


@dataclasses.dataclass(frozen=True)
class SrcFinding:
    """One source-lint diagnostic (``rule`` is the invariant name the
    mutation tests assert on)."""

    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"[srclint/{self.rule}] {self.path}:{self.line}: " \
               f"{self.message}"


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an expression (``os.environ.get`` ->
    "os.environ.get")."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _allowed(src_lines: List[str], line: int, rule: str) -> bool:
    if 1 <= line <= len(src_lines):
        txt = src_lines[line - 1]
        if _ALLOW_MARK + rule + ")" in txt:
            return True
    return False


# ---------------------------------------------------------------------------
# traced-host-io
# ---------------------------------------------------------------------------

class _FnIndex(ast.NodeVisitor):
    """Function defs by name per lexical scope + the call edges and
    traced roots of one module."""

    def __init__(self) -> None:
        self.defs: Dict[str, List[ast.FunctionDef]] = {}
        self.traced_lambdas: List[ast.Lambda] = []
        self._stack: List[ast.FunctionDef] = []
        # (caller def or None, callee simple name) edges
        self.calls: List[Tuple[Optional[ast.FunctionDef], str]] = []

    def _visit_fn(self, node: Any) -> None:
        self.defs.setdefault(node.name, []).append(node)
        # Decorator roots: @jax.jit / @jit / @partial(jax.jit, ...) — any
        # tracing entry point named anywhere in the decorator expression
        # makes the decorated def traced (the most common JAX idiom).
        for dec in node.decorator_list:
            names = {sub.attr for sub in ast.walk(dec)
                     if isinstance(sub, ast.Attribute)}
            names |= {sub.id for sub in ast.walk(dec)
                      if isinstance(sub, ast.Name)}
            if names & TRACING_ENTRY_POINTS:
                self.calls.append((None, "__root__:" + node.name))
                break
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Call(self, node: ast.Call) -> None:
        name = _call_name(node)
        caller = self._stack[-1] if self._stack else None
        if name in TRACING_ENTRY_POINTS:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    self.calls.append((caller, "__root__:" + arg.id))
                elif isinstance(arg, ast.Attribute):
                    # jax.jit(self._body): resolve by the attribute's
                    # terminal name against same-module defs.
                    self.calls.append((caller, "__root__:" + arg.attr))
                elif isinstance(arg, ast.Lambda):
                    # Resolved after the walk, when self.defs is complete.
                    self.traced_lambdas.append(arg)
        else:
            self.calls.append((caller, name))
        self.generic_visit(node)


_HOST_IO_CALLS = frozenset({"open", "input"})
_HOST_IO_PREFIXES = ("subprocess.", "os.system", "os.popen", "os.getenv",
                     "os.putenv", "os.environ")


def _traced_fns(tree: ast.Module) -> Set[ast.FunctionDef]:
    """The module's traced-function set: defs passed to a tracing entry
    point, closed over same-module calls (a traced fn's callees are
    traced too)."""
    idx = _FnIndex()
    idx.visit(tree)
    traced: Set[ast.FunctionDef] = set()
    # Traced lambdas: the functions they call (by simple name) are traced
    # — resolved here, after the walk, so later defs resolve too.
    for lam in idx.traced_lambdas:
        for sub in ast.walk(lam):
            if isinstance(sub, ast.Call):
                for d in idx.defs.get(_call_name(sub), []):
                    traced.add(d)
    for caller, callee in idx.calls:
        if callee.startswith("__root__:"):
            for d in idx.defs.get(callee[len("__root__:"):], []):
                traced.add(d)
    # Propagate: callees of traced fns (by simple name, same module).
    changed = True
    while changed:
        changed = False
        for caller, callee in idx.calls:
            if caller in traced and not callee.startswith("__root__:"):
                for d in idx.defs.get(callee, []):
                    if d not in traced:
                        traced.add(d)
                        changed = True
            # A def nested inside a traced def is traced when called
            # anywhere (the builder-closure idiom: the outer fn returns
            # the traced body).
        return_closures = set()
        for fn in traced:
            for sub in ast.walk(fn):
                if isinstance(sub, ast.FunctionDef) and sub not in traced:
                    return_closures.add(sub)
        if return_closures:
            traced |= return_closures
            changed = True
    return traced


def _lint_traced_host_io(path: str, tree: ast.Module,
                         src_lines: List[str]) -> List[SrcFinding]:
    out: List[SrcFinding] = []
    for fn in _traced_fns(tree):
        for node in ast.walk(fn):
            msg = None
            if isinstance(node, ast.Call):
                name = _call_name(node)
                dotted = _dotted(node.func)
                if name in _HOST_IO_CALLS:
                    msg = f"host I/O call {name}() inside traced " \
                          f"function {fn.name!r}"
                elif any(dotted.startswith(p) for p in _HOST_IO_PREFIXES):
                    msg = f"{dotted}() inside traced function {fn.name!r}"
            elif isinstance(node, (ast.Attribute, ast.Subscript)):
                dotted = _dotted(node if isinstance(node, ast.Attribute)
                                 else node.value)
                if dotted.startswith("os.environ"):
                    msg = f"os.environ read inside traced function " \
                          f"{fn.name!r} (freezes into the compiled " \
                          "program at trace time)"
            if msg and not _allowed(src_lines, node.lineno,
                                    "traced-host-io"):
                out.append(SrcFinding("traced-host-io", path, node.lineno,
                                      msg))
    # De-duplicate per line (the Attribute inside a flagged Call would
    # otherwise report the same read twice).
    seen: Set[int] = set()
    uniq = []
    for f in sorted(out, key=lambda f: f.line):
        if f.line not in seen:
            seen.add(f.line)
            uniq.append(f)
    return uniq


# ---------------------------------------------------------------------------
# host-only-jnp
# ---------------------------------------------------------------------------

def _lint_host_only_jnp(path: str, tree: ast.Module,
                        src_lines: List[str]) -> List[SrcFinding]:
    if not any(path.endswith(suffix) for suffix in HOST_ONLY_MODULES):
        return []
    out: List[SrcFinding] = []
    for node in ast.walk(tree):
        bad = None
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.startswith("jax.numpy"):
                    bad = alias.name
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.startswith("jax.numpy"):
                bad = mod
            elif mod == "jax" and any(a.name == "numpy"
                                      for a in node.names):
                bad = "jax.numpy"
        if bad and not _allowed(src_lines, node.lineno, "host-only-jnp"):
            out.append(SrcFinding(
                "host-only-jnp", path, node.lineno,
                f"host-only module imports {bad} (couples a pure-host "
                "path to backend initialization)"))
    return out


# ---------------------------------------------------------------------------
# wisdom-flock
# ---------------------------------------------------------------------------

LOCK_HELPER = "_advisory_lock"

# Modules whose os.replace writes must stay under the flock helper: the
# wisdom store (the rule's origin), plus every module of the serve/,
# solvers/ and persist/ packages — long-lived processes persisting
# shared state (plan-cache spills, health snapshots, solver checkpoint
# generations) re-open the exact read-merge-replace race the helper
# closes.
LOCKED_REPLACE_MODULES = (os.path.join("utils", "wisdom.py"),)
LOCKED_REPLACE_PACKAGES = ("serve", "solvers", "persist")


def _replace_lock_applies(path: str) -> bool:
    if any(path.endswith(m) for m in LOCKED_REPLACE_MODULES):
        return True
    # Match package names against components INSIDE the package tree
    # only — a checkout path that happens to contain a directory named
    # "serve" must not widen the rule to the whole repo. Paths under
    # package_root() are matched relative to it; relative paths (the
    # synthetic-source form the tests use) are matched as given; other
    # absolute paths are out of scope.
    root = package_root()
    abspath = os.path.abspath(path)
    if abspath.startswith(root + os.sep):
        rel = os.path.relpath(abspath, root)
    elif not os.path.isabs(path):
        rel = path
    else:
        return False
    parts = rel.replace("\\", "/").split("/")
    return any(pkg in parts[:-1] for pkg in LOCKED_REPLACE_PACKAGES)


def _locked_withs(tree: ast.Module) -> List[ast.With]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.With):
            for item in node.items:
                ctx = item.context_expr
                if isinstance(ctx, ast.Call) and \
                        _call_name(ctx) == LOCK_HELPER:
                    out.append(node)
    return out


def _lint_wisdom_flock(path: str, tree: ast.Module,
                       src_lines: List[str]) -> List[SrcFinding]:
    """Every ``os.replace`` (the atomic-write idiom) in a
    lock-disciplined module (wisdom store, serve/, solvers/) must sit
    inside a ``with _advisory_lock(...)`` block — lexically, or in a
    function whose every same-module call site does."""
    if not _replace_lock_applies(path):
        return []
    locked = _locked_withs(tree)
    locked_nodes: Set[ast.AST] = set()
    for w in locked:
        locked_nodes.update(ast.walk(w))

    # Map replace calls to their enclosing function defs.
    fns: Dict[str, ast.FunctionDef] = {}
    parents: Dict[ast.AST, Optional[ast.FunctionDef]] = {}

    def index(node: ast.AST, fn: Optional[ast.FunctionDef]) -> None:
        for child in ast.iter_child_nodes(node):
            here = child if isinstance(child, ast.FunctionDef) else fn
            if isinstance(child, ast.FunctionDef):
                fns[child.name] = child
            parents[child] = fn
            index(child, here)

    index(tree, None)

    def enclosing_fn(node: ast.AST) -> Optional[ast.FunctionDef]:
        return parents.get(node)

    replaces = [n for n in ast.walk(tree)
                if isinstance(n, ast.Call)
                and _dotted(n.func) == "os.replace"]
    out: List[SrcFinding] = []
    for call in replaces:
        if call in locked_nodes:
            continue
        fn = enclosing_fn(call)
        if fn is not None:
            # One indirection level: the writer helper is fine when every
            # same-module call of it happens under the lock.
            sites = [c for c in ast.walk(tree)
                     if isinstance(c, ast.Call)
                     and _call_name(c) in (fn.name,)
                     and c is not call]
            if sites and all(s in locked_nodes for s in sites):
                continue
        if _allowed(src_lines, call.lineno, "wisdom-flock"):
            continue
        out.append(SrcFinding(
            "wisdom-flock", path, call.lineno,
            "atomic store write (os.replace) reachable outside the "
            f"{LOCK_HELPER} flock helper — re-opens the "
            "read-merge-replace race"))
    return out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def lint_source(src: str, path: str = "<string>") -> List[SrcFinding]:
    """All source lints over one module's text (the harness the mutation
    tests feed synthetic sources through)."""
    tree = ast.parse(src, filename=path)
    lines = src.splitlines()
    out = _lint_traced_host_io(path, tree, lines)
    out += _lint_host_only_jnp(path, tree, lines)
    out += _lint_wisdom_flock(path, tree, lines)
    return out


def lint_file(path: str) -> List[SrcFinding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), path)


def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def scanned_files(root: Optional[str] = None,
                  skip: Iterable[str] = ()) -> List[str]:
    """Every module ``lint_repo`` walks — the canonical scope of the
    repo lints (``serve/`` and ``solvers/`` included; the completeness
    test pins that, so a new package cannot silently fall outside the
    lint gate)."""
    root = root or package_root()
    skip = set(skip)
    out: List[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            if os.path.relpath(path, root) in skip:
                continue
            out.append(path)
    return out


def lint_repo(root: Optional[str] = None,
              skip: Iterable[str] = ()) -> List[SrcFinding]:
    """Lint every module under ``distributedfft_tpu/`` (or ``root``)."""
    out: List[SrcFinding] = []
    for path in scanned_files(root, skip):
        try:
            out.extend(lint_file(path))
        except SyntaxError as e:
            out.append(SrcFinding("parse", path, e.lineno or 0,
                                  f"syntax error: {e.msg}"))
    return out
