"""Static hazard checker for the revolving-buffer ring schedules.

``SendMethod.RING_OVERLAP`` (``parallel/transpose._ring_transpose_impl``)
pipelines the ``P-1``-step ppermute ring with revolving receive buffers:
step ``t+1``'s permute is issued before block ``t``'s per-block compute,
so one wire transfer is in flight under every block's FFT. That schedule
is correct only while the buffer discipline holds — a block must never
be read before its transfer completes, and a transfer must never be
issued into a buffer whose previous block is still unconsumed. Today the
discipline is enforced implicitly by SSA dataflow at depth 2; ROADMAP
item 3 wants the depth (and block granularity) AUTOTUNED, which means
machine-generated schedules at depths 2/4/8 — exactly the schedules this
module proves safe statically, before anything traces.

A **schedule** is the ordered per-device op list of one ring exchange
(SPMD: every device runs the same program on its own rotation):

* ``issue(t, buf)`` — start step ``t``'s permute; the received block
  will land in revolving buffer ``buf``. The send operand (chunk ``t``
  of the resident array) is always ready, so the only hazard surface is
  the RECEIVE buffer.
* ``wait(t)``  — block until step ``t``'s transfer completes.
* ``compute(t)`` — consume block ``t`` from its buffer (the per-block
  decode + pipelined FFTs), freeing the buffer.

Hazard classes (``HAZARD_KINDS``; the mutation self-test proves each is
caught):

* ``read-before-arrive``  — ``compute(t)`` with no prior ``wait(t)``:
  the per-block FFT reads a buffer whose DMA has not completed;
* ``write-after-send``    — ``issue`` into a buffer whose previous
  block is issued but not yet computed: the incoming transfer overwrites
  (or races) data still needed;
* ``buffer-overflow``     — a buffer index outside the declared depth;
* ``lost-block``          — a step never issued / waited / computed (a
  hole in the exchange: the assembled output would be missing a peer's
  block);
* ``malformed``           — duplicate or out-of-order ops of one step
  (``wait`` before ``issue``, double ``compute``, ...).

``revolving_schedule(p, depth)`` generates the depth-D generalization of
the shipped schedule: pre-issue ``depth-1`` steps, then inside the loop
issue step ``t+depth-1`` BEFORE computing block ``t`` — at ``depth=2``
this is op-for-op the order ``_ring_transpose_impl`` traces under
``overlap=True`` (issue ``t+1``'s permute, then arrive block ``t``), and
at ``depth=1`` it degenerates to the plain serial RING. ``describe``
joins the timeline with ``transpose.ring_schedule``'s byte accounting so
one call answers both "is it safe" and "what is in flight".
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

HAZARD_KINDS = ("read-before-arrive", "write-after-send",
                "buffer-overflow", "lost-block", "malformed")

_OPS = ("issue", "wait", "compute")


@dataclasses.dataclass(frozen=True)
class SchedOp:
    """One schedule event: ``op`` in {issue, wait, compute}, ``step`` the
    ring step (1..P-1; step 0 is the local block and never scheduled),
    ``buf`` the revolving receive-buffer index (issue only; -1 = n/a)."""

    op: str
    step: int
    buf: int = -1

    def __post_init__(self) -> None:
        if self.op not in _OPS:
            raise ValueError(f"op must be one of {_OPS}, got {self.op!r}")

    def __str__(self) -> str:
        if self.op == "issue":
            return f"issue(step {self.step} -> buf {self.buf})"
        return f"{self.op}(step {self.step})"


@dataclasses.dataclass(frozen=True)
class Hazard:
    """One detected hazard; ``kind`` is the class the mutation tests
    assert on."""

    kind: str
    step: int
    message: str

    def __str__(self) -> str:
        return f"[schedverify/{self.kind}] step {self.step}: {self.message}"


def revolving_schedule(p: int, depth: int = 2,
                       subblocks: int = 1) -> Tuple[SchedOp, ...]:
    """The depth-D revolving-buffer pipeline of a ``p``-rank ring:
    ``p-1`` steps, up to ``depth`` blocks outstanding, block ``t`` in
    buffer ``(t-1) % depth``. ``depth=2`` reproduces the shipped
    RING_OVERLAP issue order (step ``t+1``'s permute before block
    ``t``'s compute); ``depth=1`` is the plain serial RING; ``p <= 1``
    (single-peer degenerate) schedules nothing.

    ``subblocks`` > 1 models the block-granularity axis: each peer step
    becomes ``subblocks`` MICRO-steps (sub-block ``(m-1) % S`` of peer
    step ``(m-1) // S + 1`` — the exact linearization
    ``_ring_transpose_impl`` traces), each riding its own permute into
    its own revolving buffer, so the checker proves the sub-block
    schedule under the same buffer discipline. The effective depth caps
    at ``(p-1) * subblocks``."""
    if p < 1:
        raise ValueError(f"ring size must be >= 1, got {p}")
    if depth < 1:
        raise ValueError(f"buffer depth must be >= 1, got {depth}")
    if subblocks < 1:
        raise ValueError(f"subblocks must be >= 1, got {subblocks}")
    steps = (p - 1) * subblocks
    if steps == 0:
        return ()
    d = min(depth, steps)
    ops: List[SchedOp] = [SchedOp("issue", t, (t - 1) % d)
                          for t in range(1, d)]
    for t in range(1, steps + 1):
        nxt = t + d - 1
        if nxt <= steps:
            ops.append(SchedOp("issue", nxt, (nxt - 1) % d))
        ops.append(SchedOp("wait", t))
        ops.append(SchedOp("compute", t))
    return tuple(ops)


def check_schedule(ops: Any, p: int, depth: int,
                   subblocks: int = 1) -> List[Hazard]:
    """Simulate one device's timeline and report every hazard (empty =
    the schedule is provably safe under the revolving-buffer semantics).
    ``p`` is the ring size (micro-steps 1..(p-1)*subblocks must each be
    issued, waited and computed exactly once), ``depth`` the declared
    buffer count, ``subblocks`` the per-peer block split the schedule
    was generated for."""
    hazards: List[Hazard] = []
    issued: Dict[int, int] = {}    # step -> buffer
    arrived: set = set()
    computed: set = set()
    owner: Dict[int, int] = {}     # buffer -> occupying step
    for op in ops:
        t = op.step
        if op.op == "issue":
            if t in issued:
                hazards.append(Hazard("malformed", t,
                                      "step issued more than once"))
                continue
            if not 0 <= op.buf < depth:
                hazards.append(Hazard(
                    "buffer-overflow", t,
                    f"buffer {op.buf} outside the declared depth {depth}"))
            elif op.buf in owner:
                hazards.append(Hazard(
                    "write-after-send", t,
                    f"issue into buffer {op.buf} while block "
                    f"{owner[op.buf]} is still un-computed there — the "
                    "incoming transfer overwrites live data"))
            owner[op.buf] = t
            issued[t] = op.buf
        elif op.op == "wait":
            if t not in issued:
                hazards.append(Hazard("malformed", t,
                                      "wait before issue"))
            elif t in arrived:
                hazards.append(Hazard("malformed", t,
                                      "step waited more than once"))
            arrived.add(t)
        else:  # compute
            if t in computed:
                hazards.append(Hazard("malformed", t,
                                      "step computed more than once"))
                continue
            if t not in arrived:
                hazards.append(Hazard(
                    "read-before-arrive", t,
                    "compute consumes the buffer before the transfer "
                    "completed (no prior wait)"))
            computed.add(t)
            buf = issued.get(t)
            if buf is not None and owner.get(buf) == t:
                del owner[buf]
    for t in range(1, (p - 1) * max(1, subblocks) + 1):
        missing = [name for name, seen in
                   (("issue", t in issued), ("wait", t in arrived),
                    ("compute", t in computed)) if not seen]
        if missing:
            hazards.append(Hazard(
                "lost-block", t,
                f"step never {'/'.join(missing)}d — the assembled output "
                "would be missing this peer's block"))
    return hazards


def mutated_schedule(kind: str, p: int = 8, depth: int = 2,
                     subblocks: int = 1) -> Tuple[SchedOp, ...]:
    """A synthetic schedule carrying exactly one hazard of ``kind`` —
    the self-test input proving the checker catches that class (the
    schedule analog of ``dfft-verify --mutate``). ``subblocks`` > 1
    mutates the sub-block micro-step schedule, proving the checker's
    coverage extends to the block-granularity axis."""
    ops = list(revolving_schedule(p, depth, subblocks))
    if p < 3:
        raise ValueError("mutations need a ring of >= 3 ranks")
    last = (p - 1) * max(1, subblocks)
    if kind == "read-before-arrive":
        # Swap one wait past its compute: the FFT reads the buffer while
        # the DMA is still in flight.
        i = next(i for i, o in enumerate(ops)
                 if o.op == "wait" and o.step == 2)
        ops[i], ops[i + 1] = ops[i + 1], ops[i]
    elif kind == "write-after-send":
        # Collapse every issue onto buffer 0 while still claiming the
        # declared depth: the second issue lands on a live block.
        ops = [SchedOp("issue", o.step, 0) if o.op == "issue" else o
               for o in ops]
    elif kind == "buffer-overflow":
        ops = [SchedOp("issue", o.step, depth) if o.op == "issue"
               and o.step == 1 else o for o in ops]
    elif kind == "lost-block":
        ops = [o for o in ops if not (o.op == "compute"
                                      and o.step == last)]
    elif kind == "malformed":
        ops.append(SchedOp("compute", 1))
    else:
        raise ValueError(f"unknown hazard kind {kind!r} "
                         f"(known: {HAZARD_KINDS})")
    return tuple(ops)


def describe(p: int, depth: int = 2,
             payload_shape: Optional[Tuple[int, ...]] = None,
             dtype: Any = None, wire: str = "native",
             subblocks: int = 1) -> Dict[str, Any]:
    """One ring exchange, fully described: the byte accounting from
    ``transpose.ring_schedule`` (at this ``depth``/``subblocks``), the
    generated revolving timeline, and its hazard verdict — what
    ``dfft-verify``'s schedule section and ``dfft-explain``'s graph
    section both print."""
    from ..parallel.transpose import ring_schedule

    timeline = revolving_schedule(p, depth, subblocks)
    hazards = check_schedule(timeline, p, depth, subblocks)
    # A ring of p ranks has only (p-1)*subblocks micro-steps, so at
    # most that many buffers can ever be live — revolving_schedule caps
    # there. Report the depth actually exercised so "depth 8 proven" is
    # never claimed on a mesh too small to use an 8th buffer.
    micro = max(0, p - 1) * max(1, subblocks)
    out: Dict[str, Any] = {
        "p": p, "depth": depth, "subblocks": max(1, subblocks),
        "effective_depth": min(depth, micro) if micro else 0,
        "timeline_ops": len(timeline),
        "hazards": [str(h) for h in hazards],
        "ok": not hazards,
    }
    if payload_shape is not None and dtype is not None:
        out["bytes"] = ring_schedule(payload_shape, dtype, wire, p,
                                     overlap=depth > 1, depth=depth,
                                     subblocks=subblocks)
    return out


def verify_shipped_depths(p: int,
                          depths: Tuple[int, ...] = (2, 4, 8),
                          subblock_splits: Tuple[int, ...] = (1, 2)
                          ) -> List[Dict[str, Any]]:
    """The acceptance sweep: the generalized RING_OVERLAP schedule must
    check clean at every autotune-candidate depth x sub-block split for
    this mesh size (plus the plain ring and the single-peer
    degenerate). One row per (depth, split) combo — a missing row in
    the dfft-verify output means a shipped schedule went unproven."""
    out = [describe(1, 1), describe(p, 1)]
    for d in depths:
        for s in subblock_splits:
            out.append(describe(p, d, subblocks=s))
    return out
