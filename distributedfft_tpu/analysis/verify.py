"""``dfft-verify`` — the static plan/HLO contract verifier.

Lowers and COMPILES (never executes) every rendering x direction x wire
x guard combo of the three plan families and checks each against its
declarative contract (``analysis/contracts.py``), plus:

* the PLAN-GRAPH pass per combo (``analysis/plangraph.py``): every
  family must declare a typed stage graph for every combo (a missing
  declaration is a combo FAILURE, never a skip), the graph must be
  well-formed (dataflow soundness, encode/decode pairing, dtype flow,
  payload conservation with the ring discount, guard arity, hazard-free
  ring schedules), must reconcile with the family's exchange contract,
  and must conform to the traced/compiled program;
* jaxpr dataflow lints per combo (``analysis/jaxprlint.py``);
* the schedule hazard sweep (``analysis/schedverify.py``): the
  generalized revolving-buffer RING_OVERLAP schedule must check clean
  at depths 2/4/8 x sub-block splits 1/2 for this mesh (plus the
  serial ring and the single-peer degenerate);
* zero-overhead-off fingerprint pins: obs enabled/disabled, fault spec
  set-then-unset, and ``guards="enforce"`` vs ``"check"`` compile to
  byte-identical (metadata-stripped) op graphs;
* AST repo-invariant lints (``analysis/srclint.py``) over the package
  source.

Prints a pass/fail table; ``--json`` writes the same as an artifact
(the CI ``verify`` job uploads it). Exit code 0 = everything verified.

Mutation self-test (the verifier verifying itself)::

    dfft-verify --mutate drop-decode     # breaks a contract on purpose;
    dfft-verify --mutate all             # all mutations, rc 0 iff every
                                         # one is CAUGHT with the right
                                         # diagnostic

Graph-defect mutations: ``drop-decode-node`` (a declared graph whose
decode stage was deleted), ``phantom-exchange`` (a graph declaring an
exchange the build never stages), ``hazard-schedule`` (a revolving
schedule with a write-after-send hazard), ``hazard-subblock`` (the
same hazard planted in a sub-block micro-step schedule).

Examples::

    dfft-verify --emulate-devices 8 --quick
    dfft-verify --emulate-devices 8 --families slab --wires bf16
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import tempfile
from typing import Any, Dict, Iterator, List, Optional, Sequence

MUTATIONS = ("drop-decode", "bogus-census", "flip-forbidden",
             "drop-decode-node", "phantom-exchange", "hazard-schedule",
             "hazard-subblock")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="dfft-verify", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--families", default="slab,pencil,batched",
                    help="comma list of plan families to verify")
    ap.add_argument("--renderings",
                    default="a2a,opt1,p2p,streams,ring,ring_ovl,"
                            "ring_ovl_d4,ring_ovl_d8,ring_sub2,a2a_pipe,"
                            "fused",
                    help="comma list of exchange renderings (ring_ovl = "
                         "SendMethod.RING_OVERLAP, the double-buffered "
                         "ring; ring_ovl_d4/d8 = the depth-4/8 revolving-"
                         "buffer variants; ring_sub2 = the overlapped ring "
                         "with each peer block split into 2 sub-blocks; "
                         "a2a_pipe = the software-pipelined all-to-all, "
                         "2 chunked collectives on the realigned layout; "
                         "fused = RING_OVERLAP + Config.fused_wire, "
                         "the fused Pallas wire kernels — active on the "
                         "bf16 wire cells, inert on native)")
    ap.add_argument("--wires", default="native,bf16",
                    help="comma list of wire dtypes")
    ap.add_argument("--guards", default="off,check",
                    help="comma list of guard modes (enforce compiles "
                         "identically to check — pinned by the enforce pin "
                         "instead of brute-forced)")
    ap.add_argument("--directions", default="forward,inverse")
    ap.add_argument("--sequences", default="ZY_Then_X",
                    help="comma list of slab sequences to sweep (default "
                         "ZY_Then_X; pass all three to cube the slab axis)")
    ap.add_argument("--quick", action="store_true",
                    help="native wire + guards off + forward only")
    ap.add_argument("--no-pins", action="store_true",
                    help="skip the zero-overhead-off fingerprint pins")
    ap.add_argument("--no-srclint", action="store_true",
                    help="skip the AST repo-invariant lints")
    ap.add_argument("--no-jaxprlint", action="store_true",
                    help="skip the per-combo jaxpr dataflow lints")
    ap.add_argument("--mutate", default=None,
                    choices=MUTATIONS + ("all",),
                    help="break a contract on purpose (verifier self-test)")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="write the full report as JSON")
    ap.add_argument("--emulate-devices", type=int, default=0,
                    help="force N virtual CPU devices (0 = real backend)")
    ap.add_argument("--obs", action="store_true",
                    help="print the obs metrics snapshot (hlo.* census "
                         "gauges) after the table")
    return ap


def _csv(s: str) -> List[str]:
    return [x.strip() for x in str(s).split(",") if x.strip()]


# ---------------------------------------------------------------------------
# the combo matrix
# ---------------------------------------------------------------------------

def _config(rendering: str, wire: str, guards: str) -> Any:
    import distributedfft_tpu as dfft
    from distributedfft_tpu import params as pm

    kw: Dict[str, Any] = {}
    if rendering == "a2a":
        kw.update(comm_method=pm.CommMethod.ALL2ALL)
    elif rendering == "opt1":
        kw.update(comm_method=pm.CommMethod.ALL2ALL, opt=1)
    elif rendering == "p2p":
        kw.update(comm_method=pm.CommMethod.PEER2PEER)
    elif rendering == "streams":
        kw.update(comm_method=pm.CommMethod.ALL2ALL,
                  send_method=pm.SendMethod.STREAMS, streams_chunks=3)
    elif rendering == "ring":
        kw.update(send_method=pm.SendMethod.RING)
    elif rendering == "ring_ovl":
        kw.update(send_method=pm.SendMethod.RING_OVERLAP)
    elif rendering == "ring_ovl_d4":
        kw.update(send_method=pm.SendMethod.RING_OVERLAP, overlap_depth=4)
    elif rendering == "ring_ovl_d8":
        kw.update(send_method=pm.SendMethod.RING_OVERLAP, overlap_depth=8)
    elif rendering == "ring_sub2":
        kw.update(send_method=pm.SendMethod.RING_OVERLAP,
                  overlap_subblocks=2)
    elif rendering == "a2a_pipe":
        kw.update(comm_method=pm.CommMethod.ALL2ALL, opt=1,
                  overlap_subblocks=2)
    elif rendering == "fused":
        kw.update(send_method=pm.SendMethod.RING_OVERLAP, fused_wire=True)
    else:
        raise ValueError(f"unknown rendering {rendering!r}")
    return dfft.Config(wire_dtype=wire, guards=guards, use_wisdom=False,
                       **kw)


def _make_plan(family: str, rendering: str, wire: str, guards: str,
               sequence: str, ndev: int) -> Any:
    """One combo's plan on the uneven-extent gate shape (padding on every
    decomposed axis stays covered). Returns (plan, dims)."""
    import distributedfft_tpu as dfft
    from distributedfft_tpu import params as pm

    cfg = _config(rendering, wire, guards)
    if family == "slab":
        return dfft.SlabFFTPlan(dfft.GlobalSize(20, 16, 16),
                                pm.SlabPartition(ndev), cfg,
                                sequence=sequence), 3
    if family == "pencil":
        p1 = 2 if ndev % 2 == 0 else 1
        return dfft.PencilFFTPlan(dfft.GlobalSize(20, 16, 16),
                                  pm.PencilPartition(p1, ndev // p1),
                                  cfg), 3
    if family == "batched":
        return dfft.Batched2DFFTPlan(ndev, 20, 16, pm.SlabPartition(ndev),
                                     cfg, shard="x"), 2
    raise ValueError(f"unknown family {family!r}")


def iter_combos(args: Any, ndev: int) -> Iterator[Dict[str, Any]]:
    families = _csv(args.families)
    renderings = _csv(args.renderings)
    wires = ["native"] if args.quick else _csv(args.wires)
    guards = ["off"] if args.quick else _csv(args.guards)
    directions = ["forward"] if args.quick else _csv(args.directions)
    sequences = _csv(args.sequences)
    for family in families:
        seqs = sequences if family == "slab" else [""]
        for rendering in renderings:
            for seq in seqs:
                for wire in wires:
                    for gm in guards:
                        for d in directions:
                            yield dict(family=family, rendering=rendering,
                                       sequence=seq, wire=wire, guards=gm,
                                       direction=d)
    # The no-exchange contracts: single-device reference path and the
    # embarrassingly-parallel batch sharding (one combo each — their
    # contract is "zero collectives", rendering-independent).
    if "slab" in families:
        yield dict(family="slab", rendering="none", sequence="ZY_Then_X",
                   wire="native", guards="off", direction="forward",
                   single=True)
        # The Bluestein combo (ISSUE 9): a PRIME r2c axis through the
        # chirp-z backend — the census / forbidden-op / payload pins must
        # hold on the chirp path too (the chirp's internal smooth FFTs
        # and host-constant kernel spectra stay strictly local: exactly
        # one all-to-all, native wire stays bf16-free, payload unchanged).
        yield dict(family="slab", rendering="bluestn", sequence="ZY_Then_X",
                   wire="native", guards="off", direction="forward",
                   bluestein=True)
    if "batched" in families:
        yield dict(family="batched", rendering="none", sequence="",
                   wire="native", guards="off", direction="forward",
                   batch_shard=True)


def run_combo(combo: Dict[str, Any], ndev: int,
              no_jaxprlint: bool = False) -> Dict[str, Any]:
    import distributedfft_tpu as dfft
    from distributedfft_tpu import params as pm

    from . import contracts, hloscan, jaxprlint, plangraph

    if combo.get("bluestein"):
        # Prime (non-smooth) z axis: 19 -> halved 10; x stays the uneven
        # gate extent so the padding machinery is covered alongside the
        # chirp path.
        plan, dims = dfft.SlabFFTPlan(
            dfft.GlobalSize(20, 16, 19), pm.SlabPartition(ndev),
            dfft.Config(fft_backend="bluestein", use_wisdom=False)), 3
    elif combo.get("single"):
        plan, dims = dfft.SlabFFTPlan(dfft.GlobalSize(16, 16, 16),
                                      pm.SlabPartition(1),
                                      dfft.Config(use_wisdom=False)), 3
    elif combo.get("batch_shard"):
        plan, dims = dfft.Batched2DFFTPlan(
            ndev, 20, 16, pm.SlabPartition(ndev),
            dfft.Config(use_wisdom=False), shard="batch"), 2
    else:
        plan, dims = _make_plan(combo["family"], combo["rendering"],
                                combo["wire"], combo["guards"],
                                combo["sequence"] or "ZY_Then_X", ndev)
    direction = combo["direction"]
    contract = contracts.contract_for(plan, direction, dims)
    # One compile per combo: census and contract check share the module
    # (verify_plan would compile a second time for the same answer).
    txt = hloscan.compiled_text(plan, direction, dims)
    census = hloscan.collective_census(txt)
    staged = None
    if any(r.kind == "payload" for r in contract.rules):
        staged = hloscan.staged_exchange_total(plan, direction, dims)
    violations = [str(v) for v in
                  contracts.check_contract(contract, census, txt, staged)]
    # The plan-graph pass: resolve the declared stage graph (a missing
    # declaration is a FAILURE — the completeness half of the pass),
    # check well-formedness + contract conformance, and reconcile it
    # against the traced jaxpr and the already-compiled module (shared —
    # one compile per combo stays true).
    graph_summary = None
    jaxpr = jaxprlint.plan_jaxpr(plan, direction, dims)
    try:
        graph = plangraph.graph_for(plan, direction, dims)
    except plangraph.MissingGraph as e:
        violations.append(f"[plangraph] no stage graph declared for "
                          f"this combo: {e}")
    else:
        graph_summary = dict(name=graph.name, nodes=len(graph.nodes),
                             edges=len(graph.edges),
                             exchanges=len(graph.exchanges()))
        violations += [str(v) for v in plangraph.check_graph(graph)]
        violations += [str(v) for v in
                       plangraph.check_graph_contract(graph, contract)]
        violations += [str(v) for v in plangraph.check_graph_trace(
            plan, graph, direction, dims, census=census,
            compiled_txt=txt, staged=staged, _staged_resolved=True,
            jaxpr=jaxpr)]
        # Stage-scope conformance (ISSUE 12): every declared node's
        # dfft/<family>/<node-id> scope must survive into the compiled
        # module's metadata (shared compile — same txt as above).
        violations += [str(v) for v in
                       plangraph.check_graph_scopes(graph, txt)]
    if not no_jaxprlint:
        violations += [str(f) for f in
                       jaxprlint.lint_plan(plan, direction, dims,
                                           jaxpr=jaxpr)]
    return dict(combo, contract=contract.name,
                census={k: v for k, v in census.items() if v},
                graph=graph_summary,
                violations=violations, ok=not violations)


# ---------------------------------------------------------------------------
# zero-overhead-off fingerprint pins
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def _env(key: str, value: Optional[str]) -> Iterator[None]:
    old = os.environ.get(key)
    try:
        if value is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = value
        yield
    finally:
        if old is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = old


def run_pins(ndev: int, families: Sequence[str]) -> List[Dict[str, Any]]:
    """The byte-identity pins, one per family x {obs, inject, enforce}:

    * obs    — compiled HLO with observability enabled == disabled;
    * inject — a build after setting THEN UNSETTING ``$DFFT_FAULT_SPEC``
      == the never-faulted build (and the faulted+guarded build differs,
      so the comparison is not vacuous);
    * enforce — ``guards="enforce"`` compiles the same op graph as
      ``"check"`` (the difference is host-side policy), which is why the
      matrix sweeps off/check only.
    """
    from distributedfft_tpu import obs
    from distributedfft_tpu.obs import tracing as _tracing
    from distributedfft_tpu.resilience import inject

    from . import hloscan

    out = []
    # Pin the obs-OFF side explicitly: $DFFT_OBS_DIR auto-enables tracing,
    # so without the disable() an obs-on-vs-obs-on comparison would pass
    # vacuously. The caller's obs state (env- or enable()-driven) is
    # restored afterwards.
    prev_state = (_tracing._FORCED_DIR, _tracing._FORCE_OFF)
    try:
        for family in families:
            def fp(wire: str = "native", guards: str = "off") -> str:
                plan, dims = _make_plan(family, "a2a", wire, guards,
                                        "ZY_Then_X", ndev)
                return hloscan.plan_fingerprint(plan, "forward", dims)

            obs.disable()
            base = fp()
            with tempfile.TemporaryDirectory() as td:
                obs.enable(td)
                try:
                    on = fp()
                finally:
                    obs.disable()
            out.append(dict(pin=f"{family}/obs-zero-overhead",
                            ok=on == base,
                            detail="compiled HLO obs-on == obs-off"))
            with _env(inject.ENV_VAR, "wire:bitflip"):
                faulted = fp(guards="check")
            after = fp()
            checked = fp(guards="check")
            # Non-vacuity isolates the INJECTION: faulted-guarded vs
            # unfaulted-guarded (same guard mode) — a dead injector would
            # make these equal even though both differ from guards-off.
            out.append(dict(
                pin=f"{family}/inject-zero-overhead",
                ok=(after == base) and (faulted != checked),
                detail="fault spec set-then-unset leaves the op graph "
                       "byte-identical (faulted guarded build differs "
                       "from the unfaulted guarded one)"))
            out.append(dict(
                pin=f"{family}/enforce-eq-check",
                ok=fp(guards="enforce") == checked,
                detail="guards=enforce compiles the op graph of "
                       "guards=check"))
            # Scope zero-overhead pin (ISSUE 12): the stage scopes the
            # families emit for obs/profile attribution are METADATA
            # ONLY — the metadata-stripped op graph with scopes on is
            # byte-identical to scopes off (a scope that introduces ops
            # is a combo failure, caught right here).
            from distributedfft_tpu.obs import profile as _profile
            with _profile.scopes_off():
                scopeless = fp()
            out.append(dict(
                pin=f"{family}/scope-zero-overhead",
                ok=scopeless == base,
                detail="named stage scopes on == off after metadata "
                       "strip (scopes never add ops)"))
    finally:
        _tracing._FORCED_DIR, _tracing._FORCE_OFF = prev_state
    return out


# ---------------------------------------------------------------------------
# mutations (the verifier verifying itself)
# ---------------------------------------------------------------------------

def run_mutation(name: str, ndev: int) -> Dict[str, Any]:
    """Break one contract on purpose and run the focused combo. The
    result's ``violations`` MUST be non-empty and name the right
    contract/lint — asserted by ``--mutate all`` and the test suite."""
    import dataclasses

    import distributedfft_tpu as dfft
    from distributedfft_tpu import params as pm
    from distributedfft_tpu.parallel import transpose as tr

    from . import contracts, jaxprlint

    if name == "drop-decode":
        # Drop the wire_decode: bitcast the bf16 planes away so NO convert
        # -from-bf16 remains (shapes/dtypes stay trace-valid; the payload
        # silently lost its mantissa restoration).
        import jax
        import jax.numpy as jnp

        real_decode = tr.wire_decode

        def broken_decode(y, dtype, wire=tr.WIRE_BF16):
            if wire == tr.WIRE_NATIVE:
                return real_decode(y, dtype, wire)
            import numpy as np
            f = (jnp.float64 if np.dtype(dtype) == np.complex128
                 else jnp.float32)
            z = jax.lax.bitcast_convert_type(y, jnp.int16).astype(f)
            return jax.lax.complex(z[0], z[1])

        tr.wire_decode = broken_decode
        try:
            plan = dfft.SlabFFTPlan(
                dfft.GlobalSize(16, 16, 16), pm.SlabPartition(ndev),
                dfft.Config(wire_dtype="bf16", use_wisdom=False))
            violations = [str(f) for f in
                          jaxprlint.lint_plan(plan, "forward")]
        finally:
            tr.wire_decode = real_decode
        return dict(mutation=name, violations=violations,
                    expect="unpaired wire_encode/wire_decode")
    if name in ("drop-decode-node", "phantom-exchange", "hazard-schedule",
                "hazard-subblock"):
        return _run_graph_mutation(name, ndev)
    plan, dims = _make_plan("slab", "opt1", "native", "off", "ZY_Then_X",
                            ndev)
    contract = contracts.contract_for(plan, "forward", dims)
    if name == "bogus-census":
        # Force an extra all-to-all via a bogus contract: expect 2 where
        # the realigned rendering stages exactly 1.
        rules = tuple(
            dataclasses.replace(r, value=2)
            if r.kind == "census" and r.op == "all_to_all" else r
            for r in contract.rules)
        expect = "census all_to_all == 2"
    elif name == "flip-forbidden":
        # Flip a forbidden-op rule: forbid the very collective the
        # rendering legitimately stages.
        rules = contract.rules + (contracts.Rule(
            "forbid", "all-to-all", why="mutated: forbidden on purpose"),)
        expect = "forbid 'all-to-all'"
    else:
        raise ValueError(f"unknown mutation {name!r}")
    mutated = dataclasses.replace(contract, rules=rules)
    violations = [str(v) for v in
                  contracts.verify_plan(plan, "forward", dims,
                                        contract=mutated)]
    return dict(mutation=name, violations=violations, expect=expect)


def _run_graph_mutation(name: str, ndev: int) -> Dict[str, Any]:
    """The plan-graph defect mutations: break a DECLARED graph (or a
    schedule) on purpose and prove the graph pass catches it."""
    import dataclasses

    import distributedfft_tpu as dfft
    from distributedfft_tpu import params as pm

    from . import plangraph, schedverify

    if name in ("hazard-schedule", "hazard-subblock"):
        # A revolving schedule that funnels every issue into buffer 0
        # while claiming depth 2: the second issue overwrites a live
        # block — the checker must name the hazard class.
        # ``hazard-subblock`` mutates the SUB-BLOCK micro-step schedule
        # (each peer block split in 2), proving the checker's coverage
        # extends to the block-granularity axis, not just whole blocks.
        sub = 2 if name == "hazard-subblock" else 1
        bad = schedverify.mutated_schedule("write-after-send",
                                           p=max(3, ndev), depth=2,
                                           subblocks=sub)
        hazards = schedverify.check_schedule(bad, max(3, ndev), 2,
                                             subblocks=sub)
        return dict(mutation=name,
                    violations=[str(h) for h in hazards],
                    expect="write-after-send")
    if name == "drop-decode-node":
        # Delete the decode stage from a declared compressed graph,
        # reconnecting the exchange straight to the next stage: the
        # well-formedness pass must flag the unpaired encode.
        plan = dfft.SlabFFTPlan(dfft.GlobalSize(16, 16, 16),
                                pm.SlabPartition(ndev),
                                dfft.Config(wire_dtype="bf16",
                                            use_wisdom=False))
        g = plangraph.graph_for(plan, "forward")
        dec = next((n for n in g.nodes if n.decodes()), None)
        if dec is None:
            # Single-device degenerate: no exchange, nothing to drop —
            # report NOT CAUGHT (like the other mutations at ndev=1)
            # instead of crashing.
            return dict(mutation=name, violations=[],
                        expect="unpaired encode/decode")
        (in_e,) = g.in_edges(dec.id)
        (out_e,) = g.out_edges(dec.id)
        nodes = tuple(n for n in g.nodes if n.id != dec.id)
        edges = tuple(e for e in g.edges if e not in (in_e, out_e)) \
            + (dataclasses.replace(in_e, dst=out_e.dst),)
        bad_graph = dataclasses.replace(g, nodes=nodes, edges=edges)
        return dict(mutation=name,
                    violations=[str(v) for v in
                                plangraph.check_graph(bad_graph)],
                    expect="unpaired encode/decode")
    # phantom-exchange: declare a second all-to-all exchange the build
    # function never stages; trace conformance must refuse it.
    plan, dims = _make_plan("slab", "opt1", "native", "off", "ZY_Then_X",
                            ndev)
    g = plangraph.graph_for(plan, "forward", dims)
    x = next((n for n in g.nodes if n.kind == "exchange"), None)
    if x is None:
        return dict(mutation=name, violations=[],
                    expect="phantom exchange")
    phantom = dataclasses.replace(x, id="exchange:phantom",
                                  label="phantom")
    (out_e,) = g.out_edges(x.id)
    edges = tuple(e for e in g.edges if e is not out_e) + (
        dataclasses.replace(out_e, dst="exchange:phantom"),
        dataclasses.replace(out_e, src="exchange:phantom"))
    bad_graph = dataclasses.replace(g, nodes=g.nodes + (phantom,),
                                    edges=edges)
    violations = [str(v) for v in plangraph.check_graph_trace(
        plan, bad_graph, "forward", dims)]
    return dict(mutation=name, violations=violations,
                expect="phantom exchange")


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def _combo_label(r: Dict[str, Any]) -> str:
    seq = r.get("sequence") or "-"
    return (f"{r['family']:<8} {r['rendering']:<8} {seq:<10} "
            f"{r['direction'][:3]:<4} {r['wire']:<7} {r['guards']:<6}")


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.emulate_devices:
        from distributedfft_tpu.parallel.mesh import force_cpu_devices
        force_cpu_devices(args.emulate_devices)

    import jax

    ndev = len(jax.devices())
    platform = jax.devices()[0].platform

    if args.mutate:
        names = MUTATIONS if args.mutate == "all" else (args.mutate,)
        all_caught = True
        for name in names:
            res = run_mutation(name, ndev)
            caught = any(res["expect"] in v for v in res["violations"])
            all_caught &= caught
            print(f"mutation {name}: "
                  + ("CAUGHT" if caught else "NOT CAUGHT (verifier bug!)"))
            for v in res["violations"]:
                print(f"  {v}")
        if args.mutate == "all":
            # Self-test semantics: success = every mutation caught.
            print("mutation self-test: "
                  + ("PASS" if all_caught else "FAIL"))
            return 0 if all_caught else 1
        # Single-mutation semantics: behave like a verify run of the
        # broken combo — violations mean a non-zero exit.
        return 1 if res["violations"] else 0

    report: Dict[str, Any] = {
        "devices": ndev, "platform": platform,
        "combos": [], "pins": [], "sched": [], "srclint": [],
    }
    failures = 0
    print(f"dfft-verify: {ndev} device(s) on {platform}")
    print(f"{'family':<8} {'render':<8} {'sequence':<10} {'dir':<4} "
          f"{'wire':<7} {'guards':<6} {'contract':<18} result")
    for combo in iter_combos(args, ndev):
        try:
            res = run_combo(combo, ndev, no_jaxprlint=args.no_jaxprlint)
        except Exception as e:  # noqa: BLE001 — a combo that cannot even
            # build/lower must land in the table, not abort the matrix.
            res = dict(combo, contract="-", census={},
                       violations=[f"build/lower failed: "
                                   f"{type(e).__name__}: {e}"], ok=False)
        report["combos"].append(res)
        status = "PASS" if res["ok"] else "FAIL"
        if not res["ok"]:
            failures += 1
        print(f"{_combo_label(res)} {res['contract']:<18} {status}")
        for v in res["violations"]:
            print(f"    {v}")

    if not args.no_pins:
        fams = [f for f in _csv(args.families)]
        for pin in run_pins(ndev, fams):
            report["pins"].append(pin)
            status = "PASS" if pin["ok"] else "FAIL"
            if not pin["ok"]:
                failures += 1
            print(f"pin  {pin['pin']:<38} {status}  ({pin['detail']})")

    # Schedule hazard sweep (analysis/schedverify.py): the generalized
    # revolving-buffer RING_OVERLAP schedule must prove clean at every
    # autotune-candidate depth for this mesh size, plus the serial ring
    # and the single-peer degenerate — the static precondition for
    # ROADMAP item 3's 2/4/8-way buffer-depth autotune.
    from . import schedverify
    for sched in schedverify.verify_shipped_depths(ndev):
        report["sched"].append(sched)
        status = "PASS" if sched["ok"] else "FAIL"
        if not sched["ok"]:
            failures += 1
        eff = sched.get("effective_depth", sched["depth"])
        cap = f" (effective {eff})" if eff != sched["depth"] else ""
        sub = sched.get("subblocks", 1)
        print(f"sched ring p={sched['p']:<3} depth={sched['depth']:<3}"
              f"sub={sub:<3}{cap} ({sched['timeline_ops']} op(s)) "
              f"{status}")
        for h in sched["hazards"]:
            print(f"    {h}")

    if not args.no_srclint:
        from . import srclint
        findings = srclint.lint_repo()
        for f in findings:
            report["srclint"].append(str(f))
            failures += 1
            print(f"srclint FAIL {f}")
        if not findings:
            print("srclint: clean "
                  "(traced-host-io, host-only-jnp, wisdom-flock)")

    n = len(report["combos"])
    npins = len(report["pins"])
    nsched = len(report["sched"])
    verdict = "PASS" if failures == 0 else f"FAIL ({failures} failure(s))"
    print(f"dfft-verify: {n} combo(s), {npins} pin(s), {nsched} "
          f"schedule(s), srclint "
          f"{'skipped' if args.no_srclint else 'ran'} -> {verdict}")
    report["failures"] = failures
    report["ok"] = failures == 0

    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        print(f"report written to {args.json}")
    if args.obs:
        from distributedfft_tpu import obs
        print("obs metrics: "
              + json.dumps(obs.metrics.snapshot(), sort_keys=True))
    return 0 if failures == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
