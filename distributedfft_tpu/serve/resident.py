"""Resident solver tenant: a long-running simulation living INSIDE a
serving process, with durable state (ROADMAP item 5c).

PR 9 made the solvers a product surface; this module makes one a
*workload* that lives for hours inside ``dfft-serve``: a background
thread stepping a pseudo-spectral Navier–Stokes run while the same
process serves FFT request traffic. What makes it production-grade is
the persistence contract wired through ``distributedfft_tpu/persist``:

* the resident **checkpoints** per :class:`~..persist.CheckpointPolicy`
  (every-N-steps / every-T-seconds) into a two-generation
  :class:`~..persist.CheckpointStore`;
* a **graceful drain** (``Server.close(drain=True)`` — the SIGTERM and
  fleet scale-down path) writes a final generation (``drain`` reason)
  when the policy says ``drain:on``;
* :meth:`ResidentSolver.build` **restores before ready**: a replacement
  fleet worker (``serve/fleet.py`` passes the resident spec to the slot
  that hosts it) loads the newest valid generation — falling back one
  generation on corruption — and continues the simulation from step k
  instead of restarting at 0; the ``worker:crash`` chaos drill pins
  ``restored_from > 0`` and the ``persist.checkpoint →
  fleet.worker_death → persist.restore → fleet.worker_join`` event
  chain.

Bit-exactness: the loop applies ONE jitted step function repeatedly
(never a ``lax.scan`` whose length would change across a resume), and
restore re-places the spectral state into the plan's declared sharding —
so interrupted-and-resumed runs are bit-identical to uninterrupted ones
(``tests/test_persist.py`` + the CI ``resume`` scenario prove it on the
driver, which shares :func:`advance_steps`).

A fresh start (no checkpoint) is normal; an UNUSABLE store (every
generation corrupt) degrades to a fresh start with
``persist.restore_failures`` evidence — a resident must come up even
when its disk bitrotted — while a fingerprint MISMATCH propagates: the
operator pointed a differently-configured simulation at an existing
store, and silently discarding hours of state is worse than refusing.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from .. import obs
from .. import persist
from ..parallel import mesh


def advance_steps(step_fn: Callable[[Any], Any], state: Any,
                  steps: int) -> Any:
    """Apply one jitted step function ``steps`` times, blocking each
    step — the ONE stepping idiom the resident, the ``dfft-solve``
    driver and the bit-exact tests share, so an interrupted run and its
    resume execute literally the same program sequence."""
    import jax
    for _ in range(steps):
        state = jax.block_until_ready(step_fn(state))
    return state


def build_ns_solver(spec: Dict[str, Any]) -> Any:
    """Construct the resident's solver from a picklable spec dict
    (``kind``: ``ns2d`` | ``ns3d``, ``n``, ``batch``, ``viscosity``,
    ``partitions``, ``double``) — module-level so fleet worker
    subprocesses can rebuild it from the spawn spec."""
    from .. import params as pm
    from ..solvers import NavierStokes2D, NavierStokes3D
    kind = str(spec.get("kind", "ns2d"))
    n = int(spec.get("n", 32))
    p = int(spec.get("partitions", 1))
    cfg = pm.Config(double_prec=bool(spec.get("double", False)),
                    fft_backend=str(spec.get("fft_backend", "xla")))
    nu = float(spec.get("viscosity", 1e-2))
    if kind == "ns2d":
        from ..models.batched2d import Batched2DFFTPlan
        batch = int(spec.get("batch", 1))
        plan = Batched2DFFTPlan(batch, n, n, pm.SlabPartition(p), cfg,
                                shard=str(spec.get("shard", "batch")))
        return NavierStokes2D(plan, nu)
    if kind == "ns3d":
        from ..models.slab import SlabFFTPlan
        plan = SlabFFTPlan(pm.GlobalSize(n, n, n), pm.SlabPartition(p),
                           cfg)
        return NavierStokes3D(plan, nu)
    raise ValueError(f"unknown resident solver kind {kind!r} "
                     "(choose from ns2d, ns3d)")


def initial_state(solver: Any, spec: Dict[str, Any]) -> Any:
    """The fresh-start spectral state: Taylor–Green at the spec's grid,
    in the plan's input dtype."""
    from ..solvers import taylor_green_2d, taylor_green_3d
    n = int(spec.get("n", 32))
    dt = np.float64 if spec.get("double") else np.float32
    if str(spec.get("kind", "ns2d")) == "ns2d":
        w0 = taylor_green_2d(n, batch=int(spec.get("batch", 1)), dtype=dt)
    else:
        w0 = taylor_green_3d(n, dtype=dt)
    return solver.to_spectral(w0)


class ResidentSolver:
    """One resident simulation: a solver + spectral state + checkpoint
    store/policy, stepped by a daemon thread (see module docstring)."""

    def __init__(self, name: str, solver: Any, state: Any, dt: float,
                 store: Optional[persist.CheckpointStore],
                 policy: Optional[persist.CheckpointPolicy] = None, *,
                 step: int = 0, sim_time: float = 0.0,
                 rng: Optional[Dict[str, Any]] = None,
                 restored_from: Optional[int] = None,
                 step_interval_s: float = 0.0,
                 max_steps: Optional[int] = None):
        self.name = name
        self.solver = solver
        self.state = state
        self.dt = float(dt)
        self.store = store
        self.policy = policy or persist.CheckpointPolicy()
        self.step = int(step)
        self.sim_time = float(sim_time)
        self.rng = rng
        self.restored_from = restored_from
        self.step_interval_s = float(step_interval_s)
        self.max_steps = max_steps
        self.checkpoints = 0
        self._last_saved_step = int(step)
        self._last_saved_time = time.monotonic()
        self.error: Optional[str] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._step_jit = None  # built lazily on the stepping thread
        # describe() cache: (monotonic stamp, result). status() rides
        # the fleet heartbeat (4 Hz), and an on-disk registry scan per
        # ping would put checkpoint-dir I/O latency inside the very
        # reply the death detector times; checkpoint() invalidates.
        self._describe_at = 0.0
        self._describe_cache: Optional[Dict[str, Any]] = None

    # -- construction ------------------------------------------------------

    @classmethod
    def build(cls, spec: Dict[str, Any]) -> "ResidentSolver":
        """Build (and, when the store holds a checkpoint, RESTORE) a
        resident from a picklable spec dict — the fleet worker calls
        this BEFORE announcing ready, so a replacement rejoins with the
        simulation already at step k. Spec keys: the solver keys of
        :func:`build_ns_solver` plus ``name``, ``dt``, ``dir``
        (checkpoint directory; absent = no persistence), ``policy``
        (:class:`CheckpointPolicy` spec string), ``step_interval_ms``,
        ``max_steps``."""
        name = str(spec.get("name", "resident"))
        solver = build_ns_solver(spec)
        dt = float(spec.get("dt", 1e-3))
        policy = persist.CheckpointPolicy.parse(spec.get("policy"))
        store = (persist.CheckpointStore(str(spec["dir"]))
                 if spec.get("dir") else None)
        step = 0
        sim_time = 0.0
        rng = spec.get("rng")
        restored_from: Optional[int] = None
        state: Any = None
        if store is not None:
            fp = persist.plan_fingerprint(solver.plan)
            try:
                # allow_mesh_change (ISSUE 20): a replacement worker
                # that came back on FEWER devices rebuilds the solver at
                # the shrunken partition count and restores across the
                # rank-count fingerprint diff (persist.degraded_restore
                # evidence; allclose, not bit-exact) instead of
                # crash-looping against a checkpoint its mesh can no
                # longer match bit-for-bit.
                sim = store.load(expect_fingerprint=fp,
                                 allow_mesh_change=bool(
                                     spec.get("allow_mesh_change")))
            except persist.CheckpointMissing:
                pass  # fresh start — the normal first boot
            except persist.CheckpointUnusable as e:
                # Zero loadable generations: the resident still comes
                # up (fresh), with the failure on the record — metrics
                # and the flight-recorder dump were emitted by load().
                obs.notice(f"resident {name}: checkpoint store unusable "
                           f"({e}); starting fresh",
                           name="persist.fresh_after_failure")
            else:
                state = persist.restore(sim, solver)
                step = sim.step
                sim_time = sim.sim_time
                rng = sim.rng or rng
                restored_from = sim.step
                obs.notice(f"resident {name}: restored step {sim.step} "
                           f"(sim_time {sim.sim_time:g})",
                           name="persist.resident_restored", step=sim.step)
        if state is None:
            state = initial_state(solver, spec)
        return cls(name, solver, state, dt, store, policy, step=step,
                   sim_time=sim_time, rng=rng, restored_from=restored_from,
                   step_interval_s=float(spec.get("step_interval_ms",
                                                  0.0)) / 1e3,
                   max_steps=(int(spec["max_steps"])
                              if spec.get("max_steps") else None))

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Start the stepping thread (idempotent)."""
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"{self.name}-steps")
        obs.event("resident.start", resident=self.name, step=self.step,
                  restored_from=self.restored_from,
                  policy=str(self.policy))
        self._thread.start()

    def _loop(self) -> None:
        # The whole loop is guarded: a stepping thread that dies
        # SILENTLY (compile error, device OOM, backend failure) is the
        # exact quiet-data-loss mode this layer exists to remove —
        # checkpoints would stop landing while the server kept serving.
        # The failure lands in status()["error"], the obs log, a
        # metric, and a flight-recorder dump.
        try:
            import jax
            step_jit = jax.jit(self.solver.step_fn(self.dt))
            self._step_jit = step_jit
            while not self._stop.is_set():
                if (self.max_steps is not None
                        and self.step >= self.max_steps):
                    break
                # THE shared stepping idiom (advance_steps): the
                # production path must be textually the path the
                # bit-exact tests certify. DEVICE_LOCK: on a mesh
                # worker the serving thread executes volume plans on
                # THIS device set — unserialized collectives from two
                # threads deadlock XLA's in-process rendezvous.
                with mesh.DEVICE_LOCK:
                    state = advance_steps(step_jit, self.state, 1)
                with self._lock:
                    self.state = state
                    self.step += 1
                    self.sim_time += self.dt
                reason = self.policy.due(self.step, self._last_saved_step,
                                         self._last_saved_time,
                                         time.monotonic())
                if reason is not None and self.store is not None:
                    # A TRANSIENT write failure (ENOSPC, an NFS blip)
                    # must not kill the simulation — the loss is one
                    # checkpoint window, counted and noticed; the next
                    # due trigger retries. Only a STEPPING failure
                    # (outer except) halts the resident.
                    try:
                        self.checkpoint(reason)
                    except OSError as e:
                        obs.metrics.inc("persist.checkpoint_failures")
                        obs.notice(
                            f"resident {self.name}: checkpoint write "
                            f"failed at step {self.step} "
                            f"({type(e).__name__}: {e}); stepping on",
                            name="persist.checkpoint_failed",
                            step=self.step)
                if self.step_interval_s:
                    self._stop.wait(self.step_interval_s)
        except Exception as e:  # noqa: BLE001 — must never die silently
            with self._lock:
                self.error = f"{type(e).__name__}: {e}"[:300]
            obs.metrics.inc("persist.resident_errors")
            obs.notice(f"resident {self.name}: stepping thread died at "
                       f"step {self.step} ({self.error})",
                       name="resident.error", step=self.step)
            from ..obs import flightrec
            flightrec.dump(f"resident {self.name} stepping error: "
                           f"{self.error}")

    def checkpoint(self, reason: str) -> Optional[str]:
        """Capture + save one generation now; returns the path written
        (None without a store). The ``persist.checkpoint`` event carries
        ``reason`` (which policy trigger, or ``drain``/``manual``)."""
        if self.store is None:
            return None
        with self._lock:
            sim = persist.capture(self.solver, self.state, self.step,
                                  self.dt, sim_time=self.sim_time,
                                  rng=self.rng,
                                  meta={"resident": self.name,
                                        "reason": reason})
        path = self.store.save(sim)
        with self._lock:
            self._last_saved_step = sim.step
            self._last_saved_time = time.monotonic()
            self.checkpoints += 1
            self._describe_cache = None  # registry changed
        return path

    def stop(self, checkpoint: bool = True) -> None:
        """Stop stepping; ``checkpoint=True`` (the drain path) writes
        the final generation when the policy says ``drain:on``.
        Idempotent."""
        first = not self._stop.is_set()
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(30.0)
        if first:
            if checkpoint and self.policy.on_drain and self.store is not None:
                self.checkpoint("drain")
            obs.event("resident.stop", resident=self.name, step=self.step,
                      checkpoints=self.checkpoints)

    # -- observability -----------------------------------------------------

    @property
    def running(self) -> bool:
        """Cheap liveness (no store I/O) — what poll loops should read;
        ``status()`` scans the on-disk registry and belongs on health
        cadence, not in a 50 Hz wait loop."""
        return (self._thread is not None and self._thread.is_alive()
                and not self._stop.is_set())

    def status(self) -> Dict[str, Any]:
        """The resident block of serve ``health()`` / the fleet
        heartbeat: step/sim-time progress, restore provenance, and the
        store's generation registry (the same ``describe`` surface
        ``dfft-explain`` prints)."""
        with self._lock:
            out: Dict[str, Any] = {
                "name": self.name,
                "solver": type(self.solver).__name__,
                "step": self.step,
                "sim_time": round(self.sim_time, 9),
                "restored_from": self.restored_from,
                "checkpoints": self.checkpoints,
                "policy": str(self.policy),
                "error": self.error,
                "running": self.running,
            }
        if self.store is not None:
            # ONE registry scan serves the report and the age gauge
            # (describe computes the newest valid age), throttled to
            # one scan per 2 s so the heartbeat path stays off disk.
            now = time.monotonic()
            with self._lock:
                d = (self._describe_cache
                     if (self._describe_cache is not None
                         and now - self._describe_at < 2.0) else None)
            if d is None:
                # Header-only: this runs at heartbeat cadence inside
                # the worker loop, and a full-CRC pass over a multi-MB
                # state per pong would stall the reply the death
                # detector times. The restore-accurate full verdict is
                # dfft-explain's (describe(full=True), its default).
                d = self.store.describe(full=False)
                with self._lock:
                    self._describe_cache = d
                    self._describe_at = now
            latest = d["latest"]
            if latest and latest.get("age_s") is not None:
                obs.metrics.gauge("persist.last_checkpoint_age_s",
                                  latest["age_s"])
            out["store"] = {"directory": d["directory"],
                            "latest": latest,
                            "verdict": d["fingerprint_verdict"]}
        else:
            out["store"] = None
        return out
