"""Plan-key routing and tenant admission for the serving fleet.

Three pure, process-free pieces the fleet (``fleet.py``) composes — kept
free of subprocess/pipe machinery so every routing and fairness property
is unit-testable without spawning a worker:

* :class:`RendezvousRing` — highest-random-weight (rendezvous) hashing
  of plan keys onto worker names. The property the fleet's plan caches
  live on: membership changes move the MINIMUM of key space. When a
  worker leaves, only ITS keys move (every surviving worker's score for
  every key is unchanged, so no key changes owner between survivors);
  when a worker joins, only the keys the newcomer now wins move —
  1/N of key space in expectation. Both are pinned by
  ``tests/test_fleet.py``. A restarted worker reuses its NAME, so its
  key range — and the request shapes the fleet prewarms it with —
  come back to the same slot.
* :class:`TenantPolicy` — per-tenant weighted quotas over the fleet's
  admission capacity. A tenant's quota is its weight share of the
  capacity **among currently-active tenants** (a tenant alone may use
  the whole fleet; when others are active the shares contract), so one
  hot tenant degrades to *their* budget, never the fleet's p99. Over
  quota is a structured ``Overloaded(reason="tenant_quota")``.
* :class:`FairQueue` — per-tenant FIFO subqueues drained by stride
  scheduling (each tenant carries a ``pass`` value advancing by
  ``1/weight`` per served request; the lowest pass goes next), so an
  admitted backlog from one tenant cannot starve another tenant's
  queued requests at the same worker.
"""

from __future__ import annotations

import collections
import hashlib
import threading
from typing import Any, Deque, Dict, List, Optional, Tuple

from .server import Overloaded

DEFAULT_TENANT = "default"


def _score(key: str, member: str) -> int:
    """Deterministic 64-bit rendezvous score of (key, member) — stable
    across processes and Python releases (no ``hash()`` randomization)."""
    h = hashlib.blake2b(f"{key}\x00{member}".encode(), digest_size=8)
    return int.from_bytes(h.digest(), "big")


class RendezvousRing:
    """Highest-random-weight hashing of plan keys onto member names."""

    def __init__(self, members: Tuple[str, ...] = ()):
        self._lock = threading.Lock()
        self._members: List[str] = sorted(set(members))

    def members(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(self._members)

    def __len__(self) -> int:
        with self._lock:
            return len(self._members)

    def add(self, name: str) -> None:
        with self._lock:
            if name not in self._members:
                self._members.append(name)
                self._members.sort()

    def remove(self, name: str) -> None:
        with self._lock:
            if name in self._members:
                self._members.remove(name)

    def owner(self, key: str) -> Optional[str]:
        """The member owning ``key`` (None on an empty ring)."""
        with self._lock:
            if not self._members:
                return None
            return max(self._members, key=lambda m: _score(key, m))

    def ranked(self, key: str) -> Tuple[str, ...]:
        """Every member, best owner first (the reroute order: when the
        owner dies, the key's next home is ``ranked(key)[1]`` — already
        the second-highest score, so no recomputation disagrees)."""
        with self._lock:
            return tuple(sorted(self._members,
                                key=lambda m: _score(key, m),
                                reverse=True))


class TenantPolicy:
    """Weighted per-tenant admission quotas over a shared capacity.

    ``weights`` maps tenant name -> positive weight; unknown tenants get
    ``default_weight``. ``capacity`` is the fleet's total admission
    budget in requests (outstanding = admitted and not yet resolved).
    The quota of tenant *t* at admission time is::

        quota(t) = max(1, floor(capacity * w_t / W_active))

    where ``W_active`` sums the weights of tenants with outstanding > 0
    plus *t* itself — so a tenant alone may use the whole capacity, and
    shares contract only when there is actual contention. ``admit``
    either reserves one slot or raises the structured
    ``Overloaded(reason="tenant_quota")``; every admit must be paired
    with exactly one ``release`` when the request resolves."""

    def __init__(self, capacity: int,
                 weights: Optional[Dict[str, float]] = None,
                 default_weight: float = 1.0):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self.default_weight = float(default_weight)
        self.weights: Dict[str, float] = {}
        for t, w in (weights or {}).items():
            if float(w) <= 0:
                raise ValueError(f"tenant weight must be > 0, got {t}={w}")
            self.weights[str(t)] = float(w)
        self._lock = threading.Lock()
        self._outstanding: Dict[str, int] = {}

    def weight(self, tenant: str) -> float:
        return self.weights.get(tenant, self.default_weight)

    def outstanding(self, tenant: Optional[str] = None) -> int:
        with self._lock:
            if tenant is not None:
                return self._outstanding.get(tenant, 0)
            return sum(self._outstanding.values())

    def quota(self, tenant: str) -> int:
        """Current quota of ``tenant`` given who else is active."""
        with self._lock:
            return self._quota_locked(tenant)

    def _quota_locked(self, tenant: str) -> int:
        active = {t for t, n in self._outstanding.items() if n > 0}
        active.add(tenant)
        w_active = sum(self.weight(t) for t in active)
        share = self.capacity * self.weight(tenant) / w_active
        return max(1, int(share))

    def admit(self, tenant: str) -> int:
        """Reserve one outstanding slot for ``tenant``; returns its new
        outstanding count, or raises ``Overloaded("tenant_quota")``."""
        with self._lock:
            have = self._outstanding.get(tenant, 0)
            quota = self._quota_locked(tenant)
            if have >= quota:
                err = Overloaded("tenant_quota", have, 0.0, float(quota))
                err.tenant = tenant            # type: ignore[attr-defined]
                err.quota = quota              # type: ignore[attr-defined]
                raise err
            self._outstanding[tenant] = have + 1
            return have + 1

    def release(self, tenant: str) -> None:
        with self._lock:
            n = self._outstanding.get(tenant, 0)
            if n <= 1:
                self._outstanding.pop(tenant, None)
            else:
                self._outstanding[tenant] = n - 1

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Health-endpoint view: per active/configured tenant, its
        weight, outstanding count and current quota."""
        with self._lock:
            tenants = set(self._outstanding) | set(self.weights)
            return {t: {"weight": self.weight(t),
                        "outstanding": self._outstanding.get(t, 0),
                        "quota": self._quota_locked(t)}
                    for t in sorted(tenants)}


class FairQueue:
    """Per-tenant FIFO subqueues drained by stride scheduling.

    ``push`` appends to the tenant's subqueue; ``pop`` serves the
    non-empty tenant with the LOWEST pass value and advances that pass
    by ``1/weight`` — over time tenant *t* receives a ``w_t / W`` share
    of pops while backlogged, and an idle tenant's first request after
    a gap is served ahead of a backlogged tenant's queue (its pass is
    clamped up to the global floor, never left in the past to burst).
    Single-consumer semantics; thread-safe."""

    def __init__(self, policy: Optional[TenantPolicy] = None):
        self.policy = policy
        self._lock = threading.Lock()
        self._queues: Dict[str, Deque[Any]] = {}
        self._pass: Dict[str, float] = {}
        self._clock = 0.0

    def _weight(self, tenant: str) -> float:
        return self.policy.weight(tenant) if self.policy else 1.0

    def __len__(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    def push(self, tenant: str, item: Any) -> int:
        with self._lock:
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = collections.deque()
            if not q:
                # (Re)activation: start at the scheduler clock, not an
                # old pass — an idle tenant must neither burst from the
                # past nor pay for time it was not queued.
                self._pass[tenant] = max(self._pass.get(tenant, 0.0),
                                         self._clock)
            q.append(item)
            return len(q)

    def pop(self) -> Optional[Any]:
        with self._lock:
            candidates = [(self._pass[t], t)
                          for t, q in self._queues.items() if q]
            if not candidates:
                return None
            _, tenant = min(candidates)
            item = self._queues[tenant].popleft()
            self._clock = self._pass[tenant]
            self._pass[tenant] += 1.0 / self._weight(tenant)
            if not self._queues[tenant]:
                # Prune emptied tenants: an adversarial tenant-name
                # sweep must not grow the queue's dicts without bound
                # (the reactivation clamp makes a dropped pass
                # equivalent to the clock anyway).
                del self._queues[tenant]
                del self._pass[tenant]
            return item

    def drain(self) -> List[Any]:
        """Remove and return everything, fair order preserved."""
        out = []
        while True:
            item = self.pop()
            if item is None:
                return out
            out.append(item)

    def depths(self) -> Dict[str, int]:
        with self._lock:
            return {t: len(q) for t, q in self._queues.items() if q}
