"""LRU plan cache — compiled plans stay hot between requests.

Every CLI in this repo builds a plan per invocation; a serving process
amortizes that: the first request for a shape pays plan construction +
trace + compile, every later request reuses the SAME plan object (whose
``_fwd``/``_inv`` jitted callables are already compiled — a cache hit
performs ZERO recompiles, pinned by ``tests/test_serve.py`` via build
counts). Keys are built by :func:`cache_key` on top of
``wisdom.plan_key`` — the same platform/shape/dtype/mesh/decomposition
vocabulary the wisdom store uses, extended with the coalescing batch
bucket (plans are batch-static; requests coalesce into power-of-two
buckets so a traffic mix of 1..max_coalesce concurrent same-shape
requests compiles at most ``log2(max_coalesce)+1`` programs per shape).

Eviction is strict LRU over a bounded capacity (an unbounded cache is an
unbounded-memory serving process): ``get_or_build`` moves hits to the
back, inserts at the back, and drops the front when over capacity.
``serve.plan_cache.hits/misses/evictions`` count every outcome and the
``serve.plan_cache.size`` gauge tracks occupancy. ``invalidate_prefix``
drops every bucket of a failing request key — the circuit breaker calls
it on OPEN so the half-open probe rebuilds from scratch instead of
re-executing a poisoned compiled program."""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, Tuple

from .. import obs


VOLUME_DECOMPS = ("slab", "pencil")


def request_key(nx: int, ny: int, dtype_code: str, transform: str,
                shard: str) -> str:
    """The COALESCING key: requests agreeing on it may be stacked into one
    batched execution (and share one circuit breaker). Excludes the batch
    bucket (that is an execution detail) and the direction (forward and
    inverse share a plan)."""
    return f"fft2d/{nx}x{ny}/{dtype_code}/{transform}/{shard}"


def request_key3d(nx: int, ny: int, nz: int, dtype_code: str,
                  transform: str, decomp: str) -> str:
    """The 3D-volume request key (ISSUE 20). Same contract as the 2D
    family — one key per (shape, dtype, transform, decomposition) sharing
    one plan-cache slot and one circuit breaker — except volumes execute
    SINGLE-SHOT through the slab/pencil plan families, so there is no
    batch-bucket axis: the request key IS the cache key (no ``#b``
    suffix). ``decomp`` names the distributed decomposition the volume
    runs on (``slab`` | ``pencil``)."""
    if decomp not in VOLUME_DECOMPS:
        raise ValueError(f"decomp must be slab|pencil, got {decomp!r}")
    return f"fft3d/{nx}x{ny}x{nz}/{dtype_code}/{transform}/{decomp}"


def cache_key(base_key: str, bucket: int) -> str:
    """One plan-cache slot: the request key plus the batch bucket this
    plan was built for."""
    return f"{base_key}#b{bucket}"


def parse_request_key(key: str) -> Dict[str, Any]:
    """Invert :func:`request_key` / :func:`request_key3d` (any
    ``#b<bucket>`` suffix ignored). 2D keys parse to ``{"nx", "ny",
    "dtype", "transform", "shard"}``; 3D keys to ``{"nx", "ny", "nz",
    "dtype", "transform", "decomp"}``. The fleet uses this to turn the
    hot-key set it tracked for a dead worker back into the concrete
    shapes the REPLACEMENT must ``prewarm()`` before rejoining the ring
    — including a dead MESH worker's hot volume shapes, which the
    replacement rebuilds on whatever mesh it actually acquired. Raises
    ``ValueError`` on a malformed key."""
    base = key.split("#", 1)[0]
    parts = base.split("/")
    if len(parts) != 5 or parts[0] not in ("fft2d", "fft3d"):
        raise ValueError(f"not a serve request key: {key!r}")
    extents = parts[1].split("x")
    want = 2 if parts[0] == "fft2d" else 3
    if len(extents) != want or not all(e.isdigit() for e in extents):
        raise ValueError(f"malformed shape in request key: {key!r}")
    if parts[2] not in ("f32", "f64") or parts[3] not in ("r2c", "c2c"):
        raise ValueError(f"malformed dtype/transform in key: {key!r}")
    if parts[0] == "fft2d":
        return {"nx": int(extents[0]), "ny": int(extents[1]),
                "dtype": parts[2], "transform": parts[3],
                "shard": parts[4]}
    if parts[4] not in VOLUME_DECOMPS:
        raise ValueError(f"malformed decomp in request key: {key!r}")
    return {"nx": int(extents[0]), "ny": int(extents[1]),
            "nz": int(extents[2]), "dtype": parts[2],
            "transform": parts[3], "decomp": parts[4]}


class PlanCache:
    """Bounded LRU of live plan objects (thread-safe)."""

    def __init__(self, capacity: int = 8,
                 metrics_prefix: str = "serve.plan_cache"):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self.prefix = metrics_prefix
        self._lock = threading.Lock()
        self._slots: "OrderedDict[str, Any]" = OrderedDict()
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        self._builds = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._slots)

    def keys(self) -> Tuple[str, ...]:
        """LRU order, oldest first (the next eviction victim leads)."""
        with self._lock:
            return tuple(self._slots)

    def get_or_build(self, key: str,
                     builder: Callable[[], Any]) -> Tuple[Any, bool]:
        """``(plan, hit)``. The builder runs OUTSIDE the cache lock (plan
        construction traces and compiles — seconds, not microseconds; a
        concurrent same-key build is a duplicated compile, not a
        deadlock, and the second insert wins)."""
        with self._lock:
            plan = self._slots.get(key)
            if plan is not None:
                self._slots.move_to_end(key)
                self._hits += 1
                obs.metrics.inc(f"{self.prefix}.hits")
                return plan, True
            self._misses += 1
            obs.metrics.inc(f"{self.prefix}.misses")
        with obs.span("serve.plan_build", key=key):
            plan = builder()
        with self._lock:
            self._builds += 1
            self._slots[key] = plan
            self._slots.move_to_end(key)
            while len(self._slots) > self.capacity:
                victim, _ = self._slots.popitem(last=False)
                self._evictions += 1
                obs.metrics.inc(f"{self.prefix}.evictions")
                obs.event("serve.plan_evicted", key=victim)
            obs.metrics.gauge(f"{self.prefix}.size", len(self._slots))
        return plan, False

    def invalidate_prefix(self, base_key: str) -> int:
        """Drop every bucket of ``base_key`` (circuit OPEN: the next probe
        must rebuild — a fault baked into a compiled program cannot clear
        without a rebuild). Returns the number of slots dropped."""
        dropped = 0
        with self._lock:
            for key in [k for k in self._slots
                        if k == base_key
                        or k.startswith(base_key + "#")]:
                del self._slots[key]
                dropped += 1
            obs.metrics.gauge(f"{self.prefix}.size", len(self._slots))
        if dropped:
            obs.event("serve.plan_invalidated", key=base_key, slots=dropped)
        return dropped

    def snapshot(self) -> Dict[str, Any]:
        """Health-endpoint view (counts since construction)."""
        with self._lock:
            n = len(self._slots)
            total = self._hits + self._misses
            return {"size": n, "capacity": self.capacity,
                    "hits": self._hits, "misses": self._misses,
                    "evictions": self._evictions, "builds": self._builds,
                    "hit_rate": round(self._hits / total, 4) if total else None,
                    "keys": list(self._slots)}

    def clear(self) -> None:
        with self._lock:
            self._slots.clear()
            obs.metrics.gauge(f"{self.prefix}.size", 0)


def bucket_for(n: int, max_coalesce: int) -> int:
    """The batch bucket a batch of ``n`` requests executes under: ALWAYS
    a power of two (the cache-key vocabulary ``prewarm`` enumerates) that
    fits ``n``, capped at the power-of-two CEILING of ``max_coalesce`` —
    so a non-power-of-two ``--max-coalesce`` widens the top bucket with
    padding instead of minting un-prewarmed non-power-of-two slots."""
    if n < 1:
        raise ValueError("bucket_for needs n >= 1")
    cap = 1
    while cap < max(max_coalesce, 1):
        cap <<= 1
    b = 1
    while b < n:
        b <<= 1
    b = min(b, cap)
    while b < n:  # degenerate n > max_coalesce: grow back to fit
        b <<= 1
    return b


