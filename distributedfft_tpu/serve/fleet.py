"""Shared-nothing serving fleet: N worker processes behind a plan-key
router (ROADMAP item 2a/c/d — the single-process ``Server``'s promotion
to a crash-survivable pool).

One ``Server`` process is one failure domain: a crash, hang or hot
tenant takes down 100% of capacity. The :class:`Fleet` splits that
domain into N **subprocess workers** (``multiprocessing`` spawn — no
shared jax state, no fork-after-init hazards), each running the
existing hardened ``Server`` core, behind a router that:

* **routes on the plan key** (``plancache.request_key``) with rendezvous
  hashing (``router.RendezvousRing``), so each worker's plan cache and
  circuit state stay hot and membership changes move the minimum of key
  space — a worker death moves ONLY its keys, a join at most ~1/N;
* **detects worker death** three ways — K missed heartbeats (a hung
  worker), a broken/EOF pipe (a crashed worker), a reaped exit code —
  then reroutes the dead worker's key range, **resubmits its admitted
  in-flight requests** (idempotent by trace id: the same id rides the
  retry, and an FFT is pure so re-execution cannot double-apply;
  requests whose deadline passed answer ``DeadlineExceeded`` — nothing
  silently vanishes), and **restarts** a replacement that ``prewarm()``s
  the fleet's hot shapes BEFORE rejoining the ring;
* **admits per tenant** (``router.TenantPolicy`` weighted quotas +
  ``router.FairQueue`` stride-fair dispatch), so a saturating tenant
  degrades to *their* budget — structured
  ``Overloaded(reason="tenant_quota")`` — not the fleet's p99;
* **scales on the scrape surface**: :class:`ScaleController` reads the
  shed/queue-depth/EMA signals from the SAME Prometheus exposition
  ``GET /metrics`` serves (``obs.promexp.render`` — what an external
  autoscaler would see, not private state), emits an auditable
  ``fleet.scale_decision`` record (event + flight-recorder trigger +
  ``health()["scale_decisions"]``), and grows/drains workers through
  the same join/leave path the failure detector uses.

Worker protocol (pickled tuples over a duplex pipe)::

    parent -> worker   ("req", tid, {...})  ("ping", seq)
                       ("prewarm", [(nx, ny, dtype, transform) |
                                    (nx, ny, nz, dtype, transform,
                                     decomp), ...])
                       ("drain",)  ("stop",)
    worker -> parent   ("ready", pid, generation)  ("pong", seq, stats)
                       ("res", tid, "ok", array | "err", encoded)
                       ("prewarmed", n)  ("drained", stats)

Elastic volume serving (ISSUE 20): a worker spec carries a per-worker
``devices=N`` mesh size (``worker_devices=[8, 0, 0]`` sizes worker 0 to
an 8-device CPU-emulated mesh and leaves the rest at the fleet
default), and routing is CAPABILITY-AWARE — ``fft3d/*`` volume keys
rendezvous-hash over the mesh-capable workers only (a second
``RendezvousRing`` with the same minimum-movement stability), 2D keys
over everyone. Each worker's heartbeat carries its live device count
into the ``dfft_fleet_worker_devices{worker=...}`` gauge, ``health()``
reports ``degraded`` while any worker runs short of its spec'd size,
and the ``fleet.capacity`` gauge weights workers by acquired/spec'd
devices so the scale controller sees a 4-of-8-device worker as half a
worker.

Chaos hooks: ``$DFFT_FAULT_SPEC`` ``worker:crash[:K]`` /
``worker:hang[:MS]`` (``resilience/inject.py``) fault the victim
worker's FIRST incarnation from inside its message loop, driving the
broken-pipe and missed-beats detector paths respectively; the fleet
must complete the drive with zero lost requests (CI's fleet chaos
scenario and ``tests/test_fleet.py`` pin this). ``worker:devloss[:D]``
kills the victim like a crash AND makes every respawn acquire D fewer
devices (``inject.devloss_cut`` — the parent reads the same spec when
sizing the replacement), driving the shrink-and-replan path: the
replacement rebuilds its hot plans on the smaller mesh and restores a
resident solver across the mesh change
(``persist.load(allow_mesh_change=True)`` → ``persist.degraded_restore``
evidence).

``worker_backend="stub"`` swaps the jax-backed ``Server`` core for a
protocol-identical ``np.fft`` stub with a fixed service time — the
deterministic core the routing/fairness/failure tests drive (same
pipes, same detector, same injectors; only the FFT engine differs).
"""

from __future__ import annotations

import dataclasses
import multiprocessing
import os
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import obs
from ..resilience import inject
from ..resilience.deadline import Deadline, DeadlineExceeded
from . import plancache
from .router import (DEFAULT_TENANT, FairQueue, RendezvousRing,
                     TenantPolicy)
from .server import (Overloaded, ServerClosed, _new_trace_id,
                     normalize_request, settle_future)

HEARTBEAT_INTERVAL_S = 0.5
HEARTBEAT_K = 3
SPAWN_TIMEOUT_S = 120.0
MAX_RESUBMITS = 3
HOT_KEYS_TRACKED = 16


# ---------------------------------------------------------------------------
# error transport (structured exceptions across the pipe)
# ---------------------------------------------------------------------------

class RemoteWorkerError(RuntimeError):
    """A worker-side failure with no structured twin on the router side
    (``GuardViolation``, plan-build errors, ...); carries the original
    type name so load-generator classification and logs stay honest."""

    def __init__(self, type_name: str, msg: str):
        super().__init__(f"{type_name}: {msg}")
        self.type_name = type_name


def _encode_error(e: BaseException) -> Dict[str, Any]:
    d: Dict[str, Any] = {"type": type(e).__name__, "msg": str(e)[:500]}
    for attr in ("reason", "queue_depth", "est_delay_ms", "budget_ms",
                 "key", "retry_after_s", "detail", "overrun_ms"):
        if hasattr(e, attr):
            v = getattr(e, attr)
            if isinstance(v, (bool, int, float, str)):
                d[attr] = v
    return d


def _decode_error(d: Dict[str, Any]) -> BaseException:
    t, msg = d.get("type", "RuntimeError"), d.get("msg", "")
    if t == "Overloaded":
        return Overloaded(d.get("reason", "queue_full"),
                          d.get("queue_depth", 0),
                          d.get("est_delay_ms", 0.0),
                          d.get("budget_ms", 0.0))
    if t == "DeadlineExceeded":
        return DeadlineExceeded(msg, detail=d.get("detail", "expired"),
                                overrun_ms=d.get("overrun_ms", 0.0))
    if t == "CircuitOpen":
        from ..resilience.circuit import CircuitOpen
        return CircuitOpen(d.get("key", "?"), d.get("retry_after_s", 0.0))
    if t == "ServerClosed":
        return ServerClosed(msg)
    if t in ("ValueError", "TypeError"):
        return ValueError(msg)
    return RemoteWorkerError(t, msg)


# ---------------------------------------------------------------------------
# the worker process
# ---------------------------------------------------------------------------

class _StubCore:
    """Protocol twin of ``Server`` with a deterministic ``np.fft`` engine
    and a fixed per-request service time — no jax, no compile, so the
    routing/fairness/failure tests measure the FLEET, not XLA."""

    def __init__(self, service_ms: float = 5.0, max_queue: int = 64,
                 max_coalesce: int = 8):
        self.service_ms = float(service_ms)
        self.max_queue = int(max_queue)
        self.max_coalesce = int(max_coalesce)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending: List[Tuple[Any, Future]] = []
        self._state = "running"
        self._counts = {"served": 0, "shed": 0, "deadline_expired": 0}
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def submit(self, x: Any, transform: str = "r2c",
               direction: str = "forward", *, ny: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               decomp: Optional[str] = None) -> Future:
        # decomp only picks the served plan family; the np.fft twin has
        # no mesh, so it is validated-and-ignored (routing happens on
        # the PARENT side — the stub exists to test exactly that).
        x, shape, _ = normalize_request(x, transform, direction, ny)
        dl = Deadline.after_ms(deadline_ms) if deadline_ms else None
        fut: Future = Future()
        with self._lock:
            if self._state != "running":
                raise ServerClosed(f"stub is {self._state}")
            if len(self._pending) >= self.max_queue:
                self._counts["shed"] += 1
                raise Overloaded("queue_full", len(self._pending), 0.0,
                                 float(self.max_queue))
            self._pending.append(((x, transform, direction, shape, dl),
                                  fut))
            self._cv.notify()
        return fut

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._pending and self._state == "running":
                    self._cv.wait(0.05)
                if not self._pending:
                    return
                (x, transform, direction, shape, dl), fut = \
                    self._pending.pop(0)
            if dl is not None and dl.expired():
                with self._lock:
                    self._counts["deadline_expired"] += 1
                fut.set_exception(DeadlineExceeded(
                    "stub deadline expired", detail="queued",
                    overrun_ms=-dl.remaining_ms()))
                continue
            time.sleep(self.service_ms / 1e3)
            try:
                # n-dimensional: rfftn == rfft2 on a 2D image, and the
                # same dispatch serves 3D volumes (unnormalized inverse,
                # Server-style).
                if direction == "forward":
                    out = (np.fft.rfftn(x) if transform == "r2c"
                           else np.fft.fftn(x))
                elif transform == "r2c":
                    out = np.fft.irfftn(x, s=shape) \
                        * float(np.prod(shape))
                else:
                    out = np.fft.ifftn(x) * x.size
                with self._lock:
                    self._counts["served"] += 1
                fut.set_result(np.ascontiguousarray(out))
            except Exception as e:  # noqa: BLE001 — worker loop ships it
                fut.set_exception(e)

    def prewarm(self, shape: Tuple[int, ...], dtype: Any = None,
                transform: str = "r2c", **kw: Any) -> int:
        return 0

    def health(self) -> Dict[str, Any]:
        with self._lock:
            return {"status": self._state, "queue_depth": len(self._pending),
                    "ema_ms": self.service_ms, "counters": dict(self._counts)}

    def close(self, drain: bool = True, timeout_s: float = 30.0) -> None:
        with self._cv:
            if self._state == "stopped":
                return
            self._state = "draining"
            if not drain:
                for _, fut in self._pending:
                    fut.set_exception(ServerClosed("stub closed"))
                self._pending.clear()
            self._cv.notify_all()
        self._worker.join(timeout_s)
        with self._lock:
            self._state = "stopped"


def _stats_lite(core: Any, devices: Optional[int] = None
                ) -> Dict[str, Any]:
    """The heartbeat payload: the queue/EMA/shed signals the router folds
    into its ``/metrics`` surface for the scale controller, plus the
    worker's LIVE device count (what it actually acquired — after a
    devloss respawn this is smaller than the spec, and the router's
    ``dfft_fleet_worker_devices`` gauge shows the dip)."""
    h = core.health()
    c = h.get("counters", {})
    out = {"status": h.get("status"),
           "queue_depth": h.get("queue_depth", 0),
           "ema_ms": h.get("ema_ms"),
           "served": c.get("served", 0), "shed": c.get("shed", 0),
           "deadline_expired": c.get("deadline_expired", 0),
           "batch_failures": c.get("batch_failures", 0)}
    if devices is not None:
        out["devices"] = int(devices)
    res = h.get("resident")
    if res:
        # The resident's progress rides the heartbeat so the ROUTER's
        # health/summary can report the standing tenant without an
        # extra round trip (kept small: the full registry stays in the
        # worker's own health()).
        out["resident"] = {"name": res.get("name"),
                           "step": res.get("step"),
                           "restored_from": res.get("restored_from"),
                           "checkpoints": res.get("checkpoints"),
                           "running": res.get("running")}
    return out


def _worker_main(conn: Any, spec: Dict[str, Any]) -> None:
    """Entry point of one spawned worker process (module-level so the
    spawn context can pickle it)."""
    os.environ["DFFT_WORKER_INDEX"] = str(spec["index"])
    # Worker-env overrides land BEFORE the jax backend initializes (the
    # spawn child imported jax but touched no device yet) — the fleet
    # bench uses this to pin each worker to one intra-op thread so
    # process-level scaling is real on a shared-core host.
    for k, v in (spec.get("env") or {}).items():
        os.environ[str(k)] = str(v)
    # Mesh sizing: a per-worker ``devices`` spec (the capability-aware
    # fleet's lever — and, after a devloss, the SHRUNKEN size the parent
    # computed) overrides the fleet-wide ``emulate_devices`` default.
    devices = int(spec.get("devices") or 0)
    if devices or spec.get("emulate_devices"):
        from ..parallel.mesh import force_cpu_devices
        force_cpu_devices(devices or int(spec["emulate_devices"]))
    index, generation = int(spec["index"]), int(spec["generation"])
    if spec.get("backend") == "stub":
        core: Any = _StubCore(
            service_ms=float(spec.get("stub_service_ms", 5.0)),
            max_queue=int(spec.get("server_kwargs", {})
                          .get("max_queue", 64)))
        ndev = devices or 1
    else:
        from .. import params as pm
        from .server import Server
        part = spec.get("partition") or pm.SlabPartition(1)
        if devices > 1:
            # A sized mesh worker partitions over EVERY device it
            # acquired — including the smaller count a devloss
            # replacement came back with (the replan half of
            # shrink-and-replan).
            part = pm.SlabPartition(devices)
        cfg = spec.get("config") or pm.Config()
        core = Server(part, cfg, shard=spec.get("shard", "batch"),
                      name=spec["name"], **spec.get("server_kwargs", {}))
        import jax
        ndev = len(jax.devices())
    # Resident solver tenant (ISSUE 14): build — and, when its
    # checkpoint store already holds a generation, RESTORE — the
    # standing simulation BEFORE announcing ready, so a replacement
    # worker rejoins the ring with the simulation already back at step
    # k: persist.restore precedes fleet.worker_join in the event log,
    # the chain the resume chaos drill validates.
    res_spec = spec.get("resident")
    if res_spec and spec.get("backend") != "stub":
        from .resident import ResidentSolver
        resident = ResidentSolver.build(
            dict(res_spec, name=f"{spec['name']}-resident"))
        core.attach_resident(resident)

    send_lock = threading.Lock()

    def send(msg: Tuple[Any, ...]) -> None:
        with send_lock:
            try:
                conn.send(msg)
            except (OSError, ValueError, BrokenPipeError):
                pass  # parent gone; the recv loop will exit on EOF

    def _prewarm(shapes: List[Tuple[Any, ...]]) -> int:
        built = 0
        for item in shapes:
            try:
                if len(item) == 6:  # (nx, ny, nz, code, transform, decomp)
                    nx, ny, nz, code, transform, dec = item
                    built += core.prewarm(
                        (int(nx), int(ny), int(nz)),
                        dtype="float64" if code == "f64" else "float32",
                        transform=transform, decomp=dec)
                else:
                    nx, ny, code, transform = item
                    built += core.prewarm(
                        (int(nx), int(ny)),
                        dtype="float64" if code == "f64" else "float32",
                        transform=transform)
            except Exception:  # noqa: BLE001 — a failed prewarm is a
                pass           # cold first request, not a dead worker
        return built

    def _reply(tid: str, fut: Future) -> None:
        try:
            send(("res", tid, "ok", np.asarray(fut.result())))
        except Exception as e:  # noqa: BLE001 — ship every outcome
            send(("res", tid, "err", _encode_error(e)))

    # A replacement worker prewarms the fleet's hot shapes BEFORE
    # announcing ready — it rejoins the ring hot, not cold.
    prewarmed = _prewarm(spec.get("prewarm", []))
    send(("ready", os.getpid(), generation))
    if prewarmed:
        obs.event("fleet.worker_prewarmed", worker=spec["name"],
                  built=prewarmed)
    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            break  # router died; nothing left to serve for
        inject.maybe_hang_worker(index, generation)
        kind = msg[0]
        if kind == "req":
            inject.maybe_crash_worker(index, generation)
            inject.maybe_devloss_worker(index, generation)
            tid, req = msg[1], msg[2]
            try:
                fut = core.submit(req["x"], req["transform"],
                                  req["direction"], ny=req.get("ny"),
                                  deadline_ms=req.get("deadline_ms"),
                                  decomp=req.get("decomp"))
            except Exception as e:  # noqa: BLE001 — structured transport
                send(("res", tid, "err", _encode_error(e)))
            else:
                fut.add_done_callback(
                    lambda f, tid=tid: _reply(tid, f))
        elif kind == "ping":
            send(("pong", msg[1], _stats_lite(core, devices=ndev)))
        elif kind == "prewarm":
            # OFF the pipe loop: a prewarm compiles for seconds, and a
            # worker that stops answering pings while it compiles would
            # be declared dead by the very detector that asked for the
            # prewarm (observed as a mass false-death when every worker
            # prewarmed simultaneously).
            threading.Thread(
                target=lambda shapes=msg[1]:
                    send(("prewarmed", _prewarm(shapes))),
                daemon=True).start()
        elif kind == "drain":
            core.close(drain=True)
            send(("drained", _stats_lite(core, devices=ndev)))
            break
        elif kind == "stop":
            core.close(drain=False)
            break
    try:
        conn.close()
    except OSError:
        pass


# ---------------------------------------------------------------------------
# router-side request / worker records
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _FleetRequest:
    x: np.ndarray
    transform: str
    direction: str
    ny: int  # logical extent of the (possibly halved) LAST axis
    key: str
    tenant: str
    deadline: Optional[Deadline]
    future: Future
    trace_id: str
    submitted_at: float
    attempts: int = 0
    decomp: Optional[str] = None  # volumes only: slab | pencil


class _Worker:
    """Router-side handle of one worker process."""

    def __init__(self, name: str, index: int, generation: int,
                 proc: Any, conn: Any, policy: TenantPolicy,
                 devices: int = 0, full_devices: int = 0):
        self.name = name
        self.index = index
        self.generation = generation
        # devices: the mesh size this incarnation was spawned at;
        # full_devices: the spec'd size. devices < full_devices means a
        # devloss replacement running short — health() reports degraded
        # and fleet.capacity weights it fractionally until a full-size
        # replacement rejoins.
        self.devices = int(devices)
        self.full_devices = int(full_devices)
        self.proc = proc
        self.conn = conn
        self.lock = threading.Lock()
        # Serializes pipe WRITES (dispatch, pings, prewarm/drain control
        # all send from different threads; Connection.send is not
        # thread-safe). Always acquired AFTER self.lock when both are
        # held.
        self.send_lock = threading.Lock()
        self.state = "starting"  # starting | ready | draining | dead
        self.pending = FairQueue(policy)
        self.inflight: Dict[str, _FleetRequest] = {}
        self.last_pong = time.monotonic()
        self.ping_seq = 0
        self.stats: Dict[str, Any] = {}
        self.ready_event = threading.Event()
        self.drained_event = threading.Event()
        self.prewarmed_event = threading.Event()
        self.prewarm_built = 0
        self.reader: Optional[threading.Thread] = None
        self.dispatcher: Optional[threading.Thread] = None
        # Wakes the dispatcher thread: set by admission/responses, so
        # the (potentially BLOCKING) pipe send never runs on a caller's
        # thread — a full pipe to one busy worker must stall only that
        # worker's dispatcher, not every submitter (head-of-line
        # convoying measured on the fleet bench before this split).
        self.kick = threading.Event()

    def send(self, msg: Tuple[Any, ...]) -> None:
        """Raises on a broken pipe — callers treat that as death."""
        with self.send_lock:
            self.conn.send(msg)

    def try_send(self, msg: Tuple[Any, ...]) -> bool:
        """Non-blocking variant for the monitor thread: if the
        dispatcher holds the send lock (a big payload mid-write to a
        backed-up pipe), SKIP rather than block — a frozen monitor
        would stop failure detection for the whole fleet, and the
        silent worker is caught by pong age regardless. Returns whether
        the message was sent; raises like ``send`` on a broken pipe."""
        if not self.send_lock.acquire(blocking=False):
            return False
        try:
            self.conn.send(msg)
        finally:
            self.send_lock.release()
        return True

    def kill(self) -> None:
        try:
            if self.proc.is_alive():
                self.proc.terminate()
                self.proc.join(2.0)
                if self.proc.is_alive():
                    self.proc.kill()
                    self.proc.join(1.0)
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass
        try:
            self.conn.close()
        except OSError:
            pass


class Fleet:
    """N-worker shared-nothing serving pool (see module docstring).

    The submit/request surface mirrors :class:`~.server.Server` (the
    load generator drives either), plus ``tenant=`` — the admission
    identity the quota/fairness machinery meters."""

    def __init__(self, n_workers: int = 2, *, partition: Any = None,
                 config: Any = None, shard: str = "batch",
                 emulate_devices: int = 0,
                 worker_devices: Optional[List[int]] = None,
                 volume_decomp: str = "slab",
                 worker_backend: str = "server",
                 stub_service_ms: float = 5.0,
                 heartbeat_interval_s: float = HEARTBEAT_INTERVAL_S,
                 heartbeat_k: int = HEARTBEAT_K,
                 worker_inflight: int = 4, worker_pending: int = 64,
                 tenant_weights: Optional[Dict[str, float]] = None,
                 admission_capacity: Optional[int] = None,
                 max_resubmits: int = MAX_RESUBMITS,
                 spawn_timeout_s: float = SPAWN_TIMEOUT_S,
                 name: str = "dfft-fleet",
                 worker_env: Optional[Dict[str, str]] = None,
                 resident: Optional[Dict[str, Any]] = None,
                 resident_index: int = 0,
                 **server_kwargs: Any):
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        if worker_backend not in ("server", "stub"):
            raise ValueError("worker_backend must be 'server' or 'stub'")
        if volume_decomp not in plancache.VOLUME_DECOMPS:
            raise ValueError(f"volume_decomp must be one of "
                             f"{plancache.VOLUME_DECOMPS}, "
                             f"got {volume_decomp!r}")
        self.name = name
        self.shard = shard
        self.volume_decomp = volume_decomp
        # Per-worker-INDEX mesh sizes (0 = the fleet-wide default); an
        # index past the list (scale-up mints new indices) gets the
        # default too. devices > 1 makes a worker MESH-CAPABLE: it joins
        # the volume routing ring and serves fft3d/* keys.
        self._worker_devices = [int(d) for d in (worker_devices or [])]
        self._emulate_devices = int(emulate_devices)
        self._volume_capable = (self._emulate_devices > 1
                                or any(d > 1
                                       for d in self._worker_devices))
        self.worker_inflight = max(1, int(worker_inflight))
        self.worker_pending = max(1, int(worker_pending))
        self.max_resubmits = int(max_resubmits)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.heartbeat_k = max(1, int(heartbeat_k))
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.max_coalesce = int(server_kwargs.get("max_coalesce", 8))
        cap = (int(admission_capacity) if admission_capacity
               else n_workers * self.worker_pending)
        self.policy = TenantPolicy(cap, tenant_weights)
        self.ring = RendezvousRing()
        # The capability ring: fft3d/* volume keys rendezvous-hash over
        # the mesh-capable members ONLY (2D keys over self.ring — every
        # worker). Same minimum-movement stability, per capability
        # class.
        self.mesh_ring = RendezvousRing()
        if worker_backend == "server":
            server_kwargs = dict(server_kwargs,
                                 volume_decomp=volume_decomp)
        self._spec_base = {
            "partition": partition, "config": config, "shard": shard,
            "emulate_devices": int(emulate_devices),
            "backend": worker_backend,
            "stub_service_ms": float(stub_service_ms),
            "server_kwargs": dict(server_kwargs),
            "env": dict(worker_env or {}),
        }
        # Resident solver tenant (ISSUE 14): hosted by ONE worker slot
        # (default index 0). The slot is stable across respawns — a
        # replacement worker keeps its index — so the replacement gets
        # the resident spec too and restores from the checkpoint store
        # before rejoining the ring.
        if resident is not None and worker_backend == "stub":
            raise ValueError("a resident solver needs the real Server "
                             "worker backend (worker_backend='server')")
        self._resident_spec = dict(resident) if resident else None
        self._resident_index = int(resident_index)
        self._ctx = multiprocessing.get_context("spawn")
        self._lock = threading.Lock()
        self._workers: Dict[str, _Worker] = {}
        self._next_index = 0
        self._state = "running"  # running | draining | stopped
        self._started_at = time.monotonic()
        self._stop = threading.Event()
        self._orphans: List[_FleetRequest] = []
        self._gauges_at = 0.0
        self._label_tenants: set = set()
        self._tenant_gauge_labels: set = set()
        self._hot_keys: "Dict[str, float]" = {}
        self._scale_decisions: List[Dict[str, Any]] = []
        self._controller: Optional["ScaleController"] = None
        self._counts = {"admitted": 0, "served": 0, "shed": 0,
                        "failed": 0, "deadline_expired": 0,
                        "resubmitted": 0, "abandoned": 0,
                        "worker_deaths": 0, "worker_restarts": 0,
                        "rejected_closed": 0}
        obs.event("fleet.start", fleet=name, workers=n_workers,
                  backend=worker_backend, shard=shard,
                  heartbeat_interval_s=self.heartbeat_interval_s,
                  heartbeat_k=self.heartbeat_k,
                  admission_capacity=cap)
        started = [self._spawn(self._take_index(), generation=0)
                   for _ in range(n_workers)]
        deadline = time.monotonic() + self.spawn_timeout_s
        for w in started:
            if not w.ready_event.wait(max(0.1,
                                          deadline - time.monotonic())):
                for ww in started:
                    ww.kill()
                raise RuntimeError(
                    f"fleet worker {w.name} not ready within "
                    f"{self.spawn_timeout_s:.0f} s")
            self._join_ring(w)
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True,
                                         name=f"{name}-monitor")
        self._monitor.start()

    # -- lifecycle helpers -------------------------------------------------

    def __enter__(self) -> "Fleet":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close(drain=True)

    def _take_index(self) -> int:
        with self._lock:
            i = self._next_index
            self._next_index += 1
            return i

    def _devices_for(self, index: int) -> int:
        """The spec'd (full-size) mesh of worker ``index``: its
        ``worker_devices`` entry when one exists and is nonzero, else
        the fleet-wide ``emulate_devices`` default (0 = unsized)."""
        if 0 <= index < len(self._worker_devices) \
                and self._worker_devices[index]:
            return self._worker_devices[index]
        return self._emulate_devices

    def _prewarm_shapes(self, volumes: bool = True
                        ) -> List[Tuple[Any, ...]]:
        with self._lock:
            keys = sorted(self._hot_keys,
                          key=lambda k: -self._hot_keys[k])
        shapes: List[Tuple[Any, ...]] = []
        for k in keys[:HOT_KEYS_TRACKED]:
            try:
                d = plancache.parse_request_key(k)
            except ValueError:
                continue
            if "nz" in d:
                # Hot VOLUME shapes go only to mesh-capable workers —
                # a replacement rebuilds them on whatever mesh it
                # actually acquired.
                if volumes:
                    shapes.append((d["nx"], d["ny"], d["nz"], d["dtype"],
                                   d["transform"], d["decomp"]))
            else:
                shapes.append((d["nx"], d["ny"], d["dtype"],
                               d["transform"]))
        return shapes

    def _spawn(self, index: int, generation: int,
               prewarm: Optional[List[Tuple[Any, ...]]] = None
               ) -> _Worker:
        name = f"worker-{index}"
        full = self._devices_for(index)
        cut = inject.devloss_cut(index, generation) if full else 0
        devices = max(1, full - cut) if cut else full
        resident = (self._resident_spec
                    if index == self._resident_index else None)
        if (resident is not None and devices > 1
                and (devices < full or not resident.get("partitions"))):
            # Shrink-and-replan (devloss respawn) and the unpinned
            # default on a sized mesh worker: build the resident at the
            # partition count the mesh it ACTUALLY acquired can carry,
            # and let persist restore across the rank-count fingerprint
            # diff (two-tier contract: allclose + a structured
            # persist.degraded_restore event, never silent). A spec
            # that pins ``partitions`` keeps it while the worker is
            # full-size (strict bit-exact restore).
            resident = dict(resident, partitions=devices,
                            allow_mesh_change=True)
        if devices and devices < full:
            obs.event("fleet.worker_shrunk", worker=name,
                      generation=generation, devices=devices,
                      full_devices=full, lost=cut)
        prewarm = [t for t in (prewarm or [])
                   if len(t) == 4 or (full or devices) > 1]
        spec = dict(self._spec_base, name=name, index=index,
                    generation=generation, prewarm=prewarm,
                    devices=devices, resident=resident)
        parent_conn, child_conn = self._ctx.Pipe(duplex=True)
        proc = self._ctx.Process(target=_worker_main,
                                 args=(child_conn, spec),
                                 name=name, daemon=True)
        proc.start()
        child_conn.close()
        w = _Worker(name, index, generation, proc, parent_conn,
                    self.policy, devices=devices, full_devices=full)
        w.reader = threading.Thread(target=self._reader_loop, args=(w,),
                                    daemon=True, name=f"{name}-reader")
        w.reader.start()
        w.dispatcher = threading.Thread(target=self._dispatch_loop,
                                        args=(w,), daemon=True,
                                        name=f"{name}-dispatch")
        w.dispatcher.start()
        with self._lock:
            self._workers[name] = w
        return w

    def _join_ring(self, w: _Worker) -> None:
        """Promote a ready worker into the routing ring and drain any
        parked (orphaned) requests through routing again."""
        with self._lock:
            if self._state == "stopped":
                # close() already swept self._workers (or this worker
                # registered into the post-sweep dict): nobody else will
                # ever reap it, so a plain return here leaks a live
                # subprocess plus its reader/dispatcher threads — a
                # _respawn/scale-up racing close() must die right here.
                self._workers.pop(w.name, None)
                stopped = True
            else:
                stopped = False
                w.state = "ready"
                w.last_pong = time.monotonic()
                self.ring.add(w.name)
                if max(w.devices, w.full_devices) > 1:
                    self.mesh_ring.add(w.name)
                if w.generation > 0:
                    self._counts["worker_restarts"] += 1
                orphans, self._orphans = self._orphans, []
        if stopped:
            w.kill()
            return
        obs.metrics.gauge("fleet.workers", len(self.ring))
        if w.generation > 0:
            obs.metrics.inc("fleet.worker_restarts")
        obs.event("fleet.worker_join", worker=w.name, pid=w.proc.pid,
                  generation=w.generation, devices=w.devices,
                  ring=list(self.ring.members()),
                  mesh_ring=list(self.mesh_ring.members()))
        for req in orphans:
            self._route(req)
        self._pump(w)

    # -- admission / routing ----------------------------------------------

    def submit(self, x: Any, transform: str = "r2c",
               direction: str = "forward", *, ny: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               decomp: Optional[str] = None,
               tenant: str = DEFAULT_TENANT) -> Future:
        """Admit one request — a 2D image (routed over every worker) or
        a 3D volume (``fft3d/*`` key, routed over the mesh-capable ring
        only; ``decomp`` overrides the fleet's ``volume_decomp``
        default). Returns a ``Future``. Raises the structured rejection
        at submit: ``Overloaded`` (``tenant_quota`` when the tenant is
        over its weighted share, ``queue_full`` when its worker's
        router queue is full, ``no_workers`` when the whole ring is
        down and the parking lot is full), ``ServerClosed``, or
        ``ValueError`` for a volume on a fleet with no mesh-capable
        worker configured."""
        x, shape, double = normalize_request(x, transform, direction, ny)
        code = "f64" if double else "f32"
        if len(shape) == 3:
            if not self._volume_capable:
                raise ValueError(
                    "3D volume request but no mesh-capable worker is "
                    "configured (give one a worker_devices / "
                    "emulate_devices mesh of >= 2 devices)")
            dec = decomp or self.volume_decomp
            key = plancache.request_key3d(shape[0], shape[1], shape[2],
                                          code, transform, dec)
        else:
            if decomp is not None:
                raise ValueError("decomp applies to 3D volume requests "
                                 "only")
            dec = None
            key = plancache.request_key(shape[0], shape[1], code,
                                        transform, self.shard)
        with self._lock:
            if self._state != "running":
                self._counts["rejected_closed"] += 1
                raise ServerClosed(f"fleet is {self._state}; "
                                   "not admitting new requests")
            self._hot_keys[key] = time.monotonic()
            if len(self._hot_keys) > 4 * HOT_KEYS_TRACKED:
                for k in sorted(self._hot_keys,
                                key=lambda k: self._hot_keys[k])[
                                    :len(self._hot_keys) // 2]:
                    del self._hot_keys[k]
        try:
            self.policy.admit(tenant)
        except Overloaded as e:
            self._shed(e, tenant, key)
            raise
        dl = (Deadline.after_ms(deadline_ms)
              if deadline_ms is not None else None)
        tid = _new_trace_id()
        fut: Future = Future()
        fut.trace_id = tid  # type: ignore[attr-defined]
        req = _FleetRequest(x=x, transform=transform, direction=direction,
                            ny=shape[-1], key=key, tenant=tenant,
                            deadline=dl, future=fut, trace_id=tid,
                            submitted_at=time.monotonic(), decomp=dec)
        try:
            self._route(req, admitting=True)
        except Overloaded as e:
            self.policy.release(tenant)
            self._shed(e, tenant, key)
            raise
        with self._lock:
            self._counts["admitted"] += 1
        obs.metrics.inc("fleet.admitted")
        self._refresh_gauges()
        return fut

    def request(self, x: Any, transform: str = "r2c",
                direction: str = "forward", *, ny: Optional[int] = None,
                deadline_ms: Optional[float] = None,
                decomp: Optional[str] = None,
                tenant: str = DEFAULT_TENANT,
                timeout_s: Optional[float] = None) -> np.ndarray:
        """Blocking convenience wrapper over :meth:`submit`."""
        return self.submit(x, transform, direction, ny=ny,
                           deadline_ms=deadline_ms, decomp=decomp,
                           tenant=tenant).result(timeout_s)

    def _tenant_label(self, tenant: str) -> str:
        """Bounded label cardinality (the Server._breakers lesson: an
        adversarial name sweep must not grow the metrics registry — or
        the /metrics payload — without limit): configured tenants and
        the first 32 ad-hoc names keep their own series, the rest fold
        into ``other``."""
        if tenant in self.policy.weights or tenant == DEFAULT_TENANT:
            return tenant
        with self._lock:
            if (tenant in self._label_tenants
                    or len(self._label_tenants) < 32):
                self._label_tenants.add(tenant)
                return tenant
        return "other"

    def _shed(self, e: Overloaded, tenant: str, key: str) -> None:
        with self._lock:
            self._counts["shed"] += 1
        obs.metrics.inc("fleet.shed")
        obs.metrics.inc(obs.metrics.labeled(
            "fleet.tenant.shed", tenant=self._tenant_label(tenant)))
        obs.event("fleet.shed", reason=e.reason, tenant=tenant, key=key,
                  queue_depth=e.queue_depth, budget=e.budget_ms)

    def _route(self, req: _FleetRequest, admitting: bool = False) -> None:
        """Enqueue ``req`` at its key's owner (or the parking lot while
        the ring is empty) and pump. ``admitting`` enforces the router
        queue bound — a RESUBMITTED request (a worker died under it) is
        never shed here: zero lost requests beats a tidy bound."""
        worker = None
        owner = self._ring_for(req.key).owner(req.key)
        if owner is not None:
            with self._lock:
                worker = self._workers.get(owner)
        if worker is None:
            with self._lock:
                stopped = self._state == "stopped"
                if not stopped:
                    if (admitting
                            and len(self._orphans)
                            >= self.policy.capacity):
                        raise Overloaded("no_workers", len(self._orphans),
                                         0.0, float(self.policy.capacity))
                    self._orphans.append(req)
            if stopped:
                # A late reroute (a scale-down _finish racing close())
                # must not park work in an orphan list nobody will ever
                # drain: answer structurally, release the quota slot.
                self.policy.release(req.tenant)
                settle_future(req.future, exc=ServerClosed(
                    "fleet stopped before execution"))
            return
        with worker.lock:
            # Re-check under the WORKER lock: the failure handler sets
            # state dead (fleet lock) BEFORE draining pending (worker
            # lock), so a push seen here with state still 'ready' is
            # either pre-drain (the drain will sweep it) or the worker
            # is live — a push into an already-drained queue of a dead
            # worker (a forever-unresolved future) cannot happen.
            if worker.state == "ready":
                if (admitting
                        and len(worker.pending) >= self.worker_pending):
                    raise Overloaded("queue_full", len(worker.pending),
                                     0.0, float(self.worker_pending))
                worker.pending.push(req.tenant, req)
                pushed = True
            else:
                pushed = False
        if not pushed:
            # The owner died between the ring lookup and the push: the
            # ring has (or is about to have) new ownership — re-resolve.
            self._route(req, admitting)
            return
        self._pump(worker)

    def _ring_for(self, key: str) -> RendezvousRing:
        """Capability-aware ring choice: fft3d volume keys hash over the
        mesh-capable members only; everything else over the full ring.
        Both rings keep the minimum-movement property WITHIN their
        capability class (a 2D worker's death never moves a volume
        key; a mesh worker's death moves only ITS keys in each ring)."""
        return (self.mesh_ring if key.startswith("fft3d/")
                else self.ring)

    def _pump(self, worker: _Worker) -> None:
        """Wake the worker's dispatcher (cheap, non-blocking — safe on
        admission and reader threads)."""
        worker.kick.set()

    def _dispatch_loop(self, worker: _Worker) -> None:
        """Per-worker dispatcher: pops the fair queue while the
        in-flight window has room and performs the pipe sends. The
        window (``worker_inflight``) is the fleet's fairness lever:
        small enough that a backlogged tenant cannot monopolize the
        worker's own FIFO, large enough to keep the pipe busy; the fair
        queue picks WHICH tenant refills a freed slot. Sends live on
        THIS thread because a pipe to a busy worker can block when its
        buffer fills — that back-pressure must stall only this worker's
        dispatch, never the submitters or the other workers."""
        while True:
            worker.kick.wait(0.5)
            worker.kick.clear()
            if worker.state in ("dead", "draining"):
                return
            while True:
                with worker.lock:
                    if (worker.state != "ready"
                            or len(worker.inflight)
                            >= self.worker_inflight):
                        break
                    req = worker.pending.pop()
                    if req is None:
                        break
                    if (req.deadline is not None
                            and req.deadline.expired()):
                        expired = req
                    else:
                        worker.inflight[req.trace_id] = req
                        expired = None
                        payload = {"x": req.x,
                                   "transform": req.transform,
                                   "direction": req.direction,
                                   "ny": req.ny}
                        if req.decomp is not None:
                            payload["decomp"] = req.decomp
                        if req.deadline is not None:
                            payload["deadline_ms"] = \
                                req.deadline.remaining_ms()
                if expired is not None:
                    self._expire(expired, "queued")
                    continue
                try:
                    worker.send(("req", req.trace_id, payload))
                except (OSError, ValueError, BrokenPipeError) as e:
                    self._on_worker_failure(
                        worker, f"pipe send failed: {e}")
                    return

    def _expire(self, req: _FleetRequest, detail: str) -> None:
        with self._lock:
            self._counts["deadline_expired"] += 1
        self.policy.release(req.tenant)
        over = -req.deadline.remaining_ms() if req.deadline else 0.0
        obs.event("fleet.reply", trace=req.trace_id,
                  outcome="deadline_expired", detail=detail)
        settle_future(req.future, exc=DeadlineExceeded(
            f"deadline exceeded by {over:.1f} ms ({detail})",
            detail=detail, overrun_ms=over))

    def _refresh_gauges(self, force: bool = False) -> None:
        """Fold queue occupancy into the ``/metrics`` gauges. Sweeping
        every worker's lock is O(workers), so the hot paths (submit /
        per-result) are throttled to one sweep per 0.2 s — the scrape
        and controller cadences are slower than that anyway; the
        monitor tick forces a fresh sweep."""
        now = time.monotonic()
        if not force and now - self._gauges_at < 0.2:
            return
        self._gauges_at = now
        with self._lock:
            workers = list(self._workers.values())
            orphans = len(self._orphans)
        pending = orphans
        inflight = 0
        capacity = 0.0
        for w in workers:
            with w.lock:
                pending += len(w.pending)
                inflight += len(w.inflight)
            if w.state == "ready":
                # Capacity-weighted worker count: a worker running at
                # 4 of its spec'd 8 devices contributes 0.5 — the
                # controller's signal that "2 workers" may be less than
                # two workers' worth of capacity.
                capacity += (w.devices / w.full_devices
                             if w.full_devices else 1.0)
        obs.metrics.gauge("fleet.pending", pending)
        obs.metrics.gauge("fleet.outstanding", pending + inflight)
        obs.metrics.gauge("fleet.capacity", round(capacity, 4))
        # Per-tenant quota occupancy, folded through the same bounded
        # label vocabulary as fleet.tenant.shed; a tenant that goes
        # idle keeps its series pinned at 0 rather than freezing at the
        # last nonzero sample.
        snap: Dict[str, int] = {}
        for t, d in self.policy.snapshot().items():
            lab = self._tenant_label(t)
            snap[lab] = snap.get(lab, 0) + int(d["outstanding"])
        with self._lock:
            self._tenant_gauge_labels |= set(snap)
            labels = set(self._tenant_gauge_labels)
        for t in labels:
            obs.metrics.gauge(
                obs.metrics.labeled("fleet.tenant.outstanding", tenant=t),
                snap.get(t, 0))

    # -- worker I/O --------------------------------------------------------

    def _reader_loop(self, worker: _Worker) -> None:
        while True:
            try:
                msg = worker.conn.recv()
            except (EOFError, OSError):
                with self._lock:
                    benign = (worker.state in ("draining", "dead")
                              or self._state == "stopped")
                if not benign:
                    self._on_worker_failure(worker, "pipe closed")
                return
            kind = msg[0]
            if kind == "res":
                self._on_result(worker, msg[1], msg[2], msg[3])
            elif kind == "pong":
                worker.last_pong = time.monotonic()
                worker.stats = msg[2]
                self._fold_worker_stats(worker)
            elif kind == "ready":
                worker.ready_event.set()
            elif kind == "prewarmed":
                worker.prewarm_built = int(msg[1])
                worker.prewarmed_event.set()
            elif kind == "drained":
                worker.stats = msg[1]
                worker.drained_event.set()

    def _on_result(self, worker: _Worker, tid: str, status: str,
                   payload: Any) -> None:
        with worker.lock:
            req = worker.inflight.pop(tid, None)
        if req is None:
            return  # late duplicate (the request was rerouted) — drop
        self.policy.release(req.tenant)
        if status == "ok":
            with self._lock:
                self._counts["served"] += 1
            obs.metrics.inc("fleet.served")
            obs.metrics.observe(
                "serve.e2e_ms",
                (time.monotonic() - req.submitted_at) * 1e3)
            obs.event("fleet.reply", trace=tid, outcome="ok",
                      worker=worker.name, attempts=req.attempts)
            settle_future(req.future, result=payload)
        else:
            err = _decode_error(payload)
            if isinstance(err, DeadlineExceeded):
                with self._lock:
                    self._counts["deadline_expired"] += 1
            else:
                with self._lock:
                    self._counts["failed"] += 1
            obs.event("fleet.reply", trace=tid, outcome="error",
                      worker=worker.name, error=type(err).__name__)
            settle_future(req.future, exc=err)
        self._pump(worker)
        self._refresh_gauges()

    def _drop_worker_gauges(self, worker: _Worker) -> None:
        """Retire a departed worker's labeled gauges: a frozen
        queue_depth from a dead slot would read as phantom load to the
        scale controller (and grow /metrics forever as indices are
        never reused)."""
        lab = obs.metrics.labeled
        for g in ("fleet.worker.queue_depth", "fleet.worker.ema_ms",
                  "fleet.worker.shed", "fleet.worker.inflight",
                  "fleet.worker.devices"):
            obs.metrics.drop_gauge(lab(g, worker=worker.name))

    def _fold_worker_stats(self, worker: _Worker) -> None:
        """Heartbeat stats -> labeled gauges on the router's OWN metrics
        registry, so the ``/metrics`` exposition carries per-worker
        queue depth / EMA / shed — the controller (and any external
        autoscaler) reads THIS surface, not fleet internals."""
        s = worker.stats
        lab = obs.metrics.labeled
        obs.metrics.gauge(lab("fleet.worker.queue_depth",
                              worker=worker.name),
                          s.get("queue_depth", 0))
        if s.get("ema_ms") is not None:
            obs.metrics.gauge(lab("fleet.worker.ema_ms",
                                  worker=worker.name), s["ema_ms"])
        obs.metrics.gauge(lab("fleet.worker.shed", worker=worker.name),
                          s.get("shed", 0))
        if s.get("devices") is not None:
            # The capacity surface: after a devloss respawn this series
            # dips to the shrunken mesh size — the dip CI's mesh chaos
            # scenario scrapes off /metrics.
            obs.metrics.gauge(lab("fleet.worker.devices",
                                  worker=worker.name), s["devices"])
        with worker.lock:
            obs.metrics.gauge(lab("fleet.worker.inflight",
                                  worker=worker.name),
                              len(worker.inflight))

    # -- failure detection / recovery --------------------------------------

    def _monitor_loop(self) -> None:
        last_scale = 0.0
        while not self._stop.wait(self.heartbeat_interval_s):
            now = time.monotonic()
            with self._lock:
                workers = [w for w in self._workers.values()
                           if w.state == "ready"]
            for w in workers:
                if w.proc.exitcode is not None:
                    self._on_worker_failure(
                        w, f"exited rc {w.proc.exitcode}")
                    continue
                if (now - w.last_pong
                        > self.heartbeat_k * self.heartbeat_interval_s):
                    self._on_worker_failure(
                        w, f"{self.heartbeat_k} missed heartbeats "
                           f"({now - w.last_pong:.2f} s silent)")
                    continue
                w.ping_seq += 1
                try:
                    w.try_send(("ping", w.ping_seq))
                except (OSError, ValueError, BrokenPipeError) as e:
                    self._on_worker_failure(w, f"ping failed: {e}")
            self._refresh_gauges(force=True)
            ctl = self._controller
            if ctl is not None and now - last_scale >= ctl.interval_s:
                last_scale = now
                try:
                    ctl.step()
                except Exception as e:  # noqa: BLE001 — the controller
                    # must never take down the failure detector
                    obs.notice(f"fleet: scale controller error "
                               f"({type(e).__name__}: {e})"[:300],
                               name="fleet.scale_error")

    def _on_worker_failure(self, worker: _Worker, why: str) -> None:
        with self._lock:
            if worker.state == "dead" or self._state == "stopped":
                return
            if worker.state == "starting":
                # The spawn path (_respawn / __init__) owns a
                # never-became-ready worker: its kill() closes the pipe
                # and lands the reader here, but counting a death and
                # respawning would DUPLICATE the spawn loop's own retry
                # (two workers minting the same name, orphan processes).
                worker.state = "dead"
                if self._workers.get(worker.name) is worker:
                    self._workers.pop(worker.name)
                return
            worker.state = "dead"
            self.ring.remove(worker.name)
            self.mesh_ring.remove(worker.name)
            self._counts["worker_deaths"] += 1
            respawn = self._state == "running"
            if self._workers.get(worker.name) is worker:
                self._workers.pop(worker.name)
        worker.kick.set()  # release the dispatcher thread
        obs.metrics.inc("fleet.worker_deaths")
        obs.metrics.gauge("fleet.workers", len(self.ring))
        with worker.lock:
            moved = list(worker.inflight.values())
            worker.inflight.clear()
            moved += worker.pending.drain()
        obs.event("fleet.worker_death", worker=worker.name, why=why,
                  generation=worker.generation, moved=len(moved),
                  ring=list(self.ring.members()))
        obs.notice(f"fleet: worker {worker.name} dead ({why}); "
                   f"rerouting {len(moved)} request(s)",
                   name="fleet.worker_death_notice")
        from ..obs import flightrec
        flightrec.trigger("worker_death", f"{worker.name}: {why}",
                          worker=worker.name, moved=len(moved))
        worker.kill()
        self._drop_worker_gauges(worker)
        obs.event("fleet.reroute", worker=worker.name, moved=len(moved),
                  keys=sorted({r.key for r in moved}))
        self._reroute_moved(moved)
        self._refresh_gauges()
        if respawn:
            obs.event("fleet.worker_restart", worker=worker.name,
                      generation=worker.generation + 1)
            threading.Thread(
                target=self._respawn,
                args=(worker.index, worker.generation + 1),
                daemon=True, name=f"{worker.name}-respawn").start()

    def _reroute_moved(self, moved: List[_FleetRequest]) -> None:
        """Re-home requests stranded by a worker's departure — the ONE
        reroute policy (death and scale-down paths share it): expired
        deadlines answer ``DeadlineExceeded``; a request that already
        rode ``max_resubmits`` departures answers a structured
        ``RemoteWorkerError`` instead of bouncing forever; the rest are
        resubmitted idempotently under their original trace ids."""
        for req in moved:
            if req.deadline is not None and req.deadline.expired():
                self._expire(req, "rerouted")
            elif req.attempts >= self.max_resubmits:
                with self._lock:
                    self._counts["abandoned"] += 1
                self.policy.release(req.tenant)
                obs.event("fleet.reply", trace=req.trace_id,
                          outcome="abandoned", attempts=req.attempts)
                settle_future(req.future, exc=RemoteWorkerError(
                    "WorkerDied",
                    f"request {req.trace_id} abandoned after "
                    f"{req.attempts} worker deaths"))
            else:
                req.attempts += 1
                with self._lock:
                    self._counts["resubmitted"] += 1
                obs.metrics.inc("fleet.resubmitted")
                self._route(req)

    def _respawn(self, index: int, generation: int) -> None:
        for attempt in range(3):
            with self._lock:
                if self._state != "running":
                    return
            w = self._spawn(index, generation + attempt,
                            prewarm=self._prewarm_shapes())
            if w.ready_event.wait(self.spawn_timeout_s):
                self._join_ring(w)
                return
            obs.event("fleet.worker_spawn_failed", worker=w.name,
                      generation=w.generation, attempt=attempt + 1)
            w.kill()
            with self._lock:
                self._workers.pop(w.name, None)

    # -- scaling -----------------------------------------------------------

    def attach_controller(self, controller: "ScaleController") -> None:
        self._controller = controller

    def scale_to(self, n: int) -> None:
        """Grow or shrink the ready worker set to ``n`` through the same
        join/leave machinery the failure detector uses (a drained-away
        worker's pending reroutes; its in-flight completes normally)."""
        n = max(1, int(n))
        with self._lock:
            ready = sorted((w for w in self._workers.values()
                            if w.state == "ready"),
                           key=lambda w: w.index)
            starting = sum(1 for w in self._workers.values()
                           if w.state == "starting")
        # Count STARTING workers toward the target: a repeated up
        # decision during the multi-second spawn window must not
        # over-provision past it.
        if len(ready) + starting < n:
            for _ in range(n - len(ready) - starting):
                threading.Thread(target=self._respawn,
                                 args=(self._take_index(), 0),
                                 daemon=True).start()
        elif len(ready) > n:
            for w in ready[n:]:
                self._drain_worker(w)

    def _drain_worker(self, worker: _Worker) -> None:
        """Scale-down leave: out of the ring first (new keys reroute),
        pending requests rerouted, in-flight left to finish, then a
        graceful drain message."""
        with self._lock:
            if worker.state != "ready":
                return
            worker.state = "draining"
            self.ring.remove(worker.name)
            self.mesh_ring.remove(worker.name)
        worker.kick.set()  # release the dispatcher thread
        obs.metrics.gauge("fleet.workers", len(self.ring))
        with worker.lock:
            moved = worker.pending.drain()
        obs.event("fleet.worker_leave", worker=worker.name,
                  moved=len(moved), ring=list(self.ring.members()))
        for req in moved:
            self._route(req)

        def _finish() -> None:
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                with worker.lock:
                    if not worker.inflight:
                        break
                if worker.proc.exitcode is not None:
                    break  # died mid-drain; reroute below, don't wait
                time.sleep(0.02)
            try:
                worker.send(("drain",))
                worker.drained_event.wait(10.0)
            except (OSError, ValueError, BrokenPipeError):
                pass
            worker.kill()
            self._drop_worker_gauges(worker)
            with self._lock:
                if self._workers.get(worker.name) is worker:
                    self._workers.pop(worker.name)
            # Anything STILL in flight (the worker crashed or timed out
            # mid-drain) is rerouted exactly like a death — a scale-down
            # must never be the place requests and tenant quota slots
            # silently leak.
            with worker.lock:
                leftovers = list(worker.inflight.values())
                worker.inflight.clear()
                leftovers += worker.pending.drain()
            self._reroute_moved(leftovers)

        threading.Thread(target=_finish, daemon=True,
                         name=f"{worker.name}-leave").start()

    # -- health / lifecycle ------------------------------------------------

    def health(self) -> Dict[str, Any]:
        """The fleet readiness snapshot (the ``/healthz`` payload in
        fleet mode): per-worker state/beat age/load, ring membership,
        per-tenant quota accounting, the scale-decision audit trail and
        the flight recorder's last dump path."""
        now = time.monotonic()
        with self._lock:
            state = self._state
            counts = dict(self._counts)
            workers = dict(self._workers)
            orphans = len(self._orphans)
            decisions = list(self._scale_decisions[-16:])
        wsnap = {}
        for name, w in sorted(workers.items()):
            with w.lock:
                wsnap[name] = {
                    "state": w.state, "pid": w.proc.pid,
                    "generation": w.generation,
                    "devices": w.devices,
                    "full_devices": w.full_devices,
                    "inflight": len(w.inflight),
                    "pending": len(w.pending),
                    "pending_by_tenant": w.pending.depths(),
                    "last_pong_age_s": round(now - w.last_pong, 3),
                    "stats": dict(w.stats),
                }
        # Degraded while any worker runs SHORT of its spec'd mesh (a
        # devloss replacement serving at reduced capacity) — the fleet
        # is up, but an operator watching /healthz must see that it is
        # not whole until a full-size replacement rejoins.
        degraded = (len(self.ring) < len(workers)
                    or any(s["state"] != "ready" for s in wsnap.values())
                    or any(s["devices"] < s["full_devices"]
                           for s in wsnap.values()))
        status = (state if state != "running"
                  else ("degraded" if degraded else "ok"))
        # The standing resident's progress as folded from its host
        # worker's latest heartbeat (None when no resident configured
        # or its worker has not ponged yet).
        resident = None
        for s in wsnap.values():
            if s["stats"].get("resident"):
                resident = dict(s["stats"]["resident"])
                break
        from ..obs import flightrec
        return {
            "status": status,
            "resident": resident,
            "uptime_s": round(now - self._started_at, 3),
            "workers": wsnap,
            "ring": list(self.ring.members()),
            "mesh_ring": list(self.mesh_ring.members()),
            "orphaned": orphans,
            "tenants": self.policy.snapshot(),
            "counters": counts,
            "scale_decisions": decisions,
            "flight_recorder": dict(flightrec.stats(),
                                    last_dump=flightrec.last_dump()),
            "obs_metrics": obs.snapshot(),
        }

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def prewarm(self, shape: Tuple[int, ...], dtype: Any = None,
                transform: str = "r2c", *,
                decomp: Optional[str] = None, **kw: Any) -> int:
        """Broadcast ``Server.prewarm`` to every ready worker (each only
        serves its own key range, but prewarming all keeps a future
        reroute hot too) and wait for the acknowledgements in parallel;
        returns the total plans NEWLY BUILT across workers (0 when
        every bucket was already hot — same contract as
        ``Server.prewarm``). A 3D ``shape`` prewarms the single-shot
        volume plan on the MESH-CAPABLE workers only (the ones the
        fft3d ring routes to)."""
        code = ("f64" if dtype is not None
                and np.dtype(dtype) in (np.float64, np.complex128)
                else "f32")
        if len(shape) == 3:
            nx, ny, nz = int(shape[0]), int(shape[1]), int(shape[2])
            dec = decomp or self.volume_decomp
            key = plancache.request_key3d(nx, ny, nz, code, transform,
                                          dec)
            wire: Tuple[Any, ...] = (nx, ny, nz, code, transform, dec)
        else:
            nx, ny = int(shape[0]), int(shape[1])
            key = plancache.request_key(nx, ny, code, transform,
                                        self.shard)
            wire = (nx, ny, code, transform)
        with self._lock:
            self._hot_keys[key] = time.monotonic()
            workers = [w for w in self._workers.values()
                       if w.state == "ready"
                       and (len(wire) == 4
                            or max(w.devices, w.full_devices) > 1)]
        # Clear-all THEN send-all: acks arrive concurrently, and a
        # stale ack from a previous (timed-out) prewarm cannot set an
        # event that was cleared after it landed.
        for w in workers:
            w.prewarmed_event.clear()
        sent = []
        for w in workers:
            try:
                w.send(("prewarm", [wire]))
                sent.append(w)
            except (OSError, ValueError, BrokenPipeError):
                continue
        total = 0
        deadline = time.monotonic() + self.spawn_timeout_s
        for w in sent:
            if w.prewarmed_event.wait(max(0.1,
                                          deadline - time.monotonic())):
                total += w.prewarm_built
        return total

    def close(self, drain: bool = True, timeout_s: float = 60.0) -> None:
        """Stop the fleet. ``drain=True``: reject new admissions, let
        every admitted request resolve (workers finish their queues;
        responses keep pumping the router queues), then stop workers.
        Leftovers after the timeout answer ``ServerClosed`` — the fleet
        inherits the single-process loss-proof close contract."""
        with self._lock:
            if self._state == "stopped":
                return
            already = self._state == "draining"
            self._state = "draining"
        if not already:
            obs.notice(f"fleet: draining (drain={drain})",
                       name="fleet.drain", drain=drain)
        deadline = time.monotonic() + timeout_s
        if drain:
            while time.monotonic() < deadline:
                with self._lock:
                    workers = list(self._workers.values())
                    left = len(self._orphans)
                for w in workers:
                    with w.lock:
                        left += len(w.pending) + len(w.inflight)
                if left == 0:
                    break
                time.sleep(0.02)
        self._stop.set()
        with self._lock:
            workers = list(self._workers.values())
            self._workers = {}
            leftovers = self._orphans
            self._orphans = []
            self._state = "stopped"
        for w in workers:
            w.state = "draining"
            w.kick.set()  # release the dispatcher thread
            self.ring.remove(w.name)
            self.mesh_ring.remove(w.name)
            with w.lock:
                leftovers += list(w.inflight.values())
                w.inflight.clear()
                leftovers += w.pending.drain()

            # Fire-and-forget from a disposable thread: a hung worker's
            # full pipe (or a dispatcher blocked mid-send holding the
            # send lock) must not wedge close() past its timeout — the
            # monitor that would have broken the pipe was just stopped,
            # and the join+kill below reaps the worker either way.
            def _goodbye(w=w):
                try:
                    w.send(("drain" if drain else "stop",))
                except (OSError, ValueError, BrokenPipeError):
                    pass

            threading.Thread(target=_goodbye, daemon=True,
                             name=f"{w.name}-goodbye").start()
        for w in workers:
            w.proc.join(max(0.1, min(5.0, deadline - time.monotonic())))
            w.kill()
            self._drop_worker_gauges(w)
        for req in leftovers:
            self.policy.release(req.tenant)
            settle_future(req.future, exc=ServerClosed(
                "fleet stopped before execution"))
        obs.metrics.gauge("fleet.workers", 0)
        with self._lock:
            counts = dict(self._counts)
        obs.notice(f"fleet: stopped ({counts['served']} served, "
                   f"{counts['shed']} shed, "
                   f"{counts['worker_deaths']} worker deaths)",
                   name="fleet.stop", counters=counts)


# ---------------------------------------------------------------------------
# metrics-driven worker-count controller
# ---------------------------------------------------------------------------

def parse_exposition_signals(text: str) -> Dict[str, float]:
    """Extract the controller's input signals from a Prometheus
    exposition body (the literal ``GET /metrics`` surface): live worker
    count, router pending, total shed (router + per-worker), summed
    worker queue depth, max worker EMA, capacity-weighted worker count
    (``dfft_fleet_capacity`` — devloss-shrunken workers count
    fractionally) and total acquired devices. Unknown/missing series
    read 0."""
    sig = {"workers": 0.0, "pending": 0.0, "shed_total": 0.0,
           "queue_depth": 0.0, "ema_ms": 0.0, "capacity": 0.0,
           "devices_total": 0.0}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, rest = line.partition(" ")
        base = name.partition("{")[0]
        try:
            value = float(rest.split()[0])
        except (ValueError, IndexError):
            continue
        if base == "dfft_fleet_workers":
            sig["workers"] = value
        elif base == "dfft_fleet_pending":
            sig["pending"] = value
        elif base in ("dfft_fleet_shed_total",
                      "dfft_fleet_worker_shed"):
            sig["shed_total"] += value
        elif base in ("dfft_fleet_worker_queue_depth",
                      "dfft_serve_queue_depth"):
            sig["queue_depth"] += value
        elif base in ("dfft_fleet_worker_ema_ms", "dfft_serve_ema_ms"):
            sig["ema_ms"] = max(sig["ema_ms"], value)
        elif base == "dfft_fleet_capacity":
            sig["capacity"] = value
        elif base == "dfft_fleet_worker_devices":
            sig["devices_total"] += value
    return sig


class ScaleController:
    """Worker-count controller over the ``/metrics`` exposition.

    Policy (deliberately simple and fully audited): scale UP one worker
    when the scrape shows new shed since the last step or total queue
    depth above ``queue_high`` per worker; scale DOWN one worker after
    ``down_idle_steps`` consecutive idle steps (no shed growth, empty
    queues); both within ``[min_workers, max_workers]`` and separated by
    ``cooldown_s``. Every ACTED decision (up/down) emits an auditable
    record through ``obs.event`` (``fleet.scale_decision``), the flight
    recorder (``scale_decision`` trigger, per-kind cooldown) and
    ``health()["scale_decisions"]``; ``hold`` steps return their record
    (with the signal snapshot and reason) from :meth:`step` but are not
    persisted — at one step per ``interval_s`` they would flood the
    audit trail with non-events."""

    def __init__(self, fleet: Fleet, min_workers: int, max_workers: int,
                 *, interval_s: float = 1.0, cooldown_s: float = 5.0,
                 queue_high: float = 4.0, down_idle_steps: int = 8,
                 render: Any = None):
        if min_workers < 1 or max_workers < min_workers:
            raise ValueError("need 1 <= min_workers <= max_workers")
        self.fleet = fleet
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.interval_s = float(interval_s)
        self.cooldown_s = float(cooldown_s)
        self.queue_high = float(queue_high)
        self.down_idle_steps = int(down_idle_steps)
        self._render = render  # injectable exposition source (tests)
        self._last_shed: Optional[float] = None
        self._idle_steps = 0
        self._last_action_at = 0.0

    def read_signals(self) -> Dict[str, float]:
        if self._render is not None:
            text = self._render()
        else:
            from ..obs import promexp
            text = promexp.render()
        return parse_exposition_signals(text)

    def step(self) -> Dict[str, Any]:
        """One control step; returns (and records) the decision."""
        sig = self.read_signals()
        now = time.monotonic()
        shed = sig["shed_total"]
        shed_delta = (0.0 if self._last_shed is None
                      else max(0.0, shed - self._last_shed))
        workers = int(sig["workers"])
        # Capacity-weighted worker count (ISSUE 20): a devloss-shrunken
        # worker counts fractionally, so the queue-pressure threshold
        # tightens while the fleet runs short — 4-of-8 devices is half
        # a worker, not a worker. Absent series (pre-scrape) falls back
        # to the raw count.
        capacity = sig["capacity"] if sig["capacity"] > 0 else workers
        queue_total = sig["queue_depth"] + sig["pending"]
        cooling = now - self._last_action_at < self.cooldown_s
        if self._last_shed is None or not cooling:
            # A cooldown hold must NOT consume observed shed growth:
            # rejections during the window (clients backing off leave
            # the queues empty) still demand the post-cooldown up.
            self._last_shed = shed
        # CONSECUTIVE quiet steps drive scale-down: any step that saw
        # shed growth or queued work zeroes the streak, whatever branch
        # it lands in (a cooldown hold under load must not count).
        quiet = shed_delta == 0 and queue_total == 0
        self._idle_steps = self._idle_steps + 1 if quiet else 0
        action, reason = "hold", "signals nominal"
        if workers < self.min_workers:
            action = "up"
            reason = f"below min_workers {self.min_workers}"
        elif cooling:
            reason = "cooldown"
        elif shed_delta > 0 and workers < self.max_workers:
            action = "up"
            reason = f"shed grew by {shed_delta:g} since last step"
        elif (queue_total > self.queue_high * max(capacity, 1.0)
                and workers < self.max_workers):
            action = "up"
            reason = (f"queue depth {queue_total:g} > "
                      f"{self.queue_high:g}/worker"
                      + (f" (capacity-weighted: {capacity:g} of "
                         f"{workers} workers)"
                         if capacity < workers else ""))
        elif (quiet and self._idle_steps >= self.down_idle_steps
                and workers > self.min_workers):
            action = "down"
            reason = f"{self._idle_steps} idle steps"
        if action != "hold":
            self._idle_steps = 0
            self._last_action_at = now
        target = workers + (1 if action == "up" else
                            -1 if action == "down" else 0)
        target = min(max(target, self.min_workers), self.max_workers)
        record = {"ts": round(time.time(), 3), "action": action,
                  "reason": reason, "workers": workers, "target": target,
                  "signals": {k: round(v, 4) for k, v in sig.items()}}
        if action != "hold":
            with self.fleet._lock:
                self.fleet._scale_decisions.append(record)
                del self.fleet._scale_decisions[:-64]
            obs.metrics.inc("fleet.scale_decisions")
            obs.event("fleet.scale_decision", **record)
            obs.notice(f"fleet: scale {action} {workers} -> {target} "
                       f"({reason})", name="fleet.scale_notice")
            from ..obs import flightrec
            flightrec.trigger("scale_decision",
                              f"{action} {workers} -> {target}: {reason}",
                              **record["signals"])
            self.fleet.scale_to(target)
        return record
