"""The long-lived FFT server: admission control, coalescing, circuits.

``Server`` is the interactive-traffic successor of the reference's
batch-era L6 launcher (``launch.py`` + JSON job specs): one resident
process that keeps compiled plans hot and answers 2D image AND 3D
volume FFT requests under an explicit robustness envelope. Images
coalesce into batched2d stacked execution; volumes (ISSUE 20) execute
SINGLE-SHOT through the slab/pencil plan families — no coalescing yet
(those families have no batch axis to stack along), but the same
admission, deadline, circuit-breaker and drain envelope applies. The
request path:

1. **Admission** (``submit``; caller's thread, microseconds): a closed or
   draining server rejects with :class:`ServerClosed`; a key whose
   circuit is open rejects with ``CircuitOpen``; then the BOUNDED queue
   sheds load — queue full, estimated queue delay (depth x per-request
   EMA) over the latency budget, or over the request's own deadline —
   with a structured :class:`Overloaded` carrying the numbers the client
   needs to back off. Queueing is never unbounded latency.
2. **Coalescing** (worker thread): the queue head is batched with every
   queued request that shares its coalescing key (shape/dtype/transform,
   ``plancache.request_key``) and direction, up to ``max_coalesce``; the
   stack executes as ONE ``Batched2DFFTPlan`` program from the LRU plan
   cache (power-of-two batch buckets; ``batch_chunk=1`` by default, the
   per-plane ``lax.map`` rendering — bit-identical to single-shot
   execution AND the measured winner at large planes, bench 2026-07-31).
3. **Execution envelope**: per-request deadlines propagate cooperatively
   (``resilience.deadline.scope``) into the PR 5 fallback ladder, an
   expired request is answered ``DeadlineExceeded`` WITHOUT executing,
   and the whole batch runs inside the per-key circuit breaker — K
   consecutive failures open the circuit (fast structured rejection,
   plan-cache entries invalidated so the half-open probe rebuilds),
   transitions land in the event log as ``serve.circuit.*``.
4. **Observability**: ``health()`` is the readiness snapshot (status,
   queue depth, shed counts, per-circuit state, plan-cache hit rate, the
   PR 4 metrics registry); every decision is an ``obs`` event/metric.
5. **Drain** (``close(drain=True)`` — the CLI's SIGTERM handler): stop
   admitting (new submits get ``ServerClosed``), finish everything
   already admitted, then stop the worker and emit ``serve.drain`` /
   ``serve.stop``. Wisdom writes and event-log lines are flushed as they
   happen (atomic replace / per-line append), so a drained process
   leaves no buffered state behind.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import obs
from .. import params as pm
from ..parallel import mesh as pmesh
from ..resilience import deadline as dl
from ..resilience import inject
from ..resilience.circuit import CircuitBreaker
from ..resilience.deadline import Deadline, DeadlineExceeded
from . import plancache


class Overloaded(RuntimeError):
    """Structured load-shed rejection: the request was NOT admitted.
    ``reason`` is ``queue_full`` | ``latency_budget`` | ``deadline`` —
    the queue would have held it longer than the budget (or its own
    deadline) allows."""

    def __init__(self, reason: str, queue_depth: int, est_delay_ms: float,
                 budget_ms: float):
        super().__init__(
            f"overloaded ({reason}): queue depth {queue_depth}, estimated "
            f"delay {est_delay_ms:.1f} ms, budget {budget_ms:.1f} ms")
        self.reason = reason
        self.queue_depth = int(queue_depth)
        self.est_delay_ms = float(est_delay_ms)
        self.budget_ms = float(budget_ms)


class ServerClosed(RuntimeError):
    """The server is draining or stopped; no new work is admitted."""


@dataclasses.dataclass
class _Request:
    x: np.ndarray
    nx: int
    ny: int
    transform: str
    double: bool
    direction: str
    base_key: str
    deadline: Optional[Deadline]
    future: Future
    submitted_at: float
    trace_id: str = ""
    nz: Optional[int] = None        # 3D volumes only (ISSUE 20)
    decomp: Optional[str] = None    # slab | pencil, volumes only

    @property
    def volume(self) -> bool:
        return self.nz is not None

    def coalesce_key(self) -> Tuple[str, str]:
        return (self.base_key, self.direction)


def normalize_request(x: Any, transform: str, direction: str,
                      ny: Optional[int]
                      ) -> Tuple[np.ndarray, Tuple[int, ...], bool]:
    """Validate one request payload; returns ``(x, shape, double)`` with
    ``shape`` the LOGICAL extents — ``(nx, ny)`` for a 2D image,
    ``(nx, ny, nz)`` for a 3D volume (ISSUE 20). ``ny`` names the
    logical extent of the HALVED LAST axis (y for images, z for
    volumes), needed to key/construct the plan — a spectral r2c payload
    alone cannot distinguish an even/odd last extent, so inverse r2c
    callers may pass it; default assumes even. Module-level so the
    fleet router (``fleet.py``) validates and keys requests with EXACTLY
    the vocabulary each worker's ``Server`` will use."""
    if transform not in ("r2c", "c2c"):
        raise ValueError(f"transform must be r2c|c2c, got {transform!r}")
    if direction not in ("forward", "inverse"):
        raise ValueError(
            f"direction must be forward|inverse, got {direction!r}")
    x = np.asarray(x)
    if x.ndim not in (2, 3):
        raise ValueError(
            f"serve requests are single 2D images or 3D volumes, got "
            f"shape {x.shape} (batching is the server's job — submit "
            "images concurrently and they coalesce; volumes execute "
            "single-shot)")
    complex_in = (transform == "c2c") or (direction == "inverse")
    if complex_in != np.iscomplexobj(x):
        raise ValueError(
            f"{transform} {direction} expects a "
            f"{'complex' if complex_in else 'real'} payload, got "
            f"dtype {x.dtype}")
    double = x.dtype in (np.float64, np.complex128)
    if transform == "c2c" or direction == "forward":
        shape = tuple(int(s) for s in x.shape)
        if ny is not None and int(ny) != shape[-1]:
            raise ValueError(f"ny {ny} disagrees with payload {x.shape}")
        return x, shape, double
    # inverse r2c: the LAST axis is spectral (n_last//2 + 1)
    ns = int(x.shape[-1])
    n_last = int(ny) if ny is not None else 2 * (ns - 1)
    if n_last // 2 + 1 != ns:
        raise ValueError(
            f"ny {n_last} inconsistent with spectral payload {x.shape} "
            f"(expects ny//2+1 == {ns})")
    return x, tuple(int(s) for s in x.shape[:-1]) + (n_last,), double


_EMA_ALPHA = 0.2

# Per-process trace-id counter: ids are ``<pid hex>-<seq hex>`` — unique
# within a fleet (pid disambiguates workers) and cheap (no uuid entropy
# on the admission path).
_TRACE_SEQ = [0]
_TRACE_LOCK = threading.Lock()

# Shed-burst detection window for the flight-recorder trigger: this many
# sheds inside SHED_BURST_WINDOW_S seconds dump the ring once per
# cooldown ($DFFT_FLIGHTREC_SHED_BURST overrides the count).
SHED_BURST_WINDOW_S = 2.0
SHED_BURST_DEFAULT = 10


def _new_trace_id() -> str:
    with _TRACE_LOCK:
        _TRACE_SEQ[0] += 1
        return f"{os.getpid():x}-{_TRACE_SEQ[0]:06x}"


def settle_future(fut: Future, *, result: Any = None,
                  exc: Optional[BaseException] = None) -> bool:
    """Resolve ``fut`` exactly once against a CONCURRENT resolver. The
    ``done()`` pre-check alone is check-then-act: close() answering a
    timed-out worker's popped batch races the still-running worker
    delivering the same futures, and both sides can pass ``done()``
    before either sets — the loser's ``set_*`` raises
    ``InvalidStateError``. Swallowing it here makes every resolution
    site atomic (first writer wins, the loser reports False)."""
    if fut.done():
        return False
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(result)
    except InvalidStateError:
        return False
    return True


class Server:
    """In-process FFT-as-a-service core (see module docstring).

    Parameters mirror a production serving config: ``max_queue`` bounds
    the admission queue, ``latency_budget_ms`` is the shed threshold on
    estimated queue delay, ``max_coalesce`` caps the stacked batch,
    ``circuit_k``/``circuit_cooldown_s`` parameterize the per-key
    breaker, and ``config`` is the Config TEMPLATE every served plan is
    built from (wire/guards/comm surface; ``double_prec`` is overridden
    per request from the payload dtype). ``shard`` picks the batched2d
    decomposition: ``"batch"`` (default — embarrassingly parallel,
    coalescing-friendly) or ``"x"`` (slab-style with a real exchange —
    the decomposition the chaos drill targets with wire faults).
    ``volume_decomp`` is the default 3D decomposition (``slab`` |
    ``pencil``) a volume request executes on when it does not name one
    itself."""

    def __init__(self, partition: Optional[pm.SlabPartition] = None,
                 config: Optional[pm.Config] = None, mesh: Any = None,
                 shard: str = "batch", *, max_queue: int = 64,
                 latency_budget_ms: float = 1000.0, max_coalesce: int = 8,
                 batch_chunk: Optional[int] = 1, cache_capacity: int = 8,
                 circuit_k: int = 3, circuit_cooldown_s: float = 5.0,
                 volume_decomp: str = "slab", name: str = "dfft-serve"):
        if shard not in ("batch", "x"):
            raise ValueError(f"shard must be 'batch' or 'x', got {shard!r}")
        if volume_decomp not in plancache.VOLUME_DECOMPS:
            raise ValueError(
                f"volume_decomp must be slab|pencil, got {volume_decomp!r}")
        if max_queue < 1 or max_coalesce < 1:
            raise ValueError("max_queue and max_coalesce must be >= 1")
        self.partition = partition or pm.SlabPartition(1)
        self.config = config or pm.Config()
        self.mesh = mesh
        self.shard = shard
        self.volume_decomp = volume_decomp
        self.max_queue = int(max_queue)
        self.latency_budget_ms = float(latency_budget_ms)
        self.max_coalesce = int(max_coalesce)
        self.batch_chunk = batch_chunk if shard == "batch" else None
        self.circuit_k = int(circuit_k)
        self.circuit_cooldown_s = float(circuit_cooldown_s)
        self.name = name
        self.cache = plancache.PlanCache(cache_capacity)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending: List[_Request] = []
        self._inflight_reqs: List[_Request] = []
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._ema_ms: Optional[float] = None
        self._state = "running"  # running | draining | stopped
        self._started_at = time.monotonic()
        self._counts = {"admitted": 0, "served": 0, "shed": 0,
                        "rejected_closed": 0, "rejected_circuit": 0,
                        "deadline_expired": 0, "batches": 0,
                        "batch_failures": 0, "coalesced": 0}
        self._inflight = 0
        self._shed_times: collections.deque = collections.deque()
        self._resident: Optional[Any] = None  # attach_resident()
        obs.event("serve.start", server=name, shard=shard,
                  ranks=self.partition.num_ranks, max_queue=max_queue,
                  latency_budget_ms=latency_budget_ms,
                  max_coalesce=max_coalesce, circuit_k=circuit_k)
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name=f"{name}-worker")
        self._worker.start()

    # -- context manager ---------------------------------------------------

    def __enter__(self) -> "Server":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close(drain=True)

    # -- admission ---------------------------------------------------------

    def _normalize(self, x: Any, transform: str, direction: str,
                   ny: Optional[int]
                   ) -> Tuple[np.ndarray, Tuple[int, ...], bool]:
        return normalize_request(x, transform, direction, ny)

    def _breaker(self, key: str) -> CircuitBreaker:
        """Caller holds the lock. The map is BOUNDED like the plan cache
        (an adversarial shape sweep must not grow server memory or the
        /healthz payload without limit): over the cap, idle breakers —
        closed with zero consecutive failures, i.e. carrying no state
        worth keeping — are pruned; open/half-open/failing ones always
        survive."""
        b = self._breakers.get(key)
        if b is None:
            cap = max(64, 8 * self.cache.capacity)
            if len(self._breakers) >= cap:
                for k in [k for k, v in self._breakers.items()
                          if v.state == "closed"
                          and v.snapshot()["consecutive_failures"] == 0]:
                    del self._breakers[k]
            b = CircuitBreaker(key, self.circuit_k, self.circuit_cooldown_s,
                               metrics_prefix="serve.circuit")
            self._breakers[key] = b
        return b

    def _shed(self, reason: str, depth: int, est_ms: float,
              budget_ms: float) -> Overloaded:
        self._counts["shed"] += 1
        obs.metrics.inc("serve.shed")
        obs.event("serve.shed", reason=reason, queue_depth=depth,
                  est_delay_ms=round(est_ms, 2),
                  budget_ms=round(budget_ms, 2))
        # Shed-burst flight-recorder trigger: a sustained rejection storm
        # dumps the ring once per cooldown window — "here is the queue /
        # EMA / circuit state of the seconds that led to it".
        now = time.monotonic()
        self._shed_times.append(now)
        while self._shed_times and now - self._shed_times[0] \
                > SHED_BURST_WINDOW_S:
            self._shed_times.popleft()
        try:
            burst = int(os.environ.get("DFFT_FLIGHTREC_SHED_BURST",
                                       str(SHED_BURST_DEFAULT)))
        except ValueError:
            burst = SHED_BURST_DEFAULT
        if burst > 0 and len(self._shed_times) >= burst:
            from ..obs import flightrec
            flightrec.trigger(
                "shed_burst",
                f"{len(self._shed_times)} sheds in "
                f"{SHED_BURST_WINDOW_S:.0f}s (last: {reason})",
                queue_depth=depth, budget_ms=budget_ms)
        return Overloaded(reason, depth, est_ms, budget_ms)

    def submit(self, x: Any, transform: str = "r2c",
               direction: str = "forward", *, ny: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               decomp: Optional[str] = None) -> Future:
        """Admit one FFT request — a single 2D image (coalescing-
        eligible) or a 3D volume (ISSUE 20: keyed ``fft3d/...``, executed
        SINGLE-SHOT through the slab/pencil plan families; ``decomp``
        overrides the server's ``volume_decomp`` default). Returns a
        ``Future`` resolving to the result array, or raising the
        structured rejection (:class:`Overloaded` / ``CircuitOpen`` /
        :class:`ServerClosed` / ``DeadlineExceeded``). Admission itself
        raises — a rejected request never occupies the queue."""
        x, shape, double = self._normalize(x, transform, direction, ny)
        code = "f64" if double else "f32"
        if len(shape) == 3:
            dec = decomp or self.volume_decomp
            key = plancache.request_key3d(
                shape[0], shape[1], shape[2], code, transform, dec)
            nz: Optional[int] = shape[2]
        else:
            if decomp is not None:
                raise ValueError("decomp applies to 3D volume requests "
                                 f"only, got a {len(shape)}D payload")
            key = plancache.request_key(
                shape[0], shape[1], code, transform, self.shard)
            dec, nz = None, None
        deadline = (Deadline.after_ms(deadline_ms)
                    if deadline_ms is not None else None)
        with self._lock:
            if self._state != "running":
                self._counts["rejected_closed"] += 1
                obs.metrics.inc("serve.rejected_closed")
                raise ServerClosed(f"server is {self._state}; "
                                   "not admitting new requests")
            breaker = self._breaker(key)
            if (breaker.state == "open"
                    and breaker.retry_after_s() > 0):
                self._counts["rejected_circuit"] += 1
                raise breaker.reject()
            depth = len(self._pending) + self._inflight
            est_ms = (depth * self._ema_ms) if self._ema_ms else 0.0
            if len(self._pending) >= self.max_queue:
                # est_ms (not inf): the rejection must serialize as
                # strict JSON in the HTTP 429 body and the event log.
                raise self._shed("queue_full", depth, est_ms,
                                 self.latency_budget_ms)
            if est_ms > self.latency_budget_ms:
                raise self._shed("latency_budget", depth, est_ms,
                                 self.latency_budget_ms)
            if deadline is not None and est_ms >= deadline.remaining_ms():
                raise self._shed("deadline", depth, est_ms,
                                 deadline.remaining_ms())
            fut: Future = Future()
            tid = _new_trace_id()
            req = _Request(x=x, nx=shape[0], ny=shape[1],
                           transform=transform, double=double,
                           direction=direction, base_key=key,
                           deadline=deadline, future=fut,
                           submitted_at=time.monotonic(), trace_id=tid,
                           nz=nz, decomp=dec)
            # The id rides the future so callers (the HTTP front end's
            # X-DFFT-Trace header) can hand it back to the client.
            fut.trace_id = tid  # type: ignore[attr-defined]
            self._pending.append(req)
            self._counts["admitted"] += 1
            obs.metrics.inc("serve.requests")
            obs.metrics.gauge("serve.queue_depth", len(self._pending))
            obs.event("serve.admit", trace=tid, key=key,
                      direction=direction,
                      queue_depth=len(self._pending))
            self._cv.notify()
            return fut

    def request(self, x: Any, transform: str = "r2c",
                direction: str = "forward", *, ny: Optional[int] = None,
                deadline_ms: Optional[float] = None,
                decomp: Optional[str] = None,
                timeout_s: Optional[float] = None) -> np.ndarray:
        """Blocking convenience wrapper over :meth:`submit`."""
        return self.submit(x, transform, direction, ny=ny,
                           deadline_ms=deadline_ms,
                           decomp=decomp).result(timeout_s)

    # -- worker ------------------------------------------------------------

    def _take_batch(self) -> List[_Request]:
        """Caller holds the lock: pop the queue head plus every queued
        request sharing its coalescing key and direction (FIFO order
        within the key), up to ``max_coalesce``."""
        head = self._pending.pop(0)
        batch = [head]
        # Volumes execute SINGLE-SHOT (no coalescing yet, documented):
        # the slab/pencil plan families have no batch axis to stack
        # along, so a volume head takes the worker alone and every other
        # queued request stays put.
        if self.max_coalesce > 1 and not head.volume:
            keep: List[_Request] = []
            for r in self._pending:
                if (len(batch) < self.max_coalesce
                        and r.coalesce_key() == head.coalesce_key()):
                    batch.append(r)
                else:
                    keep.append(r)
            self._pending = keep
        obs.metrics.gauge("serve.queue_depth", len(self._pending))
        self._inflight = len(batch)
        # Held until the worker clears it after execution (deliberately
        # NO finally in _run — see the comment there) so close() can
        # answer these futures too if the worker thread dies
        # mid-execution — a popped batch must be as loss-proof as the
        # queue it came from.
        self._inflight_reqs = batch
        obs.event("serve.coalesce", key=head.base_key, n=len(batch),
                  traces=[r.trace_id for r in batch])
        return batch

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._pending and self._state == "running":
                    self._cv.wait(0.05)
                if not self._pending:
                    break  # draining/stopped and drained
                batch = self._take_batch()
            try:
                self._execute(batch)
            except Exception as err:  # noqa: BLE001 — the worker is the
                # only serving thread: ANY escape (a malformed fault spec
                # raising in the injector, an obs path failing) must fail
                # THIS batch loudly and keep serving, never die silently
                # with futures dangling and close() left to hang.
                obs.metrics.inc("serve.batch_failures")
                obs.notice(
                    f"serve: worker error outside the execution envelope "
                    f"({type(err).__name__}: {err})"[:300],
                    name="serve.worker_error")
                for r in batch:
                    settle_future(r.future, exc=err)
            # Deliberately NOT a finally: on a BaseException killing the
            # thread itself (SystemExit et al.) the popped batch must
            # STAY in _inflight_reqs so close() can answer its futures
            # with ServerClosed instead of leaving them dangling.
            with self._lock:
                self._inflight = 0
                self._inflight_reqs = []

    def _expire(self, req: _Request, detail: str) -> None:
        self._counts["deadline_expired"] += 1
        obs.metrics.inc("serve.deadline_expired")
        over = -req.deadline.remaining_ms() if req.deadline else 0.0
        obs.event("serve.deadline_expired", key=req.base_key, detail=detail,
                  overrun_ms=round(over, 2), trace=req.trace_id)
        obs.event("serve.reply", trace=req.trace_id,
                  outcome="deadline_expired")
        # settle_future (here and at every resolution site): close()
        # answers a timed-out worker's popped batch with ServerClosed,
        # and a SLOW worker finishing later must not InvalidStateError
        # mid-delivery.
        settle_future(req.future, exc=DeadlineExceeded(
            f"deadline exceeded by {over:.1f} ms ({detail})",
            detail=detail, overrun_ms=over))

    def _make_plan(self, nx: int, ny: int, transform: str, double: bool,
                   bucket: int) -> Any:
        from ..models.batched2d import Batched2DFFTPlan
        cfg = dataclasses.replace(self.config, double_prec=double)
        ck = self.batch_chunk
        if ck:
            # batch_chunk must divide the plan's LOCAL padded batch
            # (models/batched2d.py contract); a configured chunk larger
            # than a small bucket's local batch clamps to its largest
            # divisor — an uncoalesced request must not be unbuildable
            # under --batch-chunk > 1.
            P = self.partition.p
            local_b = bucket if P <= 1 else pm.padded_extent(bucket, P) // P
            ck = max(d for d in range(1, min(ck, local_b) + 1)
                     if local_b % d == 0)
        return Batched2DFFTPlan(
            bucket, nx, ny, self.partition, cfg, mesh=self.mesh,
            shard=self.shard, transform=transform, batch_chunk=ck)

    def _build_plan(self, req: _Request, bucket: int) -> Any:
        return self._make_plan(req.nx, req.ny, req.transform, req.double,
                               bucket)

    def _make_volume_plan(self, nx: int, ny: int, nz: int, transform: str,
                          double: bool, decomp: str) -> Any:
        """Build the single-shot 3D plan a volume request executes on:
        the server's partition width spread over the slab x-axis, or its
        most-square (p1, p2) pencil factorization. The plan constructs
        its own mesh (``make_slab_mesh``/``make_pencil_mesh``) from the
        process's visible devices — the server's 2D ``mesh`` (if any)
        has the wrong axis names for the 3D families."""
        from ..models.pencil import PencilFFTPlan
        from ..models.slab import SlabFFTPlan
        from ..parallel.mesh import best_pencil_grid
        cfg = dataclasses.replace(self.config, double_prec=double)
        g = pm.GlobalSize(nx, ny, nz)
        p = self.partition.p
        if decomp == "slab":
            return SlabFFTPlan(g, pm.SlabPartition(p), cfg,
                               transform=transform)
        p1, p2 = best_pencil_grid(p)
        return PencilFFTPlan(g, pm.PencilPartition(p1, p2), cfg,
                             transform=transform)

    def prewarm(self, shape: Tuple[int, ...], dtype: Any = None,
                transform: str = "r2c", *,
                directions: Tuple[str, ...] = ("forward",),
                decomp: Optional[str] = None) -> int:
        """Build + compile the plan-cache slots one traffic shape needs —
        every power-of-two coalescing bucket up to ``max_coalesce`` for a
        2D image shape, the ONE single-shot slab/pencil plan for a 3D
        volume shape — BEFORE traffic arrives, so no request ever stalls
        behind a lazy compile (a rolling restart calls this between bind
        and ready; a fleet replacement calls it with the dead worker's
        hot shapes, including volumes rebuilt on whatever mesh it
        actually acquired). Runs in the caller's thread against the
        shared cache; call it before serving traffic, not during.
        Returns the number of plans newly built."""
        if len(shape) == 3:
            return self._prewarm_volume(shape, dtype, transform,
                                        directions=directions,
                                        decomp=decomp)
        nx, ny = int(shape[0]), int(shape[1])
        dt = np.dtype(dtype) if dtype is not None else np.dtype(np.float32)
        double = dt in (np.float64, np.complex128)
        key = plancache.request_key(nx, ny, "f64" if double else "f32",
                                    transform, self.shard)
        built = 0
        # Enumerate exactly the buckets bucket_for can produce (powers of
        # two through the pow2 CEILING of max_coalesce).
        top = plancache.bucket_for(self.max_coalesce, self.max_coalesce)
        b = 1
        while b <= top:
            ckey = plancache.cache_key(key, b)
            plan, hit = self.cache.get_or_build(
                ckey, lambda b=b: self._make_plan(nx, ny, transform,
                                                  double, b))
            if not hit:
                built += 1
            if transform == "c2c":
                cdt = np.complex128 if double else np.complex64
                x = np.zeros((b, nx, ny), cdt)
            else:
                x = np.zeros((b, nx, ny),
                             np.float64 if double else np.float32)
            # DEVICE_LOCK: a fleet replacement prewarms the dead
            # worker's hot shapes AFTER its restored resident already
            # steps on another thread — same mesh, same rendezvous
            # hazard as _execute.
            with pmesh.DEVICE_LOCK:
                if "forward" in directions:
                    np.asarray(plan.exec_forward(x))
                if "inverse" in directions:
                    if transform == "c2c":
                        np.asarray(plan.exec_inverse(
                            np.zeros((b, nx, ny),
                                     np.complex128 if double
                                     else np.complex64)))
                    else:
                        np.asarray(plan.exec_inverse(
                            np.zeros((b, nx, ny // 2 + 1),
                                     np.complex128 if double
                                     else np.complex64)))
            b <<= 1
        obs.event("serve.prewarm", key=key, built=built,
                  directions=list(directions))
        return built

    def _prewarm_volume(self, shape: Tuple[int, ...], dtype: Any,
                        transform: str, *, directions: Tuple[str, ...],
                        decomp: Optional[str]) -> int:
        nx, ny, nz = (int(s) for s in shape)
        dt = np.dtype(dtype) if dtype is not None else np.dtype(np.float32)
        double = dt in (np.float64, np.complex128)
        dec = decomp or self.volume_decomp
        key = plancache.request_key3d(nx, ny, nz,
                                      "f64" if double else "f32",
                                      transform, dec)
        plan, hit = self.cache.get_or_build(
            key, lambda: self._make_volume_plan(nx, ny, nz, transform,
                                                double, dec))
        cdt = np.complex128 if double else np.complex64
        rdt = np.float64 if double else np.float32
        # DEVICE_LOCK: see prewarm — the replacement-worker path runs
        # this concurrently with a stepping resident on the same mesh.
        with pmesh.DEVICE_LOCK:
            if "forward" in directions:
                x = np.zeros((nx, ny, nz),
                             cdt if transform == "c2c" else rdt)
                np.asarray(plan.exec_c2c(x) if transform == "c2c"
                           else plan.exec_r2c(x))
            if "inverse" in directions:
                c = np.zeros(plan.output_shape, cdt)
                np.asarray(plan.exec_c2c_inv(c) if transform == "c2c"
                           else plan.exec_c2r(c))
        obs.event("serve.prewarm", key=key, built=0 if hit else 1,
                  directions=list(directions))
        return 0 if hit else 1

    def _execute(self, batch: List[_Request]) -> None:
        key = batch[0].base_key
        with self._lock:
            breaker = self._breaker(key)
        if not breaker.allow():
            with self._lock:
                self._counts["rejected_circuit"] += len(batch)
            for r in batch:
                settle_future(r.future, exc=breaker.reject())
            return
        try:
            # The injected straggler (server:slow) ages the batch BEFORE
            # the expiry check, exactly like a slow host would — expired
            # requests then never execute (the test pins this).
            inject.maybe_slow_server("serve.execute")
            alive = []
            for r in batch:
                if r.deadline is not None and r.deadline.expired():
                    self._expire(r, "queued")
                else:
                    alive.append(r)
        except Exception:
            # An escape BETWEEN a successful allow() and the execution
            # envelope (e.g. a malformed fault spec raising inside the
            # injector) must release the probe slot without a verdict —
            # a leaked slot would wedge a half-open circuit forever.
            breaker.release()
            raise  # _run's guard fails the batch and keeps serving
        if not alive:
            # Nothing executed: the breaker's probe slot (if this was
            # one) must be released without a verdict about the plan.
            breaker.release()
            return
        # Queue-wait distribution (admission -> execution start), per
        # surviving request — the histogram the /metrics scrape exposes
        # next to the EMA the shedder estimates from.
        now_mono = time.monotonic()
        for r in alive:
            obs.metrics.observe("serve.queue_wait_ms",
                                (now_mono - r.submitted_at) * 1e3)
        t0 = time.perf_counter()
        head = alive[0]
        volume = head.volume
        try:
            n = len(alive)
            if volume:
                # Single-shot: no bucket axis, the request key IS the
                # cache slot, and the payload executes unstacked through
                # the slab/pencil family.
                bucket, ckey = 1, key
                plan, hit = self.cache.get_or_build(
                    key, lambda: self._make_volume_plan(
                        head.nx, head.ny, head.nz, head.transform,
                        head.double, head.decomp))
                stack = head.x
            else:
                bucket = plancache.bucket_for(n, self.max_coalesce)
                ckey = plancache.cache_key(key, bucket)
                plan, hit = self.cache.get_or_build(
                    ckey, lambda: self._build_plan(alive[0], bucket))
                stack = np.stack([r.x for r in alive])
                if bucket > n:
                    pad = np.zeros((bucket - n,) + stack.shape[1:],
                                   stack.dtype)
                    stack = np.concatenate([stack, pad])
            # The ladder scope gets the LOOSEST member deadline: expiry
            # is enforced per request before and after execution, so the
            # ambient deadline exists only to bound fallback retries —
            # one near-expired rider must not disable the ladder for the
            # whole coalesced batch (and feed its joint failure to the
            # breaker). A member WITHOUT a deadline keeps the scope open
            # (the env-level fallback horizon still applies).
            batch_dl: Optional[Deadline] = None
            if all(r.deadline is not None for r in alive):
                batch_dl = max((r.deadline for r in alive),
                               key=lambda d: d.expires_at)
            # DEVICE_LOCK: a resident solver stepping on its own thread
            # shares this worker's device mesh — interleaved collectives
            # from two threads deadlock XLA's in-process rendezvous
            # (see parallel.mesh.DEVICE_LOCK). Lock wait counts into the
            # request's measured latency: callers really do queue behind
            # the resident's current step.
            with pmesh.DEVICE_LOCK, \
                    obs.span("serve.execute", key=ckey, n=n, bucket=bucket,
                             direction=head.direction,
                             traces=[r.trace_id for r in alive]), \
                    dl.scope(batch_dl):
                fwd = head.direction == "forward"
                if volume:
                    if head.transform == "r2c":
                        out = (plan.exec_r2c(stack) if fwd
                               else plan.exec_c2r(stack))
                    else:
                        out = (plan.exec_c2c(stack) if fwd
                               else plan.exec_c2c_inv(stack))
                    # crop_* materialize to logical host arrays: the
                    # latency is real, and the padded lanes never leave
                    # the server.
                    res = (plan.crop_spectral(out) if fwd
                           else plan.crop_real(out))
                elif fwd:
                    out = plan.exec_forward(stack)
                    res = np.asarray(out)  # materialize
                else:
                    out = plan.exec_inverse(stack)
                    res = np.asarray(out)
        except Exception as err:  # noqa: BLE001 — every failure is a verdict
            opened = breaker.record_failure(err)
            if opened:
                self.cache.invalidate_prefix(key)
                # Circuit-open flight-recorder trigger: the dump carries
                # the admissions, batch events and metric deltas that
                # led to the K-th consecutive failure.
                from ..obs import flightrec
                flightrec.trigger(
                    "circuit_open", f"{type(err).__name__}: {err}"[:200],
                    key=key)
            with self._lock:
                self._counts["batch_failures"] += 1
            obs.metrics.inc("serve.batch_failures")
            obs.event("serve.batch_failed", key=key, n=len(alive),
                      error=f"{type(err).__name__}: {err}"[:300])
            for r in alive:
                obs.event("serve.reply", trace=r.trace_id,
                          outcome="error", error=type(err).__name__)
                settle_future(r.future, exc=err)
            return
        ms = (time.perf_counter() - t0) * 1e3
        breaker.record_success()
        if hit:
            # Warm (cache-hit) per-request execution distribution; cold
            # batches are build-dominated and would swamp the histogram
            # the same way they would corrupt the shed EMA.
            obs.metrics.observe("serve.exec_ms", ms / n)
        if not volume:
            if head.direction == "forward":
                res = res[:n, :head.nx, :plan._ny_spec]
            else:
                res = res[:n, :head.nx, :head.ny]
        with self._lock:
            if hit:
                # Only warm (cache-hit) executions feed the queue-delay
                # estimator: a cold batch's latency is dominated by the
                # one-time trace+compile, and folding it in would make
                # admission shed steady-state traffic it can easily carry.
                per_req = ms / n
                self._ema_ms = (per_req if self._ema_ms is None else
                                (1 - _EMA_ALPHA) * self._ema_ms
                                + _EMA_ALPHA * per_req)
                obs.metrics.gauge("serve.ema_ms", round(self._ema_ms, 4))
            self._counts["batches"] += 1
            self._counts["served"] += n
            if n > 1:
                self._counts["coalesced"] += n
        obs.metrics.inc("serve.batches")
        obs.metrics.inc("serve.requests_served", n)
        if n > 1:
            obs.metrics.inc("serve.coalesced_requests", n)
        obs.event("serve.batch", key=ckey, n=n, bucket=bucket,
                  ms=round(ms, 3), cache_hit=hit)
        done_mono = time.monotonic()
        for i, r in enumerate(alive):
            if r.deadline is not None and r.deadline.expired():
                # The result exists but arrived too late: a deadline is a
                # promise, and a late success is reported as expiry.
                self._expire(r, "executing")
            else:
                obs.metrics.observe("serve.e2e_ms",
                                    (done_mono - r.submitted_at) * 1e3)
                obs.event("serve.reply", trace=r.trace_id, outcome="ok",
                          coalesced_n=n)
                settle_future(r.future,
                              result=res if volume else np.array(res[i]))

    # -- resident solver tenant (ISSUE 14) ---------------------------------

    def attach_resident(self, resident: Any) -> None:
        """Host a :class:`~.resident.ResidentSolver`: start its stepping
        thread and own its lifecycle — ``close(drain=True)`` stops it
        THROUGH its drain-checkpoint path (the policy's ``drain:on``
        writes a final generation), so a SIGTERM'd or scaled-down server
        leaves resumable state behind; ``health()`` gains a
        ``resident`` block."""
        with self._lock:
            if self._resident is not None:
                raise RuntimeError("a resident solver is already attached")
            self._resident = resident
        resident.start()

    @property
    def resident(self) -> Optional[Any]:
        return self._resident

    # -- health / lifecycle ------------------------------------------------

    def health(self) -> Dict[str, Any]:
        """The readiness snapshot (the ``/healthz`` payload): overall
        status (``ok`` | ``degraded`` — any circuit not closed — |
        ``draining`` | ``stopped``), queue occupancy, shed/expiry
        counters, per-circuit state, plan-cache hit rate, and the PR 4
        metrics registry."""
        with self._lock:
            circuits = {k: b.snapshot() for k, b in self._breakers.items()}
            degraded = any(c["state"] != "closed"
                           for c in circuits.values())
            status = (self._state if self._state != "running"
                      else ("degraded" if degraded else "ok"))
            snap = {
                "status": status,
                "uptime_s": round(time.monotonic() - self._started_at, 3),
                "queue_depth": len(self._pending),
                "inflight": self._inflight,
                "max_queue": self.max_queue,
                "latency_budget_ms": self.latency_budget_ms,
                "max_coalesce": self.max_coalesce,
                "ema_ms": (round(self._ema_ms, 4)
                           if self._ema_ms is not None else None),
                "counters": dict(self._counts),
                "circuits": circuits,
            }
        snap["plan_cache"] = self.cache.snapshot()
        snap["obs_metrics"] = obs.snapshot()
        # Resident simulation (ISSUE 14): step progress + checkpoint
        # registry, so /healthz shows how far the standing tenant is and
        # where (and how fresh) its durable state lives.
        res = self._resident
        if res is not None:
            snap["resident"] = res.status()
        # Flight recorder (ISSUE 12): ring occupancy + the most recent
        # triggered dump's path, so an operator reading /healthz knows
        # where the post-mortem evidence landed.
        from ..obs import flightrec
        snap["flight_recorder"] = dict(flightrec.stats(),
                                       last_dump=flightrec.last_dump())
        return snap

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def close(self, drain: bool = True, timeout_s: float = 60.0) -> None:
        """Stop the server. ``drain=True`` (the SIGTERM path): reject new
        submits, FINISH everything already admitted, then stop.
        ``drain=False``: stop now; queued requests fail with
        :class:`ServerClosed`. Idempotent. Wisdom records and event-log
        lines were flushed as they were written (atomic replace /
        per-line append); the final ``serve.stop`` event carries the
        counter totals as the run's closing record."""
        # The resident stops FIRST, through its drain-checkpoint path
        # (drain=True + policy drain:on writes the final generation) —
        # its state must be on disk before the process can be reaped.
        res = self._resident
        if res is not None:
            res.stop(checkpoint=drain)
        with self._cv:
            if self._state == "stopped":
                return
            already_draining = self._state == "draining"
            self._state = "draining"
            pending = len(self._pending)
            if not already_draining:
                # notice() both prints (--obs) and logs ONE serve.drain
                # event carrying the structured attrs.
                obs.notice(f"serve: draining ({pending} queued, "
                           f"drain={drain})", name="serve.drain",
                           drain=drain, pending=pending)
            if not drain:
                for r in self._pending:
                    settle_future(r.future, exc=ServerClosed(
                        "server closed before execution"))
                self._pending.clear()
            self._cv.notify_all()
        self._worker.join(timeout_s)
        with self._cv:
            self._state = "stopped"
            # Worker died/timed out: everything it left behind — queued
            # requests AND the batch it had already popped — must be
            # answered with a structured ServerClosed, never dropped.
            leftovers = self._pending + self._inflight_reqs
            self._pending = []
            self._inflight_reqs = []
        for r in leftovers:
            settle_future(r.future, exc=ServerClosed(
                "server stopped before execution"))
        obs.notice(f"serve: stopped ({self._counts['served']} served, "
                   f"{self._counts['shed']} shed)", name="serve.stop",
                   counters=dict(self._counts))
