"""FFT-as-a-service: the hardened long-lived serving layer (ISSUE 8).

The reference's L6 launcher (``launch.py`` + JSON job specs) is a
batch-era surface: build a plan, run a job, exit. The million-user north
star (ROADMAP item 2) needs its interactive-traffic successor — one
resident process that keeps compiled plans hot and survives real traffic
and real faults. Three pieces:

* ``plancache`` — bounded LRU of live plans, keyed like wisdom plus the
  coalescing batch bucket; a cache hit performs zero recompiles.
* ``server``   — :class:`Server`: deadline-aware admission control with
  load shedding (structured :class:`Overloaded`, never unbounded
  latency), same-shape request coalescing into ``batched2d`` stacked
  execution, a per-key circuit breaker around the PR 5 fallback ladder,
  a health/readiness snapshot over the PR 4 metrics registry, and
  graceful drain.
* ``cli``      — the ``dfft-serve`` executable: ``--drive`` runs the
  open-loop load generator (``testing/workloads.serve_load``) against an
  in-process server (the chaos-CI and saturation-bench surface);
  ``--http`` serves ``/healthz`` / ``/readyz`` / ``POST /fft`` over
  stdlib HTTP.

The chaos contract: under ``$DFFT_FAULT_SPEC`` wire faults and
``server:slow`` stragglers a live server must never hang or crash —
circuits open, load sheds, deadlines expire, and every transition leaves
``serve.*`` evidence in the obs event log (CI's serve chaos job asserts
exactly that).
"""

from . import plancache
from .fleet import Fleet, RemoteWorkerError, ScaleController
from .plancache import (PlanCache, bucket_for, cache_key,
                        parse_request_key, request_key, request_key3d)
from .resident import ResidentSolver
from .router import FairQueue, RendezvousRing, TenantPolicy
from .server import Overloaded, Server, ServerClosed, normalize_request

__all__ = [
    "FairQueue", "Fleet", "Overloaded", "PlanCache", "RemoteWorkerError",
    "RendezvousRing", "ResidentSolver", "ScaleController", "Server",
    "ServerClosed", "TenantPolicy", "bucket_for", "cache_key",
    "describe_request", "normalize_request", "parse_request_key",
    "plancache", "request_key", "request_key3d",
]


def describe_request(nx: int, ny: int, nz=None, *, double: bool = False,
                     transform: str = "r2c", shard: str = "batch",
                     decomp: str = "slab", config=None, circuit_k: int = 3,
                     circuit_cooldown_s: float = 5.0,
                     max_coalesce: int = 8) -> list:
    """The ``dfft-explain`` ``serve:`` section: for one request shape,
    the plan-cache key it would occupy, its coalescing eligibility, and
    the circuit/ladder policy that would wrap its execution — all static
    (nothing is built or executed), reusing the same key and ladder
    machinery the live server uses. A 3D shape (``nz`` given) describes
    the volume form: the ``fft3d`` key family, single-shot execution on
    ``decomp``, no coalescing."""
    from ..resilience import fallback
    from ..utils.wisdom import _describe_comm
    code = "f64" if double else "f32"
    if nz is not None:
        base = request_key3d(nx, ny, int(nz), code, transform, decomp)
        lines = [
            f"  request key: {base}",
            f"  plan cache slots: {base} (single slot — volumes are "
            "single-shot, no coalescing buckets)",
            f"  coalescing: not eligible — 3D volumes execute one-shot "
            f"through the {decomp} plan family (no batch axis to stack "
            "along); concurrent volumes queue behind each other",
        ]
    else:
        base = request_key(nx, ny, code, transform, shard)
        buckets = []
        top = bucket_for(max_coalesce, max_coalesce)
        b = 1
        while b <= top:
            buckets.append(str(b))
            b <<= 1
        lines = [
            f"  request key: {base}",
            f"  plan cache slots: {base}#b{{{','.join(buckets)}}} "
            "(LRU, power-of-two coalescing buckets)",
        ]
    if nz is not None:
        pass
    elif shard == "batch":
        lines.append(
            f"  coalescing: eligible — same-key requests stack along the "
            f"batch axis (up to {max_coalesce}; batch_chunk=1 per-plane "
            "rendering, bit-identical to single-shot)")
    else:
        lines.append(
            f"  coalescing: eligible — stacked along the untouched batch "
            f"axis of the shard='x' slab pipeline (up to {max_coalesce}; "
            "whole-stack fused, exchanges per batch)")
    lines.append(
        f"  circuit: {circuit_k} consecutive failures open; half-open "
        f"probe after {circuit_cooldown_s:g} s (plan cache invalidated on "
        "open, so the probe rebuilds)")
    if config is not None:
        ladder = fallback.ladder_preview(config)
        if ladder:
            steps = " -> ".join(f"[{r}] {lbl}" for r, lbl in ladder)
            lines.append(f"  inside the circuit: fallback ladder {steps} "
                         "-> failure counts toward the breaker")
        else:
            lines.append("  inside the circuit: default rendering, no "
                         "ladder — each failure counts toward the breaker")
        lines.append(f"  served config: {_describe_comm(config)}")
    return lines
