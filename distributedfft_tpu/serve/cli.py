"""``dfft-serve`` — the long-lived FFT server as an executable.

Two complementary surfaces over one in-process :class:`Server`:

* ``--drive`` runs the open-loop load generator
  (``testing/workloads.serve_load``: Poisson arrivals, mixed
  shape/dtype traffic) against the server and prints ONE final JSON
  summary line — the surface the chaos CI job and the saturation bench
  drive. ``--health-out`` additionally writes the final health snapshot
  (the readiness document CI asserts ``degraded`` on when a fault opened
  a circuit).
* ``--http PORT`` serves the request/health API over stdlib HTTP (no new
  dependencies): ``GET /healthz`` returns the health snapshot JSON,
  ``GET /readyz`` answers 200 only while the server admits work (503
  when draining/stopped — the load-balancer contract), and
  ``POST /fft`` executes one request: body is an ``.npy`` payload
  (2D image or 3D volume), headers ``X-DFFT-Transform`` (r2c|c2c),
  ``X-DFFT-Direction`` (forward|inverse), ``X-DFFT-Ny`` (inverse r2c
  logical width of the halved last axis), ``X-DFFT-Decomp``
  (slab|pencil — volume payloads only) and ``X-DFFT-Deadline-Ms``
  select the work; rejections map to structured status codes (429
  Overloaded, 503 circuit open / closed, 504 deadline exceeded).

SIGTERM/SIGINT trigger a GRACEFUL DRAIN: in-flight and queued work
finishes, new admissions are rejected with ``ServerClosed``, wisdom and
the obs event log are already flushed (atomic replace / per-line
append), and the process exits 0 — the contract a rolling restart needs.

``--workers N`` (or ``--autoscale MIN:MAX``) promotes the process to a
**fleet** (ISSUE 13): N subprocess workers each running the Server core
behind the rendezvous plan-key router (``serve/fleet.py``), with the
heartbeat failure detector, per-tenant quotas (``--tenant-weights``) and
the metrics-driven worker-count controller. The same ``--drive``/
``--http`` surfaces apply; ``/healthz`` returns the FLEET snapshot
(workers, ring, tenants, scale decisions).

Examples::

    dfft-serve --drive --rate 50 --duration 10 --shapes 256x256,128x128 \
        --deadline-ms 500 --emulate-devices 8
    dfft-serve --http 8080 --emulate-devices 8   # curl :8080/healthz
    dfft-serve --drive --workers 3 --rate 60 --duration 10 \
        --shapes 64x64 --tenants gold,free --tenant-weights gold=3
    dfft-serve --drive --autoscale 1:4 --rate 120 --duration 20
    dfft-serve --drive --workers 3 --worker-devices 8,0,0 \
        --shapes 64x64x64,256x256 --rate 20 --duration 10
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="dfft-serve", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--partitions", "-p", type=int, default=1,
                    help="mesh width the served plans decompose over "
                         "(default 1 = single device)")
    ap.add_argument("--shard", default="batch", choices=("batch", "x"),
                    help="batched2d decomposition of served plans: "
                         "'batch' (embarrassingly parallel, default) or "
                         "'x' (slab-style with a real exchange — the "
                         "decomposition chaos drills target)")
    ap.add_argument("--max-queue", type=int, default=64,
                    help="admission queue bound (beyond it: Overloaded)")
    ap.add_argument("--latency-budget-ms", type=float, default=1000.0,
                    help="shed when estimated queue delay exceeds this")
    ap.add_argument("--max-coalesce", type=int, default=8,
                    help="max same-shape requests stacked into one "
                         "batched execution")
    ap.add_argument("--batch-chunk", type=int, default=1,
                    help="batched2d batch_chunk of served plans "
                         "(shard=batch only; 0 = whole stack fused)")
    ap.add_argument("--cache-capacity", type=int, default=8,
                    help="LRU plan cache slots")
    ap.add_argument("--circuit-k", type=int, default=3,
                    help="consecutive failures that open a plan key's "
                         "circuit")
    ap.add_argument("--circuit-cooldown-s", type=float, default=5.0,
                    help="open-circuit cooldown before the half-open probe")
    ap.add_argument("--guards", default=None,
                    choices=("off", "check", "enforce"),
                    help="in-graph numerical guards of served plans "
                         "(default $DFFT_GUARDS -> off)")
    ap.add_argument("--wire-dtype", "-wire", default="native",
                    choices=("native", "bf16"),
                    help="wire encoding of served plans' exchanges "
                         "(shard=x; no 'auto' — a serving process must "
                         "not race)")
    ap.add_argument("--comm-method", "-comm", default="All2All",
                    help="comm method of served plans (shard=x)")
    ap.add_argument("--opt", "-o", type=int, default=0, choices=(0, 1))
    ap.add_argument("--fft-backend", default="xla")
    ap.add_argument("--wisdom", default=None, metavar="PATH")
    ap.add_argument("--no-wisdom", action="store_true")
    ap.add_argument("--emulate-devices", type=int,
                    default=int(os.environ.get("DFFT_EMULATE_DEVICES", "0")))
    ap.add_argument("--obs", action="store_true",
                    help="print obs notices + the metrics snapshot")
    ap.add_argument("--obs-dir", default=None, metavar="DIR",
                    help="write the structured JSONL event log here "
                         "(same as $DFFT_OBS_DIR)")
    # fleet mode (ISSUE 13): N shared-nothing subprocess workers behind
    # the plan-key router; 0 = the classic single-process Server.
    ap.add_argument("--workers", type=int, default=0,
                    help="run a fleet of N subprocess workers behind the "
                         "plan-key router (0 = single in-process server)")
    ap.add_argument("--worker-devices", default=None, metavar="D0,D1,...",
                    help="per-worker CPU-emulated device counts, e.g. "
                         "'8,0,0' = worker 0 is an 8-device mesh worker "
                         "(serves fft3d/* volume keys), the rest fall "
                         "back to --emulate-devices (fleet mode)")
    ap.add_argument("--volume-decomp", default="slab",
                    choices=("slab", "pencil"),
                    help="default 3D decomposition of served volume "
                         "requests (per-request override: submit "
                         "decomp= / X-DFFT-Decomp)")
    ap.add_argument("--worker-backend", default="server",
                    choices=("server", "stub"),
                    help="fleet worker core: the real jax Server, or the "
                         "np.fft stub with a fixed service time (routing/"
                         "chaos experiments without compiles)")
    ap.add_argument("--heartbeat-interval-s", type=float, default=0.5,
                    help="fleet heartbeat period; a worker silent for "
                         "K intervals is declared dead")
    ap.add_argument("--heartbeat-k", type=int, default=3,
                    help="missed heartbeats that declare a worker dead")
    ap.add_argument("--worker-inflight", type=int, default=4,
                    help="router dispatch window per worker (the "
                         "tenant-fairness lever)")
    ap.add_argument("--tenant-weights", default=None, metavar="T=W,...",
                    help="per-tenant admission weights, e.g. "
                         "'gold=3,free=1' (fleet mode; unknown tenants "
                         "weigh 1)")
    ap.add_argument("--tenants", default=None, metavar="A,B,...",
                    help="mix the --drive traffic over these tenant "
                         "identities (fleet mode; adds a by_tenant "
                         "summary block)")
    ap.add_argument("--autoscale", default=None, metavar="MIN:MAX",
                    help="attach the metrics-driven worker-count "
                         "controller, bounded to [MIN, MAX] workers "
                         "(fleet mode)")
    ap.add_argument("--scale-cooldown-s", type=float, default=5.0,
                    help="minimum seconds between scale decisions")
    # Resident solver tenant + durable state (ISSUE 14): a standing
    # simulation stepping inside the serving process, checkpointed
    # crash-consistently so drain/SIGTERM/worker death cannot destroy
    # its progress. In fleet mode the resident lives on worker 0 and a
    # replacement worker RESTORES it before rejoining the ring.
    ap.add_argument("--resident", default=None, metavar="KIND:N[:BATCH]",
                    help="host a resident solver (ns2d:64, ns2d:64:4, "
                         "ns3d:32) stepping alongside request traffic")
    ap.add_argument("--resident-dt", type=float, default=1e-3,
                    help="resident integrator dt")
    ap.add_argument("--resident-interval-ms", type=float, default=5.0,
                    help="pause between resident steps (keeps the "
                         "simulation from starving request traffic)")
    ap.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                    help="two-generation checkpoint store for the "
                         "resident's state (same as $DFFT_CKPT_DIR; "
                         "unset = the resident runs without durability)")
    ap.add_argument("--checkpoint-policy", default=None,
                    metavar="steps:N[,secs:T][,drain:on|off]",
                    help="when the resident checkpoints (same as "
                         "$DFFT_CKPT_POLICY; default drain-only)")
    ap.add_argument("--http", type=int, default=0, metavar="PORT",
                    help="serve GET /healthz, GET /readyz and POST /fft "
                         "on this port (0 = off)")
    ap.add_argument("--health-out", default=None, metavar="PATH",
                    help="write the final health snapshot JSON here on "
                         "exit (the CI assertion surface)")
    # --drive: the open-loop load generator
    ap.add_argument("--drive", action="store_true",
                    help="drive the built-in open-loop load generator "
                         "against this server, print a JSON summary, "
                         "drain and exit (chaos-CI / bench surface)")
    ap.add_argument("--rate", type=float, default=20.0,
                    help="offered load, requests/sec (Poisson arrivals)")
    ap.add_argument("--duration", type=float, default=5.0,
                    help="drive window, seconds")
    ap.add_argument("--requests", type=int, default=0,
                    help="drive a fixed request count instead of "
                         "--duration")
    ap.add_argument("--shapes", default="256x256",
                    help="comma-separated NXxNY (image) or NXxNYxNZ "
                         "(volume) request shapes the traffic mixes "
                         "over")
    ap.add_argument("--dtypes", default="f32",
                    help="comma-separated payload dtypes (f32,f64)")
    ap.add_argument("--transforms", default="r2c",
                    help="comma-separated transforms (r2c,c2c)")
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="per-request deadline of the driven traffic")
    ap.add_argument("--warmup", type=int, default=1,
                    help="synchronous warmup requests per traffic cell "
                         "before the measured window (0 = cold)")
    ap.add_argument("--seed", type=int, default=0)
    return ap


def _parse_tenant_weights(s):
    if not s:
        return None
    out = {}
    for tok in s.split(","):
        tok = tok.strip()
        if not tok:
            continue
        name, sep, w = tok.partition("=")
        if not sep or not name.strip():
            raise SystemExit(f"--tenant-weights wants T=W pairs, got "
                             f"{tok!r}")
        try:
            out[name.strip()] = float(w)
        except ValueError:
            raise SystemExit(f"--tenant-weights weight not a number: "
                             f"{tok!r}") from None
    return out or None


def _parse_worker_devices(s):
    if not s:
        return None
    try:
        out = [int(tok) for tok in s.split(",") if tok.strip()]
    except ValueError:
        raise SystemExit(f"--worker-devices wants comma-separated "
                         f"integers, got {s!r}") from None
    if not out or any(d < 0 for d in out):
        raise SystemExit(f"--worker-devices counts must be >= 0, got "
                         f"{s!r}")
    return out


def _parse_autoscale(s):
    if not s:
        return None
    lo, sep, hi = s.partition(":")
    try:
        pair = (int(lo), int(hi if sep else lo))
    except ValueError:
        raise SystemExit(f"--autoscale wants MIN:MAX, got {s!r}") from None
    if not 1 <= pair[0] <= pair[1]:
        raise SystemExit(f"--autoscale needs 1 <= MIN <= MAX, got {s!r}")
    return pair


def _parse_resident(args):
    """``--resident KIND:N[:BATCH]`` -> the picklable resident spec dict
    ``serve.resident.ResidentSolver.build`` consumes (None when the flag
    is absent)."""
    if not args.resident:
        if args.checkpoint_dir or args.checkpoint_policy:
            raise SystemExit("--checkpoint-dir/--checkpoint-policy "
                             "configure the resident solver's durable "
                             "state; add --resident KIND:N")
        return None
    parts = args.resident.strip().lower().split(":")
    if (len(parts) not in (2, 3) or parts[0] not in ("ns2d", "ns3d")
            or (parts[0] == "ns3d" and len(parts) == 3)):
        # ns3d has no ensemble axis — silently dropping a BATCH the
        # operator asked for would fingerprint-bind checkpoints to an
        # unintended configuration.
        raise SystemExit(f"--resident wants ns2d:N[:BATCH] or ns3d:N, "
                         f"got {args.resident!r}")
    try:
        spec = {"kind": parts[0], "n": int(parts[1]),
                "batch": int(parts[2]) if len(parts) == 3 else 1}
    except ValueError:
        raise SystemExit(f"--resident sizes must be integers, got "
                         f"{args.resident!r}") from None
    if spec["n"] < 4 or spec["batch"] < 1:
        # A degenerate grid fails later inside a worker subprocess as
        # an opaque spawn error; refuse at startup instead.
        raise SystemExit(f"--resident needs N >= 4 and BATCH >= 1, got "
                         f"{args.resident!r}")
    from .. import persist
    try:
        ckdir, policy = persist.resolve_env(args.checkpoint_dir,
                                            args.checkpoint_policy)
    except ValueError as e:  # fail loudly at startup
        raise SystemExit(f"--checkpoint-policy: {e}") from None
    spec.update(dt=args.resident_dt,
                step_interval_ms=args.resident_interval_ms,
                dir=ckdir, policy=policy)
    return spec


def _parse_shapes(s: str):
    """``NXxNY`` image and ``NXxNYxNZ`` volume entries, mixed freely;
    a bare ``N`` means ``NxN``."""
    out = []
    for part in s.split(","):
        part = part.strip().lower()
        if not part:
            continue
        dims = [tok for tok in part.split("x") if tok]
        if len(dims) not in (1, 2, 3):
            raise SystemExit(f"--shapes wants NXxNY or NXxNYxNZ, got "
                             f"{part!r}")
        try:
            shape = tuple(int(d) for d in dims)
        except ValueError:
            raise SystemExit(f"--shapes sizes must be integers, got "
                             f"{part!r}") from None
        out.append(shape * 2 if len(shape) == 1 else shape)
    if not out:
        raise SystemExit("--shapes needs at least one NXxNY entry")
    return out


def _make_http(server, port: int):
    """Stdlib HTTP front end; returns the started ThreadingHTTPServer."""
    import io
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    import numpy as np

    from ..resilience.circuit import CircuitOpen
    from ..resilience.deadline import DeadlineExceeded
    from .server import Overloaded, ServerClosed

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet: obs is the log surface
            pass

        def _json(self, code: int, payload: dict) -> None:
            body = json.dumps(payload, sort_keys=True).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                self._json(200, server.health())
            elif self.path == "/readyz":
                ready = server.state == "running"
                self._json(200 if ready else 503,
                           {"ready": ready, "state": server.state})
            elif self.path == "/metrics":
                # Prometheus exposition of the CUMULATIVE metrics view
                # (obs/promexp.py) — the autoscaling scrape surface.
                from ..obs import promexp
                body = promexp.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", promexp.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._json(404, {"error": "unknown path"})

        def do_POST(self):
            if self.path != "/fft":
                self._json(404, {"error": "unknown path"})
                return
            trace_id = None
            try:
                n = int(self.headers.get("Content-Length", "0"))
                x = np.load(io.BytesIO(self.rfile.read(n)),
                            allow_pickle=False)
                transform = self.headers.get("X-DFFT-Transform", "r2c")
                direction = self.headers.get("X-DFFT-Direction", "forward")
                ny = self.headers.get("X-DFFT-Ny")
                decomp = self.headers.get("X-DFFT-Decomp")
                ddl = self.headers.get("X-DFFT-Deadline-Ms")
                fut = server.submit(
                    x, transform, direction,
                    ny=int(ny) if ny else None,
                    decomp=decomp or None,
                    deadline_ms=float(ddl) if ddl else None)
                # The admission trace id: one request's whole path
                # (admit -> coalesce -> execute -> reply) is
                # reconstructable from the event log by this id, and the
                # client gets it back as X-DFFT-Trace.
                trace_id = getattr(fut, "trace_id", None)
                out = fut.result()
            except Overloaded as e:
                self._json(429, {"error": "overloaded", "reason": e.reason,
                                 "queue_depth": e.queue_depth,
                                 "est_delay_ms": e.est_delay_ms})
            except CircuitOpen as e:
                self._json(503, {"error": "circuit_open", "key": e.key,
                                 "retry_after_s": e.retry_after_s})
            except ServerClosed:
                self._json(503, {"error": "closed"})
            except DeadlineExceeded as e:
                self._json(504, {"error": "deadline_exceeded",
                                 "detail": e.detail,
                                 "overrun_ms": e.overrun_ms})
            except (ValueError, OSError) as e:
                self._json(400, {"error": "bad_request", "detail": str(e)})
            except Exception as e:  # noqa: BLE001 — the envelope's edge
                self._json(500, {"error": type(e).__name__,
                                 "detail": str(e)[:300]})
            else:
                buf = io.BytesIO()
                np.save(buf, out, allow_pickle=False)
                body = buf.getvalue()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("Content-Length", str(len(body)))
                if trace_id:
                    self.send_header("X-DFFT-Trace", trace_id)
                self.end_headers()
                self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True,
                     name="dfft-serve-http").start()
    return httpd


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from .. import obs
    if args.obs_dir:
        # Export too, not just enable(): fleet WORKERS are spawned
        # subprocesses that only see the environment — without this the
        # worker-side half of the evidence chain (persist.checkpoint,
        # persist.degraded_restore, ...) silently never lands in the
        # one obs dir the flag promises.
        os.environ["DFFT_OBS_DIR"] = args.obs_dir
        obs.enable(args.obs_dir)
    if args.obs:
        obs.enable_console()

    if args.emulate_devices:
        from ..parallel.mesh import force_cpu_devices
        force_cpu_devices(args.emulate_devices)

    from .. import params as pm
    from .server import Server

    cfg = pm.Config(
        comm_method=pm.parse_comm_method(args.comm_method),
        opt=args.opt, fft_backend=args.fft_backend,
        wire_dtype=args.wire_dtype, guards=args.guards,
        wisdom_path=args.wisdom, use_wisdom=not args.no_wisdom)
    server_kwargs = dict(
        max_queue=args.max_queue,
        latency_budget_ms=args.latency_budget_ms,
        max_coalesce=args.max_coalesce,
        batch_chunk=args.batch_chunk or None,
        cache_capacity=args.cache_capacity, circuit_k=args.circuit_k,
        circuit_cooldown_s=args.circuit_cooldown_s)
    autoscale = _parse_autoscale(args.autoscale)
    resident_spec = _parse_resident(args)
    if args.workers or autoscale:
        # Fleet mode (ISSUE 13): N shared-nothing subprocess workers,
        # each a full Server, behind the rendezvous plan-key router.
        from .fleet import Fleet, ScaleController
        n0 = args.workers or autoscale[0]
        if autoscale:
            n0 = min(max(n0, autoscale[0]), autoscale[1])
        server = Fleet(
            n0, partition=pm.SlabPartition(args.partitions), config=cfg,
            shard=args.shard, emulate_devices=args.emulate_devices,
            worker_backend=args.worker_backend,
            heartbeat_interval_s=args.heartbeat_interval_s,
            heartbeat_k=args.heartbeat_k,
            worker_inflight=args.worker_inflight,
            worker_devices=_parse_worker_devices(args.worker_devices),
            volume_decomp=args.volume_decomp,
            tenant_weights=_parse_tenant_weights(args.tenant_weights),
            resident=resident_spec,
            **server_kwargs)
        if autoscale:
            server.attach_controller(ScaleController(
                server, autoscale[0], autoscale[1],
                cooldown_s=args.scale_cooldown_s))
    else:
        if args.worker_devices:
            raise SystemExit("--worker-devices requires fleet mode "
                             "(--workers N or --autoscale MIN:MAX)")
        if args.tenants or args.tenant_weights:
            # Server.submit has no tenant axis: forwarding the flag
            # would TypeError every request into a silent 100%-failed
            # drive. Fail loudly at startup instead.
            raise SystemExit("--tenants/--tenant-weights require fleet "
                             "mode (--workers N or --autoscale MIN:MAX)")
        server = Server(pm.SlabPartition(args.partitions), cfg,
                        shard=args.shard,
                        volume_decomp=args.volume_decomp,
                        **server_kwargs)
        if resident_spec is not None:
            from .. import persist
            from .resident import ResidentSolver
            try:
                server.attach_resident(ResidentSolver.build(
                    dict(resident_spec, name="resident")))
            except persist.CheckpointMismatch as e:
                # The documented operator error (the dir belongs to a
                # differently-configured run): a usage message, not a
                # traceback — mirrors dfft-solve.
                server.close(drain=False)
                raise SystemExit(
                    "dfft-serve: checkpoint store was written by a "
                    f"different configuration — {e}") from None

    httpd = _make_http(server, args.http) if args.http else None
    stop = threading.Event()

    def _graceful(signum, frame):  # noqa: ARG001 — signal contract
        print(f"dfft-serve: signal {signum} -> graceful drain",
              flush=True)
        stop.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    # SIGUSR2 -> flight-recorder dump (live debugging: kill -USR2 <pid>
    # dumps the last seconds of spans/events/metric deltas to JSONL; the
    # path lands in health()["flight_recorder"]["last_dump"]).
    obs.flightrec.install_signal_handler()

    rc = 0
    summary = None
    health = None
    try:
        if args.drive:
            from ..testing.workloads import serve_load
            kw = dict(rate_hz=args.rate,
                      shapes=_parse_shapes(args.shapes),
                      dtypes=[d.strip() for d in args.dtypes.split(",")
                              if d.strip()],
                      transforms=[t.strip() for t in
                                  args.transforms.split(",") if t.strip()],
                      deadline_ms=args.deadline_ms, seed=args.seed,
                      warmup=args.warmup, stop=stop)
            if args.tenants:
                kw["tenants"] = [t.strip() for t in
                                 args.tenants.split(",") if t.strip()]
            if args.requests:
                kw["n_requests"] = args.requests
            else:
                kw["duration_s"] = args.duration
            summary = serve_load(server, **kw)
            health = server.health()  # LIVE state (degraded circuits
            # etc.) before the drain below flips status to stopped
        else:
            print(f"dfft-serve: serving (state {server.state}"
                  + (f", http :{args.http}" if httpd else "")
                  + "); SIGTERM drains", flush=True)
            while not stop.is_set():
                stop.wait(0.2)
    finally:
        server.close(drain=True)
        if httpd is not None:
            httpd.shutdown()
        if health is None:
            health = server.health()
        if args.health_out:
            try:
                with open(args.health_out, "w", encoding="utf-8") as f:
                    json.dump(health, f, indent=1, sort_keys=True)
            except OSError as e:
                print(f"dfft-serve: health-out failed: {e}",
                      file=sys.stderr)
                rc = 1
        if summary is not None:
            summary["health_status"] = health["status"]
            if args.workers or autoscale:
                summary["workers"] = len(health.get("ring", []))
                summary["worker_deaths"] = \
                    health["counters"].get("worker_deaths", 0)
                summary["resubmitted"] = \
                    health["counters"].get("resubmitted", 0)
            if resident_spec is not None:
                summary["resident"] = health.get("resident")
            print(json.dumps(summary, sort_keys=True), flush=True)
        if args.obs:
            print("obs metrics: "
                  + json.dumps(obs.metrics.snapshot(), sort_keys=True))
    return rc


if __name__ == "__main__":
    sys.exit(main())
