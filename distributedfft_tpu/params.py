"""Parameter / configuration model for distributed FFT plans.

TPU-native re-design of the reference's parameter layer
(``include/params.hpp``): global sizes with the R2C halved axis
(``params.hpp:24-37``), slab / pencil partitions (``params.hpp:39-56``),
per-axis size/offset tables with remainder spread
(``src/slab/default/mpicufft_slab.cpp:112-128``), and the
communication-/send-method enums (``params.hpp:83-93``).

On TPU the comm/send matrix collapses into *how the XLA program is built*:

* ``CommMethod.ALL2ALL``  -> explicit ``shard_map`` + ``lax.all_to_all``
  (the device-collective analog of ``MPI_Alltoallv/w``).
* ``CommMethod.PEER2PEER`` -> GSPMD resharding: the pipeline is written as
  global-view ops with ``with_sharding_constraint`` between stages and XLA
  chooses the collective schedule (its latency-hiding scheduler plays the
  role of the reference's hand-rolled Isend/Irecv overlap engine).
* ``SendMethod.STREAMS`` -> the chunked/software-pipelined transpose: the
  local block is split into ``Config.streams_chunks`` pieces along an axis
  untouched by the exchange, and each piece runs its own
  FFT -> collective -> FFT chain — the intended role of the reference's
  Streams engine (per-peer packs on CUDA streams + callback thread +
  ``MPI_Isend``, ``src/slab/default/mpicufft_slab.cpp:343-448``).
  MEASURED RESULT (``eval/benchmarks/cpumesh8/OVERLAP.md``): under
  PEER2PEER, GSPMD re-fuses the piece reshards into ONE collective
  (HLO identical to SYNC), and even the explicit ALL2ALL rendering's K
  chunked collectives showed ZERO async collective ops — its measured
  1.2-1.4x win is a working-set effect, not overlap.
* ``SendMethod.RING`` -> the ring-pipelined transpose
  (``parallel/transpose.ring_transpose``): ``P-1`` distinct
  ``lax.ppermute`` steps XLA cannot re-fuse, with per-peer-block FFT
  compute pipelined between them — the overlap-capable rendering the
  STREAMS result motivated.
* ``SendMethod.RING_OVERLAP`` -> the same ring with the loop restructured
  as a DOUBLE-BUFFERED software pipeline: step t+1's permute is issued
  BEFORE block t's per-block FFT is traced (two revolving buffers), so a
  scheduler that respects program order can keep one transfer in flight
  under every block's compute. Same block math in a reordered schedule —
  bit-identical output to RING, pinned by tests/test_overlap.py.
  ``SYNC`` is the monolithic single-collective pipeline; ``MPI_TYPE``
  (zero-copy strided datatypes) has no analog under XLA -- packing is a
  fused transpose -- and is accepted as a benchmarking label alias of SYNC.

Everything here is pure Python (no devices required), mirroring the
reference's L1b layer which is header-only.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import List, Optional, Sequence, Tuple

from .utils import native_planner


# Valid Config.mxu_precision names (lax.Precision's string forms); a plain
# set so params.py stays importable without jax.
_MXU_PRECISIONS = frozenset({"default", "high", "highest"})

# Valid Config.guards modes (resilience/guards.py): "off" = the exact
# pre-guard programs (pinned byte-identical by tests/test_resilience.py),
# "check" = compute the in-graph energy/drift guards and REPORT violations
# (obs metrics + notice; a compressed wire additionally demotes itself to
# native for subsequent calls), "enforce" = raise a structured
# ``resilience.GuardViolation`` carrying the plan fingerprint.
GUARD_MODES = ("off", "check", "enforce")


def parse_guards(s: str) -> str:
    """Canonical guard-mode name (case-insensitive)."""
    key = str(s).strip().lower()
    if key in GUARD_MODES:
        return key
    raise ValueError(f"unknown guards mode: {s!r} (choose from {GUARD_MODES})")

# Marker for measurement-resolved Config fields: ``fft_backend=AUTO`` /
# ``comm_method=AUTO`` ask the plan constructors to consult the persistent
# wisdom store (``utils/wisdom.py``) and race-and-record on a miss. Plans
# never execute with an unresolved AUTO; resolution happens once, at
# construction.
AUTO = "auto"

# Valid Config.wire_dtype names: how the global-exchange payload is encoded
# on the wire. "native" is the bit-identical pass-through; "bf16" the
# planar (real, imag) bf16 pair (parallel/transpose.wire_encode) halving a
# complex64 exchange's bytes; "auto" races compressed vs native under
# Config.wire_error_budget at plan construction (wisdom-resolved, like the
# comm "auto").
_WIRE_DTYPES = ("native", "bf16", AUTO)

# Default Config.wire_error_budget: the max roundtrip rel error (vs the
# native path, relative to the output's max magnitude) the "auto" racer
# accepts from a compressed wire. bf16 carries an 8-bit mantissa
# (eps ~ 3.9e-3); a forward+inverse pipeline crosses the wire twice, so
# the measured roundtrip error sits at ~2-4e-3 per crossing — 2e-2 admits
# bf16 for ordinary f32 workloads while rejecting it wherever accumulation
# pushes past the percent level.
DEFAULT_WIRE_ERROR_BUDGET = 2e-2


def parse_wire_dtype(s: str) -> str:
    """Canonical wire-dtype name (case-insensitive; 'auto' = measured)."""
    key = str(s).strip().lower()
    if key in _WIRE_DTYPES:
        return key
    raise ValueError(
        f"unknown wire dtype: {s!r} (choose from {_WIRE_DTYPES})")


# Overlap depths the comm autotuner races for the revolving-buffer ring
# (and the issue-ahead window of the pipelined all_to_all): the
# schedule-verified candidate set — ``analysis/schedverify.py`` proves
# every member hazard-free per mesh size before a plan may trace it.
OVERLAP_DEPTHS = (2, 4, 8)


def parse_overlap_depth(s: "str | int") -> "str | int":
    """Canonical ``Config.overlap_depth`` value: ``"auto"`` (wisdom /
    race-resolved) or an int >= 2 (the revolving receive-buffer count;
    capped at the ring's step count at trace time)."""
    if isinstance(s, str) and s.strip().lower() == AUTO:
        return AUTO
    try:
        v = int(s)
    except (TypeError, ValueError):
        raise ValueError(
            f"overlap depth must be an int >= 2 or {AUTO!r}, got {s!r}")
    if v < 2:
        raise ValueError(f"overlap depth must be >= 2, got {v}")
    return v


def parse_comm_method(s: "str | CommMethod") -> "str | CommMethod":
    """``CommMethod.parse`` that additionally accepts ``"auto"`` (the
    wisdom-resolved marker, owning the whole comm x send x opt x chunk
    variant choice at plan construction)."""
    if isinstance(s, str) and s.strip().lower() == AUTO:
        return AUTO
    return CommMethod.parse(s)


class CommMethod(enum.Enum):
    """Global-redistribution strategy (reference ``params.hpp:83-85``)."""

    PEER2PEER = "Peer2Peer"  # GSPMD auto-resharding path
    ALL2ALL = "All2All"      # explicit shard_map + lax.all_to_all path

    @classmethod
    def parse(cls, s: "str | CommMethod") -> "CommMethod":
        if isinstance(s, CommMethod):
            return s
        key = str(s).strip().lower().replace("_", "").replace("-", "")
        if key in ("peer2peer", "p2p", "peer"):
            return cls.PEER2PEER
        if key in ("all2all", "a2a", "alltoall"):
            return cls.ALL2ALL
        raise ValueError(f"unknown comm method: {s!r}")


class SendMethod(enum.Enum):
    """Packing strategy (reference ``params.hpp:87-89``). ``STREAMS``
    selects the chunked/software-pipelined transpose (see module
    docstring); ``SYNC``/``MPI_TYPE`` are the monolithic pipeline.

    ``RING`` is an extension beyond the reference's 2x3 matrix: the
    transpose decomposed into ``P-1`` ``lax.ppermute`` ring steps
    (``parallel/transpose.ring_transpose``), one peer block per step,
    with the per-block post-transpose FFT stage pipelined between steps
    where the axis roles allow. Unlike STREAMS' chunked collectives —
    which GSPMD re-fuses under PEER2PEER and which stay K instances of
    one op under ALL2ALL — each ring step is a distinct
    ``collective-permute`` (async start/done pair on TPU) that XLA cannot
    re-fuse, so this is the rendering on which the overlap detector
    (HLO async-collective counts) actually fires. A ring is only
    expressible as an explicit ``shard_map`` program, so RING owns the
    exchange rendering regardless of ``comm_method`` (GSPMD delegation
    has no ppermute analog).

    ``RING_OVERLAP`` is RING's double-buffered schedule (the overlap
    engine of ISSUE 10): the per-block loop is restructured so step
    t+1's ``ppermute`` is issued before block t's per-block FFT, with
    two revolving buffers carrying the in-flight and the computing
    block. The per-block math is IDENTICAL to RING (bit-identical
    output, pinned), only the issue order changes — which is exactly
    what lets an asynchronous scheduler (TPU start/done pairs) hide
    each transfer under the previous block's compute instead of
    serializing permute -> FFT -> permute. Owns the rendering
    regardless of ``comm_method``, like RING."""

    SYNC = "Sync"
    STREAMS = "Streams"
    MPI_TYPE = "MPI_Type"
    RING = "Ring"
    RING_OVERLAP = "RingOverlap"

    @classmethod
    def parse(cls, s: "str | SendMethod") -> "SendMethod":
        if isinstance(s, SendMethod):
            return s
        key = str(s).strip().lower().replace("_", "").replace("-", "")
        if key == "sync":
            return cls.SYNC
        if key == "streams":
            return cls.STREAMS
        if key == "ring":
            return cls.RING
        if key in ("ringoverlap", "overlap", "ringovl"):
            return cls.RING_OVERLAP
        if key in ("mpitype", "mpit", "type"):
            return cls.MPI_TYPE
        raise ValueError(f"unknown send method: {s!r}")

    @property
    def is_ring(self) -> bool:
        """Both ppermute-ring renderings (RING and its double-buffered
        RING_OVERLAP schedule) — the predicate the plan assemblers and
        the contract/ladder layers share, so a new ring variant cannot
        be wired into one of them only."""
        return self in (SendMethod.RING, SendMethod.RING_OVERLAP)


class FFTNorm(enum.Enum):
    """Normalization policy.

    ``NONE`` reproduces cuFFT semantics (both directions unnormalized;
    the reference's round-trip test compares against the input scaled by
    ``Nx*Ny*Nz``, ``tests/src/slab/random_dist_default.cu:529-623``).
    ``BACKWARD`` is the numpy default (inverse carries 1/N).
    """

    NONE = "none"
    BACKWARD = "backward"
    ORTHO = "ortho"


@dataclasses.dataclass(frozen=True)
class GlobalSize:
    """Global 3D extent; ``nz_out`` is the R2C halved z extent
    (reference ``params.hpp:30``: ``Nz_out = Nz/2 + 1``)."""

    nx: int
    ny: int
    nz: int

    def __post_init__(self) -> None:
        for name in ("nx", "ny", "nz"):
            v = getattr(self, name)
            if not isinstance(v, int) or v <= 0:
                raise ValueError(f"{name} must be a positive int, got {v!r}")

    @property
    def nz_out(self) -> int:
        return self.nz // 2 + 1

    @property
    def ny_out(self) -> int:
        """Halved-y extent, used by the Y_Then_ZX slab sequence
        (reference ``src/slab/y_then_zx/mpicufft_slab_y_then_zx.cpp:95-103``)."""
        return self.ny // 2 + 1

    @property
    def shape(self) -> Tuple[int, int, int]:
        return (self.nx, self.ny, self.nz)

    @property
    def n_total(self) -> int:
        return self.nx * self.ny * self.nz


def block_sizes(n: int, p: int) -> List[int]:
    """Block distribution of ``n`` items over ``p`` parts with the remainder
    spread over the first ranks, exactly as the reference computes slab
    extents (``src/slab/default/mpicufft_slab.cpp:112-128``)."""
    return native_planner.block_sizes(n, p)


def block_starts(sizes: Sequence[int]) -> List[int]:
    """Exclusive prefix sum -> per-part start offsets
    (reference ``Partition_Dimensions::computeOffsets``, ``params.hpp:58-81``)."""
    return native_planner.block_starts(list(sizes))


def even_shard_sizes(n: int, n_pad: int, p: int) -> List[int]:
    """Logical per-rank extents under even padded sharding: each rank holds a
    ``n_pad/p`` block of the padded axis; ranks past the logical extent hold
    only pad and report 0. This is what the framework's NamedShardings
    actually materialize — distinct from the reference's remainder-spread
    ``block_sizes``."""
    return native_planner.even_shard_sizes(n, n_pad, p)


def padded_extent(n: int, p: int) -> int:
    """Smallest multiple of ``p`` >= ``n``.

    XLA collectives want equal splits; where the reference uses per-peer byte
    counts for uneven extents (e.g. the odd ``Nz/2+1`` axis), the TPU design
    pads the axis to ``p * ceil(n/p)`` and slices the result (SURVEY §7)."""
    return native_planner.padded_extent(n, p)


@dataclasses.dataclass(frozen=True)
class PartitionDims:
    """Per-axis local extents and offsets for one stage of a decomposition —
    the analog of the reference's ``Partition_Dimensions`` (``params.hpp:58-81``),
    holding sizes/starts for every rank rather than vectors per axis."""

    size_x: Tuple[int, ...]
    size_y: Tuple[int, ...]
    size_z: Tuple[int, ...]

    @property
    def start_x(self) -> List[int]:
        return block_starts(self.size_x)

    @property
    def start_y(self) -> List[int]:
        return block_starts(self.size_y)

    @property
    def start_z(self) -> List[int]:
        return block_starts(self.size_z)


class Partition:
    """Base partition type (reference ``params.hpp:39-43``)."""

    @property
    def num_ranks(self) -> int:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class SlabPartition(Partition):
    """1D decomposition over x (or the sequence-dependent first axis);
    reference ``Slab_Partition`` (``params.hpp:44-49``)."""

    p: int

    def __post_init__(self):
        if self.p <= 0:
            raise ValueError(f"slab partition count must be positive, got {self.p}")

    @property
    def num_ranks(self) -> int:
        return self.p


@dataclasses.dataclass(frozen=True)
class PencilPartition(Partition):
    """2D decomposition over (x, y) into a P1 x P2 grid; reference
    ``Pencil_Partition`` (``params.hpp:51-56``) with
    ``pidx = pidx_i * P2 + pidx_j`` (``src/pencil/mpicufft_pencil.cpp:83-85``)."""

    p1: int
    p2: int

    def __post_init__(self):
        if self.p1 <= 0 or self.p2 <= 0:
            raise ValueError(f"pencil grid must be positive, got {self.p1}x{self.p2}")

    @property
    def num_ranks(self) -> int:
        return self.p1 * self.p2


class SlabSequence(enum.Enum):
    """Which per-axis FFT sequence a slab plan runs (reference's three slab
    families, SURVEY §2.1)."""

    ZY_THEN_X = "ZY_Then_X"   # 2D FFT (y,z) -> transpose -> 1D FFT x  (default)
    Z_THEN_YX = "Z_Then_YX"   # 1D FFT z -> transpose -> 2D FFT (y,x)
    Y_THEN_ZX = "Y_Then_ZX"   # 1D R2C y -> transpose -> 2D FFT (z,x)

    @classmethod
    def parse(cls, s: "str | SlabSequence") -> "SlabSequence":
        if isinstance(s, SlabSequence):
            return s
        key = str(s).strip().lower().replace("-", "_")
        table = {
            "zy_then_x": cls.ZY_THEN_X, "default": cls.ZY_THEN_X, "2d_1d": cls.ZY_THEN_X,
            "z_then_yx": cls.Z_THEN_YX, "1d_2d": cls.Z_THEN_YX,
            "y_then_zx": cls.Y_THEN_ZX, "1d_2d_y": cls.Y_THEN_ZX,
        }
        if key in table:
            return table[key]
        raise ValueError(f"unknown slab sequence: {s!r}")


@dataclasses.dataclass(frozen=True)
class Config:
    """Plan-wide configuration — the analog of the reference's
    ``Configurations`` struct (``params.hpp:85-93``).

    ``comm_method2`` / ``send_method2`` apply to the pencil second transpose
    (reference CLI ``-comm2/-snd2``, ``tests/src/pencil/main.cpp:26-63``).
    ``opt`` selects the data-layout variant: 1 = the coordinate-transform
    ("realigned") layout where the pre-transpose FFT writes transposed
    coordinates (reference Opt1 classes); under XLA this is a hint that the
    transpose is fused into the producer, which the compiler does anyway, so
    opt only changes benchmark labeling and the internal einsum order.
    ``cuda_aware`` is accepted for CLI compatibility; device-resident
    collectives are always on for TPU.
    ``fft_backend`` selects the local-transform implementation: ``"xla"``
    (XLA's FFT expansion), ``"matmul"`` (MXU four-step DFT matmuls,
    ``ops/mxu_fft.py``), ``"matmul-r2"`` (same with radix-2 DIF splitting
    down to MXU-depth matmuls), ``"pallas"`` (Pallas kernels fusing the
    four-step twiddle into the DFT matmul, ``ops/pallas_fft.py``), or
    ``"bluestein"`` (``ops/bluestein.py``: chirp-z for arbitrary —
    prime, non-smooth — axis lengths at O(n log n); 5-smooth axes
    delegate to the XLA expansion bit-identically, so it costs nothing
    where the fast path already applies) — the TPU analog of the
    reference's cuFFT-plan choice at L0 (``include/cufft.hpp:23-61``).
    ``fft_backend="auto"`` defers the choice to measurement: plan
    construction consults the persistent wisdom store
    (``utils/wisdom.py``; path from ``wisdom_path`` -> ``$DFFT_WISDOM``),
    races the backends on a miss (the bluestein candidate joins exactly
    when the shape has a non-smooth axis — it would duplicate "xla"
    otherwise) and records the winner. ``comm_method=
    "auto"`` does the same for the whole comm x send x opt x streams-chunks
    variant, the RING and RING_OVERLAP ring renderings included (ignoring the explicit
    ``send_method``/``opt`` fields — the race owns them). ``use_wisdom=False`` (CLI ``--no-wisdom``) never
    touches disk; "auto" then races per process.

    ``streams_chunks`` sets how many pieces the ``SendMethod.STREAMS``
    pipelined transpose splits the local block into (None -> 4). Ignored
    unless the plan's (resolved) send method is STREAMS; clamped to the
    chunk axis extent at trace time. More chunks = more overlap windows
    but smaller (less bandwidth-efficient) exchanges.

    ``overlap_depth`` (``"auto"`` default) sets the revolving
    receive-buffer depth of the overlap schedules: RING_OVERLAP's ring
    issues up to ``depth - 1`` permutes ahead of the per-block FFTs
    (``"auto"`` -> 2, the shipped double-buffered pipeline, traced
    op-for-op as before), and the pipelined all_to_all uses the same
    value as its issue-ahead window. Capped at the exchange's step
    count (depth 8 on 8 ranks runs 7 buffers — and the descriptors say
    so). ``autotune_comm`` races depths ``OVERLAP_DEPTHS`` as wisdom
    candidates. ``overlap_subblocks`` (None -> 1) splits each
    travelling block into S sub-blocks: on a ring each peer block
    becomes S ppermute micro-steps (the first sub-block's FFT starts
    before the peer's full payload has arrived — the Streams-chunks
    idea inside the ring); under ALL2ALL + SYNC/MPI_TYPE a value > 1
    software-pipelines the monolithic collective into S chunked
    ``all_to_all``s (the ``a2a_pipe`` rendering), so opt0/opt1 get
    overlap without switching to the ring. Every depth/sub-block
    variant is bit-identical to its serial rendering;
    ``analysis/schedverify.py`` proves each shipped schedule
    hazard-free before a plan may trace it.

    ``wire_dtype`` selects the WIRE encoding of every global exchange
    (``parallel/transpose`` wire layer; CLI ``-wire``, env ``$DFFT_WIRE``):
    ``"native"`` keeps today's bit-identical payload; ``"bf16"`` packs the
    complex payload as a planar (real, imag) bf16 pair immediately before
    the collective and decodes immediately after — HALF the wire bytes of
    a complex64 exchange (quarter for complex128), an OPT-IN LOSSY choice
    (~2e-3 max rel error per crossing, measured/documented in README);
    ``"auto"`` races compressed vs native on the actual shape at plan
    construction, accepts bf16 only when its measured roundtrip error
    stays within ``wire_error_budget`` (None -> 2e-2), and records the
    winner in the wisdom store. The encoding composes with every exchange
    rendering — default/opt1 ``lax.all_to_all``, the GSPMD boundary, and
    the RING/RING_OVERLAP ppermute rings, which encode per travelling
    block so compression and overlap stack. Applies to both pencil
    transposes.

    ``fused_wire`` (opt-in, default False) renders the ring's per-block
    wire boundary with the fused Pallas kernels (``ops/pallas_fft``
    fused-wire section): the bf16 planar split + pack runs as ONE kernel
    pass on the send side, and the decode + the first pipelined per-block
    DFT stage fuse into one kernel on the receive side, so the travelling
    payload never round-trips HBM between the wire cast and the
    neighboring FFT matmul (``pallas_call`` is a custom-call boundary XLA
    cannot fuse across — the one case where the hand kernel wins; see the
    ``ops/pallas_fft.py`` module docstring). Only active on a ring
    rendering (RING / RING_OVERLAP) with ``wire_dtype="bf16"``; inert
    otherwise. Off-TPU the kernels fall back to the numerically
    equivalent jnp composition, and the fused decode+FFT stage computes
    its DFT as a matmul regardless of ``fft_backend`` (that IS the
    fusion) — numerics vs the unfused path are bounded by the wire's
    documented bf16 error (tests/test_overlap.py pins the bound).

    ``guards`` selects the in-graph numerical guards of the resilience
    layer (``resilience/guards.py``; CLI ``--guards``, env
    ``$DFFT_GUARDS``): ``None`` defers to the environment (unset = "off",
    the exact pre-guard programs); ``"check"`` adds a Parseval/energy-
    conservation residual (and, on a compressed wire, a drift probe
    against ``wire_error_budget``) to every jitted pipeline — one extra
    reduction, violations counted/noticed, a drifting wire demoted to
    native for subsequent calls; ``"enforce"`` raises a structured
    ``resilience.GuardViolation`` instead.

    ``fft3d_chunk`` bounds the SINGLE-DEVICE 3D path's peak memory: the
    z+y stages run as ``lax.map`` over that many leading-axis chunks, so
    the four-step relayout temporaries scale with a chunk instead of the
    whole cube (a 1024^3 f32 R2C's full-cube z-stage temporaries exceed a
    16 GB chip; chunked they fit). Must divide the x extent; the x stage
    (which needs the full axis) runs unchunked on the halved spectrum.
    None (default) = fused, no chunking. R2C/C2R only; ignored by
    distributed plans (shard the cube instead).

    ``mxu_precision`` / ``mxu_karatsuba`` / ``mxu_fourstep_einsum`` /
    ``mxu_direct_max`` are the matmul-family backend knobs as PLAN state
    (read at trace time through a context-scoped ``mxu_fft.MXUSettings``,
    so two plans with different settings coexist in one process). Each
    knob is tri-state: None defers PER KNOB to the deprecated
    ``mxu_fft.set_*`` process defaults; an explicit value wins.
    ``mxu_precision`` is the single-precision DFT-matmul MXU precision:
    "default" (raw bf16), "high" (the measured accuracy/speed sweet spot
    on v5e — also the process default), or "highest"; f64 always runs
    HIGHEST. ``mxu_direct_max`` is the direct-plan threshold: axes up to
    this length are one dense contraction, longer axes take the
    four-step factorization — on a v5e at 1024^3 the all-direct plan
    (``mxu_direct_max=1024``) beat the default four-step 2.9x
    (session_r5.jsonl 2026-07-31; ``autotune_local_fft`` races it
    automatically past the default threshold).
    """

    comm_method: CommMethod = CommMethod.ALL2ALL
    send_method: SendMethod = SendMethod.SYNC
    comm_method2: Optional[CommMethod] = None
    send_method2: Optional[SendMethod] = None
    opt: int = 0
    cuda_aware: bool = True
    warmup_rounds: int = 0
    iterations: int = 1
    double_prec: bool = False
    norm: FFTNorm = FFTNorm.NONE
    benchmark_dir: str = "benchmarks"
    fft_backend: str = "xla"
    mxu_precision: Optional[str] = None
    mxu_karatsuba: Optional[bool] = None
    mxu_fourstep_einsum: Optional[bool] = None
    mxu_direct_max: Optional[int] = None
    fft3d_chunk: Optional[int] = None
    streams_chunks: Optional[int] = None
    overlap_depth: "int | str" = AUTO
    overlap_subblocks: Optional[int] = None
    wire_dtype: str = "native"
    wire_error_budget: Optional[float] = None
    fused_wire: bool = False
    guards: Optional[str] = None
    wisdom_path: Optional[str] = None
    use_wisdom: bool = True

    def __post_init__(self):
        from .ops.fft import validate_backend  # lazy: ops.fft imports params
        if self.fft_backend != AUTO:
            validate_backend(self.fft_backend)
        if not (isinstance(self.comm_method, CommMethod)
                or self.comm_method == AUTO):
            raise ValueError(
                f"comm_method must be a CommMethod or {AUTO!r}, "
                f"got {self.comm_method!r}")
        if not (self.comm_method2 is None
                or isinstance(self.comm_method2, CommMethod)
                or self.comm_method2 == AUTO):
            raise ValueError(
                f"comm_method2 must be a CommMethod, {AUTO!r} or None, "
                f"got {self.comm_method2!r}")
        if self.mxu_precision is not None and \
                str(self.mxu_precision).lower() not in _MXU_PRECISIONS:
            raise ValueError(
                f"mxu_precision must be one of {sorted(_MXU_PRECISIONS)} "
                f"or None, got {self.mxu_precision!r}")
        if self.fft3d_chunk is not None and (
                not isinstance(self.fft3d_chunk, int) or self.fft3d_chunk < 1):
            raise ValueError(
                f"fft3d_chunk must be a positive int or None, "
                f"got {self.fft3d_chunk!r}")
        if self.mxu_direct_max is not None and (
                not isinstance(self.mxu_direct_max, int)
                or self.mxu_direct_max < 1):
            raise ValueError(
                f"mxu_direct_max must be a positive int or None, "
                f"got {self.mxu_direct_max!r}")
        if self.streams_chunks is not None and (
                not isinstance(self.streams_chunks, int)
                or self.streams_chunks < 1):
            # >= 1, not >= 2: the knob is documented as ignored unless the
            # send method is STREAMS, and chunks=1 degrades gracefully to
            # the monolithic exchange (chunk_slices clamps anyway).
            raise ValueError(
                f"streams_chunks must be a positive int or None, "
                f"got {self.streams_chunks!r}")
        # parse_overlap_depth canonicalizes (and rejects depths < 2) at
        # Config construction, like guards below — a typo'd depth fails
        # here, not at first trace.
        object.__setattr__(self, "overlap_depth",
                           parse_overlap_depth(self.overlap_depth))
        if self.overlap_subblocks is not None and (
                not isinstance(self.overlap_subblocks, int)
                or self.overlap_subblocks < 1):
            # >= 1, not >= 2: subblocks=1 degrades gracefully to the
            # monolithic per-peer block (ring_subblocks clamps anyway),
            # mirroring the streams_chunks contract above.
            raise ValueError(
                f"overlap_subblocks must be a positive int or None, "
                f"got {self.overlap_subblocks!r}")
        if self.wire_dtype not in _WIRE_DTYPES:
            raise ValueError(
                f"wire_dtype must be one of {_WIRE_DTYPES}, "
                f"got {self.wire_dtype!r} (parse_wire_dtype canonicalizes)")
        if self.wire_error_budget is not None and (
                not isinstance(self.wire_error_budget, (int, float))
                or not self.wire_error_budget > 0):
            raise ValueError(
                f"wire_error_budget must be a positive number or None, "
                f"got {self.wire_error_budget!r}")
        if not isinstance(self.fused_wire, bool):
            raise ValueError(
                f"fused_wire must be a bool, got {self.fused_wire!r}")
        if self.guards is not None:
            # Canonicalized here rather than at resolution so a typo'd
            # mode fails at Config construction, not at first exec.
            object.__setattr__(self, "guards", parse_guards(self.guards))

    def mxu_settings(self) -> Optional[object]:
        """The plan's ``mxu_fft.MXUSettings``, or None when every knob is
        None — None lets the deprecated ``set_*`` process defaults keep
        applying wholesale, preserving pre-Config behavior. When any knob
        is set, the OTHER knobs still fall back per-knob to the process
        defaults in effect at build time (a later ``set_*`` call does not
        reach an already-built plan)."""
        if (self.mxu_precision is None and self.mxu_karatsuba is None
                and self.mxu_fourstep_einsum is None
                and self.mxu_direct_max is None):
            return None
        import dataclasses as dc

        from .ops import mxu_fft as mx  # lazy: imports jax
        # PROCESS defaults, not current_settings(): a plan built inside an
        # ambient use_settings()/radix2() scope must not snapshot that
        # scope's overrides into its permanent state.
        base = mx.default_settings()
        kw = {}
        if self.mxu_precision is not None:
            kw["precision"] = mx.as_precision(self.mxu_precision)
        if self.mxu_karatsuba is not None:
            kw["karatsuba"] = self.mxu_karatsuba
        if self.mxu_fourstep_einsum is not None:
            kw["fourstep_einsum"] = self.mxu_fourstep_einsum
        if self.mxu_direct_max is not None:
            kw["direct_max"] = self.mxu_direct_max
        return dc.replace(base, **kw)

    def resolved_comm2(self) -> CommMethod:
        return self.comm_method2 if self.comm_method2 is not None else self.comm_method

    def resolved_snd2(self) -> SendMethod:
        return self.send_method2 if self.send_method2 is not None else self.send_method

    def resolved_streams_chunks(self) -> int:
        """Chunk count for the STREAMS pipelined transpose (None -> 4)."""
        return self.streams_chunks if self.streams_chunks is not None else 4

    def resolved_overlap_depth(self) -> int:
        """Revolving receive-buffer depth of the overlap schedules
        (RING_OVERLAP's ring, the pipelined all_to_all's issue-ahead
        window). ``"auto"`` -> 2, the shipped double-buffered pipeline —
        so every pre-depth program (and its fingerprint pin) is traced
        op-for-op unchanged unless a deeper schedule is explicitly
        chosen or wisdom-resolved. Capped at the exchange's step count
        at trace time (``ring_transpose``) and in every descriptor
        (``ring_schedule`` / ``schedverify.describe``)."""
        return 2 if self.overlap_depth == AUTO else int(self.overlap_depth)

    def resolved_overlap_subblocks(self) -> int:
        """Sub-blocks each travelling block is split into (None -> 1,
        the monolithic per-peer block). On a ring rendering this is the
        block-granularity axis (each peer block -> S ppermute
        micro-steps); under ALL2ALL + SYNC/MPI_TYPE a value > 1 selects
        the pipelined all_to_all rendering with S chunked collectives.
        Clamped to the split axis extent at trace time."""
        return (self.overlap_subblocks
                if self.overlap_subblocks is not None else 1)

    def fused_wire_for(self, snd: "SendMethod") -> bool:
        """The fused-wire predicate for an exchange rendered by ``snd``:
        opt-in ``fused_wire`` on a ring rendering (RING/RING_OVERLAP)
        with the compressed bf16 wire — inert everywhere else (read
        POST-resolution; an unresolved "auto" wire never reaches the
        assemblers). The ONE activation condition every family and the
        shared hook builder (``pallas_fft.fused_ring_hooks``) consult,
        so the three assemblers cannot drift."""
        return bool(self.fused_wire and snd.is_ring
                    and self.wire_dtype == "bf16")

    def fused_wire_active(self, second: bool = False) -> bool:
        """``fused_wire_for`` of this plan's own (first or second)
        transpose rendering."""
        return self.fused_wire_for(self.resolved_snd2() if second
                                   else self.send_method)

    def resolved_wire_budget(self) -> float:
        """Max rel error the 'auto' wire race accepts from a compressed
        wire (None -> DEFAULT_WIRE_ERROR_BUDGET)."""
        return (self.wire_error_budget if self.wire_error_budget is not None
                else DEFAULT_WIRE_ERROR_BUDGET)

    def resolved_guards(self) -> str:
        """Guard mode: the explicit ``guards`` field, else ``$DFFT_GUARDS``,
        else "off". Read once at plan construction (resilience/guards.py),
        so a mid-run env change cannot split a plan's directions across
        modes."""
        if self.guards is not None:
            return self.guards
        import os
        env = os.environ.get("DFFT_GUARDS", "").strip()
        return parse_guards(env) if env else "off"
