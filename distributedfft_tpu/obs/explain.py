"""``dfft-explain`` — resolved-plan diagnostics WITHOUT executing the FFT.

After wisdom ("auto" resolution), the ring rendering and the wire layer, a
plan's actual shape — which collective program it builds, how many bytes
cross the wire, where its config values came from — is decided at
construction and was previously visible only by reading code or timing
runs. This executable answers "why did the plan do X" for a given
config + shape:

* decomposition: kind, partition/mesh, padded shapes, partition specs;
* the per-axis FFT sequence each pipeline stage runs;
* the resolved exchange rendering (default / realigned opt1 / ring /
  streams / GSPMD) per transpose;
* wire dtype and predicted wire bytes per exchange (``wire_nbytes`` over
  the exact padded payload the plan exchanges);
* wisdom provenance: store path, on-disk schema version, hit/miss per
  consulted slot, the recorded winners and when they were recorded
  (lookup-only — a miss is REPORTED, never raced, so explain runs no
  measurement);
* HLO collective census: the forward program is lowered and compiled
  (never executed) and ``microbench.async_collective_counts`` reports the
  collective / async-start / convert instance counts;
* roofline expectation (``evalkit/roofline.py``): nominal FFT flops, the
  MXU flops the matmul backend would issue, the v5e-effective-peak ideal
  time, and the tracked ``roofline_fraction`` for this size from the
  committed BENCH_DETAILS.json "roofline" block (ISSUE 10's gate);
* overlap schedule for ring-rendered exchanges (Ring / RingOverlap):
  blocks, revolving buffers, and the wire bytes in flight per device;
* checkpoint registry (``--checkpoint-dir`` / ``$DFFT_CKPT_DIR``): the
  persist store's generations (step, age, validity), the
  plan-fingerprint match verdict for THIS plan — from the same
  ``CheckpointStore.describe`` the restore path uses, so explain cannot
  disagree with restore — and the next scheduled write under the
  resolved ``CheckpointPolicy``.

Examples::

    dfft-explain --kind slab   -nx 256 -ny 256 -nz 256 -p 8 --emulate-devices 8
    dfft-explain --kind pencil -nx 64 -ny 64 -nz 64 -p1 2 -p2 4 \
        -snd1 Ring --emulate-devices 8
    dfft-explain --kind batched -nx 4096 -ny 4096 -nz 64 --shard x -p 8 \
        -wire bf16 --emulate-devices 8
"""

from __future__ import annotations

import argparse
import sys

# Shared with the graph section so explain and dfft-verify format bytes
# identically (the analysis chain is jax-free at import).
from ..analysis.plangraph import _fmt_bytes


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="dfft-explain", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--kind", choices=("slab", "pencil", "batched"),
                    default="slab", help="plan family to explain")
    ap.add_argument("--input-dim-x", "-nx", type=int, required=True)
    ap.add_argument("--input-dim-y", "-ny", type=int, required=True)
    ap.add_argument("--input-dim-z", "-nz", type=int, required=True,
                    help="(batched: the batch count, like dfft-batched)")
    ap.add_argument("--partitions", "-p", type=int, default=0,
                    help="slab/batched mesh width (default: all devices)")
    ap.add_argument("--partition1", "-p1", type=int, default=0,
                    help="pencil grid rows")
    ap.add_argument("--partition2", "-p2", type=int, default=0,
                    help="pencil grid cols")
    ap.add_argument("--sequence", "-s", default="ZY_Then_X",
                    help="slab sequence")
    ap.add_argument("--shard", default="batch", choices=("batch", "x"),
                    help="batched2d decomposed axis")
    ap.add_argument("--fft-dim", "-f", type=int, default=3,
                    choices=(1, 2, 3), help="pencil partial-transform depth")
    ap.add_argument("--comm-method", "-comm", "-comm1", dest="comm_method",
                    default="All2All")
    ap.add_argument("--comm-method2", "-comm2", default=None)
    ap.add_argument("--send-method", "-snd", "-snd1", dest="send_method",
                    default="Sync")
    ap.add_argument("--send-method2", "-snd2", default=None)
    ap.add_argument("--opt", "-o", type=int, default=0, choices=(0, 1))
    ap.add_argument("--streams-chunks", type=int, default=None)
    ap.add_argument("--overlap-depth", default="auto",
                    help="revolving-buffer depth for RingOverlap (2|4|8 or "
                         "'auto'; capped at ranks-1 micro-steps — the "
                         "schedule block reports the effective depth)")
    ap.add_argument("--overlap-subblocks", type=int, default=None,
                    help="split each peer block into this many sub-blocks "
                         "(rings) / pipeline the all-to-all in this many "
                         "chunks (All2All + Sync/MpiType)")
    ap.add_argument("--wire-dtype", "-wire", default="native",
                    choices=("native", "bf16", "auto"))
    ap.add_argument("--wire-error-budget", type=float, default=None)
    ap.add_argument("--fused-wire", action="store_true",
                    help="explain the fused Pallas wire-kernel rendering "
                         "(active on Ring/RingOverlap + bf16 wire only)")
    ap.add_argument("--guards", default=None,
                    choices=("off", "check", "enforce"),
                    help="explain the plan's resilience posture under this "
                         "guard mode (default: $DFFT_GUARDS -> off)")
    ap.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                    help="explain the persist/ checkpoint store here "
                         "(default $DFFT_CKPT_DIR): generations, age, "
                         "step, fingerprint-match verdict vs THIS plan")
    ap.add_argument("--checkpoint-policy", default=None,
                    metavar="steps:N[,secs:T][,drain:on|off]",
                    help="resolve the checkpoint cadence shown in the "
                         "checkpoint: section (default $DFFT_CKPT_POLICY)")
    ap.add_argument("--fft-backend", default="xla")
    ap.add_argument("--double_prec", "-d", action="store_true")
    ap.add_argument("--c2c", action="store_true",
                    help="explain the C2C transform instead of R2C")
    ap.add_argument("--wisdom", default=None, metavar="PATH")
    ap.add_argument("--no-wisdom", action="store_true")
    ap.add_argument("--emulate-devices", type=int, default=0,
                    help="force N virtual CPU devices (0 = real backend)")
    ap.add_argument("--no-compile", action="store_true",
                    help="skip the HLO collective census (no XLA compile; "
                         "everything else is pure bookkeeping)")
    ap.add_argument("--obs", action="store_true",
                    help="print the obs metrics snapshot after the report")
    ap.add_argument("--obs-dir", default=None,
                    help="write the obs event log here (same as "
                         "$DFFT_OBS_DIR)")
    ap.add_argument("--profile", action="store_true",
                    help="measure a stage-attributed device profile: run "
                         "the forward plan under jax.profiler.trace and "
                         "join device time back onto the declared plan "
                         "graph (obs/profile.py) — the ONE explain mode "
                         "that executes the FFT")
    ap.add_argument("--profile-iters", type=int, default=3,
                    help="profiled iterations for --profile (default 3; "
                         "one warmup run precedes the captured window)")
    return ap




def _rendering(comm, send, opt, p: int, fused_wire: bool = False,
               depth: int = 2, subblocks: int = 1) -> str:
    """One-line resolved rendering of a single transpose. ``depth`` /
    ``subblocks`` are the resolved overlap knobs — they pick the
    revolving-buffer ring wording (with the effective P-1 cap spelled
    out) and the pipelined all-to-all rendering."""
    from .. import params as pm
    sub = (f", each peer block split into {subblocks} sub-blocks"
           if subblocks > 1 else "")
    if send is pm.SendMethod.RING_OVERLAP:
        steps = f"{p - 1} distinct lax.ppermute step" \
            + ("s" if p > 2 else "")
        fused = (", fused Pallas wire kernels (encode-pack / decode+FFT)"
                 if fused_wire else "")
        micro = max(0, p - 1) * max(1, subblocks)
        buffers = min(depth, micro) if micro else 0
        if depth == 2 and subblocks == 1:
            return (f"ring-overlap — {steps} on the DOUBLE-BUFFERED "
                    "schedule (step t+1's permute issued before block t's "
                    f"FFT; bit-identical to Ring, reordered issue{fused})")
        cap = (f" — depth {depth} capped at {buffers} by the "
               f"{micro}-micro-step schedule" if buffers < depth else "")
        return (f"ring-overlap — {steps} on the depth-{depth} "
                f"REVOLVING-BUFFER schedule ({buffers} receive buffer"
                f"{'s' if buffers != 1 else ''} in flight{cap}{sub}; "
                f"bit-identical to Ring, reordered issue{fused})")
    if send is pm.SendMethod.RING:
        steps = f"{p - 1} distinct lax.ppermute step" \
            + ("s" if p > 2 else "")
        return (f"ring — {steps} (owns the rendering regardless of comm; "
                f"per-block FFTs pipelined where axis roles allow{sub})")
    layout = "realigned (opt1 pack, pure exchange)" if opt == 1 \
        else "default layout"
    if comm is pm.CommMethod.ALL2ALL:
        base = f"explicit shard_map lax.all_to_all, {layout}"
        if send is pm.SendMethod.STREAMS:
            return base + " — STREAMS: chunked into independent piece chains"
        if subblocks > 1:
            return (f"pipelined all-to-all — {subblocks} chunked "
                    f"collectives, chunk k+1 issued while chunk k decodes "
                    f"(revolving depth {depth}), {layout}; bit-identical "
                    "to the monolithic exchange")
        return base
    base = f"GSPMD (Peer2Peer) stage-boundary reshard, {layout}"
    if send is pm.SendMethod.STREAMS:
        return base + (" — STREAMS piece reshards (GSPMD re-fuses them "
                       "into ONE collective; honest no-op, see OVERLAP.md)")
    return base


def _wire_lines(shapes, cdt, cfg) -> list:
    """Wire block: per-exchange payload shape + wire bytes."""
    import numpy as np

    from ..parallel.transpose import wire_itemsize, wire_nbytes
    wire = cfg.wire_dtype
    lines = [f"  dtype: {wire}  "
             f"({wire_itemsize(cdt, wire)} B/elem on the wire vs "
             f"{np.dtype(cdt).itemsize} B logical)"]
    for label, shape in shapes:
        wb = wire_nbytes(shape, cdt, wire)
        lb = wire_nbytes(shape, cdt, "native")
        extra = "" if wire == "native" else \
            f" (native would be {_fmt_bytes(lb)})"
        lines.append(f"  {label}: payload {tuple(shape)} -> "
                     f"wire_nbytes {_fmt_bytes(wb)}{extra}")
    if wire == "bf16":
        lines.append(f"  lossy: ~2e-3 max rel err per crossing; budget "
                     f"{cfg.resolved_wire_budget():.0e} "
                     "(README 'wire dtype')")
    return lines


def _schedule_lines(xmeta, cdt, cfg) -> list:
    """Overlap-schedule block for ring-rendered exchanges (ISSUE 10/16):
    blocks (= ring steps), sub-block split, EFFECTIVE revolving buffers
    (the requested depth under the micro-step cap — depth 8 on 8 ranks
    holds 7 and this block says so), and the per-device wire bytes in
    flight for the chosen split — ``transpose.ring_schedule`` over the
    exact padded payload each exchange moves. Empty when no exchange is
    a ring."""
    from .. import params as pm
    from ..parallel.transpose import ring_schedule
    depth = cfg.resolved_overlap_depth()
    subblocks = cfg.resolved_overlap_subblocks()
    lines = []
    for label, shape, p, snd in xmeta:
        if not snd.is_ring:
            continue
        overlap = snd is pm.SendMethod.RING_OVERLAP
        sch = ring_schedule(shape, cdt, cfg.wire_dtype, p,
                            overlap=overlap, depth=depth,
                            subblocks=subblocks)
        split = ("" if sch["subblocks"] == 1 else
                 f" split into {sch['subblocks']} sub-blocks of "
                 f"{_fmt_bytes(sch['subblock_wire_bytes'])} "
                 f"({sch['permutes']} permutes),")
        cap = (f" (depth {depth} capped by the schedule)"
               if overlap and sch["effective_depth"] < depth else "")
        lines.append(
            f"  {label}: {sch['steps']} block(s) of "
            f"{_fmt_bytes(sch['block_wire_bytes'])} on the wire,{split} "
            f"{sch['buffers']} revolving buffer(s){cap}, "
            f"{_fmt_bytes(sch['bytes_in_flight'])} in flight per device "
            f"(mesh total {_fmt_bytes(sch['total_wire_bytes'])}, the "
            f"(P-1)/P ring discount)")
    return lines


def _wisdom_lines(prov) -> list:
    lines = []
    if prov["store_path"] is None:
        lines.append("  store: none configured (--wisdom / $DFFT_WISDOM "
                     "unset, or --no-wisdom)")
    else:
        v = prov["store_version"]
        vs = "absent on disk" if v is None else f"on-disk version {v}"
        lines.append(f"  store: {prov['store_path']} ({vs})")
    if not prov["slots"]:
        lines.append("  slots: none consulted (no 'auto' Config fields)")
        return lines
    for slot, info in prov["slots"].items():
        status = info["status"]
        if status == "hit":
            rec = info.get("record") or {}
            when = rec.get("recorded_at", "recorded_at unknown")
            detail = ", ".join(f"{k}={rec[k]}" for k in sorted(rec)
                               if k != "recorded_at")
            lines.append(f"  {slot}: hit ({detail}) [{when}]")
        elif status == "miss":
            lines.append(f"  {slot}: miss ({info.get('reason')}) — a real "
                         "run would race and record; defaults shown below")
        else:
            lines.append(f"  {slot}: {status}")
    return lines


def _resilience_lines(plan, cfg, prov) -> list:
    """Resilience posture: guard mode + derived tolerances, the fallback
    ladder that WOULD apply to this rendering, and any wisdom demotion
    stamps on the resolved cell (all static — nothing executes)."""
    import numpy as np

    from ..resilience import fallback, guards
    from ..utils import wisdom

    mode = plan._guard_mode
    src = ("Config.guards" if cfg.guards is not None
           else ("$DFFT_GUARDS" if mode != "off" else "default"))
    lines = [f"  guards: {mode} ({src})"]
    fwd = plan._guard_spec("forward")
    inv = plan._guard_spec("inverse")
    n = int(np.prod(fwd.in_logical))
    tol = guards.parseval_tolerance(cfg.double_prec, cfg.wire_dtype, n)
    dt = "f64" if cfg.double_prec else "f32"
    lines.append(f"  forward check: parseval, tolerance {tol:.2e} "
                 f"(dtype {dt}, wire {cfg.wire_dtype}, N={n})")
    lines.append(f"  inverse check: {inv.check}"
                 + ("" if inv.check == "parseval" else
                    " (C2R: arbitrary spectral input is not conjugate-"
                    "symmetric, so energy is not an invariant there)"))
    if cfg.wire_dtype != "native":
        lines.append(f"  wire drift probe: budget "
                     f"{cfg.resolved_wire_budget():.0e} "
                     "(one extra encode/decode of the spectral payload)")
    ladder = fallback.ladder_preview(cfg)
    if ladder:
        steps = " -> ".join(f"[{r}] {lbl}" for r, lbl in ladder)
        lines.append(f"  fallback ladder: {steps} -> error propagates")
    else:
        lines.append("  fallback ladder: none (default rendering — "
                     "failures propagate, never retried)")
    store = wisdom.store_for_config(cfg)
    stamps = []
    if store is not None:
        for slot in ("comm", "wire"):
            rec = store.lookup(prov["key"], slot)
            if rec and rec.get("demoted"):
                in_force = wisdom.demotion_active(rec)
                verdict = ("record reads as a miss; next race re-records"
                           if in_force else
                           "EXPIRED ($DFFT_DEMOTION_TTL_S) — record "
                           "re-admitted, stamp kept as history")
                stamps.append(
                    f"  demotion stamp [{slot}]: rung "
                    f"{rec.get('demoted_rung')} at "
                    f"{rec.get('demoted_at', '?')} — "
                    f"{rec.get('demoted_reason', '')[:80]} ({verdict})")
    lines += stamps if stamps else ["  demotion stamps: none"]
    return lines


def _checkpoint_lines(args, plan) -> list:
    """The ``checkpoint:`` section (ISSUE 14): the persist store's
    generation registry, the plan-fingerprint verdict for THIS plan, and
    the next scheduled write under the resolved policy. Built from the
    SAME ``CheckpointStore.describe``/``fingerprint_mismatch`` surface
    the restore path runs — explain cannot disagree with restore about
    which generation would load or why it would refuse."""
    import os as _os
    import time as _time

    from .. import persist
    ckdir = args.checkpoint_dir or _os.environ.get(persist.ENV_DIR, "")
    if not ckdir:
        return ["  store: none configured (--checkpoint-dir / "
                "$DFFT_CKPT_DIR unset)"]
    store = persist.CheckpointStore(ckdir)
    fp = persist.plan_fingerprint(plan)
    d = store.describe(expect_fingerprint=fp)
    lines = [f"  store: {d['directory']} "
             f"({len(persist.GENERATION_SLOTS)} generation slots)"]
    for g in d["generations"]:
        name = _os.path.basename(g["path"])
        if not g["exists"]:
            lines.append(f"  {name}: absent")
        elif g["valid"]:
            age = ("age unknown" if g["age_s"] is None
                   else f"age {g['age_s']:.1f} s")
            lines.append(f"  {name}: step {g['step']}, {age}, valid")
        else:
            lines.append(f"  {name}: INVALID ({g['reason']}) — restore "
                         "skips it (one-generation fallback)")
    lines.append(f"  plan fingerprint: {d['fingerprint_verdict']}")
    try:
        policy = persist.CheckpointPolicy.parse(
            args.checkpoint_policy
            or _os.environ.get(persist.ENV_POLICY))
    except ValueError as e:
        return lines + [f"  policy: INVALID spec ({e})"]
    latest = d["latest"]
    step = latest["step"] if latest else 0
    age = latest["age_s"] if latest and latest["age_s"] is not None else 0.0
    now = _time.monotonic()
    lines.append(f"  policy: {policy} — next write "
                 + policy.describe_next(step, step, now - age, now))
    return lines


def _serve_lines(args, kind: str, plan, cfg) -> list:
    """The ``serve:`` section: how a 2D request of this plane shape would
    be served by ``dfft-serve`` — the plan-cache key it would occupy,
    coalescing eligibility, and the circuit/ladder policy that would wrap
    it. Static (reuses the resolved plan/config; nothing executes)."""
    from .. import serve
    if kind == "batched":
        nx, ny = args.input_dim_x, args.input_dim_y
        shard = args.shard
        transform = plan.transform
        lead = []
    else:
        # The serving layer's unit of traffic is a single 2D image; for a
        # 3D plan, explain the (nx x ny) front-plane request a client
        # WOULD send (3D volumes go through the CLI/batch path).
        nx, ny = args.input_dim_x, args.input_dim_y
        shard = "batch"
        transform = "c2c" if args.c2c else "r2c"
        lead = ["  (dfft-serve serves single 2D images; this 3D plan runs "
                "through the CLI/batch path — below: the nx x ny 2D "
                "request a client would send)"]
    return lead + serve.describe_request(
        nx, ny, double=cfg.double_prec, transform=transform, shard=shard,
        config=cfg)


def _roofline_lines(args, kind: str, backend: str) -> list:
    """Roofline expectation for the explained workload (cube / batched-2D
    only — the shapes the MAC model covers). Non-smooth axes get the
    HONEST Bluestein accounting (padded chirp length + overhead factor)
    instead of a silently-wrong smooth-size number."""
    from ..evalkit import roofline as rl
    from ..testing.workloads import flops_batched2d, flops_roundtrip_3d
    nx, ny, nz = args.input_dim_x, args.input_dim_y, args.input_dim_z
    lines = []
    tshape = (nx, ny) if kind == "batched" else (nx, ny, nz)
    rough = rl.nonsmooth_axes(tshape)
    for n in rough:
        m, over = rl.bluestein_axis_report(n)
        lines.append(
            f"  non-smooth axis {n}: no native fast path — bluestein "
            f"chirp length {m} (padded), ~{over:.1f}x the flops of a "
            f"smooth axis per pass"
            + ("" if backend == "bluestein" else
               f"; backend {backend} runs it "
               + ("as a dense O(n^2) contraction"
                  if backend.startswith("matmul") or backend == "pallas"
                  else "through XLA's generic expansion")
               + " (fft_backend='bluestein' takes the chirp path)"))
    if rough:
        lines.append("  (the nominal 2.5·N·log2 N model below assumes "
                     "smooth axes; scale by the factors above)")
    if kind == "batched":
        if nx != ny:
            return lines + ["  (batched roofline model needs square "
                            "planes; skipped)"]
        nominal = flops_batched2d(nz, nx, ny)
        mxu4 = rl.mxu_flops_batched2d(nz, nx)
        mxu3 = rl.mxu_flops_batched2d(nz, nx, complex_mults=3)
        what = f"{nx}^2 x {nz} roundtrip"
    elif nx == ny == nz:
        nominal = flops_roundtrip_3d(nx)
        mxu4 = rl.mxu_flops_roundtrip_3d(nx)
        mxu3 = rl.mxu_flops_roundtrip_3d(nx, complex_mults=3)
        what = f"{nx}^3 roundtrip"
    else:
        return lines + ["  (MXU MAC model covers cubes and square batched "
                        "planes only; skipped for this shape)"]
    lines.append(f"  nominal FFT flops ({what}): {nominal / 1e9:.2f} GF "
                 "(2.5·N·log2 N per direction)")
    lines.append(f"  matmul-backend MXU flops: {mxu3 / 1e9:.2f}-"
                 f"{mxu4 / 1e9:.2f} GF (3mm-4mm complex-dot bracket)")
    peak = rl.effective_peak_tflops("high")
    ideal_ms = mxu4 / (peak * 1e12) * 1e3
    lines.append(f"  v5e effective peak @high: {peak:.1f} TFLOPS -> ideal "
                 f"matmul roundtrip >= {ideal_ms:.2f} ms "
                 "(100% MXU; backend here: " + backend + ")")
    # Predicted roofline_fraction (ISSUE 10 gate): the fraction a
    # measurement of this workload would score is ideal_ms/measured_ms;
    # quote the TRACKED value from the committed BENCH_DETAILS.json
    # "roofline" block when a row for this size exists (nothing here
    # executes — bench.py is the measurement side of the gate).
    key = f"{nx}^2x{nz}" if kind == "batched" else str(nx)
    tracked = rl.tracked_fractions()
    rec = tracked.get(key) or tracked.get(f"{key}^3")
    if rec:
        lines.append(
            f"  roofline_fraction (tracked): {rec['roofline_fraction']} "
            f"at ideal {rec['ideal_ms']} ms ({rec['model']}, "
            f"{rec.get('mode', 'roundtrip')}; committed "
            "BENCH_DETAILS.json — a perf PR must move this, CI fails a "
            ">10% regression)")
    else:
        lines.append(
            f"  roofline_fraction: predicted ideal/measured — ideal "
            f"{ideal_ms:.2f} ms at the 4mm bound; no tracked row for "
            f"{key!r} in BENCH_DETAILS.json (run bench.py to record one)")
    return lines


def _graph_lines(plan, dims: int) -> list:
    """The ``graph:`` section: the declared stage graph (nodes, per-edge
    wire bytes, ring schedule depth) from the SAME plangraph registry
    ``dfft-verify`` checks — explain cannot disagree with the verifier
    about what pipeline this plan declares. Purely declarative (nothing
    compiles); a family without a declaration is reported, the exact
    condition the verify matrix fails on."""
    from ..analysis import plangraph
    try:
        graph = plangraph.graph_for(plan, "forward", dims)
    except plangraph.MissingGraph as e:
        return [f"  none declared ({e}) — dfft-verify fails this combo"]
    lines = plangraph.format_graph(graph)
    findings = plangraph.check_graph(graph)
    if findings:
        lines += [f"  WELL-FORMEDNESS VIOLATION: {v}" for v in findings]
    else:
        lines.append(
            f"  well-formed: {len(graph.nodes)} node(s) checked "
            "(dataflow, wire pairing, dtype flow, payload, guard "
            "arity, ring-schedule hazards)")
    return lines


def _census_lines(compiled) -> list:
    from ..testing.microbench import async_collective_counts
    c = async_collective_counts(compiled)
    order = ("all_to_all", "all_to_all_start", "collective_permute",
             "collective_permute_start", "async_total", "convert")
    return ["  " + "  ".join(f"{k}: {c[k]}" for k in order)]


def _contract_line(plan, compiled, dims: int) -> str:
    """The one-line contract verdict, sourced from the SAME registry and
    checker ``dfft-verify`` runs (``analysis/contracts.py``) — explain
    and verify cannot disagree about whether this program honors its
    declared contract."""
    from ..analysis import contracts, hloscan
    try:
        contract = contracts.contract_for(plan, "forward", dims)
    except KeyError:
        return "  contract: unverified (no contract registered for this " \
               "plan family)"
    try:
        txt = compiled.as_text()
        census = hloscan.collective_census(txt)
        staged = None
        if any(r.kind == "payload" for r in contract.rules):
            staged = hloscan.staged_exchange_total(plan, "forward", dims)
        violations = contracts.check_contract(contract, census, txt, staged)
    except Exception as e:  # noqa: BLE001 — diagnostics must not abort
        return f"  contract: unverified ({type(e).__name__}: {e})"
    if violations:
        return (f"  contract: VIOLATED [{contract.name}] — "
                + "; ".join(str(v) for v in violations))
    return f"  contract: verified ({contract.name}, " \
           f"{len(contract.rules)} rule(s); dfft-verify runs the full " \
           "matrix)"


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from .. import obs
    if args.obs_dir:
        obs.enable(args.obs_dir)
    if args.obs:
        obs.enable_console()

    if args.emulate_devices:
        from ..parallel.mesh import force_cpu_devices
        force_cpu_devices(args.emulate_devices)

    import jax
    import numpy as np

    if args.double_prec:
        jax.config.update("jax_enable_x64", True)

    from .. import params as pm
    from ..testing import testcases as tc
    from ..utils import wisdom

    kind = args.kind
    transform = "c2c" if args.c2c else "r2c"
    nx, ny, nz = args.input_dim_x, args.input_dim_y, args.input_dim_z
    ndev = len(jax.devices())
    cfg = pm.Config(
        comm_method=pm.parse_comm_method(args.comm_method),
        send_method=pm.SendMethod.parse(args.send_method),
        comm_method2=(pm.parse_comm_method(args.comm_method2)
                      if args.comm_method2 else None),
        send_method2=(pm.SendMethod.parse(args.send_method2)
                      if args.send_method2 else None),
        opt=args.opt, double_prec=args.double_prec,
        fft_backend=args.fft_backend,
        streams_chunks=args.streams_chunks,
        overlap_depth=pm.parse_overlap_depth(args.overlap_depth),
        overlap_subblocks=args.overlap_subblocks,
        wire_dtype=pm.parse_wire_dtype(args.wire_dtype),
        wire_error_budget=args.wire_error_budget,
        fused_wire=bool(args.fused_wire),
        guards=args.guards,
        wisdom_path=args.wisdom, use_wisdom=not args.no_wisdom)

    if kind == "pencil":
        p1 = args.partition1 or 2
        p2 = args.partition2 or max(1, ndev // p1)
        partition = pm.PencilPartition(p1, p2)
        g = pm.GlobalSize(nx, ny, nz)
        mk_kind, variant, dims = "pencil", None, args.fft_dim
    elif kind == "batched":
        p = args.partitions or ndev
        partition = pm.SlabPartition(p)
        # Batched size-slot convention: (batch, nx, ny) with -nz = batch.
        g = pm.GlobalSize(nz, nx, ny)
        mk_kind, variant, dims = "batched2d", args.shard, 2
    else:
        p = args.partitions or ndev
        partition = pm.SlabPartition(p)
        g = pm.GlobalSize(nx, ny, nz)
        mk_kind, variant, dims = "slab", None, 3

    with obs.span("explain", kind=mk_kind, shape=list(g.shape)):
        # LOOKUP-ONLY resolution: a miss is reported, never raced —
        # explain must not execute measurement programs.
        cfg, prov = wisdom.peek_config(
            mk_kind, g, partition, cfg,
            sequence=args.sequence if kind == "slab" else None,
            transform=transform, dims=dims, variant=variant)

        # Build the plan with the fully concrete config (passes through
        # resolve_config untouched — no race can trigger).
        if kind == "batched":
            from ..models.batched2d import Batched2DFFTPlan
            plan = Batched2DFFTPlan(nz, nx, ny, partition, cfg,
                                    shard=args.shard, transform=transform)
        else:
            plan = tc.make_plan(mk_kind, g, partition, cfg,
                                sequence=args.sequence, transform=transform,
                                dims=dims)
        cfg = plan.config

        platform = jax.devices()[0].platform
        cdt = np.complex128 if args.double_prec else np.complex64
        rdt = (cdt if transform == "c2c"
               else (np.float64 if args.double_prec else np.float32))
        ranks = partition.num_ranks
        mesh_desc = (dict(plan.mesh.shape) if plan.mesh is not None
                     else "single-device (fft3d fallback)")

        out = []
        out.append(f"dfft-explain: {mk_kind} {g.nx}x{g.ny}x{g.nz} "
                   f"{transform} over {ranks} rank(s) on {platform} "
                   f"(mesh {mesh_desc})")

        out.append("decomposition:")
        out.append(f"  kind: {mk_kind}"
                   + (f"  sequence: {plan.sequence.value}"
                      if kind == "slab" else "")
                   + (f"  shard: {args.shard}" if kind == "batched" else "")
                   + (f"  dims: {dims}" if kind == "pencil" else ""))
        in_spec = getattr(plan, "_in_spec", None)
        out_spec = getattr(plan, "_out_spec", None)
        out.append(f"  input : logical {tuple(plan.input_shape)}  padded "
                   f"{tuple(plan.input_padded_shape)}  spec "
                   f"{in_spec if plan.mesh is not None else '—'}")
        out.append(f"  output: logical {tuple(plan.output_shape)}  padded "
                   f"{tuple(plan.output_padded_shape)}  spec "
                   f"{out_spec if plan.mesh is not None else '—'}")

        out.append("fft sequence:")
        xshapes = []  # (label, exchanged global payload shape)
        xmeta = []    # (label, payload shape, mesh axis size, send method)
        if kind == "slab":
            s = plan._seq
            first = ("C2C" if transform == "c2c" else "R2C") \
                + f" axis {'xyz'[s.r2c_axis]}"
            if s.pre_axes:
                first += " + C2C " + ",".join("xyz"[a] for a in s.pre_axes)
            out.append(f"  stage 1: {first}")
            if ranks > 1:
                out.append(f"  exchange: scatter {'xyz'[s.split_axis]} -> "
                           "gather x")
                xshapes.append(("transpose", plan.output_padded_shape))
                xmeta.append(("transpose", plan.output_padded_shape, ranks,
                              cfg.send_method))
            out.append("  stage 2: C2C "
                       + ",".join("xyz"[a] for a in s.post_axes))
        elif kind == "pencil":
            out.append("  stage 1: " + ("C2C z" if transform == "c2c"
                                        else "R2C z"))
            if dims >= 2 and ranks > 1:
                t1_shape = (plan._nx_p1, plan._ny_p2, plan._nzc_p2)
                out.append("  exchange 1 (p2 axis): scatter z -> gather y")
                xshapes.append(("transpose 1", t1_shape))
                xmeta.append(("transpose 1", t1_shape, plan.p2,
                              cfg.send_method))
            if dims >= 2:
                out.append("  stage 2: C2C y")
            if dims >= 3 and ranks > 1:
                t2_shape = (plan._nx_p1, plan._ny_p1, plan._nzc_p2)
                out.append("  exchange 2 (p1 axis): scatter y -> gather x")
                xshapes.append(("transpose 2", t2_shape))
                xmeta.append(("transpose 2", t2_shape, plan.p1,
                              cfg.resolved_snd2()))
            if dims >= 3:
                out.append("  stage 3: C2C x")
        else:
            out.append("  stage 1: " + ("C2C y" if transform == "c2c"
                                        else "R2C y") + " (per plane)")
            if args.shard == "x" and ranks > 1:
                out.append("  exchange: scatter spectral y -> gather x")
                bshape = (plan._batch_pad, plan._nx_pad, plan._nys_pad)
                xshapes.append(("transpose", bshape))
                xmeta.append(("transpose", bshape, ranks,
                              cfg.send_method))
                out.append("  stage 2: C2C x (per plane)")
            else:
                out.append("  stage 2: C2C x (per plane; batch sharding "
                           "issues no collectives)")

        out.append("rendering:")
        if ranks == 1 or (kind == "batched" and args.shard == "batch"):
            out.append("  no exchange: "
                       + ("single-device fft3d fallback" if ranks == 1
                          else "embarrassingly parallel batch sharding "
                               "(zero collectives)"))
        elif kind == "pencil":
            out.append(f"  transpose 1: comm {cfg.comm_method.value} snd "
                       f"{cfg.send_method.value} -> "
                       + _rendering(cfg.comm_method, cfg.send_method,
                                    cfg.opt, plan.p2,
                                    cfg.fused_wire_active(),
                                    depth=cfg.resolved_overlap_depth(),
                                    subblocks=cfg
                                    .resolved_overlap_subblocks()))
            if dims >= 3:
                out.append(f"  transpose 2: comm "
                           f"{cfg.resolved_comm2().value} snd "
                           f"{cfg.resolved_snd2().value} -> "
                           + _rendering(cfg.resolved_comm2(),
                                        cfg.resolved_snd2(), cfg.opt,
                                        plan.p1,
                                        cfg.fused_wire_active(True),
                                        depth=cfg.resolved_overlap_depth(),
                                        subblocks=cfg
                                        .resolved_overlap_subblocks()))
        else:
            out.append(f"  comm {cfg.comm_method.value} snd "
                       f"{cfg.send_method.value} opt {cfg.opt} -> "
                       + _rendering(cfg.comm_method, cfg.send_method,
                                    cfg.opt, ranks,
                                    cfg.fused_wire_active(),
                                    depth=cfg.resolved_overlap_depth(),
                                    subblocks=cfg
                                    .resolved_overlap_subblocks()))
        out.append(f"  local FFT backend: {cfg.fft_backend}"
                   + (f" (mxu_precision={cfg.mxu_precision}, "
                      f"mxu_direct_max={cfg.mxu_direct_max})"
                      if cfg.fft_backend.startswith("matmul") else ""))

        out.append("graph (declared stage graph, plangraph registry):")
        out.extend(_graph_lines(plan, dims))

        sched = _schedule_lines(xmeta, cdt, cfg)
        if sched:
            out.append("overlap schedule (ring exchange, per device):")
            out.extend(sched)

        out.append("wire:")
        if xshapes:
            out.extend(_wire_lines(xshapes, cdt, cfg))
        else:
            out.append("  no exchange -> nothing on the wire")

        out.append("wisdom:")
        out.extend(_wisdom_lines(prov))

        out.append("resilience:")
        out.extend(_resilience_lines(plan, cfg, prov))

        out.append("serve:")
        out.extend(_serve_lines(args, kind, plan, cfg))

        out.append("checkpoint:")
        out.extend(_checkpoint_lines(args, plan))

        if not args.no_compile:
            out.append("hlo census (forward program, compiled, "
                       "NOT executed):")
            try:
                with obs.span("explain.compile", kind=mk_kind):
                    if kind == "pencil":
                        fn = plan._build_r2c_d(dims)
                    elif kind == "batched":
                        fn = plan._build(forward=True)
                    else:
                        fn = plan._build_r2c()
                    arg = jax.ShapeDtypeStruct(
                        tuple(plan.input_padded_shape), rdt)
                    compiled = fn.lower(arg).compile()
                out.extend(_census_lines(compiled))
                out.append(_contract_line(plan, compiled, dims))
            except Exception as e:  # noqa: BLE001 — census is best-effort
                out.append(f"  unavailable: {type(e).__name__}: {e}")
        else:
            out.append("hlo census: skipped (--no-compile)")
            out.append("  contract: unverified (needs the compiled module "
                       "— drop --no-compile or run dfft-verify)")

        out.append("roofline (evalkit/roofline.py):")
        out.extend(_roofline_lines(args, kind, cfg.fft_backend))

        if args.profile:
            out.append("stage profile (MEASURED — jax.profiler trace of "
                       f"{max(1, args.profile_iters)} forward iteration(s), "
                       "device time joined onto the declared graph):")
            try:
                from . import profile as prof_mod
                with obs.span("explain.profile", kind=mk_kind):
                    prof = prof_mod.stage_profile(
                        plan, "forward", dims,
                        iters=max(1, args.profile_iters))
                out.extend(prof_mod.format_stage_profile(prof))
            except Exception as e:  # noqa: BLE001 — diagnostics only
                out.append(f"  unavailable: {type(e).__name__}: {e}")

        print("\n".join(out))

    if args.obs:
        import json
        print("obs metrics: "
              + json.dumps(obs.metrics.snapshot(), sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
