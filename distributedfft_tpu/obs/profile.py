"""Stage-attributed device profiling: join a ``jax.profiler`` trace back
against the declared plan graph.

The plan-graph IR (``analysis/plangraph.py``) says which stages a plan
*declares*; the host-side spans (``tracing.py``) say how long the *build*
took; nothing so far says where the DEVICE time of an execution goes —
how many of the 123.4 ms at 1024^3 are exchange vs local FFT vs wire
encode. This module closes that loop in three steps:

1. **Scope emission** — the plan families wrap each declared graph
   node's emitted ops in ``jax.named_scope("dfft/<family>/<node-id>")``
   (``stage_scope``), and the wire layer tags its encode/decode with
   ``dfft/wire/encode`` / ``dfft/wire/decode`` (``wire_scope``). Scopes
   are METADATA ONLY: they ride the op ``metadata={op_name=...}``
   attribute that ``hloscan.strip_metadata`` removes, so every
   fingerprint pin and the 171-combo verify matrix are byte-identical
   with scopes on (pinned by the ``scope-zero-overhead`` pins;
   ``disable_scopes()`` / ``$DFFT_NO_STAGE_SCOPES`` exist exactly so the
   pin has an off side to compare against).
2. **Trace ingestion** — ``capture_stage_profile`` runs a plan
   direction under ``jax.profiler.trace`` and parses the dumped
   ``*.xplane.pb`` (a minimal hand-rolled protobuf walker — the XSpace
   schema is stable and tiny, and the bench image has no tensorflow to
   parse it for us) or, as a fallback/fixture format, Chrome
   trace-events JSON (``parse_trace_events``). Nested op events (an XLA
   ``call`` wrapping its fusions) are resolved by SELF-TIME attribution
   so nothing is double counted.
3. **Graph join** — ``stage_profile`` aggregates device time by scope
   and joins it onto the declared graph: per-node device time, the
   exchange-vs-compute split, the unattributed remainder (dispatch,
   h2d, ops outside any scope — honesty line, never hidden), and a
   per-stage roofline-gap row (measured vs the nominal ideal for that
   node's axes). GSPMD (``p2p``) exchanges stage no explicit op to
   scope, so their collective lands in the unattributed remainder —
   reported, not guessed.

Consumers: ``dfft-explain --profile`` (the one explain mode that
executes), the four CLIs' ``--profile-stages`` epilogue, and the bench
mesh child's ``"stage_profile"`` block in BENCH_DETAILS.json.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import math
import os
import re
import tempfile
from typing import Any, Dict, Iterator, List, Optional, Tuple

SCOPE_PREFIX = "dfft"
ENV_NO_SCOPES = "DFFT_NO_STAGE_SCOPES"

# A scope path segment pair: "dfft/<family>/<node-id>" (also
# "dfft/wire/encode"). The op_name metadata embeds nested scopes as path
# segments; attribution takes the LAST (innermost) match.
SCOPE_RE = re.compile(r"dfft/([A-Za-z0-9_.-]+/[A-Za-z0-9_.:-]+)")

_SCOPES_FORCED_OFF = [False]


def scopes_enabled() -> bool:
    """Whether the families emit stage scopes (on by default; the
    zero-overhead pin toggles this to get its comparison side)."""
    if _SCOPES_FORCED_OFF[0]:
        return False
    return os.environ.get(ENV_NO_SCOPES, "").strip().lower() \
        not in ("1", "true", "on", "yes")


def disable_scopes() -> None:
    _SCOPES_FORCED_OFF[0] = True


def enable_scopes() -> None:
    _SCOPES_FORCED_OFF[0] = False


@contextlib.contextmanager
def scopes_off() -> Iterator[None]:
    """Force scopes off for one block, restoring the PRIOR forced state
    on exit — the zero-overhead pins' comparison side. Unlike a bare
    ``disable_scopes()``/``enable_scopes()`` pair this nests correctly
    inside a caller that already disabled scopes for its own baseline."""
    prev = _SCOPES_FORCED_OFF[0]
    _SCOPES_FORCED_OFF[0] = True
    try:
        yield
    finally:
        _SCOPES_FORCED_OFF[0] = prev


def scope_name(family: str, node_id: str) -> str:
    """The canonical scope string of one declared graph node."""
    return f"{SCOPE_PREFIX}/{family}/{node_id}"


def stage_scope(family: str, node_id: str):
    """``jax.named_scope`` for one declared node's ops (trace-time;
    metadata only). A no-op context when scopes are disabled, the node id
    is falsy (an undeclared exchange), or jax is absent."""
    if not node_id or not scopes_enabled():
        return contextlib.nullcontext()
    try:
        import jax
    except Exception:  # noqa: BLE001 — jax-free interpreter
        return contextlib.nullcontext()
    return jax.named_scope(scope_name(family, node_id))


def wire_scope(kind: str):
    """The wire layer's encode/decode scope (``dfft/wire/<kind>``) —
    nested inside the enclosing family exchange scope, so attribution
    can split wire time out of the exchange."""
    return stage_scope("wire", kind)


def scoped(family: str, node_id: str, fn):
    """Wrap a pipeline closure so its traced ops carry the node scope.
    A falsy ``node_id`` (an exchange the graph does not declare, e.g. a
    size-1 mesh axis) passes the closure through unscoped."""
    if fn is None or not node_id:
        return fn

    def wrapped(*args, **kwargs):
        with stage_scope(family, node_id):
            return fn(*args, **kwargs)

    return wrapped


# ---------------------------------------------------------------------------
# xplane parsing (minimal protobuf walker over the XSpace schema)
# ---------------------------------------------------------------------------

def _pb_fields(buf: bytes) -> Iterator[Tuple[int, int, Any]]:
    """Yield ``(field_no, wire_type, value)`` over one protobuf message.
    Varint and length-delimited fields decode; fixed32/64 pass as raw
    bytes. Raises ValueError on malformed input (callers treat that as
    'not a message')."""
    i, n = 0, len(buf)
    while i < n:
        tag, shift = 0, 0
        while True:
            if i >= n:
                raise ValueError("truncated tag")
            b = buf[i]
            i += 1
            tag |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
        fno, wt = tag >> 3, tag & 7
        if wt == 0:
            v, shift = 0, 0
            while True:
                if i >= n:
                    raise ValueError("truncated varint")
                b = buf[i]
                i += 1
                v |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            yield fno, wt, v
        elif wt == 2:
            ln, shift = 0, 0
            while True:
                if i >= n:
                    raise ValueError("truncated length")
                b = buf[i]
                i += 1
                ln |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            if i + ln > n:
                raise ValueError("truncated bytes field")
            yield fno, wt, buf[i:i + ln]
            i += ln
        elif wt == 5:
            yield fno, wt, buf[i:i + 4]
            i += 4
        elif wt == 1:
            yield fno, wt, buf[i:i + 8]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")


def _collect_strings(buf: bytes, depth: int = 0, limit: int = 1) -> List[str]:
    """Shallow utf-8-decodable length-delimited fields of a message tree
    — the schema-drift-robust way to find an event metadata's OWN
    op_name strings (XEventMetadata.name/display_name, a tf_op stat
    string, a direct OpMetadata stat). Depth-limited to 1 so a full HLO
    module proto embedded in a module-level event's stats does NOT leak
    its per-instruction op_names onto that wrapper event —
    ``_harvest_hlo_scopes`` mines those separately and joins them by
    instruction name."""
    out: List[str] = []
    if depth > limit:
        return out
    try:
        for _, wt, v in _pb_fields(buf):
            if wt != 2 or not isinstance(v, bytes):
                continue
            try:
                s = v.decode("utf-8")
            except UnicodeDecodeError:
                s = None
            if s is not None and s.isprintable() and s:
                out.append(s)
            if len(v) > 3:
                out.extend(_collect_strings(v, depth + 1, limit))
    except ValueError:
        pass
    return out


def extract_scope(strings: List[str]) -> Optional[str]:
    """Innermost ``dfft/<x>/<y>`` scope across the given strings (last
    match of the LONGEST matching string, so the full nested path wins
    over a short prefix duplicate)."""
    best: Optional[str] = None
    best_len = -1
    for s in strings:
        ms = SCOPE_RE.findall(s)
        if ms and len(s) > best_len:
            best, best_len = ms[-1], len(s)
    return best


_INSTR_NAME_RE = re.compile(r"^[A-Za-z0-9_.\-]+$")


def _harvest_hlo_scopes(buf: bytes, out: Dict[str, str],
                        depth: int = 0) -> None:
    """Instruction-name -> scope map from any serialized HLO module
    embedded in a plane's stats. The CPU/TPU profilers attach the
    compiled module's HloProto to a module-level event; per-op events
    then carry only the instruction NAME (``fft.7``,
    ``transpose_copy_fusion.2``) — the op_name path with the named
    scopes lives on the proto's instructions. Schema-lightly: any
    message with a name-shaped field 1 string and a field 7 submessage
    whose field 2 matches the scope pattern is an HloInstructionProto
    (name=1, metadata=7{op_name=2}). First mapping wins (HLO names are
    unique module-wide; across modules a collision keeps the first)."""
    if depth > 12:
        return
    try:
        fields = list(_pb_fields(buf))
    except ValueError:
        return
    name: Optional[str] = None
    scope: Optional[str] = None
    for fno, wt, v in fields:
        if fno == 1 and wt == 2 and isinstance(v, bytes):
            try:
                s = v.decode("utf-8")
            except UnicodeDecodeError:
                continue
            if _INSTR_NAME_RE.match(s):
                name = s
        elif fno == 7 and wt == 2 and isinstance(v, bytes):
            try:
                for f2, w2, v2 in _pb_fields(v):
                    if f2 == 2 and w2 == 2 and isinstance(v2, bytes):
                        try:
                            s2 = v2.decode("utf-8")
                        except UnicodeDecodeError:
                            continue
                        ms = SCOPE_RE.findall(s2)
                        if ms:
                            scope = ms[-1]
            except ValueError:
                pass
    if name and scope:
        out.setdefault(name, scope)
    for fno, wt, v in fields:
        if wt == 2 and isinstance(v, bytes) and len(v) > 8:
            _harvest_hlo_scopes(v, out, depth + 1)


def parse_xplane(data: bytes) -> List[Dict[str, Any]]:
    """Parse one ``*.xplane.pb`` (XSpace) into
    ``[{"name", "lines": [{"name", "events": [{"name", "scope",
    "offset_ps", "dur_ps"}]}]}]``. Only the fields attribution needs."""
    planes: List[Dict[str, Any]] = []
    # Pass 1 — instruction-name -> scope from every embedded HLO module
    # proto in the WHOLE space: the profiler parks the serialized module
    # on a metadata plane (``/host:metadata``) while the op events live
    # on the execution planes, so the map must be global.
    name_scopes: Dict[str, str] = {}
    for fno, wt, v in _pb_fields(data):
        if fno == 1 and wt == 2:
            _harvest_hlo_scopes(v, name_scopes)
    for fno, wt, v in _pb_fields(data):
        if fno != 1 or wt != 2:
            continue
        name = ""
        raw_lines: List[bytes] = []
        emeta: Dict[int, Dict[str, Any]] = {}
        for f2, w2, v2 in _pb_fields(v):
            if f2 == 2 and w2 == 2:
                name = v2.decode(errors="replace")
            elif f2 == 3 and w2 == 2:
                raw_lines.append(v2)
            elif f2 == 4 and w2 == 2:
                # map<int64, XEventMetadata> entry: key=1, value=2
                key: Optional[int] = None
                mname = ""
                strings: List[str] = []
                for f3, w3, v3 in _pb_fields(v2):
                    if f3 == 1 and w3 == 0:
                        key = v3
                    elif f3 == 2 and w3 == 2:
                        strings = _collect_strings(v3)
                        for f4, w4, v4 in _pb_fields(v3):
                            if f4 == 2 and w4 == 2:
                                mname = v4.decode(errors="replace")
                if key is not None:
                    emeta[key] = {"name": mname,
                                  "scope": extract_scope(strings)}
        lines = []
        for lv in raw_lines:
            lname = ""
            events: List[Dict[str, Any]] = []
            for f2, w2, v2 in _pb_fields(lv):
                if f2 in (2, 11) and w2 == 2:
                    lname = v2.decode(errors="replace")
                elif f2 == 4 and w2 == 2:
                    mid: Optional[int] = None
                    off = 0
                    dur = 0
                    for f3, w3, v3 in _pb_fields(v2):
                        if f3 == 1 and w3 == 0:
                            mid = v3
                        elif f3 == 2 and w3 == 0:
                            off = v3
                        elif f3 == 3 and w3 == 0:
                            dur = v3
                    meta = emeta.get(mid, {})
                    ename = meta.get("name", "")
                    scope = meta.get("scope") or name_scopes.get(ename)
                    events.append({"name": ename, "scope": scope,
                                   "offset_ps": off, "dur_ps": dur})
            lines.append({"name": lname, "events": events})
        planes.append({"name": name, "lines": lines})
    return planes


# ---------------------------------------------------------------------------
# trace-events parsing (perfetto/chrome JSON; also the committed fixture)
# ---------------------------------------------------------------------------

def parse_trace_events(obj: Any) -> List[Dict[str, Any]]:
    """Chrome trace-events JSON (``{"traceEvents": [...]}`` or a bare
    list) -> the same event dicts ``parse_xplane`` produces, one flat
    line. ``ph == "X"`` complete events only; scope extracted from the
    event name and any string args; timestamps are microseconds in this
    format (converted to ps for uniformity)."""
    evs = obj.get("traceEvents", []) if isinstance(obj, dict) else obj
    out: List[Dict[str, Any]] = []
    for e in evs:
        if not isinstance(e, dict) or e.get("ph") != "X":
            continue
        strings = [str(e.get("name", ""))]
        args = e.get("args")
        if isinstance(args, dict):
            strings += [str(v) for v in args.values()
                        if isinstance(v, str)]
        out.append({"name": str(e.get("name", "")),
                    "scope": extract_scope(strings),
                    "offset_ps": int(float(e.get("ts", 0)) * 1e6),
                    "dur_ps": int(float(e.get("dur", 0)) * 1e6)})
    return out


def load_trace(path: str) -> List[Dict[str, Any]]:
    """One trace artifact -> planes. ``.pb`` parses as xplane;
    ``.json``/``.json.gz`` as trace-events (wrapped in one synthetic
    plane so the aggregation sees a uniform shape)."""
    if path.endswith(".pb"):
        with open(path, "rb") as f:
            return parse_xplane(f.read())
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8") as f:  # type: ignore[operator]
        obj = json.load(f)
    return [{"name": "trace-events",
             "lines": [{"name": "events",
                        "events": parse_trace_events(obj)}]}]


def find_trace_files(logdir: str) -> List[str]:
    """The newest profiler run directory's parseable artifacts, xplane
    preferred (per-op device events; the CPU backend's trace.json carries
    only host python events)."""
    runs = sorted(glob.glob(os.path.join(logdir, "plugins", "profile", "*")))
    if not runs:
        runs = [logdir]
    run = runs[-1]
    pbs = sorted(glob.glob(os.path.join(run, "*.xplane.pb")))
    if pbs:
        return pbs
    return sorted(glob.glob(os.path.join(run, "*trace.json.gz")) +
                  glob.glob(os.path.join(run, "*trace.json")))


# ---------------------------------------------------------------------------
# aggregation (self-time, per scope)
# ---------------------------------------------------------------------------

# Lines that carry host python bookkeeping, not op executions.
_SKIP_LINES = re.compile(r"^(python|launcher|\$)", re.IGNORECASE)


def _self_times(events: List[Dict[str, Any]]) -> List[Tuple[
        Optional[str], float]]:
    """``(scope, self_time_ps)`` per event of ONE line: an event interval
    that contains other events is charged only for the time its children
    do not cover (flame-graph self time), so a ``call`` op wrapping a
    fusion is not counted twice."""
    evs = [e for e in events if e.get("dur_ps", 0) > 0]
    evs.sort(key=lambda e: (e["offset_ps"], -e["dur_ps"]))
    out: List[Tuple[Optional[str], float]] = []
    stack: List[Dict[str, Any]] = []  # open ancestors, innermost last
    child_time: List[float] = []
    for e in evs:
        end = e["offset_ps"] + e["dur_ps"]
        while stack and e["offset_ps"] >= \
                stack[-1]["offset_ps"] + stack[-1]["dur_ps"]:
            parent = stack.pop()
            covered = child_time.pop()
            out.append((parent.get("scope"),
                        max(0.0, parent["dur_ps"] - covered)))
        if stack and end <= stack[-1]["offset_ps"] + stack[-1]["dur_ps"]:
            child_time[-1] += e["dur_ps"]
        stack.append(e)
        child_time.append(0.0)
    while stack:
        parent = stack.pop()
        covered = child_time.pop()
        out.append((parent.get("scope"),
                    max(0.0, parent["dur_ps"] - covered)))
    return out


def aggregate_trace(planes: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate device time by scope over the op-execution lines.
    Device planes (``/device:...``) win when present (TPU); host planes
    otherwise (the CPU backend runs its ops on host thread-pool lines).
    Returns ``{"scopes": {scope: ms}, "unattributed_ms", "total_ms",
    "planes": [names]}`` — python bookkeeping lines are skipped, nested
    ops resolved by self time."""
    device = [p for p in planes if p["name"].startswith("/device:")
              and any(ln["events"] for ln in p["lines"])]
    chosen = device or [p for p in planes
                        if any(ln["events"] for ln in p["lines"])]
    scopes: Dict[str, float] = {}
    unattributed = 0.0
    for plane in chosen:
        for line in plane["lines"]:
            if _SKIP_LINES.match(line["name"] or ""):
                continue
            for scope, ps in _self_times(line["events"]):
                if scope:
                    scopes[scope] = scopes.get(scope, 0.0) + ps
                else:
                    unattributed += ps
    to_ms = 1e-9  # ps -> ms
    return {
        "scopes": {k: round(v * to_ms, 6) for k, v in sorted(scopes.items())},
        "unattributed_ms": round(unattributed * to_ms, 6),
        "total_ms": round((sum(scopes.values()) + unattributed) * to_ms, 6),
        "planes": [p["name"] for p in chosen],
    }


# ---------------------------------------------------------------------------
# capture (executes the plan — the ONE obs surface that runs the FFT)
# ---------------------------------------------------------------------------

def capture_stage_profile(plan: Any, direction: str = "forward",
                          dims: int = 3, iters: int = 3,
                          warmup: int = 1) -> Dict[str, Any]:
    """Run one direction of a live plan under ``jax.profiler.trace`` and
    aggregate its device time by stage scope. Input is synthesized at the
    padded aval and device_put BEFORE the profiled window, so transfer
    time does not pollute the attribution. Times are per iteration."""
    import jax
    import numpy as np

    from ..analysis import hloscan
    from . import tracing

    runner = hloscan._builder(plan, direction, dims)
    aval = hloscan._input_aval(plan, direction, dims)
    rng = np.random.default_rng(0)
    if np.dtype(aval.dtype).kind == "c":
        x = (rng.standard_normal(aval.shape)
             + 1j * rng.standard_normal(aval.shape)).astype(aval.dtype)
    else:
        x = rng.standard_normal(aval.shape).astype(aval.dtype)
    sharding = (plan.input_sharding if direction == "forward"
                else plan.output_sharding)
    xd = jax.device_put(x, sharding) if sharding is not None \
        else jax.device_put(x)
    for _ in range(max(0, warmup)):
        jax.block_until_ready(runner(xd))
    iters = max(1, iters)
    with tempfile.TemporaryDirectory() as td:
        with tracing.span("profile.capture", direction=direction,
                          iters=iters):
            with jax.profiler.trace(td):
                for _ in range(iters):
                    jax.block_until_ready(runner(xd))
        files = find_trace_files(td)
        if not files:
            raise RuntimeError(
                f"jax.profiler.trace produced no parseable artifact "
                f"under {td} (xplane/trace-events expected)")
        planes: List[Dict[str, Any]] = []
        for f in files:
            planes.extend(load_trace(f))
    agg = aggregate_trace(planes)
    agg = {
        "scopes": {k: round(v / iters, 6)
                   for k, v in agg["scopes"].items()},
        "unattributed_ms": round(agg["unattributed_ms"] / iters, 6),
        "total_ms": round(agg["total_ms"] / iters, 6),
        "planes": agg["planes"],
    }
    agg["iters"] = iters
    agg["direction"] = direction
    return agg


# ---------------------------------------------------------------------------
# graph join
# ---------------------------------------------------------------------------

def node_scope_key(graph: Any, node: Any) -> Optional[str]:
    """The aggregation key one declared node's device time lands under
    (None = the node stages nothing attributable: input/output, and
    GSPMD-owned exchanges whose collective no explicit op carries)."""
    if node.kind in ("input", "output"):
        return None
    if node.kind == "exchange":
        if node.rendering == "p2p":
            return None
        return f"{graph.family}/{node.id}"
    if node.kind in ("local_fft", "guard"):
        return f"{graph.family}/{node.id}"
    if node.encodes():
        return "wire/encode"
    if node.decodes():
        return "wire/decode"
    return None


def _node_ideal_ms(graph: Any, node: Any, ranks: int) -> Optional[float]:
    """Nominal ideal time of one local-FFT stage: 2.5*N*log2(extent) per
    transformed axis over the v5e effective peak, per-chip share on the
    mesh (the roofline module's convention — communication deliberately
    unmodeled, so exchange nodes have no ideal; their measured time IS
    the roofline gap)."""
    if node.kind != "local_fft" or not node.axes:
        return None
    from ..evalkit import roofline as rl
    in_edges = graph.in_edges(node.id)
    if not in_edges:
        return None
    shape = in_edges[0].shape
    elems = 1
    for s in shape:
        elems *= int(s)
    flops = 0.0
    for a in node.axes:
        if 0 <= a < len(shape) and shape[a] > 1:
            flops += 2.5 * elems * math.log2(shape[a])
    if flops <= 0:
        return None
    peak = rl.effective_peak_tflops("high") * 1e12 * max(1, ranks)
    # Significant-digit rounding (the roofline_row convention): a tiny
    # CPU tracking ideal must never collapse to 0.0.
    return float(f"{flops / peak * 1e3:.4g}")


def stage_profile(plan: Any, direction: str = "forward", dims: int = 3,
                  iters: int = 3, warmup: int = 1,
                  capture: Optional[Dict[str, Any]] = None
                  ) -> Dict[str, Any]:
    """The joined stage-attribution report: capture (or reuse
    ``capture``), resolve the declared graph, and emit one row per
    declared node — device time, fraction of the measured total, and the
    per-stage roofline gap — plus the exchange-vs-compute split and the
    unattributed remainder. This is the ``"stage_profile"`` block shape
    bench.py commits and ``dfft-explain --profile`` prints."""
    from ..analysis import plangraph

    graph = plangraph.graph_for(plan, direction, dims)
    agg = capture if capture is not None else capture_stage_profile(
        plan, direction, dims, iters=iters, warmup=warmup)
    scopes = dict(agg["scopes"])
    total = float(agg["total_ms"]) or 1e-12
    ranks = 1
    mesh = getattr(plan, "mesh", None)
    if mesh is not None:
        ranks = math.prod(mesh.devices.shape)
    # Nodes sharing one scope key (two encodes under a dual-exchange p2p
    # pencil both land in "wire/encode") split that key's time evenly.
    keys: Dict[str, List[Any]] = {}
    for n in graph.nodes:
        k = node_scope_key(graph, n)
        if k is not None:
            keys.setdefault(k, []).append(n)
    rows: List[Dict[str, Any]] = []
    consumed: Dict[str, float] = {}
    exchange_ms = 0.0
    compute_ms = 0.0
    for n in graph.nodes:
        k = node_scope_key(graph, n)
        share = None
        approx = False
        if k is not None:
            t = scopes.get(k, 0.0)
            nshare = len(keys[k])
            share = t / nshare
            approx = nshare > 1
            consumed[k] = t
        ms = round(share, 6) if share is not None else 0.0
        ideal = _node_ideal_ms(graph, n, ranks)
        row: Dict[str, Any] = {
            "node": n.id, "kind": n.kind,
            "label": n.label or plangraph._node_brief(n),
            "device_ms": ms,
            "fraction": round(ms / total, 4),
        }
        if k is None and n.kind == "exchange":
            row["note"] = ("gspmd-owned exchange: collective carries no "
                           "stage scope; its time is in the "
                           "unattributed remainder")
        if approx:
            row["approx"] = True
        if ideal is not None:
            row["ideal_ms"] = ideal
            if ms > 0 and ideal > 0:
                row["gap_x"] = float(f"{ms / ideal:.3g}")
        if n.kind in ("exchange", "encode", "decode", "fused_kernel"):
            exchange_ms += ms
        elif n.kind in ("local_fft", "guard"):
            compute_ms += ms
        rows.append(row)
    other = {k: v for k, v in scopes.items() if k not in consumed}
    attributed = sum(consumed.values())
    return {
        "family": graph.family,
        "direction": direction,
        "iters": agg.get("iters", iters),
        "total_ms": round(total, 6),
        "attributed_ms": round(attributed, 6),
        "unattributed_ms": round(
            float(agg["unattributed_ms"]) + sum(other.values()), 6),
        "exchange_ms": round(exchange_ms, 6),
        "compute_ms": round(compute_ms, 6),
        "exchange_fraction": round(exchange_ms / total, 4),
        "stages": rows,
        "other_scopes": other,
        "planes": agg.get("planes", []),
    }


def format_stage_profile(prof: Dict[str, Any]) -> List[str]:
    """Human-readable stage table (the ``dfft-explain --profile`` and
    ``--profile-stages`` rendering)."""
    lines = [
        f"  {prof['family']}/{prof['direction']}: total "
        f"{prof['total_ms']:.3f} ms/iter over {prof['iters']} iter(s) — "
        f"exchange {prof['exchange_ms']:.3f} ms "
        f"({prof['exchange_fraction']:.0%}), compute "
        f"{prof['compute_ms']:.3f} ms, unattributed "
        f"{prof['unattributed_ms']:.3f} ms"]
    for row in prof["stages"]:
        if row["kind"] in ("input", "output"):
            continue
        extra = ""
        if "ideal_ms" in row:
            extra = f"  ideal {row['ideal_ms']:.4g} ms"
            if "gap_x" in row:
                extra += f" (gap {row['gap_x']:g}x)"
        if row.get("approx"):
            extra += "  [shared scope, split evenly]"
        if row.get("note"):
            extra += f"  [{row['note']}]"
        lines.append(
            f"  {row['node']:<16} {row['device_ms']:>10.3f} ms  "
            f"{row['fraction']:>6.1%}{extra}")
    if prof["other_scopes"]:
        for k, v in sorted(prof["other_scopes"].items()):
            lines.append(f"  (other scope {k}: {v:.3f} ms)")
    return lines
