"""Structured observability: span tracing, metrics registry, plan explain.

Three coordinated pieces (none of which may perturb a compiled program —
the zero-overhead-when-off contract is pinned by ``tests/test_obs.py``):

* ``obs.span("plan.build") / obs.event / obs.notice`` — host-side span
  tracing into a per-run JSONL event log under ``$DFFT_OBS_DIR`` (default
  off), with ``jax.profiler.TraceAnnotation`` mirroring the names into
  TensorBoard/Perfetto traces (``tracing.py``).
* ``obs.metrics`` — process-global named counters/gauges with a
  ``snapshot()`` dict that ``bench.py`` folds into ``BENCH_DETAILS.json``
  and the CLIs print under ``--obs`` (``metrics.py``).
* ``dfft-explain`` — resolved-plan diagnostics without executing the FFT
  (``explain.py``; registered in pyproject.toml).

This package imports no jax at module import time, so ``params``-level
(device-free) usage stays possible.
"""

from . import metrics
from .tracing import (ENV_VAR, console_enabled, disable, disable_console,
                      enable, enable_console, enabled, event, event_log_path,
                      notice, obs_dir, reset_enablement, span, validate_event,
                      validate_events_dir, validate_events_file)

__all__ = [
    "ENV_VAR", "console_enabled", "disable", "disable_console", "enable",
    "enable_console", "enabled", "event", "event_log_path", "metrics",
    "notice", "obs_dir", "reset_enablement", "snapshot", "reset", "span",
    "validate_event", "validate_events_dir", "validate_events_file",
]


def snapshot():
    """Shorthand for ``metrics.snapshot()``."""
    return metrics.snapshot()


def reset():
    """Shorthand for ``metrics.reset()`` (does not touch enablement)."""
    metrics.reset()
