"""Structured observability: spans, metrics, flight recorder, profiling.

Six coordinated pieces (none of which may perturb a compiled program —
the zero-overhead-when-off contract is pinned by ``tests/test_obs.py``):

* ``obs.span("plan.build") / obs.event / obs.notice`` — host-side span
  tracing into a per-run JSONL event log under ``$DFFT_OBS_DIR`` (default
  off), with ``jax.profiler.TraceAnnotation`` mirroring the names into
  TensorBoard/Perfetto traces (``tracing.py``).
* ``obs.metrics`` — process-global counters/gauges/latency histograms
  with dual per-plan vs cumulative views; ``bench.py`` folds the per-plan
  ``snapshot()`` into ``BENCH_DETAILS.json``, the Prometheus exposition
  renders the cumulative one (``metrics.py``).
* ``obs.flightrec`` — the ALWAYS-ON bounded in-memory ring of recent
  spans/events/metric deltas, dumped to JSONL on trigger
  (GuardViolation, circuit open, demotion, shed burst, SIGUSR2) — zero
  file I/O in steady state (``flightrec.py``).
* ``obs.promexp`` — Prometheus text exposition of the cumulative metrics
  view; ``dfft-serve --http`` serves it at ``GET /metrics``
  (``promexp.py``).
* ``obs.profile`` — stage-attributed device profiling: ``jax.named_scope``
  emission per declared plan-graph node (metadata only — every
  fingerprint pin holds with scopes on), a ``jax.profiler`` xplane/
  trace-events ingester, and the graph join behind
  ``dfft-explain --profile`` (``profile.py``).
* ``dfft-explain`` — resolved-plan diagnostics without executing the FFT
  (``explain.py``; registered in pyproject.toml; ``--profile`` is the one
  mode that executes).

This package imports no jax at module import time, so ``params``-level
(device-free) usage stays possible.
"""

from . import flightrec, metrics, profile, promexp
from .tracing import (ENV_VAR, console_enabled, disable, disable_console,
                      enable, enable_console, enabled, event, event_log_path,
                      notice, obs_dir, reset_enablement, span, validate_event,
                      validate_events_dir, validate_events_file)

__all__ = [
    "ENV_VAR", "console_enabled", "disable", "disable_console", "enable",
    "enable_console", "enabled", "event", "event_log_path", "flightrec",
    "metrics", "notice", "obs_dir", "profile", "promexp",
    "reset_enablement", "snapshot", "reset", "span", "validate_event",
    "validate_events_dir", "validate_events_file",
]


def snapshot():
    """Shorthand for ``metrics.snapshot()``."""
    return metrics.snapshot()


def reset():
    """Shorthand for ``metrics.reset()`` (does not touch enablement)."""
    metrics.reset()
