"""Prometheus text exposition of the obs metrics registry.

Renders the ALWAYS-CUMULATIVE view of ``obs/metrics.py`` in the
Prometheus text exposition format (version 0.0.4): counters as
``dfft_<name>_total`` (monotone across ``obs.reset()`` — the registry's
dual-view contract exists exactly so a scrape never sees a counter go
backwards), gauges as ``dfft_<name>``, and the latency histograms as
Prometheus histograms (cumulative ``_bucket{le="..."}`` series plus
``_sum``/``_count``). ``dfft-serve --http`` serves this at
``GET /metrics`` — the scrape surface ROADMAP item 2c's autoscaling
controller reads; the CI serve-chaos job scrapes it mid-drive and runs
``validate_exposition`` over the body.

Metric names are sanitized (dots and other non-name characters become
``_``) and prefixed ``dfft_``; the original registry name is kept in the
``# HELP`` line so the mapping stays greppable.

**Label convention** (ISSUE 13): the flat registry encodes Prometheus
labels in the metric NAME as a ``[key=value,...]`` suffix —
``metrics.inc("fleet.tenant.shed[tenant=acme]")`` renders as
``dfft_fleet_tenant_shed_total{tenant="acme"} 1``. Every labeled series
of a family shares ONE ``# TYPE``/``# HELP`` header (the exposition
format forbids duplicates), and label values are escaped per the
exposition rules. ``obs.metrics.labeled`` builds the convention; the
fleet uses it for per-tenant and per-worker series.

``validate_exposition`` is a strict-enough format checker for CI and
tests: line grammar, TYPE-before-samples, histogram bucket monotonicity
and the ``+Inf``-bucket == ``_count`` invariant. It validates structure,
not semantics — a scrape target can only promise the former.
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional, Tuple

from . import metrics

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{([^}]*)\})?"
    r"\s+([^\s]+)(?:\s+(-?\d+))?$")
_LABEL_RE = re.compile(r'^\s*([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"'
                       r"\s*(?:,|$)")


def sanitize(name: str) -> str:
    """Registry name -> Prometheus metric name body (dots and other
    non-name characters become ``_``)."""
    out = re.sub(r"[^a-zA-Z0-9_:]", "_", str(name))
    if not out or not _NAME_RE.match(out):
        out = "_" + out
    return out


_LABELED_NAME_RE = re.compile(r"^(.*?)\[([^\]]*)\]$")


def split_labels(name: str) -> Tuple[str, Dict[str, str]]:
    """Split a registry name carrying the ``[k=v,...]`` label suffix into
    ``(base_name, labels)``; a name without the suffix (or with a
    malformed one) is returned whole with no labels — the registry never
    rejects a metric name, so neither does the renderer."""
    m = _LABELED_NAME_RE.match(str(name))
    if not m:
        return str(name), {}
    labels: Dict[str, str] = {}
    for pair in m.group(2).split(","):
        k, sep, v = pair.partition("=")
        if not sep or not k.strip():
            return str(name), {}
        labels[sanitize(k.strip())] = v.strip()
    return m.group(1), labels


def _label_body(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    esc = {k: v.replace("\\", r"\\").replace('"', r"\"")
           .replace("\n", r"\n") for k, v in sorted(labels.items())}
    return "{" + ",".join(f'{k}="{v}"' for k, v in esc.items()) + "}"


def _fmt(v: Any) -> str:
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render(snapshot: Optional[Dict[str, Any]] = None,
           prefix: str = "dfft") -> str:
    """The full exposition body. ``snapshot`` defaults to the registry's
    CUMULATIVE view (pass one explicitly only in tests — a "plan"-view
    snapshot would break counter monotonicity across scrapes)."""
    snap = snapshot if snapshot is not None \
        else metrics.snapshot(view="cumulative")
    lines: List[str] = []
    # Labeled series ([k=v] name suffixes) of one family share a single
    # HELP/TYPE header (the format forbids duplicates): group per
    # sanitized family in first-appearance order, samples in registry
    # (sorted-name) order within each family.
    for kind, suffix, store in (("counter", "_total",
                                 snap.get("counters", {})),
                                ("gauge", "", snap.get("gauges", {}))):
        order: List[str] = []
        families: Dict[str, List[Tuple[str, Dict[str, str], Any]]] = {}
        for name, value in store.items():
            base, labels = split_labels(name)
            m = f"{prefix}_{sanitize(base)}{suffix}"
            if m not in families:
                families[m] = []
                order.append(m)
            families[m].append((base, labels, value))
        for m in order:
            base = families[m][0][0]
            desc = ("(cumulative, monotone across obs.reset())"
                    if kind == "counter" else "(last value set)")
            lines.append(f"# HELP {m} obs {kind} {base!r} {desc}")
            lines.append(f"# TYPE {m} {kind}")
            for _, labels, value in families[m]:
                lines.append(f"{m}{_label_body(labels)} {_fmt(value)}")
    for name, h in snap.get("histograms", {}).items():
        m = f"{prefix}_{sanitize(name)}"
        lines.append(f"# HELP {m} obs histogram {name!r} "
                     "(milliseconds; cumulative)")
        lines.append(f"# TYPE {m} histogram")
        running = 0
        for bound, count in zip(h["buckets"], h["counts"]):
            running += count
            lines.append(f'{m}_bucket{{le="{_fmt(bound)}"}} {running}')
        running += h["counts"][len(h["buckets"])]
        lines.append(f'{m}_bucket{{le="+Inf"}} {running}')
        lines.append(f"{m}_sum {_fmt(h['sum'])}")
        lines.append(f"{m}_count {h['count']}")
    return "\n".join(lines) + "\n"


def _parse_value(s: str) -> float:
    if s == "+Inf":
        return math.inf
    if s == "-Inf":
        return -math.inf
    if s == "NaN":
        return math.nan
    return float(s)


def _parse_labels(body: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    rest = body
    while rest.strip():
        m = _LABEL_RE.match(rest)
        if not m:
            raise ValueError(f"malformed label set {body!r}")
        out[m.group(1)] = m.group(2)
        rest = rest[m.end():]
    return out


def validate_exposition(text: str) -> int:
    """Validate one exposition body; returns the sample count, raises
    ``ValueError`` (with the line number) on the first defect. Checks:
    line grammar, every sampled family TYPE-declared first, no duplicate
    TYPE lines, and for histograms: cumulative bucket monotonicity, a
    ``+Inf`` bucket, and ``+Inf`` bucket == ``_count``."""
    types: Dict[str, str] = {}
    samples: List[Tuple[str, Dict[str, str], float]] = []
    for i, raw in enumerate(text.splitlines(), 1):
        line = raw.rstrip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                raise ValueError(f"line {i}: malformed comment {line!r}")
            if parts[1] == "TYPE":
                if len(parts) != 4 or parts[3] not in (
                        "counter", "gauge", "histogram", "summary",
                        "untyped"):
                    raise ValueError(f"line {i}: malformed TYPE {line!r}")
                if parts[2] in types:
                    raise ValueError(
                        f"line {i}: duplicate TYPE for {parts[2]}")
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {i}: malformed sample {line!r}")
        name, labels, value = m.group(1), m.group(2), m.group(3)
        try:
            v = _parse_value(value)
        except ValueError:
            raise ValueError(f"line {i}: malformed value {value!r}") \
                from None
        lbl = _parse_labels(labels) if labels else {}
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base and types.get(base) == "histogram":
                family = base
                break
        if family not in types:
            raise ValueError(
                f"line {i}: sample {name!r} before its TYPE declaration")
        if types[family] == "counter" and not name.endswith("_total"):
            raise ValueError(
                f"line {i}: counter sample {name!r} must end _total")
        samples.append((name, lbl, v))
    # Histogram invariants.
    for family, kind in types.items():
        if kind != "histogram":
            continue
        buckets = [(s[1].get("le"), s[2]) for s in samples
                   if s[0] == family + "_bucket"]
        if not buckets:
            raise ValueError(f"histogram {family} has no _bucket samples")
        if buckets[-1][0] != "+Inf":
            raise ValueError(
                f"histogram {family} missing the +Inf bucket (or it is "
                "not last)")
        les = [_parse_value(le) for le, _ in buckets]
        if les != sorted(les):
            raise ValueError(f"histogram {family} le bounds not sorted")
        counts = [c for _, c in buckets]
        if counts != sorted(counts):
            raise ValueError(
                f"histogram {family} bucket counts not cumulative")
        count = [s[2] for s in samples if s[0] == family + "_count"]
        if not count:
            raise ValueError(f"histogram {family} missing _count")
        if counts[-1] != count[0]:
            raise ValueError(
                f"histogram {family} +Inf bucket {counts[-1]} != _count "
                f"{count[0]}")
        if not any(s[0] == family + "_sum" for s in samples):
            raise ValueError(f"histogram {family} missing _sum")
    return len(samples)
