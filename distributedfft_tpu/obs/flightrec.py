"""Always-on flight recorder: a bounded in-memory ring of recent spans,
events and metric deltas, dumped to JSONL only when something goes wrong.

The event log (``tracing.py``) is opt-in and file-backed; the metrics
registry (``metrics.py``) is always-on but keeps only aggregates. Neither
answers the incident question "what happened in the last two seconds
BEFORE the guard tripped / the circuit opened / the shed burst started".
This module does: every span close, point event, notice and counter
delta is appended to a process-global ring (``collections.deque`` with a
bounded ``maxlen`` — ZERO file I/O in steady state, a dict build and a
deque append per record), and a **trigger** flushes the ring to one JSONL
dump file for post-mortem reading.

Trigger vocabulary (``TRIGGERS``; each call site names its own):

=====================  ====================================================
trigger                fired by
=====================  ====================================================
``guard_violation``    ``resilience/guards.py`` raising ``GuardViolation``
``circuit_open``       a serve circuit breaker tripping closed -> open
``fallback_demotion``  the PR 5 fallback ladder walking a rung
``shed_burst``         >= ``DFFT_FLIGHTREC_SHED_BURST`` admissions shed
                       within 2 s (``serve/server.py``)
``worker_death``       the fleet failure detector declaring a worker dead
                       (``serve/fleet.py``: missed heartbeats, broken
                       pipe, or a nonzero exit) — the dump carries the
                       beats/dispatches of the worker's final seconds
``scale_decision``     the fleet's worker-count controller acting on the
                       ``/metrics`` signals (``serve/fleet.py``) — the
                       auditable record of WHY capacity changed
``checkpoint_restore_failure``  the persist layer skipping or refusing
                       a checkpoint generation (``persist/checkpoint.py``
                       ``CheckpointStore.load``: corruption fallback,
                       fingerprint mismatch, or zero loadable
                       generations) — the dump carries the writes and
                       injected faults of the run that left the store in
                       that state
``signal``             SIGUSR2 (``install_signal_handler``; the live-
                       debugging surface: kill -USR2 a stuck server)
``manual``             programmatic ``dump()``
=====================  ====================================================

Dump location: ``$DFFT_FLIGHTREC_DIR``, else ``$DFFT_OBS_DIR``, else the
system temp dir; file name ``flightrec-<pid>-<n>.jsonl``. The first line
is a header record (``{"ev": "flightrec", "trigger": ..., "records": N,
...}``), followed by the ring's records oldest-first — the schema
``validate_dump_file`` checks and the CI chaos job asserts on. Dumps are
rate-limited per trigger kind (``DFFT_FLIGHTREC_COOLDOWN_S``, default 5 s)
so a failure storm produces one dump per window, not thousands.

``$DFFT_FLIGHTREC=off`` disables recording entirely (the escape hatch;
``add`` then returns immediately). Like every obs surface, the recorder
degrades rather than errors: an unwritable dump directory loses the dump,
never the run. Records are host-side only — nothing here can perturb a
compiled program (the obs zero-overhead HLO pin covers this module too).
"""

from __future__ import annotations

import collections
import json
import os
import tempfile
import threading
import time
from typing import Any, Deque, Dict, List, Optional

ENV_DIR = "DFFT_FLIGHTREC_DIR"
ENV_OFF = "DFFT_FLIGHTREC"
ENV_CAPACITY = "DFFT_FLIGHTREC_CAPACITY"
ENV_COOLDOWN = "DFFT_FLIGHTREC_COOLDOWN_S"

DEFAULT_CAPACITY = 2048

TRIGGERS = ("guard_violation", "circuit_open", "fallback_demotion",
            "shed_burst", "worker_death", "scale_decision",
            "checkpoint_restore_failure", "signal", "manual")

_LOCK = threading.Lock()
_RING: Deque[Dict[str, Any]] = collections.deque(maxlen=DEFAULT_CAPACITY)
_SEQ = [0]
_LAST_DUMP: Optional[Dict[str, Any]] = None
_LAST_TRIGGER_AT: Dict[str, float] = {}
_DROPPED = [0]  # records displaced by the bounded ring (accounting only)


# Parse-once-per-value env reads: every span close, event and counter
# delta lands in add()/record(), so the enablement/capacity lookups are
# process-wide hot path — re-parse only when the raw string actually
# changes (tests monkeypatch these mid-process; a plain import-time cache
# would go stale on them).
_ENV_MEMO: Dict[str, Any] = {}


def _parsed(var: str, parse: Any) -> Any:
    raw = os.environ.get(var, "")
    hit = _ENV_MEMO.get(var)
    if hit is None or hit[0] != raw:
        hit = (raw, parse(raw))
        _ENV_MEMO[var] = hit
    return hit[1]


def enabled() -> bool:
    return _parsed(ENV_OFF, lambda raw: raw.strip().lower() != "off")


def _parse_capacity(raw: str) -> int:
    try:
        return max(16, int(raw)) if raw.strip() else DEFAULT_CAPACITY
    except ValueError:
        return DEFAULT_CAPACITY


def capacity() -> int:
    return _parsed(ENV_CAPACITY, _parse_capacity)


def _parse_cooldown(raw: str) -> float:
    try:
        return float(raw) if raw.strip() else 5.0
    except ValueError:
        return 5.0


def _cooldown_s() -> float:
    return _parsed(ENV_COOLDOWN, _parse_cooldown)


def add(rec: Dict[str, Any]) -> None:
    """Append one already-built record (the tracing layer's span/event
    dicts ride through unchanged). Cheap and total: a full ring drops its
    oldest record; a disabled recorder drops everything."""
    if not enabled():
        return
    with _LOCK:
        if _RING.maxlen != capacity():
            _resize_locked()
        if len(_RING) == _RING.maxlen:
            _DROPPED[0] += 1
        _RING.append(rec)


def _resize_locked() -> None:
    global _RING
    _RING = collections.deque(_RING, maxlen=capacity())


def record(ev: str, name: str, **attrs: Any) -> None:
    """Build + append a minimal record (the metric-delta entry point:
    ``record("metric", "serve.shed", delta=1)``)."""
    if not enabled():
        return
    with _LOCK:
        _SEQ[0] += 1
        seq = _SEQ[0]
    add({"ev": ev, "name": name, "ts": round(time.time(), 6),
         "pid": os.getpid(), "seq": seq, "attrs": attrs})


def snapshot() -> List[Dict[str, Any]]:
    """Point-in-time copy of the ring, oldest-first."""
    with _LOCK:
        return list(_RING)


def stats() -> Dict[str, Any]:
    """Ring occupancy for health surfaces (``serve health()``)."""
    with _LOCK:
        return {"enabled": enabled(), "size": len(_RING),
                "capacity": _RING.maxlen, "dropped": _DROPPED[0]}


def clear() -> None:
    """Empty the ring and forget dump/cooldown state (test hygiene)."""
    global _LAST_DUMP
    with _LOCK:
        _RING.clear()
        _LAST_DUMP = None
        _LAST_TRIGGER_AT.clear()
        _DROPPED[0] = 0


def dump_dir() -> str:
    for var in (ENV_DIR, "DFFT_OBS_DIR"):
        d = os.environ.get(var, "").strip()
        if d:
            return d
    # The tracing layer's programmatic enable() also counts as "the obs
    # directory" even though it bypasses the environment.
    from . import tracing
    d = tracing.obs_dir()
    return d if d else tempfile.gettempdir()


def last_dump() -> Optional[Dict[str, Any]]:
    """``{"trigger", "path", "ts", "records"}`` of the most recent dump
    (None before the first) — reported by serve ``health()``."""
    with _LOCK:
        return dict(_LAST_DUMP) if _LAST_DUMP else None


def trigger(kind: str, reason: str = "", **attrs: Any) -> Optional[str]:
    """Flush the ring to a JSONL dump because ``kind`` happened. Returns
    the dump path, or None when disabled, rate-limited (one dump per
    ``kind`` per cooldown window) or unwritable. Never raises."""
    global _LAST_DUMP
    if not enabled():
        return None
    if kind not in TRIGGERS:
        kind = "manual"
    now = time.monotonic()
    with _LOCK:
        last = _LAST_TRIGGER_AT.get(kind)
        if last is not None and now - last < _cooldown_s():
            return None
        _LAST_TRIGGER_AT[kind] = now
        records = list(_RING)
        _SEQ[0] += 1
        seq = _SEQ[0]
    header = {"ev": "flightrec", "trigger": kind, "reason": str(reason)[:300],
              "ts": round(time.time(), 6), "pid": os.getpid(), "seq": seq,
              "records": len(records),
              "attrs": {str(k): _json_safe(v) for k, v in attrs.items()}}
    path = os.path.join(dump_dir(),
                        f"flightrec-{os.getpid()}-{seq}.jsonl")
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            f.write(json.dumps(header, sort_keys=True) + "\n")
            for rec in records:
                f.write(json.dumps(rec, sort_keys=True, default=str) + "\n")
    except OSError:
        # Observability degrades, never errors — but a FAILED write must
        # not consume the cooldown window: give back the stamp so the
        # next trigger of this kind retries (a transiently unwritable
        # dir would otherwise silently eat every dump for cooldown_s).
        with _LOCK:
            if _LAST_TRIGGER_AT.get(kind) == now:
                if last is None:
                    _LAST_TRIGGER_AT.pop(kind, None)
                else:
                    _LAST_TRIGGER_AT[kind] = last
        return None
    with _LOCK:
        _LAST_DUMP = {"trigger": kind, "path": path, "ts": header["ts"],
                      "records": len(records)}
    # The dump itself is an event worth remembering (and, when the JSONL
    # event log is on, correlating).
    from . import metrics, tracing
    metrics.inc("flightrec.dumps")
    tracing.event("flightrec.dump", trigger=kind, path=path,
                  records=len(records))
    return path


def dump(reason: str = "") -> Optional[str]:
    """Programmatic dump (the ``manual`` trigger)."""
    return trigger("manual", reason)


def _json_safe(v: Any) -> Any:
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    return str(v)


_SIGNAL_INSTALLED = [False]


def install_signal_handler() -> bool:
    """SIGUSR2 -> dump (the live-debugging surface). Main-thread only
    (signal module contract); idempotent; returns whether installed."""
    if _SIGNAL_INSTALLED[0]:
        return True
    try:
        import signal

        def _handler(signum: int, frame: Any) -> None:  # noqa: ARG001
            # Dump OFF the signal context: the handler runs between
            # bytecodes of the interrupted main thread, which may hold
            # the non-reentrant ring/metrics locks trigger() needs — a
            # direct call could deadlock the very process the signal is
            # meant to debug. A daemon thread takes the locks safely.
            threading.Thread(target=trigger,
                             args=("signal", f"signal {signum}"),
                             daemon=True).start()

        signal.signal(signal.SIGUSR2, _handler)
    except (ValueError, OSError, AttributeError):
        return False  # non-main thread / platform without SIGUSR2
    _SIGNAL_INSTALLED[0] = True
    return True


# ---------------------------------------------------------------------------
# dump schema validation (tests + the CI chaos artifact check)
# ---------------------------------------------------------------------------

def validate_dump_file(path: str) -> int:
    """Validate one flight-recorder dump: line 1 must be the header
    (``ev == "flightrec"``, a known trigger, a record count matching the
    body), every following line a well-formed ring record. Returns the
    ring-record count; raises ``ValueError`` on the first defect."""
    with open(path, encoding="utf-8") as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    if not lines:
        raise ValueError(f"{path}: empty dump")
    header = json.loads(lines[0])
    if header.get("ev") != "flightrec":
        raise ValueError(f"{path}:1: first line must be the flightrec "
                         f"header, got ev={header.get('ev')!r}")
    if header.get("trigger") not in TRIGGERS:
        raise ValueError(f"{path}:1: unknown trigger "
                         f"{header.get('trigger')!r}")
    n = 0
    for i, ln in enumerate(lines[1:], 2):
        rec = json.loads(ln)
        for key, typ in (("ev", str), ("name", str), ("ts", (int, float)),
                         ("pid", int), ("attrs", dict)):
            if not isinstance(rec.get(key), typ):
                raise ValueError(f"{path}:{i}: record {key} must be "
                                 f"{typ}, got {rec.get(key)!r}")
        n += 1
    if header.get("records") != n:
        raise ValueError(f"{path}: header claims {header.get('records')} "
                         f"record(s) but the body has {n}")
    return n
