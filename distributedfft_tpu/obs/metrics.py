"""Process-global named counters, gauges and latency histograms.

The runtime's measured decisions (wisdom hits vs. races, wire-budget
rejections, HLO collective census) previously left no machine-readable
residue; this registry is their single accounting surface. It is ALWAYS
active — incrementing a counter is a dict update under a lock, touches no
jax state, and cannot perturb a compiled program — while the event log
(``tracing.py``) stays opt-in.

TWO VIEWS, ONE STORE (the reset-semantics contract; ISSUE 12): counters
and histograms accumulate monotonically for the whole process lifetime —
``reset()`` never erases them. What ``reset()`` does is mark a **baseline**
so the default ``snapshot()`` / ``counter_value()`` read the *per-plan*
window (everything since the last ``reset()``), while
``snapshot(view="cumulative")`` / ``counter_total()`` read the raw
process totals. The split exists because the two consumers want opposite
things and conflating them corrupted both: tests and ``bench.py`` want a
clean per-plan window (reset between plans), while the Prometheus
exposition (``promexp.py``) requires monotone counters — a scrape must
NEVER see a counter go backwards, so ``/metrics`` always renders the
cumulative view. Gauges hold the last value set and are cleared by
``reset()`` (a gauge has no meaningful baseline). Every snapshot carries
its ``"view"`` so a folded JSON artifact says which window it is.

Histograms (``observe``): fixed-boundary latency histograms in
milliseconds (cumulative bucket counts, Prometheus-shaped: ``le`` upper
bounds plus +Inf, a running sum and count). The serving layer feeds
``serve.queue_wait_ms`` / ``serve.exec_ms`` / ``serve.e2e_ms`` so the
scrape surface carries distributions, not just the EMA.

Metric names (the stable vocabulary; see README "Observability"):

========================== ======= ==========================================
name                       kind    meaning
========================== ======= ==========================================
wisdom.hits                counter resolutions served from the wisdom store
wisdom.misses              counter resolutions that had to race (or default)
wisdom.migrations          counter legacy stores migrated on load (per path)
autotune.race_cells        counter candidate cells measured by any racer
wire.budget_rejections     counter bf16 twins rejected by the error budget
wire.exchanges_traced      counter exchanges built into traced programs
wire.bytes_per_transpose   gauge   wire bytes of the last traced exchange's
                                   per-shard payload (``wire_nbytes``)
hlo.all_to_all             gauge   last ``async_collective_counts`` census
hlo.all_to_all_start       gauge   (instance counts in the compiled module;
hlo.collective_permute     gauge   ``hlo.async_total`` is the async-start
hlo.collective_permute_start gauge sum — the overlap detector)
hlo.async_total            gauge
hlo.convert                gauge
guard.parseval_violations  counter energy/finiteness guard failures
guard.wire_drift_violations counter wire drift probe over the error budget
fallback.demotions         counter fallback-ladder rungs walked (total)
fallback.<rung>_demotions  counter per-rung (send/opt/comm/wire)
wisdom.demotion_stamps     counter records stamped demoted after failures
wisdom.lock_breaks         counter stale advisory locks broken (age-based)
wisdom.lock_timeouts       counter lock waits expired (write went unlocked)
multihost.connect_retries  counter coordinator connect attempts retried
autotune.cell_timeouts     counter race cells abandoned on wall-clock
selftest.runs              counter --selftest roundtrips executed
selftest.failures          counter --selftest FAIL lines
inject.wire_faults         counter wire faults injected into traced programs
inject.coordinator_failures counter simulated coordinator connect failures
inject.lock_contentions    counter simulated held-lock reads
inject.cell_hangs          counter simulated hung race cells
inject.server_slow         counter injected serve-path straggler delays
wisdom.demotion_expired    counter demotion stamps aged out (TTL) on read
flightrec.dumps            counter flight-recorder dumps written
serve.requests             counter requests admitted to the queue
serve.requests_served      counter requests answered with a result
serve.batches              counter coalesced batch executions
serve.batch_failures       counter batch executions that raised
serve.coalesced_requests   counter requests served in batches of size > 1
serve.shed                 counter admissions rejected Overloaded
serve.rejected_closed      counter admissions rejected while draining
serve.deadline_expired     counter requests expired before/after execution
serve.circuit.opened       counter circuits tripped open (closed -> open)
serve.circuit.reopened     counter half-open probes that failed
serve.circuit.half_open    counter cooldown expiries admitting a probe
serve.circuit.closed       counter probes that closed a circuit
serve.circuit.rejected     counter requests rejected on an open circuit
serve.plan_cache.hits      counter plan-cache hits (zero recompiles)
serve.plan_cache.misses    counter plan-cache misses (plan built)
serve.plan_cache.evictions counter LRU evictions
serve.plan_cache.size      gauge   live plan-cache occupancy
serve.queue_depth          gauge   admission queue depth after last change
serve.ema_ms               gauge   per-request execution EMA (warm batches)
serve.queue_wait_ms        histo   admission -> execution start, per request
serve.exec_ms              histo   warm batch execution / batch size
serve.e2e_ms               histo   admission -> reply, served requests only
fleet.workers              gauge   live (in-ring) worker count — the
                                   scale controller's own output signal
fleet.pending              gauge   router-held requests not yet dispatched
fleet.outstanding          gauge   admitted requests not yet resolved
fleet.admitted             counter requests admitted by the fleet router
fleet.served               counter requests resolved with a result
fleet.shed                 counter router admissions rejected Overloaded
fleet.resubmitted          counter in-flight requests rerouted after a
                                   worker death (idempotent by trace id)
fleet.worker_deaths        counter workers declared dead (beats/pipe/exit)
fleet.worker_restarts      counter replacement workers joined the ring
fleet.scale_decisions      counter controller decisions acted on (up/down)
inject.worker_crashes      counter injected worker:crash exits (counted
                                   in the WORKER process's registry —
                                   read them from the worker's event
                                   log, not the router's /metrics)
inject.worker_hangs        counter injected worker:hang stalls (worker-
                                   local, like worker_crashes)
========================== ======= ==========================================

**Labels**: a metric name may carry a ``[key=value,...]`` suffix (build
it with :func:`labeled`); the registry treats the whole string as one
series and the Prometheus exposition (``promexp.py``) renders the suffix
as real labels under a single per-family TYPE header. The fleet records
``fleet.tenant.shed[tenant=...]`` / ``fleet.tenant.outstanding[tenant=...]``
per tenant and ``fleet.worker.queue_depth[worker=...]`` /
``fleet.worker.inflight[worker=...]`` per worker this way.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, List, Tuple, Union

Number = Union[int, float]

_LOCK = threading.Lock()
_COUNTERS: Dict[str, Number] = {}
_BASELINE: Dict[str, Number] = {}
_GAUGES: Dict[str, Number] = {}

# Histogram store: name -> [boundaries, bucket counts (+Inf last), sum,
# count]; *_BASE mirrors counts/sum/count at the last reset().
_HISTOS: Dict[str, list] = {}
_HISTO_BASE: Dict[str, list] = {}

# Default latency boundaries (ms): sub-ms warm hits through multi-second
# cold compiles. A Prometheus histogram's +Inf bucket is implicit here
# (the last slot of the counts list).
DEFAULT_BUCKETS_MS: Tuple[float, ...] = (
    0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000)

VIEWS = ("plan", "cumulative")


def labeled(name: str, **labels: object) -> str:
    """Build a labeled series name: ``labeled("fleet.tenant.shed",
    tenant="acme") -> "fleet.tenant.shed[tenant=acme]"``. Keys are
    sorted so the same label set always names the same series. Label
    VALUES are user-controlled (tenant names arrive from ``submit``),
    so the convention's AND the exposition's structural characters —
    ``[ ] { } , =`` plus quotes/backslashes/newlines — are folded to
    ``_``: a hostile name
    may collide with another sanitized name, but it can never invent a
    label dimension or corrupt the exposition."""
    if not labels:
        return name
    body = ",".join(
        f"{k}={_LABEL_UNSAFE.sub('_', str(labels[k]))}"
        for k in sorted(labels))
    return f"{name}[{body}]"


_LABEL_UNSAFE = re.compile(r'[\[\]{},="\\\n]')


def inc(name: str, n: Number = 1) -> None:
    """Add ``n`` to counter ``name`` (creating it at 0). The delta also
    lands in the flight-recorder ring (``obs/flightrec.py``), so a dump
    shows which counters moved in the final seconds."""
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + n
    from . import flightrec
    flightrec.record("metric", name, delta=n)


def gauge(name: str, value: Number) -> None:
    """Set gauge ``name`` to ``value`` (last write wins)."""
    with _LOCK:
        _GAUGES[name] = value


def drop_gauge(name: str) -> None:
    """Remove gauge ``name`` from BOTH views (a gauge describes current
    state; when its subject permanently departs — a fleet worker slot
    retired by scale-down — a frozen last value is misinformation on
    the scrape surface, not history worth keeping)."""
    with _LOCK:
        _GAUGES.pop(name, None)


def observe(name: str, value_ms: Number,
            buckets: Tuple[float, ...] = DEFAULT_BUCKETS_MS) -> None:
    """Record one latency observation into histogram ``name``. The first
    ``observe`` of a name fixes its boundaries; later calls ignore the
    ``buckets`` argument (one histogram, one shape)."""
    v = float(value_ms)
    with _LOCK:
        h = _HISTOS.get(name)
        if h is None:
            bounds = tuple(sorted(float(b) for b in buckets))
            h = [bounds, [0] * (len(bounds) + 1), 0.0, 0]
            _HISTOS[name] = h
        bounds, counts = h[0], h[1]
        i = len(bounds)
        for j, b in enumerate(bounds):
            if v <= b:
                i = j
                break
        counts[i] += 1
        h[2] += v
        h[3] += 1


def counter_value(name: str) -> Number:
    """Per-plan view: the counter's growth since the last ``reset()``."""
    with _LOCK:
        return _COUNTERS.get(name, 0) - _BASELINE.get(name, 0)


def counter_total(name: str) -> Number:
    """Cumulative view: the raw process-lifetime total (what the
    Prometheus exposition renders — monotone across ``reset()``)."""
    with _LOCK:
        return _COUNTERS.get(name, 0)


def gauge_value(name: str, default: Number = 0) -> Number:
    with _LOCK:
        return _GAUGES.get(name, default)


def _histo_view(name: str, cumulative: bool) -> Dict[str, object]:
    """Caller holds the lock."""
    bounds, counts, total, n = _HISTOS[name]
    if not cumulative and name in _HISTO_BASE:
        bcounts, bsum, bn = _HISTO_BASE[name]
        counts = [c - b for c, b in zip(counts, bcounts)]
        total, n = total - bsum, n - bn
    else:
        counts = list(counts)
    return {"buckets": list(bounds), "counts": counts,
            "sum": round(float(total), 4), "count": n}


def snapshot(view: str = "plan") -> Dict[str, object]:
    """Point-in-time copy with deterministically ordered keys (stable for
    JSON diffs): ``{"view", "counters", "gauges", "histograms"}``.

    ``view="plan"`` (default) is the since-last-``reset()`` window — what
    ``bench.py`` folds per child and tests assert on. ``"cumulative"`` is
    the monotone process totals — what ``/metrics`` scrapes. Zero-valued
    per-plan counters are omitted (a counter untouched this plan is not
    part of this plan's story); cumulative keeps every key ever touched.
    """
    if view not in VIEWS:
        raise ValueError(f"view must be one of {VIEWS}, got {view!r}")
    cumulative = view == "cumulative"
    with _LOCK:
        if cumulative:
            counters = {k: _COUNTERS[k] for k in sorted(_COUNTERS)}
        else:
            counters = {}
            for k in sorted(_COUNTERS):
                delta = _COUNTERS[k] - _BASELINE.get(k, 0)
                if delta:
                    counters[k] = delta
        histos = {}
        for k in sorted(_HISTOS):
            h = _histo_view(k, cumulative)
            if cumulative or h["count"]:
                histos[k] = h
        return {"view": view,
                "counters": counters,
                "gauges": {k: _GAUGES[k] for k in sorted(_GAUGES)},
                "histograms": histos}


def reset() -> None:
    """Start a new per-plan window: baseline the counters/histograms and
    clear the gauges. The cumulative view (and therefore the Prometheus
    exposition) is UNAFFECTED — counters stay monotone across plans."""
    with _LOCK:
        _BASELINE.clear()
        _BASELINE.update(_COUNTERS)
        for k, h in _HISTOS.items():
            _HISTO_BASE[k] = [list(h[1]), h[2], h[3]]
        _GAUGES.clear()


def hard_reset() -> None:
    """Erase EVERYTHING, both views (process-start state). Test isolation
    between test files only — production code must use ``reset()``, which
    keeps the scrape surface monotone."""
    with _LOCK:
        _COUNTERS.clear()
        _BASELINE.clear()
        _GAUGES.clear()
        _HISTOS.clear()
        _HISTO_BASE.clear()


def histogram_names() -> List[str]:
    with _LOCK:
        return sorted(_HISTOS)
