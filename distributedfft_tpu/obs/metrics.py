"""Process-global named counters and gauges.

The runtime's measured decisions (wisdom hits vs. races, wire-budget
rejections, HLO collective census) previously left no machine-readable
residue; this registry is their single accounting surface. It is ALWAYS
active — incrementing a counter is a dict update under a lock, touches no
jax state, and cannot perturb a compiled program — while the event log
(``tracing.py``) stays opt-in.

Consumers: ``bench.py`` folds ``snapshot()`` into ``BENCH_DETAILS.json``
(per child process, keys ``obs_metrics_mesh`` / ``obs_metrics_tpu``), the
CLIs print it under ``--obs``, and ``dfft-explain`` reports the census
gauges its compile populates.

Metric names (the stable vocabulary; see README "Observability"):

========================== ======= ==========================================
name                       kind    meaning
========================== ======= ==========================================
wisdom.hits                counter resolutions served from the wisdom store
wisdom.misses              counter resolutions that had to race (or default)
wisdom.migrations          counter legacy stores migrated on load (per path)
autotune.race_cells        counter candidate cells measured by any racer
wire.budget_rejections     counter bf16 twins rejected by the error budget
wire.exchanges_traced      counter exchanges built into traced programs
wire.bytes_per_transpose   gauge   wire bytes of the last traced exchange's
                                   per-shard payload (``wire_nbytes``)
hlo.all_to_all             gauge   last ``async_collective_counts`` census
hlo.all_to_all_start       gauge   (instance counts in the compiled module;
hlo.collective_permute     gauge   ``hlo.async_total`` is the async-start
hlo.collective_permute_start gauge sum — the overlap detector)
hlo.async_total            gauge
hlo.convert                gauge
guard.parseval_violations  counter energy/finiteness guard failures
guard.wire_drift_violations counter wire drift probe over the error budget
fallback.demotions         counter fallback-ladder rungs walked (total)
fallback.<rung>_demotions  counter per-rung (send/opt/comm/wire)
wisdom.demotion_stamps     counter records stamped demoted after failures
wisdom.lock_breaks         counter stale advisory locks broken (age-based)
wisdom.lock_timeouts       counter lock waits expired (write went unlocked)
multihost.connect_retries  counter coordinator connect attempts retried
autotune.cell_timeouts     counter race cells abandoned on wall-clock
selftest.runs              counter --selftest roundtrips executed
selftest.failures          counter --selftest FAIL lines
inject.wire_faults         counter wire faults injected into traced programs
inject.coordinator_failures counter simulated coordinator connect failures
inject.lock_contentions    counter simulated held-lock reads
inject.cell_hangs          counter simulated hung race cells
inject.server_slow         counter injected serve-path straggler delays
wisdom.demotion_expired    counter demotion stamps aged out (TTL) on read
serve.requests             counter requests admitted to the queue
serve.requests_served      counter requests answered with a result
serve.batches              counter coalesced batch executions
serve.batch_failures       counter batch executions that raised
serve.coalesced_requests   counter requests served in batches of size > 1
serve.shed                 counter admissions rejected Overloaded
serve.rejected_closed      counter admissions rejected while draining
serve.deadline_expired     counter requests expired before/after execution
serve.circuit.opened       counter circuits tripped open (closed -> open)
serve.circuit.reopened     counter half-open probes that failed
serve.circuit.half_open    counter cooldown expiries admitting a probe
serve.circuit.closed       counter probes that closed a circuit
serve.circuit.rejected     counter requests rejected on an open circuit
serve.plan_cache.hits      counter plan-cache hits (zero recompiles)
serve.plan_cache.misses    counter plan-cache misses (plan built)
serve.plan_cache.evictions counter LRU evictions
serve.plan_cache.size      gauge   live plan-cache occupancy
serve.queue_depth          gauge   admission queue depth after last change
serve.ema_ms               gauge   per-request execution EMA (warm batches)
========================== ======= ==========================================

Counters accumulate until ``reset()`` (tests reset between plans); gauges
hold the last value set.
"""

from __future__ import annotations

import threading
from typing import Dict, Union

Number = Union[int, float]

_LOCK = threading.Lock()
_COUNTERS: Dict[str, Number] = {}
_GAUGES: Dict[str, Number] = {}


def inc(name: str, n: Number = 1) -> None:
    """Add ``n`` to counter ``name`` (creating it at 0)."""
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + n


def gauge(name: str, value: Number) -> None:
    """Set gauge ``name`` to ``value`` (last write wins)."""
    with _LOCK:
        _GAUGES[name] = value


def counter_value(name: str) -> Number:
    with _LOCK:
        return _COUNTERS.get(name, 0)


def gauge_value(name: str, default: Number = 0) -> Number:
    with _LOCK:
        return _GAUGES.get(name, default)


def snapshot() -> Dict[str, Dict[str, Number]]:
    """Point-in-time copy: ``{"counters": {...}, "gauges": {...}}`` with
    deterministically ordered keys (stable for JSON diffs)."""
    with _LOCK:
        return {"counters": {k: _COUNTERS[k] for k in sorted(_COUNTERS)},
                "gauges": {k: _GAUGES[k] for k in sorted(_GAUGES)}}


def reset() -> None:
    """Clear every counter and gauge (test isolation between plans)."""
    with _LOCK:
        _COUNTERS.clear()
        _GAUGES.clear()
