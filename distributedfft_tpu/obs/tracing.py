"""Host-side span tracing with a per-run structured JSONL event log.

The reference library's only instrument is the phase Timer CSV
(``include/timer.hpp``, ``utils/timer.py``): cumulative wall-clock marks of
the *execution* pipeline. After the wisdom/ring/wire work the framework also
makes invisible *build-time* decisions — wisdom hit vs. race, comm/send/wire
winners, rendering selection — and this module is the structured record of
them: a nestable ``span("plan.build")`` context manager that

* records wall-clock intervals into a per-process JSONL event log under
  ``$DFFT_OBS_DIR`` (``events-<pid>.jsonl``; one JSON object per line, see
  ``validate_event`` for the schema), and
* enters a ``jax.profiler.TraceAnnotation`` named ``dfft:<span name>``, so
  when the process is inside a ``jax.profiler`` trace (``--profile-dir``)
  the same names appear on the TensorBoard / Perfetto timeline next to the
  device ops they schedule.

ZERO-OVERHEAD-WHEN-OFF CONTRACT (amended by the flight recorder,
ISSUE 12): with no ``$DFFT_OBS_DIR`` (and no programmatic ``enable()``)
there is still **no file I/O, no jax import and no profiler annotation**
— but spans/events/notices are no longer dropped entirely: every record
is appended to the always-on in-memory flight-recorder ring
(``obs/flightrec.py``; a dict build and a bounded deque append), so a
trigger can dump the last seconds of evidence even from a run that never
enabled the log. ``$DFFT_FLIGHTREC=off`` restores the full drop. Spans
never appear *inside* jitted programs as ops: they are host-side
intervals around plan construction, autotuning, wisdom I/O and trace-time
program building, which is why neither the ring nor the log can perturb
the compiled program (``tests/test_obs.py`` pins enabled == disabled HLO
byte-for-byte).

Everything here degrades rather than errors: an unwritable log directory
silently drops events (observability must never fail a run).

Event schema (one JSON object per line)::

    {"ev": "span" | "event",
     "name": "plan.build",           # non-empty dotted name
     "ts": 1722538000.123456,        # wall-clock epoch seconds at open
     "dur_ms": 12.34,                # spans only: wall interval
     "depth": 0,                     # nesting depth at open
     "parent": null | "outer.span",  # enclosing span name
     "pid": 12345, "seq": 7,         # per-process monotone sequence
     "attrs": {...}}                 # JSON-scalar details
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Iterable, Optional

ENV_VAR = "DFFT_OBS_DIR"

_LOCK = threading.Lock()
_SEQ = [0]
_FORCED_DIR: Optional[str] = None   # enable() override
_FORCE_OFF = False                  # disable() override (beats the env)
_CONSOLE = False                    # --obs: mirror notices to stdout


class _Tls(threading.local):
    def __init__(self):
        self.stack = []  # open span names, innermost last


_TLS = _Tls()


# ---------------------------------------------------------------------------
# enablement
# ---------------------------------------------------------------------------

def obs_dir() -> Optional[str]:
    """The active event-log directory, or None when tracing is off:
    ``enable(path)`` wins, then ``$DFFT_OBS_DIR``; ``disable()`` forces
    off regardless of the environment."""
    if _FORCE_OFF:
        return None
    if _FORCED_DIR:
        return _FORCED_DIR
    d = os.environ.get(ENV_VAR, "").strip()
    return d or None


def enabled() -> bool:
    return obs_dir() is not None


def enable(path: str) -> None:
    """Write the event log under ``path`` (programmatic ``$DFFT_OBS_DIR``;
    the CLI ``--obs-dir`` calls this)."""
    global _FORCED_DIR, _FORCE_OFF
    _FORCED_DIR = str(path)
    _FORCE_OFF = False


def disable() -> None:
    """Force tracing off (overrides both ``enable()`` and the env)."""
    global _FORCED_DIR, _FORCE_OFF
    _FORCED_DIR = None
    _FORCE_OFF = True


def reset_enablement() -> None:
    """Back to the pure-environment behavior (test hygiene)."""
    global _FORCED_DIR, _FORCE_OFF
    _FORCED_DIR = None
    _FORCE_OFF = False


def enable_console() -> None:
    """Mirror ``notice()`` one-liners to stdout (the CLI ``--obs`` flag)."""
    global _CONSOLE
    _CONSOLE = True


def disable_console() -> None:
    global _CONSOLE
    _CONSOLE = False


def console_enabled() -> bool:
    return _CONSOLE


def event_log_path() -> Optional[str]:
    """This process's event-log file (None when tracing is off)."""
    d = obs_dir()
    return None if d is None else os.path.join(d, f"events-{os.getpid()}.jsonl")


# ---------------------------------------------------------------------------
# emission
# ---------------------------------------------------------------------------

def _scalar(v):
    """Attrs must round-trip through JSON; anything exotic degrades to str
    (an event log line must never raise)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (tuple, list)):
        return [_scalar(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _scalar(x) for k, x in v.items()}
    return str(v)


def _emit(rec: Dict[str, Any]) -> None:
    """One finished record: always into the flight-recorder ring (bounded,
    in-memory), and into the JSONL log file only when tracing is on."""
    from . import flightrec
    flightrec.add(rec)
    path = event_log_path()
    if path is None:
        return
    try:
        line = json.dumps(rec, sort_keys=True)
        with _LOCK:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "a", encoding="utf-8") as f:
                f.write(line + "\n")
    except (OSError, TypeError, ValueError):
        pass  # observability degrades, never errors


def _base(ev: str, name: str, attrs: Dict[str, Any]) -> Dict[str, Any]:
    with _LOCK:
        _SEQ[0] += 1
        seq = _SEQ[0]
    stack = _TLS.stack
    return {"ev": ev, "name": name, "ts": round(time.time(), 6),
            "depth": len(stack), "parent": stack[-1] if stack else None,
            "pid": os.getpid(), "seq": seq, "attrs": _scalar(attrs)}


class _NullSpan:
    """The disabled-path span: a shared, attribute-free no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ("name", "attrs", "_rec", "_p0", "_ann")

    def __init__(self, name: str, attrs: Dict[str, Any]):
        self.name = name
        self.attrs = attrs
        self._ann = None

    def __enter__(self):
        self._rec = _base("span", self.name, self.attrs)
        self._p0 = time.perf_counter()
        _TLS.stack.append(self.name)
        # Device-trace annotation: inside a jax.profiler trace the span name
        # shows on the TensorBoard/Perfetto timeline; outside one this is a
        # cheap no-op, and on a jax-free interpreter it is skipped entirely.
        # Ring-only spans (log off) skip it — the off path imports no jax.
        if not enabled():
            self._ann = None
            return self
        try:
            import jax
            self._ann = jax.profiler.TraceAnnotation(f"dfft:{self.name}")
            self._ann.__enter__()
        except Exception:  # noqa: BLE001 — annotation is best-effort
            self._ann = None
        return self

    def __exit__(self, et, ev, tb):
        if self._ann is not None:
            try:
                self._ann.__exit__(et, ev, tb)
            except Exception:  # noqa: BLE001
                pass
        if _TLS.stack and _TLS.stack[-1] == self.name:
            _TLS.stack.pop()
        self._rec["dur_ms"] = round(
            (time.perf_counter() - self._p0) * 1e3, 4)
        if et is not None:
            self._rec["attrs"]["error"] = f"{et.__name__}"
        _emit(self._rec)
        return False


def _recording() -> bool:
    """Whether anything downstream wants records: the JSONL log (opt-in)
    or the always-on flight-recorder ring."""
    if enabled():
        return True
    from . import flightrec
    return flightrec.enabled()


def span(name: str, **attrs):
    """Nestable tracing span. ``with span("plan.build", kind="slab"): ...``
    records a span record into the flight-recorder ring (always) and the
    JSONL event log (when on). Only with ``$DFFT_FLIGHTREC=off`` AND the
    log off does it degrade to the shared no-op."""
    if not _recording():
        return _NULL
    return _Span(name, attrs)


def event(name: str, **attrs) -> None:
    """One-shot point event (no duration): flight-recorder ring always,
    event log when on."""
    if not _recording():
        return
    _emit(_base("event", name, attrs))


def notice(msg: str, *, name: str = "notice", **attrs) -> None:
    """A human-readable one-liner: printed to stdout under the CLI
    ``--obs`` flag, recorded into the ring always and the event log when
    on. Used for wisdom provenance (``hit | miss | migrated(v1→v3)``) so
    the previously silent resolution is visible."""
    if _CONSOLE:
        print(msg, flush=True)
    if _recording():
        a = dict(attrs)
        a["msg"] = msg
        _emit(_base("event", name, a))


# ---------------------------------------------------------------------------
# schema validation (shared by tests and the CI artifact check)
# ---------------------------------------------------------------------------

_EV_KINDS = ("span", "event")


def validate_event(rec: Any) -> None:
    """Raise ``ValueError`` unless ``rec`` is a schema-conforming event
    (see module docstring)."""
    if not isinstance(rec, dict):
        raise ValueError(f"event must be an object, got {type(rec).__name__}")
    ev = rec.get("ev")
    if ev not in _EV_KINDS:
        raise ValueError(f"ev must be one of {_EV_KINDS}, got {ev!r}")
    name = rec.get("name")
    if not isinstance(name, str) or not name:
        raise ValueError(f"name must be a non-empty string, got {name!r}")
    ts = rec.get("ts")
    if not isinstance(ts, (int, float)) or ts <= 0:
        raise ValueError(f"ts must be a positive number, got {ts!r}")
    for key in ("pid", "seq", "depth"):
        v = rec.get(key)
        if not isinstance(v, int) or v < 0:
            raise ValueError(f"{key} must be a non-negative int, got {v!r}")
    parent = rec.get("parent", "MISSING")
    if parent is not None and not isinstance(parent, str):
        raise ValueError(f"parent must be null or a string, got {parent!r}")
    if not isinstance(rec.get("attrs"), dict):
        raise ValueError("attrs must be an object")
    if ev == "span":
        d = rec.get("dur_ms")
        if not isinstance(d, (int, float)) or d < 0:
            raise ValueError(f"span dur_ms must be >= 0, got {d!r}")
    elif "dur_ms" in rec:
        raise ValueError("point events carry no dur_ms")


def validate_events_file(path: str) -> int:
    """Validate every line of one JSONL event log; returns the event count,
    raises ``ValueError`` (with the offending line number) on the first
    defect."""
    n = 0
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
                validate_event(rec)
            except ValueError as e:
                raise ValueError(f"{path}:{i}: {e}") from None
            n += 1
    return n


def validate_events_dir(path: str,
                        pattern: str = "events-") -> int:
    """Validate every ``events-*.jsonl`` under ``path``; returns the total
    event count (0 when no log files exist)."""
    total = 0
    names: Iterable[str] = sorted(os.listdir(path))
    for fn in names:
        if fn.startswith(pattern) and fn.endswith(".jsonl"):
            total += validate_events_file(os.path.join(path, fn))
    return total
