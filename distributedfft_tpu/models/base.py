"""Abstract distributed-FFT plan — the TPU analog of the reference's
``MPIcuFFT<T>`` core runtime (``include/mpicufft.hpp:55-79``,
``src/mpicufft.cpp``).

API shape preserved from the reference: construct with a global size +
partition ("initFFT"), query per-rank input/output extents
(``getInSize/getInStart/getOutSize/getOutStart``), then execute forward /
inverse transforms. What changes is the execution model: instead of a
hand-scheduled pipeline of cuFFT calls, memcpy packs and MPI exchanges, a
plan compiles ONE jitted XLA program (local FFT -> all_to_all -> local FFT
[-> all_to_all -> local FFT]) over a ``jax.sharding.Mesh``, per BASELINE.json's
north star.

The reference's ``fft3d`` single-process fallback (``src/mpicufft.cpp:65``)
maps to a mesh-less plan that calls ``jnp.fft.rfftn`` directly.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .. import obs
from ..ops import fft as local_fft
from ..params import Config, GlobalSize, Partition
from ..resilience import fallback, guards
from ..utils import wisdom


def notice_axis_smoothness(kind: str, axes_lengths: Iterable[int],
                           config: Config) -> None:
    """Arbitrary-size axis support, the advisory half: every family
    accepts any axis length (padding handles mesh divisibility), but a
    non-5-smooth length silently leaves the fast path of the matmul /
    pallas backends (``mxu_fft._split`` degrades a prime to one dense
    O(n^2) contraction). Surface that at plan construction — with the
    fix (``fft_backend="bluestein"``, the chirp-z backend that keeps any
    length at O(n log n)) — instead of letting it show up only as a
    mystery slowdown. The xla/bluestein backends handle every length;
    no notice there."""
    from .. import obs
    from ..ops.bluestein import is_smooth
    rough = sorted({int(n) for n in axes_lengths if not is_smooth(int(n))})
    if rough and config.fft_backend in ("matmul", "matmul-r2", "pallas"):
        obs.notice(
            f"{kind} plan: non-smooth axis length(s) {rough} fall off the "
            f"{config.fft_backend} fast path (dense O(n^2) per axis); "
            "fft_backend='bluestein' keeps them O(n log n)",
            name="plan.nonsmooth_axes", kind=kind, lengths=rough,
            backend=config.fft_backend)


def _with_pad(pure: Callable[..., Any], logical_shape: Sequence[int],
              padded_shape: Sequence[int]) -> Callable[..., Any]:
    """Wrap a pure pipeline so logical-shaped input is zero-padded to the
    mesh-divisible padded shape (the traced analog of the exec_* padding
    preamble; ``jnp.pad``'s vjp slices the cotangent, so the wrapper stays
    differentiable). Padded-shaped input passes through untouched; any
    other shape raises, mirroring the exec_* validation — without this a
    shape-agnostic pipeline would silently transform a wrong-shaped input
    inconsistently with the plan."""
    logical = tuple(logical_shape)
    padded = tuple(padded_shape)

    import jax.numpy as jnp

    def fn(x: Any) -> Any:
        if tuple(x.shape) == logical:
            if logical != padded:
                x = jnp.pad(x, [(0, p - s) for p, s in zip(padded, logical)])
        elif tuple(x.shape) != padded:
            raise ValueError(
                f"input shape {tuple(x.shape)} matches neither the logical "
                f"shape {logical} nor the padded shape {padded}")
        return pure(x)

    return fn


class DistFFTPlan:
    """Base class for slab / pencil plans.

    Subclasses populate ``_in_spec`` / ``_out_spec`` (PartitionSpecs over
    ``self.mesh``) plus the per-rank size tables, and implement
    ``_build_r2c`` / ``_build_c2r`` returning jitted callables over global
    arrays. Construction is the analog of the reference's
    ``initFFT(GlobalSize*, Partition*, allocate)``.
    """

    def __init__(self, global_size: GlobalSize, partition: Partition,
                 config: Optional[Config] = None, mesh: Optional[Mesh] = None):
        self.global_size = global_size
        self.partition = partition
        self.config = config or Config()
        if wisdom.unresolved(self.config):
            # The engine constructors (SlabFFTPlan/PencilFFTPlan/
            # Batched2DFFTPlan) resolve "auto" fields via wisdom before
            # reaching here; a plan must never trace with an unresolved
            # marker (ops.fft would reject the backend much later, with a
            # far worse message).
            raise ValueError(
                "Config has unresolved 'auto' fields; construct plans "
                "through the engine classes or resolve explicitly with "
                "utils.wisdom.resolve_config(...)")
        # MXU settings resolved ONCE at plan construction: when any Config
        # knob is set, every builder reads this snapshot, so a later
        # deprecated set_* call cannot split the plan's forward and inverse
        # tracings across different knob values. An all-default Config
        # resolves to None — such plans keep deferring to the mutable
        # process defaults at trace time (legacy set_* behavior).
        self._mxu_st = self.config.mxu_settings()
        # Resilience state: the guard mode is resolved ONCE here (Config
        # field -> $DFFT_GUARDS -> off), so a mid-run env change cannot
        # split a plan's directions across modes; _guard_state holds the
        # per-direction tolerances the builders stash at wrap time.
        self._guard_mode = guards.resolved_mode(self.config)
        self._guard_state = {}
        self.mesh = mesh
        # Single-process fallback flag, exactly the reference's
        # ``fft3d = (pcnt == 1)`` (src/mpicufft.cpp:65).
        self.fft3d = mesh is None or partition.num_ranks == 1
        self._r2c = None
        self._c2r = None
        self._fwd_pure = None
        self._inv_pure = None
        self._in_spec: Optional[PartitionSpec] = None
        self._out_spec: Optional[PartitionSpec] = None

    # -- sharding queries (reference getInSize/getOutSize family) ---------

    @property
    def input_spec(self) -> PartitionSpec:
        return PartitionSpec() if self.fft3d else self._in_spec

    @property
    def output_spec(self) -> PartitionSpec:
        return PartitionSpec() if self.fft3d else self._out_spec

    @property
    def input_sharding(self) -> Optional[NamedSharding]:
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, self.input_spec)

    @property
    def output_sharding(self) -> Optional[NamedSharding]:
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, self.output_spec)

    @property
    def input_shape(self) -> Tuple[int, int, int]:
        """Global real-space shape (x, y, z)."""
        return self.global_size.shape

    @property
    def output_shape(self) -> Tuple[int, int, int]:
        """Global spectral shape; subclasses override where the halved axis
        is not z."""
        g = self.global_size
        return (g.nx, g.ny, g.nz_out)

    def in_sizes(self, axis: str = "x") -> List[int]:
        """Per-rank input extents along the decomposed axis(es)."""
        raise NotImplementedError

    def out_sizes(self, axis: str) -> List[int]:
        raise NotImplementedError

    # -- execution --------------------------------------------------------

    def exec_r2c(self, x: Any) -> Any:
        """Forward real-to-complex transform (reference ``execR2C``),
        inside the resilience envelope (``fallback.execute``): guards
        checked per the plan's mode, pipeline failures walk the
        degradation ladder."""
        return fallback.execute(self, "forward", x, self._get_r2c)

    def exec_c2r(self, x: Any) -> Any:
        """Inverse complex-to-real transform (reference ``execC2R``)."""
        return fallback.execute(self, "inverse", x, self._get_c2r)

    def _get_r2c(self) -> Any:
        if self._r2c is None:
            self._r2c = self._build_r2c()
        return self._r2c

    def _get_c2r(self) -> Any:
        if self._c2r is None:
            self._c2r = self._build_c2r()
        return self._c2r

    def _build_r2c(self) -> Any:
        raise NotImplementedError

    def _build_c2r(self) -> Any:
        raise NotImplementedError

    def _guard_spec(self, direction: str, dims: int = 3) -> Any:
        """The family's ``guards.GuardSpec`` for one direction (only
        consulted at modes check/enforce)."""
        raise NotImplementedError

    def _wisdom_key_args(self) -> Dict[str, Any]:
        """Key components of this plan's wisdom entry (the fallback
        ladder's demotion stamp targets the exact cell that failed)."""
        raise NotImplementedError

    # -- solver protocol ---------------------------------------------------
    # The spectral-application suite (``solvers/``) drives plans through
    # this transform-agnostic surface only: forward/inverse regardless of
    # the plan's transform family, which axes the transform covers, and
    # where the R2C halving sits. Implemented here for the DistFFTPlan
    # hierarchy (slab/pencil); ``Batched2DFFTPlan`` — outside the
    # hierarchy — honors the identical contract, so a solver written
    # against it runs on every family unchanged.

    @property
    def transform_axes(self) -> Tuple[int, ...]:
        """Axes the transform covers (3D plans: all three; the batched-2D
        plan reports (1, 2) — its axis 0 is a pure batch dimension)."""
        return (0, 1, 2)

    @property
    def transform_size(self) -> int:
        """Product of the logical extents over the TRANSFORMED axes — the
        N of this plan's DFT (solvers derive normalization scales from
        it; for a batched-2D plan it is nx*ny, not the stack volume)."""
        dims = self.input_shape
        out = 1
        for a in self.transform_axes:
            out *= int(dims[a])
        return out

    @property
    def spectral_halved_axis(self) -> Optional[int]:
        """Index of the ``n//2+1``-halved spectral axis, or None for C2C
        plans (no halving)."""
        if getattr(self, "transform", "r2c") == "c2c":
            return None
        return self._halved_axis_index()

    def _halved_axis_index(self) -> int:
        """R2C halved axis of this family (pencil halves z; the slab
        engine overrides per sequence)."""
        return 2

    def exec_fwd(self, x: Any) -> Any:
        """Forward transform through the plan's own transform family
        (r2c -> ``exec_r2c``, c2c -> ``exec_c2c``) — the solver suite's
        uniform entry point."""
        if getattr(self, "transform", "r2c") == "c2c":
            return self.exec_c2c(x)
        return self.exec_r2c(x)

    def exec_inv(self, c: Any) -> Any:
        """Inverse transform (see ``exec_fwd``)."""
        if getattr(self, "transform", "r2c") == "c2c":
            return self.exec_c2c_inv(c)
        return self.exec_c2r(c)

    # -- pure pipelines (compose under user transforms) --------------------

    def forward_fn(self) -> Callable[..., Any]:
        """The PURE forward pipeline: the same composition `exec_r2c` jits,
        but with no ``jax.jit`` wrapper and no input/output sharding
        annotations, so it composes under USER transforms — ``jax.grad``
        through the distributed spectral pipeline (all_to_all transposes
        included), an enclosing ``jax.jit``, etc. A capability the
        reference's hand-rolled MPI exchanges cannot express. The sharded
        collectives differentiate cleanly; the local transform's vjp is
        backend-dependent (``fft_backend="matmul"`` — pure einsum — is the
        differentiable TPU-native choice; XLA's FFT op may lack a transpose
        rule under shard_map). See tests/test_autodiff.py."""
        raise NotImplementedError

    def inverse_fn(self) -> Callable[..., Any]:
        """Pure inverse pipeline (see ``forward_fn``)."""
        raise NotImplementedError

    # -- single-device fallback ------------------------------------------

    def _chunk_for(self, nx: int) -> Optional[int]:
        """Validated ``Config.fft3d_chunk`` for a leading extent of
        ``nx`` (None = fused path)."""
        ck = self.config.fft3d_chunk
        if not ck or ck <= 1:
            return None
        if nx % ck:
            raise ValueError(f"fft3d_chunk {ck} must divide the x extent "
                             f"{nx}")
        return ck

    def _scope_family(self) -> str:
        """The plan-graph family key stage scopes are named under
        (``dfft/<family>/<node-id>``; ``obs/profile.py``)."""
        from ..analysis import contracts
        return contracts.scope_family(self)

    def _fft3d_r2c(self, jit: bool = True) -> Any:
        norm, be = self.config.norm, self.config.fft_backend
        st = self._mxu_st
        ck = self._chunk_for(self.input_shape[0])

        def run(x: Any) -> Any:
            if ck is None:
                return local_fft.rfftn_3d(x, norm=norm, backend=be,
                                          settings=st)
            # Memory-bounded large-cube path: z+y stages per leading-axis
            # chunk (lax.map serializes them, capping the four-step
            # relayout temporaries at chunk size); the x stage needs the
            # full axis and runs on the already-halved spectrum.
            nx = x.shape[0]

            def per(xs: Any) -> Any:
                c = local_fft.rfft(xs, axis=-1, norm=norm, backend=be,
                                   settings=st)
                return local_fft.fft(c, axis=-2, norm=norm, backend=be,
                                     settings=st)

            cs = jnp.reshape(x, (ck, nx // ck) + x.shape[1:])
            c = jnp.reshape(jax.lax.map(per, cs),
                            (nx,) + x.shape[1:-1] + (x.shape[-1] // 2 + 1,))
            return local_fft.fft(c, axis=-3, norm=norm, backend=be,
                                 settings=st)

        run = obs.profile.scoped(self._scope_family(), "local_fft:1", run)
        return self._jit_guarded(run, "forward") if jit else run

    def _fft3d_c2r(self, jit: bool = True) -> Any:
        norm, be = self.config.norm, self.config.fft_backend
        st = self._mxu_st
        shape = self.input_shape
        ck = self._chunk_for(shape[0])

        def run(c: Any) -> Any:
            if ck is None:
                return local_fft.irfftn_3d(c, shape, norm=norm, backend=be,
                                           settings=st)
            nz = shape[-1]
            c = local_fft.ifft(c, axis=-3, norm=norm, backend=be,
                               settings=st)

            def per(cs: Any) -> Any:
                y = local_fft.ifft(cs, axis=-2, norm=norm, backend=be,
                                   settings=st)
                return local_fft.irfft(y, n=nz, axis=-1, norm=norm,
                                       backend=be, settings=st)

            nx = c.shape[0]
            ys = jnp.reshape(c, (ck, nx // ck) + c.shape[1:])
            return jnp.reshape(jax.lax.map(per, ys), (nx,) + shape[1:])

        run = obs.profile.scoped(self._scope_family(), "local_fft:1", run)
        return self._jit_guarded(run, "inverse") if jit else run

    def _fft3d_c2c(self, forward: bool, jit: bool = True) -> Any:
        """Single-device full 3D C2C (both directions unnormalized under
        FFTNorm.NONE, like cuFFT's CUFFT_FORWARD/CUFFT_INVERSE)."""
        norm, be = self.config.norm, self.config.fft_backend
        st = self._mxu_st
        axes = (-3, -2, -1)

        def run(c: Any) -> Any:
            if forward:
                return local_fft.fftn(c, axes, norm=norm, backend=be, settings=st)
            return local_fft.ifftn(c, axes, norm=norm, backend=be, settings=st)

        run = obs.profile.scoped(self._scope_family(), "local_fft:1", run)
        if not jit:
            return run
        return self._jit_guarded(run, "forward" if forward else "inverse")

    def _jit_guarded(self, run: Callable[..., Any],
                     direction: str) -> Any:
        """Jit a single-device pipeline with the guard wrapper applied at
        modes check/enforce (``guards.maybe_wrap``; a no-op pass-through —
        same callable, identical HLO — at "off")."""
        run, _ = guards.maybe_wrap(self, run, direction)
        return jax.jit(run)

    # -- staged-execution helper (shared by slab/pencil/batched2d) ---------

    def _jit_stages(self, specs: Sequence[Tuple[Any, ...]]) -> List[Any]:
        # Staged execution only exists on multi-device plans (the
        # single-device fallback never builds stage specs), so the mesh
        # is always resolved here — narrow the Optional for mypy.
        assert self.mesh is not None, "staged execution needs a device mesh"
        return jit_stages(self.mesh, specs)


def jit_stages(mesh: Mesh,
               specs: Sequence[Tuple[Any, ...]]) -> List[Tuple[Any, Any]]:
    """Jit each (desc, body, in_spec, out_spec) as its own shard_mapped
    program so per-phase timers can fence between them. Module-level so
    plans outside the DistFFTPlan hierarchy (Batched2DFFTPlan) share the
    exact stage contract."""
    out = []
    for desc, fn, ispec, ospec in specs:
        sm = jax.shard_map(fn, mesh=mesh, in_specs=ispec, out_specs=ospec)
        out.append((desc, jax.jit(
            sm, in_shardings=NamedSharding(mesh, ispec),
            out_shardings=NamedSharding(mesh, ospec))))
    return out
