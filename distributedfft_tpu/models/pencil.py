"""Pencil (2D) decomposition engine.

TPU-native re-design of the reference's pencil family
(``src/pencil/mpicufft_pencil.cpp``, 1841 LoC + Opt1 variant): the global
``Nx x Ny x Nz`` array is decomposed over a ``P1 x P2`` grid
(``pidx = pidx_i * P2 + pidx_j``, ``src/pencil/mpicufft_pencil.cpp:83-85``),
and the transform runs

    1D FFT z  ->  transpose 1 (row communicator, P2 ranks)
              ->  1D FFT y  ->  transpose 2 (column communicator, P1 ranks)
              ->  1D FFT x

Here the two sub-communicators created by ``MPI_Comm_split``
(``mpicufft_pencil.cpp:112-123``) are the two named axes of a
``Mesh(('p1','p2'))``; each transpose is a ``lax.all_to_all`` over one axis.
The three distribution stages (input / transposed / output
``Partition_Dimensions``, ``mpicufft_pencil.cpp:87-110``) become three
``PartitionSpec``s:

    input      P('p1','p2', None)   — z-pencils
    transposed P('p1', None, 'p2')  — y-pencils
    output     P(None, 'p1','p2')   — x-pencils

Partial-dimension execution ``exec_r2c(x, dims=d)`` for d in {1,2,3} mirrors
the reference's ``execR2C(out, in, d)`` early-returns
(``mpicufft_pencil.cpp:1665-1668,1710-1711``) used to test pipeline stages in
isolation.

Per-transpose communication methods: the reference takes ``-comm1/-snd1``
and ``-comm2/-snd2`` (``tests/src/pencil/main.cpp:26-63``); here
``Config.comm_method`` governs transpose 1 and ``Config.comm_method2``
transpose 2 — ``ALL2ALL`` places an explicit ``lax.all_to_all`` inside the
shard_mapped segment, ``PEER2PEER`` breaks the pipeline at that point and
lets XLA's SPMD partitioner insert/schedule the resharding collective.

The padded-shape contract matches the slab engine (see ``models/slab.py``):
every mesh-decomposed axis of a distributed global array is zero-padded to a
multiple of its mesh axis; the halved ``Nz/2+1`` z axis is padded only for
the d>=2 transposes that scatter it.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .. import obs
from .. import params as pm
from ..ops import fft as lf
from ..parallel.mesh import PENCIL_AXES, make_pencil_mesh
from ..parallel.transpose import (WIRE_NATIVE, all_to_all_transpose,
                                  chunked_reshard, concat_axis_chunks,
                                  pad_axis_to, pipelined_all_to_all,
                                  ring_subblocks, ring_transpose,
                                  slice_axis_to, split_axis_chunks,
                                  wire_complex_dtype, wire_decode,
                                  wire_encode)
from ..resilience import inject
from ..utils import wisdom
from .base import DistFFTPlan, _with_pad, notice_axis_smoothness

P1_AXIS, P2_AXIS = PENCIL_AXES


class PencilFFTPlan(DistFFTPlan):
    """Distributed 3D R2C/C2R FFT with 2D (pencil) decomposition over (x, y)."""

    def __init__(self, global_size: pm.GlobalSize, partition: pm.PencilPartition,
                 config: Optional[pm.Config] = None, mesh: Optional[Mesh] = None,
                 transform: str = "r2c", dims: int = 3):
        # Wisdom resolution of "auto" Config fields (see SlabFFTPlan): the
        # comm race covers the pencil 2x2 (comm1 x comm2) matrix at dims=3.
        # ``dims`` is a resolution hint ONLY — the partial-transform depth
        # the run will execute (--fft-dim, an exec-time choice the plan
        # itself is agnostic to); it keys the wisdom entry and bounds the
        # race to the program that will actually run (at dims=2 only
        # transpose 1 exists, so comm2 is not raced).
        config = wisdom.resolve_config("pencil", global_size, partition,
                                       config, mesh=mesh,
                                       transform=transform, dims=dims)
        if mesh is None and partition.num_ranks > 1:
            mesh = make_pencil_mesh(partition.p1, partition.p2)
        if mesh is not None and partition.num_ranks > 1:
            for name, want in ((P1_AXIS, partition.p1), (P2_AXIS, partition.p2)):
                if name not in mesh.shape:
                    raise ValueError(
                        f"pencil mesh must have a {name!r} axis, got {mesh.axis_names}")
                if mesh.shape[name] != want:
                    raise ValueError(
                        f"mesh axis {name!r} has {mesh.shape[name]} devices but "
                        f"the partition asks for {want}")
        super().__init__(global_size, partition, config, mesh)
        if transform not in ("r2c", "c2c"):
            raise ValueError(f"transform must be 'r2c' or 'c2c', got {transform!r}")
        self.transform = transform
        g = global_size
        self.p1, self.p2 = partition.p1, partition.p2
        # Spectral z extent: halved for R2C, full for C2C (extension; the
        # reference core is R2C/C2R-only, BASELINE configs #1/#2 need C2C).
        self._nz_spec = g.nz if transform == "c2c" else g.nz_out
        if self.fft3d:
            self._nx_p1 = g.nx
            self._ny_p2 = g.ny
            self._ny_p1 = g.ny
            self._nzc_p2 = self._nz_spec
        else:
            self._nx_p1 = pm.padded_extent(g.nx, self.p1)
            self._ny_p2 = pm.padded_extent(g.ny, self.p2)
            self._ny_p1 = pm.padded_extent(g.ny, self.p1)
            self._nzc_p2 = pm.padded_extent(self._nz_spec, self.p2)
            self._in_spec = PartitionSpec(P1_AXIS, P2_AXIS, None)
            self._mid_spec = PartitionSpec(P1_AXIS, None, P2_AXIS)
            self._out_spec = PartitionSpec(None, P1_AXIS, P2_AXIS)
        # compiled-callable caches keyed by dims
        self._r2c_d: Dict[int, object] = {}
        self._c2r_d: Dict[int, object] = {}
        # The depth the wisdom entry was resolved under (the fallback
        # ladder's demotion stamp must target the exact cell).
        self._wisdom_dims = dims
        notice_axis_smoothness("pencil", g.shape, self.config)
        obs.event("plan.created", kind="pencil", transform=transform,
                  shape=list(g.shape), grid=[self.p1, self.p2],
                  comm=self.config.comm_method.value,
                  comm2=self.config.resolved_comm2().value,
                  send=self.config.send_method.value,
                  send2=self.config.resolved_snd2().value,
                  opt=self.config.opt, wire=self.config.wire_dtype,
                  backend=self.config.fft_backend)

    # -- shapes ------------------------------------------------------------

    @property
    def input_padded_shape(self) -> Tuple[int, int, int]:
        g = self.global_size
        return (self._nx_p1, self._ny_p2, g.nz)

    @property
    def output_shape(self) -> Tuple[int, int, int]:
        g = self.global_size
        return (g.nx, g.ny, self._nz_spec)

    def output_padded_shape_for(self, dims: int = 3) -> Tuple[int, int, int]:
        g = self.global_size
        if self.fft3d:
            return (g.nx, g.ny, self._nz_spec)
        if dims == 1:
            return (self._nx_p1, self._ny_p2, self._nz_spec)
        if dims == 2:
            return (self._nx_p1, g.ny, self._nzc_p2)
        return (g.nx, self._ny_p1, self._nzc_p2)

    @property
    def output_padded_shape(self) -> Tuple[int, int, int]:
        return self.output_padded_shape_for(3)

    def spec_for(self, dims: int = 3) -> PartitionSpec:
        """Output PartitionSpec per transform depth: z-pencils (d=1),
        y-pencils (d=2), x-pencils (d=3) — the three
        ``Partition_Dimensions`` of the reference."""
        if self.fft3d:
            return PartitionSpec()
        return {1: self._in_spec, 2: self._mid_spec, 3: self._out_spec}[dims]

    @property
    def output_spec(self) -> PartitionSpec:
        return self.spec_for(3)

    def output_sharding_for(self, dims: int = 3) -> Optional[NamedSharding]:
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, self.spec_for(dims))

    # -- per-rank size tables (reference Partition_Dimensions) ------------

    def partition_dims(self, stage: str) -> pm.PartitionDims:
        """Sizes per rank along each axis for 'input' / 'transposed' /
        'output' stages (reference ``mpicufft_pencil.cpp:87-110``).
        Logical extents; pad-only shards report 0."""
        g = self.global_size
        if stage == "input":
            return pm.PartitionDims(
                tuple(pm.even_shard_sizes(g.nx, self._nx_p1, self.p1)),
                tuple(pm.even_shard_sizes(g.ny, self._ny_p2, self.p2)),
                (g.nz,))
        if stage == "transposed":
            return pm.PartitionDims(
                tuple(pm.even_shard_sizes(g.nx, self._nx_p1, self.p1)),
                (g.ny,),
                tuple(pm.even_shard_sizes(self._nz_spec, self._nzc_p2, self.p2)))
        if stage == "output":
            return pm.PartitionDims(
                (g.nx,),
                tuple(pm.even_shard_sizes(g.ny, self._ny_p1, self.p1)),
                tuple(pm.even_shard_sizes(self._nz_spec, self._nzc_p2, self.p2)))
        raise ValueError(f"unknown stage {stage!r}")

    def in_sizes(self, axis: str = "x") -> List[int]:
        """Per-rank logical input extents along a decomposed axis — the
        pencil rendering of the reference's ``getInSize`` family
        (``include/mpicufft.hpp:66-79``, inherited by
        ``mpicufft_pencil.hpp``). Input pencils are decomposed over x (the
        p1 mesh axis) and y (p2); pad-only shards report 0. Thin projection
        of ``partition_dims("input")``."""
        d = self.partition_dims("input")
        if axis == "x":
            return list(d.size_x)
        if axis == "y":
            return list(d.size_y)
        raise ValueError("pencil input is decomposed over x and y only, "
                         f"not {axis!r}")

    def out_sizes(self, axis: str) -> List[int]:
        """Per-rank logical output extents along a decomposed axis (full-
        depth dims=3 output: x-pencils, decomposed over y on p1 and the
        spectral z on p2). Reference ``getOutSize`` family
        (``include/mpicufft.hpp:66-79``)."""
        d = self.partition_dims("output")
        if axis == "y":
            return list(d.size_y)
        if axis == "z":
            return list(d.size_z)
        raise ValueError("pencil output is decomposed over y and z only, "
                         f"not {axis!r}")

    # -- logical <-> padded helpers ---------------------------------------

    def pad_input(self, x):
        g = self.global_size
        pads = [(0, self._nx_p1 - g.nx), (0, self._ny_p2 - g.ny), (0, 0)]
        if any(p[1] for p in pads):
            x = jnp.pad(x, pads)
        if self.mesh is not None:
            x = jax.device_put(x, self.input_sharding)
        return x

    def crop_real(self, r):
        g = self.global_size
        return np.asarray(r)[: g.nx, : g.ny, :]

    def crop_spectral(self, c, dims: int = 3):
        g = self.global_size
        padded = self.output_padded_shape_for(dims)
        if tuple(c.shape) != padded:
            raise ValueError(
                f"crop_spectral(dims={dims}) expects padded shape {padded}, "
                f"got {tuple(c.shape)}")
        return np.asarray(c)[: g.nx, : g.ny, : self._nz_spec]

    def pad_spectral(self, c, dims: int = 3):
        g = self.global_size
        tgt = self.output_padded_shape_for(dims)
        pads = [(0, tgt[i] - s) for i, s in enumerate((g.nx, g.ny, self._nz_spec))]
        if any(p[1] for p in pads):
            c = jnp.pad(c, pads)
        if self.mesh is not None:
            c = jax.device_put(c, self.output_sharding_for(dims))
        return c

    # -- execution ---------------------------------------------------------

    def exec_c2c(self, x, dims: int = 3):
        """Forward 3D (or partial) C2C transform (transform='c2c' plans)."""
        if self.transform != "c2c":
            raise TypeError("this plan was built with transform='r2c'; "
                            "use exec_r2c/exec_c2r")
        return self._exec_fwd(x, dims)

    def exec_c2c_inv(self, c, dims: int = 3):
        """Inverse of ``exec_c2c``."""
        if self.transform != "c2c":
            raise TypeError("this plan was built with transform='r2c'; "
                            "use exec_r2c/exec_c2r")
        return self._exec_inv(c, dims)

    def exec_r2c(self, x, dims: int = 3):
        """Forward transform of the first ``dims`` axes (z, then y, then x),
        mirroring the reference's partial-dimension ``execR2C(out, in, d)``."""
        if self.transform != "r2c":
            raise TypeError("this plan was built with transform='c2c'; "
                            "use exec_c2c/exec_c2c_inv")
        return self._exec_fwd(x, dims)

    def exec_c2r(self, c, dims: int = 3):
        """Inverse of ``exec_r2c(..., dims)``."""
        if self.transform != "r2c":
            raise TypeError("this plan was built with transform='c2c'; "
                            "use exec_c2c/exec_c2c_inv")
        return self._exec_inv(c, dims)

    def _exec_fwd(self, x, dims: int = 3):
        if dims not in (1, 2, 3):
            raise ValueError(f"dims must be 1, 2 or 3, got {dims}")
        if tuple(x.shape) not in (self.input_shape, self.input_padded_shape):
            raise ValueError(
                f"forward exec expects global shape {self.input_shape} (or "
                f"padded {self.input_padded_shape}), got {tuple(x.shape)}")
        if not self.fft3d and tuple(x.shape) == self.input_shape \
                and self.input_shape != self.input_padded_shape:
            x = self.pad_input(x)
        from ..resilience import fallback

        def get():
            if dims not in self._r2c_d:
                self._r2c_d[dims] = self._build_r2c_d(dims)
            return self._r2c_d[dims]

        return fallback.execute(self, "forward", x, get, dims=dims)

    def _exec_inv(self, c, dims: int = 3):
        if dims not in (1, 2, 3):
            raise ValueError(f"dims must be 1, 2 or 3, got {dims}")
        padded = self.output_padded_shape_for(dims)
        if tuple(c.shape) not in (self.output_shape, padded):
            raise ValueError(
                f"inverse exec(dims={dims}) expects global shape "
                f"{self.output_shape} (or padded {padded}), got {tuple(c.shape)}")
        if not self.fft3d and tuple(c.shape) == self.output_shape \
                and self.output_shape != padded:
            c = self.pad_spectral(c, dims)
        from ..resilience import fallback

        def get():
            if dims not in self._c2r_d:
                self._c2r_d[dims] = self._build_c2r_d(dims)
            return self._c2r_d[dims]

        return fallback.execute(self, "inverse", c, get, dims=dims)

    # -- resilience hooks (guards + fallback ladder) -----------------------

    def _transformed_volume(self, dims: int) -> float:
        """Product of the transformed logical extents at depth ``dims``
        (the Parseval scale; matches ``testcases._roundtrip_scale``)."""
        g = self.global_size
        return float({1: g.nz, 2: g.nz * g.ny, 3: g.n_total}[dims])

    def _guard_spec(self, direction: str, dims: int = 3):
        """GuardSpec per direction AND depth (slab contract; the partial-
        dims programs conserve energy over exactly the transformed
        axes)."""
        from ..resilience.guards import GuardSpec
        g, norm = self.global_size, self.config.norm
        n = self._transformed_volume(dims)
        c2c = self.transform == "c2c"
        out_logical = (g.nx, g.ny, self._nz_spec)
        if direction == "forward":
            return GuardSpec(
                direction="forward", check="parseval",
                scale=1.0 if norm is pm.FFTNorm.ORTHO else n,
                in_logical=self.input_shape, out_logical=out_logical,
                halved_axis=None if c2c else 2,
                halved_n=0 if c2c else g.nz)
        if not c2c:
            return GuardSpec(direction="inverse", check="finite", scale=1.0,
                             in_logical=out_logical,
                             out_logical=self.input_shape)
        scale = {pm.FFTNorm.NONE: n, pm.FFTNorm.BACKWARD: 1.0 / n,
                 pm.FFTNorm.ORTHO: 1.0}[norm]
        return GuardSpec(direction="inverse", check="parseval", scale=scale,
                         in_logical=out_logical,
                         out_logical=self.input_shape)

    def _wisdom_key_args(self) -> dict:
        return {"kind": "pencil", "transform": self.transform,
                "dims": self._wisdom_dims}

    # -- pipeline bodies ---------------------------------------------------

    def _scope_ids(self, direction: str, dims: int) -> Dict[str, str]:
        """Plan-graph node ids per pipeline part (obs/profile.py stage
        scopes) — mirrors ``_declare_graph``'s per-kind numbering exactly:
        local-FFT stages count in pipeline order, exchanges count only
        when their mesh axis is > 1 (the graph declares none otherwise)."""
        ids: Dict[str, str] = {}
        lf_n = x_n = 0

        def nlf(part: str) -> None:
            nonlocal lf_n
            lf_n += 1
            ids[part] = f"local_fft:{lf_n}"

        def nx_(part: str, p: int) -> None:
            nonlocal x_n
            if p > 1:
                x_n += 1
                ids[part] = f"exchange:{x_n}"

        if direction == "forward":
            nlf("s1")
            if dims >= 2:
                nx_("t1", self.p2)
                nlf("s2")
            if dims >= 3:
                nx_("t2", self.p1)
                nlf("s3")
        else:
            if dims >= 3:
                nlf("i3")
                nx_("t2b", self.p1)
            if dims >= 2:
                nlf("i2")
                nx_("t1b", self.p2)
            nlf("i1")
        return ids

    def _fwd_parts(self, dims: int):
        """(s1, t1, s2, t2, s3): local-FFT bodies and transpose bodies for
        the forward pipeline at depth ``dims``; t's are None when the
        pipeline stops before them."""
        g, norm = self.global_size, self.config.norm
        realigned = self.config.opt == 1
        be = self.config.fft_backend
        st = self._mxu_st
        wire = self.config.wire_dtype
        nzc_p2, ny_p1 = self._nzc_p2, self._ny_p1
        ny, nx = g.ny, g.nx
        complex_mode = self.transform == "c2c"

        def s1(xl):
            if complex_mode:
                c = lf.fft(xl, axis=2, norm=norm, backend=be, settings=st)
            else:
                c = lf.rfft(xl, axis=2, norm=norm, backend=be, settings=st)
            if dims >= 2:
                c = pad_axis_to(c, 2, nzc_p2)
            return c

        def t1(cl):
            return all_to_all_transpose(cl, P2_AXIS, 2, 1, realigned=realigned,
                                        wire=wire)

        def s2(cl):
            c = slice_axis_to(cl, 1, ny)
            c = lf.fft(c, axis=1, norm=norm, backend=be, settings=st)
            if dims >= 3:
                c = pad_axis_to(c, 1, ny_p1)
            return c

        def t2(cl):
            return all_to_all_transpose(cl, P1_AXIS, 1, 0, realigned=realigned,
                                        wire=wire)

        def s3(cl):
            c = slice_axis_to(cl, 0, nx)
            return lf.fft(c, axis=0, norm=norm, backend=be, settings=st)

        # Stage scopes (obs/profile.py): graph node ids per part.
        ids = self._scope_ids("forward", dims)
        sc = obs.profile.scoped
        return (sc("pencil", ids["s1"], s1),
                sc("pencil", ids.get("t1", ""), t1) if dims >= 2 else None,
                sc("pencil", ids.get("s2", "local_fft:2"), s2),
                sc("pencil", ids.get("t2", ""), t2) if dims >= 3 else None,
                sc("pencil", ids.get("s3", "local_fft:3"), s3))

    def _inv_parts(self, dims: int):
        """(i3, t2b, i2, t1b, i1): inverse bodies mirroring ``_fwd_parts``."""
        g, norm = self.global_size, self.config.norm
        realigned = self.config.opt == 1
        be = self.config.fft_backend
        st = self._mxu_st
        wire = self.config.wire_dtype
        nx_p1, ny_p2 = self._nx_p1, self._ny_p2
        ny, nzc, nz = g.ny, self._nz_spec, g.nz
        complex_mode = self.transform == "c2c"

        def i3(cl):
            c = lf.ifft(cl, axis=0, norm=norm, backend=be, settings=st)
            return pad_axis_to(c, 0, nx_p1)

        def t2b(cl):
            return all_to_all_transpose(cl, P1_AXIS, 0, 1, realigned=realigned,
                                        wire=wire)

        def i2(cl):
            c = slice_axis_to(cl, 1, ny)
            c = lf.ifft(c, axis=1, norm=norm, backend=be, settings=st)
            return pad_axis_to(c, 1, ny_p2)

        def t1b(cl):
            return all_to_all_transpose(cl, P2_AXIS, 1, 2, realigned=realigned,
                                        wire=wire)

        def i1(cl):
            c = slice_axis_to(cl, 2, nzc)
            if complex_mode:
                return lf.ifft(c, axis=2, norm=norm, backend=be, settings=st)
            return lf.irfft(c, n=nz, axis=2, norm=norm, backend=be, settings=st)

        # Stage scopes (obs/profile.py): inverse graph node numbering.
        ids = self._scope_ids("inverse", dims)
        sc = obs.profile.scoped
        return (sc("pencil", ids.get("i3", ""), i3) if dims >= 3 else None,
                sc("pencil", ids.get("t2b", ""), t2b) if dims >= 3 else None,
                sc("pencil", ids.get("i2", ""), i2) if dims >= 2 else None,
                sc("pencil", ids.get("t1b", ""), t1b) if dims >= 2 else None,
                sc("pencil", ids["i1"], i1))

    # -- pipeline builders -------------------------------------------------

    def _fwd_segments(self, dims: int):
        """(segments, start_spec) of the forward pipeline.

        Each transpose leaves exactly one axis untouched (t1 moves z<->y,
        free x; t2 moves y<->x, free z) and the FFT stage that follows it
        never transforms that axis — so under ``SendMethod.STREAMS`` the
        (transpose, next FFT) pair chunks along the free axis into K
        independent exchange->FFT piece chains (``_attach``), the pencil
        rendering of the reference's per-transpose Streams engine
        (``src/pencil/mpicufft_pencil.cpp:678-1482`` send methods)."""
        s1, t1, s2, t2, s3 = self._fwd_parts(dims)
        ids = self._scope_ids("forward", dims)
        segments = [(s1, self._in_spec)]
        if dims >= 2:
            if not self._attach(segments, self.config.comm_method,
                                self.config.send_method, t1, s2,
                                self._mid_spec, ca=0,
                                xinfo=(P2_AXIS, 2, 1),
                                scope_id=ids.get("t1", "")):
                segments.append((s2, self._mid_spec))
        if dims >= 3:
            if not self._attach(segments, self.config.resolved_comm2(),
                                self.config.resolved_snd2(), t2, s3,
                                self._out_spec, ca=2,
                                xinfo=(P1_AXIS, 1, 0),
                                scope_id=ids.get("t2", "")):
                segments.append((s3, self._out_spec))
        return segments, self._in_spec

    def _inv_segments(self, dims: int):
        """(segments, start_spec) of the inverse pipeline (free axes mirror
        the forward: t2b moves x<->y, free z; t1b moves y<->z, free x)."""
        i3, t2b, i2, t1b, i1 = self._inv_parts(dims)
        ids = self._scope_ids("inverse", dims)
        segments: List = []
        if dims >= 3:
            segments.append((i3, self._out_spec))
            if self._attach(segments, self.config.resolved_comm2(),
                            self.config.resolved_snd2(), t2b, i2,
                            self._mid_spec, ca=2,
                            xinfo=(P1_AXIS, 0, 1),
                            scope_id=ids.get("t2b", "")):
                i2 = None  # consumed into the chunked segment
        if dims >= 2:
            if i2 is not None:
                segments.append((i2, self._mid_spec))
            if self._attach(segments, self.config.comm_method,
                            self.config.send_method, t1b, i1,
                            self._in_spec, ca=0,
                            xinfo=(P2_AXIS, 1, 2),
                            scope_id=ids.get("t1b", "")):
                i1 = None
        if i1 is not None:
            segments.append((i1, self._in_spec))
        start = {3: self._out_spec, 2: self._mid_spec, 1: self._in_spec}[dims]
        return segments, start

    def _build_r2c_d(self, dims: int):
        with obs.span("plan.build", kind="pencil", direction="forward",
                      dims=dims):
            if self.fft3d:
                return self._fft3d_r2c_d(dims)
            return self._compile(*self._fwd_segments(dims),
                                 direction="forward", dims=dims)

    def _build_c2r_d(self, dims: int):
        with obs.span("plan.build", kind="pencil", direction="inverse",
                      dims=dims):
            if self.fft3d:
                return self._fft3d_c2r_d(dims)
            return self._compile(*self._inv_segments(dims),
                                 direction="inverse", dims=dims)

    def forward_fn(self, dims: int = 3):
        """Pure forward pipeline (``DistFFTPlan.forward_fn`` contract);
        ``dims`` as in ``exec_r2c``. Cached per (plan, dims); pads
        logical-shaped input like the exec path (traced, differentiable)."""
        if self._fwd_pure is None:
            self._fwd_pure = {}
        if dims not in self._fwd_pure:
            if self.fft3d:
                run = self._fft3d_r2c_d(dims, jit=False)
            else:
                run, _ = self._compose(*self._fwd_segments(dims))
            self._fwd_pure[dims] = _with_pad(run, self.input_shape,
                                             self.input_padded_shape)
        return self._fwd_pure[dims]

    def inverse_fn(self, dims: int = 3):
        """Pure inverse pipeline (``DistFFTPlan.forward_fn`` contract).
        At dims=3 logical-shaped spectral input is padded like the exec
        path; partial-depth (dims<3) input must already be in the padded
        intermediate layout ``output_padded_shape_for(dims)``."""
        if self._inv_pure is None:
            self._inv_pure = {}
        if dims not in self._inv_pure:
            if self.fft3d:
                run = self._fft3d_c2r_d(dims, jit=False)
            else:
                run, _ = self._compose(*self._inv_segments(dims))
            if dims == 3:
                run = _with_pad(run, self.output_shape,
                                self.output_padded_shape)
            self._inv_pure[dims] = run
        return self._inv_pure[dims]

    # -- per-phase staged execution (benchmark timer support) --------------

    variant_name = "pencil"

    @property
    def section_descriptions(self) -> List[str]:
        """Reference pencil phase vocabulary
        (include/mpicufft_pencil.hpp:263-287). Phases with no XLA analog
        (pack/unpack/send bookkeeping) stay 0 in the CSV."""
        def tr(prefix, send_complete):
            xs = ["First Send", "Packing", "Start Local Transpose",
                  "Start Receive", "First Receive", "Finished Receive",
                  "Start All2All", "Finished All2All", "Unpacking"]
            if send_complete:
                xs.append("Send Complete")
            return [f"{prefix} Transpose ({x})" for x in xs]
        # 24 sections; only the First transpose has a "(Send Complete)"
        # marker in the reference list.
        # "Run complete (fused)" extends the vocabulary with the mark after
        # one extra call of the fused production program (see the slab list).
        return (["init", "1D FFT Z-Direction"] + tr("First", True)
                + ["1D FFT Y-Direction"] + tr("Second", False)
                + ["1D FFT X-Direction", "Run complete",
                   "Run complete (fused)"])

    def _xpose_desc(self, which: int) -> str:
        comm = (self.config.comm_method if which == 1
                else self.config.resolved_comm2())
        prefix = "First" if which == 1 else "Second"
        kind = ("Finished All2All" if comm is pm.CommMethod.ALL2ALL
                else "Finished Receive")
        return f"{prefix} Transpose ({kind})"

    def forward_stages(self, dims: int = 3):
        """[(phase desc, jitted stage fn)] for per-phase timed execution
        (always explicit collectives; the fused exec path is unaffected)."""
        if self.fft3d:
            return [(None, lambda x: self._exec_fwd(x, dims))]
        s1, t1, s2, t2, s3 = self._fwd_parts(dims)
        specs = [("1D FFT Z-Direction", s1, self._in_spec, self._in_spec)]
        if dims >= 2:
            specs += [(self._xpose_desc(1), t1, self._in_spec, self._mid_spec),
                      ("1D FFT Y-Direction", s2, self._mid_spec, self._mid_spec)]
        if dims >= 3:
            specs += [(self._xpose_desc(2), t2, self._mid_spec, self._out_spec),
                      ("1D FFT X-Direction", s3, self._out_spec, self._out_spec)]
        return self._jit_stages(specs)

    def inverse_stages(self, dims: int = 3):
        if self.fft3d:
            return [(None, lambda c: self._exec_inv(c, dims))]
        i3, t2b, i2, t1b, i1 = self._inv_parts(dims)
        specs = []
        if dims >= 3:
            specs += [("1D FFT X-Direction", i3, self._out_spec, self._out_spec),
                      (self._xpose_desc(2), t2b, self._out_spec, self._mid_spec)]
        if dims >= 2:
            specs += [("1D FFT Y-Direction", i2, self._mid_spec, self._mid_spec),
                      (self._xpose_desc(1), t1b, self._mid_spec, self._in_spec)]
        specs.append(("1D FFT Z-Direction", i1, self._in_spec, self._in_spec))
        return self._jit_stages(specs)



    def _attach(self, segments, comm: pm.CommMethod, snd: pm.SendMethod,
                a2a, nxt, spec_after, ca: int, *,
                xinfo: Tuple[str, int, int], scope_id: str = "") -> bool:
        """Attach a transpose to the segment list.

        ALL2ALL + SYNC: explicit collective fused into the previous segment.
        ALL2ALL + STREAMS: the previous segment is extended with K
        independent (transpose -> ``nxt``) piece chains along free axis
        ``ca``; returns True to signal ``nxt`` was consumed.
        PEER2PEER + SYNC: a segment break so GSPMD inserts the resharding
        collective at the boundary.
        PEER2PEER + STREAMS: a chunked break — the boundary reshards K
        pieces independently (``chunked_reshard``, shard-aligned pieces
        since the pencil chunk axes are mesh-sharded). Measured: GSPMD
        re-fuses the pieces into one collective (see
        ``SlabFFTPlan._assemble_pure``), so this is equivalent to SYNC;
        ALL2ALL is the genuinely chunked rendering.
        RING / RING_OVERLAP (any comm): the transpose rendered as the
        ``P-1``-step ``lax.ppermute`` ring (``ring_transpose`` over
        ``xinfo = (axis_name, split, concat)``; RING_OVERLAP issues each
        step's permute on the double-buffered schedule), fused into the
        previous segment — a ring is only expressible inside shard_map,
        so the ring renderings own the exchange regardless of ``comm``.
        Every pencil post-transpose FFT runs along the gathered axis (the
        received blocks are disjoint slices of exactly that axis), so no
        per-block compute is pipelined here; the win is the ``P-1``
        distinct, independently schedulable collective-permutes GSPMD
        cannot re-fuse the way it re-fuses the chunked reshards (and the
        fused wire uses the unpack-only arrival kernel).
        """
        if snd.is_ring:
            prev_fn, _ = segments[-1]
            axis_name, split, concat = xinfo
            wire = self.config.wire_dtype
            overlap = snd is pm.SendMethod.RING_OVERLAP
            depth = self.config.resolved_overlap_depth()
            subblocks = self.config.resolved_overlap_subblocks()
            from ..ops import pallas_fft as plf
            enc_fn, arr_fn = plf.fused_ring_hooks(self.config, snd)

            def rseg(c, f=prev_fn):
                # The ring is built here (not via the scoped a2a body), so
                # the exchange scope wraps this call site directly.
                with obs.profile.stage_scope("pencil", scope_id):
                    return ring_transpose(f(c), axis_name, split, concat,
                                          wire=wire, overlap=overlap,
                                          depth=depth, subblocks=subblocks,
                                          encode_fn=enc_fn,
                                          arrive_fn=arr_fn)

            segments[-1] = (rseg, spec_after)
            return False
        streams = snd is pm.SendMethod.STREAMS
        if comm is pm.CommMethod.ALL2ALL:
            prev_fn, _ = segments[-1]
            if streams:
                k = self.config.resolved_streams_chunks()

                def seg(c, f=prev_fn, a2a=a2a, nxt=nxt, ca=ca, k=k):
                    c = f(c)
                    return concat_axis_chunks(
                        [nxt(a2a(p)) for p in split_axis_chunks(c, ca, k)],
                        ca)

                segments[-1] = (seg, spec_after)
                return True
            if self.config.resolved_overlap_subblocks() > 1:
                # ALL2ALL + SYNC/MPI_TYPE with a sub-block split: the
                # software-pipelined monolithic exchange (a2a_pipe) —
                # chunk k+1's collective issued while chunk k decodes,
                # along the same free axis STREAMS chunks.
                axis_name, split, concat = xinfo
                wire = self.config.wire_dtype
                realigned = self.config.opt == 1
                pk = self.config.resolved_overlap_subblocks()
                depth = self.config.resolved_overlap_depth()

                def pseg(c, f=prev_fn):
                    with obs.profile.stage_scope("pencil", scope_id):
                        return pipelined_all_to_all(
                            f(c), axis_name, split, concat, chunk_axis=ca,
                            chunks=pk, depth=depth, realigned=realigned,
                            wire=wire)

                segments[-1] = (pseg, spec_after)
                return False
            segments[-1] = (lambda c, f=prev_fn: a2a(f(c)), spec_after)
            return False
        # PEER2PEER boundaries: when the wire compresses, the break carries
        # the marker so _compose wraps it encode-side / decode-side (the
        # GSPMD collective then moves the planar bf16 array). wire="native"
        # appends the exact pre-wire break tuples.
        wired = self.config.wire_dtype != WIRE_NATIVE
        if streams:
            segments.append((("CHUNKED_BREAK", ca,
                              self.config.resolved_streams_chunks(), wired),
                             spec_after))
            return False
        segments.append(("WBREAK" if wired else "BREAK", spec_after))
        return False

    def _compose(self, segments, in_spec):
        """Fuse consecutive segments that share a shard_map into staged
        shard_maps; returns the pure composition and its out spec."""
        mesh = self.mesh
        wire = self.config.wire_dtype
        cdt = wire_complex_dtype(self.config.double_prec)
        stages = []
        cur_fns: List = []
        cur_in = in_spec
        cur_out = in_spec

        def flush():
            if not cur_fns:
                return
            fns = list(cur_fns)

            def seg(xl, fns=fns):
                for f in fns:
                    xl = f(xl)
                return xl

            stages.append(jax.shard_map(seg, mesh=mesh, in_specs=cur_in,
                                        out_specs=cur_out))

        def encode_break(spec):
            """Close the current stage with a wire encode and open the next
            with the decode, so the GSPMD boundary collective between them
            moves the planar bf16 array (specs gain the leading plane
            axis). Returns the encoded next-stage spec (the boundary's
            target layout, for the chunked reshard's NamedSharding). The
            fault-injection taint sits after the encode — the corrupted
            wire image is what the boundary collective moves."""
            nonlocal cur_fns, cur_in, cur_out
            cur_fns.append(
                lambda c: inject.taint_wire(wire_encode(c, wire), "gspmd"))
            cur_out = PartitionSpec(None, *cur_out)
            flush()
            cur_fns = [lambda y: wire_decode(y, cdt, wire)]
            cur_in = PartitionSpec(None, *spec)
            cur_out = spec
            return cur_in

        for fn, spec in segments:
            if fn == "BREAK":
                # Native GSPMD boundary: the stage's output IS the wire
                # payload; the injection taint (identity without
                # $DFFT_FAULT_SPEC) closes the stage.
                if cur_fns:
                    cur_fns.append(lambda c: inject.taint_wire(c, "gspmd"))
                flush()
                cur_fns = []
                cur_in = spec
                cur_out = spec
            elif fn == "WBREAK":
                # PEER2PEER + compressed wire: the boundary reshard moves
                # the encoded planes; the decode opens the next stage.
                encode_break(spec)
            elif isinstance(fn, tuple) and fn[0] == "CHUNKED_BREAK":
                # PEER2PEER + STREAMS boundary: reshard K pieces of the
                # global array independently. Measured (8-device CPU
                # mesh): GSPMD re-fuses the piece reshards into one
                # collective — see SlabFFTPlan._assemble_pure — so this
                # rendering is equivalent to SYNC; the ALL2ALL rendering
                # is the genuinely chunked pencil path.
                _, ca, k, wired = fn
                if wired:
                    # encode_break flushes the encoded producer stage and
                    # leaves the decode pending as the next stage's first
                    # fn, so appending the reshard here lands it between
                    # them: encode -> piece reshards (compressed) ->
                    # decode. The chunk axis shifts past the plane axis.
                    sh = NamedSharding(mesh, encode_break(spec))
                    ca = ca + 1
                else:
                    flush()
                    sh = NamedSharding(mesh, spec)

                def reshard(x, sh=sh, ca=ca, k=k):
                    # The pencil chunk axes are mesh-sharded identically
                    # on both sides of their boundary (x over p1 at t1, z
                    # over p2 at t2); chunked_reshard splits within each
                    # shard's local block so the piece exchanges move
                    # exactly the monolithic exchange's bytes.
                    return chunked_reshard(x, sh, ca, k)

                stages.append(reshard)
                if not wired:
                    cur_fns = []
                    cur_in = spec
                    cur_out = spec
            else:
                cur_fns.append(fn)
                cur_out = spec
        flush()

        def run(x):
            for st in stages:
                x = st(x)
            return x

        return run, segments[-1][1]

    def _compile(self, segments, in_spec, direction: str = "forward",
                 dims: int = 3):
        """Jit the pure composition with in/out shardings; at guard modes
        check/enforce the jitted program is the guarded pipeline
        ``x -> (y, stats)`` (slab ``_assemble`` contract)."""
        from ..resilience import guards
        run, out_spec = self._compose(segments, in_spec)
        mesh = self.mesh
        run, guarded = guards.maybe_wrap(self, run, direction, dims)
        outsh = NamedSharding(mesh, out_spec)
        if guarded:
            outsh = (outsh, NamedSharding(mesh, PartitionSpec()))
        return jax.jit(run,
                       in_shardings=NamedSharding(mesh, in_spec),
                       out_shardings=outsh)

    # -- single-device partial-dim fallbacks ------------------------------

    def _fft3d_r2c_d(self, dims: int, jit: bool = True):
        norm, be = self.config.norm, self.config.fft_backend
        st = self._mxu_st
        complex_mode = self.transform == "c2c"

        def run(x):
            if complex_mode:
                c = lf.fft(x, axis=2, norm=norm, backend=be, settings=st)
            else:
                c = lf.rfft(x, axis=2, norm=norm, backend=be, settings=st)
            if dims >= 2:
                c = lf.fft(c, axis=1, norm=norm, backend=be, settings=st)
            if dims >= 3:
                c = lf.fft(c, axis=0, norm=norm, backend=be, settings=st)
            return c

        run = obs.profile.scoped("pencil", "local_fft:1", run)
        if not jit:
            return run
        from ..resilience import guards
        run, _ = guards.maybe_wrap(self, run, "forward", dims)
        return jax.jit(run)

    def _fft3d_c2r_d(self, dims: int, jit: bool = True):
        norm, be = self.config.norm, self.config.fft_backend
        st = self._mxu_st
        nz = self.global_size.nz
        complex_mode = self.transform == "c2c"

        def run(c):
            if dims >= 3:
                c = lf.ifft(c, axis=0, norm=norm, backend=be, settings=st)
            if dims >= 2:
                c = lf.ifft(c, axis=1, norm=norm, backend=be, settings=st)
            if complex_mode:
                return lf.ifft(c, axis=2, norm=norm, backend=be, settings=st)
            return lf.irfft(c, n=nz, axis=2, norm=norm, backend=be, settings=st)

        run = obs.profile.scoped("pencil", "local_fft:1", run)
        if not jit:
            return run
        from ..resilience import guards
        run, _ = guards.maybe_wrap(self, run, "inverse", dims)
        return jax.jit(run)


# ---------------------------------------------------------------------------
# contract declaration (analysis/contracts.py) — the exchanges this family
# stages at each partial-transform depth, next to the code that stages them.
# ---------------------------------------------------------------------------

def _contract_exchanges(plan, direction, dims=3):
    """Pencil: transpose 1 over p2 (scatter z, gather y; free axis x,
    chunk axis 0 sharded over p1) from dims >= 2, transpose 2 over p1
    (scatter y, gather x; free axis z, chunk axis 2 sharded over p2)
    from dims >= 3. Payloads are the padded spectral volumes both
    transposes move (``spec_for`` shapes)."""
    # Both transposes run (mirrored) in both directions; only the ring
    # sub-block split is direction-dependent — the concat axis (the one
    # the arriving blocks slice along) flips with the direction.
    if plan.fft3d:
        return ()
    from ..analysis import contracts as _c
    cfg = plan.config
    fwd = direction == "forward"
    sub = cfg.resolved_overlap_subblocks()
    out = []
    if dims >= 2 and plan.p2 > 1:
        r1 = _c.rendering_name(cfg)
        k1 = s1 = 1
        if r1 == "streams":
            k1 = min(cfg.resolved_streams_chunks(),
                     plan._nx_p1 // plan.p1)
        elif r1 == "a2a_pipe":
            k1 = ring_subblocks(plan._nx_p1 // plan.p1, sub)
        elif r1 in ("ring", "ring_overlap"):
            # Forward t1 gathers y (concat 1); inverse t1b gathers z
            # (concat 2). Local extents, same clamp as ring_transpose.
            ext = (plan._ny_p2 // plan.p2 if fwd
                   else plan._nzc_p2 // plan.p2)
            s1 = ring_subblocks(ext, sub)
        out.append(_c.ExchangeDecl(
            "transpose 1", (plan._nx_p1, plan._ny_p2, plan._nzc_p2),
            plan.p2, r1, k1, subblocks=s1))
    if dims >= 3 and plan.p1 > 1:
        r2 = _c.rendering_name(cfg, second=True)
        k2 = s2 = 1
        if r2 == "streams":
            k2 = min(cfg.resolved_streams_chunks(),
                     plan._nzc_p2 // plan.p2)
        elif r2 == "a2a_pipe":
            k2 = ring_subblocks(plan._nzc_p2 // plan.p2, sub)
        elif r2 in ("ring", "ring_overlap"):
            # Forward t2 gathers x (concat 0); inverse t2b gathers y
            # (concat 1).
            ext = (plan._nx_p1 // plan.p1 if fwd
                   else plan._ny_p1 // plan.p1)
            s2 = ring_subblocks(ext, sub)
        out.append(_c.ExchangeDecl(
            "transpose 2", (plan._nx_p1, plan._ny_p1, plan._nzc_p2),
            plan.p1, r2, k2, subblocks=s2))
    return tuple(out)


def _declare_graph(plan, direction, dims=3):
    """Pencil stage graph (analysis/plangraph.py): z FFT -> transpose 1
    (p2 axis, present from dims >= 2 when p2 > 1) -> y FFT -> transpose
    2 (p1 axis, from dims >= 3 when p1 > 1) -> x FFT, mirrored for the
    inverse; encode/decode around each compressed exchange (the fused
    wire uses the unpack-only arrival kernel — every pencil
    post-transpose FFT runs along the gathered axis, so nothing
    pipelines per block); guard at modes check/enforce."""
    from ..analysis import plangraph as _pg
    cfg = plan.config
    cdt, rdt = _pg.payload_dtypes(cfg, plan.transform)
    fwd = direction == "forward"
    b = _pg.GraphBuilder("pencil", direction, wire=cfg.wire_dtype,
                         guards=plan._guard_mode, complex_dtype=cdt)
    decls = {d.label: d for d in _contract_exchanges(plan, direction, dims)}

    def add_exchange(label, spec_after, second=False):
        d = decls.get(label)
        if d is None:
            return
        fused = cfg.fused_wire_active(second)
        b.exchange(d.label, d.payload_shape, d.axis_size, d.rendering,
                   chunks=d.chunks, subblocks=d.subblocks,
                   schedule_depth=_pg.shipped_schedule_depth(d.rendering,
                                                             cfg),
                   decoded_spec=spec_after, fused_encode=fused,
                   decode_fuses=("decode",) if fused else None)

    if fwd:
        b.node("input")
        b.payload(plan.input_padded_shape, rdt, plan.input_spec)
        if plan.fft3d:
            b.node("local_fft", axes=tuple((2, 1, 0)[:dims]),
                   label="fft3d")
        else:
            b.node("local_fft", axes=(2,), label="z stage")
            if dims >= 2:
                add_exchange("transpose 1", plan._mid_spec)
                b.node("local_fft", axes=(1,), label="y stage")
            if dims >= 3:
                add_exchange("transpose 2", plan._out_spec, second=True)
                b.node("local_fft", axes=(0,), label="x stage")
        b.payload(plan.output_padded_shape_for(dims), cdt,
                  plan.spec_for(dims) if not plan.fft3d else "")
    else:
        b.node("input")
        b.payload(plan.output_padded_shape_for(dims), cdt,
                  plan.spec_for(dims) if not plan.fft3d else "")
        if plan.fft3d:
            b.node("local_fft", axes=tuple(reversed((2, 1, 0)[:dims])),
                   label="fft3d")
        else:
            if dims >= 3:
                b.node("local_fft", axes=(0,), label="x stage")
                add_exchange("transpose 2", plan._mid_spec, second=True)
            if dims >= 2:
                b.node("local_fft", axes=(1,), label="y stage")
                add_exchange("transpose 1", plan._in_spec)
            b.node("local_fft", axes=(2,), label="z stage")
        b.payload(plan.input_padded_shape, rdt,
                  plan.input_spec if not plan.fft3d else "")
    if plan._guard_mode != "off":
        b.node("guard")
    b.node("output")
    return b.graph()


def _register_contracts():
    from ..analysis import contracts as _c
    from ..analysis import plangraph as _pg
    _c.register_family("pencil", "PencilFFTPlan", _contract_exchanges)
    _pg.register_graph_family("pencil", _declare_graph)


_register_contracts()
