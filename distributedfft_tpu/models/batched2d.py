"""Batched distributed 2D FFT — the convolution-workload plan.

BASELINE config #4 ("Batched 2D FFT 4096^2 x 64, 1D mesh") stresses an axis
the reference never tested (SURVEY §7 hard parts: "plan the planner API to
allow batch dims from day 1"). Arrays are ``(batch, nx, ny)``; the transform
runs over (x, y) with ``batch`` as a pure batch dimension (cuFFT "batched
plan" analog — the reference reaches batching only through cufftMakePlanMany
batch counts, e.g. ``src/slab/default/mpicufft_slab.cpp:154-167``).

Two decompositions over a 1D mesh:

* ``shard="batch"`` — embarrassingly parallel: the batch axis is sharded,
  each device transforms its images locally, zero collectives. The right
  choice whenever ``batch >= P``.
* ``shard="x"`` — slab-style: x sharded, 1D FFT y -> all_to_all transpose
  -> 1D FFT x, for batches too small to fill the mesh or images too large
  for one device.

Same padded-shape contract and comm-method mapping as the 3D engines.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .. import obs
from .. import params as pm
from ..ops import fft as lf
from ..parallel.mesh import SLAB_AXIS, make_slab_mesh
from ..parallel.transpose import (all_to_all_transpose, chunked_reshard,
                                  concat_axis_chunks, pad_axis_to,
                                  pipelined_all_to_all, ring_subblocks,
                                  ring_transpose, slice_axis_to,
                                  split_axis_chunks, wire_gspmd_stages)
from ..utils import wisdom
from .base import _with_pad, jit_stages, notice_axis_smoothness


class Batched2DFFTPlan:
    """Distributed batched 2D R2C/C2R (or C2C) FFT over a 1D mesh."""

    def __init__(self, batch: int, nx: int, ny: int,
                 partition: pm.SlabPartition,
                 config: Optional[pm.Config] = None,
                 mesh: Optional[Mesh] = None,
                 shard: str = "batch", transform: str = "r2c",
                 batch_chunk: Optional[int] = None):
        """``batch_chunk``: transform the (per-device) batch in sequential
        chunks of THIS SIZE via ``lax.map`` instead of one fused program
        (``batch_chunk=1`` = per-plane slices, the most chunked form;
        ``None``/0 = whole stack fused). Caps the peak intermediate
        footprint and the compiled program size — and at large plane
        sizes the finest slices are also the fastest: the 2026-07-31
        on-chip sweep at 4096^2 x 64 measured 483 ms roundtrip at
        ``batch_chunk=1`` vs 542/610/609 ms at 2/4/8 (the whole-stack
        fused program itself was not measured on-chip; its 2026-07-30
        attempt failed remote compile). Only meaningful when
        the batch axis is a pure batch dimension (``shard='batch'`` or the
        single-process fallback); must divide the (local, padded) batch."""
        if shard not in ("batch", "x"):
            raise ValueError(f"shard must be 'batch' or 'x', got {shard!r}")
        if transform not in ("r2c", "c2c"):
            raise ValueError(f"transform must be 'r2c' or 'c2c', got {transform!r}")
        if batch <= 0 or nx <= 0 or ny <= 0:
            raise ValueError("batch/nx/ny must be positive")
        if batch_chunk == 0:
            batch_chunk = None  # documented alias: 0 = whole stack fused
        # Wisdom resolution of "auto" Config fields (see SlabFFTPlan);
        # shard='batch' issues no collectives, so its comm "auto" resolves
        # to the defaults without a race.
        config = wisdom.resolve_config(
            "batched2d", pm.GlobalSize(batch, nx, ny), partition, config,
            mesh=mesh, transform=transform, dims=2, variant=shard)
        if mesh is None and partition.p > 1:
            mesh = make_slab_mesh(partition.p)
        if mesh is not None and partition.p > 1 \
                and mesh.shape.get(SLAB_AXIS) != partition.p:
            raise ValueError(
                f"mesh axis {SLAB_AXIS!r} must have {partition.p} devices")
        self.batch, self.nx, self.ny = batch, nx, ny
        self.partition = partition
        self.config = config or pm.Config()
        # Settings snapshot at construction (see DistFFTPlan.__init__).
        self._mxu_st = self.config.mxu_settings()
        # Resilience state (DistFFTPlan contract — this plan sits outside
        # that hierarchy but honors the same guard/fallback envelope).
        from ..resilience import guards as _guards
        self._guard_mode = _guards.resolved_mode(self.config)
        self._guard_state = {}
        self.mesh = mesh
        self.shard = shard
        self.transform = transform
        self.fft3d = mesh is None or partition.p == 1
        P = partition.p
        self._P = P
        self._ny_spec = ny if transform == "c2c" else ny // 2 + 1
        if self.fft3d:
            self._batch_pad, self._nx_pad, self._nys_pad = batch, nx, self._ny_spec
            self._in_spec = self._out_spec = PartitionSpec()
        elif shard == "batch":
            self._batch_pad = pm.padded_extent(batch, P)
            self._nx_pad, self._nys_pad = nx, self._ny_spec
            self._in_spec = PartitionSpec(SLAB_AXIS, None, None)
            self._out_spec = PartitionSpec(SLAB_AXIS, None, None)
        else:
            self._batch_pad = batch
            self._nx_pad = pm.padded_extent(nx, P)
            self._nys_pad = pm.padded_extent(self._ny_spec, P)
            self._in_spec = PartitionSpec(None, SLAB_AXIS, None)
            self._out_spec = PartitionSpec(None, None, SLAB_AXIS)
        self.batch_chunk = batch_chunk
        if batch_chunk is not None:
            if batch_chunk <= 0:
                raise ValueError("batch_chunk must be positive")
            if not (self.fft3d or shard == "batch"):
                raise ValueError("batch_chunk requires shard='batch' (or "
                                 "the single-process fallback): with "
                                 "shard='x' the batch axis is not chunkable "
                                 "independently of the collectives")
            local_b = self._batch_pad if self.fft3d else self._batch_pad // P
            if local_b % batch_chunk:
                raise ValueError(
                    f"batch_chunk {batch_chunk} must divide the local "
                    f"padded batch {local_b}")
        self._fwd = None
        self._inv = None
        self._fwd_unguarded = None  # staged path under guard modes
        self._inv_unguarded = None
        self._fwd_pure = None
        self._inv_pure = None
        notice_axis_smoothness("batched2d", (nx, ny), self.config)
        obs.event("plan.created", kind="batched2d", shard=shard,
                  transform=transform, shape=[batch, nx, ny], ranks=P,
                  batch_chunk=batch_chunk,
                  comm=self.config.comm_method.value,
                  send=self.config.send_method.value, opt=self.config.opt,
                  wire=self.config.wire_dtype,
                  backend=self.config.fft_backend)

    # -- shapes ------------------------------------------------------------

    @property
    def input_shape(self) -> Tuple[int, int, int]:
        return (self.batch, self.nx, self.ny)

    @property
    def input_padded_shape(self) -> Tuple[int, int, int]:
        # batch-sharded pads batch; x-sharded pads x; single-device neither.
        return (self._batch_pad, self._nx_pad, self.ny)

    @property
    def output_shape(self) -> Tuple[int, int, int]:
        return (self.batch, self.nx, self._ny_spec)

    @property
    def output_padded_shape(self) -> Tuple[int, int, int]:
        if self.fft3d or self.shard == "batch":
            return (self._batch_pad, self.nx, self._ny_spec)
        return (self.batch, self.nx, self._nys_pad)

    @property
    def input_sharding(self) -> Optional[NamedSharding]:
        return None if self.mesh is None else NamedSharding(self.mesh, self._in_spec)

    @property
    def output_sharding(self) -> Optional[NamedSharding]:
        return None if self.mesh is None else NamedSharding(self.mesh, self._out_spec)

    # -- pad/crop ----------------------------------------------------------

    def pad_input(self, x):
        tgt = self.input_padded_shape
        pads = [(0, tgt[i] - s) for i, s in enumerate(x.shape)]
        if any(p[1] for p in pads):
            x = jnp.pad(x, pads)
        if self.mesh is not None:
            x = jax.device_put(x, self.input_sharding)
        return x

    def pad_spectral(self, c):
        """Logical spectral array -> padded, device-placed output layout
        (same helper pair as the 3D plans)."""
        tgt = self.output_padded_shape
        pads = [(0, t - s) for t, s in zip(tgt, c.shape)]
        if any(p[1] for p in pads):
            c = jnp.pad(c, pads)
        if self.mesh is not None:
            c = jax.device_put(c, self.output_sharding)
        return c

    def crop_spectral(self, c) -> np.ndarray:
        return np.asarray(c)[: self.batch, : self.nx, : self._ny_spec]

    def crop_real(self, r) -> np.ndarray:
        return np.asarray(r)[: self.batch, : self.nx, : self.ny]

    # -- execution ---------------------------------------------------------

    def exec_forward(self, x):
        """Batched 2D forward transform over (x, y)."""
        if tuple(x.shape) not in (self.input_shape, self.input_padded_shape):
            raise ValueError(
                f"expected {self.input_shape} (or padded "
                f"{self.input_padded_shape}), got {tuple(x.shape)}")
        if tuple(x.shape) == self.input_shape \
                and self.input_shape != self.input_padded_shape:
            x = self.pad_input(x)
        from ..resilience import fallback
        return fallback.execute(self, "forward", x, self._get_fwd, dims=2)

    def exec_inverse(self, c):
        """Batched 2D inverse transform."""
        if tuple(c.shape) not in (self.output_shape, self.output_padded_shape):
            raise ValueError(
                f"expected {self.output_shape} (or padded "
                f"{self.output_padded_shape}), got {tuple(c.shape)}")
        if tuple(c.shape) == self.output_shape \
                and self.output_shape != self.output_padded_shape:
            c = self.pad_spectral(c)
        from ..resilience import fallback
        return fallback.execute(self, "inverse", c, self._get_inv, dims=2)

    def _get_fwd(self):
        if self._fwd is None:
            self._fwd = self._build(forward=True)
        return self._fwd

    def _get_inv(self):
        if self._inv is None:
            self._inv = self._build(forward=False)
        return self._inv

    # -- solver protocol (models/base.py contract; this plan sits outside
    #    the DistFFTPlan hierarchy but honors the identical surface) -------

    @property
    def transform_axes(self) -> Tuple[int, ...]:
        """The 2D transform covers (x, y); axis 0 is a pure batch
        dimension the solver suite broadcasts its symbols over."""
        return (1, 2)

    @property
    def transform_size(self) -> int:
        """N of the per-plane 2D transform (nx*ny; the batch axis carries
        no normalization — ``DistFFTPlan.transform_size`` contract)."""
        return self.nx * self.ny

    @property
    def spectral_halved_axis(self) -> Optional[int]:
        return None if self.transform == "c2c" else 2

    def exec_fwd(self, x):
        """Solver-protocol forward (``DistFFTPlan.exec_fwd`` contract)."""
        return self.exec_forward(x)

    def exec_inv(self, c):
        return self.exec_inverse(c)

    # -- resilience hooks (guards + fallback ladder) -----------------------

    def _guard_spec(self, direction: str, dims: int = 2):
        """GuardSpec of the batched-2D pipelines (slab contract): the
        transform covers (x, y) of every plane, so the Parseval volume is
        ``nx * ny`` and the R2C halved axis is the last slot."""
        from ..resilience.guards import GuardSpec
        norm = self.config.norm
        n = float(self.nx * self.ny)
        c2c = self.transform == "c2c"
        if direction == "forward":
            return GuardSpec(
                direction="forward", check="parseval",
                scale=1.0 if norm is pm.FFTNorm.ORTHO else n,
                in_logical=self.input_shape,
                out_logical=self.output_shape,
                halved_axis=None if c2c else 2,
                halved_n=0 if c2c else self.ny)
        if not c2c:
            return GuardSpec(direction="inverse", check="finite", scale=1.0,
                             in_logical=self.output_shape,
                             out_logical=self.input_shape)
        scale = {pm.FFTNorm.NONE: n, pm.FFTNorm.BACKWARD: 1.0 / n,
                 pm.FFTNorm.ORTHO: 1.0}[norm]
        return GuardSpec(direction="inverse", check="parseval", scale=scale,
                         in_logical=self.output_shape,
                         out_logical=self.input_shape)

    def _wisdom_key_args(self) -> dict:
        return {"kind": "batched2d", "variant": self.shard,
                "transform": self.transform, "dims": 2}

    # -- builders ----------------------------------------------------------

    def _fft2(self, x, forward: bool):
        norm, be = self.config.norm, self.config.fft_backend
        st = self._mxu_st
        if forward:
            if self.transform == "c2c":
                c = lf.fft(x, axis=2, norm=norm, backend=be, settings=st)
            else:
                c = lf.rfft(x, axis=2, norm=norm, backend=be, settings=st)
            return lf.fft(c, axis=1, norm=norm, backend=be, settings=st)
        c = lf.ifft(x, axis=1, norm=norm, backend=be, settings=st)
        if self.transform == "c2c":
            return lf.ifft(c, axis=2, norm=norm, backend=be, settings=st)
        return lf.irfft(c, n=self.ny, axis=2, norm=norm, backend=be, settings=st)

    def _chunked(self, base):
        """Wrap a whole-(local-)batch transform in a sequential ``lax.map``
        over ``batch_chunk``-sized slices (see __init__)."""
        ck = self.batch_chunk
        if not ck:
            return base

        def fn(x):
            if x.shape[0] <= ck:
                return base(x)
            xs = x.reshape((x.shape[0] // ck, ck) + x.shape[1:])
            ys = jax.lax.map(base, xs)
            return ys.reshape((x.shape[0],) + ys.shape[2:])

        return fn

    def _build(self, forward: bool, guard: bool = True):
        with obs.span("plan.build", kind="batched2d", shard=self.shard,
                      direction="forward" if forward else "inverse"):
            from ..resilience import guards
            direction = "forward" if forward else "inverse"
            pure, in_spec, out_spec = self._build_pure(forward)
            guarded = False
            if guard:
                pure, guarded = guards.maybe_wrap(self, pure, direction,
                                                  dims=2)
            if self.mesh is None:
                return jax.jit(pure)
            outsh = NamedSharding(self.mesh, out_spec)
            if guarded:
                outsh = (outsh, NamedSharding(self.mesh, PartitionSpec()))
            return jax.jit(pure,
                           in_shardings=NamedSharding(self.mesh, in_spec),
                           out_shardings=outsh)

    def _build_pure(self, forward: bool):
        """(pure_fn, in_spec, out_spec) — the specs travel with the
        composition so the jit wrapper cannot drift from the shard_map."""
        if self.fft3d or self.shard == "batch":
            # Stage scope (obs/profile.py): the collective-free graph's
            # one local_fft node covers the whole per-plane 2D transform.
            fn = self._chunked(obs.profile.scoped(
                "batched2d", "local_fft:1",
                lambda x: self._fft2(x, forward)))
            if self.mesh is None:
                return fn, PartitionSpec(), PartitionSpec()
            return (jax.shard_map(fn, mesh=self.mesh, in_specs=self._in_spec,
                                  out_specs=self._out_spec),
                    self._in_spec, self._out_spec)
        return self._build_slab_pure(forward)

    def forward_fn(self):
        """Pure forward pipeline (``DistFFTPlan.forward_fn`` contract: no
        jit, no sharding annotations — composes under user grad/jit).
        Cached; pads logical-shaped input inside the trace."""
        if self._fwd_pure is None:
            self._fwd_pure = _with_pad(self._build_pure(True)[0],
                                       self.input_shape,
                                       self.input_padded_shape)
        return self._fwd_pure

    def inverse_fn(self):
        """Pure inverse pipeline (see ``forward_fn``)."""
        if self._inv_pure is None:
            self._inv_pure = _with_pad(self._build_pure(False)[0],
                                       self.output_shape,
                                       self.output_padded_shape)
        return self._inv_pure

    def _slab_parts(self, forward: bool):
        """(first, xpose, last) stage closures of the shard='x' pipeline —
        composed fused by ``_build_slab_pure``, jitted individually by
        ``forward_stages``/``inverse_stages`` for per-phase timing."""
        norm, be = self.config.norm, self.config.fft_backend
        st = self._mxu_st
        realigned = self.config.opt == 1
        wire = self.config.wire_dtype
        nys_pad, nx_pad = self._nys_pad, self._nx_pad
        nx, ny, nys = self.nx, self.ny, self._ny_spec
        complex_mode = self.transform == "c2c"

        if forward:
            def first(xl):  # (B, nxb, ny)
                if complex_mode:
                    c = lf.fft(xl, axis=2, norm=norm, backend=be, settings=st)
                else:
                    c = lf.rfft(xl, axis=2, norm=norm, backend=be, settings=st)
                return pad_axis_to(c, 2, nys_pad)

            def xpose(c):
                return all_to_all_transpose(c, SLAB_AXIS, 2, 1,
                                            realigned=realigned, wire=wire)

            def last(c):
                c = slice_axis_to(c, 1, nx)
                return lf.fft(c, axis=1, norm=norm, backend=be, settings=st)
        else:
            def first(cl):  # (B, nx, nysb)
                c = lf.ifft(cl, axis=1, norm=norm, backend=be, settings=st)
                return pad_axis_to(c, 1, nx_pad)

            def xpose(c):
                return all_to_all_transpose(c, SLAB_AXIS, 1, 2,
                                            realigned=realigned, wire=wire)

            def last(c):
                c = slice_axis_to(c, 2, nys)
                if complex_mode:
                    return lf.ifft(c, axis=2, norm=norm, backend=be,
                                   settings=st)
                return lf.irfft(c, n=ny, axis=2, norm=norm, backend=be,
                                settings=st)
        # Stage scopes (obs/profile.py): the shard='x' graph's nodes.
        sc = obs.profile.scoped
        return (sc("batched2d", "local_fft:1", first),
                sc("batched2d", "exchange:1", xpose),
                sc("batched2d", "local_fft:2", last))

    def _build_slab_pure(self, forward: bool):
        """shard='x': 1D FFT y -> transpose (x-split -> y-split) -> 1D FFT x,
        the 2D restriction of the slab ZY_Then_X pipeline.

        Comm-method mapping follows ``SlabFFTPlan._assemble_pure``: ALL2ALL
        is one shard_map with the explicit collective; PEER2PEER omits it —
        two shard_map stages whose boundary sharding change makes XLA's
        SPMD partitioner insert and schedule the collective. (Without this
        split the sweep's comm axis would compare two runs of the same
        program.)

        ``SendMethod.STREAMS`` chunks along the batch axis (the one axis
        the 2D transform and the transpose both leave untouched) into K
        independent exchange->FFT piece chains, exactly like the slab
        engine's pipelined rendering.

        ``SendMethod.RING`` / ``RING_OVERLAP`` render the exchange as the
        ``P-1``-step ``lax.ppermute`` ring (``ring_transpose``;
        RING_OVERLAP on the double-buffered schedule) — owning the
        rendering regardless of ``comm_method``, the slab contract. The
        post-transpose FFT runs along the gathered axis, so no per-block
        compute is pipelined; ``last`` runs on the assembled block (the
        fused wire therefore uses the unpack-only arrival kernel)."""
        first, xpose, last = self._slab_parts(forward)
        mesh = self.mesh
        if forward:
            in_spec, out_spec = self._in_spec, self._out_spec
        else:
            in_spec, out_spec = self._out_spec, self._in_spec
        wire = self.config.wire_dtype
        if self.config.send_method.is_ring:
            split, concat = (2, 1) if forward else (1, 2)
            overlap = self.config.send_method is pm.SendMethod.RING_OVERLAP
            depth = self.config.resolved_overlap_depth()
            subblocks = self.config.resolved_overlap_subblocks()
            from ..ops import pallas_fft as plf
            enc_fn, arr_fn = plf.fused_ring_hooks(self.config)

            def rbody(v):
                with obs.profile.stage_scope("batched2d", "exchange:1"):
                    y = ring_transpose(first(v), SLAB_AXIS, split,
                                       concat, wire=wire,
                                       overlap=overlap, depth=depth,
                                       subblocks=subblocks,
                                       encode_fn=enc_fn,
                                       arrive_fn=arr_fn)
                return last(y)

            return (jax.shard_map(rbody, mesh=mesh, in_specs=in_spec,
                                  out_specs=out_spec),
                    in_spec, out_spec)
        streams = self.config.send_method is pm.SendMethod.STREAMS
        k = self.config.resolved_streams_chunks()
        if self.config.comm_method is pm.CommMethod.ALL2ALL:
            if streams:
                def body(v):
                    c = first(v)
                    return concat_axis_chunks(
                        [last(xpose(p))
                         for p in split_axis_chunks(c, 0, k)], 0)
            elif self.config.resolved_overlap_subblocks() > 1:
                # a2a_pipe: the software-pipelined monolithic exchange,
                # chunked along the untouched batch axis (chunk k+1's
                # collective issued while chunk k decodes).
                split, concat = (2, 1) if forward else (1, 2)
                realigned = self.config.opt == 1
                pk = self.config.resolved_overlap_subblocks()
                depth = self.config.resolved_overlap_depth()

                def body(v):
                    with obs.profile.stage_scope("batched2d", "exchange:1"):
                        y = pipelined_all_to_all(
                            first(v), SLAB_AXIS, split, concat,
                            chunk_axis=0, chunks=pk, depth=depth,
                            realigned=realigned, wire=wire)
                    return last(y)
            else:
                def body(v):
                    return last(xpose(first(v)))
            return (jax.shard_map(body, mesh=mesh, in_specs=in_spec,
                                  out_specs=out_spec),
                    in_spec, out_spec)
        # PEER2PEER wire layer (wire_gspmd_stages, the slab contract): a
        # compressed wire makes stage1 emit the planar bf16 encoding and
        # stage2 decode it, so the GSPMD boundary collective moves half
        # the bytes; "native" is the unchanged pre-wire stage pair. The
        # STREAMS batch chunk axis (0) shifts past the plane axis.
        stage1, stage2, bspec, shift = wire_gspmd_stages(
            mesh, first, last, in_spec, out_spec, wire,
            self.config.double_prec)
        if streams:
            boundary = NamedSharding(mesh, bspec)
            ca = shift

            def pure(v):
                with obs.profile.stage_scope("batched2d", "exchange:1"):
                    y = chunked_reshard(stage1(v), boundary, ca, k)
                return stage2(y)

            return pure, in_spec, out_spec
        return (lambda v: stage2(stage1(v)), in_spec, out_spec)

    # -- per-phase staged execution (benchmark timer support; same hooks
    #    as the 3D engines so testcases/Timer/eval reach this plan) -------

    @property
    def global_size(self) -> pm.GlobalSize:
        """(batch, nx, ny) mapped onto the 3-slot size schema of the Timer
        CSV filenames and testcases. The halved spectral axis is ny (the
        last slot), so ``nz_out`` equals ``self._ny_spec`` for r2c — the
        batched plan is structurally the 3D schema with batch riding the
        first slot and no transform along it."""
        return pm.GlobalSize(self.batch, self.nx, self.ny)

    @property
    def variant_name(self) -> str:
        """Chunked runs get their own variant directory: the reference
        filename schema has no chunk slot, and mixing chunked/unchunked
        blocks in one CSV would read as iterations of one config."""
        base = f"batched2d_{self.shard}"
        return f"{base}_ck{self.batch_chunk}" if self.batch_chunk else base

    @property
    def section_descriptions(self):
        """Phase vocabulary: the slab transpose marker set for shard='x'
        (same CSV columns the eval layer already reduces), one fused-2D
        marker for the collective-free batch sharding."""
        if self.fft3d or self.shard == "batch":
            return ["init", "2D FFT X-Y-Direction", "Run complete",
                    "Run complete (fused)"]
        xpose = ["Transpose (First Send)", "Transpose (Packing)",
                 "Transpose (Start Local Transpose)",
                 "Transpose (Start Receive)", "Transpose (First Receive)",
                 "Transpose (Finished Receive)", "Transpose (Start All2All)",
                 "Transpose (Finished All2All)", "Transpose (Unpacking)"]
        return ["init", "1D FFT Y-Direction"] + xpose + [
            "1D FFT X-Direction", "Run complete", "Run complete (fused)"]

    def _xpose_desc(self) -> str:
        return ("Transpose (Finished All2All)"
                if self.config.comm_method is pm.CommMethod.ALL2ALL
                else "Transpose (Finished Receive)")

    def _jit_stages(self, specs):
        return jit_stages(self.mesh, specs)

    def forward_stages(self):
        """[(phase desc, jitted stage fn)] for per-phase timed execution
        (slab contract). Batch sharding has no collective, so its staged
        path IS the fused program under one descriptive marker — built
        UNGUARDED when guards are on (the staged loop threads raw arrays
        between phases; the guard tuple belongs to the exec envelope)."""
        if self.fft3d or self.shard == "batch":
            if self._guard_mode != "off":
                if self._fwd_unguarded is None:
                    self._fwd_unguarded = self._build(forward=True,
                                                      guard=False)
                return [("2D FFT X-Y-Direction", self._fwd_unguarded)]
            if self._fwd is None:
                self._fwd = self._build(forward=True)
            return [("2D FFT X-Y-Direction", self._fwd)]
        first, xpose, last = self._slab_parts(True)
        return self._jit_stages(
            [("1D FFT Y-Direction", first, self._in_spec, self._in_spec),
             (self._xpose_desc(), xpose, self._in_spec, self._out_spec),
             ("1D FFT X-Direction", last, self._out_spec, self._out_spec)])

    def inverse_stages(self):
        if self.fft3d or self.shard == "batch":
            if self._guard_mode != "off":
                if self._inv_unguarded is None:
                    self._inv_unguarded = self._build(forward=False,
                                                      guard=False)
                return [("2D FFT X-Y-Direction", self._inv_unguarded)]
            if self._inv is None:
                self._inv = self._build(forward=False)
            return [("2D FFT X-Y-Direction", self._inv)]
        first, xpose, last = self._slab_parts(False)
        return self._jit_stages(
            [("1D FFT X-Direction", first, self._out_spec, self._out_spec),
             (self._xpose_desc(), xpose, self._out_spec, self._in_spec),
             ("1D FFT Y-Direction", last, self._in_spec, self._in_spec)])

# ---------------------------------------------------------------------------
# contract declaration (analysis/contracts.py) — the exchange this family
# stages, next to the code that stages it.
# ---------------------------------------------------------------------------

def _contract_exchanges(plan, direction, dims=2):
    """Batched-2D: ``shard="x"`` stages one exchange (scatter spectral y,
    gather x; STREAMS chunks along the untouched batch axis);
    ``shard="batch"`` and the single-device fallback are collective-free
    by construction."""
    del dims
    if plan.fft3d or plan.shard == "batch":
        return ()
    from ..analysis import contracts as _c
    cfg = plan.config
    rendering = _c.rendering_name(cfg)
    chunks = 1
    subblocks = 1
    if rendering == "streams":
        chunks = min(cfg.resolved_streams_chunks(), plan._batch_pad)
    elif rendering == "a2a_pipe":
        chunks = ring_subblocks(plan._batch_pad,
                                cfg.resolved_overlap_subblocks())
    elif rendering in ("ring", "ring_overlap"):
        # The sub-block split slices arriving blocks along the concat
        # axis: forward gathers x (local extent nx_pad/P), inverse
        # gathers spectral y (nys_pad/P).
        p = plan.partition.num_ranks
        ext = (plan._nx_pad // p if direction == "forward"
               else plan._nys_pad // p)
        subblocks = ring_subblocks(ext, cfg.resolved_overlap_subblocks())
    return (_c.ExchangeDecl(
        "transpose", (plan._batch_pad, plan._nx_pad, plan._nys_pad),
        plan.partition.num_ranks, rendering, chunks,
        subblocks=subblocks),)


def _declare_graph(plan, direction, dims=2):
    """Batched-2D stage graph (analysis/plangraph.py): ``shard="x"`` is
    the 2D slab restriction — per-plane y FFT -> exchange -> per-plane
    x FFT (encode/decode under a compressed wire; the fused wire uses
    the unpack-only arrival — the post-transpose FFT runs along the
    gathered axis); ``shard="batch"`` and the single-device fallback are
    one collective-free fused 2D FFT node. Guard at check/enforce."""
    del dims
    from ..analysis import plangraph as _pg
    cfg = plan.config
    cdt, rdt = _pg.payload_dtypes(cfg, plan.transform)
    fwd = direction == "forward"
    b = _pg.GraphBuilder("batched2d", direction, wire=cfg.wire_dtype,
                         guards=plan._guard_mode, complex_dtype=cdt)
    in_shape = plan.input_padded_shape if fwd else plan.output_padded_shape
    out_shape = plan.output_padded_shape if fwd else plan.input_padded_shape
    in_dtype, out_dtype = (rdt, cdt) if fwd else (cdt, rdt)
    in_spec = plan._in_spec if fwd else plan._out_spec
    out_spec = plan._out_spec if fwd else plan._in_spec
    b.node("input")
    b.payload(in_shape, in_dtype, in_spec)
    if plan.fft3d or plan.shard == "batch":
        b.node("local_fft", axes=(2, 1) if fwd else (1, 2),
               label="2D FFT per plane")
        b.payload(out_shape, out_dtype, out_spec)
    else:
        (decl,) = _contract_exchanges(plan, direction)
        b.node("local_fft", axes=(2,) if fwd else (1,), label="stage 1")
        depth = _pg.shipped_schedule_depth(decl.rendering, cfg)
        fused = cfg.fused_wire_active()
        b.exchange(decl.label, decl.payload_shape, decl.axis_size,
                   decl.rendering, chunks=decl.chunks,
                   subblocks=decl.subblocks,
                   schedule_depth=depth, decoded_spec=out_spec,
                   fused_encode=fused,
                   decode_fuses=("decode",) if fused else None)
        b.node("local_fft", axes=(1,) if fwd else (2,), label="stage 2")
        b.payload(out_shape, out_dtype, out_spec)
    if plan._guard_mode != "off":
        b.node("guard")
    b.node("output")
    return b.graph()


def _register_contracts():
    from ..analysis import contracts as _c
    from ..analysis import plangraph as _pg
    _c.register_family("batched2d", "Batched2DFFTPlan", _contract_exchanges)
    _pg.register_graph_family("batched2d", _declare_graph)


_register_contracts()
