"""Slab (1D) decomposition engine — all three per-axis FFT sequences.

TPU-native re-design of the reference's slab family:

* ``ZY_Then_X`` (default): 2D FFT (y,z) -> transpose (x-split -> y-split) ->
  1D FFT x (``src/slab/default/mpicufft_slab.cpp``).
* ``Z_Then_YX``: 1D FFT z -> transpose (x-split -> z-split) -> 2D FFT (y,x);
  output distributed over the halved z axis
  (``src/slab/z_then_yx/mpicufft_slab_z_then_yx.cpp:96-104``).
* ``Y_Then_ZX``: 1D R2C y -> transpose (x-split -> y-split) -> 2D FFT (z,x);
  the halved axis is y (``src/slab/y_then_zx/mpicufft_slab_y_then_zx.cpp:95-103``).
  The reference implements this sequence forward-only; here the inverse comes
  for free from the shared pipeline builder and is provided as an extension.

Where the reference implements seven classes x a 2x3 comm/send matrix of
hand-scheduled pack/exchange/unpack variants, this engine expresses each
sequence as ONE jitted XLA program parameterized by axis roles, with two
communication strategies preserved for the reference's comparative spirit:

* ``CommMethod.ALL2ALL``  -> explicit ``shard_map`` + ``lax.all_to_all``.
* ``CommMethod.PEER2PEER`` -> GSPMD: global-view ops + sharding constraints;
  XLA chooses/schedules the collectives (its latency-hiding scheduler is the
  analog of the reference's Isend/Irecv + callback-thread overlap engine).

``config.opt == 1`` maps to the "realigned" layout (sender-contiguous
relayout before the collective), the analog of the reference's Opt1
coordinate-transform classes (``include/mpicufft_slab_opt1.hpp:46-54``).

Padded-shape contract
---------------------
XLA device meshes want extents divisible by the mesh axis, so every
*decomposed* axis of a distributed global array is zero-padded up to the next
multiple of P (``padded_extent``); undecomposed axes — including an odd
``N/2+1`` halved axis that stays local — are never padded. Where the
reference handles uneven extents with per-peer byte counts
(``src/slab/default/mpicufft_slab.cpp:217-228``), this engine pads:

* plan input  : real, ``input_padded_shape``  (x padded), sharded over x;
* plan output : complex, ``output_padded_shape`` (split axis padded),
  sharded over the split axis; pad lanes are exact zeros in forward output
  and are ignored by the inverse.

``pad_input`` / ``crop_real`` / ``pad_spectral`` / ``crop_spectral`` convert
between logical and padded forms. For mesh-divisible sizes (every benchmark
config) padded == logical and all of this is a no-op.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .. import obs
from .. import params as pm
from ..ops import fft as lf
from ..parallel.mesh import SLAB_AXIS, make_slab_mesh
from ..parallel.transpose import (all_to_all_transpose, chunked_reshard,
                                  concat_axis_chunks, pad_axis_to,
                                  pipelined_all_to_all, ring_subblocks,
                                  ring_transpose, slice_axis_to,
                                  split_axis_chunks, wire_gspmd_stages)
from ..utils import wisdom
from .base import DistFFTPlan, _with_pad, notice_axis_smoothness


@dataclasses.dataclass(frozen=True)
class _SeqDef:
    """Axis roles for one slab sequence."""

    r2c_axis: int                 # axis of the real-to-complex transform
    pre_axes: Tuple[int, ...]     # C2C axes before the transpose
    split_axis: int               # axis scattered by the transpose
    post_axes: Tuple[int, ...]    # C2C axes after the transpose

    @property
    def halved(self) -> str:
        """The logical axis carrying the n//2+1 halving (the R2C axis)."""
        return "xyz"[self.r2c_axis]


_SEQS = {
    pm.SlabSequence.ZY_THEN_X: _SeqDef(2, (1,), 1, (0,)),
    pm.SlabSequence.Z_THEN_YX: _SeqDef(2, (), 2, (1, 0)),
    pm.SlabSequence.Y_THEN_ZX: _SeqDef(1, (), 1, (2, 0)),
}


class SlabFFTPlan(DistFFTPlan):
    """Distributed 3D R2C/C2R FFT with 1D (slab) decomposition over x."""

    def __init__(self, global_size: pm.GlobalSize, partition: pm.SlabPartition,
                 config: Optional[pm.Config] = None, mesh: Optional[Mesh] = None,
                 sequence: "pm.SlabSequence | str" = pm.SlabSequence.ZY_THEN_X,
                 transform: str = "r2c"):
        # Measurement-resolved Config fields (fft_backend="auto" /
        # comm_method="auto") are settled HERE, before anything reads the
        # config: wisdom hit -> reuse, miss -> bounded race-and-record
        # (utils/wisdom.py). Concrete configs pass through untouched.
        config = wisdom.resolve_config("slab", global_size, partition,
                                       config, mesh=mesh, sequence=sequence,
                                       transform=transform)
        if mesh is None and partition.p > 1:
            mesh = make_slab_mesh(partition.p)
        if mesh is not None and partition.p > 1:
            if SLAB_AXIS not in mesh.shape:
                raise ValueError(
                    f"slab mesh must have a {SLAB_AXIS!r} axis, got {mesh.axis_names}")
            if mesh.shape[SLAB_AXIS] != partition.p:
                raise ValueError(
                    f"mesh axis {SLAB_AXIS!r} has {mesh.shape[SLAB_AXIS]} devices "
                    f"but the partition asks for {partition.p}")
        super().__init__(global_size, partition, config, mesh)
        if transform not in ("r2c", "c2c"):
            raise ValueError(f"transform must be 'r2c' or 'c2c', got {transform!r}")
        self.transform = transform
        self.sequence = pm.SlabSequence.parse(sequence)
        self._seq = _SEQS[self.sequence]
        g, P = global_size, partition.p
        self._P = P
        if transform == "c2c":
            # No halved axis: complex-to-complex keeps the full extents
            # (BASELINE configs #1/#2 are 3D C2C transforms; the reference
            # core is R2C/C2R-only, so this is an extension).
            self._spec_shape = g.shape
        elif self._seq.halved == "z":
            self._spec_shape = (g.nx, g.ny, g.nz_out)
        else:
            self._spec_shape = (g.nx, g.ny_out, g.nz)
        self._split_ext = self._spec_shape[self._seq.split_axis]
        if self.fft3d:
            self._nx_pad = g.nx
            self._split_pad = self._split_ext
        else:
            self._nx_pad = pm.padded_extent(g.nx, P)
            self._split_pad = pm.padded_extent(self._split_ext, P)
            self._in_spec = PartitionSpec(SLAB_AXIS, None, None)
            out = [None, None, None]
            out[self._seq.split_axis] = SLAB_AXIS
            self._out_spec = PartitionSpec(*out)
        notice_axis_smoothness("slab", g.shape, self.config)
        obs.event("plan.created", kind="slab", sequence=self.sequence.value,
                  transform=transform, shape=list(g.shape), ranks=P,
                  comm=self.config.comm_method.value,
                  send=self.config.send_method.value, opt=self.config.opt,
                  wire=self.config.wire_dtype,
                  backend=self.config.fft_backend)

    # -- shapes & size tables (reference getInSize/getOutSize family,
    #    include/mpicufft.hpp:66-79) --------------------------------------

    @property
    def output_shape(self) -> Tuple[int, int, int]:
        return self._spec_shape

    @property
    def input_padded_shape(self) -> Tuple[int, int, int]:
        g = self.global_size
        return (self._nx_pad, g.ny, g.nz)

    @property
    def output_padded_shape(self) -> Tuple[int, int, int]:
        s = list(self._spec_shape)
        s[self._seq.split_axis] = self._split_pad
        return tuple(s)

    def in_sizes(self, axis: str = "x") -> List[int]:
        if axis != "x":
            raise ValueError("slab input is decomposed over x only")
        return pm.even_shard_sizes(self.global_size.nx, self._nx_pad, self._P)

    def out_sizes(self, axis: Optional[str] = None) -> List[int]:
        """Per-rank extents of the decomposed output axis (y for ZY_Then_X /
        Y_Then_ZX, z for Z_Then_YX) — logical extents, excluding pad lanes."""
        expected = "xyz"[self._seq.split_axis]
        if axis is not None and axis != expected:
            raise ValueError(
                f"{self.sequence.value} output is decomposed over {expected}")
        return pm.even_shard_sizes(self._split_ext, self._split_pad, self._P)

    # -- logical <-> padded conversion helpers ----------------------------

    def pad_input(self, x):
        """Logical real input -> padded, device-placed input shard layout.
        Stays on device for jax arrays (no host round-trip)."""
        pad = self._nx_pad - self.global_size.nx
        if pad:
            x = jnp.pad(x, [(0, pad), (0, 0), (0, 0)])
        if self.mesh is not None:
            x = jax.device_put(x, self.input_sharding)
        return x

    def crop_real(self, r):
        """Padded inverse output -> logical (nx, ny, nz) host array."""
        return np.asarray(r)[: self.global_size.nx]

    def pad_spectral(self, c):
        pad = self._split_pad - self._split_ext
        if pad:
            widths = [(0, 0)] * 3
            widths[self._seq.split_axis] = (0, pad)
            c = jnp.pad(c, widths)
        if self.mesh is not None:
            c = jax.device_put(c, self.output_sharding)
        return c

    def crop_spectral(self, c):
        """Padded forward output -> logical spectral host array."""
        c = np.asarray(c)
        sl = [slice(None)] * 3
        sl[self._seq.split_axis] = slice(0, self._split_ext)
        return c[tuple(sl)]

    # -- execution (thin guarded wrappers over shared impl) ----------------

    def exec_r2c(self, x):
        if self.transform != "r2c":
            raise TypeError("this plan was built with transform='c2c'; "
                            "use exec_c2c/exec_c2c_inv")
        return self._exec_fwd(x)

    def exec_c2r(self, c):
        if self.transform != "r2c":
            raise TypeError("this plan was built with transform='c2c'; "
                            "use exec_c2c/exec_c2c_inv")
        return self._exec_inv(c)

    def exec_c2c(self, x):
        """Forward 3D C2C transform (transform='c2c' plans). Same pipeline
        as R2C with the first-axis transform complex."""
        if self.transform != "c2c":
            raise TypeError("this plan was built with transform='r2c'; "
                            "use exec_r2c/exec_c2r")
        return self._exec_fwd(x)

    def exec_c2c_inv(self, c):
        """Inverse 3D C2C transform (transform='c2c' plans)."""
        if self.transform != "c2c":
            raise TypeError("this plan was built with transform='r2c'; "
                            "use exec_r2c/exec_c2r")
        return self._exec_inv(c)

    def _exec_fwd(self, x):
        if tuple(x.shape) not in (self.input_shape, self.input_padded_shape):
            raise ValueError(
                f"forward exec expects global shape {self.input_shape} (or "
                f"padded {self.input_padded_shape}), got {tuple(x.shape)}")
        if not self.fft3d and tuple(x.shape) == self.input_shape \
                and self.input_shape != self.input_padded_shape:
            x = self.pad_input(x)
        from ..resilience import fallback
        return fallback.execute(self, "forward", x, self._get_r2c)

    def _exec_inv(self, c):
        if tuple(c.shape) not in (self.output_shape, self.output_padded_shape):
            raise ValueError(
                f"inverse exec expects global shape {self.output_shape} (or "
                f"padded {self.output_padded_shape}), got {tuple(c.shape)}")
        if not self.fft3d and tuple(c.shape) == self.output_shape \
                and self.output_shape != self.output_padded_shape:
            c = self.pad_spectral(c)
        from ..resilience import fallback
        return fallback.execute(self, "inverse", c, self._get_c2r)

    def _halved_axis_index(self) -> int:
        """Solver-protocol hook: the sequence's R2C axis carries the
        halving (y for Y_Then_ZX, z otherwise)."""
        return self._seq.r2c_axis

    # -- resilience hooks (guards + fallback ladder) -----------------------

    def _guard_spec(self, direction: str, dims: int = 3):
        """GuardSpec of the slab pipelines (``resilience/guards.py``):
        forward = Parseval with the sequence's R2C axis weighted (plain
        for c2c); inverse = Parseval for c2c (exact for any input),
        finiteness for C2R (arbitrary spectral input is not conjugate-
        symmetric — the transform projects it, so energy is not an
        invariant of that direction)."""
        from ..resilience.guards import GuardSpec
        g, norm = self.global_size, self.config.norm
        n = float(g.n_total)
        c2c = self.transform == "c2c"
        if direction == "forward":
            return GuardSpec(
                direction="forward", check="parseval",
                scale=1.0 if norm is pm.FFTNorm.ORTHO else n,
                in_logical=self.input_shape,
                out_logical=self._spec_shape,
                halved_axis=None if c2c else self._seq.r2c_axis,
                halved_n=0 if c2c else (g.nz if self._seq.halved == "z"
                                        else g.ny))
        if not c2c:
            return GuardSpec(direction="inverse", check="finite", scale=1.0,
                             in_logical=self.output_shape,
                             out_logical=self.input_shape)
        scale = {pm.FFTNorm.NONE: n, pm.FFTNorm.BACKWARD: 1.0 / n,
                 pm.FFTNorm.ORTHO: 1.0}[norm]
        return GuardSpec(direction="inverse", check="parseval", scale=scale,
                         in_logical=self.output_shape,
                         out_logical=self.input_shape)

    def _wisdom_key_args(self) -> dict:
        return {"kind": "slab", "sequence": self.sequence,
                "transform": self.transform, "dims": 3}

    # -- pipeline bodies ---------------------------------------------------
    # Three reusable local bodies per direction. The fused builders compose
    # them into one program; the GSPMD path drops the explicit transpose and
    # lets the stage boundary trigger the collective; forward_stages()/
    # inverse_stages() jit them individually for per-phase timing.

    def _streams_chunk_axis(self) -> int:
        """The axis the STREAMS pipelined transpose chunks along: the one
        axis involved in neither side of the exchange (slab transposes move
        ``split_axis`` <-> 0, leaving exactly one of {1, 2} free)."""
        return next(a for a in (1, 2) if a != self._seq.split_axis)

    def _a2a_pipe_chunks(self) -> int:
        """Resolved chunk count of the software-pipelined monolithic
        all-to-all (rendering ``a2a_pipe``: ALL2ALL + SYNC/MPI_TYPE with
        ``Config.overlap_subblocks`` > 1), clamped to the free-axis
        extent; 1 whenever another rendering owns the exchange."""
        cfg = self.config
        if (self.fft3d
                or cfg.comm_method is not pm.CommMethod.ALL2ALL
                or cfg.send_method not in (pm.SendMethod.SYNC,
                                           pm.SendMethod.MPI_TYPE)):
            return 1
        ca = self._streams_chunk_axis()
        return ring_subblocks(self.output_padded_shape[ca],
                              cfg.resolved_overlap_subblocks())

    def _xpose_bodies(self, realigned=None, chunks: Optional[int] = None,
                      wire: Optional[str] = None):
        """The pipeline's own transpose bodies ``(forward, inverse)`` for a
        given layout rendering (``realigned=None`` -> this plan's
        ``config.opt``). Single source of truth for what the slab exchange
        does — the fraction-gate microbench times exactly these, so the gate
        cannot drift from the shipped pipeline.

        ``chunks`` > 1 renders each transpose as that many independent
        per-piece collectives along the free axis (the exchange half of the
        STREAMS engine, without the interleaved FFTs — what the fraction
        chain races to see whether chunked exchanges alone pay or win).

        ``wire`` overrides this plan's wire encoding (``None`` -> the
        resolved ``config.wire_dtype``) — the bench layer's wire rows time
        exactly these bodies at each encoding."""
        if realigned is None:
            realigned = self.config.opt == 1
        if wire is None:
            wire = self.config.wire_dtype
        sa = self._seq.split_axis
        ca = self._streams_chunk_axis()

        def one(cl, split, concat):
            # Stage scope (obs/profile.py): the whole monolithic exchange
            # group — encode/collective/decode — attributes to the graph's
            # exchange:1 node (the wire layer nests its own sub-scopes).
            with obs.profile.stage_scope("slab", "exchange:1"):
                return all_to_all_transpose(cl, SLAB_AXIS, split, concat,
                                            realigned=realigned, wire=wire)

        if chunks is None and self._a2a_pipe_chunks() > 1:
            # ALL2ALL + SYNC/MPI_TYPE with a sub-block split: the
            # software-pipelined monolithic exchange (chunk k+1's
            # collective issued while chunk k decodes) along the one
            # free axis — opt0/opt1 overlap without switching to ring.
            pk = self._a2a_pipe_chunks()
            depth = self.config.resolved_overlap_depth()

            def piped(cl, split, concat):
                with obs.profile.stage_scope("slab", "exchange:1"):
                    return pipelined_all_to_all(
                        cl, SLAB_AXIS, split, concat, chunk_axis=ca,
                        chunks=pk, depth=depth, realigned=realigned,
                        wire=wire)

            return (lambda cl: piped(cl, sa, 0)), (lambda cl: piped(cl, 0, sa))

        if chunks is None or chunks <= 1:
            return (lambda cl: one(cl, sa, 0)), (lambda cl: one(cl, 0, sa))

        def chunked(cl, split, concat):
            return concat_axis_chunks(
                [one(p, split, concat)
                 for p in split_axis_chunks(cl, ca, chunks)], ca)

        return (lambda cl: chunked(cl, sa, 0)), (lambda cl: chunked(cl, 0, sa))

    def _fwd_parts(self):
        s, norm, g = self._seq, self.config.norm, self.global_size
        realigned = self.config.opt == 1
        be = self.config.fft_backend
        st = self._mxu_st
        split_pad, nx = self._split_pad, g.nx

        complex_mode = self.transform == "c2c"

        def first(xl):
            if complex_mode:
                c = lf.fft(xl, axis=s.r2c_axis, norm=norm, backend=be, settings=st)
            else:
                c = lf.rfft(xl, axis=s.r2c_axis, norm=norm, backend=be, settings=st)
            for a in s.pre_axes:
                c = lf.fft(c, axis=a, norm=norm, backend=be, settings=st)
            return pad_axis_to(c, s.split_axis, split_pad)

        xpose = self._xpose_bodies(realigned)[0]

        def last(cl):
            # Drop the zero pad rows of x before transforming along it.
            c = slice_axis_to(cl, 0, nx)
            for a in s.post_axes:
                c = lf.fft(c, axis=a, norm=norm, backend=be, settings=st)
            return c

        # Stage scopes: the graph's local_fft:1 / local_fft:2 nodes
        # (metadata only — obs/profile.py attribution).
        return (obs.profile.scoped("slab", "local_fft:1", first), xpose,
                obs.profile.scoped("slab", "local_fft:2", last))

    def _inv_parts(self):
        s, norm, g = self._seq, self.config.norm, self.global_size
        realigned = self.config.opt == 1
        be = self.config.fft_backend
        st = self._mxu_st
        nx_pad, split_ext = self._nx_pad, self._split_ext
        real_n = g.nz if s.halved == "z" else g.ny
        complex_mode = self.transform == "c2c"

        def first(cl):
            c = cl
            for a in reversed(s.post_axes):
                c = lf.ifft(c, axis=a, norm=norm, backend=be, settings=st)
            return pad_axis_to(c, 0, nx_pad)

        xpose = self._xpose_bodies(realigned)[1]

        def last(cl):
            # Drop the pad lanes of the split axis before inverting along the
            # remaining axes.
            c = slice_axis_to(cl, s.split_axis, split_ext)
            for a in reversed(s.pre_axes):
                c = lf.ifft(c, axis=a, norm=norm, backend=be, settings=st)
            if complex_mode:
                return lf.ifft(c, axis=s.r2c_axis, norm=norm, backend=be, settings=st)
            return lf.irfft(c, n=real_n, axis=s.r2c_axis, norm=norm,
                            backend=be, settings=st)

        # Inverse graph numbering: its stage 1 (post-axis inverses) is
        # local_fft:1, its stage 2 (pre-axis + r2c inverses) local_fft:2.
        return (obs.profile.scoped("slab", "local_fft:1", first), xpose,
                obs.profile.scoped("slab", "local_fft:2", last))

    # -- STREAMS (chunked / software-pipelined) bodies ---------------------
    # The TPU rendering of the reference's Streams send engine (per-peer
    # packs on CUDA streams + callback thread + MPI_Isend,
    # src/slab/default/mpicufft_slab.cpp:343-448): split the local block
    # into K pieces along the one axis the exchange leaves free, and give
    # each piece its own transpose -> FFT chain. The K chains share no
    # data, so the scheduler may run piece i's collective concurrently
    # with piece i-1's FFT (async all-to-all-start/done pairs on TPU).
    # FFTs along the chunk axis itself cannot be chunked and run once on
    # the re-assembled block; separable DFT axes commute, so hoisting them
    # across the per-chunk transforms preserves the result exactly.

    def _streams_split(self):
        """(chunk_axis, chunks, per-chunk post axes, after-concat post
        axes) — the static plan of the STREAMS pipeline."""
        ca = self._streams_chunk_axis()
        k = self.config.resolved_streams_chunks()
        per_chunk = tuple(a for a in self._seq.post_axes if a != ca)
        after = tuple(a for a in self._seq.post_axes if a == ca)
        return ca, k, per_chunk, after

    def _streams_fwd_body(self):
        """Local forward body for ALL2ALL + STREAMS: first-stage FFTs, then
        K independent (transpose -> post-FFT) piece chains."""
        norm, g = self.config.norm, self.global_size
        be, st = self.config.fft_backend, self._mxu_st
        ca, k, per_chunk, after = self._streams_split()
        first = self._fwd_parts()[0]
        xpose = self._xpose_bodies()[0]
        nx = g.nx

        def body(xl):
            c = first(xl)
            outs = []
            for piece in split_axis_chunks(c, ca, k):
                y = xpose(piece)
                with obs.profile.stage_scope("slab", "local_fft:2"):
                    y = slice_axis_to(y, 0, nx)
                    for a in per_chunk:
                        y = lf.fft(y, axis=a, norm=norm, backend=be,
                                   settings=st)
                outs.append(y)
            with obs.profile.stage_scope("slab", "local_fft:2"):
                c = concat_axis_chunks(outs, ca)
                for a in after:
                    c = lf.fft(c, axis=a, norm=norm, backend=be, settings=st)
            return c

        return body

    def _streams_inv_body(self):
        """Local inverse body for ALL2ALL + STREAMS: mirror of
        ``_streams_fwd_body`` (chunk-axis inverse FFT first, then K
        independent (inverse-FFT -> transpose-back) piece chains, then the
        shared last stage)."""
        norm = self.config.norm
        be, st = self.config.fft_backend, self._mxu_st
        ca, k, per_chunk, after = self._streams_split()
        xpose_inv = self._xpose_bodies()[1]
        last = self._inv_parts()[2]
        nx_pad = self._nx_pad

        def body(cl):
            c = cl
            with obs.profile.stage_scope("slab", "local_fft:1"):
                for a in after:
                    c = lf.ifft(c, axis=a, norm=norm, backend=be,
                                settings=st)
            outs = []
            for piece in split_axis_chunks(c, ca, k):
                with obs.profile.stage_scope("slab", "local_fft:1"):
                    y = piece
                    for a in reversed(per_chunk):
                        y = lf.ifft(y, axis=a, norm=norm, backend=be,
                                    settings=st)
                    y = pad_axis_to(y, 0, nx_pad)
                outs.append(xpose_inv(y))
            return last(concat_axis_chunks(outs, ca))

        return body

    # -- RING / RING_OVERLAP (ppermute-pipelined) bodies -------------------
    # SendMethod.RING decomposes each transpose into P-1 DISTINCT
    # ``lax.ppermute`` steps (``parallel/transpose.ring_transpose``) and
    # runs the post-transpose FFT stages that do not touch the gathered
    # axis on each peer block AS IT ARRIVES — receiver-side pipelining,
    # the TPU analog of the reference Streams engine's per-peer
    # MPI_Isend/compute interleave. Unlike the STREAMS chunked collectives
    # (which GSPMD re-fuses — OVERLAP.md), the P-1 permutes carry
    # different data and cannot be merged, so the scheduler can genuinely
    # hide step t+1's wire time behind block t's FFT. The gathered-axis
    # FFT (always axis 0 on the slab forward) needs the assembled block
    # and runs after the ring drains, as does the shape-changing C2R
    # half-axis inverse.
    #
    # SendMethod.RING_OVERLAP runs the SAME per-block math on the
    # double-buffered schedule (ring_transpose(overlap=True): step t+1's
    # permute issued before block t's FFT — bit-identical output,
    # reordered issue), and Config.fused_wire swaps the per-block wire
    # boundary for the fused Pallas kernels (_ring_hooks below).

    def _ring_overlap(self, second: bool = False) -> bool:
        snd = (self.config.resolved_snd2() if second
               else self.config.send_method)
        return snd is pm.SendMethod.RING_OVERLAP

    def _ring_hooks(self, pipe_axes, inverse: bool = False):
        """``(encode_fn, arrive_fn, pipe)`` for a ring exchange whose
        arriving blocks run per-block FFTs over ``pipe_axes``: under the
        fused wire (``Config.fused_wire_active``) the encode is the
        one-pass Pallas pack and the arrival fuses the decode with the
        FIRST pipelined DFT stage (remaining axes run the plain pipe);
        otherwise ``(None, None, pipe)`` keeps the plain wire layer. The
        returned ``pipe`` is always the FULL per-block pipeline — the
        local block never touches the wire, so ring_transpose applies it
        unfused regardless."""
        pipe = self._ring_pipe(pipe_axes, inverse=inverse)
        if not self.config.fused_wire_active():
            return None, None, pipe
        from ..ops import pallas_fft as plf
        if not pipe_axes:
            # No pipelined per-block FFT: the shared unpack-only hooks
            # (the pencil/batched2d arrival).
            enc_fn, arr_fn = plf.fused_ring_hooks(self.config)
            return enc_fn, arr_fn, pipe
        from ..parallel.transpose import wire_complex_dtype
        cdt = wire_complex_dtype(self.config.double_prec)
        norm, st = self.config.norm, self._mxu_st
        first_ax, rest = pipe_axes[0], tuple(pipe_axes[1:])
        rest_pipe = self._ring_pipe(rest, inverse=inverse)

        def arrive(b):
            b = plf.decode_fft_fused(b, cdt, first_ax, inverse=inverse,
                                     norm=norm, settings=st)
            return rest_pipe(b) if rest_pipe is not None else b

        return plf.wire_encode_fused, arrive, pipe

    def _ring_pipe(self, axes, inverse: bool = False):
        """Shape-preserving per-block FFT pipeline over ``axes`` (None when
        empty — ring_transpose then skips the per-block stage)."""
        if not axes:
            return None
        norm, be, st = self.config.norm, self.config.fft_backend, self._mxu_st
        tf = lf.ifft if inverse else lf.fft

        def pipe(b):
            # The pipelined per-block FFTs belong to the graph's stage-2
            # local-FFT node even though they trace inside the ring
            # (innermost scope wins in attribution).
            with obs.profile.stage_scope("slab", "local_fft:2"):
                for a in axes:
                    b = tf(b, axis=a, norm=norm, backend=be, settings=st)
                return b

        return pipe

    def _ring_fwd_body(self):
        """Local forward body for SendMethod.RING: first-stage FFTs, then
        the ring-decomposed exchange with the non-gathered post-axis FFTs
        pipelined per arriving peer block (Z_Then_YX's y axis, Y_Then_ZX's
        z axis; ZY_Then_X's only post axis is the gathered x), then the
        gathered-axis FFT on the assembled block."""
        s, norm, g = self._seq, self.config.norm, self.global_size
        be, st = self.config.fft_backend, self._mxu_st
        first = self._fwd_parts()[0]
        enc_fn, arr_fn, pipe = self._ring_hooks(
            tuple(a for a in s.post_axes if a != 0))
        after = tuple(a for a in s.post_axes if a == 0)
        sa, nx = s.split_axis, g.nx
        wire = self.config.wire_dtype
        overlap = self._ring_overlap()
        depth = self.config.resolved_overlap_depth()
        subblocks = self.config.resolved_overlap_subblocks()

        def body(xl):
            with obs.profile.stage_scope("slab", "exchange:1"):
                y = ring_transpose(first(xl), SLAB_AXIS, sa, 0,
                                   pipeline_fn=pipe, wire=wire,
                                   overlap=overlap, depth=depth,
                                   subblocks=subblocks, encode_fn=enc_fn,
                                   arrive_fn=arr_fn)
            with obs.profile.stage_scope("slab", "local_fft:2"):
                y = slice_axis_to(y, 0, nx)
                for a in after:
                    y = lf.fft(y, axis=a, norm=norm, backend=be, settings=st)
            return y

        return body

    def _ring_inv_body(self):
        """Mirror of ``_ring_fwd_body``: the inverse exchange gathers the
        split axis, so the pipelined set is the last-stage C2C axes other
        than it (the C2C r2c-axis inverse where it is not the split axis);
        the shape-changing C2R transform always waits for assembly. Note
        the one rounding consequence in this PR: pipelining hoists that
        C2C r2c-axis IFFT ahead of the split-axis IFFT, so the c2c inverse
        agrees with the SYNC rendering to ~1e-15 RELATIVE rather than to
        the bit (every other path — bare ring, all forwards, r2c inverses
        — is bit-identical; tests/test_ring.py pins both levels)."""
        s, norm, g = self._seq, self.config.norm, self.global_size
        be, st = self.config.fft_backend, self._mxu_st
        first = self._inv_parts()[0]
        sa, split_ext = s.split_axis, self._split_ext
        real_n = g.nz if s.halved == "z" else g.ny
        complex_mode = self.transform == "c2c"
        pipe_axes = tuple(a for a in reversed(s.pre_axes) if a != sa)
        if complex_mode and s.r2c_axis != sa:
            pipe_axes = pipe_axes + (s.r2c_axis,)
        enc_fn, arr_fn, pipe = self._ring_hooks(pipe_axes, inverse=True)
        after = tuple(a for a in reversed(s.pre_axes) if a == sa)
        wire = self.config.wire_dtype
        overlap = self._ring_overlap()
        depth = self.config.resolved_overlap_depth()
        subblocks = self.config.resolved_overlap_subblocks()

        def body(cl):
            with obs.profile.stage_scope("slab", "exchange:1"):
                y = ring_transpose(first(cl), SLAB_AXIS, 0, sa,
                                   pipeline_fn=pipe, wire=wire,
                                   overlap=overlap, depth=depth,
                                   subblocks=subblocks, encode_fn=enc_fn,
                                   arrive_fn=arr_fn)
            with obs.profile.stage_scope("slab", "local_fft:2"):
                y = slice_axis_to(y, sa, split_ext)
                for a in after:
                    y = lf.ifft(y, axis=a, norm=norm, backend=be,
                                settings=st)
                if complex_mode:
                    if s.r2c_axis == sa:
                        y = lf.ifft(y, axis=s.r2c_axis, norm=norm,
                                    backend=be, settings=st)
                    return y
                return lf.irfft(y, n=real_n, axis=s.r2c_axis, norm=norm,
                                backend=be, settings=st)

        return body

    # -- pipeline builders -------------------------------------------------

    def _build_r2c(self):
        with obs.span("plan.build", kind="slab", direction="forward",
                      sequence=self.sequence.value):
            if self.fft3d:
                return (self._fft3d_c2c(forward=True)
                        if self.transform == "c2c" else self._fft3d_r2c())
            return self._assemble(self._fwd_parts(), self._in_spec,
                                  self._out_spec, self.config.comm_method,
                                  forward=True)

    def _build_c2r(self):
        with obs.span("plan.build", kind="slab", direction="inverse",
                      sequence=self.sequence.value):
            if self.fft3d:
                return (self._fft3d_c2c(forward=False)
                        if self.transform == "c2c" else self._fft3d_c2r())
            return self._assemble(self._inv_parts(), self._out_spec,
                                  self._in_spec, self.config.comm_method,
                                  forward=False)

    def _assemble(self, parts, in_spec, out_spec, comm: pm.CommMethod,
                  forward: bool = True):
        """Compose (first, xpose, last) into one jitted program (the pure
        composition from ``_assemble_pure`` with in/out shardings). At
        guard modes check/enforce the program is the GUARDED pipeline
        ``x -> (y, stats)`` (``resilience/guards.py``: the Parseval/drift
        reductions traced into the same jit); at "off" it is byte-
        identical to the pre-guard program."""
        from ..resilience import guards
        pure = self._assemble_pure(parts, in_spec, out_spec, comm,
                                   forward=forward)
        mesh = self.mesh
        pure, guarded = guards.maybe_wrap(
            self, pure, "forward" if forward else "inverse")
        outsh = NamedSharding(mesh, out_spec)
        if guarded:
            outsh = (outsh, NamedSharding(mesh, PartitionSpec()))
        return jax.jit(pure, in_shardings=NamedSharding(mesh, in_spec),
                       out_shardings=outsh)

    def _assemble_pure(self, parts, in_spec, out_spec, comm: pm.CommMethod,
                       forward: bool = True):
        """Compose (first, xpose, last) into one pure callable.

        ALL2ALL: a single shard_map containing the explicit collective.
        PEER2PEER: two shard_map stages with the transpose omitted — the
        sharding change at the stage boundary makes XLA's SPMD partitioner
        insert and schedule the collective (its latency-hiding scheduler is
        the analog of the reference's Isend/Irecv + callback-thread overlap
        engine).

        ``SendMethod.STREAMS`` swaps in the chunked pipelined rendering:
        ALL2ALL uses the ``_streams_*_body`` per-piece chains — measured
        to genuinely emit K distinct ``all-to-all`` ops. PEER2PEER splits
        the stage boundary into per-piece reshards
        (``chunked_reshard``); MEASURED RESULT (8-device CPU mesh, k=4):
        GSPMD's partitioner re-fuses the piece reshards into ONE
        collective — identical HLO to SYNC — whether or not the stage-2
        FFT is interleaved per piece (it lowers constraint-of-slice as
        slice-of-reshard and CSEs the shared exchange). Under GSPMD
        delegation a chunked exchange cannot be forced; the explicit
        ALL2ALL rendering is the real chunked path, so a P2P+STREAMS
        config is an honest no-op rather than a mismeasured variant.

        ``SendMethod.RING`` / ``RING_OVERLAP`` render the exchange as the
        ``P-1``-step ``lax.ppermute`` ring (``_ring_fwd_body``/
        ``_ring_inv_body``; RING_OVERLAP on the double-buffered schedule).
        A ring is only expressible as an explicit shard_map program, so
        the ring renderings own the exchange regardless of ``comm``
        (params.py contract: GSPMD delegation has no ppermute analog)."""
        first, xpose, last = parts
        mesh = self.mesh
        if self.config.send_method.is_ring:
            body = self._ring_fwd_body() if forward else self._ring_inv_body()
            return jax.shard_map(body, mesh=mesh, in_specs=in_spec,
                                 out_specs=out_spec)
        streams = self.config.send_method is pm.SendMethod.STREAMS
        if comm is pm.CommMethod.ALL2ALL:
            if streams:
                body = (self._streams_fwd_body() if forward
                        else self._streams_inv_body())
                return jax.shard_map(body, mesh=mesh, in_specs=in_spec,
                                     out_specs=out_spec)
            return jax.shard_map(lambda xl: last(xpose(first(xl))), mesh=mesh,
                                 in_specs=in_spec, out_specs=out_spec)
        # PEER2PEER wire layer (wire_gspmd_stages): a compressed wire makes
        # stage1 emit the planar bf16 encoding and stage2 decode it, so
        # the GSPMD-inserted boundary collective moves the compressed
        # array; wire="native" is the unchanged pre-wire stage pair. Under
        # STREAMS the chunk axis shifts past the plane axis and the piece
        # reshards move the compressed planes (GSPMD re-fuses them either
        # way — the honest-no-op contract is unchanged, just half the
        # bytes).
        stage1, stage2, bspec, shift = wire_gspmd_stages(
            mesh, first, last, in_spec, out_spec, self.config.wire_dtype,
            self.config.double_prec)
        if not streams:
            return lambda x: stage2(stage1(x))
        ca, k, _, _ = self._streams_split()
        boundary = NamedSharding(mesh, bspec)
        ca = ca + shift

        def pure(x):
            with obs.profile.stage_scope("slab", "exchange:1"):
                y = chunked_reshard(stage1(x), boundary, ca, k)
            return stage2(y)

        return pure

    def forward_fn(self):
        """Pure forward pipeline (``DistFFTPlan.forward_fn`` contract).
        Cached per plan (a fresh closure per call would defeat the caller's
        jit cache); pads logical-shaped input like ``exec_r2c`` does, with
        a traced ``jnp.pad`` so the preamble stays differentiable."""
        if self._fwd_pure is None:
            if self.fft3d:
                pure = (self._fft3d_c2c(forward=True, jit=False)
                        if self.transform == "c2c"
                        else self._fft3d_r2c(jit=False))
            else:
                pure = self._assemble_pure(self._fwd_parts(), self._in_spec,
                                           self._out_spec,
                                           self.config.comm_method,
                                           forward=True)
            self._fwd_pure = _with_pad(pure, self.input_shape,
                                       self.input_padded_shape)
        return self._fwd_pure

    def inverse_fn(self):
        """Pure inverse pipeline (``DistFFTPlan.forward_fn`` contract)."""
        if self._inv_pure is None:
            if self.fft3d:
                pure = (self._fft3d_c2c(forward=False, jit=False)
                        if self.transform == "c2c"
                        else self._fft3d_c2r(jit=False))
            else:
                pure = self._assemble_pure(self._inv_parts(), self._out_spec,
                                           self._in_spec,
                                           self.config.comm_method,
                                           forward=False)
            self._inv_pure = _with_pad(pure, self.output_shape,
                                       self.output_padded_shape)
        return self._inv_pure

    # -- per-phase staged execution (benchmark timer support) --------------

    @property
    def variant_name(self) -> str:
        return {
            pm.SlabSequence.ZY_THEN_X: "slab_default",
            pm.SlabSequence.Z_THEN_YX: "slab_z_then_yx",
            pm.SlabSequence.Y_THEN_ZX: "slab_y_then_zx",
        }[self.sequence]

    @property
    def section_descriptions(self) -> List[str]:
        """Reference phase vocabulary for this sequence (slab default:
        include/mpicufft_slab.hpp:209-223; z_then_yx: :121-134; y_then_zx:
        :107-109). Phases that have no analog under XLA (pack/unpack/send
        bookkeeping) remain 0 in the CSV."""
        first, last = self._stage_descs()
        xpose = ["Transpose (First Send)", "Transpose (Packing)",
                 "Transpose (Start Local Transpose)", "Transpose (Start Receive)",
                 "Transpose (First Receive)", "Transpose (Finished Receive)",
                 "Transpose (Start All2All)", "Transpose (Finished All2All)",
                 "Transpose (Unpacking)"]
        # "Run complete (fused)" extends the reference vocabulary: the marker
        # after ONE extra call of the fused production program, so the CSV
        # carries both staged phase attribution and the true fused runtime
        # (fused = this mark minus the "Run complete" mark).
        if self.sequence is pm.SlabSequence.ZY_THEN_X:
            # The reference slab_default list carries an extra "2D FFT (Sync)"
            # marker before the 2D FFT row (mpicufft_slab.hpp:209-223).
            return ["init", "2D FFT (Sync)", first] + xpose + [
                last, "Run complete", "Run complete (fused)"]
        if self.sequence is pm.SlabSequence.Y_THEN_ZX:
            # y_then_zx has the short 9-entry list (mpicufft_slab_y_then_zx
            # .hpp:107-109): only P2P phases, no All2All markers.
            return ["init", first, "Transpose (First Send)",
                    "Transpose (Packing)", "Transpose (Start Local Transpose)",
                    "Transpose (Start Receive)", "Transpose (Finished Receive)",
                    last, "Run complete", "Run complete (fused)"]
        return ["init", first] + xpose + [last, "Run complete",
                                          "Run complete (fused)"]

    def _stage_descs(self) -> Tuple[str, str]:
        return {
            pm.SlabSequence.ZY_THEN_X: ("2D FFT Y-Z-Direction", "1D FFT X-Direction"),
            pm.SlabSequence.Z_THEN_YX: ("1D FFT Z-Direction", "2D FFT Y-X-Direction"),
            pm.SlabSequence.Y_THEN_ZX: ("1D FFT Y-Direction", "2D FFT Z-X-Direction"),
        }[self.sequence]

    def _xpose_desc(self) -> str:
        # y_then_zx's short reference list has no All2All markers (it is
        # hardcoded Peer2Peer there); keep its transpose time under the
        # receive marker for either comm method.
        if self.sequence is pm.SlabSequence.Y_THEN_ZX:
            return "Transpose (Finished Receive)"
        return ("Transpose (Finished All2All)"
                if self.config.comm_method is pm.CommMethod.ALL2ALL
                else "Transpose (Finished Receive)")

    def forward_stages(self):
        """[(phase desc, jitted stage fn)] for per-phase timed execution.
        Always uses the explicit collective (timing needs a materialization
        boundary); the fused exec path is unaffected."""
        if self.fft3d:
            return [(None, self._exec_fwd)]
        first, xpose, last = self._fwd_parts()
        d1, d2 = self._stage_descs()
        return self._jit_stages(
            [(d1, first, self._in_spec, self._in_spec),
             (self._xpose_desc(), xpose, self._in_spec, self._out_spec),
             (d2, last, self._out_spec, self._out_spec)])

    def inverse_stages(self):
        if self.fft3d:
            return [(None, self._exec_inv)]
        first, xpose, last = self._inv_parts()
        d1, d2 = self._stage_descs()
        return self._jit_stages(
            [(d2, first, self._out_spec, self._out_spec),
             (self._xpose_desc(), xpose, self._out_spec, self._in_spec),
             (d1, last, self._in_spec, self._in_spec)])




# ---------------------------------------------------------------------------
# contract declaration (analysis/contracts.py) — the exchange this family
# stages, declared next to the code that stages it so the verifier and the
# pipeline cannot drift apart.
# ---------------------------------------------------------------------------

def _contract_exchanges(plan, direction, dims=3):
    """Slab: one symmetric global exchange per direction (scatter the
    sequence's split axis, gather x), payload = the padded spectral
    volume. The single-device fallback stages none. The payload and
    rendering are direction-symmetric; only the ring sub-block split
    depends on ``direction`` (the concat axis — and hence the extent
    the split clamps to — flips with it)."""
    del dims
    if plan.fft3d:
        return ()
    from ..analysis import contracts as _c
    cfg = plan.config
    rendering = _c.rendering_name(cfg)
    # The exchanged block carries BOTH paddings: the split axis padded to
    # the mesh (output_padded_shape) AND x padded to nx_pad — the forward
    # `last` stage slices x back to nx only after the exchange.
    payload = list(plan.output_padded_shape)
    payload[0] = plan._nx_pad
    chunks = 1
    subblocks = 1
    if rendering == "streams":
        # chunk_slices clamps the piece count to the free-axis extent at
        # trace time; mirror it so the expected all-to-all count is exact.
        ca = plan._streams_chunk_axis()
        chunks = min(cfg.resolved_streams_chunks(), payload[ca])
    elif rendering == "a2a_pipe":
        chunks = plan._a2a_pipe_chunks()
    elif rendering in ("ring", "ring_overlap"):
        # The sub-block split slices arriving blocks along the concat
        # axis (forward gathers x = axis 0, inverse gathers the split
        # axis); ring_subblocks applies the same trace-time clamp as
        # ring_transpose, on the LOCAL (per-rank) extent.
        c = 0 if direction == "forward" else plan._seq.split_axis
        subblocks = ring_subblocks(payload[c] // plan._P,
                                   cfg.resolved_overlap_subblocks())
    return (_c.ExchangeDecl("transpose", tuple(payload),
                            plan._P, rendering, chunks,
                            subblocks=subblocks),)


def _declare_graph(plan, direction, dims=3):
    """Slab stage graph (analysis/plangraph.py): stage-1 local FFTs
    (the sequence's R2C axis + pre axes) -> one symmetric exchange
    (encode/decode around it under a compressed wire; fused Pallas
    kernels when ``Config.fused_wire`` is active) -> stage-2 local FFTs
    (post axes) -> guard (modes check/enforce). The single-device
    fallback is one fused local-FFT node."""
    from ..analysis import plangraph as _pg
    cfg = plan.config
    c2c = plan.transform == "c2c"
    cdt, rdt = _pg.payload_dtypes(cfg, plan.transform)
    fwd = direction == "forward"
    b = _pg.GraphBuilder("slab", direction, wire=cfg.wire_dtype,
                         guards=plan._guard_mode, complex_dtype=cdt)
    in_shape = plan.input_padded_shape if fwd else plan.output_padded_shape
    out_shape = plan.output_padded_shape if fwd else plan.input_padded_shape
    in_dtype, out_dtype = (rdt, cdt) if fwd else (cdt, rdt)
    b.node("input")
    b.payload(in_shape, in_dtype,
              plan.input_spec if fwd else plan.output_spec)
    if plan.fft3d:
        b.node("local_fft", axes=(2, 1, 0) if fwd else (0, 1, 2),
               label="fft3d")
        b.payload(out_shape, out_dtype, "")
    else:
        s = plan._seq
        (decl,) = _contract_exchanges(plan, direction, dims)
        if fwd:
            stage1 = (s.r2c_axis,) + s.pre_axes
            stage2 = s.post_axes
            pipe_axes = tuple(a for a in s.post_axes if a != 0)
        else:
            stage1 = tuple(reversed(s.post_axes))
            stage2 = tuple(reversed(s.pre_axes)) + (s.r2c_axis,)
            pipe_axes = tuple(a for a in reversed(s.pre_axes)
                              if a != s.split_axis)
            if c2c and s.r2c_axis != s.split_axis:
                pipe_axes += (s.r2c_axis,)
        b.node("local_fft", axes=stage1, label="stage 1")
        depth = _pg.shipped_schedule_depth(decl.rendering, cfg)
        fused = cfg.fused_wire_active()
        spec_after = plan.output_spec if fwd else plan.input_spec
        b.exchange(decl.label, decl.payload_shape, decl.axis_size,
                   decl.rendering, chunks=decl.chunks,
                   subblocks=decl.subblocks,
                   schedule_depth=depth, decoded_spec=spec_after,
                   fused_encode=fused,
                   decode_fuses=(("decode", "fft") if pipe_axes
                                 else ("decode",)) if fused else None)
        b.node("local_fft", axes=stage2, label="stage 2")
        b.payload(out_shape, out_dtype, spec_after)
    if plan._guard_mode != "off":
        b.node("guard")
    b.node("output")
    return b.graph()


def _register_contracts():
    from ..analysis import contracts as _c
    from ..analysis import plangraph as _pg
    _c.register_family("slab", "SlabFFTPlan", _contract_exchanges)
    _pg.register_graph_family("slab", _declare_graph)


_register_contracts()
