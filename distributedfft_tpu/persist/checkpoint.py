"""Crash-consistent checkpoint format + two-generation store.

ROADMAP item 5(c): a turbulence run resident inside ``dfft-serve`` is
only production-grade if a SIGTERM, an OOM-kill or a fleet scale-down
cannot destroy hours of simulation progress. PR 8's drain and PR 13's
worker-death recovery protect in-flight *requests*; this module protects
long-lived *state* — the spectral fields, the step counter, and the
plan/wisdom provenance that makes a resumed run reproducible.

Checkpoint file format (one generation = one self-describing file)::

    bytes  0..7    magic  b"DFFTCKP1"
    bytes  8..11   header length H (u32 LE)
    bytes 12..15   CRC32C of the H header bytes (u32 LE)
    bytes 16..16+H header JSON (utf-8)
    then the raw C-contiguous array payloads, concatenated

The header carries ``version`` (schema), the solver step counter, ``dt``,
simulated time, the RNG/forcing phase, the **plan fingerprint**
(``resilience.guards.fingerprint`` — family, shape, rendering, wire,
backend), **wisdom provenance** (store path + on-disk schema version at
save time), free-form ``meta``, and one section record per array
(``name``/``dtype``/``shape``/``sharding``/``offset``/``nbytes``/
``crc32c``). Every section is independently CRC32C-checksummed, so a
single flipped byte anywhere is detected before ANY bytes reach a device
array — a corrupt checkpoint can cost a generation, never a garbage
restore.

Crash consistency is the wisdom-store discipline (``utils/wisdom.py``):
the blob is written to a temp file in the target directory, ``fsync``'d,
then ``os.replace``'d into its generation slot under the advisory flock
(``_advisory_lock`` — srclint's replace-under-lock rule covers this
package), and the directory entry is fsync'd; a torn write can only tear
the temp file, never a slot. The :class:`CheckpointStore` rotates TWO
generation slots (``ckpt-a.dfft`` / ``ckpt-b.dfft``) and always
overwrites the OLDER one, so even a fault that lands a corrupt newest
generation (``$DFFT_FAULT_SPEC=checkpoint:torn|corrupt|stale``,
``resilience/inject.py``) leaves one loadable checkpoint — ``load``
falls back exactly one generation (``persist.generation_fallbacks``
metric + ``checkpoint_restore_failure`` flight-recorder trigger) and
refuses with a structured error when both are bad.

Restore contract: ``load`` validates checksums and schema version,
REFUSES a plan whose fingerprint disagrees with the checkpoint's
(:class:`CheckpointMismatch` — a mismatched plan is a configuration
error, not corruption, so no generation fallback), and ``state.py``
re-places the arrays into the *current* plan's shardings so a resumed
run continues **bit-exactly** (the acceptance experiment: SIGTERM at
step k, resume, run to n, compare bit-for-bit with an uninterrupted
n-step run — ``tests/test_persist.py`` + the CI ``resume`` chaos
scenario).

Everything here is host-side numpy + file I/O: the persist layer adds
ZERO traced ops to any compiled program (the dfft-verify fingerprint
pins cover it by construction).
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import obs
from ..resilience import inject
from ..utils.wisdom import _advisory_lock

MAGIC = b"DFFTCKP1"
CHECKPOINT_VERSION = 1
_HEADER_FIXED = len(MAGIC) + 8  # magic + u32 header_len + u32 header_crc

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli) — the checksum the format stamps on every section.
# ---------------------------------------------------------------------------

_CRC32C_POLY = 0x82F63B78


def _build_table() -> List[int]:
    table = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ _CRC32C_POLY if c & 1 else c >> 1
        table.append(c)
    return table


_TABLE = _build_table()

try:  # hardware-accelerated wheels, when the image happens to carry one
    from crc32c import crc32c as _crc32c_hw  # type: ignore[import-not-found]
except ImportError:  # pure-python fallback (the common case here)
    _crc32c_hw = None


def crc32c(data: Any, crc: int = 0) -> int:
    """CRC32C (Castagnoli) of ``data`` (bytes-like), continuing from
    ``crc`` — the polynomial iSCSI/ext4 use, table-driven pure python
    with an optional accelerated backend. Known answer:
    ``crc32c(b"123456789") == 0xE3069283``.

    Performance note: the pure-python loop runs a few MB/s — fine for
    the in-tree solver states (KBs–MBs per generation) but a real cost
    per write/validate on 100-MB-class states; deployments at that
    scale should install a ``crc32c`` wheel (picked up automatically
    above, C speed, same answers). The checksum stays CRC32C — the
    on-disk format pins the polynomial, and swapping to zlib's CRC32
    would silently invalidate every existing generation."""
    buf = memoryview(data).cast("B") if not isinstance(data, (bytes, bytearray)) \
        else data
    if _crc32c_hw is not None:
        return int(_crc32c_hw(bytes(buf), crc))
    c = crc ^ 0xFFFFFFFF
    table = _TABLE
    for b in buf:
        c = (c >> 8) ^ table[(c ^ b) & 0xFF]
    return c ^ 0xFFFFFFFF


# ---------------------------------------------------------------------------
# structured failures
# ---------------------------------------------------------------------------

class CheckpointError(RuntimeError):
    """Base of every structured persist failure."""


class CheckpointCorrupt(CheckpointError):
    """One checkpoint file failed validation (bad magic, unsupported
    schema version, short file, or a CRC32C mismatch); carries where and
    why so the generation-fallback path can report what it skipped."""

    def __init__(self, path: str, reason: str):
        super().__init__(f"corrupt checkpoint {path}: {reason}")
        self.path = path
        self.reason = reason


class CheckpointMissing(CheckpointError):
    """No generation file exists at all — a FRESH simulation, not a
    failure (residents start from the initial condition on this)."""

    def __init__(self, directory: str):
        super().__init__(f"no checkpoint generations in {directory}")
        self.directory = directory


class CheckpointMismatch(CheckpointError):
    """The checkpoint was written by a DIFFERENT plan than the one asked
    to resume (fingerprint disagreement) — a configuration error, never
    auto-resolved: loading spectral state into a differently-rendered
    plan would silently change the simulation."""

    def __init__(self, path: str, diffs: Dict[str, Tuple[Any, Any]]):
        detail = ", ".join(f"{k}: checkpoint={a!r} plan={b!r}"
                           for k, (a, b) in sorted(diffs.items()))
        super().__init__(f"checkpoint {path} fingerprint mismatch "
                         f"({detail})")
        self.path = path
        self.diffs = diffs


class CheckpointUnusable(CheckpointError):
    """EVERY generation failed validation — the store has zero loadable
    checkpoints; carries the per-generation reasons."""

    def __init__(self, directory: str, reasons: Dict[str, str]):
        detail = "; ".join(f"{os.path.basename(p)}: {r}"
                           for p, r in sorted(reasons.items()))
        super().__init__(
            f"no loadable checkpoint in {directory} ({detail})")
        self.directory = directory
        self.reasons = reasons


# ---------------------------------------------------------------------------
# the state a checkpoint carries
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class SimState:
    """One checkpointable simulation state: named host arrays plus the
    scalar/bookkeeping fields the header records. ``rng`` is the
    RNG/forcing phase (JSON-able dict; e.g. a forcing seed + draw
    counter), ``plan_fingerprint`` the identity restore validates, and
    ``wisdom`` the provenance of the autotuned choices the plan was
    built from."""

    arrays: Dict[str, np.ndarray]
    step: int = 0
    dt: float = 0.0
    sim_time: float = 0.0
    rng: Optional[Dict[str, Any]] = None
    plan_fingerprint: Dict[str, Any] = dataclasses.field(default_factory=dict)
    wisdom: Dict[str, Any] = dataclasses.field(default_factory=dict)
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)
    written_at: Optional[float] = None  # stamped by write_checkpoint


# ---------------------------------------------------------------------------
# single-file writer / reader
# ---------------------------------------------------------------------------

def _fsync_dir(directory: str) -> None:
    """Best-effort fsync of the directory entry (the rename itself must
    survive the crash, not only the file bytes)."""
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_checkpoint(path: str, state: SimState) -> int:
    """Serialize ``state`` to ``path`` crash-consistently (temp + fsync +
    ``os.replace`` under the advisory flock, directory fsync'd); returns
    the bytes written. Raises ``OSError``/``TypeError`` on an unwritable
    target or un-serializable state — persistence failures are loud, a
    silently-lost checkpoint is the failure mode this module exists to
    remove."""
    sections: List[Dict[str, Any]] = []
    payloads: List[bytes] = []
    offset = 0
    for name in sorted(state.arrays):
        arr = np.ascontiguousarray(state.arrays[name])
        raw = arr.tobytes()
        sections.append({
            "name": name, "dtype": arr.dtype.str,
            "shape": list(arr.shape), "offset": offset,
            "nbytes": len(raw), "crc32c": crc32c(raw),
        })
        payloads.append(raw)
        offset += len(raw)
    written_at = time.time()
    header = {
        "version": CHECKPOINT_VERSION,
        "step": int(state.step),
        "dt": float(state.dt),
        "sim_time": float(state.sim_time),
        "rng": state.rng,
        "plan_fingerprint": state.plan_fingerprint,
        "wisdom": state.wisdom,
        "meta": state.meta,
        "written_at": written_at,
        "arrays": sections,
    }
    hdr = json.dumps(header, sort_keys=True).encode("utf-8")
    blob = (MAGIC + len(hdr).to_bytes(4, "little")
            + crc32c(hdr).to_bytes(4, "little") + hdr + b"".join(payloads))
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    with obs.span("persist.write", path=path, step=int(state.step),
                  nbytes=len(blob)), _advisory_lock(path):
        fd, tmp = tempfile.mkstemp(prefix=".ckpt.", dir=d)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
        _fsync_dir(d)
    state.written_at = written_at
    # The fault injectors tear/corrupt/stale-stamp the LANDED file —
    # simulating a write the filesystem lost mid-rename or bitrot the
    # disk returned — so the restore path's validation is exercised
    # against exactly what it would see in the field.
    inject.maybe_taint_checkpoint(path)
    obs.metrics.inc("persist.writes")
    obs.metrics.inc("persist.bytes_written", len(blob))
    obs.event("persist.checkpoint", path=path, step=int(state.step),
              nbytes=len(blob), arrays=len(sections))
    return len(blob)


def _read_validated(path: str, header_only: bool = False
                    ) -> Tuple[Dict[str, Any], Optional[bytes]]:
    """Read + validate one checkpoint file; returns ``(header,
    payload_bytes)`` (payload None when ``header_only``). Raises
    :class:`CheckpointCorrupt` on ANY defect — validation happens before
    a single payload byte is interpreted."""
    try:
        with open(path, "rb") as f:
            head = f.read(_HEADER_FIXED)
            if len(head) < _HEADER_FIXED:
                raise CheckpointCorrupt(path, "short file (no header)")
            if head[:len(MAGIC)] != MAGIC:
                raise CheckpointCorrupt(
                    path, f"bad magic {head[:len(MAGIC)]!r}")
            hlen = int.from_bytes(head[len(MAGIC):len(MAGIC) + 4], "little")
            hcrc = int.from_bytes(head[len(MAGIC) + 4:], "little")
            hdr_bytes = f.read(hlen)
            if len(hdr_bytes) != hlen:
                raise CheckpointCorrupt(path, "truncated header")
            if crc32c(hdr_bytes) != hcrc:
                raise CheckpointCorrupt(path, "header CRC32C mismatch")
            try:
                header = json.loads(hdr_bytes.decode("utf-8"))
            except (ValueError, UnicodeDecodeError) as e:
                raise CheckpointCorrupt(path,
                                        f"unparsable header ({e})") from e
            version = header.get("version")
            if version != CHECKPOINT_VERSION:
                raise CheckpointCorrupt(
                    path, f"unsupported schema version {version!r} "
                          f"(this build reads {CHECKPOINT_VERSION})")
            if not isinstance(header.get("arrays"), list):
                raise CheckpointCorrupt(path, "header carries no array "
                                              "section table")
            if header_only:
                return header, None
            payload = f.read()
    except OSError as e:
        raise CheckpointCorrupt(path, f"unreadable ({e})") from e
    for sec in header["arrays"]:
        off, n = int(sec["offset"]), int(sec["nbytes"])
        if off + n > len(payload):
            raise CheckpointCorrupt(
                path, f"torn payload: section {sec['name']!r} wants "
                      f"[{off}:{off + n}] of {len(payload)} byte(s)")
        if crc32c(payload[off:off + n]) != int(sec["crc32c"]):
            raise CheckpointCorrupt(
                path, f"section {sec['name']!r} CRC32C mismatch")
    return header, payload


def read_checkpoint(path: str) -> SimState:
    """Load + fully validate one checkpoint file into a
    :class:`SimState` (host numpy arrays). Raises
    :class:`CheckpointCorrupt` on any defect; no bytes are interpreted
    as array data until every section checksum has passed."""
    header, payload = _read_validated(path)
    assert payload is not None
    arrays: Dict[str, np.ndarray] = {}
    for sec in header["arrays"]:
        off, n = int(sec["offset"]), int(sec["nbytes"])
        arr = np.frombuffer(payload[off:off + n],
                            dtype=np.dtype(sec["dtype"]))
        arrays[sec["name"]] = arr.reshape(tuple(sec["shape"])).copy()
    return SimState(
        arrays=arrays, step=int(header["step"]), dt=float(header["dt"]),
        sim_time=float(header.get("sim_time", 0.0)),
        rng=header.get("rng"),
        plan_fingerprint=dict(header.get("plan_fingerprint") or {}),
        wisdom=dict(header.get("wisdom") or {}),
        meta=dict(header.get("meta") or {}),
        written_at=header.get("written_at"))


# ---------------------------------------------------------------------------
# two-generation store
# ---------------------------------------------------------------------------

GENERATION_SLOTS = ("ckpt-a.dfft", "ckpt-b.dfft")


# The fingerprint fields a MESH CHANGE (and nothing else) flips: rank
# count, the sequence the autotuner picked for the new rank count, and
# the variant label derived from both. An ``allow_mesh_change`` restore
# tolerates diffs confined to this set — shape, transform, dtype, comm
# and backend disagreements remain configuration errors and refuse.
MESH_CHANGE_FIELDS = frozenset({"ranks", "sequence", "variant"})


def fingerprint_mismatch(stored: Dict[str, Any],
                         current: Dict[str, Any]
                         ) -> Dict[str, Tuple[Any, Any]]:
    """Field-wise diff of two plan fingerprints (empty dict = match).
    The RESTORE path and ``dfft-explain``'s ``checkpoint:`` section both
    call this — one comparison, so explain cannot disagree with
    restore."""
    diffs: Dict[str, Tuple[Any, Any]] = {}
    for k in set(stored) | set(current):
        if stored.get(k) != current.get(k):
            diffs[k] = (stored.get(k), current.get(k))
    return diffs


class CheckpointStore:
    """Two-generation rotating checkpoint store over one directory.

    ``save`` always overwrites the OLDER (or invalid) slot, so the
    newest valid generation is never the write target — a torn write can
    cost at most the generation being written. ``load`` returns the
    newest valid generation, falling back exactly one generation on
    corruption; :meth:`describe` is the registry surface
    ``dfft-explain`` and serve ``health()`` read, built from the SAME
    validation the load path runs."""

    def __init__(self, directory: str):
        self.directory = os.path.abspath(os.path.expanduser(str(directory)))

    def _slot_paths(self) -> List[str]:
        return [os.path.join(self.directory, s) for s in GENERATION_SLOTS]

    def _scan(self, full: bool = False) -> List[Dict[str, Any]]:
        """Validate every slot: one record per slot with ``path``/
        ``exists``/``valid``/``step``/``written_at``/``reason``.
        Default is header-only (cheap — header CRC; the load path
        re-validates its chosen generation in full anyway); ``full``
        additionally runs every SECTION checksum, so a verdict built on
        it (``describe``) cannot call a payload-corrupt generation
        valid when restore would skip it."""
        out: List[Dict[str, Any]] = []
        for path in self._slot_paths():
            rec: Dict[str, Any] = {"path": path,
                                   "exists": os.path.exists(path),
                                   "valid": False, "step": None,
                                   "written_at": None, "reason": None,
                                   "mtime": None}
            if rec["exists"]:
                try:
                    rec["mtime"] = os.path.getmtime(path)
                except OSError:
                    pass
                try:
                    header, _ = _read_validated(path, header_only=not full)
                    rec.update(valid=True, step=int(header["step"]),
                               written_at=header.get("written_at"),
                               fingerprint=dict(
                                   header.get("plan_fingerprint") or {}))
                except CheckpointCorrupt as e:
                    rec["reason"] = e.reason
            else:
                rec["reason"] = "absent"
            out.append(rec)
        return out

    def _write_target(self) -> str:
        """The slot ``save`` must overwrite: an absent/invalid slot
        first, else the OLDER valid generation — the newest
        fully-loadable checkpoint is never the write target. FULL
        validation (section checksums, not just the header): a
        payload-torn newest generation must read as the invalid slot
        here, or save would overwrite the only generation ``load``
        could actually restore."""
        scan = self._scan(full=True)
        for rec in scan:
            if not rec["valid"]:
                return str(rec["path"])
        oldest = min(scan, key=lambda r: (r["step"], r["written_at"] or 0))
        return str(oldest["path"])

    def save(self, state: SimState) -> str:
        """Write ``state`` into the rotation; returns the generation
        path written."""
        path = self._write_target()
        write_checkpoint(path, state)
        obs.metrics.gauge("persist.last_checkpoint_age_s", 0.0)
        return path

    def load(self, expect_fingerprint: Optional[Dict[str, Any]] = None,
             allow_mesh_change: bool = False) -> SimState:
        """The newest fully-valid generation, newest-step-first with
        exactly-one-generation fallback on corruption
        (``persist.generation_fallbacks`` + the
        ``checkpoint_restore_failure`` flight-recorder trigger document
        every skipped generation). ``expect_fingerprint`` (the CURRENT
        plan's ``persist.plan_fingerprint``) refuses a mismatched
        checkpoint with :class:`CheckpointMismatch` — no fallback: a
        fingerprint disagreement is configuration, not corruption.

        ``allow_mesh_change=True`` is the shrink-and-replan escape
        hatch (ISSUE 20): a diff confined to :data:`MESH_CHANGE_FIELDS`
        (rank count + the sequence/variant that follow from it) loads
        anyway — the state is re-placed into the CURRENT plan's
        sharding by ``persist.restore`` — with the two-tier numerical
        contract: same mesh stays bit-exact (this branch never fires),
        changed mesh is allclose under the Parseval guard. NEVER
        silent: the tolerated diff is recorded as a structured
        ``persist.degraded_restore`` event + counter. Any diff outside
        the mesh set still raises :class:`CheckpointMismatch`.

        Raises :class:`CheckpointMissing` when no generation file
        exists, :class:`CheckpointUnusable` when all that exist fail
        validation."""
        from ..obs import flightrec
        scan = [r for r in self._scan() if r["exists"]]
        if not scan:
            raise CheckpointMissing(self.directory)

        def _fell_back(path: str, reason: str) -> None:
            obs.metrics.inc("persist.generation_fallbacks")
            obs.notice(
                f"persist: generation {os.path.basename(path)} invalid "
                f"({reason}); falling back one generation",
                name="persist.generation_fallback", path=path)
            flightrec.trigger("checkpoint_restore_failure",
                              f"generation fallback: {reason}", path=path)

        # Candidates: VALID headers ordered by highest step — the same
        # choice describe()/health advertise as "latest" (mtime is wall
        # clock and survives neither cp nor a clock step, so it must
        # not pick the restore target). Header-invalid generations are
        # recorded up front; one NEWER (by write time) than the best
        # valid candidate means the latest write was lost — an honest
        # generation fallback, accounted before the older state loads.
        order = sorted((r for r in scan if r["valid"]),
                       key=lambda r: (r["step"], r["mtime"] or 0),
                       reverse=True)
        reasons: Dict[str, str] = {}
        for rec in scan:
            if not rec["valid"]:
                path = str(rec["path"])
                reasons[path] = str(rec["reason"])
                obs.event("persist.generation_skipped", path=path,
                          reason=str(rec["reason"]))
                if order and (rec["mtime"] or 0) >= \
                        (order[0]["mtime"] or 0):
                    _fell_back(path, str(rec["reason"]))
        for i, rec in enumerate(order):
            path = str(rec["path"])
            try:
                state = read_checkpoint(path)  # full section CRC pass
            except CheckpointCorrupt as e:
                reasons[path] = e.reason
                obs.event("persist.generation_skipped", path=path,
                          reason=e.reason)
                if i + 1 < len(order):
                    _fell_back(path, e.reason)
                continue
            if expect_fingerprint is not None:
                # The stored fingerprint participates even when EMPTY
                # (a hand-rolled writer that skipped capture): restore
                # and describe() must render the same verdict.
                diffs = fingerprint_mismatch(state.plan_fingerprint,
                                             expect_fingerprint)
                if diffs and allow_mesh_change \
                        and set(diffs) <= MESH_CHANGE_FIELDS:
                    obs.metrics.inc("persist.degraded_restores")
                    obs.event(
                        "persist.degraded_restore", path=path,
                        step=int(state.step),
                        diffs={k: list(v) for k, v in sorted(diffs.items())})
                    obs.notice(
                        "persist: restoring across a mesh change "
                        f"({', '.join(f'{k}: {v[0]!r} -> {v[1]!r}' for k, v in sorted(diffs.items()))}) "
                        "— allclose contract, not bit-exact",
                        name="persist.degraded_restore")
                    diffs = {}
                if diffs:
                    obs.metrics.inc("persist.restore_failures")
                    flightrec.trigger(
                        "checkpoint_restore_failure",
                        f"fingerprint mismatch: {sorted(diffs)}",
                        path=path)
                    raise CheckpointMismatch(path, diffs)
            self.touch_age_gauge(state.written_at)
            obs.metrics.inc("persist.restores")
            obs.event("persist.restore", path=path, step=state.step,
                      fallbacks=len(reasons))
            return state
        obs.metrics.inc("persist.restore_failures")
        flightrec.trigger("checkpoint_restore_failure",
                          "all generations unusable",
                          directory=self.directory)
        raise CheckpointUnusable(self.directory, reasons)

    def touch_age_gauge(self, written_at: Optional[float] = None) -> None:
        """Refresh ``persist.last_checkpoint_age_s`` from the newest
        valid generation (or an explicit stamp) — serve ``health()``
        calls this so the scrape surface carries a live age."""
        if written_at is None:
            valid = [r for r in self._scan() if r["valid"]
                     and r["written_at"] is not None]
            if not valid:
                return
            written_at = max(float(r["written_at"]) for r in valid)
        obs.metrics.gauge("persist.last_checkpoint_age_s",
                          round(max(0.0, time.time() - float(written_at)), 3))

    def describe(self, expect_fingerprint: Optional[Dict[str, Any]] = None,
                 full: bool = True) -> Dict[str, Any]:
        """The registry ``dfft-explain``'s ``checkpoint:`` section and
        serve ``health()`` read: per-slot validity/step/age plus the
        verdict of what :meth:`load` would do for
        ``expect_fingerprint`` — computed by the SAME fingerprint
        comparison the restore path uses, over a FULL (every section
        checksum) validation pass by default, so a payload-corrupt
        generation reads invalid here exactly as restore will treat it.
        ``full=False`` is the cheap header-only variant for hot
        liveness surfaces (the resident's heartbeat-cadence
        ``status()``) where re-reading multi-MB states per pong would
        stall the very reply the death detector times."""
        now = time.time()
        scan = self._scan(full=full)
        gens = []
        for rec in scan:
            gens.append({
                "path": str(rec["path"]), "exists": rec["exists"],
                "valid": rec["valid"], "step": rec["step"],
                "age_s": (round(now - float(rec["written_at"]), 3)
                          if rec.get("written_at") else None),
                "reason": rec["reason"],
            })
        valid = [r for r in scan if r["valid"]]
        latest = max(valid, key=lambda r: (r["step"], r["written_at"] or 0),
                     default=None)
        verdict = "no checkpoint (fresh start)"
        latest_out: Optional[Dict[str, Any]] = None
        if latest is not None:
            latest_out = {
                "path": str(latest["path"]), "step": latest["step"],
                "age_s": (round(now - float(latest["written_at"]), 3)
                          if latest.get("written_at") else None),
            }
            if expect_fingerprint is None:
                verdict = f"restorable (step {latest['step']})"
            else:
                diffs = fingerprint_mismatch(
                    dict(latest.get("fingerprint") or {}),
                    expect_fingerprint)
                verdict = (f"MATCH — restore loads step {latest['step']}"
                           if not diffs else
                           "MISMATCH (CheckpointMismatch): " + ", ".join(
                               f"{k}: checkpoint={a!r} plan={b!r}"
                               for k, (a, b) in sorted(diffs.items())))
        elif any(r["exists"] for r in scan):
            verdict = "UNUSABLE: every generation fails validation"
        return {"directory": self.directory, "generations": gens,
                "latest": latest_out, "fingerprint_verdict": verdict}
