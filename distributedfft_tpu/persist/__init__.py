"""``persist/`` — durable simulation state (ROADMAP item 5c).

Crash-consistent checkpoint/restore for the spectral solvers:

* :mod:`~.checkpoint` — the versioned, self-describing, per-section
  CRC32C-checksummed file format; atomic writes with the wisdom-store
  discipline (temp + fsync + ``os.replace`` under the advisory flock);
  the two-generation :class:`CheckpointStore` rotation whose ``load``
  falls back exactly one generation on corruption and refuses a
  fingerprint-mismatched plan with a structured
  :class:`CheckpointMismatch`;
* :mod:`~.policy` — :class:`CheckpointPolicy` (every-N-steps /
  every-T-seconds / on-drain) with the strict ``steps:N,secs:T,drain:*``
  spec grammar;
* :mod:`~.state` — solver-protocol capture/restore: device arrays
  gathered to host with plan fingerprint + wisdom provenance, restored
  into the CURRENT plan's spectral sharding for bit-exact resume.

Host-side only: nothing in this package adds a traced op to any
compiled program. Chaos surface: ``$DFFT_FAULT_SPEC=
checkpoint:torn|corrupt|stale`` (``resilience/inject.py``), the
``persist.*`` metrics on ``/metrics``, and the
``checkpoint_restore_failure`` flight-recorder trigger.
"""

from __future__ import annotations

from .checkpoint import (CHECKPOINT_VERSION, CheckpointCorrupt,
                         CheckpointError, CheckpointMismatch,
                         CheckpointMissing, CheckpointStore,
                         CheckpointUnusable, GENERATION_SLOTS,
                         MESH_CHANGE_FIELDS, SimState, crc32c,
                         fingerprint_mismatch, read_checkpoint,
                         write_checkpoint)
from .policy import CheckpointPolicy
from .state import capture, plan_fingerprint, restore, wisdom_provenance

ENV_DIR = "DFFT_CKPT_DIR"
ENV_POLICY = "DFFT_CKPT_POLICY"


def resolve_env(dir_arg: "str | None",
                policy_arg: "str | None") -> "tuple[str | None, str | None]":
    """The ONE flag-else-env resolution every CLI shares: checkpoint
    directory (``$DFFT_CKPT_DIR``) and policy spec
    (``$DFFT_CKPT_POLICY``), the policy validated LOUDLY (``ValueError``
    — callers turn it into their usage error) before any work starts.
    Returns ``(abs_dir_or_None, policy_str_or_None)``."""
    import os as _os
    d = dir_arg or _os.environ.get(ENV_DIR) or None
    p = policy_arg or _os.environ.get(ENV_POLICY) or None
    if p:
        CheckpointPolicy.parse(p)
    return (_os.path.abspath(_os.path.expanduser(d)) if d else None, p)

__all__ = [
    "CHECKPOINT_VERSION", "GENERATION_SLOTS", "ENV_DIR", "ENV_POLICY",
    "CheckpointError", "CheckpointCorrupt", "CheckpointMissing",
    "CheckpointMismatch", "CheckpointUnusable", "CheckpointPolicy",
    "CheckpointStore", "MESH_CHANGE_FIELDS", "SimState", "capture", "crc32c",
    "fingerprint_mismatch", "plan_fingerprint", "read_checkpoint",
    "resolve_env", "restore", "wisdom_provenance",
]
