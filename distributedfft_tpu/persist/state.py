"""Solver-state capture/restore — the bridge between the solver
protocol (``models/base.py``) and the checkpoint format.

A pseudo-spectral solver's durable state is its SPECTRAL pytree (one
array for :class:`~..solvers.navier_stokes.NavierStokes2D`, a 3-tuple of
component spectra for ``NavierStokes3D``) plus the integration
bookkeeping (step, dt, simulated time, RNG/forcing phase). ``capture``
gathers the device arrays to host numpy (on a single-process CPU/TPU
mesh ``np.asarray`` materializes the global padded array; each leaf's
sharding spec is recorded in the section table for provenance) and
stamps the plan fingerprint + wisdom provenance; ``restore`` re-places
the validated host arrays into the CURRENT plan's declared spectral
sharding (``plan.output_sharding``), so the resumed state is bit-for-bit
the captured state, laid out exactly where the plan's pipelines expect
it — the precondition of the bit-exact resume contract.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple, Union

import numpy as np

from .checkpoint import SimState

StateTree = Union[Any, Tuple[Any, ...]]

_FIELD = "field{}"


def plan_fingerprint(plan: Any) -> Dict[str, Any]:
    """The identity a checkpoint records and restore validates —
    ``resilience.guards.fingerprint`` with a fixed direction label (a
    checkpoint belongs to the plan, not one direction)."""
    from ..resilience import guards
    return guards.fingerprint(plan, "state")


def wisdom_provenance(plan: Any) -> Dict[str, Any]:
    """Where the plan's autotuned choices came from: the wisdom store
    path + its on-disk schema version at capture time (or an explicit
    "no store"), so a resumed run's report can say whether it was built
    from the same measurements."""
    from ..utils import wisdom
    store = wisdom.store_for_config(plan.config)
    if store is None:
        return {"path": None, "version": None}
    return {"path": store.path, "version": store.raw_version()}


def _leaves(state: StateTree) -> Tuple[Any, ...]:
    return tuple(state) if isinstance(state, (tuple, list)) else (state,)


def capture(solver: Any, state: StateTree, step: int, dt: float, *,
            sim_time: float = 0.0, rng: Optional[Dict[str, Any]] = None,
            meta: Optional[Dict[str, Any]] = None) -> SimState:
    """Gather a solver's spectral state into a checkpointable
    :class:`SimState` (host numpy; device arrays are materialized
    here — call between steps, never inside a traced function)."""
    leaves = _leaves(state)
    plan = solver.plan
    spec = getattr(plan, "output_spec", None)
    arrays = {_FIELD.format(i): np.asarray(leaf)
              for i, leaf in enumerate(leaves)}
    meta_out = dict(meta or {})
    meta_out.update({
        "solver": type(solver).__name__,
        "n_fields": len(leaves),
        "tuple_state": isinstance(state, (tuple, list)),
        "sharding": str(spec) if spec is not None else None,
    })
    return SimState(arrays=arrays, step=int(step), dt=float(dt),
                    sim_time=float(sim_time), rng=rng,
                    plan_fingerprint=plan_fingerprint(plan),
                    wisdom=wisdom_provenance(plan), meta=meta_out)


def _fit_padded(host: np.ndarray, plan: Any) -> np.ndarray:
    """Adapt a captured global spectral array to the CURRENT plan's
    padded shape (the mesh-change restore path: a different rank count
    pads decomposed axes to a different multiple). The logical region
    is p-independent, and the slab/pencil padded-shape contract says pad
    lanes are exact zeros in forward output — so crop to the logical
    extents and zero-pad back out. Same shape (every same-mesh restore,
    and mesh-divisible sizes across any mesh change) returns ``host``
    UNTOUCHED, preserving the bit-exact contract byte for byte."""
    padded = getattr(plan, "output_padded_shape", None)
    if padded is None or tuple(host.shape) == tuple(padded):
        return host
    logical = tuple(getattr(plan, "output_shape", padded))
    if len(logical) != host.ndim or len(padded) != host.ndim:
        return host  # a rank disagreement is for the device_put to refuse
    cropped = host[tuple(slice(0, min(h, l))
                         for h, l in zip(host.shape, logical))]
    pad = [(0, p - s) for p, s in zip(padded, cropped.shape)]
    return np.pad(cropped, pad) if any(w for _, w in pad) else cropped


def restore(sim: SimState, solver: Any) -> StateTree:
    """Re-place a validated :class:`SimState` onto the devices in the
    CURRENT plan's spectral sharding; returns the solver-shaped state
    pytree (tuple for multi-field solvers). Raises ``ValueError`` when
    the checkpoint's field count disagrees with what it recorded —
    format-level corruption is already excluded by the checksum pass,
    so this only fires on a hand-edited header. A checkpoint captured
    on a DIFFERENT mesh (``CheckpointStore.load(allow_mesh_change=
    True)`` admitted it) is shape-adapted through :func:`_fit_padded`
    before placement."""
    import jax
    n = int(sim.meta.get("n_fields", len(sim.arrays)))
    names = [_FIELD.format(i) for i in range(n)]
    missing = [nm for nm in names if nm not in sim.arrays]
    if missing:
        raise ValueError(f"checkpoint meta claims {n} field(s) but "
                         f"sections {missing} are absent")
    sharding = getattr(solver.plan, "output_sharding", None)
    leaves = []
    for nm in names:
        host = _fit_padded(sim.arrays[nm], solver.plan)
        if sharding is not None:
            leaves.append(jax.device_put(host, sharding))
        else:
            leaves.append(jax.device_put(host))
    if sim.meta.get("tuple_state", n > 1):
        return tuple(leaves)
    return leaves[0]
