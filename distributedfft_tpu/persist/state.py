"""Solver-state capture/restore — the bridge between the solver
protocol (``models/base.py``) and the checkpoint format.

A pseudo-spectral solver's durable state is its SPECTRAL pytree (one
array for :class:`~..solvers.navier_stokes.NavierStokes2D`, a 3-tuple of
component spectra for ``NavierStokes3D``) plus the integration
bookkeeping (step, dt, simulated time, RNG/forcing phase). ``capture``
gathers the device arrays to host numpy (on a single-process CPU/TPU
mesh ``np.asarray`` materializes the global padded array; each leaf's
sharding spec is recorded in the section table for provenance) and
stamps the plan fingerprint + wisdom provenance; ``restore`` re-places
the validated host arrays into the CURRENT plan's declared spectral
sharding (``plan.output_sharding``), so the resumed state is bit-for-bit
the captured state, laid out exactly where the plan's pipelines expect
it — the precondition of the bit-exact resume contract.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple, Union

import numpy as np

from .checkpoint import SimState

StateTree = Union[Any, Tuple[Any, ...]]

_FIELD = "field{}"


def plan_fingerprint(plan: Any) -> Dict[str, Any]:
    """The identity a checkpoint records and restore validates —
    ``resilience.guards.fingerprint`` with a fixed direction label (a
    checkpoint belongs to the plan, not one direction)."""
    from ..resilience import guards
    return guards.fingerprint(plan, "state")


def wisdom_provenance(plan: Any) -> Dict[str, Any]:
    """Where the plan's autotuned choices came from: the wisdom store
    path + its on-disk schema version at capture time (or an explicit
    "no store"), so a resumed run's report can say whether it was built
    from the same measurements."""
    from ..utils import wisdom
    store = wisdom.store_for_config(plan.config)
    if store is None:
        return {"path": None, "version": None}
    return {"path": store.path, "version": store.raw_version()}


def _leaves(state: StateTree) -> Tuple[Any, ...]:
    return tuple(state) if isinstance(state, (tuple, list)) else (state,)


def capture(solver: Any, state: StateTree, step: int, dt: float, *,
            sim_time: float = 0.0, rng: Optional[Dict[str, Any]] = None,
            meta: Optional[Dict[str, Any]] = None) -> SimState:
    """Gather a solver's spectral state into a checkpointable
    :class:`SimState` (host numpy; device arrays are materialized
    here — call between steps, never inside a traced function)."""
    leaves = _leaves(state)
    plan = solver.plan
    spec = getattr(plan, "output_spec", None)
    arrays = {_FIELD.format(i): np.asarray(leaf)
              for i, leaf in enumerate(leaves)}
    meta_out = dict(meta or {})
    meta_out.update({
        "solver": type(solver).__name__,
        "n_fields": len(leaves),
        "tuple_state": isinstance(state, (tuple, list)),
        "sharding": str(spec) if spec is not None else None,
    })
    return SimState(arrays=arrays, step=int(step), dt=float(dt),
                    sim_time=float(sim_time), rng=rng,
                    plan_fingerprint=plan_fingerprint(plan),
                    wisdom=wisdom_provenance(plan), meta=meta_out)


def restore(sim: SimState, solver: Any) -> StateTree:
    """Re-place a validated :class:`SimState` onto the devices in the
    CURRENT plan's spectral sharding; returns the solver-shaped state
    pytree (tuple for multi-field solvers). Raises ``ValueError`` when
    the checkpoint's field count disagrees with what it recorded —
    format-level corruption is already excluded by the checksum pass,
    so this only fires on a hand-edited header."""
    import jax
    n = int(sim.meta.get("n_fields", len(sim.arrays)))
    names = [_FIELD.format(i) for i in range(n)]
    missing = [nm for nm in names if nm not in sim.arrays]
    if missing:
        raise ValueError(f"checkpoint meta claims {n} field(s) but "
                         f"sections {missing} are absent")
    sharding = getattr(solver.plan, "output_sharding", None)
    leaves = []
    for nm in names:
        host = sim.arrays[nm]
        if sharding is not None:
            leaves.append(jax.device_put(host, sharding))
        else:
            leaves.append(jax.device_put(host))
    if sim.meta.get("tuple_state", n > 1):
        return tuple(leaves)
    return leaves[0]
