"""When to checkpoint: the ``CheckpointPolicy`` every persistence
surface resolves.

A resident simulation wants three triggers, composable:

* **every-N-steps** — bounded re-computation after a crash (the
  replacement worker re-runs at most N-1 steps);
* **every-T-seconds** — bounded wall-clock loss for slow-stepping runs;
* **on-drain** — the graceful-shutdown path (SIGTERM / fleet
  scale-down / ``Server.close(drain=True)``) writes a final generation
  so a PLANNED restart resumes at the exact step it stopped.

Spec grammar (CLI ``--checkpoint-policy`` / ``$DFFT_CKPT_POLICY``),
strict like the fault-spec parser — a policy that silently parsed as
"never checkpoint" would vacuously pass every durability drill::

    steps:N[,secs:T][,drain:on|off]

    steps:10             # every 10 steps (+ the default drain:on)
    secs:30              # every 30 s
    steps:50,secs:60     # whichever comes first
    drain:off            # only explicit saves

Empty/unset resolves to the default: periodic triggers off,
``drain:on``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class CheckpointPolicy:
    """Resolved checkpoint cadence (see module docstring)."""

    every_steps: Optional[int] = None
    every_s: Optional[float] = None
    on_drain: bool = True

    @classmethod
    def parse(cls, spec: Optional[str]) -> "CheckpointPolicy":
        """Parse the strict grammar above; ``None``/empty -> default.
        Raises ``ValueError`` on anything malformed."""
        if spec is None or not str(spec).strip():
            return cls()
        every_steps: Optional[int] = None
        every_s: Optional[float] = None
        on_drain = True
        seen = set()
        for tok in str(spec).split(","):
            tok = tok.strip()
            if not tok:
                raise ValueError(
                    f"empty element in checkpoint policy {spec!r}")
            key, sep, val = tok.partition(":")
            key = key.strip().lower()
            if not sep or key in seen:
                raise ValueError(
                    f"checkpoint policy wants unique key:value tokens "
                    f"(steps:N, secs:T, drain:on|off), got {tok!r}")
            seen.add(key)
            if key == "steps":
                every_steps = int(val)
                if every_steps < 1:
                    raise ValueError(f"steps must be >= 1, got {val!r}")
            elif key == "secs":
                every_s = float(val)
                if every_s <= 0:
                    raise ValueError(f"secs must be > 0, got {val!r}")
            elif key == "drain":
                v = val.strip().lower()
                if v not in ("on", "off"):
                    raise ValueError(f"drain wants on|off, got {val!r}")
                on_drain = v == "on"
            else:
                raise ValueError(f"unknown checkpoint-policy key {key!r} "
                                 "(choose from steps, secs, drain)")
        return cls(every_steps, every_s, on_drain)

    def __str__(self) -> str:  # round-trips through parse
        toks = []
        if self.every_steps is not None:
            toks.append(f"steps:{self.every_steps}")
        if self.every_s is not None:
            toks.append(f"secs:{self.every_s:g}")
        toks.append(f"drain:{'on' if self.on_drain else 'off'}")
        return ",".join(toks)

    def due(self, step: int, last_step: int, last_time: float,
            now: float) -> Optional[str]:
        """Why a checkpoint is due at ``step``/``now`` given the last
        save's step/time, or ``None`` — the reason string lands in the
        ``persist.checkpoint`` event so a log reader knows which trigger
        fired."""
        if (self.every_steps is not None
                and step - last_step >= self.every_steps):
            return f"steps:{self.every_steps}"
        if self.every_s is not None and now - last_time >= self.every_s:
            return f"secs:{self.every_s:g}"
        return None

    def describe_next(self, step: int, last_step: int, last_time: float,
                      now: float) -> str:
        """Human line for ``dfft-explain``: the next scheduled write
        under this policy from the given save bookkeeping."""
        parts = []
        if self.every_steps is not None:
            nxt = last_step + self.every_steps
            parts.append(f"at step {nxt} "
                         f"({max(0, nxt - step)} step(s) away)")
        if self.every_s is not None:
            left = max(0.0, last_time + self.every_s - now)
            parts.append(f"in {left:.1f} s")
        if not parts:
            return ("on drain only" if self.on_drain
                    else "never (drain:off, no periodic trigger)")
        return (" / ".join(parts)
                + (", plus on drain" if self.on_drain else ""))
