"""Real-to-real transforms (DCT / DST, types I-III) via the R2C machinery.

Non-periodic boundary conditions live in the cosine/sine bases; this
module exposes them scipy-compatibly (``scipy.fft.dct/dst`` conventions,
``norm=None`` and ``"ortho"``) while routing every flop through the
repo's local R2C layer (``ops/fft.py``), so a DCT inherits whichever
backend the caller picked — XLA, the MXU matmul family, or Bluestein for
extension lengths that fall off the smooth fast path.

The construction is the classic even/odd EXTENSION + TWIDDLE
post-processing:

* DCT-II: y = [x, flip x] (the half-sample-symmetric extension, length
  2n) -> ``rfft`` -> ``C[k] = Re(e^{-iπk/2n} Y[k])``;
* DST-II: y = [x, -flip x] -> ``rfft`` -> ``S[k] = -Im(e^{-iπ(k+1)/2n}
  Y[k+1])``;
* DCT-I / DST-I: the whole-sample extensions (lengths 2(n-1) / 2(n+1)),
  no twiddle (their spectra are already real / imaginary);
* type III = the transpose of type II: reconstruct the extension
  spectrum from the coefficients (the same twiddles, conjugated),
  ``irfft``, and read the first n samples.

The same identities power the Poisson solver's Dirichlet/Neumann boxes
(``solvers/poisson.py bc=...``) — there the twiddle extraction is
unnecessary because the solve is diagonal in the extended FFT basis;
here it is exactly what converts FFT bins into the scipy-layout R2R
coefficients.

These are LOCAL (per-shard / host-array) transforms — axis-wise jnp
functions that compose under jit/vmap/grad — not distributed plans: a
distributed non-periodic solve goes through a plan built at the extended
size (see ``PoissonSolver``). ``dctn``/``dstn`` apply along several axes.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Sequence

import numpy as np

import jax.numpy as jnp

from ..ops import fft as lf
from ..params import FFTNorm

_NORMS = (None, "ortho")


def _check(x, type: int, norm: Optional[str], kinds=(1, 2, 3)) -> None:
    if type not in kinds:
        raise ValueError(f"transform type must be one of {kinds}, got {type}")
    if norm not in _NORMS:
        raise ValueError(f"norm must be None or 'ortho', got {norm!r}")
    if jnp.iscomplexobj(x):
        raise TypeError("R2R transforms take real input")


def _dbl(x) -> bool:
    return jnp.dtype(x.dtype) == jnp.dtype(np.float64)


@functools.lru_cache(maxsize=None)
def _twiddle_np(n: int, double: bool, shift: int = 0) -> np.ndarray:
    """e^{-iπ(k+shift)/(2n)}, k in [0, n) — the half-sample phase that
    aligns the length-2n extension spectrum with the DCT/DST layout."""
    dt = np.complex128 if double else np.complex64
    k = np.arange(n, dtype=np.float64) + shift
    return np.exp(-1j * np.pi * k / (2 * n)).astype(dt)


def _rfft(y, backend: str):
    return lf.rfft(y, axis=-1, norm=FFTNorm.NONE, backend=backend)


def _irfft(Y, n: int, backend: str):
    return lf.irfft(Y, n=n, axis=-1, norm=FFTNorm.BACKWARD, backend=backend)


# ---------------------------------------------------------------------------
# forward transforms along the LAST axis (norm=None scipy conventions)
# ---------------------------------------------------------------------------


def _dct2_last(x, backend: str):
    n = x.shape[-1]
    ext = jnp.concatenate([x, jnp.flip(x, axis=-1)], axis=-1)
    Y = _rfft(ext, backend)[..., :n]
    tw = jnp.asarray(_twiddle_np(n, _dbl(x)))
    return jnp.real(tw * Y)


def _dst2_last(x, backend: str):
    n = x.shape[-1]
    ext = jnp.concatenate([x, -jnp.flip(x, axis=-1)], axis=-1)
    Y = _rfft(ext, backend)[..., 1: n + 1]
    tw = jnp.asarray(_twiddle_np(n, _dbl(x), shift=1))
    return -jnp.imag(tw * Y)


def _dct1_last(x, backend: str):
    n = x.shape[-1]
    if n < 2:
        raise ValueError("DCT-I needs n >= 2")
    ext = jnp.concatenate([x, jnp.flip(x[..., 1:-1], axis=-1)], axis=-1)
    return jnp.real(_rfft(ext, backend))[..., :n]


def _dst1_last(x, backend: str):
    n = x.shape[-1]
    z = jnp.zeros(x.shape[:-1] + (1,), dtype=x.dtype)
    ext = jnp.concatenate([z, x, z, -jnp.flip(x, axis=-1)], axis=-1)
    return -jnp.imag(_rfft(ext, backend))[..., 1: n + 1]


def _dct3_last(x, backend: str):
    """Type III = 2n * (type-II inverse): rebuild the extension spectrum
    Y[k] = conj(tw)[k] * x_k (Y[n] = 0 — the half-sample-symmetric class
    has no Nyquist energy), irfft, read the first n samples."""
    n = x.shape[-1]
    dbl = _dbl(x)
    cdt = np.complex128 if dbl else np.complex64
    tw = jnp.asarray(np.conj(_twiddle_np(n, dbl)))
    Y = x.astype(cdt) * tw
    Y = jnp.concatenate([Y, jnp.zeros(Y.shape[:-1] + (1,), dtype=Y.dtype)],
                        axis=-1)
    ext = _irfft(Y, 2 * n, backend)
    return 2 * n * ext[..., :n]


def _dst3_last(x, backend: str):
    """Type III = 2n * (type-II inverse): Y[m] = -i conj(tw)[m] x_{m-1}
    for m in [1, n], Y[0] = 0 (an odd extension has zero mean)."""
    n = x.shape[-1]
    dbl = _dbl(x)
    cdt = np.complex128 if dbl else np.complex64
    tw = jnp.asarray(np.conj(_twiddle_np(n, dbl, shift=1)))
    Y = -1j * tw * x.astype(cdt)
    Y = jnp.concatenate([jnp.zeros(Y.shape[:-1] + (1,), dtype=Y.dtype), Y],
                        axis=-1)
    ext = _irfft(Y, 2 * n, backend)
    return 2 * n * ext[..., :n]


# ---------------------------------------------------------------------------
# ortho scalings (scipy conventions; orthonormal matrices, so type III
# ortho is exactly the inverse of type II ortho)
# ---------------------------------------------------------------------------


def _ortho_post_2(y, kind: str):
    """Post-scale a norm=None type-II result to ortho: sqrt(1/(2n))
    everywhere except the distinguished element (k=0 for DCT, k=n-1 for
    DST) at sqrt(1/(4n))."""
    n = y.shape[-1]
    f = np.full(n, math.sqrt(1.0 / (2 * n)))
    f[0 if kind == "dct" else n - 1] = math.sqrt(1.0 / (4 * n))
    return y * jnp.asarray(f.astype("float64" if _dbl(y) else "float32"))


def _ortho_pre_3(x, kind: str):
    """Pre-scale type-III ortho input: the transpose of ``_ortho_post_2``
    composed with the g-diagonal relating type III to the type-II
    transpose (distinguished element carries 2*sqrt(1/(4n)) =
    sqrt(1/n))."""
    n = x.shape[-1]
    f = np.full(n, math.sqrt(1.0 / (2 * n)))
    f[0 if kind == "dct" else n - 1] = math.sqrt(1.0 / n)
    return x * jnp.asarray(f.astype("float64" if _dbl(x) else "float32"))


# ---------------------------------------------------------------------------
# public API (scipy.fft signatures, + backend)
# ---------------------------------------------------------------------------


def dct(x, type: int = 2, axis: int = -1, norm: Optional[str] = None,
        backend: str = "xla"):
    """Discrete cosine transform (types 1-3, scipy conventions). ``norm``
    is None (unnormalized) or "ortho"; ``backend`` picks the local R2C
    implementation (``ops/fft.py``)."""
    _check(x, type, norm)
    if type == 1 and norm == "ortho":
        raise NotImplementedError("ortho-normalized DCT-I is not provided "
                                  "(types 2/3 cover the solver suite)")
    y = jnp.moveaxis(jnp.asarray(x), axis, -1)
    if type == 1:
        out = _dct1_last(y, backend)
    elif type == 2:
        out = _dct2_last(y, backend)
        if norm == "ortho":
            out = _ortho_post_2(out, "dct")
    else:
        out = _dct3_last(_ortho_pre_3(y, "dct"), backend) if norm == "ortho" \
            else _dct3_last(y, backend)
    return jnp.moveaxis(out, -1, axis)


def dst(x, type: int = 2, axis: int = -1, norm: Optional[str] = None,
        backend: str = "xla"):
    """Discrete sine transform (types 1-3, scipy conventions)."""
    _check(x, type, norm)
    if type == 1 and norm == "ortho":
        raise NotImplementedError("ortho-normalized DST-I is not provided")
    y = jnp.moveaxis(jnp.asarray(x), axis, -1)
    if type == 1:
        out = _dst1_last(y, backend)
    elif type == 2:
        out = _dst2_last(y, backend)
        if norm == "ortho":
            out = _ortho_post_2(out, "dst")
    else:
        out = _dst3_last(_ortho_pre_3(y, "dst"), backend) if norm == "ortho" \
            else _dst3_last(y, backend)
    return jnp.moveaxis(out, -1, axis)


def idct(x, type: int = 2, axis: int = -1, norm: Optional[str] = None,
         backend: str = "xla"):
    """Inverse DCT (scipy ``idct``): the ortho family is self-inverse via
    the transpose; norm=None divides by the roundtrip factor (2n for
    types 2/3, 2(n-1) for type 1)."""
    _check(x, type, norm)
    n = jnp.asarray(x).shape[axis]
    inv_type = {1: 1, 2: 3, 3: 2}[type]
    y = dct(x, type=inv_type, axis=axis, norm=norm, backend=backend)
    if norm is None:
        y = y / (2.0 * (n - 1) if type == 1 else 2.0 * n)
    return y


def idst(x, type: int = 2, axis: int = -1, norm: Optional[str] = None,
         backend: str = "xla"):
    """Inverse DST (scipy ``idst``)."""
    _check(x, type, norm)
    n = jnp.asarray(x).shape[axis]
    inv_type = {1: 1, 2: 3, 3: 2}[type]
    y = dst(x, type=inv_type, axis=axis, norm=norm, backend=backend)
    if norm is None:
        y = y / (2.0 * (n + 1) if type == 1 else 2.0 * n)
    return y


def dctn(x, type: int = 2, axes: Optional[Sequence[int]] = None,
         norm: Optional[str] = None, backend: str = "xla"):
    """Separable multi-axis DCT (scipy ``dctn``)."""
    if axes is None:
        axes = range(jnp.asarray(x).ndim)
    for a in axes:
        x = dct(x, type=type, axis=a, norm=norm, backend=backend)
    return x


def dstn(x, type: int = 2, axes: Optional[Sequence[int]] = None,
         norm: Optional[str] = None, backend: str = "xla"):
    """Separable multi-axis DST (scipy ``dstn``)."""
    if axes is None:
        axes = range(jnp.asarray(x).ndim)
    for a in axes:
        x = dst(x, type=type, axis=a, norm=norm, backend=backend)
    return x
