"""``dfft-solve`` — a pseudo-spectral solver run as a standalone,
SIGTERM-drainable, crash-resumable process (ROADMAP item 5c).

The executable half of the durability contract: run a Navier–Stokes
simulation with crash-consistent checkpointing
(``distributedfft_tpu/persist``), drain a final generation on
SIGTERM/SIGINT, and ``--resume`` a later invocation from the newest
valid generation — continuing **bit-exactly**. The CI ``resume`` chaos
scenario is exactly this binary: SIGTERM a run at step k, ``--resume``
to step n, ``cmp`` the ``--out`` field byte-for-byte against an
uninterrupted n-step run (on batched2d AND slab plans on the 8-device
CPU mesh).

The stepping engine is the serve layer's :class:`ResidentSolver`
(``serve/resident.py``) — one jitted step function applied stepwise,
never a ``lax.scan`` whose length would differ across a resume — so
``dfft-solve`` and a ``dfft-serve`` resident share one durability path
and one bit-exactness argument.

Examples::

    dfft-solve --kind ns2d --n 64 --steps 200 --emulate-devices 8 -p 8 \
        --shard x --checkpoint-dir /tmp/ck --checkpoint-policy steps:10
    dfft-solve --kind ns3d --n 32 --steps 100 --emulate-devices 8 -p 8 \
        --checkpoint-dir /tmp/ck3 --resume --out final.npy
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="dfft-solve", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--kind", default="ns2d", choices=("ns2d", "ns3d"),
                    help="ns2d: vorticity NS on a batched-2D plan; "
                         "ns3d: rotational NS on a slab plan")
    ap.add_argument("--n", type=int, default=32,
                    help="grid extent per transformed axis")
    ap.add_argument("--batch", type=int, default=1,
                    help="ns2d ensemble size (independent flows)")
    ap.add_argument("--partitions", "-p", type=int, default=1,
                    help="mesh width the plan decomposes over")
    ap.add_argument("--shard", default="batch", choices=("batch", "x"),
                    help="ns2d decomposition: 'x' exercises a real "
                         "exchange (the resume drill uses it)")
    ap.add_argument("--steps", type=int, default=50,
                    help="target step count (resume continues toward "
                         "the SAME target)")
    ap.add_argument("--dt", type=float, default=1e-3)
    ap.add_argument("--viscosity", type=float, default=1e-2)
    ap.add_argument("--double", "-d", action="store_true",
                    help="f64 state (enables jax x64)")
    ap.add_argument("--fft-backend", default="xla")
    ap.add_argument("--step-interval-ms", type=float, default=0.0,
                    help="pause between steps (chaos drills use this to "
                         "widen the SIGTERM window)")
    ap.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                    help="two-generation checkpoint store (same as "
                         "$DFFT_CKPT_DIR; unset = no durability)")
    ap.add_argument("--checkpoint-policy", default=None,
                    metavar="steps:N[,secs:T][,drain:on|off]",
                    help="checkpoint cadence (same as $DFFT_CKPT_POLICY; "
                         "default drain-only)")
    ap.add_argument("--resume", action="store_true",
                    help="REQUIRE a restorable checkpoint and continue "
                         "from it (refuses to start fresh)")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="write the final PHYSICAL field as .npy (the "
                         "bit-exact comparison artifact)")
    ap.add_argument("--seed", type=int, default=0,
                    help="recorded as the RNG/forcing phase provenance")
    ap.add_argument("--emulate-devices", type=int,
                    default=int(os.environ.get("DFFT_EMULATE_DEVICES",
                                               "0")))
    ap.add_argument("--obs", action="store_true")
    ap.add_argument("--obs-dir", default=None, metavar="DIR")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from .. import obs
    if args.obs_dir:
        obs.enable(args.obs_dir)
    if args.obs:
        obs.enable_console()
    if args.emulate_devices:
        from ..parallel.mesh import force_cpu_devices
        force_cpu_devices(args.emulate_devices)
    import jax
    if args.double:
        jax.config.update("jax_enable_x64", True)

    import numpy as np

    from .. import persist
    from ..serve.resident import ResidentSolver

    try:
        ckdir, policy = persist.resolve_env(args.checkpoint_dir,
                                            args.checkpoint_policy)
    except ValueError as e:
        raise SystemExit(f"--checkpoint-policy: {e}") from None
    if args.resume and not ckdir:
        raise SystemExit("--resume needs --checkpoint-dir (or "
                         f"${persist.ENV_DIR})")
    spec = {"kind": args.kind, "n": args.n, "batch": args.batch,
            "partitions": args.partitions, "shard": args.shard,
            "double": args.double, "fft_backend": args.fft_backend,
            "viscosity": args.viscosity, "dt": args.dt,
            "dir": ckdir,
            "policy": policy, "rng": {"seed": args.seed, "draws": 0},
            "step_interval_ms": args.step_interval_ms,
            "max_steps": args.steps, "name": "dfft-solve"}
    try:
        res = ResidentSolver.build(spec)
    except persist.CheckpointMismatch as e:
        # The documented operator error (this dir belongs to a
        # differently-configured run): a usage message, not a traceback.
        raise SystemExit(f"dfft-solve: checkpoint in {ckdir} was written "
                         f"by a different configuration — {e}") from None
    if args.resume and res.restored_from is None:
        raise SystemExit(f"--resume: no restorable checkpoint in {ckdir}")

    stop = threading.Event()

    def _graceful(signum, frame):  # noqa: ARG001 — signal contract
        print(f"dfft-solve: signal {signum} -> drain checkpoint + exit",
              flush=True)
        stop.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)

    interrupted = False
    res.start()
    while res.step < args.steps:
        if stop.wait(0.02):
            interrupted = True
            break
        if not res.running:  # cheap liveness — no store I/O in the poll
            break
    # stop() drains through the policy's on-drain checkpoint — the
    # SIGTERM contract: durable state lands BEFORE the process exits 0.
    res.stop(checkpoint=True)

    out_path = None
    if args.out and not interrupted:
        phys = np.asarray(res.solver.to_physical(res.state))
        np.save(args.out, phys, allow_pickle=False)
        out_path = args.out
    summary = {"kind": args.kind, "n": args.n, "steps_target": args.steps,
               "step": res.step, "restored_from": res.restored_from,
               "checkpoints": res.checkpoints,
               "interrupted": interrupted, "error": res.error,
               "sim_time": round(res.sim_time, 9), "out": out_path}
    print(json.dumps(summary, sort_keys=True), flush=True)
    if args.obs:
        print("obs metrics: "
              + json.dumps(obs.metrics.snapshot(), sort_keys=True))
    # A stepping-thread failure is a loud failure: the run did NOT reach
    # its target and no later checkpoint will land.
    return 0 if res.error is None else 1


if __name__ == "__main__":
    sys.exit(main())
