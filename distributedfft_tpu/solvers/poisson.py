"""FFT-diagonalized Poisson solver — BASELINE config #5
("3D Poisson solve (FFT-diagonalized Laplacian) 2048^3").

Solves the periodic Poisson problem  ∇²u = f  by forward transform, division
by the Laplacian symbol, inverse transform — the user-facing version of the
reference's testcase-4 Laplacian validation (its ``derivativeCoefficients``
kernel, ``tests/src/slab/random_dist_default.cu:71-119``, applies exactly
this operator forward).

The whole solve (symbol multiply included) runs in the plan's distributed
spectral layout: the symbol is precomputed on the PADDED spectral grid and
device_put with the plan's output sharding, so applying it is one fused
elementwise multiply per shard, with no re-distribution beyond the plan's
own transposes.

Two wavenumber conventions:

* ``mode="physical"``: k_i = 2π m_i / L_i with numpy fftfreq folding — the
  PDE-correct symbol for a box of side lengths ``lengths``.
* ``mode="integer"``: the reference's convention (integer wavenumbers,
  Nyquist zeroed) for bit-compatible comparisons with testcase 4.

The k = 0 mode is set to zero (zero-mean gauge, the standard periodic
compatibility condition).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import params as pm
from ..models.slab import SlabFFTPlan


def _axis_freqs(n: int, ext: int, halved: bool, integer_mode: bool) -> np.ndarray:
    """Folded wavenumber per spectral index along one axis, zero in pad
    lanes (ext >= logical spectral extent).

    integer mode replicates the reference kernel's fold exactly
    (``random_dist_default.cu:80-88``): k = i for i < n//2, k = n - i for
    i > n//2, and 0 at i == n//2 (Nyquist, also for odd n). physical mode
    uses the numpy fftfreq fold (Nyquist kept), the PDE-correct symbol."""
    k = np.zeros(ext)
    if halved:
        m = np.arange(n // 2 + 1, dtype=np.float64)
        if integer_mode:
            m[n // 2] = 0.0
        k[: n // 2 + 1] = m
    else:
        if integer_mode:
            m = np.zeros(n)
            for i in range(n):
                if i < n // 2:
                    m[i] = i
                elif i > n // 2:
                    m[i] = n - i
        else:
            m = np.fft.fftfreq(n) * n
        k[:n] = m
    return k


class PoissonSolver:
    """Periodic Poisson solve on top of a distributed FFT plan."""

    def __init__(self, plan, lengths: Optional[Sequence[float]] = None,
                 mode: str = "physical"):
        if mode not in ("physical", "integer"):
            raise ValueError(f"mode must be 'physical' or 'integer', got {mode!r}")
        self.plan = plan
        self.mode = mode
        g = plan.global_size
        if lengths is None:
            lengths = (2 * np.pi,) * 3
        self.lengths = tuple(float(v) for v in lengths)

        shape = plan.output_padded_shape
        halved_axis = self._halved_axis()
        dims = [g.nx, g.ny, g.nz]
        rt, _ = _plan_dtypes(plan)
        ks = []
        for ax in range(3):
            k = _axis_freqs(dims[ax], shape[ax], ax == halved_axis,
                            mode == "integer")
            if mode == "physical":
                k = k * (2 * np.pi / self.lengths[ax])
            ks.append(k.astype(rt))
        # Only the three 1D wavenumber vectors are stored; the dense symbol
        # is formed by broadcasting inside the jitted apply, so each device
        # materializes (at most) its own shard — at the module's 2048^3
        # target a host-side dense cube would be tens of GB.
        self._ks = ks
        # Fold the round-trip normalization into the symbol so the solve is
        # exactly: inverse(forward(f) * symbol).
        self._scale = (1.0 / g.n_total
                       if plan.config.norm is pm.FFTNorm.NONE else 1.0)
        self._apply = None
        self._solve_pure = None

    def _halved_axis(self) -> int:
        plan = self.plan
        if getattr(plan, "transform", "r2c") == "c2c":
            return -1  # no halved axis
        if isinstance(plan, SlabFFTPlan) and plan._seq.halved == "y":
            return 1
        return 2

    def _apply_pure(self):
        """The spectral symbol multiply as a pure function (shared by the
        jitted apply and ``solve_fn``)."""
        k1, k2, k3 = (jnp.asarray(k) for k in self._ks)
        scale = self._scale

        def apply(c):
            k2sum = (k1[:, None, None] ** 2 + k2[None, :, None] ** 2
                     + k3[None, None, :] ** 2)
            inv = jnp.where(k2sum > 0,
                            -scale / jnp.where(k2sum > 0, k2sum, 1.0), 0.0)
            return c * inv.astype(c.real.dtype)

        return apply

    def _build_apply(self):
        plan = self.plan
        apply = self._apply_pure()
        if plan.mesh is not None:
            ns = plan.output_sharding
            return jax.jit(apply, in_shardings=ns, out_shardings=ns)
        return jax.jit(apply)

    def solve_fn(self):
        """Pure solve pipeline (forward -> symbol multiply -> inverse) with
        no jit and no sharding annotations: composes under user transforms,
        so ``jax.grad`` flows through the full distributed spectral solve
        (see ``DistFFTPlan.forward_fn`` and tests/test_autodiff.py). Uses
        the plan's transform family automatically (r2c or c2c)."""
        if self._solve_pure is None:
            plan = self.plan
            fwd, inv = plan.forward_fn(), plan.inverse_fn()
            apply = self._apply_pure()

            def fn(f):
                return inv(apply(fwd(f)))

            self._solve_pure = fn
        return self._solve_pure

    def solve(self, f):
        """u with ∇²u = f (periodic, zero-mean). Accepts logical or padded
        global shape; returns the plan's padded real-space array (crop with
        ``plan.crop_real``)."""
        plan = self.plan
        if self._apply is None:
            self._apply = self._build_apply()
        if getattr(plan, "transform", "r2c") == "c2c":
            c = plan.exec_c2c(f)
            c = self._apply(c)
            return plan.exec_c2c_inv(c)
        c = plan.exec_r2c(f)
        c = self._apply(c)
        return plan.exec_c2r(c)


def _plan_dtypes(plan) -> Tuple[np.dtype, np.dtype]:
    from ..ops.fft import dtypes_for
    return dtypes_for(plan.config.double_prec)
