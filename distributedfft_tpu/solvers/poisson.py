"""FFT-diagonalized Poisson solver — BASELINE config #5
("3D Poisson solve (FFT-diagonalized Laplacian) 2048^3").

Solves the Poisson problem  ∇²u = f  by forward transform, division by the
Laplacian symbol, inverse transform — the user-facing version of the
reference's testcase-4 Laplacian validation (its ``derivativeCoefficients``
kernel, ``tests/src/slab/random_dist_default.cu:71-119``, applies exactly
this operator forward).

The solver drives the plan through the solver protocol of
``models/base.py`` (``exec_fwd``/``exec_inv``, ``transform_axes``,
``spectral_halved_axis``), so it runs unchanged on every plan family:
slab (any sequence), pencil, and the batched-2D plan — there the batch
axis is a pure broadcast dimension and each plane is an independent 2D
Poisson solve. The whole solve (symbol multiply included) runs in the
plan's distributed spectral layout: the symbol is broadcast from 1D
wavenumber vectors on the PADDED spectral grid inside the jitted apply
(with the plan's output sharding), so applying it is one fused
elementwise multiply per shard, with no re-distribution beyond the
plan's own transposes.

Two wavenumber conventions:

* ``mode="physical"``: k_i = 2π m_i / L_i with numpy fftfreq folding — the
  PDE-correct symbol for a box of side lengths ``lengths``.
* ``mode="integer"``: the reference's convention (integer wavenumbers,
  Nyquist zeroed) for bit-compatible comparisons with testcase 4.

Boundary conditions (``bc``, the R2R upgrade — see ``solvers/r2r.py``
for the underlying extension identities):

* ``"periodic"`` (default): the classic periodic box; the k = 0 mode is
  set to zero (zero-mean gauge, the standard compatibility condition).
* ``"dirichlet"`` — homogeneous u = 0 walls on the staggered grid
  x_j = (j + 1/2) L / n: the input is ODD-extended along the axis
  (period 2L, the DST-II extension) before the plan's transform, and the
  folded wavenumbers become k_m = π m / L. The extension makes the FFT
  spectrum live entirely in the sine basis, so the diagonal symbol
  divide IS the DST-space solve — no twiddle extraction needed.
* ``"neumann"`` — homogeneous ∂u/∂n = 0 walls, the EVEN (DCT-II)
  extension, same folded k_m = π m / L.

Per-axis mixing is supported (``bc=("dirichlet", "periodic",
"neumann")``); non-periodic axes require the PLAN to be built at the
EXTENDED extent (2n for an interior of n — ``interior_shape`` reports
the solve domain) and ``solve`` then takes/returns interior-shaped
arrays. A plan whose non-periodic axis is odd cannot host the extension
and is rejected at construction.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import params as pm

_BCS = ("periodic", "dirichlet", "neumann")


def _axis_freqs(n: int, ext: int, halved: bool, integer_mode: bool) -> np.ndarray:
    """Folded wavenumber per spectral index along one PERIODIC axis, zero
    in pad lanes (ext >= logical spectral extent).

    integer mode replicates the reference kernel's fold exactly
    (``random_dist_default.cu:80-88``): k = i for i < n//2, k = n - i for
    i > n//2, and 0 at i == n//2 (Nyquist, also for odd n). physical mode
    uses the numpy fftfreq fold (Nyquist kept), the PDE-correct symbol."""
    k = np.zeros(ext)
    if halved:
        m = np.arange(n // 2 + 1, dtype=np.float64)
        if integer_mode:
            m[n // 2] = 0.0
        k[: n // 2 + 1] = m
    else:
        if integer_mode:
            m = np.zeros(n)
            for i in range(n):
                if i < n // 2:
                    m[i] = i
                elif i > n // 2:
                    m[i] = n - i
        else:
            m = np.fft.fftfreq(n) * n
        k[:n] = m
    return k


def _extension_freqs(n_ext: int, ext: int, halved: bool) -> np.ndarray:
    """Folded HALF-integer-grid wavenumber index for a DCT/DST-extended
    axis: the plan transforms the period-2L extension of length
    ``n_ext = 2n``, whose FFT bin m carries the cosine/sine mode
    ``fold(m) = min(m, n_ext - m)`` at k = π·fold(m)/L. (The symbol must
    be symmetric under m <-> n_ext - m to preserve the extension's
    symmetry class — a fold, not a signed fftfreq.) Zero in pad lanes."""
    k = np.zeros(ext)
    cnt = n_ext // 2 + 1 if halved else n_ext
    m = np.arange(cnt, dtype=np.float64)
    k[:cnt] = np.minimum(m, n_ext - m)
    return k


def _parse_bc(bc, axes: Tuple[int, ...], ndim: int = 3):
    """Per-array-axis bc tuple from a scalar or per-axis sequence; axes
    outside ``axes`` (the batch axis of a batched-2D plan) must stay
    periodic (they are not transformed at all)."""
    if isinstance(bc, str):
        per = ["periodic"] * ndim
        for a in axes:
            per[a] = bc
    else:
        per = [str(b) for b in bc]
        if len(per) != ndim:
            raise ValueError(f"bc must be a string or a length-{ndim} "
                             f"sequence, got {bc!r}")
    for a, b in enumerate(per):
        if b not in _BCS:
            raise ValueError(f"unknown bc {b!r} (choose from {_BCS})")
        if b != "periodic" and a not in axes:
            raise ValueError(f"axis {a} is not transformed by this plan "
                             f"(transform_axes={axes}); only 'periodic' "
                             "is meaningful there")
    return tuple(per)


class PoissonSolver:
    """Poisson solve on top of any distributed FFT plan family."""

    def __init__(self, plan, lengths: Optional[Sequence[float]] = None,
                 mode: str = "physical", bc="periodic"):
        if mode not in ("physical", "integer"):
            raise ValueError(f"mode must be 'physical' or 'integer', got {mode!r}")
        self.plan = plan
        self.mode = mode
        axes = tuple(plan.transform_axes)
        dims = tuple(int(n) for n in plan.input_shape)
        self.bc = _parse_bc(bc, axes, len(dims))
        if mode == "integer" and any(b != "periodic" for b in self.bc):
            raise ValueError("mode='integer' is the reference's periodic "
                             "testcase convention; non-periodic boxes use "
                             "mode='physical'")
        for a, b in enumerate(self.bc):
            if b != "periodic" and dims[a] % 2:
                raise ValueError(
                    f"axis {a} has bc={b!r}: the plan must be built at the "
                    f"even EXTENDED extent 2n (got {dims[a]}) — the solver "
                    "odd/even-extends an interior of n samples")
        if lengths is None:
            lengths = (2 * np.pi,) * len(dims)
        self.lengths = tuple(float(v) for v in lengths)

        shape = plan.output_padded_shape
        halved_axis = self._halved_axis()
        rt, _ = _plan_dtypes(plan)
        ks = []
        for ax in range(len(dims)):
            if ax not in axes:
                # Pure batch axis (batched-2D plans): the symbol is
                # constant along it — each plane solves independently.
                k = np.zeros(shape[ax])
            elif self.bc[ax] == "periodic":
                k = _axis_freqs(dims[ax], shape[ax], ax == halved_axis,
                                mode == "integer")
                if mode == "physical":
                    k = k * (2 * np.pi / self.lengths[ax])
            else:
                # Extended axis: plan length 2n over period 2L ->
                # k = (2π/2L)·fold(m) = π·fold(m)/L with L the INTERIOR
                # domain length.
                k = _extension_freqs(dims[ax], shape[ax],
                                     ax == halved_axis)
                k = k * (np.pi / self.lengths[ax])
            ks.append(k.astype(rt))
        # Only the 1D wavenumber vectors are stored; the dense symbol is
        # formed by broadcasting inside the jitted apply, so each device
        # materializes (at most) its own shard — at the module's 2048^3
        # target a host-side dense cube would be tens of GB.
        self._ks = ks
        # Fold the round-trip normalization into the symbol so the solve
        # is exactly: inverse(forward(f) * symbol). The transform volume
        # is ``plan.transform_size`` — the TRANSFORMED axes only (a
        # batched-2D plan's batch axis carries no 1/N).
        self._scale = (1.0 / float(plan.transform_size)
                       if plan.config.norm is pm.FFTNorm.NONE else 1.0)
        self._apply = None
        self._solve_pure = None

    # -- shapes ------------------------------------------------------------

    @property
    def interior_shape(self) -> Tuple[int, ...]:
        """The solve domain: the plan's logical shape with every
        non-periodic axis halved (the plan transforms the 2n extension of
        an n-sample interior). Equals ``plan.input_shape`` for the
        all-periodic box."""
        return tuple(n // 2 if b != "periodic" else n
                     for n, b in zip(self.plan.input_shape, self.bc))

    @property
    def _extended(self) -> bool:
        return any(b != "periodic" for b in self.bc)

    def _halved_axis(self) -> int:
        h = self.plan.spectral_halved_axis
        return -1 if h is None else h

    # -- the spectral symbol ----------------------------------------------

    def _apply_pure(self):
        """The spectral symbol multiply as a pure function (shared by the
        jitted apply and ``solve_fn``)."""
        ks = [jnp.asarray(k) for k in self._ks]
        scale = self._scale
        nd = len(ks)

        def apply(c):
            k2sum = None
            for ax, k in enumerate(ks):
                sl = [None] * nd
                sl[ax] = slice(None)
                term = k[tuple(sl)] ** 2
                k2sum = term if k2sum is None else k2sum + term
            inv = jnp.where(k2sum > 0,
                            -scale / jnp.where(k2sum > 0, k2sum, 1.0), 0.0)
            return c * inv.astype(c.real.dtype)

        return apply

    def _build_apply(self):
        plan = self.plan
        apply = self._apply_pure()
        if plan.mesh is not None:
            ns = plan.output_sharding
            return jax.jit(apply, in_shardings=ns, out_shardings=ns)
        return jax.jit(apply)

    # -- extension / restriction (the R2R boundary-condition machinery) ----

    def _extend(self, f):
        """Interior -> extension: odd ([x, -flip x], Dirichlet) or even
        ([x, flip x], Neumann) per non-periodic axis. Pure jnp, so the
        preamble differentiates (the vjp of concatenate+flip is
        slice+flip)."""
        for ax, b in enumerate(self.bc):
            if b == "periodic":
                continue
            mirror = jnp.flip(f, axis=ax)
            if b == "dirichlet":
                mirror = -mirror
            f = jnp.concatenate([f, mirror], axis=ax)
        return f

    def _restrict(self, u):
        """Extension (padded) -> interior slab."""
        sl = tuple(slice(0, n) for n in self.interior_shape)
        return u[sl]

    # -- execution ---------------------------------------------------------

    def solve_fn(self):
        """Pure solve pipeline (forward -> symbol multiply -> inverse) with
        no jit and no sharding annotations: composes under user transforms,
        so ``jax.grad`` flows through the full distributed spectral solve
        (see ``DistFFTPlan.forward_fn`` and tests/test_autodiff.py). Uses
        the plan's transform family automatically (r2c or c2c). For a
        non-periodic box the function maps interior -> interior (the
        odd/even extension and the restriction are traced in)."""
        if self._solve_pure is None:
            plan = self.plan
            fwd, inv = plan.forward_fn(), plan.inverse_fn()
            apply = self._apply_pure()
            if self._extended:
                ext, restrict = self._extend, self._restrict

                def fn(f):
                    return restrict(inv(apply(fwd(ext(f)))))
            else:
                def fn(f):
                    return inv(apply(fwd(f)))

            self._solve_pure = fn
        return self._solve_pure

    def solve(self, f):
        """u with ∇²u = f (under this solver's ``bc``). Periodic box:
        accepts the logical or padded global shape and returns the plan's
        padded real-space array (crop with ``plan.crop_real``) — the
        historical contract. Non-periodic box: takes the
        ``interior_shape`` forcing and returns the interior solution."""
        plan = self.plan
        if self._apply is None:
            self._apply = self._build_apply()
        if self._extended:
            if tuple(f.shape) != self.interior_shape:
                raise ValueError(
                    f"bc={self.bc}: solve expects the interior shape "
                    f"{self.interior_shape}, got {tuple(f.shape)}")
            f = self._extend(f)
        c = plan.exec_fwd(f)
        c = self._apply(c)
        u = plan.exec_inv(c)
        return self._restrict(u) if self._extended else u


def _plan_dtypes(plan) -> Tuple[np.dtype, np.dtype]:
    from ..ops.fft import dtypes_for
    return dtypes_for(plan.config.double_prec)
