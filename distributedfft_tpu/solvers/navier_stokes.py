"""Pseudo-spectral incompressible Navier-Stokes on the distributed core.

The flagship repeated-transform workload (ROADMAP item 4): every
right-hand-side evaluation is a burst of forward/inverse pairs through
the plan's distributed pipelines — exactly the serving layer's
steady-state traffic shape — and ``jit(grad)`` through an N-step solve
is the strongest correctness gate the repo can put on the pure
pipelines (``forward_fn``/``inverse_fn`` composing under ``lax.scan``
and reverse-mode AD, collectives included).

Two solvers, one per dimensionality, both driving plans through the
solver protocol of ``models/base.py``:

* :class:`NavierStokes2D` — vorticity form on a ``Batched2DFFTPlan``
  (the batch axis is an ENSEMBLE of independent flows, served by the
  same stacked execution the serve layer coalesces into):

      dω/dt + u·∇ω = ν ∇²ω,      u = ∂ψ/∂y, v = -∂ψ/∂x, ω = -∇²ψ.

  State lives in spectral space; each RHS is 4 inverse + 1 forward
  transforms (u, v, ∂ω/∂x, ∂ω/∂y out; the dealiased nonlinear term
  back).

* :class:`NavierStokes3D` — rotational (Lamb) velocity form on a slab
  or pencil plan:

      du/dt = u × ω - ∇Π + ν ∇²u,   ω = ∇ × u,   ∇·u = 0,

  with the pressure head Π eliminated by the spectral Leray projection
  P(k) = I - k kᵀ/k². Each RHS is 6 inverse + 3 forward transforms.

Both integrate with classic RK4 in spectral space and apply the 2/3-rule
dealiasing mask to the nonlinear term. The mask — like the Poisson
symbol — is built from 1D per-axis vectors on the plan's PADDED spectral
grid (zeros in pad lanes, so pad lanes stay exact zeros through every
step) and broadcast inside the jitted step: no dense mask cube is ever
materialized on the host, and applying it is one fused elementwise
multiply per shard in the plan's own spectral sharding — no
redistribution beyond the plan's transposes.

Everything is pure ``jnp`` on top of the plans' pure pipelines, so
``solve_fn(steps, dt)`` composes under ``jax.jit``, ``lax.scan`` and
``jax.grad`` end to end; use ``fft_backend="matmul"`` (or
``"bluestein"`` for non-smooth grids) for a differentiable local
transform (tests/test_autodiff.py rationale).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import params as pm


# ---------------------------------------------------------------------------
# shared spectral bookkeeping (pad-lane-aware, like the Poisson symbol)
# ---------------------------------------------------------------------------


def signed_wavenumbers(plan, lengths: Sequence[float]) -> List[np.ndarray]:
    """Per-array-axis SIGNED wavenumber vector k = 2π m / L on the plan's
    padded spectral grid (numpy fftfreq fold; the halved axis carries the
    non-negative half), zero in pad lanes and along pure batch axes."""
    from .poisson import _plan_dtypes
    shape = plan.output_padded_shape
    dims = plan.input_shape
    axes = tuple(plan.transform_axes)
    halved = plan.spectral_halved_axis
    rt, _ = _plan_dtypes(plan)
    ks = []
    for ax in range(len(dims)):
        k = np.zeros(shape[ax])
        if ax in axes:
            n = dims[ax]
            scale = 2 * np.pi / float(lengths[ax])
            if ax == halved:
                k[: n // 2 + 1] = np.arange(n // 2 + 1) * scale
            else:
                k[:n] = np.fft.fftfreq(n) * n * scale
        ks.append(k.astype(rt))
    return ks


def dealias_vectors(plan) -> List[np.ndarray]:
    """Per-array-axis 2/3-rule keep-mask vector on the padded spectral
    grid: 1.0 where the integer mode |m| <= n//3, 0.0 above (and in the
    pad lanes, so the mask doubles as the pad-lane scrubber); all-ones
    along pure batch axes except their pad lanes."""
    from .poisson import _plan_dtypes
    shape = plan.output_padded_shape
    dims = plan.input_shape
    axes = tuple(plan.transform_axes)
    halved = plan.spectral_halved_axis
    rt, _ = _plan_dtypes(plan)
    vecs = []
    for ax in range(len(dims)):
        v = np.zeros(shape[ax])
        n = dims[ax]
        if ax in axes:
            cut = n // 3
            if ax == halved:
                m = np.arange(n // 2 + 1, dtype=np.float64)
                v[: n // 2 + 1] = (m <= cut).astype(np.float64)
            else:
                m = np.abs(np.fft.fftfreq(n) * n)
                v[:n] = (m <= cut).astype(np.float64)
        else:
            v[:n] = 1.0  # batch axis: keep every logical plane
        vecs.append(v.astype(rt))
    return vecs


def _bcast(vec, axis: int, nd: int):
    sl = [None] * nd
    sl[axis] = slice(None)
    return jnp.asarray(vec)[tuple(sl)]


def _inv_roundtrip_scale(plan) -> float:
    """Scalar s making ``s * inverse(forward(x)) == x`` under the plan's
    norm — physical fields are always reconstructed through this, so the
    spectral representation is norm-agnostic."""
    if plan.config.norm is pm.FFTNorm.NONE:
        return 1.0 / float(plan.transform_size)
    return 1.0  # BACKWARD / ORTHO roundtrips are already the identity


def _rk4(rhs, w, dt: float):
    """One classic RK4 stage over an arbitrary pytree state."""
    k1 = rhs(w)
    k2 = rhs(jax.tree_util.tree_map(lambda a, b: a + 0.5 * dt * b, w, k1))
    k3 = rhs(jax.tree_util.tree_map(lambda a, b: a + 0.5 * dt * b, w, k2))
    k4 = rhs(jax.tree_util.tree_map(lambda a, b: a + dt * b, w, k3))

    def comb(a, b1, b2, b3, b4):
        return a + (dt / 6.0) * (b1 + 2.0 * b2 + 2.0 * b3 + b4)

    return jax.tree_util.tree_map(comb, w, k1, k2, k3, k4)


class _NSBase:
    """Shared plumbing: symbol construction, scan-based multi-step
    drivers, physical<->spectral entry/exit."""

    def __init__(self, plan, viscosity: float,
                 lengths: Optional[Sequence[float]] = None):
        self.plan = plan
        self.viscosity = float(viscosity)
        nd = len(plan.input_shape)
        if lengths is None:
            lengths = (2 * np.pi,) * nd
        if len(lengths) != nd:
            raise ValueError(f"lengths must have {nd} entries, got {lengths}")
        self.lengths = tuple(float(v) for v in lengths)
        self._ks = signed_wavenumbers(plan, self.lengths)
        self._mask_vecs = dealias_vectors(plan)
        self._s = _inv_roundtrip_scale(plan)
        self._nd = nd
        self._run_cache: dict = {}

    def _k(self, axis: int):
        return _bcast(self._ks[axis], axis, self._nd)

    def _mask(self, c):
        for ax, v in enumerate(self._mask_vecs):
            c = c * _bcast(v, ax, self._nd).astype(c.real.dtype)
        return c

    def _k2(self):
        out = None
        for ax in self.plan.transform_axes:
            t = self._k(ax) ** 2
            out = t if out is None else out + t
        return out

    def _inv_k2(self):
        k2 = self._k2()
        return jnp.where(k2 > 0, 1.0 / jnp.where(k2 > 0, k2, 1.0), 0.0)

    # subclasses: rhs(state) over spectral pytree state, to_spectral /
    # to_physical converting the user-facing array.

    def step_fn(self, dt: float):
        """Pure single-RK4-step function over the SPECTRAL state."""
        rhs = self.rhs_fn()

        def step(w):
            return _rk4(rhs, w, dt)

        return step

    def solve_fn(self, steps: int, dt: float):
        """Pure physical -> physical N-step integrator: forward once,
        ``lax.scan`` the RK4 step (one traced body regardless of
        ``steps``, and reverse-mode AD through scan gives the adjoint
        solver), inverse once. Composes under jit/grad — the repo's
        strongest autodiff gate."""
        if steps < 1:
            raise ValueError(f"steps must be >= 1, got {steps}")
        step = self.step_fn(dt)
        to_spec, to_phys = self.to_spectral, self.to_physical

        def fn(w0):
            wh = to_spec(w0)
            wh = jax.lax.scan(lambda c, _: (step(c), None), wh,
                              None, length=steps)[0]
            return to_phys(wh)

        return fn

    def run(self, w0, steps: int, dt: float):
        """Jitted convenience driver (physical in, physical out)."""
        if self._run_cache.get((steps, dt)) is None:
            self._run_cache[(steps, dt)] = jax.jit(self.solve_fn(steps, dt))
        return self._run_cache[(steps, dt)](w0)


class NavierStokes2D(_NSBase):
    """2D vorticity-form pseudo-spectral Navier-Stokes over a batched-2D
    plan: each batch plane is an independent flow (ensemble semantics).

    ``plan`` must transform exactly two axes (``Batched2DFFTPlan``; r2c
    or c2c). The spectral state is the vorticity spectrum on the plan's
    padded spectral grid."""

    def __init__(self, plan, viscosity: float,
                 lengths: Optional[Sequence[float]] = None):
        if len(tuple(plan.transform_axes)) != 2:
            raise ValueError(
                "NavierStokes2D needs a 2D-transform plan "
                f"(Batched2DFFTPlan); got transform_axes="
                f"{tuple(plan.transform_axes)} — use NavierStokes3D for "
                "slab/pencil plans")
        super().__init__(plan, viscosity, lengths)

    def to_spectral(self, w):
        """Physical vorticity (logical or padded shape) -> dealiased
        spectrum."""
        return self._mask(self.plan.forward_fn()(w))

    def to_physical(self, wh):
        return self.plan.inverse_fn()(wh) * self._s

    def velocity_fn(self):
        """Pure spectral-vorticity -> (u, v) physical velocity fields
        (via the streamfunction ψ: ω = -∇²ψ, u = ψ_y, v = -ψ_x)."""
        ax_x, ax_y = self.plan.transform_axes
        kx, ky = self._k(ax_x), self._k(ax_y)
        inv_k2 = self._inv_k2()
        inv = self.plan.inverse_fn()
        s = self._s

        def vel(wh):
            psi = wh * inv_k2.astype(wh.real.dtype)
            u = inv((1j * ky).astype(wh.dtype) * psi) * s
            v = inv((-1j * kx).astype(wh.dtype) * psi) * s
            return u, v

        return vel

    def rhs_fn(self):
        """Pure spectral RHS: dealiased advection + viscous decay."""
        ax_x, ax_y = self.plan.transform_axes
        kx, ky = self._k(ax_x), self._k(ax_y)
        k2 = self._k2()
        nu = self.viscosity
        fwd, inv = self.plan.forward_fn(), self.plan.inverse_fn()
        s = self._s
        vel = self.velocity_fn()
        mask = self._mask

        def rhs(wh):
            u, v = vel(wh)
            wx = inv((1j * kx).astype(wh.dtype) * wh) * s
            wy = inv((1j * ky).astype(wh.dtype) * wh) * s
            adv = fwd(u * wx + v * wy)
            return -mask(adv) - (nu * k2).astype(wh.real.dtype) * wh

        return rhs

    def diagnostics(self, wh):
        """{'energy', 'enstrophy'} per batch plane (mean over the
        TRANSFORMED plane of 0.5|u|² and 0.5ω²), computed from physical
        fields on device — a host-friendly sanity probe (inviscid runs
        conserve both to RK4 accuracy under the 2/3 truncation)."""
        u, v = self.velocity_fn()(wh)
        w = self.to_physical(wh)
        ax = tuple(self.plan.transform_axes)
        # Padded lanes are exact zeros; normalize by the LOGICAL volume.
        nvol = float(self.plan.transform_size)
        e = 0.5 * jnp.sum((jnp.abs(u) ** 2 + jnp.abs(v) ** 2), axis=ax) / nvol
        z = 0.5 * jnp.sum(jnp.abs(w) ** 2, axis=ax) / nvol
        return {"energy": e, "enstrophy": z}


class NavierStokes3D(_NSBase):
    """3D rotational-form pseudo-spectral Navier-Stokes over a slab or
    pencil plan. The user-facing state is the stacked velocity
    ``u[3, nx, ny, nz]`` (real for r2c plans); the spectral state is the
    3-tuple of component spectra, kept divergence-free by the Leray
    projection applied to the initial condition and to every nonlinear
    increment."""

    def __init__(self, plan, viscosity: float,
                 lengths: Optional[Sequence[float]] = None):
        if len(tuple(plan.transform_axes)) != 3:
            raise ValueError(
                "NavierStokes3D needs a 3D plan (slab/pencil); got "
                f"transform_axes={tuple(plan.transform_axes)} — use "
                "NavierStokes2D for batched-2D plans")
        super().__init__(plan, viscosity, lengths)

    def _kvec(self):
        return tuple(self._k(a) for a in self.plan.transform_axes)

    def _project(self, ch: Tuple):
        """Leray projection: ĉ - k (k·ĉ)/k² componentwise."""
        k = self._kvec()
        inv_k2 = self._inv_k2()
        div = sum(ki.astype(ci.real.dtype) * ci for ki, ci in zip(k, ch))
        div = div * inv_k2.astype(div.real.dtype)
        return tuple(ci - ki.astype(ci.real.dtype) * div
                     for ki, ci in zip(k, ch))

    def to_spectral(self, u):
        """Stacked physical velocity (3, ...) -> projected, dealiased
        component spectra."""
        fwd = self.plan.forward_fn()
        ch = tuple(self._mask(fwd(u[i])) for i in range(3))
        return self._project(ch)

    def to_physical(self, ch: Tuple):
        inv = self.plan.inverse_fn()
        return jnp.stack([inv(c) * self._s for c in ch])

    def _curl(self, ch: Tuple):
        kx, ky, kz = self._kvec()
        ux, uy, uz = ch

        def d(k, c):
            return (1j * k).astype(c.dtype) * c

        return (d(ky, uz) - d(kz, uy),
                d(kz, ux) - d(kx, uz),
                d(kx, uy) - d(ky, ux))

    def rhs_fn(self):
        """du/dt = P(F(u × ω)) - ν k² û, dealiased."""
        nu = self.viscosity
        k2 = self._k2()
        fwd, inv = self.plan.forward_fn(), self.plan.inverse_fn()
        s = self._s
        mask = self._mask
        project = self._project
        curl = self._curl

        def rhs(ch):
            u = [inv(c) * s for c in ch]
            w = [inv(c) * s for c in curl(ch)]
            lamb = (u[1] * w[2] - u[2] * w[1],
                    u[2] * w[0] - u[0] * w[2],
                    u[0] * w[1] - u[1] * w[0])
            nh = project(tuple(mask(fwd(c)) for c in lamb))
            return tuple(n - (nu * k2).astype(n.real.dtype) * c
                         for n, c in zip(nh, ch))

        return rhs

    def diagnostics(self, ch: Tuple):
        """{'energy', 'enstrophy'}: volume means of 0.5|u|² and 0.5|ω|²
        from the physical fields."""
        inv = self.plan.inverse_fn()
        u = [inv(c) * self._s for c in ch]
        w = [inv(c) * self._s for c in self._curl(ch)]
        nvol = float(self.plan.transform_size)
        e = 0.5 * sum(jnp.sum(jnp.abs(c) ** 2) for c in u) / nvol
        z = 0.5 * sum(jnp.sum(jnp.abs(c) ** 2) for c in w) / nvol
        return {"energy": e, "enstrophy": z}


def taylor_green_2d(n: int, batch: int = 1, lengths=(2 * np.pi, 2 * np.pi),
                    dtype=np.float64) -> np.ndarray:
    """Classic Taylor-Green vorticity ω = 2 cos x cos y on an n×n grid —
    the standard smoke/benchmark initial condition, batched."""
    x = np.arange(n) * (lengths[0] / n)
    y = np.arange(n) * (lengths[1] / n)
    w = 2.0 * np.cos(x)[:, None] * np.cos(y)[None, :]
    return np.broadcast_to(w, (batch, n, n)).astype(dtype)


def taylor_green_3d(n: int, lengths=(2 * np.pi,) * 3,
                    dtype=np.float64) -> np.ndarray:
    """Taylor-Green velocity (u, v, w) = (cos x sin y sin z,
    -sin x cos y sin z, 0) stacked as (3, n, n, n) — divergence-free by
    construction."""
    i = np.arange(n) * (lengths[0] / n)
    cx, sx = np.cos(i), np.sin(i)
    u = cx[:, None, None] * sx[None, :, None] * sx[None, None, :]
    v = -sx[:, None, None] * cx[None, :, None] * sx[None, None, :]
    w = np.zeros((n, n, n))
    return np.stack([u, v, w]).astype(dtype)
