"""Large-kernel spectral convolution / correlation on the distributed core.

FFT convolution with CORRECT zero-padding: images/volumes and kernels are
embedded in a plan whose logical extent covers the full linear-convolution
support ``n + k - 1`` per transformed axis (rounded up to a 5-smooth size
by default — ``ops/bluestein.good_size`` — so the transform stays on the
fast path; pass ``pad="exact"`` with ``fft_backend="bluestein"`` to
transform the exact support instead). Because the transform length covers
the whole linear support, the circular convolution the FFT computes
equals the linear one on the first ``n + k - 1`` samples — no wraparound
leaks into any output mode:

* ``mode="full"``  — all ``n + k - 1`` samples (np.convolve semantics);
* ``mode="same"``  — the centered ``n`` samples;
* ``mode="valid"`` — the ``n - k + 1`` samples where the kernel fits.

``correlate=True`` flips the kernel along every transformed axis before
padding (``np.correlate(x, k, "full") == np.convolve(x, k[::-1])``), so
correlation shares the exact convolution path bit for bit.

Image BATCHES ride the batched-2D plan's stacked execution — the same
decomposition the serving layer coalesces same-shape requests into
(``serve/server.py``): one :class:`SpectralConvolver` over a
``Batched2DFFTPlan`` convolves every plane of the stack against the
cached kernel spectrum in one distributed program. Volumes use a slab or
pencil plan. In both cases the kernel spectrum is transformed ONCE at
construction and ``device_put`` with the plan's output sharding, so the
steady-state cost per call is one forward + one pointwise multiply + one
inverse in the plan's own spectral layout.

The convolver is built on the plans' pure pipelines, so ``conv_fn()``
composes under jit and ``jax.grad`` (gradient w.r.t. the image is
correlation with the kernel — free via autodiff).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import params as pm
from ..ops.bluestein import good_size

_MODES = ("full", "same", "valid")


def conv_shape(image_shape: Sequence[int], kernel_shape: Sequence[int],
               pad: str = "smooth") -> Tuple[int, ...]:
    """Per-axis transform extent for a linear convolution: the full
    support ``n + k - 1``, rounded up to the next 5-smooth size
    (``pad="smooth"``, the fast-path default) or kept exact
    (``pad="exact"``, for the Bluestein backend race)."""
    if len(image_shape) != len(kernel_shape):
        raise ValueError("image and kernel rank differ: "
                         f"{image_shape} vs {kernel_shape}")
    if pad not in ("smooth", "exact"):
        raise ValueError(f"pad must be 'smooth' or 'exact', got {pad!r}")
    out = []
    for n, k in zip(image_shape, kernel_shape):
        full = int(n) + int(k) - 1
        out.append(good_size(full) if pad == "smooth" else full)
    return tuple(out)


def _spectrum_scale(plan) -> float:
    """Scalar folding the convolution-theorem normalization into the
    kernel spectrum so the pipeline is exactly
    ``inverse(forward(x) * K)``: under FFTNorm.NONE the unnormalized
    inverse leaves a factor N; BACKWARD is exact; ORTHO leaves 1/sqrt(N)
    net (two 1/sqrt(N) forwards, one 1/sqrt(N) inverse, against the
    1/N the theorem wants)."""
    nvol = float(plan.transform_size)
    norm = plan.config.norm
    if norm is pm.FFTNorm.NONE:
        return 1.0 / nvol
    if norm is pm.FFTNorm.ORTHO:
        return float(np.sqrt(nvol))
    return 1.0  # BACKWARD


class SpectralConvolver:
    """Linear convolution/correlation of images or volumes against one
    FIXED kernel through a distributed FFT plan.

    ``plan`` must be built at the padded transform extent
    (``conv_shape(image_shape, kernel.shape)`` per transformed axis; use
    :func:`make_convolver` to do both in one call). ``image_shape`` is
    the LOGICAL image extent per transformed axis."""

    def __init__(self, plan, kernel, image_shape: Sequence[int],
                 mode: str = "same", correlate: bool = False):
        if mode not in _MODES:
            raise ValueError(f"mode must be one of {_MODES}, got {mode!r}")
        self.plan = plan
        self.mode = mode
        self.correlate = bool(correlate)
        axes = tuple(plan.transform_axes)
        kernel = np.asarray(kernel)
        if kernel.ndim != len(axes):
            raise ValueError(
                f"kernel rank {kernel.ndim} != transformed rank {len(axes)}")
        self.image_shape = tuple(int(n) for n in image_shape)
        if len(self.image_shape) != len(axes):
            raise ValueError("image_shape must cover the transformed axes")
        self.kernel_shape = tuple(int(k) for k in kernel.shape)
        plan_ext = tuple(plan.input_shape[a] for a in axes)
        want = tuple(n + k - 1 for n, k in zip(self.image_shape,
                                              self.kernel_shape))
        for ext, w in zip(plan_ext, want):
            if ext < w:
                raise ValueError(
                    f"plan extent {plan_ext} cannot hold the linear "
                    f"convolution support {want} (image {self.image_shape} "
                    f"* kernel {self.kernel_shape}); build the plan at "
                    f"conv_shape(...) = {conv_shape(self.image_shape, self.kernel_shape)}")
        if self.mode == "valid" and any(
                n < k for n, k in zip(self.image_shape, self.kernel_shape)):
            raise ValueError("mode='valid' needs image >= kernel per axis")
        if self.correlate:
            kernel = kernel[(slice(None, None, -1),) * kernel.ndim]
        self._khat = self._kernel_spectrum(kernel)
        self._fn = None
        self._jit = None

    # -- kernel spectrum (once, device-placed in the plan's layout) --------

    def _kernel_spectrum(self, kernel: np.ndarray):
        plan = self.plan
        axes = tuple(plan.transform_axes)
        rt = np.float64 if plan.config.double_prec else np.float32
        c2c = plan.spectral_halved_axis is None
        full = np.zeros(tuple(plan.input_shape), dtype=rt)
        # Kernel occupies the axis origin of every transformed axis;
        # batch axes (batched-2D) broadcast the same kernel per plane.
        sl = [slice(0, 1)] * full.ndim
        for a, ext in zip(axes, kernel.shape):
            sl[a] = slice(0, ext)
        shape = [1] * full.ndim
        for a, ext in zip(axes, kernel.shape):
            shape[a] = ext
        bshape = list(full.shape)
        for i in range(full.ndim):
            if i not in axes:
                sl[i] = slice(None)
            else:
                bshape[i] = shape[i]
        full[tuple(sl)] = np.broadcast_to(
            kernel.reshape(shape), tuple(bshape)).astype(rt)
        if c2c:
            full = full.astype(np.complex128 if plan.config.double_prec
                               else np.complex64)
        khat = self.plan.forward_fn()(jnp.asarray(full))
        khat = khat * jnp.asarray(_spectrum_scale(plan), dtype=khat.real.dtype)
        if plan.mesh is not None:
            khat = jax.device_put(khat, plan.output_sharding)
        return khat

    # -- crop offsets ------------------------------------------------------

    def _crop_slices(self):
        plan = self.plan
        axes = tuple(plan.transform_axes)
        sl = [slice(None)] * len(plan.input_shape)
        for i in range(len(sl)):
            if i not in axes:
                # batch axis: crop any mesh padding back to the logical
                # batch extent
                sl[i] = slice(0, plan.input_shape[i])
        for a, n, k in zip(axes, self.image_shape, self.kernel_shape):
            if self.mode == "full":
                sl[a] = slice(0, n + k - 1)
            elif self.mode == "same":
                # Centered crop of the full support. Correlation centers
                # at k//2 (scipy.signal.correlate), convolution at
                # (k-1)//2 (np.convolve) — they differ for even kernels.
                start = k // 2 if self.correlate else (k - 1) // 2
                sl[a] = slice(start, start + n)
            else:  # valid
                sl[a] = slice(k - 1, n)
        return tuple(sl)

    # -- execution ---------------------------------------------------------

    def _padded_fn(self):
        """Pure pad -> forward -> kernel multiply -> inverse pipeline,
        returning the FULL padded convolution (no crop)."""
        plan = self.plan
        axes = tuple(plan.transform_axes)
        fwd, inv = plan.forward_fn(), plan.inverse_fn()
        khat = self._khat
        pad_to = tuple(plan.input_shape)
        image_shape = self.image_shape
        c2c = plan.spectral_halved_axis is None

        def fn(x):
            widths = [(0, 0)] * x.ndim
            for a, n in zip(axes, image_shape):
                if x.shape[a] != n:
                    raise ValueError(
                        f"image extent {tuple(x.shape)} != logical "
                        f"image shape {image_shape} on axes {axes}")
                widths[a] = (0, pad_to[a] - n)
            x = jnp.pad(x, widths)
            if c2c and not jnp.iscomplexobj(x):
                x = x.astype(jnp.complex128 if x.dtype == jnp.float64
                             else jnp.complex64)
            return inv(fwd(x) * khat)

        return fn

    def conv_fn(self):
        """Pure function: logical image stack (image_shape on the
        transformed axes, plan batch extent on the rest) -> cropped
        convolution. Composes under grad and — with a matmul-family
        local backend — under a single enclosing jit. CAVEAT (the reason
        ``__call__`` crops OUTSIDE its jit, matching the repo-wide
        crop_real/crop_spectral convention): on the CPU runtime, XLA's
        FFT thunk rejects the layout it is assigned when a shard_mapped
        jnp.fft pipeline and a slice of its output compile into ONE
        program (``LayoutUtil::IsMonotonicWithDim0Major`` RET_CHECK) —
        so jit this whole function only with ``fft_backend="matmul"``
        (pure einsum, no FFT thunk)."""
        if self._fn is None:
            padded = self._padded_fn()
            crop = self._crop_slices()

            def fn(x):
                return padded(x)[crop]

            self._fn = fn
        return self._fn

    def __call__(self, x):
        """Convolve a logical-extent image stack: the padded pipeline
        runs jitted, the mode crop slices its materialized output (the
        crop_real convention — and the CPU FFT-thunk layout caveat on
        ``conv_fn`` is sidestepped for every backend)."""
        if self._jit is None:
            self._jit = jax.jit(self._padded_fn())
        return self._jit(x)[self._crop_slices()]


def make_convolver(kernel, image_shape: Sequence[int], *, batch: int = 1,
                   partition=None, config: Optional[pm.Config] = None,
                   mesh=None, family: str = "batched2d",
                   mode: str = "same", correlate: bool = False,
                   pad: str = "smooth", shard: str = "x",
                   batch_chunk: Optional[int] = None) -> SpectralConvolver:
    """One-call construction: size the plan at the linear-convolution
    support (``conv_shape``), build it in the requested family, and wrap
    it in a :class:`SpectralConvolver`.

    * ``family="batched2d"`` — image batches: a ``(batch, nx, ny)``
      stacked plan (``shard='x'`` serves the exchange-bearing
      decomposition; ``shard='batch'`` the embarrassingly parallel one —
      the serve layer's coalescing shape).
    * ``family="slab"`` / ``"pencil"`` — 3D volumes (``batch`` ignored).
    """
    from ..models.batched2d import Batched2DFFTPlan
    from ..models.pencil import PencilFFTPlan
    from ..models.slab import SlabFFTPlan

    kernel = np.asarray(kernel)
    ext = conv_shape(image_shape, kernel.shape, pad=pad)
    if family == "batched2d":
        if len(ext) != 2:
            raise ValueError("batched2d convolver needs 2D images/kernels")
        partition = partition or pm.SlabPartition(1)
        plan = Batched2DFFTPlan(batch, ext[0], ext[1], partition, config,
                                mesh=mesh, shard=shard,
                                batch_chunk=batch_chunk)
    elif family in ("slab", "pencil"):
        if len(ext) != 3:
            raise ValueError(f"{family} convolver needs 3D volumes/kernels")
        g = pm.GlobalSize(*ext)
        if family == "slab":
            partition = partition or pm.SlabPartition(1)
            plan = SlabFFTPlan(g, partition, config, mesh=mesh)
        else:
            partition = partition or pm.PencilPartition(1, 1)
            plan = PencilFFTPlan(g, partition, config, mesh=mesh)
    else:
        raise ValueError(f"unknown family {family!r}")
    return SpectralConvolver(plan, kernel, image_shape, mode=mode,
                             correlate=correlate)
