"""``solvers/`` — the spectral application suite on the distributed core.

The reference library's entire upper layer exists to prove the
distributed FFT on real spectral applications (SURVEY L5, its testcase
executables); this package is that product surface, grown from the
original Poisson workload into a suite (ROADMAP item 4). Every solver
drives plans through the transform-agnostic solver protocol of
``models/base.py`` (``exec_fwd``/``exec_inv``, ``forward_fn``/
``inverse_fn``, ``transform_axes``, ``spectral_halved_axis``), so the
same solver runs on slab, pencil, and batched-2D plans unchanged:

* :class:`PoissonSolver` — FFT-diagonalized ∇²u = f; periodic,
  Dirichlet and Neumann boxes (via the R2R extensions).
* :class:`NavierStokes2D` / :class:`NavierStokes3D` — pseudo-spectral
  incompressible Navier-Stokes (RK4, 2/3-rule dealiasing),
  differentiable end to end.
* :class:`SpectralConvolver` — large-kernel linear convolution /
  correlation with correct zero-padding (images batched through the
  batched-2D stacked execution, volumes through slab/pencil).
* ``dct`` / ``dst`` (+ ``idct``/``idst``/``dctn``/``dstn``) — scipy-
  convention real-to-real transforms via the R2C machinery
  (``solvers/r2r.py``).

``make_solver(kind, plan, ...)`` is the uniform entry point.
"""

from __future__ import annotations

from .convolve import SpectralConvolver, conv_shape, make_convolver
from .navier_stokes import (NavierStokes2D, NavierStokes3D, taylor_green_2d,
                            taylor_green_3d)
from .poisson import PoissonSolver
from .r2r import dct, dctn, dst, dstn, idct, idst

_KINDS = ("poisson", "navier_stokes", "convolve")


def make_solver(kind: str, plan, **kwargs):
    """Build a solver of ``kind`` over ``plan``:

    * ``"poisson"`` -> :class:`PoissonSolver` (kwargs: ``lengths``,
      ``mode``, ``bc``);
    * ``"navier_stokes"`` -> :class:`NavierStokes2D` or
      :class:`NavierStokes3D`, dispatched on the plan's
      ``transform_axes`` rank (kwargs: ``viscosity`` [required],
      ``lengths``);
    * ``"convolve"`` -> :class:`SpectralConvolver` (kwargs: ``kernel``
      [required], ``image_shape`` [required], ``mode``, ``correlate``).
    """
    key = str(kind).strip().lower().replace("-", "_")
    if key == "poisson":
        return PoissonSolver(plan, **kwargs)
    if key in ("navier_stokes", "ns"):
        if "viscosity" not in kwargs:
            raise TypeError("make_solver('navier_stokes', ...) requires "
                            "viscosity=")
        nd = len(tuple(plan.transform_axes))
        cls = {2: NavierStokes2D, 3: NavierStokes3D}.get(nd)
        if cls is None:
            raise ValueError(f"no Navier-Stokes solver for a {nd}D-transform "
                             "plan")
        return cls(plan, **kwargs)
    if key == "convolve":
        if "kernel" not in kwargs or "image_shape" not in kwargs:
            raise TypeError("make_solver('convolve', ...) requires kernel= "
                            "and image_shape=")
        return SpectralConvolver(plan, kwargs.pop("kernel"),
                                 kwargs.pop("image_shape"), **kwargs)
    raise ValueError(f"unknown solver kind {kind!r} (choose from {_KINDS})")


__all__ = [
    "NavierStokes2D", "NavierStokes3D", "PoissonSolver",
    "SpectralConvolver", "conv_shape", "dct", "dctn", "dst", "dstn",
    "idct", "idst", "make_convolver", "make_solver", "taylor_green_2d",
    "taylor_green_3d",
]
