"""MXU matmul-based FFT backend (four-step Cooley-Tukey).

TPU has no FFT hardware unit: XLA expands ``fft`` HLOs into scalar/vector
code that runs on the VPU, leaving the 128x128 MXU systolic array — where
virtually all of the chip's FLOPs live — idle. This backend reformulates the
DFT as dense matrix multiplication so the transform runs on the MXU:

* **Direct**: for ``n <= DIRECT_MAX`` the transform along an axis is one
  batched matmul with the ``n x n`` DFT matrix ``F[j,k] = w^(jk)``,
  ``w = exp(-2*pi*i/n)``.
* **Four-step** (Bailey): for larger ``n = n1*n2``, decompose index
  ``n = s*n1 + r`` (r in [0,n1)), ``k = k1*n2 + k2``:

      X[k1*n2+k2] = sum_r W_n1^(r*k1) * [ W_n^(r*k2) * sum_s x[s*n1+r] * W_n2^(s*k2) ]

  i.e. reshape -> DFT matmul (n2) -> twiddle multiply -> DFT matmul (n1) ->
  reshape, recursing when a factor still exceeds ``DIRECT_MAX``. The matmul
  count is O(n * (n1+n2)) flops — more than O(n log n), but on the MXU's
  dense-matmul throughput rather than the VPU's. The factor choice is the
  MXU-deep split (``_split_for``): whenever both factors can stay on the
  direct path, the dominant factor is the largest divisor <= ``direct_max``
  (2048 -> 4x512, 4096 -> 8x512) rather than the balanced pair (32x64,
  64x64), so intermediate lengths keep the systolic array's full
  contraction depth — the large-axis extension of the direct table, driven
  by the measured direct-beats-balanced 1024^3 result (652 vs 228
  GFLOPS/chip, session_r5 2026-07-31).

The matmul is the hot op of this backend; it lowers to plain XLA
``dot_general`` so the compiler fuses the twiddle multiplies into the
surrounding elementwise graph.

Role in the framework: selected by ``Config.fft_backend = "matmul"`` as a
drop-in alternative to the XLA-FFT local layer (``ops/fft.py``); this is the
TPU-first analog of the reference's cuFFT plan choice (the reference's L0
shim, ``include/cufft.hpp:23-61``, hard-wires cuFFT — on TPU the equivalent
"vendor transform" is a compiler expansion, so the framework supplies its own
MXU-shaped implementation and lets benchmarks pick the winner, preserving
the reference's comparative spirit).

Normalization follows the cuFFT "unnormalized both ways" convention mapped
through ``FFTNorm`` exactly like ``ops/fft.py``.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import functools
import math
from typing import Optional, Sequence, Tuple

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..params import FFTNorm

# Largest length transformed by a single direct DFT matmul. 128 lanes x
# 4 sublane-tiles keeps each operand tile comfortably inside VMEM while the
# contraction depth (= n) stays a multiple of the MXU's 128-deep pipeline.
DIRECT_MAX = 512


@dataclasses.dataclass(frozen=True)
class MXUSettings:
    """Per-call backend knobs, read at TRACE time.

    Replaces the four former module globals (precision / radix2 /
    karatsuba / fourstep_einsum) so two plans with different settings can
    coexist in one process: every public entry point accepts
    ``settings=``, scoped through a ``contextvars.ContextVar`` for the
    duration of the (trace-time) call, so concurrent traces in other
    threads/contexts are unaffected. ``Config.mxu_settings()`` builds one
    from plan configuration; the ``set_*`` module functions survive as
    deprecated shims that mutate the process-default instance.

    * ``precision`` — MXU precision for SINGLE-precision DFT matmuls.
      Raw bf16 (DEFAULT) leaves 5.4e-4 max rel error at 256^3 (v5e,
      f32 vs f64 truth); three-pass bf16 emulation (HIGH) reaches
      8.2e-7 — O(f32 eps) — at half the MXU passes of HIGHEST (3.0e-8),
      so HIGH is the default. f64 inputs always use HIGHEST.
    * ``radix2`` — DIF splitting of C2C stages down to depth-128
      matmuls (see the analysis above ``_fft_radix2``).
    * ``karatsuba`` — 3-matmul complex multiply (see ``_matmul_F``).
    * ``fourstep_einsum`` — relayout-free four-step (see
      ``_fourstep_einsum``).
    * ``direct_max`` — largest length transformed by one direct DFT
      matmul before the four-step split kicks in (default the module
      ``DIRECT_MAX``). Lowering it forces a four-step factorization of
      lengths that would otherwise run direct — the knob behind the
      512-direct vs 256x2-four-step efficiency comparison.
    """

    precision: lax.Precision = lax.Precision.HIGH
    radix2: bool = False
    karatsuba: bool = False
    fourstep_einsum: bool = False
    direct_max: int = DIRECT_MAX

    @classmethod
    def make(cls, precision=None, radix2: bool = False,
             karatsuba: bool = False, fourstep_einsum: bool = False,
             direct_max: Optional[int] = None) -> "MXUSettings":
        """Build from loosely-typed values (precision may be a string
        name in any case, a ``lax.Precision``, or None for the HIGH
        default)."""
        p = lax.Precision.HIGH if precision is None else as_precision(
            precision)
        return cls(p, bool(radix2), bool(karatsuba), bool(fourstep_einsum),
                   DIRECT_MAX if direct_max is None else int(direct_max))


def as_precision(p) -> lax.Precision:
    """Coerce a ``lax.Precision`` or its string name (any case) — string
    values come from ``Config.mxu_precision``, which validates
    case-insensitively, so the coercion must be too."""
    return p if isinstance(p, lax.Precision) else lax.Precision(
        str(p).lower())


# Process-default settings, mutated only by the deprecated ``set_*`` shims.
_DEFAULTS = MXUSettings()

# Active per-call override; None -> fall through to _DEFAULTS. A ContextVar
# (not a bare global) so a trace running in another thread or asyncio task
# never observes a neighbour's scoped settings.
_ACTIVE: contextvars.ContextVar[Optional[MXUSettings]] = \
    contextvars.ContextVar("mxu_settings", default=None)


def current_settings() -> MXUSettings:
    """Settings in effect for the current context (scoped override if one
    is active, else the process defaults)."""
    return _ACTIVE.get() or _DEFAULTS


def default_settings() -> MXUSettings:
    """The process-default settings (what the deprecated ``set_*`` shims
    mutate), ignoring any active scoped override — the base
    ``Config.mxu_settings()`` resolves unset knobs against."""
    return _DEFAULTS


@contextlib.contextmanager
def use_settings(settings: Optional[MXUSettings]):
    """Scope ``settings`` as the active MXUSettings for this context.
    ``None`` is a no-op (keeps whatever is already in effect)."""
    if settings is None:
        yield
        return
    token = _ACTIVE.set(settings)
    try:
        yield
    finally:
        _ACTIVE.reset(token)


def _set_default(**kw) -> None:
    global _DEFAULTS
    _DEFAULTS = dataclasses.replace(_DEFAULTS, **kw)


def set_precision(p) -> None:
    """DEPRECATED shim: set the process-DEFAULT MXU precision for
    single-precision DFT matmuls (``lax.Precision`` or its string name).
    Prefer ``Config(mxu_precision=...)`` / an explicit ``MXUSettings`` —
    this global default is read at TRACE time and is not thread-scoped.
    Already-compiled programs keep the precision they were traced with."""
    _set_default(precision=as_precision(p))


def _prec_for(dtype):
    return (lax.Precision.HIGHEST if _is_double(dtype)
            else current_settings().precision)


# ---------------------------------------------------------------------------
# DFT / twiddle constants (host-side, cached; closed over as jit constants)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _dft_np(n: int, inverse: bool, double: bool) -> np.ndarray:
    """Dense DFT matrix F[j,k] = exp(-+ 2*pi*i*j*k/n) (numpy, cached)."""
    dt = np.complex128 if double else np.complex64
    j = np.arange(n)
    sign = 2j if inverse else -2j
    # W^(jk) = W^(jk mod n): reduce the exponent first so sin/cos see small
    # exact angles (f64 trig loses ~n*eps for angles of order n).
    return np.exp(sign * np.pi * (np.outer(j, j) % n) / n).astype(dt)


@functools.lru_cache(maxsize=None)
def _twiddle_np(n1: int, n2: int, inverse: bool, double: bool) -> np.ndarray:
    """Four-step twiddle T[r,k2] = exp(-+ 2*pi*i*r*k2/(n1*n2))."""
    dt = np.complex128 if double else np.complex64
    n = n1 * n2
    sign = 2j if inverse else -2j
    return np.exp(sign * np.pi * np.outer(np.arange(n1), np.arange(n2)) / n
                  ).astype(dt)


def _is_double(dtype) -> bool:
    return jnp.dtype(dtype) in (jnp.dtype(np.complex128),
                                jnp.dtype(np.float64))


@functools.lru_cache(maxsize=None)
def _split(n: int) -> Tuple[int, int]:
    """Balanced factorization n = n1*n2 with n1 <= n2, n1 maximal.

    Returns (1, n) for primes — the caller then falls back to a direct
    matmul of the full length (acceptable: benchmark sizes are smooth).
    """
    r = int(math.isqrt(n))
    for n1 in range(r, 1, -1):
        if n % n1 == 0:
            return n1, n // n1
    return 1, n


@functools.lru_cache(maxsize=None)
def _split_wide(n: int, direct_max: int) -> Tuple[int, int]:
    """MXU-deep factorization n = n1*n2 with n2 the LARGEST divisor of
    ``n`` not exceeding ``direct_max`` (and n1 = n/n2). Returns (1, n)
    when no such divisor > 1 exists (primes)."""
    for n2 in range(min(int(direct_max), n - 1), 1, -1):
        if n % n2 == 0:
            return n // n2, n2
    return 1, n


@functools.lru_cache(maxsize=None)
def _split_for(n: int, direct_max: int) -> Tuple[int, int]:
    """The factorization the four-step dispatch actually uses for an
    axis of length ``n > direct_max`` — the large-axis extension of the
    direct table (ISSUE 10 tentpole c).

    The balanced split minimizes MACs (n1+n2 smallest) but starves the
    MXU at intermediate lengths: 2048 under the default ``DIRECT_MAX``
    factors as 32x64, two contractions well below the systolic array's
    128-deep pipeline — exactly the regime where the measured all-direct
    1024^3 result (652 vs 228 GFLOPS, session_r5 2026-07-31) showed
    depth beating flop count ~3x. So when a factorization with BOTH
    factors on the direct-DFT matmul path exists, prefer the one whose
    dominant factor is as DEEP as possible: n2 = the largest divisor
    <= direct_max (2048 -> 4x512, 4096 -> 8x512 at the default table;
    2048 -> 2x1024 under the raced direct_max=1024), so the contraction
    carrying ~all the volume runs at full direct depth and 2048/4096
    axes stop falling off the MXU. When the deep co-factor n1 would
    itself exceed ``direct_max`` (n > direct_max^2, or divisor
    structure forbids it), fall back to the balanced split and let the
    recursion handle the large factor."""
    n1, n2 = _split_wide(n, direct_max)
    if 1 < n1 <= direct_max:
        return n1, n2
    return _split(n)


# ---------------------------------------------------------------------------
# Core transform along the LAST axis
# ---------------------------------------------------------------------------


# Complex matmul strategy for the C2C stages. XLA decomposes a complex dot
# into 4 real matmuls (ArFr - AiFi, ArFi + AiFr); the Karatsuba-style
# 3-multiplication form (t1=ArFr, t2=AiFi, t3=(Ar+Ai)(Fr+Fi); Re=t1-t2,
# Im=t3-t1-t2) trades one matmul for two extra additions. Measured on v5e
# at 256^3 it is a net LOSS (~1.9-2.2 ms roundtrip vs ~1.5 ms): at these
# sizes the stages are close to HBM-bound, so trimming MXU passes while
# adding elementwise operand traffic costs more than it saves. Off by
# default (``MXUSettings.karatsuba``); the toggle stays as a benchmarkable
# axis for larger / more compute-bound shapes.


def set_karatsuba(on: bool) -> None:
    """DEPRECATED shim: set the process-DEFAULT 3-matmul complex-multiply
    form (prefer ``Config(mxu_karatsuba=...)``)."""
    _set_default(karatsuba=bool(on))


# Radix-2 splitting of the C2C stages. A direct depth-n DFT matmul costs
# O(n) MXU passes per output row-block; decimation-in-frequency recursion
#
#   X[2k]   = DFT_{n/2}(x1 + x2)                 (x1 = first half, x2 = second)
#   X[2k+1] = DFT_{n/2}((x1 - x2) * w^j),  w = exp(-+2*pi*i/n)
#
# halves the matmul depth per level at the cost of one VPU butterfly and an
# even/odd output interleave. Recursing down to depth _R2_BASE = 128 — the
# MXU's native contraction depth, below which passes waste systolic rows —
# turns the depth-256 stages of a 256^3 transform into two depth-128
# matmuls plus cheap elementwise work: ~2x fewer MXU passes on the stages
# that dominate the roundtrip. Measured on v5e at 256^3 f32 it is a net
# LOSS (2.64 ms roundtrip vs 1.52 ms direct, same session): the interleave
# store is a full-array relayout per stage that XLA does NOT fold away, and
# like the Karatsuba toggle above, trading MXU passes for extra HBM traffic
# loses on an op that is already bandwidth-balanced. Kept as a raced
# backend ("matmul-r2") because the trade-off flips where compute dominates
# (deeper axes / cheaper memory systems); both input halves are contiguous
# (DIF, not DIT), so no strided gather on the input side.
_R2_BASE = 128


def set_radix2(on: bool) -> None:
    """DEPRECATED shim: set the process-DEFAULT radix-2 DIF splitting of
    C2C stages (prefer backend "matmul-r2" / an explicit MXUSettings)."""
    _set_default(radix2=bool(on))


@contextlib.contextmanager
def radix2(on: bool = True):
    """Scoped radix-2 override: the current settings with ``radix2=on``,
    context-local (thread/task-safe), restored on exit."""
    with use_settings(dataclasses.replace(current_settings(),
                                          radix2=bool(on))):
        yield


@functools.lru_cache(maxsize=None)
def _r2_twiddle_np(n: int, inverse: bool, double: bool) -> np.ndarray:
    """Radix-2 DIF twiddle w^j = exp(-+2*pi*i*j/n), j in [0, n/2)."""
    dt = np.complex128 if double else np.complex64
    sign = 2j if inverse else -2j
    return np.exp(sign * np.pi * np.arange(n // 2) / n).astype(dt)


def _fft_radix2(x, inverse: bool):
    """DIF radix-2 split of an even-length last-axis DFT: two half-length
    DFTs (recursively down to ``_R2_BASE``) + butterfly + interleave."""
    n = x.shape[-1]
    h = n // 2
    dbl = _is_double(x.dtype)
    x1 = x[..., :h]
    x2 = x[..., h:]
    even = _fft_last(x1 + x2, inverse)
    odd = _fft_last((x1 - x2) * jnp.asarray(_r2_twiddle_np(n, inverse, dbl)),
                    inverse)
    # X[2k] = even[k], X[2k+1] = odd[k]
    return jnp.stack([even, odd], axis=-1).reshape(x.shape[:-1] + (n,))


def _matmul_F(x, F_np: np.ndarray):
    """x @ F for complex x and a constant complex DFT matrix."""
    prec = _prec_for(x.dtype)
    if not current_settings().karatsuba:
        return jnp.matmul(x, jnp.asarray(F_np), precision=prec)
    rdt = np.float64 if _is_double(x.dtype) else np.float32
    Fr = jnp.asarray(np.ascontiguousarray(F_np.real.astype(rdt)))
    Fi = jnp.asarray(np.ascontiguousarray(F_np.imag.astype(rdt)))
    Fs = jnp.asarray((F_np.real + F_np.imag).astype(rdt))
    ar, ai = jnp.real(x), jnp.imag(x)
    t1 = jnp.matmul(ar, Fr, precision=prec)
    t2 = jnp.matmul(ai, Fi, precision=prec)
    t3 = jnp.matmul(ar + ai, Fs, precision=prec)
    return lax.complex(t1 - t2, t3 - t1 - t2)


def _rmatmul_F(x_real, F_np: np.ndarray):
    """x @ F for REAL x: two real matmuls instead of a complex one (halves
    the MXU work for the R2C first stage and the four-step first stage)."""
    prec = _prec_for(x_real.dtype)
    re = jnp.matmul(x_real, jnp.asarray(np.ascontiguousarray(F_np.real)),
                    precision=prec)
    im = jnp.matmul(x_real, jnp.asarray(np.ascontiguousarray(F_np.imag)),
                    precision=prec)
    return lax.complex(re, im)


# Four-step layout strategy. The original formulation materializes three
# jnp.swapaxes relayouts of the full array per four-step level (pack to
# [r,s], re-pack between the stages, unpack at the end); the einsum
# formulation contracts the reshaped factor axes directly (dot_general
# with non-trailing contracting dims), letting XLA pick operand layouts.
# Measured on v5e (batched-2D 2048^2 x 64 roundtrip, same session):
# einsum 167.3 ms vs swapaxes 137.2 ms — XLA's layout assignment for the
# non-trailing contraction is WORSE than the explicit relayout pipeline,
# so the swapaxes path stays the default and the einsum variant remains a
# benchmarkable toggle (``MXUSettings.fourstep_einsum``; exact same math,
# bit-identical in f64 on CPU). Applies when both factors are direct-sized
# (n <= DIRECT_MAX^2 = 256k — every practical axis).


def set_fourstep_einsum(on: bool) -> None:
    """DEPRECATED shim: set the process-DEFAULT einsum (relayout-free)
    four-step formulation (prefer ``Config(mxu_fourstep_einsum=...)``)."""
    _set_default(fourstep_einsum=bool(on))


@contextlib.contextmanager
def fourstep_einsum(on: bool = True):
    """Scoped fourstep-einsum override, context-local (same pattern as
    ``radix2``)."""
    with use_settings(dataclasses.replace(current_settings(),
                                          fourstep_einsum=bool(on))):
        yield


def _fourstep_einsum(x4, inverse: bool, n1: int, n2: int, dbl: bool):
    """Four-step stages as direct contractions of a [..., s, r] factor
    array (x[..., s*n1 + r]); returns [..., k1, k2] (X[k1*n2 + k2])."""
    prec = _prec_for(x4.dtype)
    if jnp.iscomplexobj(x4):
        b = jnp.einsum("...sr,sk->...kr", x4,
                       jnp.asarray(_dft_np(n2, inverse, dbl)), precision=prec)
    else:  # real first stage: two real contractions (R2C fast path)
        F2 = _dft_np(n2, inverse, dbl)
        br = jnp.einsum("...sr,sk->...kr", x4,
                        jnp.asarray(np.ascontiguousarray(F2.real)),
                        precision=prec)
        bi = jnp.einsum("...sr,sk->...kr", x4,
                        jnp.asarray(np.ascontiguousarray(F2.imag)),
                        precision=prec)
        b = lax.complex(br, bi)
    # Twiddle transposed to the [k2, r] layout of b.
    c = b * jnp.asarray(np.ascontiguousarray(
        _twiddle_np(n1, n2, inverse, dbl).T))
    d = jnp.einsum("...kr,rj->...jk", c,
                   jnp.asarray(_dft_np(n1, inverse, dbl)), precision=prec)
    return d.reshape(d.shape[:-2] + (n1 * n2,))


def _fft_last(x, inverse: bool):
    """Unnormalized DFT along the last axis of a complex array."""
    n = x.shape[-1]
    dbl = _is_double(x.dtype)
    st = current_settings()
    if st.radix2 and n > _R2_BASE and n % 2 == 0:
        return _fft_radix2(x, inverse)
    if n <= st.direct_max:
        return _matmul_F(x, _dft_np(n, inverse, dbl))
    n1, n2 = _split_for(n, st.direct_max)
    if n1 == 1:  # prime length: direct full-size matmul
        return _matmul_F(x, _dft_np(n, inverse, dbl))
    if st.fourstep_einsum and n1 <= st.direct_max and n2 <= st.direct_max:
        return _fourstep_einsum(x.reshape(x.shape[:-1] + (n2, n1)),
                                inverse, n1, n2, dbl)
    # x[..., s*n1 + r] -> A[..., r, s]
    a = jnp.swapaxes(x.reshape(x.shape[:-1] + (n2, n1)), -1, -2)
    b = _fft_last(a, inverse)                       # DFT over s -> (r, k2)
    c = b * jnp.asarray(_twiddle_np(n1, n2, inverse, dbl))
    d = _fft_last(jnp.swapaxes(c, -1, -2), inverse)  # DFT over r -> (k2, k1)
    return jnp.swapaxes(d, -1, -2).reshape(x.shape[:-1] + (n,))


def _rfft_last(x):
    """Unnormalized R2C DFT along the last axis of a real array; output
    length n//2+1 (the reference's R2C halving, ``params.hpp:30``)."""
    n = x.shape[-1]
    n_out = n // 2 + 1
    dbl = _is_double(x.dtype)
    st = current_settings()
    if n <= st.direct_max:
        return _rmatmul_F(x, _dft_np(n, False, dbl)[:, :n_out])
    n1, n2 = _split_for(n, st.direct_max)
    if n1 == 1:
        return _rmatmul_F(x, _dft_np(n, False, dbl)[:, :n_out])
    if st.fourstep_einsum and n1 <= st.direct_max and n2 <= st.direct_max:
        full = _fourstep_einsum(x.reshape(x.shape[:-1] + (n2, n1)),
                                False, n1, n2, dbl)
        return full[..., :n_out]
    a = jnp.swapaxes(x.reshape(x.shape[:-1] + (n2, n1)), -1, -2)
    # First stage on real data: real matmul pair.
    if n2 <= st.direct_max:
        b = _rmatmul_F(a, _dft_np(n2, False, dbl))
    else:
        cdt = np.complex128 if dbl else np.complex64
        b = _fft_last(a.astype(cdt), False)
    c = b * jnp.asarray(_twiddle_np(n1, n2, False, dbl))
    d = _fft_last(jnp.swapaxes(c, -1, -2), False)
    full = jnp.swapaxes(d, -1, -2).reshape(x.shape[:-1] + (n,))
    return full[..., :n_out]


@functools.lru_cache(maxsize=None)
def _c2r_np(n: int, double: bool) -> Tuple[np.ndarray, np.ndarray]:
    """Half-spectrum inverse-DFT matrices (CR, CI) with conjugate symmetry
    folded in: for Hermitian input of length n//2+1,
    ``y = Re(c) @ CR - Im(c) @ CI`` equals ``Re(idft(hermitian_extend(c)))``
    with a quarter of the MXU work of the full complex matmul (2 real
    matmuls of n//2+1 depth vs 4 of n)."""
    dt = np.float64 if double else np.float32
    n_out = n // 2 + 1
    jk = np.outer(np.arange(n_out), np.arange(n)) % n  # reduce for exact trig
    ang = 2.0 * np.pi * jk / n
    a = np.full((n_out, 1), 2.0)
    a[0] = 1.0
    if n % 2 == 0:
        a[n // 2] = 1.0
    return (a * np.cos(ang)).astype(dt), (a * np.sin(ang)).astype(dt)


def _hermitian_extend(c, n: int):
    """Rebuild the full length-n spectrum from its n//2+1 half (C2R input)."""
    tail = jnp.conj(c[..., 1:(n + 1) // 2])[..., ::-1]
    return jnp.concatenate([c, tail], axis=-1)


def _fit_axis(c, axis: int, n: int):
    """Crop or zero-pad axis to extent n (jnp.fft's ``s=``/``n=`` semantics,
    applied before transforming along that axis)."""
    cur = c.shape[axis]
    if cur > n:
        c = lax.slice_in_dim(c, 0, n, axis=axis)
    elif cur < n:
        widths = [(0, 0)] * c.ndim
        widths[axis % c.ndim] = (0, n - cur)
        c = jnp.pad(c, widths)
    return c


# ---------------------------------------------------------------------------
# Norm scaling (same FFTNorm semantics as ops/fft.py)
# ---------------------------------------------------------------------------


def _fwd_scale(n: int, norm: FFTNorm) -> float:
    return 1.0 / math.sqrt(n) if norm is FFTNorm.ORTHO else 1.0


def _inv_scale(n: int, norm: FFTNorm) -> float:
    if norm is FFTNorm.ORTHO:
        return 1.0 / math.sqrt(n)
    if norm is FFTNorm.BACKWARD:
        return 1.0 / n
    return 1.0  # NONE: unnormalized inverse (cuFFT convention)


def _scaled(y, s: float):
    return y if s == 1.0 else y * jnp.asarray(s, dtype=y.dtype).real


# ---------------------------------------------------------------------------
# Public API (mirrors ops/fft.py signatures)
# ---------------------------------------------------------------------------


def fft(x, axis: int, norm: FFTNorm = FFTNorm.NONE):
    cdt = np.complex128 if _is_double(x.dtype) else np.complex64
    x = jnp.moveaxis(x.astype(cdt), axis, -1)
    y = _scaled(_fft_last(x, False), _fwd_scale(x.shape[-1], norm))
    return jnp.moveaxis(y, -1, axis)


def ifft(x, axis: int, norm: FFTNorm = FFTNorm.NONE):
    cdt = np.complex128 if _is_double(x.dtype) else np.complex64
    x = jnp.moveaxis(x.astype(cdt), axis, -1)
    y = _scaled(_fft_last(x, True), _inv_scale(x.shape[-1], norm))
    return jnp.moveaxis(y, -1, axis)


def rfft(x, axis: int, norm: FFTNorm = FFTNorm.NONE):
    x = jnp.moveaxis(x, axis, -1)
    y = _scaled(_rfft_last(x), _fwd_scale(x.shape[-1], norm))
    return jnp.moveaxis(y, -1, axis)


def irfft(x, n: int, axis: int, norm: FFTNorm = FFTNorm.NONE):
    cdt = np.complex128 if _is_double(x.dtype) else np.complex64
    c = jnp.moveaxis(x.astype(cdt), axis, -1)
    # jnp.fft.irfft contract: the spectral axis is cropped/zero-padded to
    # n//2+1 before inversion.
    c = _fit_axis(c, -1, n // 2 + 1)
    if n <= current_settings().direct_max:
        dbl = _is_double(c.dtype)
        CR, CI = _c2r_np(n, dbl)
        prec = _prec_for(c.dtype)
        y = (jnp.matmul(jnp.real(c), jnp.asarray(CR), precision=prec)
             - jnp.matmul(jnp.imag(c), jnp.asarray(CI), precision=prec))
    else:
        full = _hermitian_extend(c, n)
        y = jnp.real(_fft_last(full, True))
    return jnp.moveaxis(_scaled(y, _inv_scale(n, norm)), -1, axis)


def fftn(x, axes: Sequence[int], norm: FFTNorm = FFTNorm.NONE):
    for a in axes:
        x = fft(x, axis=a, norm=norm)
    return x


def ifftn(x, axes: Sequence[int], norm: FFTNorm = FFTNorm.NONE):
    for a in axes:
        x = ifft(x, axis=a, norm=norm)
    return x


def rfftn_3d(x, norm: FFTNorm = FFTNorm.NONE):
    c = rfft(x, axis=-1, norm=norm)
    c = fft(c, axis=-2, norm=norm)
    return fft(c, axis=-3, norm=norm)


# ---------------------------------------------------------------------------
# All-real-planes 3D transform: the same DFT matmuls with the complex
# arithmetic written out on separate (re, im) f32 planes, so the compiled
# program contains NO complex dtypes anywhere — input, output, and every
# intermediate are real. Exists because the axon TPU tunnel has been
# observed to degrade into a state where any executable touching complex64
# fails with UNIMPLEMENTED (even device_put); since XLA lowers complex dots
# to exactly these real matmuls anyway, this formulation measures the same
# hardware work. Direct sizes only (every axis <= DIRECT_MAX); bench.py
# falls back to it when its probe finds complex broken.
# ---------------------------------------------------------------------------


_RP_EINSUM = ("ak,ayz->kyz", "ak,xaz->xkz", "ak,xya->xyk")


def _rp_stage(ar, ai, F_np: np.ndarray, axis: int):
    """One DFT stage along ``axis`` of split-plane data. ``ai=None`` means
    real input (the R2C first stage's two-matmul fast path)."""
    eq = _RP_EINSUM[axis]
    prec = _prec_for(ar.dtype)
    Fr = jnp.asarray(np.ascontiguousarray(F_np.real.astype(np.float32)))
    Fi = jnp.asarray(np.ascontiguousarray(F_np.imag.astype(np.float32)))

    def e(M, a):
        return jnp.einsum(eq, M, a, precision=prec)

    if ai is None:
        return e(Fr, ar), e(Fi, ar)
    return e(Fr, ar) - e(Fi, ai), e(Fr, ai) + e(Fi, ar)


def rfftn_3d_planes(x):
    """Unnormalized forward R2C over the trailing 3 axes of a REAL 3D f32
    array, returned as (re, im) f32 planes of shape (X, Y, Z//2+1)."""
    X, Y, Z = x.shape
    for n in (X, Y, Z):
        if n > DIRECT_MAX:
            raise ValueError(f"rfftn_3d_planes is direct-size only "
                             f"(axis {n} > {DIRECT_MAX})")
    ar, ai = _rp_stage(x.astype(jnp.float32), None,
                       _dft_np(Z, False, False)[:, :Z // 2 + 1], 2)
    ar, ai = _rp_stage(ar, ai, _dft_np(Y, False, False), 1)
    return _rp_stage(ar, ai, _dft_np(X, False, False), 0)


def irfftn_3d_planes(cr, ci, shape_3d):
    """Unnormalized inverse of ``rfftn_3d_planes``: (re, im) spectral planes
    of shape (X, Y, Z//2+1) -> real f32 (X, Y, Z)."""
    X, Y, Z = shape_3d
    for n in (X, Y, Z):
        if n > DIRECT_MAX:
            raise ValueError(f"irfftn_3d_planes is direct-size only "
                             f"(axis {n} > {DIRECT_MAX})")
    er, ei = _rp_stage(cr, ci, _dft_np(X, True, False), 0)
    er, ei = _rp_stage(er, ei, _dft_np(Y, True, False), 1)
    CR, CI = _c2r_np(Z, False)
    prec = _prec_for(er.dtype)
    return (jnp.einsum(_RP_EINSUM[2], jnp.asarray(CR), er, precision=prec)
            - jnp.einsum(_RP_EINSUM[2], jnp.asarray(CI), ei, precision=prec))


def irfftn_3d(x, shape_3d: Tuple[int, int, int], norm: FFTNorm = FFTNorm.NONE):
    c = ifft(_fit_axis(x, -3, shape_3d[-3]), axis=-3, norm=norm)
    c = ifft(_fit_axis(c, -2, shape_3d[-2]), axis=-2, norm=norm)
    return irfft(c, n=shape_3d[-1], axis=-1, norm=norm)
