"""Local (per-shard) FFT layer — the TPU analog of the reference's L0 shim.

The reference maps ``T in {float, double}`` to cuFFT types and exec function
pointers (``include/cufft.hpp:23-61``). Here the same role is played by
``jnp.fft`` lowered by XLA to its native FFT implementation; the dtype policy
maps precision to (real, complex) jnp dtypes, and the normalization policy
maps the cuFFT "unnormalized both ways" convention onto numpy norm strings.

All functions are shape-polymorphic, jit-safe wrappers; batching comes from
the untouched axes (cuFFT "batched plan" ≙ XLA treating other axes as batch).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp

from ..params import FFTNorm


def dtypes_for(double_prec: bool) -> Tuple[jnp.dtype, jnp.dtype]:
    """(real, complex) dtypes; f64/c128 requires ``jax_enable_x64`` and is
    intended for CPU-backend correctness gates (TPU has no native f64)."""
    if double_prec:
        return jnp.dtype(jnp.float64), jnp.dtype(jnp.complex128)
    return jnp.dtype(jnp.float32), jnp.dtype(jnp.complex64)


def _fwd_norm(norm: FFTNorm) -> str:
    # cuFFT forward is unnormalized == numpy "backward" forward.
    return "ortho" if norm is FFTNorm.ORTHO else "backward"


def _inv_norm(norm: FFTNorm) -> str:
    # cuFFT inverse is also unnormalized; numpy's norm="forward" puts the
    # full 1/N on the forward side, making the inverse unnormalized.
    if norm is FFTNorm.NONE:
        return "forward"
    if norm is FFTNorm.ORTHO:
        return "ortho"
    return "backward"


def rfft(x, axis: int, norm: FFTNorm = FFTNorm.NONE):
    """Forward R2C along one axis (cuFFT ``execR2C`` analog, 1D case)."""
    return jnp.fft.rfft(x, axis=axis, norm=_fwd_norm(norm))


def irfft(x, n: int, axis: int, norm: FFTNorm = FFTNorm.NONE):
    """Inverse C2R along one axis; ``n`` is the real output extent (needed
    because the halved axis length ``n//2+1`` is ambiguous)."""
    return jnp.fft.irfft(x, n=n, axis=axis, norm=_inv_norm(norm))


def fft(x, axis: int, norm: FFTNorm = FFTNorm.NONE):
    """Forward C2C along one axis (cuFFT ``execC2C(..., CUFFT_FORWARD)``)."""
    return jnp.fft.fft(x, axis=axis, norm=_fwd_norm(norm))


def ifft(x, axis: int, norm: FFTNorm = FFTNorm.NONE):
    """Inverse C2C along one axis (cuFFT ``execC2C(..., CUFFT_INVERSE)``)."""
    return jnp.fft.ifft(x, axis=axis, norm=_inv_norm(norm))


def fftn(x, axes: Sequence[int], norm: FFTNorm = FFTNorm.NONE):
    return jnp.fft.fftn(x, axes=tuple(axes), norm=_fwd_norm(norm))


def ifftn(x, axes: Sequence[int], norm: FFTNorm = FFTNorm.NONE):
    return jnp.fft.ifftn(x, axes=tuple(axes), norm=_inv_norm(norm))


def rfftn_3d(x, norm: FFTNorm = FFTNorm.NONE):
    """Single-device full 3D R2C over the trailing three axes — the analog of
    the reference's ``cufftMakePlan3d`` single-process fallback
    (``src/mpicufft.cpp:65``, ``src/slab/default/mpicufft_slab.cpp:142-145``).
    The halved axis is z (the last), matching cuFFT's layout."""
    return jnp.fft.rfftn(x, axes=(-3, -2, -1), norm=_fwd_norm(norm))


def irfftn_3d(x, shape_3d: Tuple[int, int, int], norm: FFTNorm = FFTNorm.NONE):
    return jnp.fft.irfftn(x, s=shape_3d, axes=(-3, -2, -1), norm=_inv_norm(norm))
