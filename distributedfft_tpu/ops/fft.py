"""Local (per-shard) FFT layer — the TPU analog of the reference's L0 shim.

The reference maps ``T in {float, double}`` to cuFFT types and exec function
pointers (``include/cufft.hpp:23-61``). Here the same role is played by
``jnp.fft`` lowered by XLA to its native FFT implementation; the dtype policy
maps precision to (real, complex) jnp dtypes, and the normalization policy
maps the cuFFT "unnormalized both ways" convention onto numpy norm strings.

All functions are shape-polymorphic, jit-safe wrappers; batching comes from
the untouched axes (cuFFT "batched plan" ≙ XLA treating other axes as batch).

Every entry point takes ``backend``: ``"xla"`` (default) lowers to XLA's FFT
expansion; ``"matmul"`` dispatches to the MXU matmul four-step backend
(``ops/mxu_fft.py``) — the TPU-first alternative that keeps the FLOPs on the
systolic array; ``"matmul-r2"`` is the same backend with radix-2 DIF
splitting of the C2C stages down to MXU-depth matmuls (measured slower on
v5e at 256^3 — see ``mxu_fft.MXUSettings.radix2`` — raced for completeness);
``"pallas"`` runs the same four-step with hand-written Pallas kernels
fusing the twiddle epilogue into the DFT matmul (``ops/pallas_fft.py``);
``"bluestein"`` is the arbitrary-size backend (``ops/bluestein.py``):
5-smooth axes delegate to the XLA expansion bit-identically, while prime /
non-smooth lengths run the chirp-z identity at O(n log n) instead of
falling off every fast path (the matmul four-step degrades to a dense
O(n^2) contraction there). Selected plan-wide via ``Config.fft_backend``.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Sequence, Tuple

import jax.numpy as jnp

from ..params import FFTNorm

BACKENDS = ("xla", "matmul", "matmul-r2", "pallas", "bluestein")


def _mxu():
    from . import mxu_fft
    return mxu_fft


def _pallas():
    from . import pallas_fft
    return pallas_fft


def _bluestein():
    from . import bluestein
    return bluestein


def validate_backend(backend: str) -> str:
    if backend not in BACKENDS:
        raise ValueError(f"unknown fft backend {backend!r}; expected one of "
                         f"{BACKENDS}")
    return backend


def _impl(backend: str):
    """Non-XLA implementation module for ``backend``, or None for "xla"."""
    b = validate_backend(backend)
    if b in ("matmul", "matmul-r2"):
        return _mxu()
    if b == "pallas":
        return _pallas()
    if b == "bluestein":
        return _bluestein()
    return None


def _settings_ctx(backend: str, settings):
    """Context scoping per-call ``MXUSettings`` around a non-XLA dispatch
    (the settings are read at TRACE time inside the backend; the ContextVar
    scope makes the read thread/task-safe). ``"matmul-r2"`` is the matmul
    backend with ``radix2`` forced on — overriding whatever the caller's
    settings say, since the backend string is the more specific request.
    The pallas backend reads only ``precision`` (via ``mxu_fft._prec_for``)
    but is scoped identically so a plan's precision choice reaches it."""
    mx = _mxu()
    if backend == "matmul-r2":
        settings = dataclasses.replace(settings or mx.current_settings(),
                                       radix2=True)
    if settings is None:
        return contextlib.nullcontext()
    return mx.use_settings(settings)


def dtypes_for(double_prec: bool) -> Tuple[jnp.dtype, jnp.dtype]:
    """(real, complex) dtypes; f64/c128 requires ``jax_enable_x64`` and is
    intended for CPU-backend correctness gates (TPU has no native f64)."""
    if double_prec:
        return jnp.dtype(jnp.float64), jnp.dtype(jnp.complex128)
    return jnp.dtype(jnp.float32), jnp.dtype(jnp.complex64)


def _fwd_norm(norm: FFTNorm) -> str:
    # cuFFT forward is unnormalized == numpy "backward" forward.
    return "ortho" if norm is FFTNorm.ORTHO else "backward"


def _inv_norm(norm: FFTNorm) -> str:
    # cuFFT inverse is also unnormalized; numpy's norm="forward" puts the
    # full 1/N on the forward side, making the inverse unnormalized.
    if norm is FFTNorm.NONE:
        return "forward"
    if norm is FFTNorm.ORTHO:
        return "ortho"
    return "backward"


def rfft(x, axis: int, norm: FFTNorm = FFTNorm.NONE, backend: str = "xla",
         settings=None):
    """Forward R2C along one axis (cuFFT ``execR2C`` analog, 1D case).

    ``settings`` (all entry points): optional ``mxu_fft.MXUSettings``
    scoped around the dispatch — the per-plan alternative to the
    deprecated ``set_*`` process globals. Ignored by the "xla" backend."""
    m = _impl(backend)
    if m is not None:
        with _settings_ctx(backend, settings):
            return m.rfft(x, axis=axis, norm=norm)
    return jnp.fft.rfft(x, axis=axis, norm=_fwd_norm(norm))


def irfft(x, n: int, axis: int, norm: FFTNorm = FFTNorm.NONE,
          backend: str = "xla", settings=None):
    """Inverse C2R along one axis; ``n`` is the real output extent (needed
    because the halved axis length ``n//2+1`` is ambiguous)."""
    m = _impl(backend)
    if m is not None:
        with _settings_ctx(backend, settings):
            return m.irfft(x, n=n, axis=axis, norm=norm)
    return jnp.fft.irfft(x, n=n, axis=axis, norm=_inv_norm(norm))


def fft(x, axis: int, norm: FFTNorm = FFTNorm.NONE, backend: str = "xla",
        settings=None):
    """Forward C2C along one axis (cuFFT ``execC2C(..., CUFFT_FORWARD)``)."""
    m = _impl(backend)
    if m is not None:
        with _settings_ctx(backend, settings):
            return m.fft(x, axis=axis, norm=norm)
    return jnp.fft.fft(x, axis=axis, norm=_fwd_norm(norm))


def ifft(x, axis: int, norm: FFTNorm = FFTNorm.NONE, backend: str = "xla",
         settings=None):
    """Inverse C2C along one axis (cuFFT ``execC2C(..., CUFFT_INVERSE)``)."""
    m = _impl(backend)
    if m is not None:
        with _settings_ctx(backend, settings):
            return m.ifft(x, axis=axis, norm=norm)
    return jnp.fft.ifft(x, axis=axis, norm=_inv_norm(norm))


def fftn(x, axes: Sequence[int], norm: FFTNorm = FFTNorm.NONE,
         backend: str = "xla", settings=None):
    m = _impl(backend)
    if m is not None:
        with _settings_ctx(backend, settings):
            return m.fftn(x, axes=axes, norm=norm)
    return jnp.fft.fftn(x, axes=tuple(axes), norm=_fwd_norm(norm))


def ifftn(x, axes: Sequence[int], norm: FFTNorm = FFTNorm.NONE,
          backend: str = "xla", settings=None):
    m = _impl(backend)
    if m is not None:
        with _settings_ctx(backend, settings):
            return m.ifftn(x, axes=axes, norm=norm)
    return jnp.fft.ifftn(x, axes=tuple(axes), norm=_inv_norm(norm))


def rfftn_3d(x, norm: FFTNorm = FFTNorm.NONE, backend: str = "xla",
             settings=None):
    """Single-device full 3D R2C over the trailing three axes — the analog of
    the reference's ``cufftMakePlan3d`` single-process fallback
    (``src/mpicufft.cpp:65``, ``src/slab/default/mpicufft_slab.cpp:142-145``).
    The halved axis is z (the last), matching cuFFT's layout."""
    m = _impl(backend)
    if m is not None:
        with _settings_ctx(backend, settings):
            return m.rfftn_3d(x, norm=norm)
    return jnp.fft.rfftn(x, axes=(-3, -2, -1), norm=_fwd_norm(norm))


def irfftn_3d(x, shape_3d: Tuple[int, int, int], norm: FFTNorm = FFTNorm.NONE,
              backend: str = "xla", settings=None):
    m = _impl(backend)
    if m is not None:
        with _settings_ctx(backend, settings):
            return m.irfftn_3d(x, shape_3d=shape_3d, norm=norm)
    return jnp.fft.irfftn(x, s=shape_3d, axes=(-3, -2, -1), norm=_inv_norm(norm))
