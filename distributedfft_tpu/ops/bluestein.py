"""Bluestein (chirp-z) FFT backend — arbitrary axis sizes on the fast path.

Every fast path in this repo assumes 5-smooth (2^a * 3^b * 5^c) axis
sizes: XLA's FFT expansion degrades off powers of small primes, and the
MXU matmul backend's four-step split returns ``(1, n)`` for a prime
length — a dense O(n^2) contraction (``mxu_fft._split``: "acceptable:
benchmark sizes are smooth"). This module removes that cliff with the
chirp-z identity

    X[k] = c*_k * ( (x * c) circ-conv b )[k],   c_j = exp(-i*pi*j^2/n),
                                                b_j = conj(c_j) = c*_j,

which evaluates a length-``n`` DFT (any ``n``: prime, 251, whatever) as
one pointwise chirp multiply, a circular convolution at the padded CHIRP
LENGTH ``m = chirp_length(n)`` (the next power of two >= 2n-1), and a
final chirp multiply. The convolution runs as FFT(m) -> pointwise ->
IFFT(m); the kernel spectrum ``FFT(b)`` is a host-precomputed constant
(``functools.lru_cache``, closed over as a jit constant like the DFT
matrices of ``ops/mxu_fft.py``), so each chirp-z pass costs exactly two
smooth power-of-two transforms plus O(m) elementwise work — O(n log n)
for every n, at a bounded overhead over a natively smooth axis
(``evalkit/roofline.bluestein_axis_report`` quotes the factor honestly).

Registered as ``Config(fft_backend="bluestein")`` (``ops/fft.py``
dispatch): smooth axes delegate to the XLA expansion untouched
(bit-identical to ``"xla"`` there), non-smooth axes take the chirp path.
``fft_backend="auto"`` races it against the other backends — for a
non-smooth shape that is the race between the chirp-z transform and the
O(n^2) direct fallbacks; for smooth shapes it is skipped (identical to
xla by construction, racing it would double-count one candidate).

Everything here is ``jnp`` elementwise ops + smooth FFTs, so the chirp
path is differentiable end to end (the solver suite's ``jit(grad)``
gates cover it) and composes under ``shard_map`` exactly like the other
local backends: plans stay oblivious — the exchange renderings, wire
encodings and guards wrap it unchanged.

The quadratic chirp exponent is reduced mod 2n before the trig
(``j^2 mod 2n``), the same exact-angle trick as ``mxu_fft._dft_np`` —
f64 sin/cos lose ~n*eps for angles of order n^2 otherwise.
"""

from __future__ import annotations

import functools
import math
from typing import Sequence, Tuple

import numpy as np

import jax.numpy as jnp

from ..params import FFTNorm

# The smoothness radix set of every fast path in the repo (XLA FFT /
# mxu four-step benchmark sizes are 2^a*3^b*5^c).
SMOOTH_RADICES = (2, 3, 5)


def is_smooth(n: int, radices: Tuple[int, ...] = SMOOTH_RADICES) -> bool:
    """True when ``n`` factors entirely over ``radices`` (5-smooth by
    default) — the sizes the non-chirp fast paths handle natively."""
    if n < 1:
        return False
    for p in radices:
        while n % p == 0:
            n //= p
    return n == 1


def chirp_length(n: int) -> int:
    """The chirp-z working length for a length-``n`` axis: the smallest
    power of two >= 2n-1 (the circular convolution must hold the full
    linear-convolution support so no wraparound aliases the first n
    outputs)."""
    if n < 1:
        raise ValueError(f"axis length must be positive, got {n}")
    return 1 << (max(2 * n - 1, 1) - 1).bit_length()


def good_size(n: int, radices: Tuple[int, ...] = SMOOTH_RADICES) -> int:
    """The smallest 5-smooth integer >= ``n`` — the zero-padding target
    for workloads that may legally round an axis up (spectral
    convolution pads to linear-conv length anyway; an exact-length FFT
    cannot use this and takes the chirp path instead)."""
    if n < 1:
        raise ValueError(f"axis length must be positive, got {n}")
    m = n
    while not is_smooth(m, radices):
        m += 1
    return m


# ---------------------------------------------------------------------------
# host-side chirp constants (jit constants, like mxu_fft's DFT matrices)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _chirp_np(n: int, inverse: bool, double: bool) -> np.ndarray:
    """c_j = exp(-+ i*pi*j^2/n), j in [0, n) (sign flipped for the
    inverse transform). Exponent reduced mod 2n for exact trig."""
    dt = np.complex128 if double else np.complex64
    j = np.arange(n, dtype=np.int64)
    sign = 1j if inverse else -1j
    return np.exp(sign * np.pi * ((j * j) % (2 * n)) / n).astype(dt)


@functools.lru_cache(maxsize=None)
def _kernel_spectrum_np(n: int, inverse: bool, double: bool) -> np.ndarray:
    """FFT(m) of the symmetric chirp kernel b_j = conj(c_j) laid out for
    circular convolution: b at [0, n) and mirrored into the tail
    [m-n+1, m) so index k-j wraps to b_{|k-j|}."""
    m = chirp_length(n)
    c = _chirp_np(n, inverse, True)  # build in f64, cast after the FFT
    b = np.zeros(m, dtype=np.complex128)
    b[:n] = np.conj(c)
    b[m - n + 1:] = np.conj(c[1:][::-1])
    dt = np.complex128 if double else np.complex64
    return np.fft.fft(b).astype(dt)


def _is_double(dtype) -> bool:
    return jnp.dtype(dtype) in (jnp.dtype(np.complex128),
                                jnp.dtype(np.float64))


# ---------------------------------------------------------------------------
# core transform along the LAST axis
# ---------------------------------------------------------------------------


def _fft_last(x, inverse: bool):
    """Unnormalized DFT along the last axis of a complex array: smooth
    lengths delegate to the XLA expansion (bit-identical to the "xla"
    backend), everything else runs the chirp-z identity."""
    n = x.shape[-1]
    if is_smooth(n):
        return jnp.fft.ifft(x, norm="forward") if inverse \
            else jnp.fft.fft(x, norm="backward")
    dbl = _is_double(x.dtype)
    m = chirp_length(n)
    c = jnp.asarray(_chirp_np(n, inverse, dbl))
    bf = jnp.asarray(_kernel_spectrum_np(n, inverse, dbl))
    a = jnp.fft.fft(x * c, n=m, norm="backward")
    y = jnp.fft.ifft(a * bf, norm="backward")[..., :n]
    return y * c


# ---------------------------------------------------------------------------
# norm scaling (same FFTNorm semantics as ops/mxu_fft.py)
# ---------------------------------------------------------------------------


def _fwd_scale(n: int, norm: FFTNorm) -> float:
    return 1.0 / math.sqrt(n) if norm is FFTNorm.ORTHO else 1.0


def _inv_scale(n: int, norm: FFTNorm) -> float:
    if norm is FFTNorm.ORTHO:
        return 1.0 / math.sqrt(n)
    if norm is FFTNorm.BACKWARD:
        return 1.0 / n
    return 1.0  # NONE: unnormalized inverse (cuFFT convention)


def _scaled(y, s: float):
    return y if s == 1.0 else y * jnp.asarray(s, dtype=y.dtype).real


def _hermitian_extend(c, n: int):
    """Rebuild the full length-n spectrum from its n//2+1 half (C2R)."""
    tail = jnp.conj(c[..., 1:(n + 1) // 2])[..., ::-1]
    return jnp.concatenate([c, tail], axis=-1)


def _fit_axis(c, axis: int, n: int):
    """Crop or zero-pad ``axis`` to extent n (jnp.fft's ``n=`` semantics)."""
    cur = c.shape[axis]
    if cur > n:
        from jax import lax
        c = lax.slice_in_dim(c, 0, n, axis=axis)
    elif cur < n:
        widths = [(0, 0)] * c.ndim
        widths[axis % c.ndim] = (0, n - cur)
        c = jnp.pad(c, widths)
    return c


# ---------------------------------------------------------------------------
# public API (mirrors ops/mxu_fft.py signatures, dispatched by ops/fft.py)
# ---------------------------------------------------------------------------


def fft(x, axis: int, norm: FFTNorm = FFTNorm.NONE):
    cdt = np.complex128 if _is_double(x.dtype) else np.complex64
    x = jnp.moveaxis(x.astype(cdt), axis, -1)
    y = _scaled(_fft_last(x, False), _fwd_scale(x.shape[-1], norm))
    return jnp.moveaxis(y, -1, axis)


def ifft(x, axis: int, norm: FFTNorm = FFTNorm.NONE):
    cdt = np.complex128 if _is_double(x.dtype) else np.complex64
    x = jnp.moveaxis(x.astype(cdt), axis, -1)
    y = _scaled(_fft_last(x, True), _inv_scale(x.shape[-1], norm))
    return jnp.moveaxis(y, -1, axis)


def rfft(x, axis: int, norm: FFTNorm = FFTNorm.NONE):
    """Forward R2C: smooth axes delegate to the native rfft; a chirp axis
    runs the full complex transform and crops the half spectrum (an odd
    non-smooth length has no real-matmul shortcut worth special-casing)."""
    n = x.shape[axis]
    if is_smooth(n):
        y = jnp.moveaxis(x, axis, -1)
        y = jnp.fft.rfft(y, norm="ortho" if norm is FFTNorm.ORTHO
                         else "backward")
        return jnp.moveaxis(y, -1, axis)
    cdt = np.complex128 if _is_double(x.dtype) else np.complex64
    c = jnp.moveaxis(x.astype(cdt), axis, -1)
    y = _scaled(_fft_last(c, False), _fwd_scale(n, norm))[..., :n // 2 + 1]
    return jnp.moveaxis(y, -1, axis)


def irfft(x, n: int, axis: int, norm: FFTNorm = FFTNorm.NONE):
    if is_smooth(n):
        inorm = {FFTNorm.NONE: "forward", FFTNorm.ORTHO: "ortho",
                 FFTNorm.BACKWARD: "backward"}[norm]
        y = jnp.moveaxis(x, axis, -1)
        y = jnp.fft.irfft(y, n=n, norm=inorm)
        return jnp.moveaxis(y, -1, axis)
    cdt = np.complex128 if _is_double(x.dtype) else np.complex64
    c = jnp.moveaxis(x.astype(cdt), axis, -1)
    c = _fit_axis(c, -1, n // 2 + 1)
    y = jnp.real(_fft_last(_hermitian_extend(c, n), True))
    return jnp.moveaxis(_scaled(y, _inv_scale(n, norm)), -1, axis)


# The n-dimensional wrappers delegate WHOLESALE to the exact jnp.fft
# calls the "xla" backend makes whenever every transformed axis is
# smooth — per-axis composition of the same transforms is numerically
# equivalent but not bit-identical to the fused rfftn/irfftn ops, and
# the backend's contract is "bit-identical to xla off the chirp path"
# (what lets the 'auto' race skip it on smooth shapes).


def fftn(x, axes: Sequence[int], norm: FFTNorm = FFTNorm.NONE):
    if all(is_smooth(x.shape[a]) for a in axes):
        return jnp.fft.fftn(x, axes=tuple(axes),
                            norm="ortho" if norm is FFTNorm.ORTHO
                            else "backward")
    for a in axes:
        x = fft(x, axis=a, norm=norm)
    return x


def ifftn(x, axes: Sequence[int], norm: FFTNorm = FFTNorm.NONE):
    if all(is_smooth(x.shape[a]) for a in axes):
        inorm = {FFTNorm.NONE: "forward", FFTNorm.ORTHO: "ortho",
                 FFTNorm.BACKWARD: "backward"}[norm]
        return jnp.fft.ifftn(x, axes=tuple(axes), norm=inorm)
    for a in axes:
        x = ifft(x, axis=a, norm=norm)
    return x


def rfftn_3d(x, norm: FFTNorm = FFTNorm.NONE):
    if all(is_smooth(n) for n in x.shape[-3:]):
        return jnp.fft.rfftn(x, axes=(-3, -2, -1),
                             norm="ortho" if norm is FFTNorm.ORTHO
                             else "backward")
    c = rfft(x, axis=-1, norm=norm)
    c = fft(c, axis=-2, norm=norm)
    return fft(c, axis=-3, norm=norm)


def irfftn_3d(x, shape_3d: Tuple[int, int, int], norm: FFTNorm = FFTNorm.NONE):
    if all(is_smooth(n) for n in shape_3d[-3:]):
        inorm = {FFTNorm.NONE: "forward", FFTNorm.ORTHO: "ortho",
                 FFTNorm.BACKWARD: "backward"}[norm]
        return jnp.fft.irfftn(x, s=shape_3d, axes=(-3, -2, -1), norm=inorm)
    c = ifft(_fit_axis(x, -3, shape_3d[-3]), axis=-3, norm=norm)
    c = ifft(_fit_axis(c, -2, shape_3d[-2]), axis=-2, norm=norm)
    return irfft(c, n=shape_3d[-1], axis=-1, norm=norm)
