"""Pallas TPU kernel FFT backend (fused multi-stage DFT kernels).

The ``"matmul"`` backend (``ops/mxu_fft.py``) expresses each DFT stage as
XLA ``dot_general`` calls plus elementwise epilogues, trusting the compiler
to fuse and schedule them. This backend hand-writes the hot ops as Pallas
kernels instead, at two granularities:

* **fused 3D path** (``_rfftn3d_fused`` / ``_irfftn3d_fused``): at direct
  sizes (every axis <= ``mxu_fft.DIRECT_MAX``) one kernel computes TWO
  transform stages per HBM pass — z-R2C + y-C2C forward, y-C2C + z-C2R
  inverse — with the inter-stage intermediate resident in VMEM only;
* **per-axis four-step path** (everything else): one kernel = one four-step
  stage, the complex matmul and the twiddle epilogue in a single
  VMEM-resident pass; a real-input variant halves the MXU work of the R2C
  first stage.

MEASURED VERDICT (v5e, 256^3 f32 roundtrip, chained-iteration harness;
round-2 numbers): **matmul@HIGH 1.48-1.51 ms, pallas fused 3.17 ms,
matmul@HIGHEST 2.61 ms** — the matmul backend stays the default, and the
gap is structural, not a tuning artifact:

* the fused zy kernel alone (one HBM pass) measures 0.91 ms where XLA's two
  SEPARATE giant dot_generals + marshalling measure 0.61 ms: Mosaic's
  per-row left-multiply matmuls (needed to keep the kernel transpose-free)
  run at ~2/3 the throughput of XLA's one wide contraction, which costs
  more than the saved intermediate round-trip (~0.17 ms of HBM traffic at
  820 GB/s) recovers;
* ``pallas_call`` is a custom-call boundary: XLA cannot fuse the chain
  carrier or the next stage's operand prep into it, so the composed
  pipeline pays ~0.8 ms of extra HBM passes that the pure-jnp backend's
  end-to-end fusion avoids entirely.

For THIS op — dense matmuls with elementwise epilogues and no data-dependent
access — XLA's own scheduling is already near-optimal, and the productive
TPU-first wins are in backend-level policy (bf16x3 HIGH precision, the
half-spectrum C2R constants, the four-step factorization), not in replacing
dot_general with Mosaic. The backend remains supported, raced honestly by
``testing/autotune.py`` on every platform, and is the right substrate for
ops XLA genuinely schedules badly (double-buffered collective-compute
overlap), but it is NOT the default.

Mosaic constraints encoded here (all discovered on hardware):
``precision=HIGH`` does not lower inside kernels — the HIGH policy is
emulated with an explicit bf16 hi/lo split (``_dot2``); block shapes pad to
(8, 128) tiles, so VMEM budgeting must use padded extents (a 129-wide
half-spectrum block occupies 256 lanes) and the ~16 MB scoped-vmem limit is
a hard compile error when exceeded.

Row-twiddle contract of the per-axis path: for a stage input reshaped to
``(..., n1, n2)`` the flattened matmul row index is ``b*n1 + r``, so the
twiddle row is ``row % n1`` — the kernel receives the twiddle pre-tiled to
the row-block height (a multiple of ``n1``), keeping the epilogue a plain
elementwise multiply with no gather.

Selected via ``Config.fft_backend = "pallas"``. Off-TPU (the CPU test mesh)
the kernels run in Pallas interpret mode; f64 inputs fall back to the
``matmul`` backend's jnp path on TPU (no native f64 there — correctness
gates for double precision run on CPU, SURVEY §7 hard parts).

Public API mirrors ``ops/mxu_fft.py`` (same signatures, same FFTNorm
semantics); the four-step recursion and constant caches are shared with it.
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pltpu imports fail on builds without TPU support compiled in
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..params import FFTNorm
from . import mxu_fft as mx

# Row-block height per grid step (padded up to a multiple of the twiddle
# period n1 when a twiddle is fused). 256 f32 rows x <=512 lanes keeps
# x/y/F/T blocks ~4.5 MB total, comfortably inside ~16 MB VMEM.
_ROW_BLOCK = 256

# Largest contraction length the kernel accepts with the full DFT matrix
# resident in VMEM. Above this (huge prime axis lengths), fall back to the
# jnp matmul path.
_N_MAX = 1024

# MXU precision follows the matmul backend's policy (HIGH three-pass bf16
# for f32 — measured 8.2e-7 fwd rel err at 256^3 — HIGHEST only for f64,
# which this kernel routes to the fallback anyway). See
# mxu_fft.MXUSettings.precision (read via mxu_fft._prec_for, so a plan's
# context-scoped settings reach this kernel too).
def _prec():
    return mx._prec_for(jnp.float32)


def _interpret() -> bool:
    """Compile on TPU; interpret elsewhere (the CPU test mesh)."""
    return jax.default_backend() != "tpu"


def available() -> bool:
    return _HAS_PLTPU


# ---------------------------------------------------------------------------
# Kernels. Complex arrays travel as (real, imag) f32 pairs: Mosaic has no
# native complex tiles, and split planes let each product hit the MXU as a
# plain f32 matmul.
# ---------------------------------------------------------------------------


def _split_bf16(a):
    """bf16 hi + residual lo planes of an f32 value (HIGH emulation)."""
    ah = a.astype(jnp.bfloat16)
    return ah, (a - ah.astype(jnp.float32)).astype(jnp.bfloat16)


def _planes(a):
    """Precision-dependent operand prep, done ONCE per value so constants
    and reused intermediates are not re-split per product.

    Mosaic rejects ``precision=HIGH`` inside kernels (only DEFAULT/HIGHEST
    lower), so the HIGH policy — three-pass bf16 emulation, the measured
    accuracy/speed sweet spot (mxu_fft.MXUSettings.precision) — is emulated by
    splitting each operand into bf16 hi + residual lo here and taking the
    three significant cross products in ``_dot2``, exactly what XLA emits
    for HIGH outside Pallas."""
    if _prec() == lax.Precision.HIGH:
        return _split_bf16(a)
    return (a, None)


def _dot2(ap, bp):
    """Matmul of two ``_planes`` operands at the backend's precision."""
    ah, al = ap
    bh, bl = bp
    if al is None:
        return jnp.dot(ah, bh, precision=_prec(),
                       preferred_element_type=jnp.float32)

    def d(u, v):
        return jnp.dot(u, v, preferred_element_type=jnp.float32)

    return d(ah, bh) + d(ah, bl) + d(al, bh)


def _c2r_kernel(xr_ref, xi_ref, cr_ref, ci_ref, y_ref):
    """Half-spectrum inverse: y = Re(c) @ CR - Im(c) @ CI with conjugate
    symmetry folded into the constant matrices (mxu_fft._c2r_np) — half the
    MXU work of inverting the Hermitian-extended full spectrum."""
    y_ref[:] = (_dot2(_planes(xr_ref[:]), _planes(cr_ref[:]))
                - _dot2(_planes(xi_ref[:]), _planes(ci_ref[:])))


def _cmatmul_kernel(xr_ref, xi_ref, fr_ref, fi_ref, yr_ref, yi_ref):
    xr, xi = _planes(xr_ref[:]), _planes(xi_ref[:])
    fr, fi = _planes(fr_ref[:]), _planes(fi_ref[:])
    yr_ref[:] = _dot2(xr, fr) - _dot2(xi, fi)
    yi_ref[:] = _dot2(xr, fi) + _dot2(xi, fr)


def _cmatmul_tw_kernel(xr_ref, xi_ref, fr_ref, fi_ref, tr_ref, ti_ref,
                       yr_ref, yi_ref):
    xr, xi = _planes(xr_ref[:]), _planes(xi_ref[:])
    fr, fi = _planes(fr_ref[:]), _planes(fi_ref[:])
    yr = _dot2(xr, fr) - _dot2(xi, fi)
    yi = _dot2(xr, fi) + _dot2(xi, fr)
    tr, ti = tr_ref[:], ti_ref[:]
    yr_ref[:] = yr * tr - yi * ti      # twiddle epilogue, fused in VMEM
    yi_ref[:] = yr * ti + yi * tr


def _rmatmul_kernel(x_ref, fr_ref, fi_ref, yr_ref, yi_ref):
    x = _planes(x_ref[:])
    yr_ref[:] = _dot2(x, _planes(fr_ref[:]))
    yi_ref[:] = _dot2(x, _planes(fi_ref[:]))


def _rmatmul_tw_kernel(x_ref, fr_ref, fi_ref, tr_ref, ti_ref,
                       yr_ref, yi_ref):
    x = _planes(x_ref[:])
    yr = _dot2(x, _planes(fr_ref[:]))
    yi = _dot2(x, _planes(fi_ref[:]))
    tr, ti = tr_ref[:], ti_ref[:]
    yr_ref[:] = yr * tr - yi * ti
    yi_ref[:] = yr * ti + yi * tr


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------


def _row_block(period: int) -> int:
    """Row-block height: a multiple of the twiddle period covering >= 256
    rows when possible (period 1 = no twiddle alignment constraint)."""
    if period >= _ROW_BLOCK:
        return period
    return period * (_ROW_BLOCK // period)


@functools.lru_cache(maxsize=None)
def _tiled_twiddle(n1: int, n2: int, inverse: bool, tb: int) -> Tuple[np.ndarray, np.ndarray]:
    """Four-step twiddle tiled up to the row-block height (f32 planes)."""
    t = mx._twiddle_np(n1, n2, inverse, False)
    t = np.tile(t, (tb // n1, 1))
    return (np.ascontiguousarray(t.real.astype(np.float32)),
            np.ascontiguousarray(t.imag.astype(np.float32)))


def _f32_planes(F: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    return (np.ascontiguousarray(F.real.astype(np.float32)),
            np.ascontiguousarray(F.imag.astype(np.float32)))


def _sds(shape, dtype, vma):
    """``ShapeDtypeStruct`` carrying the vma set where the runtime supports
    it (jax >= 0.5); pre-vma runtimes take the bare struct — the set is
    always empty there (see ``_vma``), so nothing is lost."""
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _vma(x) -> frozenset:
    """The value's varying-across-mesh-axes set, or empty when the runtime
    predates ``jax.typeof``/vma tracking (jax < 0.5, where shard_map has no
    per-value vma and nothing needs lifting)."""
    typeof = getattr(jax, "typeof", None)
    return getattr(typeof(x), "vma", frozenset()) if typeof else frozenset()


def _under_rewrite() -> bool:
    """True inside shard_map's replication-checking rewrite on runtimes
    predating vma tracking (jax < 0.5), where ``pallas_call`` has no
    replication rule — the kernels' jnp-equivalent interpret fallback must
    apply there. On vma runtimes ``_vma`` carries this signal instead."""
    if hasattr(jax, "typeof"):
        return False
    try:
        from jax._src import core as jcore
        return type(jcore.trace_ctx.trace).__name__ in ("RewriteTrace",
                                                        "ShardMapTrace")
    except Exception:  # noqa: BLE001 — unknown internals: assume plain trace
        return False


def _lift_vma(args, vma):
    """Under shard_map every kernel operand must carry the same
    varying-across-mesh-axes set; lift replicated constants to match the
    per-shard data."""
    if not vma:
        return args

    def one(a):
        missing = vma - _vma(a)
        return lax.pvary(a, tuple(missing)) if missing else a

    return [one(a) for a in args]


def _call_stage(x2, F_np: np.ndarray, twiddle: "Tuple[int, int, bool] | None"):
    """One DFT stage on 2D data: ``y = (x2 @ F) [* T]``.

    x2: (M, n) complex64 or float32 (real-input fast path); F_np: (n, k);
    twiddle: (n1, n2, inverse) with rows of x2 cycling through n1.
    Returns (M, k) complex64.
    """
    m, n = x2.shape
    k = F_np.shape[1]
    real_in = not jnp.issubdtype(x2.dtype, jnp.complexfloating)

    if _interpret() and (_vma(x2) or _under_rewrite()):
        # Pallas's HLO interpreter cannot yet thread shard_map's vma through
        # its internal grid loop carries; off-TPU, inside shard_map, compute
        # the stage with the equivalent jnp ops (the compiled Mosaic path on
        # real TPU takes the kernel below).
        F = jnp.asarray(F_np.astype(np.complex64))
        y = (mx._rmatmul_F(x2.astype(jnp.float32), F_np.astype(np.complex64))
             if real_in else jnp.matmul(x2.astype(jnp.complex64), F,
                                        precision=_prec()))
        if twiddle is not None:
            n1, n2, inv = twiddle
            tr, ti = _tiled_twiddle(n1, n2, inv, _row_block(n1))
            t = lax.complex(jnp.asarray(tr), jnp.asarray(ti))
            reps = (m + t.shape[0] - 1) // t.shape[0]
            y = y * jnp.tile(t, (reps, 1))[:m]
        return y

    period = twiddle[0] if twiddle is not None else 1
    tb = _row_block(period)
    m_pad = tb * ((m + tb - 1) // tb)
    if m_pad != m:
        x2 = jnp.pad(x2, [(0, m_pad - m), (0, 0)])
    grid = (m_pad // tb,)

    fr, fi = _f32_planes(F_np)
    row_spec = pl.BlockSpec((tb, n), lambda i: (i, 0))
    const_spec = pl.BlockSpec((n, k), lambda i: (0, 0))
    tw_spec = pl.BlockSpec((tb, k), lambda i: (0, 0))
    out_spec = pl.BlockSpec((tb, k), lambda i: (i, 0))
    # Propagate the input's varying-across-mesh-axes set so the kernel works
    # under shard_map's vma checking (per-shard data varies over the mesh).
    vma = _vma(x2)
    out_shape = [_sds((m_pad, k), jnp.float32, vma)] * 2

    flops_c = (2 if real_in else 4) * 2 * m_pad * n * k
    cost = pl.CostEstimate(flops=flops_c, transcendentals=0,
                           bytes_accessed=4 * (m_pad * (n + k) * 2 + n * k * 2))

    if real_in:
        args = [x2.astype(jnp.float32), fr, fi]
        specs = [row_spec, const_spec, const_spec]
        kern = _rmatmul_kernel if twiddle is None else _rmatmul_tw_kernel
    else:
        xc = x2.astype(jnp.complex64)
        args = [jnp.real(xc), jnp.imag(xc), fr, fi]
        specs = [row_spec, row_spec, const_spec, const_spec]
        kern = _cmatmul_kernel if twiddle is None else _cmatmul_tw_kernel
    if twiddle is not None:
        n1, n2, inv = twiddle
        tr, ti = _tiled_twiddle(n1, n2, inv, tb)
        args += [jnp.asarray(tr), jnp.asarray(ti)]
        specs += [tw_spec, tw_spec]
    args = _lift_vma(args, vma)

    yr, yi = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=specs,
        out_specs=[out_spec, out_spec],
        out_shape=out_shape,
        cost_estimate=cost,
        interpret=_interpret(),
    )(*args)
    y = lax.complex(yr, yi)
    return y[:m] if m_pad != m else y


def _stage(x, F_np: np.ndarray, twiddle=None):
    """DFT stage along the LAST axis of an nd array (rows = flattened rest)."""
    lead = x.shape[:-1]
    y2 = _call_stage(x.reshape((-1, x.shape[-1])), F_np, twiddle)
    return y2.reshape(lead + (F_np.shape[1],))


def _c2r_stage(c, n: int):
    """Half-spectrum C2R along the last axis (length n//2+1 -> n, real)."""
    lead = c.shape[:-1]
    c2 = c.reshape((-1, c.shape[-1])).astype(jnp.complex64)
    m, n_in = c2.shape
    CR, CI = mx._c2r_np(n, False)
    xr, xi = jnp.real(c2), jnp.imag(c2)

    if _interpret() and (_vma(c2) or _under_rewrite()):
        y2 = (jnp.matmul(xr, jnp.asarray(CR), precision=_prec())
              - jnp.matmul(xi, jnp.asarray(CI), precision=_prec()))
        return y2.reshape(lead + (n,))

    tb = _row_block(1)
    m_pad = tb * ((m + tb - 1) // tb)
    if m_pad != m:
        xr = jnp.pad(xr, [(0, m_pad - m), (0, 0)])
        xi = jnp.pad(xi, [(0, m_pad - m), (0, 0)])
    vma = _vma(c2)
    row_spec = pl.BlockSpec((tb, n_in), lambda i: (i, 0))
    const_spec = pl.BlockSpec((n_in, n), lambda i: (0, 0))
    out_spec = pl.BlockSpec((tb, n), lambda i: (i, 0))
    args = _lift_vma([xr, xi, jnp.asarray(CR), jnp.asarray(CI)], vma)
    y2 = pl.pallas_call(
        _c2r_kernel,
        grid=(m_pad // tb,),
        in_specs=[row_spec, row_spec, const_spec, const_spec],
        out_specs=out_spec,
        out_shape=_sds((m_pad, n), jnp.float32, vma),
        cost_estimate=pl.CostEstimate(
            flops=2 * 2 * m_pad * n_in * n, transcendentals=0,
            bytes_accessed=4 * (m_pad * (2 * n_in + n) + 2 * n_in * n)),
        interpret=_interpret(),
    )(*args)
    return (y2[:m] if m_pad != m else y2).reshape(lead + (n,))


def _use_fallback(x) -> bool:
    """jnp-matmul fallback: no pltpu build, f64 data (kernel is f32-only;
    f64 gates run via the matmul backend on CPU), or oversized axis."""
    return (not _HAS_PLTPU) or mx._is_double(x.dtype)


# ---------------------------------------------------------------------------
# Fused 3D path: two kernels per direction instead of three axis stages.
#
# At direct sizes (every axis <= mxu_fft.DIRECT_MAX) the per-axis path's cost
# is not the MXU work but the marshalling between stages: each axis transform
# materializes a moveaxis copy plus split real/imag planes in HBM, which XLA
# fuses away for the jnp-matmul backend but a per-axis pallas_call cannot.
# Round 1 measured the consequence: pallas 5.16 ms vs matmul@HIGH 1.51 ms at
# 256^3 (VERDICT "weak" #6). The fused path removes that traffic instead of
# racing it: one kernel computes z-R2C AND y-C2C per x-block entirely in
# VMEM, a second contracts x — two HBM passes per direction, intermediates
# never leave the core. Two structural tricks keep the kernels transpose-free:
#
# * the DFT matrix is symmetric (F[j,k] = w^(jk) = F[k,j]), so the y/x
#   transforms are LEFT-multiplies by the same constant the right-multiply
#   would use: out[k, z] = sum_y F[k, y] c[y, z] — output lands directly in
#   (k, z) order, no in-kernel transpose, and the operand never moves;
# * the C2R half-spectrum matrices (mxu_fft._c2r_np) fold conjugate symmetry
#   into the constants, so the inverse's z stage is two real matmuls fused
#   after the y-inverse in the same kernel pass.
#
# This is the TPU rendering of the reference's opt1 idea taken further: where
# opt1 bakes the transpose into the cuFFT plan's output striding
# (include/mpicufft_slab_opt1.hpp:46-54), here BOTH the layout change and the
# next transform happen inside the producer kernel.
# ---------------------------------------------------------------------------


def _zy_fwd_kernel(x_ref, fzr_ref, fzi_ref, fyr_ref, fyi_ref, yr_ref, yi_ref):
    """z-R2C over the whole block as one wide matmul pair, then per-row
    y-C2C left-multiplies; the (B, Y, Zo) intermediate lives only in VMEM."""
    B, Y, Z = x_ref.shape
    Zo = fzr_ref.shape[1]
    fzr, fzi = _planes(fzr_ref[:]), _planes(fzi_ref[:])
    fyr, fyi = _planes(fyr_ref[:]), _planes(fyi_ref[:])
    xz = _planes(x_ref[:].reshape(B * Y, Z))
    cr = _dot2(xz, fzr).reshape(B, Y, Zo)
    ci = _dot2(xz, fzi).reshape(B, Y, Zo)
    for b in range(B):
        crb, cib = _planes(cr[b]), _planes(ci[b])
        yr_ref[b] = _dot2(fyr, crb) - _dot2(fyi, cib)   # (Ky, Zo)
        yi_ref[b] = _dot2(fyr, cib) + _dot2(fyi, crb)


def _x_c2c_kernel(xr_ref, xi_ref, fr_ref, fi_ref, yr_ref, yi_ref):
    """C2C along axis 0 (x) as a left-multiply, per ky-column of the tile."""
    fr, fi = _planes(fr_ref[:]), _planes(fi_ref[:])
    for t in range(xr_ref.shape[1]):
        ar, ai = _planes(xr_ref[:, t]), _planes(xi_ref[:, t])   # (X, Zo)
        yr_ref[:, t] = _dot2(fr, ar) - _dot2(fi, ai)
        yi_ref[:, t] = _dot2(fr, ai) + _dot2(fi, ar)


def _yz_inv_kernel(xr_ref, xi_ref, fyr_ref, fyi_ref, czr_ref, czi_ref, y_ref,
                   er_s, ei_s):
    """Per x-row y-C2C inverse (left-multiply) into VMEM scratch, then the
    half-spectrum C2R over the whole block as one wide matmul pair."""
    B, Y, Zo = xr_ref.shape
    Z = czr_ref.shape[1]
    fyr, fyi = _planes(fyr_ref[:]), _planes(fyi_ref[:])
    czr, czi = _planes(czr_ref[:]), _planes(czi_ref[:])
    for b in range(B):
        ar, ai = _planes(xr_ref[b]), _planes(xi_ref[b])   # (Ky, Zo)
        er_s[b] = _dot2(fyr, ar) - _dot2(fyi, ai)         # (Y, Zo)
        ei_s[b] = _dot2(fyr, ai) + _dot2(fyi, ar)
    er = _planes(er_s[:].reshape(B * Y, Zo))
    ei = _planes(ei_s[:].reshape(B * Y, Zo))
    y_ref[:] = (_dot2(er, czr) - _dot2(ei, czi)).reshape(B, Y, Z)


# Per-grid-step VMEM budget for the sliced block operands. Mosaic
# double-buffers revolving blocks and keeps the constants resident on top,
# and the ~16 MB scoped-vmem limit is hard (measured: an 18.3 MB working
# set is a compile error, not a slowdown), so the budget is conservative.
_VMEM_BLOCK_BUDGET = 5 << 20

# Mosaic tile geometry: the last block dim pads to 128 lanes, the
# second-to-last to 8 sublanes — VMEM accounting must use PADDED extents
# (a 129-wide half-spectrum block occupies 256 lanes, 2x its logical size).
_SUBLANE = 8


def _lane_pad(n: int) -> int:
    return -(-n // 128) * 128


def _block_rows(per_row_bytes: int) -> int:
    """x-rows per grid step: a power of two <= 8 within the VMEM budget."""
    b = min(8, max(1, _VMEM_BLOCK_BUDGET // max(per_row_bytes, 1)))
    return 1 << (b.bit_length() - 1)


def _x_tile(X: int, Zo: int) -> int:
    """ky-tile for the x-contraction kernel (multiple of 8), or 0 when even
    the smallest legal tile blows the VMEM budget — the caller then
    contracts x with a plain dot_general instead (XLA contracts axis 0
    natively, no marshalling, and supports precision=HIGH outside Mosaic)."""
    per_t = 16 * X * _lane_pad(Zo)   # 4 f32 planes (in r/i + out r/i)
    tk = (_VMEM_BLOCK_BUDGET // max(per_t, 1)) // _SUBLANE * _SUBLANE
    return min(tk, 16) if tk >= _SUBLANE else 0


def _pad_axis_to(x, axis: int, mult: int):
    n = x.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return x, n
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths), n


def fused3d_applicable(shape3, dtype) -> bool:
    """The fused two-kernel path handles 3D arrays whose axes all take a
    single direct DFT matmul; larger axes go through the four-step
    per-axis path."""
    return (_HAS_PLTPU and not mx._is_double(dtype)
            and len(shape3) == 3
            and all(2 <= n <= mx.DIRECT_MAX for n in shape3))


def _const_planes(*mats) -> list:
    out = []
    for m in mats:
        r, i = _f32_planes(m)
        out += [jnp.asarray(r), jnp.asarray(i)]
    return out


def _x_transform(yr, yi, inverse: bool, vma):
    """C2C along axis 0 of split-plane (X, Ky, Zo) data: the Pallas
    left-multiply kernel when a legal tile fits VMEM, else one dot_general
    (XLA contracts axis 0 in place; no moveaxis copies either way)."""
    X, Ky, Zo = yr.shape
    fx = mx._dft_np(X, inverse, False)
    tk = _x_tile(X, Zo)
    if tk == 0:
        z = jnp.einsum("xk,xyz->kyz", jnp.asarray(fx),
                       lax.complex(yr, yi), precision=_prec())
        return jnp.real(z), jnp.imag(z)
    yr, _ = _pad_axis_to(yr, 1, tk)
    yi, _ = _pad_axis_to(yi, 1, tk)
    Kp = yr.shape[1]
    args = _lift_vma([yr, yi] + _const_planes(fx), vma)
    zr, zi = pl.pallas_call(
        _x_c2c_kernel,
        grid=(Kp // tk,),
        in_specs=[pl.BlockSpec((X, tk, Zo), lambda i: (0, i, 0)),
                  pl.BlockSpec((X, tk, Zo), lambda i: (0, i, 0)),
                  pl.BlockSpec((X, X), lambda i: (0, 0)),
                  pl.BlockSpec((X, X), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((X, tk, Zo), lambda i: (0, i, 0))] * 2,
        out_shape=[_sds((X, Kp, Zo), jnp.float32, vma)] * 2,
        cost_estimate=pl.CostEstimate(
            flops=4 * X * X * Kp * Zo * 2, transcendentals=0,
            bytes_accessed=4 * X * Kp * Zo * 4),
        interpret=_interpret(),
    )(*args)
    return zr[:, :Ky], zi[:, :Ky]


def _rfftn3d_fused(x):
    """(X, Y, Z) f32 -> (X, Y, Z//2+1) c64, unnormalized forward."""
    X, Y, Z = x.shape
    Zo = Z // 2 + 1
    vma = _vma(x)

    # Pass 1: fused z-R2C + y-C2C, grid over x blocks. The per-row working
    # set is the input plane, the two output planes, AND the two in-kernel
    # cr/ci intermediate planes the z-stage materializes before the y-stage
    # consumes them.
    B = _block_rows(Y * _lane_pad(Z) * 4 + 4 * Y * _lane_pad(Zo) * 4)
    x, _ = _pad_axis_to(x.astype(jnp.float32), 0, B)
    Xp = x.shape[0]
    fz = mx._dft_np(Z, False, False)[:, :Zo]
    fy = mx._dft_np(Y, False, False)        # symmetric: left-multiply = DFT
    consts = _const_planes(fz, fy)
    args = _lift_vma([x] + consts, vma)
    yr, yi = pl.pallas_call(
        _zy_fwd_kernel,
        grid=(Xp // B,),
        in_specs=[pl.BlockSpec((B, Y, Z), lambda i: (i, 0, 0)),
                  pl.BlockSpec((Z, Zo), lambda i: (0, 0)),
                  pl.BlockSpec((Z, Zo), lambda i: (0, 0)),
                  pl.BlockSpec((Y, Y), lambda i: (0, 0)),
                  pl.BlockSpec((Y, Y), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((B, Y, Zo), lambda i: (i, 0, 0))] * 2,
        out_shape=[_sds((Xp, Y, Zo), jnp.float32, vma)] * 2,
        cost_estimate=pl.CostEstimate(
            flops=2 * Xp * Y * Z * Zo * 2 + 4 * Xp * Y * Y * Zo * 2,
            transcendentals=0,
            bytes_accessed=4 * Xp * Y * (Z + 2 * Zo)),
        interpret=_interpret(),
    )(*args)
    # Pass 2: x-C2C contraction.
    zr, zi = _x_transform(yr[:X], yi[:X], False, vma)
    return lax.complex(zr, zi)


def _irfftn3d_fused(c, shape_3d):
    """(X, Y, Z//2+1)-croppable c64 -> (X, Y, Z) f32, unnormalized inverse."""
    X, Y, Z = shape_3d
    Zo = Z // 2 + 1
    c = c.astype(jnp.complex64)
    for ax, n in ((-3, X), (-2, Y), (-1, Zo)):
        c = mx._fit_axis(c, ax, n)
    vma = _vma(c)

    # Pass 1: x-C2C inverse contraction.
    er, ei = _x_transform(jnp.real(c), jnp.imag(c), True, vma)

    # Pass 2: fused y-C2C inverse + z-C2R, grid over x blocks (the scratch
    # planes for the y-stage intermediate count against the same budget).
    B = _block_rows(4 * Y * _lane_pad(Zo) * 4 + Y * _lane_pad(Z) * 4)
    er, _ = _pad_axis_to(er, 0, B)
    ei, _ = _pad_axis_to(ei, 0, B)
    Xp = er.shape[0]
    fy = mx._dft_np(Y, True, False)
    CR, CI = mx._c2r_np(Z, False)
    args = _lift_vma([er, ei] + _const_planes(fy) +
                     [jnp.asarray(CR), jnp.asarray(CI)], vma)
    y = pl.pallas_call(
        _yz_inv_kernel,
        grid=(Xp // B,),
        in_specs=[pl.BlockSpec((B, Y, Zo), lambda i: (i, 0, 0)),
                  pl.BlockSpec((B, Y, Zo), lambda i: (i, 0, 0)),
                  pl.BlockSpec((Y, Y), lambda i: (0, 0)),
                  pl.BlockSpec((Y, Y), lambda i: (0, 0)),
                  pl.BlockSpec((Zo, Z), lambda i: (0, 0)),
                  pl.BlockSpec((Zo, Z), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((B, Y, Z), lambda i: (i, 0, 0)),
        out_shape=_sds((Xp, Y, Z), jnp.float32, vma),
        scratch_shapes=[pltpu.VMEM((B, Y, Zo), jnp.float32)] * 2,
        cost_estimate=pl.CostEstimate(
            flops=4 * Xp * Y * Y * Zo * 2 + 2 * Xp * Y * Zo * Z * 2,
            transcendentals=0,
            bytes_accessed=4 * Xp * Y * (2 * Zo + Z)),
        interpret=_interpret(),
    )(*args)
    return y[:X]


# ---------------------------------------------------------------------------
# Four-step recursion (structure shared with mxu_fft, stages fused here)
# ---------------------------------------------------------------------------


def _fft_last(x, inverse: bool):
    n = x.shape[-1]
    if _use_fallback(x):
        return mx._fft_last(x, inverse)
    if n <= mx.DIRECT_MAX:
        return _stage(x, mx._dft_np(n, inverse, False))
    n1, n2 = mx._split_for(n, mx.DIRECT_MAX)
    if n1 == 1:  # prime length
        if n <= _N_MAX:
            return _stage(x, mx._dft_np(n, inverse, False))
        return mx._fft_last(x, inverse)
    a = jnp.swapaxes(x.reshape(x.shape[:-1] + (n2, n1)), -1, -2)  # (.., n1, n2)
    if n2 <= mx.DIRECT_MAX:
        # Fused: DFT over s and the twiddle epilogue in one kernel pass.
        c = _stage(a, mx._dft_np(n2, inverse, False), twiddle=(n1, n2, inverse))
    else:
        c = _fft_last(a, inverse) * jnp.asarray(
            mx._twiddle_np(n1, n2, inverse, False))
    d = _fft_last(jnp.swapaxes(c, -1, -2), inverse)
    return jnp.swapaxes(d, -1, -2).reshape(x.shape[:-1] + (n,))


def _rfft_last(x):
    n = x.shape[-1]
    n_out = n // 2 + 1
    if _use_fallback(x):
        return mx._rfft_last(x)
    if n <= mx.DIRECT_MAX:
        return _stage(x, mx._dft_np(n, False, False)[:, :n_out])
    n1, n2 = mx._split_for(n, mx.DIRECT_MAX)
    if n1 == 1:
        if n <= _N_MAX:
            return _stage(x, mx._dft_np(n, False, False)[:, :n_out])
        return mx._rfft_last(x)
    a = jnp.swapaxes(x.reshape(x.shape[:-1] + (n2, n1)), -1, -2)
    if n2 <= mx.DIRECT_MAX:
        # Real-input fused stage: two MXU matmuls + twiddle epilogue.
        c = _stage(a, mx._dft_np(n2, False, False), twiddle=(n1, n2, False))
    else:
        c = _fft_last(a.astype(jnp.complex64), False) * jnp.asarray(
            mx._twiddle_np(n1, n2, False, False))
    d = _fft_last(jnp.swapaxes(c, -1, -2), False)
    full = jnp.swapaxes(d, -1, -2).reshape(x.shape[:-1] + (n,))
    return full[..., :n_out]


# ---------------------------------------------------------------------------
# Fused wire kernels (ISSUE 10, the overlap engine's HBM lever).
#
# The ring renderings encode each TRAVELLING block to the bf16 planar wire
# immediately before its ppermute and decode + FFT it on arrival
# (parallel/transpose.ring_transpose). Composed from jnp ops, that boundary
# costs extra HBM round-trips on TPU whenever a pallas_call sits nearby:
# the custom-call boundary stops XLA from fusing the split/cast/stack into
# the neighboring kernels (the exact structural limit the module verdict
# above documents), so the payload is materialized once in f32 planes and
# again in bf16. These kernels collapse the boundary:
#
# * ``wire_encode_fused``  — planar split + bf16 cast + pack in ONE kernel
#   pass (the send side; there is structurally no per-block FFT to fuse
#   with here — the last pre-exchange FFT always runs along the split
#   axis, so it cannot commute past the per-peer chunking);
# * ``decode_fft_fused``   — bf16 unpack + the first pipelined per-block
#   DFT matmul stage in ONE kernel (the receive side): the planes convert
#   to f32 inside VMEM and feed the MXU contraction directly, so the
#   decoded f32 image never lands in HBM;
# * ``wire_decode_fused``  — unpack-only variant for blocks with no
#   pipelined FFT (every pencil/batched2d ring block, slab ZY_Then_X).
#
# Numerics contract: the jnp fallbacks (off-TPU, f64, oversized axes,
# interpret-mode shard_map) are EXACTLY the unfused compositions, and the
# kernel paths agree with them to the wire's documented bf16 bound (the
# fused DFT runs at the backend's HIGH three-pass emulation; the bf16 wire
# quantization dominates — tests/test_overlap.py pins the bound). The
# encode/decode formulas mirror ``parallel/transpose.wire_encode``/
# ``wire_decode`` and must stay in sync with them.
# ---------------------------------------------------------------------------


def _enc_pack_kernel(xr_ref, xi_ref, yr_ref, yi_ref):
    """Planar split + bf16 cast ("encode + pack") in one VMEM pass."""
    yr_ref[:] = xr_ref[:].astype(jnp.bfloat16)
    yi_ref[:] = xi_ref[:].astype(jnp.bfloat16)


def _dec_unpack_kernel(pr_ref, pi_ref, yr_ref, yi_ref):
    """bf16 planes -> f32 planes (decode/unpack) in one VMEM pass."""
    yr_ref[:] = pr_ref[:].astype(jnp.float32)
    yi_ref[:] = pi_ref[:].astype(jnp.float32)


def _dec_cmatmul_kernel(pr_ref, pi_ref, fr_ref, fi_ref, yr_ref, yi_ref):
    """Fused decode + complex DFT matmul: the bf16 wire planes convert to
    f32 inside VMEM and feed the MXU contraction directly."""
    xr = _planes(pr_ref[:].astype(jnp.float32))
    xi = _planes(pi_ref[:].astype(jnp.float32))
    fr, fi = _planes(fr_ref[:]), _planes(fi_ref[:])
    yr_ref[:] = _dot2(xr, fr) - _dot2(xi, fi)
    yi_ref[:] = _dot2(xr, fi) + _dot2(xi, fr)


def _wire_planes_encode_jnp(x):
    """The unfused encode (== transpose.wire_encode's formula)."""
    return jnp.stack([jnp.real(x), jnp.imag(x)]).astype(jnp.bfloat16)


def _wire_planes_decode_jnp(y, dtype):
    """The unfused decode (== transpose.wire_decode's formula)."""
    f = (jnp.float64 if mx._is_double(dtype) else jnp.float32)
    z = y.astype(f)
    return lax.complex(z[0], z[1])


def _wire_kernel_usable(x) -> bool:
    """Whether the fused wire kernels can run on this value: a pltpu
    build, f32-family data, and not the interpret-mode shard_map corner
    (same contract as ``_call_stage``'s fallback)."""
    return (_HAS_PLTPU and not mx._is_double(x.dtype)
            and not (_interpret() and (_vma(x) or _under_rewrite())))


def _plane_pass(kern, planes, out_dtype):
    """Run an elementwise two-plane kernel over (M, n)-reshaped planes
    with the shared row-block grid."""
    shape = planes[0].shape
    p2 = [p.reshape((-1, shape[-1])) for p in planes]
    m, n = p2[0].shape
    tb = _row_block(1)
    m_pad = tb * ((m + tb - 1) // tb)
    if m_pad != m:
        p2 = [jnp.pad(p, [(0, m_pad - m), (0, 0)]) for p in p2]
    vma = _vma(planes[0])
    spec = pl.BlockSpec((tb, n), lambda i: (i, 0))
    args = _lift_vma(p2, vma)
    yr, yi = pl.pallas_call(
        kern,
        grid=(m_pad // tb,),
        in_specs=[spec, spec],
        out_specs=[spec, spec],
        out_shape=[_sds((m_pad, n), out_dtype, vma)] * 2,
        interpret=_interpret(),
    )(*args)
    if m_pad != m:
        yr, yi = yr[:m], yi[:m]
    return yr.reshape(shape), yi.reshape(shape)


def fused_ring_hooks(config, snd=None):
    """``(encode_fn, arrive_fn)`` for a ring exchange whose arriving
    blocks run NO pipelined per-block FFTs (every pencil and batched-2D
    ring block): the one-pass encode-pack and the unpack-only arrival.
    ``(None, None)`` — the plain wire layer — when the fused wire is
    inactive for this transpose (``Config.fused_wire_for``; ``snd``
    defaults to the config's first-transpose send method). Slab's
    pipelined arrivals build their decode+FFT hook via
    ``SlabFFTPlan._ring_hooks`` instead; both share this module's
    kernels and the Config predicate, so the activation condition lives
    in exactly one place."""
    active = (config.fused_wire_for(snd) if snd is not None
              else config.fused_wire_active())
    if not active:
        return None, None
    from ..parallel.transpose import wire_complex_dtype
    cdt = wire_complex_dtype(config.double_prec)
    return wire_encode_fused, (lambda b: wire_decode_fused(b, cdt))


def wire_encode_fused(x):
    """Complex array -> planar (real, imag) bf16 pair along a new leading
    axis, as ONE kernel pass (the ring's per-travelling-block encode +
    pack). Fallback (off-TPU / f64 / interpret shard_map): the exact
    unfused formula — bit-identical to ``transpose.wire_encode``."""
    if not (jnp.iscomplexobj(x) and _wire_kernel_usable(x)):
        return _wire_planes_encode_jnp(x)
    yr, yi = _plane_pass(_enc_pack_kernel,
                         [jnp.real(x.astype(jnp.complex64)),
                          jnp.imag(x.astype(jnp.complex64))],
                         jnp.bfloat16)
    return jnp.stack([yr, yi])


def wire_decode_fused(y, dtype):
    """Planar bf16 pair -> complex ``dtype`` as ONE kernel pass (the
    unpack-only arrival path of ring blocks with no pipelined FFT).
    Fallback: the exact unfused formula (== ``transpose.wire_decode``)."""
    if mx._is_double(dtype) or not _wire_kernel_usable(y):
        return _wire_planes_decode_jnp(y, dtype)
    zr, zi = _plane_pass(_dec_unpack_kernel, [y[0], y[1]], jnp.float32)
    return lax.complex(zr, zi)


def decode_fft_fused(y, dtype, axis: int, *, inverse: bool = False,
                     norm: FFTNorm = FFTNorm.NONE, settings=None):
    """Fused wire decode + per-block DFT along ``axis`` of the decoded
    block: the bf16 planes feed the MXU contraction inside VMEM, so the
    decoded f32 image never round-trips HBM. The DFT is the direct
    matmul (the fusion IS the matmul — regardless of the plan's
    ``fft_backend``); axes past ``_N_MAX`` and every fallback condition
    run the exact unfused composition ``mxu_fft.(i)fft(decode(y))``
    under the same settings."""
    with mx.use_settings(settings):
        n = y.shape[1:][axis]
        # The f64 guard keys on the TARGET dtype, not the payload (the
        # bf16 planes are never 'double'): a double_prec plan's arrived
        # blocks must restore complex128 via the unfused composition,
        # not silently drop to the f32 kernel.
        if (mx._is_double(dtype) or not _wire_kernel_usable(y)
                or n > _N_MAX):
            c = _wire_planes_decode_jnp(y, dtype)
            return (mx.ifft if inverse else mx.fft)(c, axis=axis, norm=norm)
        # Planes to (M, n) rows with the DFT axis last (the same relayout
        # the unfused lf.fft pays), then one fused kernel.
        pr = jnp.moveaxis(y[0], axis, -1)
        pi = jnp.moveaxis(y[1], axis, -1)
        shape = pr.shape
        pr2, pi2 = pr.reshape((-1, n)), pi.reshape((-1, n))
        m = pr2.shape[0]
        tb = _row_block(1)
        m_pad = tb * ((m + tb - 1) // tb)
        if m_pad != m:
            pr2 = jnp.pad(pr2, [(0, m_pad - m), (0, 0)])
            pi2 = jnp.pad(pi2, [(0, m_pad - m), (0, 0)])
        fr, fi = _f32_planes(mx._dft_np(n, inverse, False))
        vma = _vma(y)
        row_spec = pl.BlockSpec((tb, n), lambda i: (i, 0))
        const_spec = pl.BlockSpec((n, n), lambda i: (0, 0))
        args = _lift_vma([pr2, pi2, jnp.asarray(fr), jnp.asarray(fi)], vma)
        yr, yi = pl.pallas_call(
            _dec_cmatmul_kernel,
            grid=(m_pad // tb,),
            in_specs=[row_spec, row_spec, const_spec, const_spec],
            out_specs=[row_spec, row_spec],
            out_shape=[_sds((m_pad, n), jnp.float32, vma)] * 2,
            cost_estimate=pl.CostEstimate(
                flops=4 * 2 * m_pad * n * n, transcendentals=0,
                bytes_accessed=2 * m_pad * n * 2 + 4 * (m_pad + n) * n * 2),
            interpret=_interpret(),
        )(*args)
        if m_pad != m:
            yr, yi = yr[:m], yi[:m]
        out = lax.complex(yr, yi).reshape(shape)
        scale = (mx._inv_scale(n, norm) if inverse
                 else mx._fwd_scale(n, norm))
        return jnp.moveaxis(mx._scaled(out, scale), -1, axis)


# ---------------------------------------------------------------------------
# Public API (mirrors ops/mxu_fft.py; same FFTNorm semantics)
# ---------------------------------------------------------------------------


def fft(x, axis: int, norm: FFTNorm = FFTNorm.NONE):
    x = jnp.moveaxis(x, axis, -1)
    if not mx._is_double(x.dtype):
        x = x.astype(jnp.complex64)
    y = mx._scaled(_fft_last(x, False), mx._fwd_scale(x.shape[-1], norm))
    return jnp.moveaxis(y, -1, axis)


def ifft(x, axis: int, norm: FFTNorm = FFTNorm.NONE):
    x = jnp.moveaxis(x, axis, -1)
    if not mx._is_double(x.dtype):
        x = x.astype(jnp.complex64)
    y = mx._scaled(_fft_last(x, True), mx._inv_scale(x.shape[-1], norm))
    return jnp.moveaxis(y, -1, axis)


def rfft(x, axis: int, norm: FFTNorm = FFTNorm.NONE):
    x = jnp.moveaxis(x, axis, -1)
    y = mx._scaled(_rfft_last(x), mx._fwd_scale(x.shape[-1], norm))
    return jnp.moveaxis(y, -1, axis)


def irfft(x, n: int, axis: int, norm: FFTNorm = FFTNorm.NONE):
    c = jnp.moveaxis(x, axis, -1)
    if not mx._is_double(c.dtype):
        c = c.astype(jnp.complex64)
    c = mx._fit_axis(c, -1, n // 2 + 1)
    if _use_fallback(c) or n > mx.DIRECT_MAX:
        full = mx._hermitian_extend(c, n)
        y = jnp.real(_fft_last(full, True))
    else:
        y = _c2r_stage(c, n)
    return jnp.moveaxis(mx._scaled(y, mx._inv_scale(n, norm)), -1, axis)


def fftn(x, axes: Sequence[int], norm: FFTNorm = FFTNorm.NONE):
    for a in axes:
        x = fft(x, axis=a, norm=norm)
    return x


def ifftn(x, axes: Sequence[int], norm: FFTNorm = FFTNorm.NONE):
    for a in axes:
        x = ifft(x, axis=a, norm=norm)
    return x


def _fused3d_usable(x, shape3) -> bool:
    # Under shard_map in interpret mode (the CPU test mesh) the per-axis
    # path's jnp fallback applies; everywhere else the fused path rules at
    # direct sizes.
    return (fused3d_applicable(shape3, x.dtype)
            and not (_interpret()
                     and (_vma(x) or _under_rewrite())))


def rfftn_3d(x, norm: FFTNorm = FFTNorm.NONE):
    if x.ndim == 3 and _fused3d_usable(x, x.shape):
        s = 1.0
        for n in x.shape:
            s *= mx._fwd_scale(n, norm)
        return mx._scaled(_rfftn3d_fused(x), s)
    c = rfft(x, axis=-1, norm=norm)
    c = fft(c, axis=-2, norm=norm)
    return fft(c, axis=-3, norm=norm)


def irfftn_3d(x, shape_3d: Tuple[int, int, int], norm: FFTNorm = FFTNorm.NONE):
    if x.ndim == 3 and _fused3d_usable(x, shape_3d):
        s = 1.0
        for n in shape_3d:
            s *= mx._inv_scale(n, norm)
        return mx._scaled(_irfftn3d_fused(x, tuple(shape_3d)), s)
    c = ifft(mx._fit_axis(x, -3, shape_3d[-3]), axis=-3, norm=norm)
    c = ifft(mx._fit_axis(c, -2, shape_3d[-2]), axis=-2, norm=norm)
    return irfft(c, n=shape_3d[-1], axis=-1, norm=norm)
