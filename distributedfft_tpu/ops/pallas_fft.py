"""Pallas TPU kernel FFT backend (fused DFT-matmul + twiddle epilogue).

The ``"matmul"`` backend (``ops/mxu_fft.py``) expresses each four-step DFT
stage as XLA ``dot_general`` calls plus a separate elementwise twiddle
multiply, trusting the compiler to fuse and schedule them. This backend makes
that hot op a hand-written Pallas kernel instead:

* one kernel = one four-step stage: the complex matmul (four real MXU
  matmuls) **and** the twiddle multiply run in a single VMEM-resident pass,
  so intermediate stage output never round-trips to HBM between the matmul
  and the twiddle (the analog of the reference baking the transpose into the
  cuFFT plan's striding, ``include/mpicufft_slab_opt1.hpp:46-54`` — move work
  into the producer instead of a separate pass);
* a real-input variant halves the MXU work for the R2C first stage (two real
  matmuls instead of four);
* the grid tiles the flattened batch rows; DFT/twiddle constants are a
  single VMEM block reused by every grid step.

Row-twiddle contract: for a stage input reshaped to ``(..., n1, n2)`` the
flattened matmul row index is ``b*n1 + r``, so the twiddle row is
``row % n1`` — the kernel receives the twiddle pre-tiled to the row-block
height (a multiple of ``n1``), keeping the epilogue a plain elementwise
multiply with no gather.

Selected via ``Config.fft_backend = "pallas"``. Off-TPU (the CPU test mesh)
the kernels run in Pallas interpret mode; f64 inputs fall back to the
``matmul`` backend's jnp path on TPU (no native f64 there — correctness
gates for double precision run on CPU, SURVEY §7 hard parts).

Public API mirrors ``ops/mxu_fft.py`` (same signatures, same FFTNorm
semantics); the four-step recursion and constant caches are shared with it.
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pltpu imports fail on builds without TPU support compiled in
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..params import FFTNorm
from . import mxu_fft as mx

# Row-block height per grid step (padded up to a multiple of the twiddle
# period n1 when a twiddle is fused). 256 f32 rows x <=512 lanes keeps
# x/y/F/T blocks ~4.5 MB total, comfortably inside ~16 MB VMEM.
_ROW_BLOCK = 256

# Largest contraction length the kernel accepts with the full DFT matrix
# resident in VMEM. Above this (huge prime axis lengths), fall back to the
# jnp matmul path.
_N_MAX = 1024

# MXU precision follows the matmul backend's policy (HIGH three-pass bf16
# for f32 — measured 8.2e-7 fwd rel err at 256^3 — HIGHEST only for f64,
# which this kernel routes to the fallback anyway). See mxu_fft._PREC_SINGLE.
def _prec():
    return mx._prec_for(jnp.float32)


def _interpret() -> bool:
    """Compile on TPU; interpret elsewhere (the CPU test mesh)."""
    return jax.default_backend() != "tpu"


def available() -> bool:
    return _HAS_PLTPU


# ---------------------------------------------------------------------------
# Kernels. Complex arrays travel as (real, imag) f32 pairs: Mosaic has no
# native complex tiles, and split planes let each product hit the MXU as a
# plain f32 matmul.
# ---------------------------------------------------------------------------


def _dot(a, b):
    return jnp.dot(a, b, precision=_prec(), preferred_element_type=jnp.float32)


def _c2r_kernel(xr_ref, xi_ref, cr_ref, ci_ref, y_ref):
    """Half-spectrum inverse: y = Re(c) @ CR - Im(c) @ CI with conjugate
    symmetry folded into the constant matrices (mxu_fft._c2r_np) — half the
    MXU work of inverting the Hermitian-extended full spectrum."""
    y_ref[:] = _dot(xr_ref[:], cr_ref[:]) - _dot(xi_ref[:], ci_ref[:])


def _cmatmul_kernel(xr_ref, xi_ref, fr_ref, fi_ref, yr_ref, yi_ref):
    xr, xi = xr_ref[:], xi_ref[:]
    fr, fi = fr_ref[:], fi_ref[:]
    yr_ref[:] = _dot(xr, fr) - _dot(xi, fi)
    yi_ref[:] = _dot(xr, fi) + _dot(xi, fr)


def _cmatmul_tw_kernel(xr_ref, xi_ref, fr_ref, fi_ref, tr_ref, ti_ref,
                       yr_ref, yi_ref):
    xr, xi = xr_ref[:], xi_ref[:]
    fr, fi = fr_ref[:], fi_ref[:]
    yr = _dot(xr, fr) - _dot(xi, fi)
    yi = _dot(xr, fi) + _dot(xi, fr)
    tr, ti = tr_ref[:], ti_ref[:]
    yr_ref[:] = yr * tr - yi * ti      # twiddle epilogue, fused in VMEM
    yi_ref[:] = yr * ti + yi * tr


def _rmatmul_kernel(x_ref, fr_ref, fi_ref, yr_ref, yi_ref):
    x = x_ref[:]
    yr_ref[:] = _dot(x, fr_ref[:])
    yi_ref[:] = _dot(x, fi_ref[:])


def _rmatmul_tw_kernel(x_ref, fr_ref, fi_ref, tr_ref, ti_ref,
                       yr_ref, yi_ref):
    x = x_ref[:]
    yr = _dot(x, fr_ref[:])
    yi = _dot(x, fi_ref[:])
    tr, ti = tr_ref[:], ti_ref[:]
    yr_ref[:] = yr * tr - yi * ti
    yi_ref[:] = yr * ti + yi * tr


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------


def _row_block(period: int) -> int:
    """Row-block height: a multiple of the twiddle period covering >= 256
    rows when possible (period 1 = no twiddle alignment constraint)."""
    if period >= _ROW_BLOCK:
        return period
    return period * (_ROW_BLOCK // period)


@functools.lru_cache(maxsize=None)
def _tiled_twiddle(n1: int, n2: int, inverse: bool, tb: int) -> Tuple[np.ndarray, np.ndarray]:
    """Four-step twiddle tiled up to the row-block height (f32 planes)."""
    t = mx._twiddle_np(n1, n2, inverse, False)
    t = np.tile(t, (tb // n1, 1))
    return (np.ascontiguousarray(t.real.astype(np.float32)),
            np.ascontiguousarray(t.imag.astype(np.float32)))


def _f32_planes(F: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    return (np.ascontiguousarray(F.real.astype(np.float32)),
            np.ascontiguousarray(F.imag.astype(np.float32)))


def _lift_vma(args, vma):
    """Under shard_map every kernel operand must carry the same
    varying-across-mesh-axes set; lift replicated constants to match the
    per-shard data."""
    if not vma:
        return args

    def one(a):
        missing = vma - getattr(jax.typeof(a), "vma", frozenset())
        return lax.pvary(a, tuple(missing)) if missing else a

    return [one(a) for a in args]


def _call_stage(x2, F_np: np.ndarray, twiddle: "Tuple[int, int, bool] | None"):
    """One DFT stage on 2D data: ``y = (x2 @ F) [* T]``.

    x2: (M, n) complex64 or float32 (real-input fast path); F_np: (n, k);
    twiddle: (n1, n2, inverse) with rows of x2 cycling through n1.
    Returns (M, k) complex64.
    """
    m, n = x2.shape
    k = F_np.shape[1]
    real_in = not jnp.issubdtype(x2.dtype, jnp.complexfloating)

    if _interpret() and getattr(jax.typeof(x2), "vma", frozenset()):
        # Pallas's HLO interpreter cannot yet thread shard_map's vma through
        # its internal grid loop carries; off-TPU, inside shard_map, compute
        # the stage with the equivalent jnp ops (the compiled Mosaic path on
        # real TPU takes the kernel below).
        F = jnp.asarray(F_np.astype(np.complex64))
        y = (mx._rmatmul_F(x2.astype(jnp.float32), F_np.astype(np.complex64))
             if real_in else jnp.matmul(x2.astype(jnp.complex64), F,
                                        precision=_prec()))
        if twiddle is not None:
            n1, n2, inv = twiddle
            tr, ti = _tiled_twiddle(n1, n2, inv, _row_block(n1))
            t = lax.complex(jnp.asarray(tr), jnp.asarray(ti))
            reps = (m + t.shape[0] - 1) // t.shape[0]
            y = y * jnp.tile(t, (reps, 1))[:m]
        return y

    period = twiddle[0] if twiddle is not None else 1
    tb = _row_block(period)
    m_pad = tb * ((m + tb - 1) // tb)
    if m_pad != m:
        x2 = jnp.pad(x2, [(0, m_pad - m), (0, 0)])
    grid = (m_pad // tb,)

    fr, fi = _f32_planes(F_np)
    row_spec = pl.BlockSpec((tb, n), lambda i: (i, 0))
    const_spec = pl.BlockSpec((n, k), lambda i: (0, 0))
    tw_spec = pl.BlockSpec((tb, k), lambda i: (0, 0))
    out_spec = pl.BlockSpec((tb, k), lambda i: (i, 0))
    # Propagate the input's varying-across-mesh-axes set so the kernel works
    # under shard_map's vma checking (per-shard data varies over the mesh).
    vma = getattr(jax.typeof(x2), "vma", frozenset())
    out_shape = [jax.ShapeDtypeStruct((m_pad, k), jnp.float32, vma=vma)] * 2

    flops_c = (2 if real_in else 4) * 2 * m_pad * n * k
    cost = pl.CostEstimate(flops=flops_c, transcendentals=0,
                           bytes_accessed=4 * (m_pad * (n + k) * 2 + n * k * 2))

    if real_in:
        args = [x2.astype(jnp.float32), fr, fi]
        specs = [row_spec, const_spec, const_spec]
        kern = _rmatmul_kernel if twiddle is None else _rmatmul_tw_kernel
    else:
        xc = x2.astype(jnp.complex64)
        args = [jnp.real(xc), jnp.imag(xc), fr, fi]
        specs = [row_spec, row_spec, const_spec, const_spec]
        kern = _cmatmul_kernel if twiddle is None else _cmatmul_tw_kernel
    if twiddle is not None:
        n1, n2, inv = twiddle
        tr, ti = _tiled_twiddle(n1, n2, inv, tb)
        args += [jnp.asarray(tr), jnp.asarray(ti)]
        specs += [tw_spec, tw_spec]
    args = _lift_vma(args, vma)

    yr, yi = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=specs,
        out_specs=[out_spec, out_spec],
        out_shape=out_shape,
        cost_estimate=cost,
        interpret=_interpret(),
    )(*args)
    y = lax.complex(yr, yi)
    return y[:m] if m_pad != m else y


def _stage(x, F_np: np.ndarray, twiddle=None):
    """DFT stage along the LAST axis of an nd array (rows = flattened rest)."""
    lead = x.shape[:-1]
    y2 = _call_stage(x.reshape((-1, x.shape[-1])), F_np, twiddle)
    return y2.reshape(lead + (F_np.shape[1],))


def _c2r_stage(c, n: int):
    """Half-spectrum C2R along the last axis (length n//2+1 -> n, real)."""
    lead = c.shape[:-1]
    c2 = c.reshape((-1, c.shape[-1])).astype(jnp.complex64)
    m, n_in = c2.shape
    CR, CI = mx._c2r_np(n, False)
    xr, xi = jnp.real(c2), jnp.imag(c2)

    if _interpret() and getattr(jax.typeof(c2), "vma", frozenset()):
        y2 = (jnp.matmul(xr, jnp.asarray(CR), precision=_prec())
              - jnp.matmul(xi, jnp.asarray(CI), precision=_prec()))
        return y2.reshape(lead + (n,))

    tb = _row_block(1)
    m_pad = tb * ((m + tb - 1) // tb)
    if m_pad != m:
        xr = jnp.pad(xr, [(0, m_pad - m), (0, 0)])
        xi = jnp.pad(xi, [(0, m_pad - m), (0, 0)])
    vma = getattr(jax.typeof(c2), "vma", frozenset())
    row_spec = pl.BlockSpec((tb, n_in), lambda i: (i, 0))
    const_spec = pl.BlockSpec((n_in, n), lambda i: (0, 0))
    out_spec = pl.BlockSpec((tb, n), lambda i: (i, 0))
    args = _lift_vma([xr, xi, jnp.asarray(CR), jnp.asarray(CI)], vma)
    y2 = pl.pallas_call(
        _c2r_kernel,
        grid=(m_pad // tb,),
        in_specs=[row_spec, row_spec, const_spec, const_spec],
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct((m_pad, n), jnp.float32, vma=vma),
        cost_estimate=pl.CostEstimate(
            flops=2 * 2 * m_pad * n_in * n, transcendentals=0,
            bytes_accessed=4 * (m_pad * (2 * n_in + n) + 2 * n_in * n)),
        interpret=_interpret(),
    )(*args)
    return (y2[:m] if m_pad != m else y2).reshape(lead + (n,))


def _use_fallback(x) -> bool:
    """jnp-matmul fallback: no pltpu build, f64 data (kernel is f32-only;
    f64 gates run via the matmul backend on CPU), or oversized axis."""
    return (not _HAS_PLTPU) or mx._is_double(x.dtype)


# ---------------------------------------------------------------------------
# Four-step recursion (structure shared with mxu_fft, stages fused here)
# ---------------------------------------------------------------------------


def _fft_last(x, inverse: bool):
    n = x.shape[-1]
    if _use_fallback(x):
        return mx._fft_last(x, inverse)
    if n <= mx.DIRECT_MAX:
        return _stage(x, mx._dft_np(n, inverse, False))
    n1, n2 = mx._split(n)
    if n1 == 1:  # prime length
        if n <= _N_MAX:
            return _stage(x, mx._dft_np(n, inverse, False))
        return mx._fft_last(x, inverse)
    a = jnp.swapaxes(x.reshape(x.shape[:-1] + (n2, n1)), -1, -2)  # (.., n1, n2)
    if n2 <= mx.DIRECT_MAX:
        # Fused: DFT over s and the twiddle epilogue in one kernel pass.
        c = _stage(a, mx._dft_np(n2, inverse, False), twiddle=(n1, n2, inverse))
    else:
        c = _fft_last(a, inverse) * jnp.asarray(
            mx._twiddle_np(n1, n2, inverse, False))
    d = _fft_last(jnp.swapaxes(c, -1, -2), inverse)
    return jnp.swapaxes(d, -1, -2).reshape(x.shape[:-1] + (n,))


def _rfft_last(x):
    n = x.shape[-1]
    n_out = n // 2 + 1
    if _use_fallback(x):
        return mx._rfft_last(x)
    if n <= mx.DIRECT_MAX:
        return _stage(x, mx._dft_np(n, False, False)[:, :n_out])
    n1, n2 = mx._split(n)
    if n1 == 1:
        if n <= _N_MAX:
            return _stage(x, mx._dft_np(n, False, False)[:, :n_out])
        return mx._rfft_last(x)
    a = jnp.swapaxes(x.reshape(x.shape[:-1] + (n2, n1)), -1, -2)
    if n2 <= mx.DIRECT_MAX:
        # Real-input fused stage: two MXU matmuls + twiddle epilogue.
        c = _stage(a, mx._dft_np(n2, False, False), twiddle=(n1, n2, False))
    else:
        c = _fft_last(a.astype(jnp.complex64), False) * jnp.asarray(
            mx._twiddle_np(n1, n2, False, False))
    d = _fft_last(jnp.swapaxes(c, -1, -2), False)
    full = jnp.swapaxes(d, -1, -2).reshape(x.shape[:-1] + (n,))
    return full[..., :n_out]


# ---------------------------------------------------------------------------
# Public API (mirrors ops/mxu_fft.py; same FFTNorm semantics)
# ---------------------------------------------------------------------------


def fft(x, axis: int, norm: FFTNorm = FFTNorm.NONE):
    x = jnp.moveaxis(x, axis, -1)
    if not mx._is_double(x.dtype):
        x = x.astype(jnp.complex64)
    y = mx._scaled(_fft_last(x, False), mx._fwd_scale(x.shape[-1], norm))
    return jnp.moveaxis(y, -1, axis)


def ifft(x, axis: int, norm: FFTNorm = FFTNorm.NONE):
    x = jnp.moveaxis(x, axis, -1)
    if not mx._is_double(x.dtype):
        x = x.astype(jnp.complex64)
    y = mx._scaled(_fft_last(x, True), mx._inv_scale(x.shape[-1], norm))
    return jnp.moveaxis(y, -1, axis)


def rfft(x, axis: int, norm: FFTNorm = FFTNorm.NONE):
    x = jnp.moveaxis(x, axis, -1)
    y = mx._scaled(_rfft_last(x), mx._fwd_scale(x.shape[-1], norm))
    return jnp.moveaxis(y, -1, axis)


def irfft(x, n: int, axis: int, norm: FFTNorm = FFTNorm.NONE):
    c = jnp.moveaxis(x, axis, -1)
    if not mx._is_double(c.dtype):
        c = c.astype(jnp.complex64)
    c = mx._fit_axis(c, -1, n // 2 + 1)
    if _use_fallback(c) or n > mx.DIRECT_MAX:
        full = mx._hermitian_extend(c, n)
        y = jnp.real(_fft_last(full, True))
    else:
        y = _c2r_stage(c, n)
    return jnp.moveaxis(mx._scaled(y, mx._inv_scale(n, norm)), -1, axis)


def fftn(x, axes: Sequence[int], norm: FFTNorm = FFTNorm.NONE):
    for a in axes:
        x = fft(x, axis=a, norm=norm)
    return x


def ifftn(x, axes: Sequence[int], norm: FFTNorm = FFTNorm.NONE):
    for a in axes:
        x = ifft(x, axis=a, norm=norm)
    return x


def rfftn_3d(x, norm: FFTNorm = FFTNorm.NONE):
    c = rfft(x, axis=-1, norm=norm)
    c = fft(c, axis=-2, norm=norm)
    return fft(c, axis=-3, norm=norm)


def irfftn_3d(x, shape_3d: Tuple[int, int, int], norm: FFTNorm = FFTNorm.NONE):
    c = ifft(mx._fit_axis(x, -3, shape_3d[-3]), axis=-3, norm=norm)
    c = ifft(mx._fit_axis(c, -2, shape_3d[-2]), axis=-2, norm=norm)
    return irfft(c, n=shape_3d[-1], axis=-1, norm=norm)
