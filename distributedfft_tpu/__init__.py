"""distributedfft_tpu — TPU-native distributed 3D FFT framework.

A from-scratch JAX/XLA re-design of the capabilities of the reference
CUDA/MPI library eggersn/DistributedFFT: slab and pencil domain
decompositions of 3D R2C/C2R (and C2C) FFTs, executed as single jitted XLA
programs of local FFTs and mesh collectives over ICI/DCN, with the
reference's plan/execute API shape, testcase semantics, benchmark timer and
evaluation tooling.
"""

# jax version shim: the framework (and its tests) call ``jax.shard_map``,
# which jax only exports at top level from 0.5; on older runtimes alias the
# experimental implementation so every call site keeps working. Installed
# here because importing ANY package submodule runs this first.
import jax as _jax

if not hasattr(_jax, "shard_map"):  # jax < 0.5
    from jax.experimental.shard_map import shard_map as _shard_map

    _jax.shard_map = _shard_map
del _jax

from .params import (
    AUTO,
    CommMethod,
    Config,
    FFTNorm,
    GlobalSize,
    PartitionDims,
    PencilPartition,
    SendMethod,
    SlabPartition,
    SlabSequence,
    block_sizes,
    block_starts,
    padded_extent,
    parse_comm_method,
)
from .parallel.mesh import (
    PENCIL_AXES,
    SLAB_AXIS,
    best_pencil_grid,
    make_pencil_mesh,
    make_slab_mesh,
)
from .parallel.multihost import (
    global_from_local,
    maybe_initialize,
    process_local_slices,
)
from .models.base import DistFFTPlan
from .models.batched2d import Batched2DFFTPlan
from .models.pencil import PencilFFTPlan
from .models.slab import SlabFFTPlan
from .resilience import GuardViolation
from .solvers import (
    NavierStokes2D,
    NavierStokes3D,
    PoissonSolver,
    SpectralConvolver,
    make_convolver,
    make_solver,
)

__all__ = [
    "AUTO", "CommMethod", "Config", "FFTNorm", "GlobalSize", "PartitionDims",
    "PencilPartition", "SendMethod", "SlabPartition", "SlabSequence",
    "block_sizes", "block_starts", "padded_extent", "parse_comm_method",
    "PENCIL_AXES", "SLAB_AXIS", "best_pencil_grid", "make_pencil_mesh",
    "make_slab_mesh", "Batched2DFFTPlan", "DistFFTPlan", "GuardViolation",
    "NavierStokes2D", "NavierStokes3D", "PencilFFTPlan", "PoissonSolver",
    "SlabFFTPlan", "SpectralConvolver", "global_from_local",
    "make_convolver", "make_solver", "maybe_initialize",
    "process_local_slices",
]

__version__ = "0.1.0"
