"""distributedfft_tpu — TPU-native distributed 3D FFT framework.

A from-scratch JAX/XLA re-design of the capabilities of the reference
CUDA/MPI library eggersn/DistributedFFT: slab and pencil domain
decompositions of 3D R2C/C2R (and C2C) FFTs, executed as single jitted XLA
programs of local FFTs and mesh collectives over ICI/DCN, with the
reference's plan/execute API shape, testcase semantics, benchmark timer and
evaluation tooling.
"""

from .params import (
    CommMethod,
    Config,
    FFTNorm,
    GlobalSize,
    PartitionDims,
    PencilPartition,
    SendMethod,
    SlabPartition,
    SlabSequence,
    block_sizes,
    block_starts,
    padded_extent,
)
from .parallel.mesh import (
    PENCIL_AXES,
    SLAB_AXIS,
    best_pencil_grid,
    make_pencil_mesh,
    make_slab_mesh,
)
from .parallel.multihost import (
    global_from_local,
    maybe_initialize,
    process_local_slices,
)
from .models.base import DistFFTPlan
from .models.batched2d import Batched2DFFTPlan
from .models.pencil import PencilFFTPlan
from .models.slab import SlabFFTPlan
from .solvers.poisson import PoissonSolver

__all__ = [
    "CommMethod", "Config", "FFTNorm", "GlobalSize", "PartitionDims",
    "PencilPartition", "SendMethod", "SlabPartition", "SlabSequence",
    "block_sizes", "block_starts", "padded_extent",
    "PENCIL_AXES", "SLAB_AXIS", "best_pencil_grid", "make_pencil_mesh",
    "make_slab_mesh", "Batched2DFFTPlan", "DistFFTPlan", "PencilFFTPlan",
    "PoissonSolver", "SlabFFTPlan",
    "global_from_local", "maybe_initialize", "process_local_slices",
]

__version__ = "0.1.0"
