"""Per-key circuit breaker — stop re-executing a plan that keeps failing.

The fallback ladder (``fallback.py``) handles ONE failure gracefully:
demote a rung, rebuild, retry. A serving process needs the next layer up:
when a plan key fails repeatedly even through the ladder (a poisoned
shape, a faulted link, a compiler regression), re-running it burns the
queue's latency budget on work that is known-bad. The breaker turns that
into fast, structured rejection:

* ``closed``    — normal operation; failures are counted, any success
  resets the count.
* ``open``      — ``failure_threshold`` CONSECUTIVE failures trip the
  circuit: ``allow()`` answers False (callers reject with
  :class:`CircuitOpen` instead of executing) until ``cooldown_s`` has
  passed.
* ``half_open`` — after the cooldown, exactly ONE probe call is admitted.
  Its success closes the circuit (normal traffic resumes); its failure
  re-opens it for another cooldown.

Every transition is loud: an ``obs.event`` named
``<prefix>.open|half_open|close`` (the serving layer uses prefix
``serve.circuit``, so chaos CI can grep the event log for
``serve.circuit.*`` evidence) and ``<prefix>.opened/closed/reopened``
metrics. The breaker is thread-safe and makes no assumptions about WHAT
failed — callers decide which exceptions count via ``record_failure``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from .. import obs

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitOpen(RuntimeError):
    """Structured rejection: the key's circuit is open (or its half-open
    probe slot is taken); the request was NOT executed."""

    def __init__(self, key: str, retry_after_s: float):
        super().__init__(
            f"circuit open for {key!r} (retry after "
            f"{max(retry_after_s, 0.0):.2f} s)")
        self.key = key
        self.retry_after_s = max(float(retry_after_s), 0.0)


class CircuitBreaker:
    """One key's breaker; see module docstring for the state machine."""

    def __init__(self, key: str, failure_threshold: int = 3,
                 cooldown_s: float = 5.0,
                 metrics_prefix: str = "circuit"):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if cooldown_s < 0:
            raise ValueError("cooldown_s must be >= 0")
        self.key = key
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self.prefix = metrics_prefix
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self._last_error: Optional[str] = None

    # -- introspection ----------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> Dict[str, object]:
        """Health-endpoint view of this breaker."""
        with self._lock:
            snap: Dict[str, object] = {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "failure_threshold": self.failure_threshold,
            }
            if self._state != CLOSED:
                snap["cooldown_remaining_s"] = round(
                    max(self._opened_at + self.cooldown_s
                        - time.monotonic(), 0.0), 3)
            if self._last_error:
                snap["last_error"] = self._last_error
            return snap

    def retry_after_s(self) -> float:
        with self._lock:
            if self._state == CLOSED:
                return 0.0
            return max(self._opened_at + self.cooldown_s
                       - time.monotonic(), 0.0)

    # -- state machine ----------------------------------------------------

    def _transition(self, to: str, why: str) -> None:
        """Caller holds the lock."""
        frm, self._state = self._state, to
        verb = {OPEN: "opened" if frm == CLOSED else "reopened",
                HALF_OPEN: "half_open", CLOSED: "closed"}[to]
        obs.metrics.inc(f"{self.prefix}.{verb}")
        obs.event(f"{self.prefix}.{'close' if to == CLOSED else to}",
                  key=self.key, frm=frm, why=why,
                  consecutive_failures=self._consecutive_failures)
        obs.notice(f"circuit[{self.key}]: {frm} -> {to} ({why})",
                   name=f"{self.prefix}.transition", key=self.key,
                   frm=frm, to=to)

    def allow(self) -> bool:
        """Whether a call may proceed now. In ``half_open`` exactly one
        caller gets True (the probe); a True answer obliges the caller to
        later invoke ``record_success`` or ``record_failure``."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if time.monotonic() - self._opened_at < self.cooldown_s:
                    return False
                self._transition(HALF_OPEN, "cooldown elapsed; probing")
                self._probe_inflight = True
                return True
            # half_open: one probe at a time
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def release(self) -> None:
        """Release an ``allow()`` slot WITHOUT a verdict (the admitted
        call never executed — e.g. every request in the batch had already
        expired): failure counts and state are untouched, but a
        half-open probe slot is freed for the next caller."""
        with self._lock:
            self._probe_inflight = False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._probe_inflight = False
            self._last_error = None
            if self._state != CLOSED:
                self._transition(CLOSED, "probe succeeded")

    def record_failure(self, err: Optional[BaseException] = None) -> bool:
        """Count one failure; returns True when this failure OPENED (or
        re-opened) the circuit — callers use that edge to invalidate
        cached artifacts of the failing key (the serve plan cache drops
        the plan so the half-open probe rebuilds from scratch)."""
        with self._lock:
            self._consecutive_failures += 1
            self._probe_inflight = False
            if err is not None:
                self._last_error = f"{type(err).__name__}: {err}"[:300]
            if self._state == HALF_OPEN:
                self._opened_at = time.monotonic()
                self._transition(OPEN, "probe failed")
                return True
            if (self._state == CLOSED
                    and self._consecutive_failures >= self.failure_threshold):
                self._opened_at = time.monotonic()
                self._transition(
                    OPEN, f"{self._consecutive_failures} consecutive "
                          "failures")
                return True
            return False

    def reject(self) -> CircuitOpen:
        """The structured rejection for a disallowed call (also counts
        it: ``<prefix>.rejected``)."""
        obs.metrics.inc(f"{self.prefix}.rejected")
        return CircuitOpen(self.key, self.retry_after_s())
