"""Cooperative per-request deadlines — the serving layer's time budget.

A long-lived server cannot let one slow request consume unbounded wall
clock: every admitted request carries a deadline, and every host-side
layer under it (the fallback ladder's rebuild-and-retry loop, the serve
executor, future retry machinery) must be able to ask "how much time is
left?" without threading a parameter through every call. This module is
that channel: a monotonic-clock :class:`Deadline` value plus a
thread-local ambient scope —

    with deadline.scope(Deadline.after_ms(250)):
        ...            # anything on this thread can call deadline.current()

Scopes nest; the EFFECTIVE deadline is always the tightest enclosing one
(a caller can only shrink the budget of its callees, never extend it).
``fallback.execute`` consults the ambient deadline so a ladder walk on
behalf of a served request stops when the request's budget is gone, not
at the process-wide ``DFFT_FALLBACK_DEADLINE_S`` horizon.

Deadlines here are COOPERATIVE: nothing is interrupted mid-flight (a
jitted pipeline cannot be preempted anyway); expiry is observed at the
next check point. The serving layer checks before execution (an expired
request never executes) and after (a result that arrived too late is
reported as :class:`DeadlineExceeded`, not as a success).
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Iterator, Optional


class DeadlineExceeded(TimeoutError):
    """Structured expiry: the request's budget was exhausted before (or
    while) producing its result. ``detail`` says where expiry was
    observed (``queued`` / ``executing`` / ``ladder``)."""

    def __init__(self, msg: str, *, detail: str = "expired",
                 overrun_ms: float = 0.0):
        super().__init__(msg)
        self.detail = detail
        self.overrun_ms = float(overrun_ms)


@dataclasses.dataclass(frozen=True)
class Deadline:
    """An absolute instant on the monotonic clock (``time.monotonic``
    seconds). Immutable; compare/propagate freely across threads."""

    expires_at: float

    @classmethod
    def after_ms(cls, ms: float) -> "Deadline":
        return cls(time.monotonic() + float(ms) / 1e3)

    @classmethod
    def after_s(cls, s: float) -> "Deadline":
        return cls(time.monotonic() + float(s))

    def remaining_s(self) -> float:
        """Seconds left; negative once expired."""
        return self.expires_at - time.monotonic()

    def remaining_ms(self) -> float:
        return self.remaining_s() * 1e3

    def expired(self) -> bool:
        return self.remaining_s() <= 0.0

    def tighter(self, other: Optional["Deadline"]) -> "Deadline":
        """The earlier of the two (``other=None`` keeps self)."""
        if other is None or self.expires_at <= other.expires_at:
            return self
        return other


class _Tls(threading.local):
    def __init__(self) -> None:
        self.stack: list = []


_TLS = _Tls()


def current() -> Optional[Deadline]:
    """The ambient (tightest enclosing) deadline of this thread, or None
    when no scope is open."""
    stack = _TLS.stack
    return stack[-1] if stack else None


@contextlib.contextmanager
def scope(dl: Optional[Deadline]) -> Iterator[Optional[Deadline]]:
    """Install ``dl`` as the ambient deadline for the ``with`` body.
    Nested scopes only ever TIGHTEN: the effective deadline is the min of
    ``dl`` and any enclosing scope. ``scope(None)`` is a no-op pass-through
    (callers need not branch on "has a deadline")."""
    if dl is None:
        yield current()
        return
    eff = dl.tighter(current())
    _TLS.stack.append(eff)
    try:
        yield eff
    finally:
        _TLS.stack.pop()


def remaining_s(default: float) -> float:
    """Seconds left on the ambient deadline, or ``default`` without one."""
    dl = current()
    return default if dl is None else dl.remaining_s()


def check(detail: str = "expired") -> None:
    """Raise :class:`DeadlineExceeded` if the ambient deadline has passed
    (a cheap cooperative checkpoint for host-side loops)."""
    dl = current()
    if dl is not None and dl.expired():
        over = -dl.remaining_ms()
        raise DeadlineExceeded(
            f"deadline exceeded by {over:.1f} ms ({detail})",
            detail=detail, overrun_ms=over)
